"""Cache-aware request placement over serving replicas.

Each replica's radix prefix cache is an independent store; without
placement awareness, a request whose prefix is hot on replica A lands
on replica B by round-robin luck and pays a full prefill. The router
turns hit rate into a decision:

- **cache_aware** (default): probe every accepting replica that can
  admit the request (``Scheduler.can_admit`` — the side-effect-free
  admission ledger) with the prefix cache's read-only
  ``longest_prefix_len`` and pick the replica holding the LONGEST
  cached prefix of the request's tokens. Ties break by load — fewest
  queued + in-flight tokens owed, then most free + evictable pages,
  then the stable replica index (determinism). The probe is a shadow
  read of each replica's published prefixes: nothing is pinned, no LRU
  clock moves, so probing N replicas costs N trie walks and perturbs
  none of them.
- **round_robin**: rotate over admitting replicas — the baseline arm
  every bench compares against.

Every decision lands in a bounded log (the ``/debug/fleet`` forensics
and the Perfetto router track — ``telemetry.chrometrace.
router_trace_events``) plus ``router.*`` counters.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

from pipegoose_tpu.serving.control_plane.replica import Replica
from pipegoose_tpu.telemetry.registry import get_registry

POLICIES = ("cache_aware", "round_robin", "disagg")


class ShadowIndex:
    """Router-side radix over the prompts ROUTED to one replica — the
    shadow of that replica's prefix cache, block-granular (one node per
    ``page_size`` token block, same keying as the real trie).

    Fed by placements, not only by published pages: the real cache
    publishes a prefix only when its prefill completes, so during a
    bursty cold start every probe reads 0 and same-prefix requests
    scatter by the load tie-break — each replica then pays its own cold
    prefill for the same prefix. Recording the placement OPTIMISTICALLY
    (the routed prompt's pages WILL be published a few ticks later)
    keeps the second occurrence of a prefix behind the first one's
    replica, which is the whole point of cache-aware routing. The
    read-only ``longest_prefix_len`` probe of the real cache remains
    the ground truth the router maxes this against — a shadow that
    over-claims after an eviction costs one suboptimal placement, never
    correctness (admission re-checks everything).

    Bounded: past ``max_blocks`` nodes the shadow resets empty and
    rebuilds from subsequent placements + probes (coarse, self-healing,
    and O(1) — a per-chain LRU would cost more than the misroutes it
    prevents at this size)."""

    __slots__ = ("page_size", "max_blocks", "_root", "_blocks",
                 "resets_total", "on_reset")

    def __init__(self, page_size: int, max_blocks: int = 4096):
        self.page_size = int(page_size)
        self.max_blocks = int(max_blocks)
        self._root: Dict[tuple, dict] = {}
        self._blocks = 0
        self.resets_total = 0        # cap-triggered resets only
        self.on_reset = None         # callback(shadow) at each cap reset

    def insert(self, tokens) -> None:
        ps = self.page_size
        toks = [int(t) for t in tokens]
        children = self._root
        for i in range(len(toks) // ps):
            blk = tuple(toks[i * ps:(i + 1) * ps])
            node = children.get(blk)
            if node is None:
                if self._blocks >= self.max_blocks:
                    self.clear()
                    self.resets_total += 1
                    if self.on_reset is not None:
                        self.on_reset(self)
                    return
                node = {}
                children[blk] = node
                self._blocks += 1
            children = node

    def longest_match(self, tokens) -> int:
        """Matched tokens, page-granular (the shadow has no COW-head
        notion — the probe of the real cache supplies that
        refinement)."""
        ps = self.page_size
        toks = [int(t) for t in tokens]
        children = self._root
        i = 0
        while (i + 1) * ps <= len(toks):
            node = children.get(tuple(toks[i * ps:(i + 1) * ps]))
            if node is None:
                break
            children = node
            i += 1
        return i * ps

    def clear(self) -> None:
        self._root = {}
        self._blocks = 0


class Router:
    def __init__(self, policy: str = "cache_aware", *, registry=None,
                 max_decisions: int = 512,
                 affinity_slack_tokens: int = 192,
                 memory_pressure_steps: float = 0.0,
                 memory_pressure_penalty_tokens: int = 8192):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown routing policy {policy!r} (expected one of "
                f"{POLICIES})"
            )
        if affinity_slack_tokens < 0:
            raise ValueError(
                f"affinity_slack_tokens must be >= 0, got "
                f"{affinity_slack_tokens}"
            )
        if memory_pressure_steps < 0 or memory_pressure_penalty_tokens < 0:
            raise ValueError(
                "memory_pressure_steps and memory_pressure_penalty_tokens "
                "must be >= 0"
            )
        self.policy = policy
        self.affinity_slack_tokens = int(affinity_slack_tokens)
        # memory-ledger routing signal: a replica whose steps-to-
        # exhaustion forecast (capacity_snapshot, present only when a
        # MemoryLedger is attached) is at or below
        # ``memory_pressure_steps`` carries a synthetic token debt, so
        # cache affinity stops piling prefixes onto a pool about to
        # start evicting them. 0 disables (default).
        self.memory_pressure_steps = float(memory_pressure_steps)
        self.memory_pressure_penalty_tokens = int(
            memory_pressure_penalty_tokens)
        self.registry = registry if registry is not None else get_registry()
        self.decisions: deque = deque(maxlen=max_decisions)
        self._rr_next = 0
        self._shadows: Dict[str, ShadowIndex] = {}  # replica name -> shadow
        reg = self.registry
        self._m_decisions = reg.counter("router.decisions_total")
        self._m_cache_routed = reg.counter(
            "router.cache_routed_total",
            help="decisions where a nonzero cached prefix chose the replica",
        )
        self._m_matched = reg.counter(
            "router.matched_tokens_total",
            help="prefix tokens already cached on the chosen replica",
        )
        self._m_unplaceable = reg.counter(
            "router.unplaceable_total",
            help="route() calls where no replica could admit",
        )
        self._m_shadow_resets = reg.counter(
            "router.shadow_resets_total",
            help="shadow-index cap resets (graceful degradation: the "
                 "shadow rebuilds from subsequent placements)",
        )

    def route(self, req: Any, replicas: List[Replica],
              now: float, seq: Optional[int] = None) -> Optional[Replica]:
        """Pick the replica for ``req`` among ``replicas`` (None when
        no accepting replica can admit it right now — the dispatcher
        requeues and retries next tick). Pure reads: the only mutation
        anywhere is the router's own decision log/counters."""
        if self.policy == "disagg":
            raise ValueError(
                "the disagg policy dispatches through route_disagg("
                "prefill_replicas, decode_replicas) — one pool cannot "
                "serve both roles"
            )
        cands = [rep for rep in replicas
                 if rep.accepting and rep.engine.sched.can_admit(req)]
        if not cands:
            self._m_unplaceable.inc()
            return None
        matched = 0
        if self.policy == "round_robin":
            chosen = cands[self._rr_next % len(cands)]
            self._rr_next += 1
        else:
            tokens = req.tokens   # prompt + generated: a migrated
            # request probes with everything its re-prefill will walk,
            # so the replica that cached its prefix pre-drain wins
            matched, chosen = self._pick_cache_aware(cands, tokens)
        chosen.dispatched += 1
        self._m_decisions.inc()
        if matched:
            self._m_cache_routed.inc()
            self._m_matched.inc(matched)
        self.decisions.append({
            "t": now,
            "seq": seq,   # control-plane dispatch sequence (uid is
            # replica-local and not assigned until the target submits)
            # trace_id is the FLEET-stable identity (fleettrace.py):
            # it joins this decision to the stitched timeline a uid
            # cannot (uids change per leg, trace_ids never do)
            "trace_id": getattr(req, "trace_id", None),
            "tenant": req.tenant,
            "replica": chosen.name,
            "policy": self.policy,
            "matched_tokens": matched,
            "prompt_len": req.prompt_len,
            "candidates": len(cands),
        })
        return chosen

    def _replica_load(self, rep: Replica, snap: Optional[dict] = None
                      ) -> int:
        if snap is None:
            snap = rep.engine.sched.capacity_snapshot()
        # transfer_tokens_owed: a staged cross-pool transfer owes only
        # its unmaterialized tail + decode budget (scheduler ledger),
        # but it IS load this pool will pay — count it or disagg
        # dispatch piles onto a pool whose queue merely LOOKS empty
        load = (snap["queued_tokens"] + snap["active_tokens_remaining"]
                + snap.get("transfer_tokens_owed", 0))
        if self.memory_pressure_steps > 0:
            steps = snap.get("steps_to_exhaustion")
            if steps is not None and steps <= self.memory_pressure_steps:
                load += self.memory_pressure_penalty_tokens
        return load

    def _pick_cache_aware(self, cands: List[Replica], tokens):
        """The cache-aware scoring shared by ``cache_aware`` routing
        and the disagg decode-replica pin: rank every candidate by the
        longest cached prefix it already holds — the read-only
        ``longest_prefix_len`` probe maxed with the router-side shadow
        (which covers the publication lag) — with an IMBALANCE GUARD:
        take the FIRST candidate in (match desc, owed-tokens asc,
        free+evictable pages desc, stable index) order whose load stays
        within ``affinity_slack_tokens`` of the fleet minimum. Pure
        affinity piles a hot prefix onto one replica while its peers
        idle (p99 pays the queue); pure load-balancing scatters the
        prefix and every replica pays its own cold prefill. The guard
        bounds the pile-up to a fixed token debt, and a spill warms the
        spill target's cache, so the cost is one cold prefill per guard
        trip. Records the placement in the winner's shadow and returns
        ``(matched_tokens, replica)``."""
        scored = []
        for rep in cands:
            cache = rep.engine.prefix_cache
            m = (cache.longest_prefix_len(tokens)
                 if cache is not None else 0)
            shadow = self._shadows.get(rep.name)
            if shadow is not None:
                # max(published, placed): the shadow covers the
                # publication lag, the probe is the ground truth
                m = max(m, shadow.longest_match(tokens))
            snap = rep.engine.sched.capacity_snapshot()
            headroom = snap["free_pages"] + snap["evictable_pages"]
            scored.append((-m, self._replica_load(rep, snap), -headroom,
                           rep.index, rep))
        scored.sort(key=lambda s: s[:4])
        min_load = min(s[1] for s in scored)
        best = next(s for s in scored
                    if s[1] <= min_load + self.affinity_slack_tokens)
        matched, chosen = -best[0], best[4]
        shadow = self._shadows.get(chosen.name)
        if shadow is None:
            shadow = ShadowIndex(chosen.engine.page_size)
            shadow.on_reset = lambda _s: self._m_shadow_resets.inc()
            self._shadows[chosen.name] = shadow
        shadow.insert(tokens)
        return matched, chosen

    def route_disagg(self, req: Any, prefill_replicas: List[Replica],
                     decode_replicas: List[Replica], now: float,
                     seq: Optional[int] = None):
        """Disaggregated dispatch (serving/disagg/): pick the PREFILL
        replica by least owed work among accepting replicas that can
        admit the prompt (their prefill-only ledgers reserve prompt
        pages only), and PIN the DECODE replica up front — cache-aware
        over the decode pool (longest cached prefix, shadow-covered,
        load-guarded exactly like ``cache_aware``), because the decode
        replica is where the request's KV will live and where a later
        request sharing its prefix must land. Pinning at route time is
        what makes decode-pool affinity a decision rather than
        whatever pool had a free slot when the transfer completed.
        Returns ``(prefill_replica, decode_replica)`` or ``None`` when
        either pool has no candidate right now."""
        p_cands = [rep for rep in prefill_replicas
                   if rep.accepting and rep.engine.sched.can_admit(req)]
        d_cands = [rep for rep in decode_replicas if rep.accepting]
        if not p_cands or not d_cands:
            self._m_unplaceable.inc()
            return None
        prefill = min(p_cands,
                      key=lambda rep: (self._replica_load(rep), rep.index))
        matched, decode = self._pick_cache_aware(d_cands, req.tokens)
        prefill.dispatched += 1
        decode.dispatched += 1
        self._m_decisions.inc()
        if matched:
            self._m_cache_routed.inc()
            self._m_matched.inc(matched)
        self.decisions.append({
            "t": now,
            "seq": seq,
            "trace_id": getattr(req, "trace_id", None),
            "tenant": req.tenant,
            "policy": "disagg",
            "replica": decode.name,      # the pin: where the KV lands
            "prefill_replica": prefill.name,
            "matched_tokens": matched,
            "prompt_len": req.prompt_len,
            "candidates": len(p_cands) + len(d_cands),
        })
        return prefill, decode

    def drop_replica(self, name: str) -> None:
        """Forget a drained/stopped replica's shadow (its cache is
        going away with it)."""
        self._shadows.pop(name, None)

    def clear_shadows(self) -> None:
        for shadow in self._shadows.values():
            shadow.clear()

    def stats(self) -> Dict[str, Any]:
        return {
            "policy": self.policy,
            "decisions_total": self._m_decisions.value,
            "cache_routed_total": self._m_cache_routed.value,
            "matched_tokens_total": self._m_matched.value,
            "unplaceable_total": self._m_unplaceable.value,
            "shadow_resets_total": self._m_shadow_resets.value,
            "recent_decisions": list(self.decisions)[-16:],
        }
