"""ControlPlane: the front door over N serving-engine replicas.

Drives the replicas' steppable-run API (``start_run`` / ``tick_once``
/ ``take_finished`` / ``finish_run``) in one host thread:

    while work remains:
        autoscale            (fleet SLO burn -> add replica / drain one)
        shed expired ingress (tenant-queue deadline valve)
        dispatch             (ledger DRR batch -> router placement ->
                              replica.submit_request; migrated-out
                              requests re-place FIRST — they already
                              paid admission once)
        tick every busy replica  (each advances prefills + one decode
                                  step, exactly like a lone engine)
        collect finished     (per-tenant TTFT/e2e observation,
                              completion bookkeeping)
        progress drains      (DRAINING replica empties -> STOPPED,
                              metrics captured)

Placement is strictly read-only against the replicas (``can_admit``,
``capacity_snapshot``, ``longest_prefix_len``); the only cross-replica
state is the control plane's own (ledger queues, router log, fleet
registry). Determinism: same requests + same replica factory + same
tick schedule => same placements, same tokens (greedy parity is
per-engine; routing is lexicographic over deterministic scores).

Every replica gets its OWN ``MetricsRegistry``; ``fleet`` is the
merged view (telemetry/fleet.py) the fleet ``SLOMonitor`` and
``/debug/fleet`` read. Per-tenant TTFT/e2e land in the control plane's
registry as ``serving.tenant.<name>.*`` — ``per_tenant_slo_targets``
builds one SLO target per tenant over them.
"""
from __future__ import annotations

import math
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pipegoose_tpu.serving.control_plane.autoscaler import Autoscaler
from pipegoose_tpu.serving.control_plane.replica import (
    Replica,
    ReplicaState,
)
from pipegoose_tpu.serving.control_plane.router import Router
from pipegoose_tpu.serving.control_plane.tenants import TenantLedger
from pipegoose_tpu.serving.engine import RequestOutput
from pipegoose_tpu.serving.kv_tier.directory import PrefixDirectory
from pipegoose_tpu.serving.scheduler import Request, Status
from pipegoose_tpu.telemetry.fleet import FleetRegistry
from pipegoose_tpu.telemetry.registry import MetricsRegistry
from pipegoose_tpu.telemetry.slo import SLOTarget


def per_tenant_slo_targets(
    tenants: Sequence[str], *,
    ttft_objective_s: float = 0.5, ttft_p: float = 0.95,
) -> List[SLOTarget]:
    """One TTFT latency target per tenant over the control plane's
    ``serving.tenant.<name>.ttft_seconds`` histograms — the per-tenant
    half of the fleet verdict (a single hot tenant breaching ITS
    target while the fleet aggregate looks fine is a fairness page,
    not a capacity one)."""
    return [
        SLOTarget(name=f"tenant_{t}_ttft",
                  metric=f"serving.tenant.{t}.ttft_seconds",
                  objective=ttft_objective_s, target=ttft_p)
        for t in tenants
    ]


#: uid block reserved per replica: replica i mints uids from
#: i * UID_STRIDE, so a salvage resubmit with ``reuse_uid`` can never
#: collide with a live uid on the survivor it lands on — the "caller
#: owns cross-scheduler uniqueness" contract Scheduler.submit states,
#: made true by construction (a replica would have to serve a million
#: requests in one process to leak into its neighbor's block).
UID_STRIDE = 1_000_000


class ControlPlane:
    """Front door over N replicas (module docstring).

    ``replica_factory(name, registry) -> ServingEngine`` builds one
    replica engine wired to ITS registry; engines must enable the
    paged prefill path (``prefix_cache=True`` and/or
    ``prefill_chunk=``) — drain migration re-admits requests that
    already hold generated tokens, which the monolithic prefill cannot
    resume. ``policy`` is the routing arm ("cache_aware" |
    "round_robin"). ``autoscaler`` (optional) consumes the fleet SLO
    monitor; without one, :meth:`scale_up` / :meth:`start_drain` are
    the operator's manual controls (and the bench/test seam).
    """

    def __init__(self, replica_factory: Callable[[str, MetricsRegistry], Any],
                 *, n_replicas: int = 2, policy: str = "cache_aware",
                 ledger: Optional[TenantLedger] = None,
                 autoscaler: Optional[Autoscaler] = None,
                 registry: Optional[MetricsRegistry] = None,
                 stall_patience: int = 200,
                 affinity_slack_tokens: int = 192,
                 recorder: Optional[Any] = None,
                 suspect_after_ticks: int = 5,
                 failed_after_ticks: int = 20,
                 probation_ticks: int = 8,
                 pull_hints: bool = True,
                 fleet_tracer: Optional[Any] = None,
                 memledger: bool = False,
                 goodput: Any = False):
        """``recorder``: optional ``telemetry.FlightRecorder`` — every
        replica failure dumps ONE ``replica_failure`` black box naming
        the replica and the salvaged/resubmitted/lost uids; an
        UNRECOVERED failure (admitted work lost, or no survivors) stays
        pending so ``/healthz`` flips 503. ``suspect_after_ticks`` /
        ``failed_after_ticks``: the heartbeat thresholds of the health
        state machine (ticks with work but no progress before
        SERVING->SUSPECT and ->FAILED; must satisfy suspect < failed <
        stall_patience so a single wedged replica is quarantined long
        before the whole-fleet watchdog gives up).
        ``probation_ticks``: dispatch cooldown after :meth:`rejoin`.
        ``pull_hints``: hint cross-replica KV pulls through the fleet
        prefix directory at placement (serving/kv_tier/); off, replicas
        recompute what their own cache misses — the routing benchmark
        disables it to isolate placement from fleet prefix sharing.
        ``fleet_tracer``: optional ``telemetry.fleettrace.FleetTracer``
        — the plane mints a ``trace_id`` per ingress, marks every hop
        hand-over, attaches one named ``RequestTracer`` per replica
        (unless the factory attached its own), and the tracer stitches
        them into one cross-replica timeline per request (plane hops +
        replica phases == fleet e2e, the PR 8 contract fleet-wide).
        ``memledger``: attach one ``telemetry.MemoryLedger`` per
        replica (factory-attached ledgers are kept) — the fleet-minimum
        steps-to-exhaustion then feeds the autoscaler and
        ``fleet_status()`` grows a per-replica memory rollup.
        ``goodput``: ``True`` (or a ``telemetry.GoodputLedger``
        instance) attributes every replica-second of the run's wall
        into the goodput/badput taxonomy and mints one ``Incident`` per
        failure episode (telemetry/goodput.py) — ``fleet_status()``
        grows a ``goodput`` rollup, ``run()``'s metrics a ``goodput``
        row, and each ``replica_failure`` black box embeds its
        incident. Off (the default), the per-tick cost is one
        attribute read + branch."""
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if stall_patience < 1:
            raise ValueError(
                f"stall_patience must be >= 1, got {stall_patience}"
            )
        if not 1 <= suspect_after_ticks < failed_after_ticks:
            raise ValueError(
                f"need 1 <= suspect_after_ticks ({suspect_after_ticks}) "
                f"< failed_after_ticks ({failed_after_ticks})"
            )
        if failed_after_ticks >= stall_patience:
            raise ValueError(
                f"failed_after_ticks ({failed_after_ticks}) must be < "
                f"stall_patience ({stall_patience}): the fleet watchdog "
                f"must never fire before a wedged replica is quarantined"
            )
        if probation_ticks < 0:
            raise ValueError(
                f"probation_ticks must be >= 0, got {probation_ticks}"
            )
        self.replica_factory = replica_factory
        self.recorder = recorder
        self.pull_hints = pull_hints
        self.memledger = memledger
        self.fleettrace = fleet_tracer
        if (fleet_tracer is not None and recorder is not None
                and hasattr(recorder, "set_fleet_tracer")):
            recorder.set_fleet_tracer(fleet_tracer)
        self.suspect_after_ticks = suspect_after_ticks
        self.failed_after_ticks = failed_after_ticks
        self.probation_ticks = probation_ticks
        self.registry = (registry if registry is not None
                         else MetricsRegistry(enabled=True))
        # goodput wall-clock ledger (telemetry/goodput.py): True
        # constructs one publishing into the plane's registry; an
        # instance is adopted as-is (tests inject seeded ledgers)
        if goodput is True:
            from pipegoose_tpu.telemetry.goodput import GoodputLedger

            goodput = GoodputLedger(registry=self.registry)
        self.goodput = goodput or None
        self._tick = 0   # last tick seen by run() — lifecycle calls
        #                  outside the loop (rejoin/drain) stamp it
        self.router = Router(policy, registry=self.registry,
                             affinity_slack_tokens=affinity_slack_tokens)
        self.ledger = ledger if ledger is not None else TenantLedger()
        self.autoscaler = autoscaler
        self.stall_patience = stall_patience
        self.fleet = FleetRegistry([("control_plane", self.registry)])
        self.replicas: List[Replica] = []
        self._next_replica = 0
        self._now: Callable[[], float] = time.perf_counter
        self._running = False
        self._started: List[Replica] = []    # replicas active this run
        self._migrated: List[Request] = []   # drain re-placement queue
        self._seq = 0                        # control-plane dispatch ids
        self._order: Dict[int, int] = {}     # id(req) -> submit order
        self._outputs: Dict[int, RequestOutput] = {}  # submit order -> out
        # crash salvage: requests flagged here re-submit with
        # reuse_uid=True (the resubmit-from-prompt degradation keeps
        # the uid its tracer timeline is keyed by)
        self._reuse: set = set()
        # unplanned capacity loss not yet compensated: +1 per replica
        # failure, -1 per scale_up/rejoin — the autoscaler's
        # "FAILED counts as capacity loss" signal
        self._capacity_gap = 0
        reg = self.registry
        self._m_replicas = reg.gauge("control_plane.replicas_serving")
        self._m_dispatched = reg.counter("control_plane.dispatched_total")
        self._m_migrated = reg.counter("control_plane.migrated_total")
        self._m_drains = reg.counter("control_plane.drains_total")
        self._m_scaleups = reg.counter("control_plane.scaleups_total")
        self._m_shed = reg.counter("control_plane.shed_total")
        self._m_failures = reg.counter("serving.fleet.failures_total")
        self._m_salvaged = reg.counter("serving.fleet.salvaged_total")
        self._m_resubmitted = reg.counter("serving.fleet.resubmitted_total")
        self._m_lost = reg.counter("serving.fleet.lost_total")
        # fleet prefix directory (serving/kv_tier/): which replica
        # holds which prefix, HBM or host tier — created lazily from
        # the first cached replica's page_size; None when the fleet
        # runs cache-less
        self.directory: Optional[PrefixDirectory] = None
        for _ in range(n_replicas):
            self._add_replica()

    # -- replica lifecycle -------------------------------------------------

    def _add_replica(self) -> Replica:
        name = f"replica{self._next_replica}"
        self._next_replica += 1
        reg = MetricsRegistry(enabled=True)
        engine = self.replica_factory(name, reg)
        if not getattr(engine, "_paged_prefill", False):
            raise ValueError(
                f"replica {name!r}: control-plane engines need the paged "
                f"prefill path (prefix_cache=True and/or prefill_chunk=) — "
                f"drain migration re-admits requests holding generated "
                f"tokens, which monolithic prefill cannot resume"
            )
        rep = Replica(name, engine, registry=reg, index=self._next_replica - 1)
        # fleet-unique uid blocks (see UID_STRIDE): outputs are keyed by
        # submit ORDER so this changes nothing user-visible, but tracer
        # timelines and reuse_uid salvage stay collision-free fleet-wide
        engine.sched._next_uid = max(engine.sched._next_uid,
                                     rep.index * UID_STRIDE)
        if engine.prefix_cache is not None:
            if self.directory is None:
                self.directory = PrefixDirectory(engine.page_size)
            directory = self.directory

            def _publish(tokens, location, _name=name, _dir=directory):
                _dir.publish(_name, tokens, location)

            engine.on_prefix_publish = _publish
        if self.memledger and getattr(engine, "memledger", None) is None:
            from pipegoose_tpu.telemetry.memledger import MemoryLedger

            engine.attach_memledger(MemoryLedger())
        if self.fleettrace is not None:
            # one NAMED RequestTracer per replica (fragments the
            # stitcher seals/joins); a factory-attached tracer is kept
            # — shared-tracer fleets still stitch via the composite
            # (trace_id, uid) timeline key
            tracer = getattr(engine, "tracer", None)
            if tracer is None:
                from pipegoose_tpu.telemetry.reqtrace import RequestTracer

                tracer = RequestTracer(registry=reg, name=name)
                engine.attach_tracer(tracer)
            elif getattr(tracer, "name", None) is None:
                tracer.name = name
            self.fleettrace.register_replica(name, tracer)
        self.replicas.append(rep)
        self.fleet.add_member(name, reg)
        if self._running:
            engine.start_run((), now=self._now)
            self._started.append(rep)
            if self.goodput is not None:
                # mid-run scale-up: the account's alive wall starts NOW
                self.goodput.touch(name, self._now(), "serving",
                                   self._tick)
        self._m_replicas.set(float(len(self.serving_replicas())))
        return rep

    def serving_replicas(self) -> List[Replica]:
        return [r for r in self.replicas
                if r.state is ReplicaState.SERVING]

    def failed_replicas(self) -> List[Replica]:
        return [r for r in self.replicas
                if r.state is ReplicaState.FAILED]

    def scale_up(self) -> Replica:
        """Add one replica (autoscaler "up", or the operator). The new
        engine compiles its programs on first use — on real fleets the
        factory hands back a pre-warmed engine. Closes one unit of
        unplanned capacity gap when a failure opened one."""
        closed_gap = self._capacity_gap > 0
        rep = self._add_replica()
        self._m_scaleups.inc()
        self._capacity_gap = max(0, self._capacity_gap - 1)
        if self.goodput is not None and closed_gap:
            # replacement capacity is accepting: the OLDEST open
            # incident's MTTR window closes here
            self.goodput.resolve_incident(None, self._tick, self._now(),
                                          "scale_up")
        return rep

    def rejoin(self, name: str, *,
               probation_ticks: Optional[int] = None) -> Replica:
        """Bring a FAILED replica back: clear its injected fault, flip
        it to SERVING **on probation** (ticked, but not routed fresh
        ingress for ``probation_ticks``), and restart its steppable run
        when one is live. The replica's scheduler must be empty —
        salvage emptied it on the clean path; residue means the failure
        left state this rejoin cannot trust."""
        match = [r for r in self.replicas if r.name == name]
        if not match:
            raise ValueError(f"no replica named {name!r}")
        rep = match[0]
        sched = rep.engine.sched
        if (rep.salvage_degraded or not sched.all_done()
                or sched._outstanding_total != 0 or sched.transfers):
            # a CLEAN salvage leaves all of these empty; the degraded
            # path scrubs slots/queue by hand, so all_done() alone
            # would wave a corrupted admission ledger back in
            raise ValueError(
                f"replica {name!r} still holds scheduler state (or its "
                f"salvage was degraded) — a partially salvaged failure "
                f"cannot rejoin (replace it with scale_up instead)"
            )
        rep.engine.inject_fault(None)
        if self.goodput is not None:
            # book the quarantine dwell up to this very moment, then
            # close the replica's incident: MTTR = detection -> HERE
            t_rejoin = self._now()
            self.goodput.touch(rep.name, t_rejoin, rep.state.value,
                               self._tick)
            self.goodput.resolve_incident(rep.name, self._tick,
                                          t_rejoin, "rejoin")
        rep.rejoin(self.probation_ticks if probation_ticks is None
                   else probation_ticks, tick=self._tick)
        self._capacity_gap = max(0, self._capacity_gap - 1)
        if self._running and not rep.engine.run_in_progress:
            rep.engine.start_run((), now=self._now)
            if rep not in self._started:
                self._started.append(rep)
        self._m_replicas.set(float(len(self.serving_replicas())))
        return rep

    def start_drain(self, name: Optional[str] = None) -> Replica:
        """Begin draining one replica (autoscaler "down", or the
        operator): routing stops immediately, its requests migrate to
        the re-placement queue (dispatched ahead of fresh ingress next
        tick), and the replica stops once empty. Defaults to the
        SERVING replica with the least work owed — the cheapest
        drain."""
        serving = self.serving_replicas()
        if len(serving) <= 1:
            raise ValueError("cannot drain the last serving replica")
        if name is None:
            def owed(rep: Replica) -> Tuple[int, int]:
                snap = rep.engine.sched.capacity_snapshot()
                return (snap["queued_tokens"]
                        + snap["active_tokens_remaining"], rep.index)
            rep = min(serving, key=owed)
        else:
            match = [r for r in serving if r.name == name]
            if not match:
                raise ValueError(f"no serving replica named {name!r}")
            rep = match[0]
        migrated = rep.start_drain(tick=self._tick)
        self.router.drop_replica(rep.name)
        if self.directory is not None:
            self.directory.retract_replica(rep.name)
        if self.fleettrace is not None:
            t_leave = self._now()
            for req in migrated:
                self.fleettrace.on_leave(req, rep.name, t_leave, "drain")
        self._migrated.extend(migrated)
        self._m_migrated.inc(len(migrated))
        self._m_drains.inc()
        self._m_replicas.set(float(len(self.serving_replicas())))
        return rep

    def clear_prefix_caches(self) -> None:
        """Drop every live replica's unpinned cached pages — the bench
        and test seam for measuring a COLD-cache trace on warm-compiled
        engines (routing decides the hit rate only while caches are
        filling; a fully warmed fleet hits everywhere under any
        policy)."""
        for rep in self.replicas:
            if (rep.state is not ReplicaState.STOPPED
                    and rep.engine.prefix_cache is not None):
                rep.engine.prefix_cache.clear()
                if rep.engine.host_tier is not None:
                    rep.engine.host_tier.clear()
        self.router.clear_shadows()
        if self.directory is not None:
            self.directory.clear()

    # -- ingress -----------------------------------------------------------

    def submit(self, req: Request, now: float) -> None:
        """Accept one request into the tenant ledger. The control plane
        stamps the submit time (``Scheduler.submit`` preserves it — the
        user-visible clock starts HERE, not at replica dispatch)."""
        if req.t_submit is None:
            req.t_submit = now
        if self.fleettrace is not None:
            # the trace's t0 is the SAME float as req.t_submit — the
            # stitched sum's left edge and the user-visible clock start
            # are one number, which is what makes the conservation
            # contract exact rather than approximate
            self.fleettrace.on_ingress(req, req.t_submit)
        self._order[id(req)] = len(self._order)
        self.ledger.submit(req)

    # -- the loop ----------------------------------------------------------

    def _dispatchable(self, rep: Replica, tick: int) -> bool:
        """The health-aware dispatch gate: SERVING (past probation)
        flows freely; SUSPECT is PROBED with exponential backoff (ONE
        routed request per probe window — the retry that discovers
        recovery without piling fresh work on a maybe-dead replica);
        FAILED/DRAINING/STOPPED never receive work."""
        if rep.state is ReplicaState.SERVING:
            return rep.probation_ticks_left == 0
        if rep.state is ReplicaState.SUSPECT:
            return rep.probe_allowed(tick)
        return False

    def _place(self, req: Request, rep: Replica, cands: List[Replica],
               tick: int) -> List[Replica]:
        """Submit ``req`` on ``rep`` and return the candidate set for
        the REST of this tick: placing on a SUSPECT replica consumes
        its probe window (backoff doubles) and removes it from the
        remaining candidates — one probe request per window, never a
        whole batch piled onto a maybe-dead replica."""
        rep.engine.submit_request(
            req, reuse_uid=id(req) in self._reuse
        )
        self._reuse.discard(id(req))
        rep.inflight[id(req)] = req
        if self.fleettrace is not None:
            self.fleettrace.on_dispatched(req, rep.name)
        if (self.pull_hints and self.directory is not None
                and rep.engine.kv_tier is not None):
            # fleet prefix sharing: when a PEER holds a longer prefix
            # than this replica could have, hint the pull — the
            # engine's pre-admission intercept verifies the peer's
            # actual inventory (the directory may be stale; a stale
            # hint costs one read-only probe, never correctness)
            m, holder, _loc = self.directory.longest_holder(
                req.tokens, exclude=rep.name
            )
            if holder is not None and m > 0:
                peer = self._peer_engine(holder)
                if peer is not None and peer is not rep.engine:
                    rep.engine.kv_tier.hint_pull(req, peer)
                    tracer = getattr(rep.engine, "tracer", None)
                    if tracer is not None:
                        # name the pull SOURCE on the timeline — the
                        # merged Perfetto export draws its arrow from
                        # this event's peer to the import completion
                        tracer.annotate(req, "pull_hint", peer=holder,
                                        matched_tokens=int(m))
        if rep.state is ReplicaState.SUSPECT:
            rep.note_probe(tick)
            return [c for c in cands if c is not rep]
        return cands

    def _peer_engine(self, name: str):
        """Live engine for a directory-named replica (pull source).
        FAILED/STOPPED replicas never serve pulls — their pages are
        gone or untrustworthy."""
        for rep in self.replicas:
            if rep.name == name and rep.state in (ReplicaState.SERVING,
                                                  ReplicaState.SUSPECT,
                                                  ReplicaState.DRAINING):
                return rep.engine
        return None

    def _dispatch(self, now: float, tick: int) -> int:
        """Place migrated/salvaged requests first, then one DRR batch
        of fresh ingress. A request no replica can admit right now goes
        back where it came from and retries next tick."""
        cands = [rep for rep in self.replicas
                 if self._dispatchable(rep, tick)]
        placed = 0
        if self.fleettrace is not None:
            self.fleettrace.on_dispatch_pass(now)
        still: List[Request] = []
        for req in self._migrated:
            rep = self.router.route(req, cands, now, seq=self._seq)
            if rep is None:
                still.append(req)
                continue
            self._seq += 1
            if self.fleettrace is not None:
                self.fleettrace.on_routed(req, now, rep.name)
            cands = self._place(req, rep, cands, tick)
            placed += 1
        self._migrated = still
        if self._migrated:
            return placed   # re-placement backlog first, fresh traffic waits
        # fresh-batch sizing counts HEALTHY capacity only: a suspect's
        # free slots must not inflate the DRR batch it may never serve
        free_slots = sum(
            rep.engine.sched.capacity_snapshot()["free_slots"]
            for rep in cands if rep.state is ReplicaState.SERVING
        )
        if free_slots < 1:
            return placed
        batch = self.ledger.next_batch(free_slots)
        if self.fleettrace is not None:
            for req in batch:
                self.fleettrace.on_ledger_pop(req, now)
        for i, req in enumerate(batch):
            rep = self.router.route(req, cands, now, seq=self._seq)
            if rep is None:
                # requeue the WHOLE unplaced tail, not just the failed
                # head — every batch member was already popped from its
                # tenant FIFO, so dropping one here would silently lose
                # the request (reversed: requeue_front prepends, so the
                # original FIFO order survives)
                for r in reversed(batch[i:]):
                    self.ledger.requeue_front(r)
                break
            self._seq += 1
            if self.fleettrace is not None:
                self.fleettrace.on_routed(req, now, rep.name)
            cands = self._place(req, rep, cands, tick)
            self._m_dispatched.inc()
            placed += 1
        return placed

    def _seq_for(self, req: Request) -> int:
        """Submit-order index for ``req`` — tolerant of carryovers: a
        request stranded by an ABORTED previous run (still queued on a
        replica or in the ledger) drains during the next run and gets
        appended past that run's own submit order instead of KeyError-
        ing the bookkeeping."""
        seq = self._order.get(id(req))
        if seq is None:
            seq = len(self._order)
            self._order[id(req)] = seq
        return seq

    def _observe_finished(self, req: Request, out: RequestOutput) -> None:
        reg = self.registry
        tenant = req.tenant or "default"
        self.ledger.record_done(req)
        reg.counter(f"serving.tenant.{tenant}.requests_total").inc()
        if out.finish_reason == "shed":
            reg.counter(f"serving.tenant.{tenant}.shed_total").inc()
        if out.ttft_s is not None:
            reg.histogram(f"serving.tenant.{tenant}.ttft_seconds").observe(
                out.ttft_s
            )
        if out.finish_reason != "shed":
            reg.histogram(
                f"serving.tenant.{tenant}.e2e_latency_seconds"
            ).observe(out.e2e_latency_s)
        if self.fleettrace is not None:
            self.fleettrace.on_finished(req, out)
        self._outputs[self._seq_for(req)] = out

    def _shed_expired(self, now: float) -> None:
        for req in self.ledger.shed_expired(now):
            self._m_shed.inc()
            if self.fleettrace is not None:
                self.fleettrace.on_plane_shed(req, req.t_done)
            tenant = req.tenant or "default"
            self.registry.counter(
                f"serving.tenant.{tenant}.requests_total").inc()
            self.registry.counter(
                f"serving.tenant.{tenant}.shed_total").inc()
            e2e = req.t_done - req.t_submit
            seq = self._seq_for(req)
            # ledger-shed requests never reached a scheduler, so they
            # have no replica uid — a UNIQUE negative sentinel keeps
            # the uid-keyed conventions of engine outputs intact
            self._outputs[seq] = RequestOutput(
                uid=-(seq + 1), prompt=np.asarray(req.prompt),
                generated=np.asarray(req.generated, np.int64),
                finish_reason="shed", queue_latency_s=e2e, ttft_s=None,
                decode_tokens_per_s=None, e2e_latency_s=e2e,
                tenant=req.tenant,
            )

    # -- unplanned failure: detection fan-in + in-flight salvage -----------

    def _output_from(self, req: Request) -> RequestOutput:
        """Plane-side output builder for a request that FINISHED on a
        replica whose run can no longer build it (the engine was
        aborted by the failure path) — mirrors the engine's own
        ``_build_output`` arithmetic."""
        e2e = req.t_done - req.t_submit
        if req.finish_reason == "shed":
            return RequestOutput(
                uid=req.uid, prompt=np.asarray(req.prompt),
                generated=np.asarray(req.generated, np.int64),
                finish_reason="shed", queue_latency_s=e2e, ttft_s=None,
                decode_tokens_per_s=None, e2e_latency_s=e2e,
                tenant=req.tenant,
            )
        decode_s = max(req.t_done - req.t_admit, 1e-9)
        return RequestOutput(
            uid=req.uid, prompt=np.asarray(req.prompt),
            generated=np.asarray(req.generated, np.int64),
            finish_reason=req.finish_reason,
            queue_latency_s=req.t_admit - req.t_submit,
            ttft_s=(req.t_first_token - req.t_submit
                    if req.t_first_token is not None else None),
            decode_tokens_per_s=len(req.generated) / decode_s,
            e2e_latency_s=e2e, tenant=req.tenant,
        )

    def _salvage_reset(self, req: Request, sched: Any) -> None:
        """Resubmit-from-prompt degradation: the request's scheduler-
        side state is unreachable (harvest raised), so scrub what we
        can reach, DROP the harvested tokens (greedy determinism
        re-emits them token-identically from the prompt) and flag the
        request for a reuse_uid re-submission. Every step is
        best-effort — the scheduler may be arbitrarily broken."""
        try:
            if req.slot is not None and sched.slots[req.slot] is req:
                sched.slots[req.slot] = None
        except Exception:  # noqa: BLE001 - dead scheduler, best effort
            pass
        try:
            sched.queue.remove(req)
        except Exception:  # noqa: BLE001
            pass
        req.generated = []
        req.clear_residency()
        self._reuse.add(id(req))

    def _fail_replica(self, rep: Replica, tick: int, reason: str) -> None:
        """Quarantine ``rep`` and salvage its admitted work: mark
        FAILED, drop its router shadow, best-effort abort its run, then
        harvest every request the PLANE knows it owns (``rep.inflight``
        — independent of the dead scheduler) and re-queue them ahead of
        fresh ingress. Per request: finished-but-untaken ones emit
        their output directly; live ones preempt/withdraw cleanly
        (pages released, generated tokens kept — the re-prefill path
        resumes at the pending token, token-identical); a request whose
        scheduler state is unreachable degrades to resubmit-from-prompt
        with ``reuse_uid`` (still token-identical by greedy
        determinism, wait books as stall). One ``replica_failure``
        black box names the replica, every uid by disposition, and the
        router verdict; a fully recovered failure (nothing lost,
        survivors serving) consumes its own trigger so ``/healthz``
        flips only on an UNRECOVERED failure."""
        rep.mark_failed(reason, tick=tick)
        self.router.drop_replica(rep.name)
        if self.directory is not None:
            self.directory.retract_replica(rep.name)
        self._m_failures.inc()
        self._capacity_gap += 1
        try:
            rep.engine.abort_run()
        except Exception:  # noqa: BLE001 - best effort on a dead engine
            pass
        sched = rep.engine.sched
        salvaged: List[int] = []
        resubmitted: List[int] = []
        completed: List[int] = []
        lost: List[int] = []
        harvest = sorted(rep.inflight.values(), key=self._seq_for)
        for req in harvest:
            try:
                if req.status is Status.DONE and req.finish_reason:
                    # finished before the crash, output never taken
                    self._observe_finished(req, self._output_from(req))
                    completed.append(req.uid)
                    continue
                if req.status in (Status.PREFILL, Status.DECODE):
                    sched.preempt(req)
                if req.status is Status.QUEUED:
                    sched.withdraw(req)
                salvaged.append(req.uid)
            except Exception:  # noqa: BLE001 - unreachable state path
                rep.salvage_degraded = True   # rejoin refuses from here
                try:
                    self._salvage_reset(req, sched)
                    resubmitted.append(req.uid)
                except Exception:  # noqa: BLE001 - truly gone
                    lost.append(req.uid)
                    if self.fleettrace is not None:
                        self.fleettrace.on_lost(req, self._now())
                    continue
            if self.fleettrace is not None:
                # seal the fragment on the dead replica: its wait to
                # re-route books as the salvage hop from here
                self.fleettrace.on_leave(req, rep.name, self._now(),
                                         "salvage")
            self._migrated.append(req)
        rep.inflight.clear()
        rep.salvaged_out += len(salvaged) + len(resubmitted)
        self._m_salvaged.inc(len(salvaged))
        self._m_resubmitted.inc(len(resubmitted))
        self._m_lost.inc(len(lost))
        self._m_replicas.set(float(len(self.serving_replicas())))
        incident = None
        if self.goodput is not None:
            # one Incident per failure episode, joined to the
            # chaos.injection ring for detection latency; it stays open
            # (capacity-gap integral accruing per tick) until rejoin or
            # scale_up closes its MTTR window
            incident = self.goodput.open_incident(
                "wedge" if reason.startswith("wedged") else "crash",
                rep.name, tick, self._now(), reason=reason,
                recorder=self.recorder,
                injection_kinds=("replica_crash", "replica_wedge"),
                salvaged_uids=salvaged, resubmitted_uids=resubmitted,
                completed_uids=completed, lost_uids=lost,
                capacity_gap=self._capacity_gap,
            )
        if self.recorder is None:
            return
        recovered = not lost and bool(self.serving_replicas())
        # an EARLIER unconsumed trigger (a previous unrecovered failure,
        # a decode stall...) must survive this dump: fire_trigger
        # overwrites last_trigger, and the recovered path below would
        # otherwise consume-and-clear a problem that is still real
        pending = self.recorder.last_trigger
        exemplar = None
        if self.fleettrace is not None:
            try:
                # the slowest completed fleet trace, dominant hop named
                # — so the box answers "what does this failure COST"
                # with a concrete request instead of bare counts
                exemplar = self.fleettrace.exemplar("e2e")
            except Exception:  # noqa: BLE001 - forensics must not raise
                exemplar = None
        trig = self.recorder.fire_trigger(
            "replica_failure",
            f"replica {rep.name} failed at tick {tick}: {reason} — "
            f"salvaged {len(salvaged)}, resubmitted {len(resubmitted)}, "
            f"completed {len(completed)}, lost {len(lost)}",
            tick,
            details={
                "replica": rep.name,
                "reason": reason,
                "exemplar": exemplar,
                "salvaged_uids": salvaged,
                "resubmitted_uids": resubmitted,
                "completed_uids": completed,
                "lost_uids": lost,
                "recovered": recovered,
                "incident": (incident.as_dict()
                             if incident is not None else None),
                "router": {
                    "verdict": "quarantined",
                    "shadow_dropped": True,
                    "serving_replicas": [
                        r.name for r in self.serving_replicas()
                    ],
                },
            },
        )
        if recovered and self.recorder.last_trigger is trig:
            # the black box stays on disk; only the PENDING flag (the
            # /healthz signal) clears — admitted work is safe on the
            # survivors, so the fleet is degraded, not down. An earlier
            # still-pending trigger is put back, not discarded.
            self.recorder.take_trigger()
            if pending is not None:
                self.recorder.last_trigger = pending

    def _fleet_memory_steps(self) -> Optional[float]:
        """Fleet MINIMUM of the per-replica steps-to-exhaustion
        forecast — the autoscaler's memory capacity signal. None when
        no serving replica has a ledger attached or every forecast is
        still infinite (no consumption trend yet)."""
        steps = [
            ml.steps_to_exhaustion
            for rep in self.serving_replicas()
            if (ml := getattr(rep.engine, "memledger", None)) is not None
        ]
        finite = [s for s in steps if not math.isinf(s)]
        return min(finite) if finite else None

    def _autoscale(self, tick: int, now: float) -> None:
        if self.autoscaler is None:
            return
        decision = self.autoscaler.decide(
            tick, len(self.serving_replicas()),
            # a prior drain's unplaced refugees count as backlog too:
            # draining ANOTHER replica while they wait is exactly the
            # churn the backlog guard exists to prevent
            self.ledger.pending() + len(self._migrated),
            now=now,
            n_failed=self._capacity_gap,
            memory_steps=self._fleet_memory_steps(),
        )
        if decision == "up":
            self.scale_up()
        elif decision == "down" and len(self.serving_replicas()) > 1:
            self.start_drain()

    def _busy(self) -> bool:
        return (bool(self._migrated) or self.ledger.pending() > 0
                or any(rep.busy for rep in self.replicas))

    def run(self, requests: Sequence[Request], now=time.perf_counter,
            tick_hook=None):
        """Serve ``requests`` across the fleet to completion; returns
        (outputs in submit order, fleet-metrics dict).
        ``tick_hook(plane, tick)`` is the orchestration seam (tests and
        benches force drains/scale-ups mid-run through it)."""
        if self._running:
            raise RuntimeError("control plane is already running")
        self._now = now
        if self.fleettrace is not None:
            self.fleettrace.set_clock(now)
        self._running = True
        self._outputs = {}
        self._order = {}
        self._migrated = []
        self._reuse = set()
        t0 = now()
        try:
            self._started = [rep for rep in self.replicas
                             if rep.state not in (ReplicaState.STOPPED,
                                                  ReplicaState.FAILED)]
            gp = self.goodput
            for rep in self._started:
                rep.engine.start_run((), now=now)
            if gp is not None:
                # alive wall opens at run start for every participant
                # (existing accounts book the between-runs gap into the
                # class their current state implies)
                t_open = now()
                for rep in self._started:
                    gp.touch(rep.name, t_open, rep.state.value, 0)
            for req in requests:
                self.submit(req, now())
            tick = 0
            idle_ticks = 0
            while self._busy():
                tick += 1
                self._tick = tick
                if tick_hook is not None:
                    tick_hook(self, tick)
                self._autoscale(tick, now())
                self._shed_expired(now())
                placed = self._dispatch(now(), tick)
                progressed = placed > 0
                marks = [] if gp is not None else None
                for rep in self.replicas:
                    if rep.state in (ReplicaState.STOPPED,
                                     ReplicaState.FAILED):
                        if (gp is not None
                                and rep.state is ReplicaState.FAILED):
                            # quarantined replicas burn wall too — the
                            # taxonomy is exhaustive over ALIVE
                            # replicas, and FAILED is alive-but-useless
                            marks.append((rep, "failed_quarantine"))
                        continue
                    if rep.probation_ticks_left > 0:
                        rep.probation_ticks_left -= 1
                    eng = rep.engine
                    pre = gp.pre_tick(rep) if gp is not None else None
                    had_work = not eng.sched.all_done()
                    ticked = False
                    if had_work:
                        try:
                            ticked = eng.tick_once()
                        except Exception as e:  # noqa: BLE001 - crash
                            # detection: ReplicaFault (the seam), the
                            # engine's own stall watchdog, anything
                            # escaping a replica tick — quarantine +
                            # salvage instead of taking the fleet down
                            self._fail_replica(
                                rep, tick,
                                f"tick_once raised "
                                f"{type(e).__name__}: {e}",
                            )
                            progressed = True  # handling IS progress
                            if gp is not None:
                                marks.append((rep, "failed_quarantine"))
                            continue
                    took = False
                    for req, out in eng.take_finished():
                        rep.inflight.pop(id(req), None)
                        self._observe_finished(req, out)
                        took = True
                    if ticked or took:
                        rep.note_progress(tick)
                        progressed = True
                    elif had_work:
                        # heartbeat miss with work pending: the wedge
                        # ladder (SERVING -> SUSPECT -> FAILED)
                        n = rep.note_no_progress()
                        if n >= self.failed_after_ticks:
                            self._fail_replica(
                                rep, tick,
                                f"wedged: no progress for {n} ticks "
                                f"with work pending",
                            )
                            progressed = True
                        elif n >= self.suspect_after_ticks:
                            rep.mark_suspect(tick)
                    rep.maybe_stop(tick)
                    if gp is not None:
                        marks.append(
                            (rep, gp.classify(rep, pre, had_work,
                                              ticked, took)))
                        kvt = getattr(eng, "kv_tier", None)
                        if (kvt is not None
                                and kvt.fallbacks > pre[2]):
                            gp.note_transfer_flap(
                                rep.name, tick, now(),
                                kvt.fallbacks - pre[2],
                                recorder=self.recorder,
                            )
                self._goodput_flush(marks, tick, now)
                if progressed:
                    idle_ticks = 0
                else:
                    idle_ticks += 1
                    if idle_ticks >= self.stall_patience:
                        raise RuntimeError(
                            f"control-plane stall: {self.ledger.pending()} "
                            f"queued + {len(self._migrated)} migrated "
                            f"requests, no replica made progress for "
                            f"{self.stall_patience} ticks"
                        )
            per_replica: Dict[str, dict] = {}
            for rep in self._started:
                if rep.engine.run_in_progress:
                    # drain any completion the last tick left behind
                    # before closing the run
                    for req, out in rep.engine.take_finished():
                        rep.inflight.pop(id(req), None)
                        self._observe_finished(req, out)
                    _, metrics = rep.engine.finish_run()
                    per_replica[rep.name] = metrics
                elif rep.final_metrics is not None:
                    per_replica[rep.name] = rep.final_metrics
        except BaseException:
            # the stall watchdog (or a raising tick_hook) must not
            # wedge the fleet: abort every replica's steppable run so a
            # retry can start_run again — best-effort PER replica (one
            # raising abort_run must not skip the rest, or they wedge
            # forever on "run already in progress")
            for rep in self._started:
                try:
                    rep.engine.abort_run()
                except Exception:  # noqa: BLE001 - teardown best effort
                    pass
            raise
        finally:
            self._running = False
        wall = max(now() - t0, 1e-9)
        outputs = [self._outputs[i] for i in sorted(self._outputs)]
        generated = sum(len(o.generated) for o in outputs)
        metrics = {
            "wall_time_s": round(wall, 6),
            "requests": len(outputs),
            "generated_tokens": generated,
            "decode_tokens_per_s": round(generated / wall, 2),
            # the fleet FLOP meter: prompt tokens actually forwarded
            # through any replica's prefill — cache-aware routing's
            # acceptance metric (fewer forwarded tokens, same output)
            "prefill_tokens": sum(
                m.get("prefill_tokens", 0) for m in per_replica.values()
            ),
            "shed_requests": sum(
                1 for o in outputs if o.finish_reason == "shed"
            ),
            "per_replica": per_replica,
            "router": self.router.stats(),
            "tenants": self.ledger.stats(),
        }
        if self.directory is not None:
            metrics["kv_directory"] = self.directory.stats()
        if self.autoscaler is not None:
            metrics["autoscaler"] = list(self.autoscaler.log)
        if self.goodput is not None:
            self.goodput.publish()
            metrics["goodput"] = self.goodput.summary()
        return outputs, metrics

    def _goodput_flush(self, marks, tick: int, now) -> None:
        """Book one tick's wall into the goodput ledger: every mark is
        (replica, class) and each replica's share is the wall since ITS
        last mark — the telescoping sum that makes conservation exact.
        Ledger off => one attribute load + compare (the <5 µs guard)."""
        if self.goodput is None:
            return
        gp = self.goodput
        t_mark = now()
        for rep, klass in marks:
            gp.account(rep.name, t_mark, klass, rep.state.value, tick)
        gp.on_tick(tick, t_mark)

    # -- observability -----------------------------------------------------

    def fleet_memory(self) -> Optional[Dict[str, Any]]:
        """Fleet memory rollup: each replica's ledger condensed to the
        numbers an operator pages on — per-class pages, conservation
        verdict, leak tally, exhaustion forecast, host-tier bytes —
        plus fleet aggregates (total bytes by class, the minimum
        forecast, whether ANY replica ever broke conservation). None
        when no replica carries a ledger."""
        per: Dict[str, Any] = {}
        totals: Dict[str, int] = {}
        for rep in self.replicas:
            ml = getattr(rep.engine, "memledger", None)
            if ml is None:
                continue
            c = ml.counts()
            cons = ml.conservation()
            steps = ml.steps_to_exhaustion
            per[rep.name] = {
                "classes_pages": c,
                "bytes_per_page": ml.bytes_per_page,
                "conservation_ok": cons["ok"],
                "conservation_failures": ml.conservation_failures,
                "leaks": (len(ml.last_audit["leaks"])
                          if ml.last_audit else 0),
                "mismatched_releases": ml.mismatched_releases,
                "steps_to_exhaustion": (None if math.isinf(steps)
                                        else steps),
                "fragmentation": round(ml.pool.fragmentation(), 4),
                "host_tier_bytes": (ml.host_tier.resident_bytes
                                    if ml.host_tier is not None else None),
            }
            for k, v in c.items():
                totals[k] = totals.get(k, 0) + v * ml.bytes_per_page
        if not per:
            return None
        return {
            "replicas": per,
            "total_bytes_by_class": totals,
            "min_steps_to_exhaustion": self._fleet_memory_steps(),
            "conservation_ok": all(r["conservation_ok"]
                                   for r in per.values()),
            "conservation_failures": sum(r["conservation_failures"]
                                         for r in per.values()),
            "leaks": sum(r["leaks"] for r in per.values()),
        }

    def fleet_status(self) -> Dict[str, Any]:
        """The ``/debug/fleet`` payload: per-replica state + load,
        router stats, per-tenant ledger shares, autoscaler audit log,
        memory-ledger rollup — everything JSON-able, snapshot-style."""
        rows = [rep.status() for rep in self.replicas]
        if self.goodput is not None:
            for row in rows:
                row["state_seconds"] = self.goodput.state_seconds(
                    row["name"])
        return {
            "replicas": rows,
            "serving": len(self.serving_replicas()),
            "failed": len(self.failed_replicas()),
            "capacity_gap": self._capacity_gap,
            "router": self.router.stats(),
            "kv_directory": (self.directory.stats()
                             if self.directory is not None else None),
            "tenants": self.ledger.stats(),
            "migrated_pending": len(self._migrated),
            "autoscaler": (list(self.autoscaler.log)
                           if self.autoscaler is not None else None),
            "memory": self.fleet_memory(),
            "goodput": (self.goodput.summary()
                        if self.goodput is not None else None),
        }
