"""Continuous-batching scheduler: request lifecycle + slot/page admission.

The request lifecycle is QUEUED -> PREFILL -> DECODE -> DONE. A fixed
number of decode SLOTS bounds the jitted step's batch dim (static
shapes); the scheduler's job is to keep those slots full:

- **admission** pops the FIFO queue into free slots whenever the page
  pool can cover the candidate's WORST-CASE footprint
  (``ceil((prompt + max_new) / page_size)``) on top of every active
  request's outstanding reservation. Pages are then allocated LAZILY —
  the first prefill chunk's pages at admission (the WHOLE prompt's when
  chunked prefill is off: one monolithic chunk), decode pages one at a
  time as the write position crosses a page boundary — so
  short-finishing requests never hold their worst case, while the
  reservation arithmetic guarantees a lazy ``alloc`` can never fail
  mid-flight. Head-of-line blocking is deliberate: FIFO admission keeps
  the schedule deterministic.
- **prefix caching** (``prefix_cache=PrefixCache(pool)``) short-cuts
  admission: the longest page-aligned cached prefix of the prompt is
  SHARED (refcount bump, no alloc, no prefill) and only the unique tail
  is prefilled. The admission ledger then counts
  ``free + cache-evictable`` as capacity and debits pages the hit pins
  (refcount 1 -> 2), so a reservation made when a page looked evictable
  can never be stranded by a later hit; ``_alloc`` evicts
  least-recently-used unpinned cache pages on demand.
- **eviction** frees a finished request's pages and reservation the
  step its last token is emitted — shared pages just drop a reference —
  so the next ``admit`` can re-use both the slot and the pages
  mid-stream (continuous batching). :meth:`Scheduler.preempt` is the
  mid-flight variant: a live request's pages all go back (cache-shared
  ones survive in the cache) and the request re-queues at the HEAD;
  re-admission re-prefills ``prompt + generated[:-1]`` (hitting the
  cache for the shared prefix) and resumes decoding with the last
  generated token pending — token-for-token identical to an
  uninterrupted run.

``continuous=False`` turns the same machinery into the naive padded
baseline: a batch is admitted only into an EMPTY slot set and drains
fully before the next one — slots idle behind the batch's longest
member exactly the way padded ``generate`` rows do, which is the A/B
the serving bench measures.
"""
from __future__ import annotations

import enum
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from pipegoose_tpu.serving.kv_pool import PagePool


class Status(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    # disaggregated serving (serving/disagg/): the request's KV pages
    # are in flight between pools — left the prefill scheduler via
    # finish_handoff, staged on the decode scheduler via begin_transfer
    TRANSFER = "transfer"
    DONE = "done"


@dataclass
class Request:
    """One generation request. Engine/scheduler fill the lifecycle
    fields; callers provide the first three."""

    prompt: np.ndarray                 # (S,) token ids
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    # graceful degradation: seconds from submit after which the request
    # is SHED at admission time instead of admitted (the answer would
    # arrive too late to matter, so spending prefill+decode on it only
    # makes every other request later). None = never shed.
    deadline_s: Optional[float] = None
    # multi-tenant identity: who this request belongs to. The scheduler
    # itself is tenant-blind; the control plane's fair-share ledger
    # (serving/control_plane/tenants.py) keys on it, the tracer carries
    # it through timeline events, and per-request metric dicts report it.
    tenant: Optional[str] = None

    uid: Optional[int] = None
    # fleet-trace identity (telemetry/fleettrace.py): minted once at
    # ControlPlane.submit ingress and carried by THIS object through
    # every dispatch, drain migration, crash salvage, disagg handoff
    # and kv-tier pull — uids are replica-local (and reused by design
    # on salvage), so the trace_id is the only safe cross-replica join
    # key. None for requests that never crossed a control plane.
    # Deliberately NOT scrubbed by clear_residency(): identity, like
    # timestamps, survives the degraded salvage path.
    trace_id: Optional[int] = None
    status: Status = Status.QUEUED
    generated: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    pages: List[int] = field(default_factory=list)
    outstanding: int = 0               # worst-case pages not yet allocated
    prefilled_len: int = 0             # tokens whose KV is in pages + forwarded
    hit_tokens: int = 0                # of those, tokens served by the cache
    cow: Optional[Tuple[int, int]] = None  # (src page, valid tokens) pending copy
    finish_reason: Optional[str] = None
    # timestamp contract (attribution depends on it): t_submit and
    # t_admit mark the FIRST submission/admission and survive
    # preempt -> re-admit untouched, as does t_first_token — so
    # queue_latency_s and ttft_s always measure the user-visible waits,
    # never a requeue artifact. ttft_observed dedupes the engine's TTFT
    # histogram observation (exactly once per request, whichever
    # prefill path(s) the request crosses).
    t_submit: Optional[float] = None
    t_admit: Optional[float] = None
    t_first_token: Optional[float] = None
    t_done: Optional[float] = None
    ttft_observed: bool = False

    def clear_residency(self) -> None:
        """Scrub the scheduler-residency fields (slot, pages,
        reservation, COW, prefill progress) WITHOUT touching identity,
        tokens, or timestamps — the crash-salvage paths' best-effort
        reset before re-submitting a request harvested off a broken
        scheduler onto a healthy one (the normal lifecycle resets these
        through preempt/admit; this is for when those paths raised)."""
        self.slot = None
        self.pages = []
        self.outstanding = 0
        self.cow = None
        self.prefilled_len = self.hit_tokens = 0

    @property
    def prompt_len(self) -> int:
        return int(np.asarray(self.prompt).shape[0])

    @property
    def cached_len(self) -> int:
        """Tokens currently in the KV pages: the whole prompt plus every
        generated token except the pending one (the decode step writes
        the pending token before attending)."""
        return self.prompt_len + max(len(self.generated) - 1, 0)

    @property
    def target_len(self) -> int:
        """Tokens a (re-)prefill must put in the pages before decoding
        can resume: the prompt, plus — after a preemption — every
        generated token except the pending last one. Equals
        ``cached_len`` by construction; named separately because during
        PREFILL it is the goal, not the state."""
        return self.cached_len

    @property
    def tokens(self) -> np.ndarray:
        return np.concatenate(
            [np.asarray(self.prompt, np.int64),
             np.asarray(self.generated, np.int64)]
        )


class Scheduler:
    def __init__(self, num_slots: int, pool: PagePool, max_context: int,
                 continuous: bool = True, prefix_cache=None,
                 chunk_tokens: Optional[int] = None, tracer=None,
                 prefill_only: bool = False):
        if num_slots < 1:
            raise ValueError("need at least one decode slot")
        if chunk_tokens is not None and (
                chunk_tokens < pool.page_size or chunk_tokens % pool.page_size):
            raise ValueError(
                f"chunk_tokens={chunk_tokens} must be a positive multiple "
                f"of page_size={pool.page_size} (chunks end on page "
                f"boundaries so every chunk's pages exist before it runs)"
            )
        self.num_slots = num_slots
        self.pool = pool
        self.max_context = max_context
        self.continuous = continuous
        self.cache = prefix_cache
        self.chunk_tokens = chunk_tokens
        # disaggregated prefill pool (serving/disagg/): requests here
        # only ever hold their PROMPT's pages — they hand off to a
        # decode pool at prefill completion instead of decoding — so
        # the admission ledger reserves pages_for(prompt) rather than
        # pages_for(prompt + max_new). Reserving the decode worst case
        # on a pool that never decodes would throttle prefill admission
        # by pages nobody here will ever write.
        self.prefill_only = prefill_only
        # request-lifecycle observer (telemetry/reqtrace.py): the
        # scheduler owns the lifecycle transitions, so it drives the
        # tracer's submit/admit/preempt/first-token/done hooks; None
        # (the default) costs one attribute read + branch per event
        self.tracer = tracer
        self.slots: List[Optional[Request]] = [None] * num_slots
        self.queue: deque = deque()
        # deadline-shed requests since the last drain_shed() — the
        # engine drains these per tick to count them and emit outputs
        self.shed: List[Request] = []
        # inbound cross-pool transfers staged via begin_transfer,
        # uid -> {"req", "pages", "outstanding", "tokens"}: pages
        # materialize here chunk by chunk until admit_with_pages binds
        # the request to a slot (serving/disagg/). Scheduler-side
        # records, NOT request fields — the request may still be live
        # on its prefill scheduler while pages stream.
        self.transfers: dict = {}
        self._outstanding_total = 0
        self._next_uid = 0
        # lifetime count of admissions deferred by the memory ledger's
        # worst-case check (the head didn't fit): the goodput ledger
        # reads the per-tick delta to book a no-progress tick as
        # admission-blocked wall rather than a stall (always on — one
        # int increment on a path that just did pool arithmetic)
        self.admission_deferrals = 0

    def _worst_tokens(self, req: Request) -> int:
        """Tokens the admission ledger reserves pages for: the decode
        worst case, or just the prompt on a prefill-only pool."""
        if self.prefill_only:
            return req.prompt_len
        return req.prompt_len + req.max_new_tokens

    # -- lifecycle ---------------------------------------------------------

    def submit(self, req: Request, now: float,
               reuse_uid: bool = False) -> None:
        worst = self.pool.pages_for(self._worst_tokens(req))
        if req.prompt_len < 1:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if req.deadline_s is not None and req.deadline_s < 0:
            raise ValueError(
                f"deadline_s must be >= 0, got {req.deadline_s}"
            )
        if self._worst_tokens(req) > self.max_context:
            raise ValueError(
                f"request needs {self._worst_tokens(req)} "
                f"context but the engine was sized for {self.max_context}"
            )
        if worst > self.pool.capacity:
            raise ValueError(
                f"request worst case is {worst} pages but the pool only "
                f"has {self.pool.capacity}"
            )
        if not (reuse_uid and req.uid is not None):
            # reuse_uid=True: a cross-scheduler flow (the disagg
            # fallback and the crash-salvage resubmit path re-submitting
            # a request onto another pool) keeps the uid its tracer
            # timeline is keyed by; the CALLER owns uniqueness across
            # the schedulers involved — disagg uids all come from the
            # prefill scheduler's counter, and the control plane mints
            # each replica's uids from a disjoint block (UID_STRIDE).
            # The local counter deliberately does NOT jump past a
            # reused uid: jumping would leak this scheduler's counter
            # into another replica's block, recreating the very
            # collision the blocks exist to prevent.
            req.uid = self._next_uid
            self._next_uid += 1
        if req.t_submit is None:
            # FIRST submission only — the same contract admit() keeps for
            # t_admit: a request MIGRATED between replicas (control-plane
            # drain: withdraw here, submit there) keeps the user-visible
            # submit time, so queue_latency_s/ttft_s never go negative
            # against a preserved t_admit
            req.t_submit = now
        req.status = Status.QUEUED
        self.queue.append(req)
        if self.tracer is not None:
            self.tracer.on_submit(req, now)

    def _shed_expired(self, now: float) -> None:
        """Graceful degradation: drop QUEUED requests already past
        their deadline — serving them would spend decode slots on
        answers nobody is waiting for while fresh requests queue behind
        them. Shedding is load-dependent but deterministic given the
        same arrival times and schedule; shed requests land in
        ``self.shed`` (terminal, finish_reason="shed") for the engine
        to drain. Only the never-admitted QUEUE sheds: an admitted
        request has paid its prefill and always runs to completion —
        including one preempted back into the queue (``t_admit`` set),
        which already holds generated tokens."""
        if not any(r.deadline_s is not None for r in self.queue):
            return
        kept: deque = deque()
        for req in self.queue:
            if (req.deadline_s is not None
                    and req.t_admit is None
                    and req.t_submit is not None
                    and now - req.t_submit > req.deadline_s):
                req.status = Status.DONE
                req.finish_reason = "shed"
                req.t_done = now
                self.shed.append(req)
                if self.tracer is not None:
                    self.tracer.on_shed(req, now)
            else:
                kept.append(req)
        self.queue = kept

    def drain_shed(self) -> List[Request]:
        """Shed requests since the last drain (engine tick bookkeeping:
        counter + terminal outputs)."""
        out, self.shed = self.shed, []
        return out

    def _admission_check(self, req: Request):
        """The admission ledger, side-effect-free: can the pool (plus
        evictable cache pages, minus pins a cache hit would take) cover
        ``req``'s worst case beyond all outstanding reservations?
        Returns ``(fits, hit)`` — the SINGLE implementation both
        :meth:`admit` and the router-facing :meth:`can_admit` probe
        evaluate, so probe and admission cannot disagree on the same
        state (pinned by test). ``lookup`` is side-effect-free, so a
        False verdict leaves the cache LRU order and every refcount
        untouched."""
        target = req.target_len
        worst = self.pool.pages_for(self._worst_tokens(req))
        hit = None
        shared: List[int] = []
        evictable = pinned = 0
        if self.cache is not None and (
            self.pool.free_count + self.cache.cached_pages
            - self._outstanding_total
            < worst - (target - 1) // self.pool.page_size
        ):
            # O(1) reject: even if EVERY cached page were evictable
            # and the hit were the longest possible, the head can't
            # fit — skip the trie walk + whole-trie evictable scan.
            # (A head blocked only by the EXACT ledger still rescans
            # each tick; acceptable until caches reach a size where
            # incremental evictable accounting pays for itself.)
            return False, None
        if self.cache is not None:
            # >= 1 token must be forwarded: its logits produce the
            # next token (resumed requests re-derive their pending)
            hit = self.cache.lookup(req.tokens[:target],
                                    max_tokens=target - 1)
            shared = hit.pages
            pins = shared + (
                [hit.cow_page] if hit.cow_page is not None else []
            )
            pinned = sum(1 for p in pins if self.pool.refcount(p) == 1)
            evictable = self.cache.evictable_count()
        need_new = worst - len(shared)
        if (self.pool.free_count + evictable - pinned
                - self._outstanding_total < need_new):
            return False, hit
        return True, hit

    def can_admit(self, req: Request) -> bool:
        """Side-effect-free admission probe: would :meth:`admit` admit
        ``req`` RIGHT NOW if it sat at the head of the queue? Evaluates
        the exact ledger admit() uses (:meth:`_admission_check` is the
        shared implementation) plus slot availability, without debiting
        the reservation total, pinning a hit's pages, or touching the
        cache's LRU clock — the control-plane router calls this per
        routing decision, and a probe that mutated state would skew the
        very admission it predicts."""
        if not any(s is None for s in self.slots):
            return False
        if not self.continuous and any(s is not None for s in self.slots):
            return False  # naive padded batching: drain before refill
        return self._admission_check(req)[0]

    def capacity_snapshot(self) -> dict:
        """Read-only load + capacity view (free/evictable pages, queued
        tokens) — the router's tie-break signal. ``queued_tokens`` and
        ``active_tokens_remaining`` count work still owed: prefill
        targets plus undecoded new-token budgets — on a prefill-only
        pool a request owes no decode, so only its prefill target
        counts. ``transfer_tokens_owed`` is the pages-attached ledger
        case: a TRANSFER-staged request already holds the KV of its
        materialized prefix, so it owes only the UNMATERIALIZED tail of
        its target plus its decode budget — counting its full prefill
        again would double-bill work the prefill pool already paid and
        skew routing/autoscaling load signals. Like :meth:`can_admit`,
        this never mutates anything."""
        active = self.active()

        def owed_new(r: Request) -> int:
            if self.prefill_only:
                return 0
            return max(r.max_new_tokens - len(r.generated), 0)

        snap = {
            "free_slots": sum(1 for s in self.slots if s is None),
            "num_slots": self.num_slots,
            "free_pages": self.pool.free_count,
            "evictable_pages": (self.cache.evictable_count()
                                if self.cache is not None else 0),
            "outstanding_pages": self._outstanding_total,
            "queued_requests": len(self.queue),
            "queued_tokens": sum(
                r.target_len + owed_new(r) for r in self.queue
            ),
            "active_requests": len(active),
            "active_tokens_remaining": sum(owed_new(r) for r in active),
            "transfer_requests": len(self.transfers),
            "transfer_tokens_owed": sum(
                max(s["req"].target_len - s["tokens"], 0)
                + owed_new(s["req"])
                for s in self.transfers.values()
            ),
        }
        led = self.pool.ledger
        if led is not None:
            # memory-pressure signal for the router/autoscaler: the
            # ledger forecaster's steps-to-exhaustion (None = no trend)
            s = led.steps_to_exhaustion
            snap["steps_to_exhaustion"] = (
                None if s == float("inf") else s)
        return snap

    def withdraw(self, req: Request) -> Request:
        """Remove a QUEUED request from this scheduler (control-plane
        drain: this replica gives the request up so another replica's
        :meth:`submit` can take it). Only queue members can be
        withdrawn — an active request must be :meth:`preempt`-ed back
        into the queue first, which releases its pages. Lifecycle
        timestamps survive (submit/admit both preserve existing marks),
        so withdraw → submit elsewhere books the wait between them as
        stall time, never as a fresh queue latency."""
        try:
            self.queue.remove(req)
        except ValueError:
            raise ValueError(
                f"request uid={req.uid} is not queued on this scheduler"
            )
        req.slot = None
        return req

    def admit(self, now: float) -> List[Request]:
        """Move queued requests into free slots while the pool (plus
        evictable cache pages) can cover their worst case beyond all
        outstanding reservations. A prefix-cache hit shares the matched
        pages and shrinks both the worst case and the prefill. Queued
        requests past their ``deadline_s`` are SHED first (admission is
        the deadline checkpoint). Returns the newly admitted requests
        (they still need a prefill for their unique tail, possibly
        empty chunks at a time)."""
        self._shed_expired(now)
        admitted: List[Request] = []
        if not self.continuous and any(s is not None for s in self.slots):
            return admitted  # naive padded batching: drain before refill
        while self.queue:
            free_slots = [i for i, s in enumerate(self.slots) if s is None]
            if not free_slots:
                break
            req = self.queue[0]
            target = req.target_len
            worst = self.pool.pages_for(self._worst_tokens(req))
            fits, hit = self._admission_check(req)
            led = self.pool.ledger
            if led is not None:
                # admission-pressure feed for the exhaustion forecaster:
                # the head's worst-case need, and whether memory let it in
                led.note_admission(worst, fits)
            if not fits:
                self.admission_deferrals += 1
                break  # FIFO head-of-line: deterministic admission order
            shared: List[int] = hit.pages if hit is not None else []
            need_new = worst - len(shared)
            self.queue.popleft()
            req.slot = free_slots[0]
            self.slots[req.slot] = req
            req.status = Status.PREFILL
            if req.t_admit is None:
                # FIRST admission only: a preempted request's re-admit
                # must not rewrite queue_latency_s (the attribution
                # layer books the requeue wait as stall time instead)
                req.t_admit = now
            req.cow = None
            req.pages = []
            req.prefilled_len = req.hit_tokens = 0
            if hit is not None:
                # pins shared + COW source pages, tagged to this request
                self.cache.acquire(hit, owner=req.uid)
                req.pages = list(shared)
                req.prefilled_len = hit.tokens
                req.hit_tokens = hit.total_tokens
                if hit.cow_page is not None:
                    req.cow = (hit.cow_page, hit.cow_tokens)
            cow_tokens = req.cow[1] if req.cow else 0
            chunk_end = target if self.chunk_tokens is None else min(
                req.prefilled_len + cow_tokens + self.chunk_tokens, target
            )
            n_now = self.pool.pages_for(chunk_end) - len(req.pages)
            req.pages += self._alloc(n_now, tag=("req", req.uid))
            req.outstanding = need_new - n_now
            self._outstanding_total += req.outstanding
            admitted.append(req)
            if self.tracer is not None:
                self.tracer.on_admit(req, now)
        return admitted

    def preempt(self, req: Request) -> None:
        """Mid-stream eviction under memory pressure (or an operator's
        rebalance): give back every page — shared prefix pages survive
        in the cache for the re-admission to hit — and re-queue the
        request ahead of never-admitted arrivals, ordered by ORIGINAL
        submit order among preempted peers (a bare appendleft would
        reverse two requests preempted in the same tick), so FIFO
        determinism survives any preemption pattern. Generated tokens
        are kept; re-admission re-prefills prompt + generated minus the
        pending token, which decode then resumes on."""
        if req.status not in (Status.PREFILL, Status.DECODE):
            raise ValueError(f"cannot preempt a {req.status.value} request")
        self._release_all(req)
        self._outstanding_total -= req.outstanding
        req.outstanding = 0
        self.slots[req.slot] = None
        req.slot = None
        req.prefilled_len = req.hit_tokens = 0
        req.status = Status.QUEUED
        # t_admit marks a previously admitted (re-queued) request;
        # fresh submissions have none and always sort after them
        pos = 0
        while (pos < len(self.queue)
               and self.queue[pos].t_admit is not None
               and self.queue[pos].uid < req.uid):
            pos += 1
        self.queue.insert(pos, req)
        if self.tracer is not None:
            self.tracer.on_preempt(req)

    # -- disaggregated prefill/decode (serving/disagg/) --------------------

    def finish_handoff(self, req: Request, now: float) -> None:
        """Prefill-pool exit: the request's prompt KV has been EXPORTED
        (the engine's handoff hook runs before this) — free the slot,
        the pages, and the reservation, but do NOT finish the request:
        it leaves this scheduler as ``Status.TRANSFER`` and lives on in
        the decode pool. Fires the tracer's first-token hook (the first
        token exists the moment prefill emits it — the handoff carries
        it) and opens the ``transfer`` attribution phase; ``on_done``
        belongs to the decode scheduler that finishes the request."""
        if req.status is not Status.PREFILL:
            raise ValueError(
                f"cannot hand off a {req.status.value} request"
            )
        if req.t_first_token is None:
            req.t_first_token = now
            if self.tracer is not None:
                self.tracer.on_first_token(req, now)
        self._release_all(req)
        self._outstanding_total -= req.outstanding
        req.outstanding = 0
        self.slots[req.slot] = None
        req.slot = None
        req.status = Status.TRANSFER
        if self.tracer is not None:
            self.tracer.on_transfer_start(req, now)

    def begin_transfer(self, req: Request, now: float) -> bool:
        """Stage an inbound cross-pool transfer: reserve the request's
        FULL decode worst case against ``free + evictable`` capacity
        before any page is imported, exactly like :meth:`admit` would —
        so lazy growth during the transfer and the decode that follows
        can never fail. Returns False (no side effects) when the
        ledger cannot cover it right now: the transfer queue holds the
        handoff and retries — that backpressure is the disagg engine's
        admission control.

        The staging state lives in a SCHEDULER-side record
        (``self.transfers[uid]``), never on the request: while pages
        stream, the same ``Request`` object is still live on the
        PREFILL scheduler (that is the point of streaming), so its
        ``status``/``pages``/``prefilled_len`` belong to that side
        until :meth:`admit_with_pages` takes ownership. No cache
        lookup happens: the pages come off the wire, not from this
        pool's prefix cache."""
        worst = self.pool.pages_for(self._worst_tokens(req))
        if worst > self.pool.capacity:
            raise ValueError(
                f"request worst case is {worst} pages but the pool only "
                f"has {self.pool.capacity}"
            )
        if self._worst_tokens(req) > self.max_context:
            raise ValueError(
                f"request needs {self._worst_tokens(req)} context but "
                f"the engine was sized for {self.max_context}"
            )
        if req.uid in self.transfers:
            raise ValueError(f"uid={req.uid} is already staged here")
        evictable = (self.cache.evictable_count()
                     if self.cache is not None else 0)
        if (self.pool.free_count + evictable
                - self._outstanding_total < worst):
            return False
        self.transfers[req.uid] = {
            "req": req, "pages": [], "outstanding": worst, "tokens": 0,
        }
        self._outstanding_total += worst
        return True

    def transfer_pages(self, req: Request, n_tokens: int) -> List[int]:
        """Lazy growth for a staged transfer: allocate destination
        pages to cover ``n_tokens`` materialized positions (the import
        scatters the wire payload into them) and return the stage's
        full page list. Same never-fail contract as
        :meth:`ensure_pages` — the reservation was made by
        :meth:`begin_transfer`, and the cache-ledger hole is closed by
        the same owner-retraction path."""
        stage = self.transfers.get(req.uid)
        if stage is None:
            raise RuntimeError(
                f"transfer_pages on unstaged uid={req.uid}"
            )
        while len(stage["pages"]) * self.pool.page_size < n_tokens:
            stage["pages"] += self._alloc(1, owner=req,
                                          tag=("stage", req.uid))
            stage["outstanding"] -= 1
            self._outstanding_total -= 1
        stage["tokens"] = max(stage["tokens"], n_tokens)
        return stage["pages"]

    def abort_transfer(self, req: Request) -> None:
        """Transfer failed: release every imported page and the whole
        reservation. The caller re-submits the request for a local
        re-prefill (the disagg fallback path) once the prefill pool
        has let go of it — ``submit`` restores the QUEUED lifecycle."""
        stage = self.transfers.pop(req.uid, None)
        if stage is None:
            raise ValueError(f"uid={req.uid} is not staged here")
        if stage["pages"]:
            if self.pool.ledger is not None:
                self.pool.tag = ("stage", req.uid)
            self.pool.release(stage["pages"])
        self._outstanding_total -= stage["outstanding"]

    def alloc_for_restore(self, n: int) -> List[int]:
        """Best-effort page allocation for the kv_tier restore path
        (serving/kv_tier/): evict cold cache pages like any alloc, but
        return UP TO ``n`` pages instead of retracting live requests —
        a restore is opportunistic, not owed. No ledger debit is
        needed: the caller inserts the restored chain into the prefix
        cache and releases its own reference immediately, so the pages
        re-enter the ``free + evictable`` total the reservation
        arithmetic spends — capacity is moved, never consumed."""
        if n <= 0:
            return []
        if self.cache is not None and self.pool.free_count < n:
            self.cache.evict(n - self.pool.free_count)
        got = min(n, self.pool.free_count)
        if got and self.pool.ledger is not None:
            self.pool.tag = ("restore",)
        return self.pool.alloc(got) if got else []

    def admit_with_pages(self, req: Request, first_token: Optional[int],
                         now: float, *,
                         prefilled_len: Optional[int] = None) -> bool:
        """The disagg admission: bind a fully materialized transfer to
        a free slot and SKIP prefill entirely — the pages already hold
        the prompt's KV, so the request debits nothing beyond the tail
        reservation :meth:`begin_transfer` made, and decoding starts on
        the handoff's first token immediately. Returns False when no
        slot is free (the stage keeps its pages + reservation). The
        request object must have LEFT its prefill scheduler by now
        (``finish_handoff`` marks it ``Status.TRANSFER``) — this is the
        ownership handover point where the staged pages become the
        request's own. ``t_admit`` survives from the prefill-pool
        admission (first admission wins), so queue latency stays the
        user-visible wait.

        ``prefilled_len`` < ``target_len`` is the PARTIAL variant (the
        kv_tier cross-replica pull): the staged pages cover only the
        pulled page-aligned prefix, no first token exists yet, and the
        request stays ``Status.PREFILL`` so the engine's chunked
        prefill RESUMES at ``prefilled_len`` — admission-by-transfer
        composing with the ordinary prefill machinery instead of
        bypassing it."""
        stage = self.transfers.get(req.uid)
        if stage is None:
            raise ValueError(f"uid={req.uid} is not staged here")
        if req.status is not Status.TRANSFER:
            raise ValueError(
                f"admit_with_pages needs a handed-off request, got "
                f"{req.status.value} (still live on the prefill pool?)"
            )
        free_slots = [i for i, s in enumerate(self.slots) if s is None]
        if not free_slots:
            return False
        del self.transfers[req.uid]
        req.slot = free_slots[0]
        self.slots[req.slot] = req
        req.status = Status.PREFILL   # momentary: record_token -> DECODE
        req.pages = list(stage["pages"])
        led = self.pool.ledger
        if led is not None:
            # ownership handover, no refcount change: staged transfer
            # pages become this request's KV in the ledger too
            led.retag(req.pages, ("stage", req.uid), ("req", req.uid))
        req.outstanding = stage["outstanding"]
        req.cow = None
        if req.t_admit is None:
            req.t_admit = now
        req.hit_tokens = 0
        if prefilled_len is not None and prefilled_len < req.target_len:
            if first_token is not None:
                raise ValueError(
                    "a partial admit_with_pages carries no first token "
                    "(prefill has not finished anywhere yet)"
                )
            if prefilled_len % self.pool.page_size:
                raise ValueError(
                    f"prefilled_len={prefilled_len} must be page-aligned "
                    f"(pulled pages hold whole blocks)"
                )
            req.prefilled_len = prefilled_len
            if self.tracer is not None:
                self.tracer.on_transfer_done(req, now, resume="prefill")
            return True
        req.prefilled_len = req.target_len
        if self.tracer is not None:
            self.tracer.on_transfer_done(req, now)
        self.record_token(req, int(first_token), now)
        return True

    def ensure_pages(self, req: Request, n_tokens: int) -> None:
        """Lazy growth to cover ``n_tokens`` cached positions (decode:
        one past the pending write; chunked prefill: the chunk's end;
        speculation: the draft bundle's end). Cannot fail: admission
        reserved the worst case against free + evictable capacity, and
        the one hole in that ledger — a LATER ``insert`` hanging a
        live request's child under a node an earlier admission already
        credited as evictable, which makes the ancestor unrecoverable
        with no debit — is closed by RETRACTION (``_alloc(owner=req)``
        preempts the newest other active request; it re-queues and
        re-prefills through the cache). The submit-time
        ``worst <= capacity`` check guarantees retraction terminates:
        with every other request preempted and the cache drained, the
        owner's worst case always fits."""
        if req.status not in (Status.PREFILL, Status.DECODE):
            # growing a slotless request would drive its reservation
            # negative and leak the pages at re-admission — callers
            # iterating a materialized batch must re-check status after
            # any neighbor's ensure_pages (it may have retracted them)
            raise RuntimeError(
                f"ensure_pages on a {req.status.value} request "
                f"(retracted mid-batch by a neighbor's lazy growth?)"
            )
        while len(req.pages) * self.pool.page_size < n_tokens:
            req.pages += self._alloc(1, owner=req, tag=("req", req.uid))
            req.outstanding -= 1
            self._outstanding_total -= 1

    def ensure_page(self, req: Request) -> None:
        """Decode-step growth: cover the pending token's write position."""
        self.ensure_pages(req, req.cached_len + 1)

    def record_token(self, req: Request, token: int, now: float) -> None:
        if req.t_first_token is None:
            req.t_first_token = now
            if self.tracer is not None:
                self.tracer.on_first_token(req, now)
        req.status = Status.DECODE
        req.generated.append(int(token))
        if req.eos_token_id is not None and int(token) == req.eos_token_id:
            self._finish(req, "eos", now)
        elif len(req.generated) >= req.max_new_tokens:
            self._finish(req, "length", now)

    def _alloc(self, n: int, owner: Optional[Request] = None,
               tag=None) -> List[int]:
        """Pool alloc that treats LRU-evictable cache pages as free.
        With ``owner`` set (the must-not-fail reservation path), a
        shortfall that eviction cannot cover retracts newest-first
        OTHER active requests until it can — see :meth:`ensure_pages`.
        Admission never passes ``owner``: its ledger check and alloc
        are atomic within one ``admit`` iteration (no insert can
        intervene), and a blocked admission simply waits. ``tag`` is
        the memory-ledger owner label for the allocated pages."""
        if n <= 0:
            return []
        if self.cache is not None and self.pool.free_count < n:
            self.cache.evict(n - self.pool.free_count)
            if self.pool.free_count < n and owner is not None:
                for victim in sorted(
                    (r for r in self.slots
                     if r is not None and r is not owner),
                    key=lambda r: r.uid, reverse=True,
                ):
                    self.preempt(victim)
                    self.cache.evict(n - self.pool.free_count)
                    if self.pool.free_count >= n:
                        break
        if self.pool.ledger is not None:
            # set AFTER any eviction/retraction above: those release
            # with their own tags, each event consuming the one-shot tag
            self.pool.tag = tag if tag is not None else (
                ("req", owner.uid) if owner is not None else None)
        return self.pool.alloc(n)

    def _release_all(self, req: Request) -> None:
        if req.cow is not None:          # un-run COW copy: drop the pin
            if self.pool.ledger is not None:
                self.pool.tag = ("cow", req.uid)
            self.pool.release([req.cow[0]])
            req.cow = None
        if req.pages:
            if self.pool.ledger is not None:
                self.pool.tag = ("req", req.uid)
            self.pool.release(req.pages)
            req.pages = []

    def _finish(self, req: Request, reason: str, now: float) -> None:
        req.status = Status.DONE
        req.finish_reason = reason
        req.t_done = now
        self._release_all(req)
        self._outstanding_total -= req.outstanding
        req.outstanding = 0
        self.slots[req.slot] = None
        if self.tracer is not None:
            self.tracer.on_done(req, now)

    # -- queries -----------------------------------------------------------

    def active(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    def all_done(self) -> bool:
        return not self.queue and all(s is None for s in self.slots)
