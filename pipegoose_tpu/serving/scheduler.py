"""Continuous-batching scheduler: request lifecycle + slot/page admission.

The request lifecycle is QUEUED -> PREFILL -> DECODE -> DONE. A fixed
number of decode SLOTS bounds the jitted step's batch dim (static
shapes); the scheduler's job is to keep those slots full:

- **admission** pops the FIFO queue into free slots whenever the page
  pool can cover the candidate's WORST-CASE footprint
  (``ceil((prompt + max_new) / page_size)``) on top of every active
  request's outstanding reservation. Pages are then allocated LAZILY —
  prompt pages at admission, decode pages one at a time as the write
  position crosses a page boundary — so short-finishing requests never
  hold their worst case, while the reservation arithmetic guarantees a
  lazy ``alloc`` can never fail mid-flight. Head-of-line blocking is
  deliberate: FIFO admission keeps the schedule deterministic.
- **eviction** frees a finished request's pages and reservation the
  step its last token is emitted, so the next ``admit`` can re-use both
  the slot and the pages mid-stream (continuous batching).

``continuous=False`` turns the same machinery into the naive padded
baseline: a batch is admitted only into an EMPTY slot set and drains
fully before the next one — slots idle behind the batch's longest
member exactly the way padded ``generate`` rows do, which is the A/B
the serving bench measures.
"""
from __future__ import annotations

import enum
from collections import deque
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from pipegoose_tpu.serving.kv_pool import PagePool


class Status(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    DONE = "done"


@dataclass
class Request:
    """One generation request. Engine/scheduler fill the lifecycle
    fields; callers provide the first three."""

    prompt: np.ndarray                 # (S,) token ids
    max_new_tokens: int
    eos_token_id: Optional[int] = None

    uid: Optional[int] = None
    status: Status = Status.QUEUED
    generated: List[int] = field(default_factory=list)
    slot: Optional[int] = None
    pages: List[int] = field(default_factory=list)
    outstanding: int = 0               # worst-case pages not yet allocated
    finish_reason: Optional[str] = None
    t_submit: Optional[float] = None
    t_admit: Optional[float] = None
    t_first_token: Optional[float] = None
    t_done: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return int(np.asarray(self.prompt).shape[0])

    @property
    def cached_len(self) -> int:
        """Tokens currently in the KV pages: the whole prompt plus every
        generated token except the pending one (the decode step writes
        the pending token before attending)."""
        return self.prompt_len + max(len(self.generated) - 1, 0)

    @property
    def tokens(self) -> np.ndarray:
        return np.concatenate(
            [np.asarray(self.prompt, np.int64),
             np.asarray(self.generated, np.int64)]
        )


class Scheduler:
    def __init__(self, num_slots: int, pool: PagePool, max_context: int,
                 continuous: bool = True):
        if num_slots < 1:
            raise ValueError("need at least one decode slot")
        self.num_slots = num_slots
        self.pool = pool
        self.max_context = max_context
        self.continuous = continuous
        self.slots: List[Optional[Request]] = [None] * num_slots
        self.queue: deque = deque()
        self._outstanding_total = 0
        self._next_uid = 0

    # -- lifecycle ---------------------------------------------------------

    def submit(self, req: Request, now: float) -> None:
        worst = self.pool.pages_for(req.prompt_len + req.max_new_tokens)
        if req.prompt_len < 1:
            raise ValueError("empty prompt")
        if req.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if req.prompt_len + req.max_new_tokens > self.max_context:
            raise ValueError(
                f"request needs {req.prompt_len + req.max_new_tokens} "
                f"context but the engine was sized for {self.max_context}"
            )
        if worst > self.pool.capacity:
            raise ValueError(
                f"request worst case is {worst} pages but the pool only "
                f"has {self.pool.capacity}"
            )
        req.uid = self._next_uid
        self._next_uid += 1
        req.t_submit = now
        req.status = Status.QUEUED
        self.queue.append(req)

    def admit(self, now: float) -> List[Request]:
        """Move queued requests into free slots while the pool can cover
        their worst case beyond all outstanding reservations. Returns the
        newly admitted requests (they still need a prefill)."""
        admitted: List[Request] = []
        if not self.continuous and any(s is not None for s in self.slots):
            return admitted  # naive padded batching: drain before refill
        while self.queue:
            free_slots = [i for i, s in enumerate(self.slots) if s is None]
            if not free_slots:
                break
            req = self.queue[0]
            worst = self.pool.pages_for(req.prompt_len + req.max_new_tokens)
            if self.pool.free_count - self._outstanding_total < worst:
                break  # FIFO head-of-line: deterministic admission order
            self.queue.popleft()
            req.slot = free_slots[0]
            self.slots[req.slot] = req
            req.status = Status.PREFILL
            req.t_admit = now
            n_prompt = self.pool.pages_for(req.prompt_len)
            req.pages = self.pool.alloc(n_prompt)
            req.outstanding = worst - n_prompt
            self._outstanding_total += req.outstanding
            admitted.append(req)
        return admitted

    def ensure_page(self, req: Request) -> None:
        """Lazy growth: allocate the next page when the pending token's
        write position crosses into unallocated territory. Cannot fail —
        admission reserved the worst case."""
        pos = req.cached_len  # position the next step writes
        if pos >= len(req.pages) * self.pool.page_size:
            req.pages += self.pool.alloc(1)
            req.outstanding -= 1
            self._outstanding_total -= 1

    def record_token(self, req: Request, token: int, now: float) -> None:
        if req.t_first_token is None:
            req.t_first_token = now
        req.status = Status.DECODE
        req.generated.append(int(token))
        if req.eos_token_id is not None and int(token) == req.eos_token_id:
            self._finish(req, "eos", now)
        elif len(req.generated) >= req.max_new_tokens:
            self._finish(req, "length", now)

    def _finish(self, req: Request, reason: str, now: float) -> None:
        req.status = Status.DONE
        req.finish_reason = reason
        req.t_done = now
        self.pool.free(req.pages)
        req.pages = []
        self._outstanding_total -= req.outstanding
        req.outstanding = 0
        self.slots[req.slot] = None

    # -- queries -----------------------------------------------------------

    def active(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    def all_done(self) -> bool:
        return not self.queue and all(s is None for s in self.slots)
