"""Content-addressed prefix cache over the paged KV pool.

A fleet of requests sharing a system prompt / few-shot prefix
re-computes and re-stores the same KV pages once per request through
the plain engine — ROADMAP open item 1 names that the top serving
bottleneck. This module is the HOST-side index that turns the refcounted
:class:`~pipegoose_tpu.serving.kv_pool.PagePool` into a
content-addressed store:

- **Hash granularity = one page.** The trie is keyed by page-aligned
  token BLOCKS (the exact ``page_size`` token ids that produced a page's
  KV), chained parent→child, so a lookup walks the prompt page by page —
  a radix tree over blocks, vLLM/SGLang-style. Keying on the full block
  chain (not a rolling hash) makes false sharing impossible: equal chain
  ⇒ equal token prefix ⇒ equal KV (the model is deterministic).
- **Sharing = refcount.** A hit bumps each matched page's refcount
  (``pool.share``); the cache itself holds one reference per cached
  page, so pages survive their creator request. A request's release at
  finish drops its reference — cached pages fall back to refcount 1
  (cache-only) and become evictable, never dangling.
- **COW for mid-page tails.** When the prompt diverges from (or ends
  inside) a cached child block, the longest matching HEAD of that block
  is still valid KV — ``lookup`` reports it as a copy-on-write
  candidate and the engine duplicates the page
  (:func:`~pipegoose_tpu.serving.kv_pool.copy_page`) before the new
  request writes its own tail mid-page. The shared page is never
  written by anyone but its creator-by-construction.
- **Eviction = refcount-1 LRU leaves.** Only pages no live request
  shares (refcount 1: the cache's own reference) can be evicted, and
  only trie LEAVES (evicting an inner node would orphan its reachable
  children) — least-recently-touched first, driven by a monotonic
  clock so the order is deterministic. ``evictable_count`` feeds the
  scheduler's admission ledger: reservation math counts
  ``free + evictable`` as the true capacity, and pins (hit pages moving
  refcount 1→2) are debited so an earlier request's worst-case
  reservation can never be stranded by a later hit.

The cache never touches device memory — it maps token content to page
IDS; all KV bytes stay in the pool buffers.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from pipegoose_tpu.serving.kv_pool import PagePool


class _Node:
    """One cached page: the block of token ids it holds + trie links."""

    __slots__ = ("block", "page", "parent", "children", "last_used")

    def __init__(self, block: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.block = block
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0

    def __repr__(self):  # debugging only
        return f"_Node(page={self.page}, used={self.last_used})"


@dataclass
class PrefixHit:
    """Result of a lookup: ``pages`` are fully matched shared pages
    (``tokens = len(pages) * page_size`` prompt tokens whose KV needs no
    prefill), ``cow_page``/``cow_tokens`` an optional partially matched
    page whose first ``cow_tokens`` positions are valid after a
    copy-on-write duplication. ``nodes`` is the matched trie chain (for
    recency touching at acquire time — lookup itself is side-effect
    free, so a failed admission leaves the LRU order untouched)."""

    pages: List[int] = field(default_factory=list)
    tokens: int = 0
    cow_page: Optional[int] = None
    cow_tokens: int = 0
    nodes: List[_Node] = field(default_factory=list)

    @property
    def total_tokens(self) -> int:
        return self.tokens + self.cow_tokens


class PrefixCache:
    """Radix index mapping page-aligned prompt prefixes to pool pages."""

    def __init__(self, pool: PagePool):
        self.pool = pool
        self.page_size = pool.page_size
        self._roots: Dict[Tuple[int, ...], _Node] = {}
        # flat view for eviction scans, keyed by identity so removal is
        # O(1) (a list's .remove would make pressure eviction O(N^2))
        self._nodes: Dict[int, _Node] = {}
        self._clock = 0                 # deterministic LRU ordering
        # eviction intercept (serving/kv_tier/): called as
        # ``spill_hook(token_chain, page)`` BEFORE a victim's page goes
        # back to the pool — the engine's hook exports the page's KV to
        # the host tier so the prefix survives eviction. Best-effort by
        # contract: eviction MUST proceed either way (the scheduler's
        # never-fail reservation arithmetic rests on evict recovering
        # pages), so a failing hook loses the tier copy, never the pool
        # page.
        self.spill_hook = None

    # -- queries -----------------------------------------------------------

    @property
    def cached_pages(self) -> int:
        return len(self._nodes)

    def evictable_count(self) -> int:
        """Pages leaf-first eviction can ACTUALLY recover right now: a
        node counts only when its page has refcount 1 (cache-only) and
        its entire subtree does too. The subtree condition is not
        implied by refcounts alone — ``insert`` can hang a new
        request's child under an existing node WITHOUT the inserter
        referencing the parent chain (it only shares pages it adds), so
        a refcount-1 inner node may sit above a pinned child and never
        become a leaf while that child lives. The scheduler's admission
        ledger treats this count as spendable capacity (its never-fail
        reservation contract rests on it), so it must be exact, not an
        upper bound."""
        memo = {}

        def recoverable(node: _Node) -> bool:
            got = memo.get(id(node))
            if got is None:
                got = self.pool.refcount(node.page) == 1 and all(
                    recoverable(c) for c in node.children.values()
                )
                memo[id(node)] = got
            return got

        return sum(1 for n in self._nodes.values() if recoverable(n))

    def longest_prefix_len(self, tokens: Sequence[int]) -> int:
        """TOKEN-granular length of the longest cached prefix of
        ``tokens``: fully matched pages plus the longest matching head
        of a partially matched (COW-candidate) page. Built on the
        side-effect-free :meth:`lookup`, so probing NEVER pins a page,
        never touches the LRU clock, and never evicts (pinned by test)
        — this is the read-only probe the control-plane router calls
        against every replica per routing decision. Capped at
        ``len(tokens) - 1`` exactly like admission's lookup (at least
        one token must always be forwarded to produce logits), so the
        router's score equals the hit the chosen replica will see."""
        n = len(np.asarray(tokens))
        if n <= 1:
            return 0
        return self.lookup(tokens, max_tokens=n - 1).total_tokens

    def restorable_len(self, tokens: Sequence[int], tier,
                       max_tokens: Optional[int] = None) -> int:
        """Tier-aware probe: token length of the longest prefix servable
        WITHOUT recompute — the HBM hit's full pages plus the contiguous
        run of host-tier blocks extending it (the first gap stops the
        walk: a restore must land front-to-back). COW partials do not
        extend into the tier (a tier entry is keyed by the exact block
        chain). Side-effect free like :meth:`lookup` — never touches
        the tier's LRU order either (``tier.contains``)."""
        toks = [int(t) for t in np.asarray(tokens)]
        cap = len(toks) if max_tokens is None else min(max_tokens, len(toks))
        ps = self.page_size
        hit = self.lookup(toks, max_tokens=cap)
        if tier is None:
            return hit.tokens
        i = hit.tokens // ps
        while (i + 1) * ps <= cap and tier.contains(
                tuple(toks[:(i + 1) * ps])):
            i += 1
        return i * ps

    def lookup(self, tokens: Sequence[int], max_tokens: Optional[int] = None
               ) -> PrefixHit:
        """Longest cached prefix of ``tokens``, capped at ``max_tokens``
        (callers cap at ``len(tokens) - 1``: at least one token must be
        forwarded to produce logits). Full-page matches come first; if
        the walk stops mid-trie, the child block sharing the longest
        HEAD with the remaining tokens becomes the COW candidate.
        Side-effect free — pair with :meth:`acquire`."""
        toks = [int(t) for t in np.asarray(tokens)]
        cap = len(toks) if max_tokens is None else min(max_tokens, len(toks))
        ps = self.page_size
        hit = PrefixHit()
        children = self._roots
        i = 0
        while (i + 1) * ps <= cap:
            blk = tuple(toks[i * ps:(i + 1) * ps])
            node = children.get(blk)
            if node is None:
                break
            hit.pages.append(node.page)
            hit.nodes.append(node)
            children = node.children
            i += 1
        hit.tokens = i * ps
        rem = toks[i * ps:cap]
        if rem and children:
            best, best_m = None, 0
            # sorted iteration: deterministic winner among equal-length
            # head matches (block order, then page id, is stable)
            for blk in sorted(children):
                m = 0
                for a, b in zip(blk, rem):
                    if a != b:
                        break
                    m += 1
                if m > best_m:
                    best, best_m = children[blk], m
            if best is not None:
                hit.cow_page = best.page
                hit.cow_tokens = best_m
                hit.nodes.append(best)
        return hit

    # -- mutation ----------------------------------------------------------

    def acquire(self, hit: PrefixHit, owner=None) -> None:
        """Take one reference per matched page on behalf of a request
        and refresh the chain's recency. The COW candidate is pinned
        TOO: the copy is a device op the engine performs a tick later,
        and an eviction in between could hand the source page to a new
        owner who overwrites it — the engine releases the pin right
        after :func:`~pipegoose_tpu.serving.kv_pool.copy_page` runs.
        ``owner`` (a request uid, or None for anonymous probe pins)
        labels the references for the memory ledger."""
        pool = self.pool
        if hit.pages:
            if pool.ledger is not None:
                pool.tag = ("req", owner)
            pool.share(hit.pages)
        if hit.cow_page is not None:
            if pool.ledger is not None:
                pool.tag = ("cow", owner)
            pool.share([hit.cow_page])
        for node in hit.nodes:
            self._clock += 1
            node.last_used = self._clock
        return None

    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Register a prefilled request's page-aligned prefix: page ``i``
        of ``pages`` holds the KV of tokens ``[i*ps, (i+1)*ps)``. Only
        FULL pages are inserted (a partial tail page keeps growing under
        its owner — its content is not stable). Existing nodes win (two
        requests racing the same prefix converge on the first's pages;
        the second's stay private). Each newly inserted page gains the
        cache's own reference. Returns the number of new nodes."""
        toks = [int(t) for t in np.asarray(tokens)]
        ps = self.page_size
        n_full = min(len(toks) // ps, len(pages))
        children = self._roots
        parent = None
        added = 0
        for i in range(n_full):
            blk = tuple(toks[i * ps:(i + 1) * ps])
            node = children.get(blk)
            if node is None:
                node = _Node(blk, int(pages[i]), parent)
                if self.pool.ledger is not None:
                    self.pool.tag = ("cache",)
                self.pool.share([node.page])
                children[blk] = node
                self._nodes[id(node)] = node
                added += 1
            self._clock += 1
            node.last_used = self._clock
            parent = node
            children = node.children
        return added

    def evict(self, n: int) -> int:
        """Free up to ``n`` pages back to the pool: repeatedly drop the
        least-recently-used LEAF whose page only the cache references.
        Returns the number actually freed (< n when everything left is
        pinned by live requests)."""
        freed = 0
        while freed < n:
            victim = None
            for node in self._nodes.values():
                if node.children or self.pool.refcount(node.page) != 1:
                    continue
                if victim is None or node.last_used < victim.last_used:
                    victim = node
            if victim is None:
                break
            if self.spill_hook is not None:
                try:
                    self.spill_hook(self._chain(victim), victim.page)
                except Exception:
                    # spill is best-effort: the tier copy is lost, the
                    # eviction (and the reservation ledger resting on
                    # it) proceeds regardless
                    pass
            self._remove(victim)
            if self.pool.ledger is not None:
                self.pool.tag = ("cache",)
            self.pool.release([victim.page])
            freed += 1
        return freed

    def _chain(self, node: _Node) -> Tuple[int, ...]:
        """The full token chain that produced ``node``'s page — root
        block through ``node.block`` inclusive (the host tier's key and
        the spill black box's name for the prefix)."""
        blocks = []
        cur: Optional[_Node] = node
        while cur is not None:
            blocks.append(cur.block)
            cur = cur.parent
        out: List[int] = []
        for blk in reversed(blocks):
            out.extend(blk)
        return tuple(out)

    def clear(self) -> int:
        """Drop every unpinned page (tests / shutdown). Pinned pages
        stay — their requests still read them."""
        return self.evict(len(self._nodes))

    def _remove(self, node: _Node) -> None:
        siblings = (node.parent.children if node.parent is not None
                    else self._roots)
        del siblings[node.block]
        del self._nodes[id(node)]
