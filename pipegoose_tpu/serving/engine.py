"""Synchronous continuous-batching serving engine over the paged pool.

``ServingEngine.run(requests)`` drives the host-side loop the ROADMAP's
"heavy traffic" north star needs above the per-call ``generate()``:

    while work remains:
        admit queued requests into free slots        (scheduler.admit)
        prefill each admission, scatter its KV pages (one jitted program
                                                      per page bucket)
        one jitted decode step over ALL active slots (paged_decode_step)
        record tokens; evict finished, reclaim pages (scheduler)

Everything device-side is compiled with STATIC shapes: the decode step
is one program for the (num_slots, page-table-width) layout regardless
of which slots are live, and prefills bucket prompt lengths to page
multiples (LEFT-padded through the existing ragged-mask machinery, then
repacked unpadded into pages) so at most ``max_context / page_size``
prefill programs ever compile. Page buffers are DONATED through every
step — the pool lives in place, never copied.

Greedy decoding only (the continuous-batching contract here is
token-identity with per-request ``generate()``); under a mesh the whole
step runs in shard_map with head-sharded pages and
``global_greedy_pick`` over the vocab shards, exactly like
models/_decode.py's sharded driver.

Metrics follow utils/profiler.py's convention of returning plain dicts
the caller can JSON-dump: per-request queue latency / TTFT / decode
tok/s, plus aggregate slot and page occupancy (the utilization numbers
that justify continuous batching over padded batches).

The engine is additionally instrumented against the telemetry registry
(pipegoose_tpu/telemetry/): queue-depth / occupancy gauges and events
per decode step (a live TIME SERIES, where the end-of-run dict can only
average), TTFT and per-token decode-latency histograms, token/prefill
counters, and prefill/decode spans. Disabled-registry cost is one
branch per site; pass ``registry=`` or enable the global one to record.
The legacy aggregate dict keeps its exact keys — ``serving_ab_benchmark``
and existing callers parse it.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from pipegoose_tpu.distributed.compat import shard_map
from pipegoose_tpu.models._decode import (
    global_greedy_pick,
    greedy_token,
    vocab_mask_for,
)
from pipegoose_tpu.models.generate import forward_cached, init_cache
from pipegoose_tpu.serving.kv_pool import (
    PagePool,
    init_pages,
    paged_decode_step,
    write_prompt_pages,
)
from pipegoose_tpu.serving.scheduler import Request, Scheduler, Status
from pipegoose_tpu.telemetry.registry import get_registry
from pipegoose_tpu.telemetry.spans import span


@dataclass
class RequestOutput:
    uid: int
    prompt: np.ndarray
    generated: np.ndarray
    finish_reason: str
    queue_latency_s: float
    ttft_s: float
    decode_tokens_per_s: float
    e2e_latency_s: float = 0.0  # submit -> done wall time

    @property
    def tokens(self) -> np.ndarray:
        return np.concatenate([np.asarray(self.prompt, np.int64),
                               np.asarray(self.generated, np.int64)])


class ServingEngine:
    """Greedy continuous-batching inference over a paged KV pool.

    ``num_slots`` bounds the decode batch, ``num_pages * page_size`` the
    pooled KV capacity, ``max_context`` the per-request prompt+new
    budget (it fixes the page-table width, i.e. the attention span the
    step compiles for). Pass ``mesh``/``param_specs`` for tensor
    parallelism (vocab/head-sharded params, same contract as
    ``generate_tp``); ``continuous=False`` degrades the scheduler to
    naive padded batching for A/B measurement."""

    def __init__(self, params, config, *, num_slots: int = 4,
                 num_pages: int = 64, page_size: int = 16,
                 max_context: int = 256, mesh=None, param_specs=None,
                 tp_axis: str = "tensor", continuous: bool = True,
                 registry=None, recorder=None, stall_patience: int = 100):
        """``recorder``: optional ``telemetry.FlightRecorder`` — every
        decode step lands in its ring, and the no-decode-progress
        watchdog dumps a black box through it before raising.
        ``stall_patience``: scheduler iterations that admit nothing and
        decode nothing before the watchdog declares a stall (admission
        is deterministic, so a genuinely stuck queue stops progressing
        after ONE such iteration; the slack absorbs future time-based
        admission policies)."""
        if max_context % page_size:
            raise ValueError("max_context must be a multiple of page_size")
        if stall_patience < 1:
            raise ValueError(f"stall_patience must be >= 1, got {stall_patience}")
        self.recorder = recorder
        self.stall_patience = stall_patience
        self.registry = registry if registry is not None else get_registry()
        # resolve metric handles ONCE: inc/set/observe check the enabled
        # flag themselves, so the hot loop's disabled cost stays one
        # branch per site (no per-step registry lock + name lookup)
        reg = self.registry
        self._m_tokens = reg.counter("serving.tokens_total")
        self._m_prefills = reg.counter("serving.prefills_total")
        self._m_steps = reg.counter("serving.decode_steps_total")
        self._m_ttft = reg.histogram("serving.ttft_seconds")
        self._m_tok_lat = reg.histogram("serving.decode_token_seconds")
        self._m_e2e = reg.histogram("serving.e2e_latency_seconds")
        self._m_queue = reg.gauge("serving.queue_depth")
        self._m_active = reg.gauge("serving.slots_active")
        self._m_slot_occ = reg.gauge("serving.slot_occupancy")
        self._m_page_occ = reg.gauge("serving.page_occupancy")
        self._m_tps = reg.gauge("serving.tokens_per_s")
        self.params = params
        self.config = config
        self.num_slots = num_slots
        self.page_size = page_size
        self.table_width = max_context // page_size
        self.mesh = mesh
        self.param_specs = param_specs
        self.tp_axis = tp_axis
        tp = mesh.shape[tp_axis] if mesh is not None else 1
        if config.n_head % tp:
            raise ValueError(f"n_head={config.n_head} not divisible by tp={tp}")
        self.pool = PagePool(num_pages, page_size)
        self.sched = Scheduler(num_slots, self.pool, max_context,
                               continuous=continuous)
        self.k_pages, self.v_pages = init_pages(config, num_pages, page_size)
        valid = getattr(config, "valid_vocab_size", None)
        mask_fn = vocab_mask_for(config)

        if mesh is None:
            def _prefill(params, ids, mask):
                cache = init_cache(config, 1, ids.shape[1])
                logits, cache = forward_cached(
                    params, ids, cache, 0, config, extras={"mask": mask}
                )
                return greedy_token(logits, mask_fn), cache

            def _write(k_pages, v_pages, cache, phys, pad):
                return write_prompt_pages(
                    k_pages, v_pages, cache, phys, pad, page_size
                )

            def _step(params, tokens, k_pages, v_pages, table, seq_lens):
                logits, k_pages, v_pages = paged_decode_step(
                    params, tokens, k_pages, v_pages, table, seq_lens, config
                )
                return greedy_token(logits, mask_fn), k_pages, v_pages

            self._prefill = jax.jit(_prefill)
            self._write = jax.jit(_write, donate_argnums=(0, 1))
            self._step = jax.jit(_step, donate_argnums=(2, 3))
        else:
            pspec = P(None, None, None, tp_axis, None)   # pages: head-sharded
            cspec = {"k": pspec, "v": pspec}             # cache: same layout

            def _prefill_body(params, ids, mask):
                cache = init_cache(config, 1, ids.shape[1], tp)
                logits, cache = forward_cached(
                    params, ids, cache, 0, config, tp_axis,
                    extras={"mask": mask},
                )
                return global_greedy_pick(logits, tp_axis, valid), cache

            def _write_body(k_pages, v_pages, cache, phys, pad):
                return write_prompt_pages(
                    k_pages, v_pages, cache, phys, pad, page_size
                )

            def _step_body(params, tokens, k_pages, v_pages, table, seq_lens):
                logits, k_pages, v_pages = paged_decode_step(
                    params, tokens, k_pages, v_pages, table, seq_lens,
                    config, tp_axis,
                )
                tok = global_greedy_pick(logits, tp_axis, valid)
                return tok, k_pages, v_pages

            self._prefill = jax.jit(shard_map(
                _prefill_body, mesh=mesh,
                in_specs=(param_specs, P(), P()), out_specs=(P(), cspec),
                check_vma=False,
            ))
            self._write = jax.jit(shard_map(
                _write_body, mesh=mesh,
                in_specs=(pspec, pspec, cspec, P(), P()),
                out_specs=(pspec, pspec), check_vma=False,
            ), donate_argnums=(0, 1))
            self._step = jax.jit(shard_map(
                _step_body, mesh=mesh,
                in_specs=(param_specs, P(), pspec, pspec, P(), P()),
                out_specs=(P(), pspec, pspec), check_vma=False,
            ), donate_argnums=(2, 3))
            sharding = NamedSharding(mesh, pspec)
            self.k_pages = jax.device_put(self.k_pages, sharding)
            self.v_pages = jax.device_put(self.v_pages, sharding)
            self._pspec = pspec

    def doctor(self, large_bytes: int = 1 << 20, registry=None):
        """Mesh-doctor report (telemetry/doctor.py) for the compiled
        paged DECODE step — the serving hot path: actual shardings of
        params and KV pages diffed against the engine's intended specs
        (head-sharded pages under TP), the collective schedule
        (``global_greedy_pick``'s all_gathers are the only intended
        traffic), and the per-device HBM budget dominated by the page
        pool. Shape-only: nothing executes, no pages are touched."""
        from pipegoose_tpu.telemetry.doctor import diagnose, set_doctor_gauges

        i32 = jnp.int32
        tokens = jax.ShapeDtypeStruct((self.num_slots,), i32)
        table = jax.ShapeDtypeStruct((self.num_slots, self.table_width), i32)
        seq_lens = jax.ShapeDtypeStruct((self.num_slots,), i32)
        intended = None
        if self.mesh is not None:
            intended = (self.param_specs, P(), self._pspec, self._pspec,
                        P(), P())
        report = diagnose(
            self._step, self.params, tokens, self.k_pages, self.v_pages,
            table, seq_lens,
            intended=intended,
            labels=("params", "tokens", "k_pages", "v_pages", "table",
                    "seq_lens"),
            mesh=self.mesh, large_bytes=large_bytes,
        )
        set_doctor_gauges(report, registry=registry or self.registry)
        return report

    # -- internals ---------------------------------------------------------

    def _prefill_request(self, req: Request, now) -> None:
        """Run the bucketed prefill, scatter the prompt KV into the
        request's pages, and record the first generated token."""
        with span("serving.prefill", registry=self.registry):
            s = req.prompt_len
            bucket = self.pool.pages_for(s) * self.page_size
            pad = bucket - s
            ids = np.zeros((1, bucket), np.int32)
            ids[0, pad:] = np.asarray(req.prompt, np.int32)
            mask = np.zeros((1, bucket), np.int32)
            mask[0, pad:] = 1
            tok, cache = self._prefill(
                self.params, jnp.asarray(ids), jnp.asarray(mask)
            )
            phys = np.zeros((self.table_width,), np.int32)
            phys[:len(req.pages)] = req.pages
            self.k_pages, self.v_pages = self._write(
                self.k_pages, self.v_pages, cache, jnp.asarray(phys),
                jnp.asarray(pad, jnp.int32),
            )
            # the token fetch syncs the device, so the span's wall time
            # covers the prefill's actual device work
            self.sched.record_token(req, int(np.asarray(tok)[0]), now())
        self._m_prefills.inc()
        self._m_tokens.inc()  # the prefill's token
        if req.t_first_token is not None and req.t_submit is not None:
            self._m_ttft.observe(req.t_first_token - req.t_submit)

    def _stall(self, steps: int, wall_s: float) -> None:
        """No-decode-progress watchdog tripped: dump a black box (when a
        recorder is attached) and raise instead of livelocking."""
        queued = len(self.sched.queue)
        head = self.sched.queue[0] if queued else None
        reason = (
            f"no decode progress for {self.stall_patience} scheduler "
            f"iterations: {queued} queued, 0 active, "
            f"{self.pool.free_count}/{self.pool.capacity} pages free"
        )
        if head is not None:
            worst = self.pool.pages_for(head.prompt_len + head.max_new_tokens)
            reason += (
                f"; queue head uid={head.uid} needs {worst} pages worst-case"
            )
        where = ""
        if self.recorder is not None:
            trig = self.recorder.trigger_decode_stall(
                steps, reason,
                context={
                    "num_slots": self.num_slots,
                    "page_size": self.page_size,
                    "pages_free": self.pool.free_count,
                    "pages_total": self.pool.capacity,
                    "queued": queued,
                    "decode_steps": steps,
                    "wall_s": wall_s,
                },
            )
            if trig.dump_path:
                where = f" (black box: {trig.dump_path})"
        raise RuntimeError(f"serving decode stall: {reason}{where}")

    # -- API ---------------------------------------------------------------

    def run(self, requests: Sequence[Request], now=time.perf_counter):
        """Serve ``requests`` to completion; returns
        (list[RequestOutput] in submit order, aggregate-metrics dict)."""
        reg = self.registry
        for r in requests:
            self.sched.submit(r, now())
        self._m_queue.set(len(self.sched.queue))
        tok0 = self._m_tokens.value
        done: List[Request] = []
        steps = prefills = 0
        occ_slots = occ_pages = 0.0
        table = np.zeros((self.num_slots, self.table_width), np.int32)
        seq_lens = np.zeros((self.num_slots,), np.int32)
        tokens = np.zeros((self.num_slots,), np.int32)
        t0 = now()
        stalled = 0
        while not self.sched.all_done():
            admitted = self.sched.admit(now())
            for req in admitted:
                self._prefill_request(req, now)
                prefills += 1
                if req.status is Status.DONE:
                    done.append(req)
            active = self.sched.active()
            self._m_queue.set(len(self.sched.queue))
            if not active:
                # no admission AND no decode work: nothing in this loop
                # is time-dependent, so repeated no-progress iterations
                # mean the queue is stuck (e.g. a reservation the pool
                # can never cover). The watchdog turns that silent
                # livelock into a black-box dump + a loud error.
                if admitted:
                    stalled = 0
                else:
                    stalled += 1
                    if stalled >= self.stall_patience:
                        self._stall(steps, now() - t0)
                continue  # everything admitted finished at prefill
            stalled = 0
            table.fill(0)
            seq_lens.fill(0)
            tokens.fill(0)
            for req in active:
                self.sched.ensure_page(req)
                table[req.slot, :len(req.pages)] = req.pages
                seq_lens[req.slot] = req.cached_len
                tokens[req.slot] = req.generated[-1]
            t_step = now()
            with span("serving.decode_step", registry=reg):
                nxt, self.k_pages, self.v_pages = self._step(
                    self.params, jnp.asarray(tokens), self.k_pages,
                    self.v_pages, jnp.asarray(table), jnp.asarray(seq_lens),
                )
                nxt = np.asarray(nxt)  # host fetch syncs: span = device work
            t = now()
            steps += 1
            slot_occ = len(active) / self.num_slots
            page_occ = self.pool.used_count / self.pool.capacity
            occ_slots += slot_occ
            occ_pages += page_occ
            # every active slot received exactly one token this step, so
            # the step latency IS the per-token decode latency
            self._m_tok_lat.observe(t - t_step)
            self._m_steps.inc()
            self._m_tokens.inc(len(active))
            self._m_active.set(len(active))
            self._m_slot_occ.set(slot_occ)
            self._m_page_occ.set(page_occ)
            # the occupancy TIME SERIES the end-of-run averages flatten
            reg.event("serving.step", step=steps, active=len(active),
                      queue_depth=len(self.sched.queue), dur_s=t - t_step,
                      slot_occupancy=slot_occ, page_occupancy=page_occ)
            if self.recorder is not None:
                self.recorder.observe_serving_step(
                    steps, active=len(active),
                    queue_depth=len(self.sched.queue), dur_s=t - t_step,
                    tokens=len(active),
                )
            for req in active:
                self.sched.record_token(req, int(nxt[req.slot]), t)
                if req.status is Status.DONE:
                    done.append(req)
        wall = max(now() - t0, 1e-9)
        # telemetry tokens/s from the COUNTER delta: cross-checks the
        # per-step instrumentation against the legacy aggregate below
        # (tests pin agreement within 1%)
        self._m_tps.set((self._m_tokens.value - tok0) / wall)

        done.sort(key=lambda r: r.uid)
        outputs, per_request = [], []
        for r in done:
            decode_s = max(r.t_done - r.t_admit, 1e-9)
            e2e = r.t_done - r.t_submit
            self._m_e2e.observe(e2e)
            outputs.append(RequestOutput(
                uid=r.uid, prompt=np.asarray(r.prompt),
                generated=np.asarray(r.generated, np.int64),
                finish_reason=r.finish_reason,
                queue_latency_s=r.t_admit - r.t_submit,
                ttft_s=r.t_first_token - r.t_submit,
                decode_tokens_per_s=len(r.generated) / decode_s,
                e2e_latency_s=e2e,
            ))
            per_request.append({
                "uid": r.uid,
                "prompt_len": r.prompt_len,
                "new_tokens": len(r.generated),
                "finish_reason": r.finish_reason,
                "queue_latency_s": round(r.t_admit - r.t_submit, 6),
                "ttft_s": round(r.t_first_token - r.t_submit, 6),
                "e2e_latency_s": round(e2e, 6),
                "decode_tokens_per_s": round(len(r.generated) / decode_s, 2),
            })
        generated = sum(len(o.generated) for o in outputs)
        metrics = {
            "wall_time_s": round(wall, 6),
            "decode_steps": steps,
            "prefills": prefills,
            "generated_tokens": generated,
            "decode_tokens_per_s": round(generated / wall, 2),
            "slot_occupancy": round(occ_slots / steps, 4) if steps else 0.0,
            "page_occupancy": round(occ_pages / steps, 4) if steps else 0.0,
            "requests": per_request,
        }
        return outputs, metrics


def serving_ab_benchmark(params, config, request_specs, *, num_slots=4,
                         num_pages=64, page_size=16, max_context=256,
                         mesh=None, param_specs=None, tp_axis="tensor",
                         seed=0):
    """A/B the continuous-batching scheduler against naive padded
    batching on ONE model + request mix; returns a JSON-able dict.

    ``request_specs`` is a list of (prompt_len, max_new_tokens[, eos])
    tuples; prompts are seeded-random tokens so both arms and repeat
    runs see the identical workload. Each arm warms up once (compiles)
    and is then measured on a fresh copy of the workload.
    """
    rng = np.random.RandomState(seed)
    vocab = getattr(config, "valid_vocab_size", None) or config.vocab_size
    prompts = [rng.randint(1, vocab, (int(spec[0]),)) for spec in request_specs]

    def make_requests():
        return [
            Request(prompt=p, max_new_tokens=int(spec[1]),
                    eos_token_id=(int(spec[2]) if len(spec) > 2 else None))
            for p, spec in zip(prompts, request_specs)
        ]

    results = {}
    for label, continuous in (("continuous", True), ("static", False)):
        engine = ServingEngine(
            params, config, num_slots=num_slots, num_pages=num_pages,
            page_size=page_size, max_context=max_context, mesh=mesh,
            param_specs=param_specs, tp_axis=tp_axis, continuous=continuous,
        )
        engine.run(make_requests())          # warmup: compile every bucket
        _, metrics = engine.run(make_requests())
        results[label] = {
            "decode_tokens_per_s": metrics["decode_tokens_per_s"],
            "decode_steps": metrics["decode_steps"],
            "slot_occupancy": metrics["slot_occupancy"],
            "page_occupancy": metrics["page_occupancy"],
            "wall_time_s": metrics["wall_time_s"],
        }
    results["speedup"] = round(
        results["continuous"]["decode_tokens_per_s"]
        / max(results["static"]["decode_tokens_per_s"], 1e-9), 3,
    )
    results["num_slots"] = num_slots
    results["requests"] = len(request_specs)
    return results
