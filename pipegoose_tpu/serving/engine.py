"""Synchronous continuous-batching serving engine over the paged pool.

``ServingEngine.run(requests)`` drives the host-side loop the ROADMAP's
"heavy traffic" north star needs above the per-call ``generate()``:

    while work remains:
        admit queued requests into free slots        (scheduler.admit;
                                                      prefix-cache hits
                                                      share KV pages)
        advance prefills                             (one CHUNK per
                                                      prefilling request
                                                      per tick, or the
                                                      legacy monolithic
                                                      prefill)
        one jitted decode step over ALL active slots (paged_decode_step,
                                                      or a draft+verify
                                                      speculative cycle)
        record tokens; evict finished, reclaim pages (scheduler)

Three opt-in performance modes layer onto the PR 1 engine without
changing its defaults:

- ``prefix_cache=True`` — content-addressed COW page sharing
  (serving/prefix_cache.py): a new request whose prompt prefix is
  already cached SKIPS prefill for the shared pages entirely; only its
  unique tail is forwarded, with copy-on-write duplication when the
  tail begins mid-page of a shared page.
- ``prefill_chunk=N`` — chunked prefill: long prompts advance N tokens
  per engine tick THROUGH the page tables (``paged_prefill_chunk``),
  interleaved with decode steps, instead of one monolithic prefill that
  stalls every decoding neighbor. The per-tick mixed step keeps the
  PR 3 ``decode_stall`` watchdog quiet and bounds the inter-decode-step
  gap (``serving.decode_gap_seconds``) by one chunk's compute.
- ``speculative=(k, n)`` — SELF-speculative decoding: a shallow-exit
  draft (the first ``k`` transformer layers + final LN + lm head, same
  weights) proposes up to ``n`` tokens per slot, and ONE batched
  verification pass through the full model (the same
  ``paged_prefill_chunk`` program, all-logits mode) scores the whole
  bundle. Accepted tokens are exactly the full model's greedy tokens —
  greedy parity is structural, not approximate.

Everything device-side is compiled with STATIC shapes: the decode step
is one program for the (num_slots, page-table-width) layout regardless
of which slots are live, prefills bucket prompt lengths to page
multiples (chunked prefill compiles exactly ONE chunk shape), and the
draft/verify pair adds two more. Page buffers are DONATED through every
step — the pool lives in place, never copied.

Greedy decoding only (the continuous-batching contract here is
token-identity with per-request ``generate()`` — the prefix cache,
chunking, and speculation are all invisible in the tokens); under a
mesh the whole step runs in shard_map with head-sharded pages and
``global_greedy_pick`` over the vocab shards, exactly like
models/_decode.py's sharded driver.

Metrics follow utils/profiler.py's convention of returning plain dicts
the caller can JSON-dump, and the engine is instrumented against the
telemetry registry: on top of the PR 2 gauges/histograms/spans it
counts prefix-cache ``hit_tokens``/``miss_tokens``/``shared_pages``/
``cow_copies``, prefill chunks and forwarded prefill tokens (the
prefill-FLOP meter the cache shrinks), pool fragmentation, decode-step
gaps, and speculative draft/accept tallies. The legacy aggregate dict
keeps its exact keys — ``serving_ab_benchmark`` and existing callers
parse it; new information lands under NEW keys only.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from pipegoose_tpu.distributed.compat import shard_map
from pipegoose_tpu.models._decode import (
    global_greedy_pick,
    greedy_token,
    vocab_mask_for,
)
from pipegoose_tpu.models.generate import forward_cached, init_cache
from pipegoose_tpu.serving.kv_pool import (
    PagePool,
    check_attn_impl,
    check_kv_dtype,
    copy_page,
    init_pages,
    paged_decode_step,
    paged_prefill_chunk,
    write_prompt_pages,
)
from pipegoose_tpu.serving.kv_tier.restore import (
    RestoreManager,
    RestorePlanner,
)
from pipegoose_tpu.serving.prefix_cache import PrefixCache
from pipegoose_tpu.serving.scheduler import Request, Scheduler, Status
from pipegoose_tpu.telemetry.registry import Histogram, get_registry
from pipegoose_tpu.telemetry.spans import span


class ReplicaFault(RuntimeError):
    """An unplanned replica failure (the deterministic fault seam's
    crash kind, or a real exception escaping ``tick_once``). The
    control plane's contract on catching one: quarantine the replica
    (FAILED), best-effort ``abort_run``, and SALVAGE its admitted
    requests onto the survivors (serving/control_plane/plane.py)."""


@dataclass
class RequestOutput:
    uid: int
    prompt: np.ndarray
    generated: np.ndarray
    finish_reason: str
    queue_latency_s: float
    # None when the request was never served (finish_reason="shed"):
    # a 0.0 would read as an instant first token and drag aggregate
    # TTFT DOWN exactly when the system is degraded — filter shed rows
    # (or skip Nones) before aggregating
    ttft_s: Optional[float]
    decode_tokens_per_s: Optional[float]
    e2e_latency_s: float = 0.0  # submit -> done wall time
    tenant: Optional[str] = None  # multi-tenant identity (None = untagged)

    @property
    def tokens(self) -> np.ndarray:
        return np.concatenate([np.asarray(self.prompt, np.int64),
                               np.asarray(self.generated, np.int64)])


class _RunState:
    """Accumulators for one serving run — the state ``run()`` kept in
    locals before the steppable extraction (``start_run`` /
    ``tick_once`` / ``finish_run``), so a control plane can interleave
    N replica engines tick-by-tick in one host thread. Host-side only;
    nothing here touches device memory."""

    __slots__ = (
        "now", "tick_hook", "t0", "tok0", "done", "outputs",
        "per_request", "generated_total", "shed_count", "steps",
        "prefills", "chunks", "spec_drafted", "spec_accepted",
        "occ_slots", "occ_pages", "stalled", "tick", "t_last_decode",
        "max_gap", "step_time", "table", "seq_lens", "tokens",
    )

    def __init__(self, engine: "ServingEngine", now, tick_hook):
        self.now = now
        self.tick_hook = tick_hook
        self.t0 = 0.0                   # set at the end of start_run
        self.tok0 = engine._m_tokens.value
        self.done: List[Request] = []   # finished, outputs not built yet
        self.outputs: List[RequestOutput] = []
        self.per_request: List[dict] = []
        self.generated_total = 0
        self.shed_count = 0
        self.steps = self.prefills = self.chunks = 0
        self.spec_drafted = self.spec_accepted = 0
        self.occ_slots = self.occ_pages = 0.0
        self.stalled = 0
        self.tick = 0
        self.t_last_decode: Optional[float] = None
        self.max_gap = 0.0
        self.step_time = 0.0            # summed decode-step wall time
        self.table = np.zeros((engine.num_slots, engine.table_width),
                              np.int32)
        self.seq_lens = np.zeros((engine.num_slots,), np.int32)
        self.tokens = np.zeros((engine.num_slots,), np.int32)


class ServingEngine:
    """Greedy continuous-batching inference over a paged KV pool.

    ``num_slots`` bounds the decode batch, ``num_pages * page_size`` the
    pooled KV capacity, ``max_context`` the per-request prompt+new
    budget (it fixes the page-table width, i.e. the attention span the
    step compiles for). Pass ``mesh``/``param_specs`` for tensor
    parallelism (vocab/head-sharded params, same contract as
    ``generate_tp``); ``continuous=False`` degrades the scheduler to
    naive padded batching for A/B measurement. ``prefix_cache``/
    ``prefill_chunk``/``speculative`` are the opt-in serving-perf modes
    (module docstring); all default OFF, preserving the PR 1 engine
    bit-for-bit."""

    def __init__(self, params, config, *, num_slots: int = 4,
                 num_pages: int = 64, page_size: int = 16,
                 max_context: int = 256, mesh=None, param_specs=None,
                 tp_axis: str = "tensor", continuous: bool = True,
                 registry=None, recorder=None, stall_patience: int = 100,
                 prefix_cache: bool = False,
                 prefill_chunk: Optional[int] = None,
                 speculative: Optional[Tuple[int, int]] = None,
                 tracer=None,
                 weight_dtype: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 weight_group_size: int = 32,
                 prefill_only: bool = False,
                 sentinel=None,
                 host_tier=None,
                 host_tier_wire: Optional[str] = None,
                 cost_model=None,
                 memledger=None,
                 attn_kernel: str = "gather"):
        """``recorder``: optional ``telemetry.FlightRecorder`` — every
        decode step lands in its ring, and the no-decode-progress
        watchdog dumps a black box through it before raising.
        ``stall_patience``: scheduler iterations that admit nothing,
        prefill nothing, and decode nothing before the watchdog declares
        a stall. ``speculative=(k, n)``: draft with the first ``k``
        layers, propose up to ``n`` tokens per verification.
        ``tracer``: optional ``telemetry.reqtrace.RequestTracer`` —
        records every request's lifecycle timeline (admit, prefill
        chunks + cache hits, first token, decode ticks, spec cycles,
        preemptions) and attributes its TTFT/e2e latency; default None
        keeps the tick path at one attribute read + branch per hook
        site (guard-tested < 5 µs).

        ``weight_dtype`` ("int8" | "int4", default None; "fp" is an
        accepted alias for None, matching kv_dtype): quantize
        the block kernels at construction (quant/quantize_params) — the
        TP layers dispatch to the dequant-fused matmul, halving (or
        quartering) resident weight HBM. ``kv_dtype`` ("int8", default
        None=fp): int8 KV pages with a per-page scale plane —
        quantize-on-write, dequantize-in-gather (serving/kv_pool.py).
        ``weight_group_size``: int4 contraction-group width. Both
        default OFF: a default-constructed engine builds the exact
        PR 1/6 programs, byte for byte.

        ``prefill_only=True`` turns the engine into a disaggregated
        PREFILL POOL (serving/disagg/): the admission ledger reserves
        only ``pages_for(prompt)`` (nothing here ever decodes), and a
        completed prefill HANDS OFF — first token + exported KV pages —
        through the handoff hook (:meth:`set_handoff_hook`) instead of
        entering decode. Requires ``prefill_chunk`` (the chunk is the
        streaming boundary) and a hook before the first run.

        ``host_tier``: optional ``serving.kv_tier.HostTier`` — evicted
        refcount-1 prefix chains spill into host DRAM at wire precision
        and later lookup misses restore the pages instead of
        recomputing them (requires ``prefix_cache=True``).
        ``host_tier_wire`` ("bf16"): narrow FP pools on the wire;
        forbidden for int8 pools (their q+scale planes ARE the wire
        format). ``cost_model``: optional calibrated
        ``planner.cost.CostModel`` — its fitted launch/bandwidth/
        overhead constants decide restore-vs-recompute per prefix
        length; default None always restores.

        ``memledger``: optional ``telemetry.memledger.MemoryLedger``
        (or ``True`` to construct one) — live byte-exact per-owner-
        class page accounting with leak audits and an exhaustion
        forecast. Default None keeps every pool event and tick at one
        attribute read + branch (guard-tested < 5 µs).

        ``attn_kernel`` ("gather" | "paged", default "gather"): decode/
        chunk attention implementation. "paged" routes every paged
        program (decode step, speculative draft/verify, chunked
        prefill) through the fused Pallas kernel
        (ops/paged_attention.py) — one HBM pass over raw pages at wire
        precision, no contiguous KV materialization. "gather" is the
        two-pass XLA reference the kernel is parity-pinned against."""
        if max_context % page_size:
            raise ValueError("max_context must be a multiple of page_size")
        if prefill_only and prefill_chunk is None:
            raise ValueError(
                "prefill_only requires prefill_chunk: the chunk is the "
                "disagg streaming boundary (and the monolithic prefill "
                "path cannot hand off)"
            )
        if stall_patience < 1:
            raise ValueError(f"stall_patience must be >= 1, got {stall_patience}")
        if speculative is not None:
            k, n = speculative
            if not 1 <= k < config.n_layer:
                raise ValueError(
                    f"speculative draft depth {k} must be in "
                    f"[1, n_layer={config.n_layer})"
                )
            if n < 1:
                raise ValueError(f"speculative draft length {n} must be >= 1")
        self.recorder = recorder
        self.stall_patience = stall_patience
        self.tracer = tracer
        # ``sentinel``: optional ``telemetry.sentinel.PerfSentinel`` —
        # every finished run's tokens/s + decode-step/idle split is
        # compared against its rolling baseline, and a regression fires
        # a perf_regression black box naming the component. Default
        # None keeps finish_run at one attribute read + branch
        # (guard-tested < 5 µs, the tracer/recorder contract).
        self.sentinel = sentinel
        self.last_doctor_report = None   # refreshed by doctor()/doctor_chunk()
        self.last_step_profile = None    # refreshed by profile()
        self._run: Optional[_RunState] = None   # live steppable run
        # deterministic failure seam (testing/chaos.py replica_crash /
        # replica_wedge): None | "crash" (tick_once raises ReplicaFault
        # every call until cleared) | "wedge" (tick_once returns without
        # doing any work — the engine looks alive but makes no progress,
        # which is exactly what the control plane's heartbeat must catch)
        self._fault: Optional[str] = None
        if recorder is not None and tracer is not None:
            # a decode_stall (or any) black box then embeds the live
            # request timelines: the dump NAMES the stuck request
            recorder.set_request_tracer(tracer)
        self.registry = registry if registry is not None else get_registry()
        # resolve metric handles ONCE: inc/set/observe check the enabled
        # flag themselves, so the hot loop's disabled cost stays one
        # branch per site (no per-step registry lock + name lookup)
        reg = self.registry
        self._m_tokens = reg.counter("serving.tokens_total")
        self._m_requests = reg.counter("serving.requests_total")
        # deadline shedding (graceful degradation): shed / requests is
        # the degraded-mode ratio the default SLO set watches
        # (telemetry/slo.py shed_fraction target)
        self._m_shed = reg.counter("serving.shed_total")
        self._m_prefills = reg.counter("serving.prefills_total")
        self._m_steps = reg.counter("serving.decode_steps_total")
        self._m_ttft = reg.histogram("serving.ttft_seconds")
        self._m_tok_lat = reg.histogram("serving.decode_token_seconds")
        self._m_e2e = reg.histogram("serving.e2e_latency_seconds")
        self._m_queue = reg.gauge("serving.queue_depth")
        self._m_active = reg.gauge("serving.slots_active")
        self._m_slot_occ = reg.gauge("serving.slot_occupancy")
        self._m_page_occ = reg.gauge("serving.page_occupancy")
        self._m_tps = reg.gauge("serving.tokens_per_s")
        # prefix cache / chunked prefill / speculative instrumentation
        self._m_hit_tok = reg.counter("serving.prefix_cache.hit_tokens")
        self._m_miss_tok = reg.counter("serving.prefix_cache.miss_tokens")
        self._m_shared = reg.counter("serving.prefix_cache.shared_pages")
        self._m_cow = reg.counter("serving.prefix_cache.cow_copies")
        self._m_cached = reg.gauge("serving.prefix_cache.cached_pages")
        # pages leaf-first eviction could recover right now — the head-
        # room half of the admission ledger, and the router's tie-break
        self._m_evictable = reg.gauge("serving.prefix_cache.evictable_pages")
        self._m_frag = reg.gauge("serving.pool.fragmentation")
        self._m_prefill_tok = reg.counter("serving.prefill_tokens_total")
        self._m_chunks = reg.counter("serving.prefill_chunks_total")
        self._m_gap = reg.histogram("serving.decode_gap_seconds")
        self._m_spec_cycles = reg.counter("serving.spec.cycles")
        self._m_spec_draft = reg.counter("serving.spec.draft_tokens")
        self._m_spec_acc = reg.counter("serving.spec.accepted_tokens")
        self.params = params
        self.config = config
        self.num_slots = num_slots
        self.page_size = page_size
        self.table_width = max_context // page_size
        self.mesh = mesh
        self.param_specs = param_specs
        self.tp_axis = tp_axis
        self.prefill_chunk = prefill_chunk
        self.speculative = speculative
        tp = mesh.shape[tp_axis] if mesh is not None else 1
        if config.n_head % tp:
            raise ValueError(f"n_head={config.n_head} not divisible by tp={tp}")
        # quantized inference knobs (ROADMAP item 4) — both default OFF.
        # "fp" is the explicit no-quantization alias both knobs accept
        # (check_kv_dtype does the same for kv_dtype), so a planner row's
        # candidate dict feeds straight back into the constructor
        if weight_dtype == "fp":
            weight_dtype = None
        self.weight_dtype = weight_dtype
        self.kv_dtype = check_kv_dtype(kv_dtype)
        check_attn_impl(attn_kernel)
        self.attn_kernel = attn_kernel
        self.quant_spec = None
        if weight_dtype is not None:
            from pipegoose_tpu.quant import (
                QuantSpec,
                quantize_param_specs,
                quantize_params,
            )
            from pipegoose_tpu.quant.weights import validate_tp_compat

            self.quant_spec = QuantSpec(weight_dtype, weight_group_size)
            validate_tp_compat(config, tp, self.quant_spec)
            if mesh is not None and param_specs is not None:
                # derive the q/scale PartitionSpecs from the fp tree
                # BEFORE the params change shape underneath them
                param_specs = quantize_param_specs(
                    param_specs, params, self.quant_spec
                )
            params = quantize_params(params, self.quant_spec)
            self.params = params
            self.param_specs = param_specs
        self.pool = PagePool(num_pages, page_size)
        self._run_prefill_tokens = self._run_hit_tokens = 0  # set per run()
        self.prefix_cache = PrefixCache(self.pool) if prefix_cache else None
        # KV memory hierarchy (serving/kv_tier/): optional host-DRAM
        # spill target behind the prefix cache. ``host_tier_wire``
        # narrows FP pools on the wire (int8 pools already spill
        # wire-exact q+scale planes and forbid a wire dtype).
        if host_tier is not None and self.prefix_cache is None:
            raise ValueError("host_tier requires prefix_cache=True "
                             "(the tier backs the cache's evictions)")
        if host_tier_wire is not None:
            if host_tier is None:
                raise ValueError("host_tier_wire requires a host_tier")
            if self.kv_dtype == "int8":
                raise ValueError(
                    "host_tier_wire is for fp pools; int8 pages already "
                    "spill wire-exact (q+scale planes verbatim)")
        self.host_tier = host_tier
        self.host_tier_wire = host_tier_wire
        self.prefill_only = prefill_only
        # disagg handoff seam: hook(engine, req, first_token, t) runs at
        # prefill completion BEFORE the scheduler releases the pages, so
        # it can export them (serving/disagg/workers.py)
        self._handoff_hook = None
        self.sched = Scheduler(num_slots, self.pool, max_context,
                               continuous=continuous,
                               prefix_cache=self.prefix_cache,
                               chunk_tokens=prefill_chunk,
                               tracer=tracer,
                               prefill_only=prefill_only)
        # paged prefill path: required by the cache (the tail attends to
        # shared pages) and by chunking; the legacy monolithic
        # forward_cached + write_prompt_pages path stays the default
        self._paged_prefill = prefix_cache or prefill_chunk is not None
        # fleet-directory publication seam: the control plane installs
        # hook(tokens, location) per replica; None costs one branch
        self.on_prefix_publish = None
        # goodput compile/warmup detection (telemetry/goodput.py): one
        # entry per jitted program family x width actually executed —
        # the control plane reads the counter delta around a tick to
        # book first-run (compile + warmup) wall separately from
        # steady-state productive wall
        self._progs_seen: set = set()
        self.programs_run = 0
        # every cached engine gets a RestoreManager (cheap — nothing
        # compiles until the first spill/pull), so it can serve as a
        # pull PEER even without a host tier of its own
        self.kv_tier = (RestoreManager(self)
                        if self.prefix_cache is not None else None)
        if self.kv_tier is not None and cost_model is not None:
            n_params = sum(int(x.size)
                           for x in jax.tree_util.tree_leaves(params))
            self.kv_tier.planner = RestorePlanner(
                cost_model, n_params=n_params)
        if self.host_tier is not None:
            if self.host_tier._m_bytes is None:
                self.host_tier.bind_registry(self.registry)
            self.prefix_cache.spill_hook = self.kv_tier.spill
        self.k_pages, self.v_pages = init_pages(
            config, num_pages, page_size, kv_dtype=self.kv_dtype
        )
        valid = getattr(config, "valid_vocab_size", None)
        mask_fn = vocab_mask_for(config)
        spec_k = speculative[0] if speculative else None

        if mesh is None:
            def _prefill(params, ids, mask):
                cache = init_cache(config, 1, ids.shape[1])
                logits, cache = forward_cached(
                    params, ids, cache, 0, config, extras={"mask": mask}
                )
                return greedy_token(logits, mask_fn), cache

            def _write(k_pages, v_pages, cache, phys, pad):
                return write_prompt_pages(
                    k_pages, v_pages, cache, phys, pad, page_size
                )

            def _step(params, tokens, k_pages, v_pages, table, seq_lens):
                logits, k_pages, v_pages = paged_decode_step(
                    params, tokens, k_pages, v_pages, table, seq_lens, config,
                    attn_impl=attn_kernel,
                )
                return greedy_token(logits, mask_fn), k_pages, v_pages

            def _chunk(params, ids, k_pages, v_pages, table, start, n_valid):
                logits, k_pages, v_pages = paged_prefill_chunk(
                    params, ids, k_pages, v_pages, table, start, n_valid,
                    config, attn_impl=attn_kernel,
                )
                return greedy_token(logits, mask_fn), k_pages, v_pages

            def _copy(k_pages, v_pages, src, dst):
                return copy_page(k_pages, v_pages, src, dst)

            def _draft(params, tokens, k_pages, v_pages, table, seq_lens, ok):
                logits, k_pages, v_pages = paged_decode_step(
                    params, tokens, k_pages, v_pages, table, seq_lens,
                    config, write_ok=ok, draft_layers=spec_k,
                    attn_impl=attn_kernel,
                )
                return greedy_token(logits, mask_fn), k_pages, v_pages

            def _verify(params, ids, k_pages, v_pages, table, start, n_valid):
                logits, k_pages, v_pages = paged_prefill_chunk(
                    params, ids, k_pages, v_pages, table, start, n_valid,
                    config, all_logits=True, attn_impl=attn_kernel,
                )
                return greedy_token(logits, mask_fn), k_pages, v_pages

            self._prefill = jax.jit(_prefill)
            self._write = jax.jit(_write, donate_argnums=(0, 1))
            self._step = jax.jit(_step, donate_argnums=(2, 3))
            self._chunk = jax.jit(_chunk, donate_argnums=(2, 3))
            self._copy = jax.jit(_copy, donate_argnums=(0, 1))
            self._draft = jax.jit(_draft, donate_argnums=(2, 3))
            self._verify = jax.jit(_verify, donate_argnums=(2, 3))
        else:
            vspec = P(None, None, None, tp_axis, None)   # pages: head-sharded
            # int8 pools are {"q", "scale"} pytrees: the scale plane has
            # no head_dim, so its spec drops the trailing entry — the
            # per-head scales shard WITH their heads
            pspec = (
                {"q": vspec, "scale": P(None, None, None, tp_axis)}
                if self.kv_dtype == "int8" else vspec
            )
            cspec = {"k": vspec, "v": vspec}             # fp prefill cache

            def _prefill_body(params, ids, mask):
                cache = init_cache(config, 1, ids.shape[1], tp)
                logits, cache = forward_cached(
                    params, ids, cache, 0, config, tp_axis,
                    extras={"mask": mask},
                )
                return global_greedy_pick(logits, tp_axis, valid), cache

            def _write_body(k_pages, v_pages, cache, phys, pad):
                return write_prompt_pages(
                    k_pages, v_pages, cache, phys, pad, page_size
                )

            def _step_body(params, tokens, k_pages, v_pages, table, seq_lens):
                logits, k_pages, v_pages = paged_decode_step(
                    params, tokens, k_pages, v_pages, table, seq_lens,
                    config, tp_axis, attn_impl=attn_kernel,
                )
                tok = global_greedy_pick(logits, tp_axis, valid)
                return tok, k_pages, v_pages

            def _chunk_body(params, ids, k_pages, v_pages, table, start,
                            n_valid):
                logits, k_pages, v_pages = paged_prefill_chunk(
                    params, ids, k_pages, v_pages, table, start, n_valid,
                    config, tp_axis, attn_impl=attn_kernel,
                )
                tok = global_greedy_pick(logits, tp_axis, valid)
                return tok, k_pages, v_pages

            def _copy_body(k_pages, v_pages, src, dst):
                return copy_page(k_pages, v_pages, src, dst)

            def _draft_body(params, tokens, k_pages, v_pages, table,
                            seq_lens, ok):
                logits, k_pages, v_pages = paged_decode_step(
                    params, tokens, k_pages, v_pages, table, seq_lens,
                    config, tp_axis, write_ok=ok, draft_layers=spec_k,
                    attn_impl=attn_kernel,
                )
                tok = global_greedy_pick(logits, tp_axis, valid)
                return tok, k_pages, v_pages

            def _verify_body(params, ids, k_pages, v_pages, table, start,
                             n_valid):
                logits, k_pages, v_pages = paged_prefill_chunk(
                    params, ids, k_pages, v_pages, table, start, n_valid,
                    config, tp_axis, all_logits=True, attn_impl=attn_kernel,
                )
                b, c, _ = logits.shape
                tok = global_greedy_pick(
                    logits.reshape(b * c, -1), tp_axis, valid
                ).reshape(b, c)
                return tok, k_pages, v_pages

            self._prefill = jax.jit(shard_map(
                _prefill_body, mesh=mesh,
                in_specs=(param_specs, P(), P()), out_specs=(P(), cspec),
                check_vma=False,
            ))
            self._write = jax.jit(shard_map(
                _write_body, mesh=mesh,
                in_specs=(pspec, pspec, cspec, P(), P()),
                out_specs=(pspec, pspec), check_vma=False,
            ), donate_argnums=(0, 1))
            self._step = jax.jit(shard_map(
                _step_body, mesh=mesh,
                in_specs=(param_specs, P(), pspec, pspec, P(), P()),
                out_specs=(P(), pspec, pspec), check_vma=False,
            ), donate_argnums=(2, 3))
            self._chunk = jax.jit(shard_map(
                _chunk_body, mesh=mesh,
                in_specs=(param_specs, P(), pspec, pspec, P(), P(), P()),
                out_specs=(P(), pspec, pspec), check_vma=False,
            ), donate_argnums=(2, 3))
            self._copy = jax.jit(shard_map(
                _copy_body, mesh=mesh,
                in_specs=(pspec, pspec, P(), P()),
                out_specs=(pspec, pspec), check_vma=False,
            ), donate_argnums=(0, 1))
            self._draft = jax.jit(shard_map(
                _draft_body, mesh=mesh,
                in_specs=(param_specs, P(), pspec, pspec, P(), P(), P()),
                out_specs=(P(), pspec, pspec), check_vma=False,
            ), donate_argnums=(2, 3))
            self._verify = jax.jit(shard_map(
                _verify_body, mesh=mesh,
                in_specs=(param_specs, P(), pspec, pspec, P(), P(), P()),
                out_specs=(P(), pspec, pspec), check_vma=False,
            ), donate_argnums=(2, 3))
            sharding = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), pspec,
                is_leaf=lambda x: isinstance(x, P),
            )
            self.k_pages = jax.device_put(self.k_pages, sharding)
            self.v_pages = jax.device_put(self.v_pages, sharding)
            self._pspec = pspec
        # live memory ledger (telemetry/memledger.py) — attached LAST:
        # bytes-per-page is measured from the live pool arrays above
        self.memledger = None
        if memledger:
            from pipegoose_tpu.telemetry.memledger import MemoryLedger

            self.attach_memledger(
                memledger if isinstance(memledger, MemoryLedger)
                else MemoryLedger())

    def doctor(self, large_bytes: int = 1 << 20, registry=None):
        """Mesh-doctor report (telemetry/doctor.py) for the compiled
        paged DECODE step — the serving hot path: actual shardings of
        params and KV pages diffed against the engine's intended specs
        (head-sharded pages under TP), the collective schedule
        (``global_greedy_pick``'s all_gathers are the only intended
        traffic), and the per-device HBM budget dominated by the page
        pool. Shape-only: nothing executes, no pages are touched."""
        from pipegoose_tpu.telemetry.doctor import diagnose, set_doctor_gauges

        i32 = jnp.int32
        tokens = jax.ShapeDtypeStruct((self.num_slots,), i32)
        table = jax.ShapeDtypeStruct((self.num_slots, self.table_width), i32)
        seq_lens = jax.ShapeDtypeStruct((self.num_slots,), i32)
        intended = None
        if self.mesh is not None:
            intended = (self.param_specs, P(), self._pspec, self._pspec,
                        P(), P())
        report = diagnose(
            self._step, self.params, tokens, self.k_pages, self.v_pages,
            table, seq_lens,
            intended=intended,
            labels=("params", "tokens", "k_pages", "v_pages", "table",
                    "seq_lens"),
            mesh=self.mesh, large_bytes=large_bytes,
        )
        if self.attn_kernel == "paged":
            report.extras = {"paged_tile": self._paged_tile(n_queries=1)}
        set_doctor_gauges(report, registry=registry or self.registry)
        self.last_doctor_report = report   # /debug/doctor serves this
        return report

    def _paged_tile(self, n_queries: int) -> dict:
        """Chosen Pallas paged-attention tile geometry for this engine's
        pool — logged into the doctor report (``extras["paged_tile"]``)
        so the CI artifact records which VMEM footprint the feasibility
        guard approved."""
        from pipegoose_tpu.ops.paged_attention import paged_tile_geometry

        head_dim = self.config.hidden_size // self.config.n_head
        return paged_tile_geometry(
            self.page_size, head_dim, n_queries,
            quantized=self.kv_dtype == "int8",
        )

    def doctor_chunk(self, large_bytes: int = 1 << 20, registry=None):
        """Same report for the compiled CHUNKED-PREFILL program — the
        other half of the mixed step. CI pins it at zero
        partitioner-inserted resharding (scripts/mesh_doctor.py
        --serving), so a PartitionSpec regression in the chunk path dies
        at compile time like one in the decode path would."""
        from pipegoose_tpu.telemetry.doctor import diagnose, set_doctor_gauges

        i32 = jnp.int32
        c = self.prefill_chunk or self.page_size
        ids = jax.ShapeDtypeStruct((1, c), i32)
        table = jax.ShapeDtypeStruct((1, self.table_width), i32)
        start = jax.ShapeDtypeStruct((1,), i32)
        n_valid = jax.ShapeDtypeStruct((1,), i32)
        intended = None
        if self.mesh is not None:
            intended = (self.param_specs, P(), self._pspec, self._pspec,
                        P(), P(), P())
        report = diagnose(
            self._chunk, self.params, ids, self.k_pages, self.v_pages,
            table, start, n_valid,
            intended=intended,
            labels=("params", "ids", "k_pages", "v_pages", "table",
                    "start", "n_valid"),
            mesh=self.mesh, large_bytes=large_bytes,
        )
        if self.attn_kernel == "paged":
            report.extras = {"paged_tile": self._paged_tile(n_queries=c)}
        set_doctor_gauges(report, registry=registry or self.registry)
        self.last_doctor_report = report
        return report

    def profile(self, steps: int = 3, warmup: int = 2,
                trace_dir: Optional[str] = None, registry=None):
        """Measured device-time attribution (telemetry/xprof.py) of the
        compiled paged DECODE step — the runtime twin of
        :meth:`doctor`: runs the real step on a synthetic full-slot
        batch whose page tables point at the NULL page (so the writes
        land in the page whose content is garbage by design and no live
        request's KV is touched), under the XLA profiler, and returns
        the ``StepProfile`` splitting the fenced step into compute /
        per-axis collectives / idle. Cached on ``last_step_profile``
        (the ops server's ``/debug/profile`` provider). Not callable
        mid-run — the step donates the KV pages and the engine adopts
        the final buffers afterwards."""
        from pipegoose_tpu.telemetry.xprof import profile_step

        if self._run is not None:
            raise RuntimeError("profile() cannot run during a serving run")
        i32 = jnp.int32
        tokens = jnp.zeros((self.num_slots,), i32)
        table = jnp.zeros((self.num_slots, self.table_width), i32)
        seq_lens = jnp.zeros((self.num_slots,), i32)
        final: dict = {}

        def update(out, cur):
            # out = (next_tokens, k_pages, v_pages); the pages were
            # donated — thread (and finally adopt) the new buffers
            final["k"], final["v"] = out[1], out[2]
            return (cur[0], cur[1], out[1], out[2], cur[4], cur[5])

        try:
            profile = profile_step(
                self._step, self.params, tokens, self.k_pages, self.v_pages,
                table, seq_lens,
                steps=steps, warmup=warmup, update_args=update,
                mesh=self.mesh, trace_dir=trace_dir,
                registry=registry or self.registry,
            )
        finally:
            # the FIRST executed call already donated the stored page
            # buffers: adopt the newest generation even when trace
            # parsing/export raises, or the engine's next decode step
            # would touch deleted arrays
            if final:
                self.k_pages, self.v_pages = final["k"], final["v"]
        self.last_step_profile = profile
        return profile

    def memory_report(self, registry=None) -> dict:
        """Host-side HBM census of the engine's RESIDENT state — the
        serving view of the doctor's memory budget, grouped by dtype so
        a quantized engine's ~2x drop is a number, not a vibe. Weights
        come from the live param tree (quantized leaves count their
        int8/int4+scale bytes), KV from the live pool arrays (values +
        scale planes). ``page_capacity_ratio`` is the measured
        bytes-per-page multiplier vs an fp pool of the same geometry:
        how many times more pages the same KV HBM holds at this
        ``kv_dtype`` (the >= 1.8x acceptance meter). Sets the
        ``serving.hbm.weights_bytes`` / ``serving.hbm.kv_bytes`` gauge
        pair next to ``doctor.hbm_peak_bytes``."""
        from pipegoose_tpu.quant.weights import quantized_weight_bytes

        weights = quantized_weight_bytes(self.params)
        kv_by: dict = {}
        for leaf in jax.tree_util.tree_leaves((self.k_pages, self.v_pages)):
            nbytes = int(leaf.size) * int(np.dtype(leaf.dtype).itemsize)
            key = str(leaf.dtype)
            kv_by[key] = kv_by.get(key, 0) + nbytes
        kv_total = int(sum(kv_by.values()))
        cfg = self.config
        num_pages = self.pool.num_pages
        fp_total = (2 * cfg.n_layer * num_pages * self.page_size
                    * cfg.n_head * cfg.head_dim
                    * int(np.dtype(cfg.dtype).itemsize))
        report = {
            "weight_dtype": self.weight_dtype or "fp",
            "kv_dtype": self.kv_dtype or "fp",
            "weights": weights,
            "kv": {
                "bytes_by_dtype": kv_by,
                "total_bytes": kv_total,
                "num_pages": num_pages,
                "bytes_per_page": kv_total // num_pages,
                "fp_bytes_per_page": fp_total // num_pages,
                "page_capacity_ratio": round(fp_total / max(kv_total, 1), 4),
            },
        }
        if self.host_tier is not None:
            # exact slab census: pages x wire bytes (q+scale for int8
            # pools — never fp-sized), the ISSUE's pinned invariant
            report["host_tier"] = {
                "resident_pages": self.host_tier.resident_pages,
                "resident_bytes": self.host_tier.resident_bytes,
                "budget_bytes": self.host_tier.byte_budget,
            }
        reg = registry if registry is not None else self.registry
        reg.gauge(
            "serving.hbm.weights_bytes",
            help="resident model weight bytes (quantized leaves counted "
                 "at their wire size)",
        ).set(float(weights["total_bytes"]))
        reg.gauge(
            "serving.hbm.kv_bytes",
            help="resident KV page-pool bytes (values + scale planes)",
        ).set(float(kv_total))
        reg.gauge(
            "serving.hbm.kv_page_capacity_ratio",
            help="pages the same HBM holds vs an fp pool (1.0 = fp)",
        ).set(float(report["kv"]["page_capacity_ratio"]))
        return report

    # -- internals ---------------------------------------------------------

    def attach_tracer(self, tracer) -> None:
        """Attach (or detach, with None) a ``RequestTracer`` after
        construction — the engine and its scheduler share the handle,
        and an attached flight recorder starts embedding the tracer's
        timelines in black-box dumps. Post-hoc attachment exists so a
        warm engine (compiled programs, seeded cache) can run one traced
        replay without rebuilding."""
        self.tracer = tracer
        self.sched.tracer = tracer
        if self.recorder is not None:
            self.recorder.set_request_tracer(tracer)

    def attach_memledger(self, ledger) -> None:
        """Attach (or detach, with None) a ``telemetry.memledger.
        MemoryLedger``: binds it to the pool (as the synchronous event
        observer), the scheduler, the prefix cache, the host tier, the
        flight recorder, and the registry, with the bytes-per-page
        MEASURED from the live pool arrays (q+scale planes for int8
        pools — the same census ``memory_report`` does). Post-hoc
        attachment adopts a warm pool via the ledger's ``resync``."""
        if ledger is None:
            if self.memledger is not None:
                self.memledger.unbind()
            self.memledger = None
            return
        total = 0
        for leaf in jax.tree_util.tree_leaves((self.k_pages, self.v_pages)):
            total += int(leaf.size) * int(np.dtype(leaf.dtype).itemsize)
        ledger.bind(
            self.pool, sched=self.sched, cache=self.prefix_cache,
            host_tier=self.host_tier, recorder=self.recorder,
            registry=self.registry,
            bytes_per_page=total // self.pool.num_pages,
        )
        self.memledger = ledger

    def _note_program(self, family: str, width: int) -> None:
        """Record one jitted-program execution for the goodput
        ledger's compile/warmup detection: the first (family, width)
        pair is the tick that paid the XLA compile."""
        key = (family, width)
        if key not in self._progs_seen:
            self._progs_seen.add(key)
            self.programs_run += 1

    def _ledger_tick(self, rs) -> None:
        """Per-tick ledger hook (conservation check + forecast +
        occupancy sample). With no ledger attached (the default) the
        cost is this one attribute read + branch — the disabled-path
        guard test times exactly this call."""
        ml = self.memledger
        if ml is None:
            return
        ml.on_tick(rs.tick, t=rs.now())

    def set_handoff_hook(self, hook) -> None:
        """Install (or clear, with None) the disagg handoff seam:
        ``hook(engine, req, first_token, t)`` runs at each prefill's
        completion, BEFORE the scheduler releases the request's pages —
        the one moment the finished prompt KV is both complete and
        still addressable for export (serving/disagg/workers.py's
        PrefillWorker is the production hook)."""
        self._handoff_hook = hook

    def set_peer_source(self, peer) -> None:
        """Default cross-replica pull source: every queued request
        probes ``peer``'s prefix inventory before admission (bench /
        two-engine tests; the control plane hints per request through
        the fleet directory instead). Requires a prefix cache."""
        if self.kv_tier is None:
            raise RuntimeError("set_peer_source requires prefix_cache=True")
        self.kv_tier.set_peer_source(peer)

    def admit_transferred(self, req: Request, first_token: int) -> bool:
        """Disagg decode-pool admission: bind a fully materialized
        transfer (every page imported at wire precision) to a free
        slot, skipping prefill — ``Scheduler.admit_with_pages`` does
        the lifecycle; this wrapper adds the engine bookkeeping a
        normal admission would have accrued (request counter, prefix-
        cache publication of the transferred-in prompt pages so later
        LOCAL re-prefills hit them, run-state done collection when the
        request finishes at admission). Returns False when no slot is
        free (the staged transfer keeps its pages + reservation)."""
        rs = self._run
        if rs is None:
            raise RuntimeError("admit_transferred needs start_run first")
        if self.prefix_cache is not None:
            # transferred-in pages are real prompt KV with FINAL
            # content: publish the full pages exactly like a local
            # prefill would, so a fallback (or migrated) request
            # sharing the prefix hits them. BEFORE admission, from the
            # stage record — a request finishing AT admission
            # (max_new=1/eos) releases its pages inside
            # admit_with_pages, and publishing freed pages would be a
            # no-op at best
            stage = self.sched.transfers.get(req.uid)
            if stage is not None:
                n_full = req.prompt_len // self.page_size
                self.prefix_cache.insert(
                    np.asarray(req.prompt)[:n_full * self.page_size],
                    stage["pages"][:n_full],
                )
                self._m_cached.set(self.prefix_cache.cached_pages)
                if self.on_prefix_publish is not None and n_full:
                    self.on_prefix_publish(
                        np.asarray(req.prompt)[:n_full * self.page_size],
                        "hbm",
                    )
        if not self.sched.admit_with_pages(req, first_token, rs.now()):
            return False
        self._m_requests.inc()
        self._observe_ttft(req)
        if req.status is Status.DONE:
            rs.done.append(req)
        return True

    def _observe_ttft(self, req: Request) -> None:
        """Record TTFT into the histogram EXACTLY ONCE per request. Two
        engine paths can complete a prefill (the monolithic
        ``_prefill_request`` and the paged ``_prefill_chunk_tick``), and
        a preempted-then-re-admitted request re-enters prefill with its
        preserved ``t_first_token`` — the ``ttft_observed`` flag makes a
        double observation structurally impossible regardless of which
        path(s) a request crosses."""
        if (req.ttft_observed or req.t_first_token is None
                or req.t_submit is None):
            return
        req.ttft_observed = True
        self._m_ttft.observe(req.t_first_token - req.t_submit)

    def _prefill_request(self, req: Request, now) -> None:
        """Legacy monolithic prefill: run the bucketed contiguous
        forward, scatter the prompt KV into the request's pages, and
        record the first generated token."""
        if req.generated:
            raise RuntimeError(
                "re-admitting a preempted request requires the paged "
                "prefill path — construct the engine with prefix_cache "
                "and/or prefill_chunk"
            )
        tr = self.tracer
        t0 = now() if tr is not None else 0.0
        with span("serving.prefill", registry=self.registry):
            s = req.prompt_len
            bucket = self.pool.pages_for(s) * self.page_size
            self._note_program("prefill", bucket)
            pad = bucket - s
            ids = np.zeros((1, bucket), np.int32)
            ids[0, pad:] = np.asarray(req.prompt, np.int32)
            mask = np.zeros((1, bucket), np.int32)
            mask[0, pad:] = 1
            tok, cache = self._prefill(
                self.params, jnp.asarray(ids), jnp.asarray(mask)
            )
            phys = np.zeros((self.table_width,), np.int32)
            phys[:len(req.pages)] = req.pages
            self.k_pages, self.v_pages = self._write(
                self.k_pages, self.v_pages, cache, jnp.asarray(phys),
                jnp.asarray(pad, jnp.int32),
            )
            # the token fetch syncs the device, so the span's wall time
            # covers the prefill's actual device work
            tok = int(np.asarray(tok)[0])  # host fetch syncs the device:
            t1 = now()                     # span + chunk dur = device work
            if tr is not None:
                tr.on_prefill_chunk(req, t1, dur_s=t1 - t0, tokens=s)
            self.sched.record_token(req, tok, t1)
        self._m_prefill_tok.inc(s)
        self._run_prefill_tokens += s
        self._m_prefills.inc()
        self._m_tokens.inc()  # the prefill's token
        self._observe_ttft(req)

    def _start_prefill(self, req: Request, now) -> None:
        """Paged-path admission follow-up: account the cache hit and run
        the pending copy-on-write duplication (the shared page whose
        mid-page tail this request will write gets a private copy; the
        admission pin on the source is dropped right after)."""
        if self.prefix_cache is not None:
            # chunk-only engines have no cache: 100%-miss counters here
            # would read as a misconfigured cache on a dashboard
            self._m_hit_tok.inc(req.hit_tokens)
            self._m_miss_tok.inc(req.target_len - req.hit_tokens)
            self._m_shared.inc(req.prefilled_len // self.page_size)
            self._run_hit_tokens += req.hit_tokens
        if req.cow is not None:
            src, m = req.cow
            dst = req.pages[req.prefilled_len // self.page_size]
            self.k_pages, self.v_pages = self._copy(
                self.k_pages, self.v_pages,
                jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32),
            )
            if self.pool.ledger is not None:
                self.pool.tag = ("cow", req.uid)
            self.pool.release([src])   # the PrefixCache.acquire pin
            req.cow = None
            req.prefilled_len += m
            self._m_cow.inc()
            if self.tracer is not None:
                self.tracer.on_cow(req, now())

    def _prefill_chunk_tick(self, req: Request, now) -> None:
        """Advance one prefill chunk through the page tables; on
        reaching the target, record the first token (fresh request) or
        resume decoding (preempted re-admission: the pending token is
        already in ``generated``)."""
        target = req.target_len
        begin = req.prefilled_len
        end = min(begin + (self.prefill_chunk or target - begin), target)
        n = end - begin
        # program width: ONE shape when chunking (the last chunk pads),
        # page-multiple buckets otherwise — same compile bound as the
        # monolithic path's prompt buckets
        prog = (self.prefill_chunk if self.prefill_chunk is not None
                else self.pool.pages_for(n) * self.page_size)
        self._note_program("chunk", prog)
        self.sched.ensure_pages(req, end)
        ids = np.zeros((1, prog), np.int32)
        ids[0, :n] = req.tokens[begin:end]
        table = np.zeros((1, self.table_width), np.int32)
        table[0, :len(req.pages)] = req.pages
        tr = self.tracer
        t0 = now() if tr is not None else 0.0
        with span("serving.prefill", registry=self.registry):
            tok, self.k_pages, self.v_pages = self._chunk(
                self.params, jnp.asarray(ids), self.k_pages, self.v_pages,
                jnp.asarray(table), jnp.asarray([begin], jnp.int32),
                jnp.asarray([n], jnp.int32),
            )
            tok = int(np.asarray(tok)[0])  # sync: span = device work
        if tr is not None:
            t1 = now()
            tr.on_prefill_chunk(req, t1, dur_s=t1 - t0, tokens=n)
        req.prefilled_len = end
        self._m_chunks.inc()
        self._m_prefill_tok.inc(n)
        self._run_prefill_tokens += n
        if end < target:
            return
        if self.prefix_cache is not None:
            # content now stable for every FULL prompt page: publish
            n_full = req.prompt_len // self.page_size
            self.prefix_cache.insert(
                np.asarray(req.prompt)[:n_full * self.page_size],
                req.pages[:n_full],
            )
            self._m_cached.set(self.prefix_cache.cached_pages)
            if self.on_prefix_publish is not None and n_full:
                self.on_prefix_publish(
                    np.asarray(req.prompt)[:n_full * self.page_size], "hbm",
                )
        self._m_prefills.inc()
        if self._handoff_hook is not None:
            # disagg prefill pool: the first token exists NOW — hand it
            # off with the remaining un-streamed pages instead of
            # decoding here. The hook exports from the still-allocated
            # pages; finish_handoff then frees slot + pages +
            # reservation and opens the transfer attribution phase.
            t1 = now()
            self._m_tokens.inc()       # the prefill's token, as always
            self._handoff_hook(self, req, int(tok), t1)
            self.sched.finish_handoff(req, t1)
            self._observe_ttft(req)
            return
        if req.generated:
            # resumed after preemption: the forwarded tail's last logits
            # re-derive the pending token (greedy is deterministic);
            # nothing new to record — decode picks up where it left off
            req.status = Status.DECODE
            if tr is not None:
                tr.on_resume(req, now())
            return
        had_first = req.t_first_token is not None
        self.sched.record_token(req, tok, now())
        self._m_tokens.inc()
        self._observe_ttft(req)
        if tr is not None and had_first and req.status is not Status.DONE:
            # disagg transfer-failure fallback: the request already
            # carries its handoff-time first token, so record_token
            # fired no first-token hook — without this resume the
            # timeline would book the whole decode as prefill (a DONE
            # request's timeline just closed; re-opening it would leak
            # a ghost)
            tr.on_resume(req, now())

    def _spec_cycle(self, rows: List[Request], now, done: List[Request]):
        """One speculative decode cycle over the active batch: draft up
        to n tokens per slot with the k-layer shallow exit, verify the
        whole bundle in one full-model pass, emit the longest verified
        prefix plus the correction token. Finished requests land in
        ``done``. Returns (emitted, drafted, accepted, surviving rows
        — lazy growth may retract a neighbor mid-batch)."""
        spec_k, n_spec = self.speculative
        self._note_program("spec", n_spec)
        table = np.zeros((self.num_slots, self.table_width), np.int32)
        seq = np.zeros((self.num_slots,), np.int32)
        tok0 = np.zeros((self.num_slots,), np.int32)
        g = np.zeros((self.num_slots,), np.int32)
        for r in rows:
            if r.status is not Status.DECODE:
                continue  # retracted by an earlier row's lazy growth
            # bound per-slot draft depth so verified writes stay inside
            # the admission worst case: positions <= cached + remaining-1
            g_i = min(n_spec, r.max_new_tokens - len(r.generated) - 1)
            self.sched.ensure_pages(r, r.cached_len + g_i + 1)
        rows = [r for r in rows if r.status is Status.DECODE]
        for r in rows:
            g_i = min(n_spec, r.max_new_tokens - len(r.generated) - 1)
            table[r.slot, :len(r.pages)] = r.pages
            seq[r.slot] = r.cached_len
            tok0[r.slot] = r.generated[-1]
            g[r.slot] = g_i
        drafts: List[np.ndarray] = []
        cur = jnp.asarray(tok0)
        jtable = jnp.asarray(table)
        tr = self.tracer
        t_c0 = now() if tr is not None else 0.0
        # same span as the plain path: speculative mode must not make
        # the decode-step stream vanish from dashboards/Perfetto
        with span("serving.decode_step", registry=self.registry):
            for j in range(n_spec):
                cur, self.k_pages, self.v_pages = self._draft(
                    self.params, cur, self.k_pages, self.v_pages, jtable,
                    jnp.asarray(seq + j), jnp.asarray(g > j),
                )
                drafts.append(cur)   # device array: no sync between steps
            # one host fetch AFTER the loop so every draft dispatch
            # enqueues back-to-back (no per-token dispatch-RTT gaps)
            drafts = [np.asarray(d) for d in drafts]
            ids = np.zeros((self.num_slots, n_spec + 1), np.int32)
            ids[:, 0] = tok0
            for j, d in enumerate(drafts):
                ids[:, j + 1] = d
            toks, self.k_pages, self.v_pages = self._verify(
                self.params, jnp.asarray(ids), self.k_pages, self.v_pages,
                jtable, jnp.asarray(seq), jnp.asarray(g + 1),
            )
            toks = np.asarray(toks)  # host fetch syncs: span = device work
        t = now()
        emitted = accepted = 0
        for r in rows:
            i = r.slot
            m = 0
            while m < g[i] and int(drafts[m][i]) == int(toks[i, m]):
                m += 1
            accepted += m
            if tr is not None:
                tr.on_spec(r, t, dur_s=t - t_c0, drafted=int(g[i]),
                           accepted=m)
            # the verified tokens ARE the full model's greedy stream:
            # m matched drafts + the correction/bonus token
            for j in range(m + 1):
                self.sched.record_token(r, int(toks[i, j]), t)
                emitted += 1
                if r.status is Status.DONE:
                    done.append(r)
                    break
        drafted = int(g.sum())
        self._m_spec_cycles.inc()
        self._m_spec_draft.inc(drafted)
        self._m_spec_acc.inc(accepted)
        return emitted, drafted, accepted, rows

    def _trace_tick(self, active, t_step: float, t: float) -> None:
        """Per-request decode-tick fan-out into the tracer (one bounded
        event per active request). With tracing off (the default) the
        cost is this one attribute read + branch — the disabled-path
        guard test times exactly this call."""
        tr = self.tracer
        if tr is None:
            return
        dur = t - t_step
        for req in active:
            tr.on_decode_tick(req, t, dur_s=dur)

    def _stall(self, steps: int, wall_s: float) -> None:
        """No-decode-progress watchdog tripped: dump a black box (when a
        recorder is attached) and raise instead of livelocking."""
        queued = len(self.sched.queue)
        head = self.sched.queue[0] if queued else None
        reason = (
            f"no decode progress for {self.stall_patience} scheduler "
            f"iterations: {queued} queued, 0 active, "
            f"{self.pool.free_count}/{self.pool.capacity} pages free"
        )
        if head is not None:
            worst = self.pool.pages_for(self.sched._worst_tokens(head))
            reason += (
                f"; queue head uid={head.uid} needs {worst} pages worst-case"
            )
        where = ""
        if self.recorder is not None:
            trig = self.recorder.trigger_decode_stall(
                steps, reason,
                context={
                    "num_slots": self.num_slots,
                    "page_size": self.page_size,
                    "pages_free": self.pool.free_count,
                    "pages_total": self.pool.capacity,
                    "queued": queued,
                    "decode_steps": steps,
                    "wall_s": wall_s,
                },
            )
            if trig.dump_path:
                where = f" (black box: {trig.dump_path})"
        self._run = None   # the stall is terminal for this run
        raise RuntimeError(f"serving decode stall: {reason}{where}")

    # -- API ---------------------------------------------------------------

    def run(self, requests: Sequence[Request], now=time.perf_counter,
            tick_hook=None):
        """Serve ``requests`` to completion; returns
        (list[RequestOutput] in submit order, aggregate-metrics dict).
        ``tick_hook(engine, tick)``: optional per-iteration callback —
        the test/orchestration seam for mid-run interventions such as
        ``engine.sched.preempt`` (the evict/re-admit contract).

        A thin driver over the steppable run API (``start_run`` /
        ``tick_once`` / ``finish_run``): same order of operations as
        the pre-extraction monolith, token-identity test-pinned. The
        control plane (serving/control_plane/) uses the steppable form
        directly to interleave N replica engines in one host thread."""
        self.start_run(requests, now=now, tick_hook=tick_hook)
        try:
            while not self.sched.all_done():
                self.tick_once()
            return self.finish_run()
        except BaseException:
            # a raising tick_hook (or the stall watchdog) must leave
            # the engine reusable, exactly like the pre-extraction
            # monolith whose state lived in locals
            self.abort_run()
            raise

    def abort_run(self) -> None:
        """Discard a live steppable run (exception recovery): per-run
        accumulators drop, the engine becomes reusable. Requests still
        in the scheduler are NOT touched — callers owning them (the
        control plane's drain path) withdraw first. No-op when no run
        is in progress. The injected fault (if any) stays armed: a
        crashed replica stays crashed until :meth:`inject_fault`
        explicitly clears it (the rejoin path)."""
        self._run = None

    def inject_fault(self, kind: Optional[str]) -> None:
        """Arm (or clear, ``kind=None``) the deterministic failure
        seam: ``"crash"`` makes every subsequent :meth:`tick_once`
        raise :class:`ReplicaFault`; ``"wedge"`` makes it return
        without doing any work — alive on the wire, dead in fact. The
        chaos harness's ``replica_crash`` / ``replica_wedge`` kinds arm
        this; the control plane's health state machine is what must
        notice."""
        if kind not in (None, "crash", "wedge"):
            raise ValueError(
                f"unknown fault kind {kind!r} (expected None, 'crash' "
                f"or 'wedge')"
            )
        self._fault = kind

    def start_run(self, requests: Sequence[Request] = (),
                  now=time.perf_counter, tick_hook=None) -> None:
        """Begin a steppable run: reset the per-run accumulators, point
        the tracer at ``now``'s time domain, submit ``requests``. Drive
        with :meth:`tick_once` until ``sched.all_done()`` (or until an
        orchestrator decides to stop) and close with
        :meth:`finish_run`."""
        if self._run is not None:
            raise RuntimeError("a serving run is already in progress")
        if self.prefill_only and self._handoff_hook is None:
            raise RuntimeError(
                "a prefill_only engine needs a handoff hook before it "
                "runs (set_handoff_hook) — finished prefills have "
                "nowhere to go otherwise"
            )
        self._run_prefill_tokens = 0   # prompt tokens forwarded this run
        self._run_hit_tokens = 0       # prompt tokens served by the cache
        if self.kv_tier is not None:
            self.kv_tier.on_run_start()
        if self.tracer is not None:
            # one time domain: tracer-internal timestamps (e.g. preempt
            # hooks) must come from the same clock as t_submit/t_done
            self.tracer.set_clock(now)
        rs = _RunState(self, now, tick_hook)
        self._run = rs
        for r in requests:
            self.submit_request(r)
        rs.t0 = now()

    def submit_request(self, req: Request, reuse_uid: bool = False) -> None:
        """Mid-run ingress — the control-plane router's dispatch entry
        point (and the drain path's re-admission target: a migrated
        request keeps its first-submission timestamps, see
        ``Scheduler.submit``). ``reuse_uid=True`` keeps an existing
        cross-scheduler uid (the disagg transfer-failure fallback)."""
        rs = self._run
        if rs is None:
            raise RuntimeError("submit_request needs start_run first")
        self.sched.submit(req, rs.now(), reuse_uid=reuse_uid)
        self._m_requests.inc()
        self._m_queue.set(len(self.sched.queue))

    @property
    def run_in_progress(self) -> bool:
        return self._run is not None

    def tick_once(self) -> bool:
        """One scheduler iteration: admit, shed, advance prefills, one
        decode step over the active slots, record tokens. Returns True
        when the tick made progress (admitted / prefilled / decoded /
        shed) — the idle-replica signal a control plane polls."""
        rs = self._run
        if rs is None:
            raise RuntimeError("tick_once needs start_run first")
        if self._fault == "crash":
            raise ReplicaFault(
                "injected replica crash (testing/chaos.py fault seam)"
            )
        if self._fault == "wedge":
            # no work, no state change — but the engine's OWN stall
            # watchdog still counts, so a standalone run() eventually
            # raises instead of livelocking; a control plane's health
            # heartbeat catches the wedge much earlier
            rs.stalled += 1
            if rs.stalled >= self.stall_patience:
                self._stall(rs.steps, rs.now() - rs.t0)
            return False
        reg = self.registry
        now = rs.now
        rs.tick += 1
        if rs.tick_hook is not None:
            rs.tick_hook(self, rs.tick)
        if self.kv_tier is not None:
            # KV-tier pre-admission intercept: give the queue head one
            # shot at a cross-replica pull and/or a host-tier restore,
            # so the admission below sees the pages as ordinary cache
            # hits (restore) or resumes chunked prefill (pull)
            self.kv_tier.tick_intercept(now)
        admitted = self.sched.admit(now())
        shed_now = self.sched.drain_shed()
        if shed_now:
            # shedding IS the degraded-but-healthy mode: a counter
            # and terminal outputs, never a watchdog trigger — the
            # SLO shed-fraction target decides when it's too much
            self._m_shed.inc(len(shed_now))
            rs.done.extend(shed_now)
        chunked_this_tick = 0
        if self._paged_prefill:
            for req in admitted:
                self._start_prefill(req, now)
            # one chunk per prefilling request per tick: the "mixed
            # step" — prefill advances below, decode advances after,
            # every tick
            for req in [r for r in self.sched.active()
                        if r.status is Status.PREFILL]:
                if req.status is not Status.PREFILL:
                    continue  # retracted by an earlier neighbor's
                    # lazy growth this very loop: back in the queue
                self._prefill_chunk_tick(req, now)
                rs.chunks += 1
                chunked_this_tick += 1
                if req.status is Status.DONE:
                    rs.done.append(req)
                if req.status is not Status.PREFILL:
                    rs.prefills += 1
        else:
            for req in admitted:
                self._prefill_request(req, now)
                rs.prefills += 1
                if req.status is Status.DONE:
                    rs.done.append(req)
        active = [r for r in self.sched.active()
                  if r.status is Status.DECODE]
        self._m_queue.set(len(self.sched.queue))
        if not active:
            # no admission, no prefill chunk AND no decode work:
            # nothing in this loop is time-dependent, so repeated
            # no-progress iterations mean the queue is stuck (e.g. a
            # reservation the pool can never cover). The watchdog
            # turns that silent livelock into a black-box dump + a
            # loud error.
            if admitted or chunked_this_tick or shed_now:
                # shedding is progress: the queue shrank
                rs.stalled = 0
            else:
                rs.stalled += 1
                if rs.stalled >= self.stall_patience:
                    self._stall(rs.steps, now() - rs.t0)
            rs.t_last_decode = None
            self._ledger_tick(rs)
            # everything admitted finished at prefill
            return bool(admitted or chunked_this_tick or shed_now)
        rs.stalled = 0
        use_spec = (
            self.speculative is not None
            and any(r.max_new_tokens - len(r.generated) > 1
                    for r in active)
        )
        if use_spec:
            t_step = now()
            emitted, drafted, accepted, active = self._spec_cycle(
                active, now, rs.done)
            rs.spec_drafted += drafted
            rs.spec_accepted += accepted
            t = now()
        else:
            for req in active:
                if req.status is Status.DECODE:
                    self.sched.ensure_page(req)
            # lazy growth may have RETRACTED a neighbor (temporal
            # cache-ledger interference — see Scheduler.ensure_pages);
            # only still-decoding survivors join the step
            active = [r for r in active if r.status is Status.DECODE]
            rs.table.fill(0)
            rs.seq_lens.fill(0)
            rs.tokens.fill(0)
            for req in active:
                rs.table[req.slot, :len(req.pages)] = req.pages
                rs.seq_lens[req.slot] = req.cached_len
                rs.tokens[req.slot] = req.generated[-1]
            self._note_program("step", 0)
            t_step = now()
            with span("serving.decode_step", registry=reg):
                nxt, self.k_pages, self.v_pages = self._step(
                    self.params, jnp.asarray(rs.tokens), self.k_pages,
                    self.v_pages, jnp.asarray(rs.table),
                    jnp.asarray(rs.seq_lens),
                )
                nxt = np.asarray(nxt)  # host fetch syncs: span = work
            t = now()
            emitted = len(active)
            self._trace_tick(active, t_step, t)
        if rs.t_last_decode is not None:
            gap = t_step - rs.t_last_decode
            self._m_gap.observe(gap)
            rs.max_gap = max(rs.max_gap, gap)
        rs.t_last_decode = t
        rs.steps += 1
        rs.step_time += t - t_step
        slot_occ = len(active) / self.num_slots
        page_occ = self.pool.used_count / self.pool.capacity
        rs.occ_slots += slot_occ
        rs.occ_pages += page_occ
        # per-token decode latency: a plain step emits one token per
        # active slot; a speculative cycle may emit several — both
        # normalize to seconds per token per slot
        self._m_tok_lat.observe(
            (t - t_step) * len(active) / max(emitted, 1))
        self._m_steps.inc()
        self._m_tokens.inc(emitted)
        self._m_active.set(len(active))
        self._m_slot_occ.set(slot_occ)
        self._m_page_occ.set(page_occ)
        if reg.enabled:
            # fragmentation() sorts the free list — too heavy for
            # the disabled path's one-branch cost contract
            self._m_frag.set(self.pool.fragmentation())
            if self.prefix_cache is not None:
                # refresh per step, not just on insert: pressure
                # eviction happens exactly when dashboards look
                self._m_cached.set(self.prefix_cache.cached_pages)
                self._m_evictable.set(
                    self.prefix_cache.evictable_count()
                )
        # the occupancy TIME SERIES the end-of-run averages flatten
        reg.event("serving.step", step=rs.steps, active=len(active),
                  queue_depth=len(self.sched.queue), dur_s=t - t_step,
                  slot_occupancy=slot_occ, page_occupancy=page_occ,
                  tokens=emitted)
        if self.recorder is not None:
            self.recorder.observe_serving_step(
                rs.steps, active=len(active),
                queue_depth=len(self.sched.queue), dur_s=t - t_step,
                tokens=emitted,
            )
        if not use_spec:
            for req in active:
                self.sched.record_token(req, int(nxt[req.slot]), t)
                if req.status is Status.DONE:
                    rs.done.append(req)
        self._ledger_tick(rs)
        return True

    def _build_output(self, r: Request) -> RequestOutput:
        """One finished request -> (RequestOutput, per-request dict),
        appended to the run's accumulated rows."""
        rs = self._run
        if r.finish_reason == "shed":
            # terminal but never served: the whole life was queue
            # (or requeue) wait; TTFT/decode are None (matching the
            # per_request dict) and the latency histograms are NOT
            # observed — a shed row must not flatter (or poison)
            # the served tail
            rs.shed_count += 1
            e2e = r.t_done - r.t_submit
            out = RequestOutput(
                uid=r.uid, prompt=np.asarray(r.prompt),
                generated=np.asarray(r.generated, np.int64),
                finish_reason="shed",
                queue_latency_s=e2e,
                ttft_s=None,
                decode_tokens_per_s=None,
                e2e_latency_s=e2e,
                tenant=r.tenant,
            )
            row = {
                "uid": r.uid,
                "tenant": r.tenant,
                "prompt_len": r.prompt_len,
                "new_tokens": len(r.generated),
                "finish_reason": "shed",
                "queue_latency_s": round(e2e, 6),
                "ttft_s": None,
                "e2e_latency_s": round(e2e, 6),
                "decode_tokens_per_s": None,
            }
        else:
            decode_s = max(r.t_done - r.t_admit, 1e-9)
            e2e = r.t_done - r.t_submit
            self._m_e2e.observe(e2e)
            out = RequestOutput(
                uid=r.uid, prompt=np.asarray(r.prompt),
                generated=np.asarray(r.generated, np.int64),
                finish_reason=r.finish_reason,
                queue_latency_s=r.t_admit - r.t_submit,
                ttft_s=r.t_first_token - r.t_submit,
                decode_tokens_per_s=len(r.generated) / decode_s,
                e2e_latency_s=e2e,
                tenant=r.tenant,
            )
            row = {
                "uid": r.uid,
                "tenant": r.tenant,
                "prompt_len": r.prompt_len,
                "new_tokens": len(r.generated),
                "finish_reason": r.finish_reason,
                "queue_latency_s": round(r.t_admit - r.t_submit, 6),
                "ttft_s": round(r.t_first_token - r.t_submit, 6),
                "e2e_latency_s": round(e2e, 6),
                "decode_tokens_per_s": round(len(r.generated) / decode_s, 2),
            }
        rs.outputs.append(out)
        rs.per_request.append(row)
        rs.generated_total += len(out.generated)
        return out

    def take_finished(self) -> List[Tuple[Request, RequestOutput]]:
        """Pop requests finished since the last call as
        (request, output) pairs — the control plane's incremental
        collection point, so completions can be attributed to tenants
        and replicas while the run is still going. :meth:`finish_run`
        still reports EVERY request in its outputs/metrics regardless
        (rows accumulate run-wide)."""
        rs = self._run
        if rs is None:
            raise RuntimeError("take_finished needs start_run first")
        taken = [(r, self._build_output(r))
                 for r in sorted(rs.done, key=lambda r: r.uid)]
        rs.done = []
        return taken

    def finish_run(self):
        """Close the run: build outputs for everything not already
        taken, set the wall-rate gauge, return (outputs in uid order,
        aggregate-metrics dict). The metrics cover the WHOLE run
        including requests handed out through :meth:`take_finished`."""
        rs = self._run
        if rs is None:
            raise RuntimeError("finish_run needs start_run first")
        now = rs.now
        wall = max(now() - rs.t0, 1e-9)
        # telemetry tokens/s from the COUNTER delta: cross-checks the
        # per-step instrumentation against the legacy aggregate below
        # (tests pin agreement within 1%)
        self._m_tps.set((self._m_tokens.value - rs.tok0) / wall)
        for r in sorted(rs.done, key=lambda r: r.uid):
            self._build_output(r)
        rs.done = []
        order = sorted(range(len(rs.outputs)),
                       key=lambda i: rs.outputs[i].uid)
        outputs = [rs.outputs[i] for i in order]
        per_request = [rs.per_request[i] for i in order]
        metrics = {
            "wall_time_s": round(wall, 6),
            "decode_steps": rs.steps,
            # summed decode-step wall time: generated / this = the
            # decode-POOL rate (prefill stalls excluded) — the disagg
            # bench's "prefill off the critical path" meter
            "decode_step_time_s": round(rs.step_time, 6),
            "prefills": rs.prefills,
            "generated_tokens": rs.generated_total,
            "decode_tokens_per_s": round(rs.generated_total / wall, 2),
            "slot_occupancy": round(rs.occ_slots / rs.steps, 4)
            if rs.steps else 0.0,
            "page_occupancy": round(rs.occ_pages / rs.steps, 4)
            if rs.steps else 0.0,
            "requests": per_request,
            # tokens actually forwarded through prefill this run — the
            # FLOP meter every engine flavor reports on the same basis
            # (prompt tokens only, never decode; cache hits subtract)
            "prefill_tokens": self._run_prefill_tokens,
            # deadline-shed terminal count (graceful degradation)
            "shed_requests": rs.shed_count,
        }
        if self._paged_prefill:
            metrics["prefill_chunks"] = rs.chunks
            metrics["max_decode_gap_s"] = round(rs.max_gap, 6)
        if self.prefix_cache is not None:
            hit = self._run_hit_tokens
            fwd = self._run_prefill_tokens
            metrics["prefix_cache"] = {
                "hit_tokens": hit,
                "prefill_tokens": fwd,
                "hit_rate": round(hit / (hit + fwd), 4) if hit + fwd else 0.0,
                "cached_pages": self.prefix_cache.cached_pages,
                "shared_pages_now": self.pool.shared_count,
            }
        if self.kv_tier is not None and (
                self.host_tier is not None
                or self.kv_tier.pulls or self.kv_tier.fallbacks):
            metrics["kv_tier"] = dict(self.kv_tier.run_stats())
            if self.host_tier is not None:
                metrics["kv_tier"]["host"] = self.host_tier.stats()
        if self.memledger is not None:
            # peak per-class occupancy + fragmentation + leak/audit
            # verdicts: the memory trajectory one bench row carries
            metrics["memory"] = self.memledger.run_summary()
        if self.speculative is not None:
            metrics["speculative"] = {
                "draft_tokens": rs.spec_drafted,
                "accepted_tokens": rs.spec_accepted,
                "acceptance_rate": round(
                    rs.spec_accepted / rs.spec_drafted, 4)
                if rs.spec_drafted else 0.0,
            }
        self._sentinel_observe(rs, wall)
        self._run = None
        return outputs, metrics

    def _sentinel_observe(self, rs, wall: float) -> None:
        """Per-run perf-sentinel hook: with no sentinel attached (the
        default) the cost is this one attribute read + branch — the
        disabled-path guard test times exactly this call. With one, the
        run's throughput and its decode-step vs idle split feed the
        rolling baseline; a regression dumps a perf_regression black
        box naming the component ("idle time 3.2x baseline")."""
        s = self.sentinel
        if s is None:
            return
        if rs.steps == 0:
            # a run with no decode steps — everything deadline-shed, or
            # a prefill-only/handoff run — is the DEGRADED-BUT-HEALTHY
            # mode (docs/robustness.md), not a perf sample: tokens/s=0
            # and idle=wall would fire a spurious perf_regression
            # against a per-step baseline it isn't comparable to
            return
        steps = rs.steps
        s.observe(
            {
                "decode_step_s": rs.step_time / steps,
                # host-side time between decode steps (queue handling,
                # prefill waits, stalls) — the component a host stall
                # or scheduler regression inflates
                "idle_s": max(wall - rs.step_time, 0.0) / steps,
            },
            step=rs.steps,
            tokens_per_s=rs.generated_total / wall if wall > 0 else 0.0,
            context={"num_slots": self.num_slots,
                     "decode_steps": rs.steps,
                     "wall_s": wall},
        )


QUANT_BENCH_ARMS = {
    "fp": {},
    "int8w": {"weight_dtype": "int8"},
    "int8kv": {"kv_dtype": "int8"},
    "int8w+int8kv": {"weight_dtype": "int8", "kv_dtype": "int8"},
}


def _quant_arm_row(engine, outs, metrics):
    """One quant-arm bench row: throughput, TTFT quantiles through the
    shared telemetry Histogram, and the memory-report capacity numbers
    — every arm reports the same fields so fp-vs-int8 divides
    like-for-like."""
    h_ttft = Histogram("quant_arm.ttft_seconds")  # standalone reservoir
    for o in outs:
        if o.ttft_s is not None:
            h_ttft.observe(o.ttft_s)
    mem = engine.memory_report()
    return {
        "decode_tokens_per_s": metrics["decode_tokens_per_s"],
        "ttft_p50_s": round(h_ttft.quantile(0.5), 6),
        "ttft_p99_s": round(h_ttft.quantile(0.99), 6),
        "decode_steps": metrics["decode_steps"],
        "wall_time_s": metrics["wall_time_s"],
        "weights_bytes": mem["weights"]["total_bytes"],
        "kv_bytes": mem["kv"]["total_bytes"],
        "page_capacity_ratio": mem["kv"]["page_capacity_ratio"],
    }


def serving_ab_benchmark(params, config, request_specs, *, num_slots=4,
                         num_pages=64, page_size=16, max_context=256,
                         mesh=None, param_specs=None, tp_axis="tensor",
                         seed=0, quant_arms=False, paged_kernel=False,
                         **engine_kwargs):
    """A/B the continuous-batching scheduler against naive padded
    batching on ONE model + request mix; returns a JSON-able dict.

    ``request_specs`` is a list of (prompt_len, max_new_tokens[, eos])
    tuples; prompts are seeded-random tokens so both arms and repeat
    runs see the identical workload. Each arm warms up once (compiles)
    and is then measured on a fresh copy of the workload. Extra
    ``engine_kwargs`` (prefix_cache, prefill_chunk, speculative) apply
    to BOTH arms.

    ``quant_arms=True`` adds a ``quant`` block measuring the SAME
    workload through continuous engines at fp / int8w / int8kv /
    int8w+int8kv (ROADMAP item 4): tokens/s, TTFT p50/p99, and the
    HBM + page-capacity numbers from ``memory_report()``, each pinned
    against the fp row of the same run.

    ``paged_kernel=True`` adds a ``paged_kernel`` block A/B-ing the
    fused Pallas paged-attention kernel against the XLA gather path on
    the SAME int8-pool workload: tokens/s, measured wall, and the
    ``profile()`` decode-step component split (compute/comm/idle
    fractions — the kernel's regression surface for PerfSentinel),
    plus the token-identity verdict and the chosen tile geometry.
    """
    rng = np.random.RandomState(seed)
    vocab = getattr(config, "valid_vocab_size", None) or config.vocab_size
    prompts = [rng.randint(1, vocab, (int(spec[0]),)) for spec in request_specs]

    def make_requests():
        return [
            Request(prompt=p, max_new_tokens=int(spec[1]),
                    eos_token_id=(int(spec[2]) if len(spec) > 2 else None))
            for p, spec in zip(prompts, request_specs)
        ]

    results = {}
    fp_arm = None            # (engine, outs, metrics) of the continuous arm
    for label, continuous in (("continuous", True), ("static", False)):
        engine = ServingEngine(
            params, config, num_slots=num_slots, num_pages=num_pages,
            page_size=page_size, max_context=max_context, mesh=mesh,
            param_specs=param_specs, tp_axis=tp_axis, continuous=continuous,
            **engine_kwargs,
        )
        engine.run(make_requests())          # warmup: compile every bucket
        outs, metrics = engine.run(make_requests())
        if continuous:
            fp_arm = (engine, outs, metrics)
        results[label] = {
            "decode_tokens_per_s": metrics["decode_tokens_per_s"],
            "decode_steps": metrics["decode_steps"],
            "slot_occupancy": metrics["slot_occupancy"],
            "page_occupancy": metrics["page_occupancy"],
            "wall_time_s": metrics["wall_time_s"],
        }
    results["speedup"] = round(
        results["continuous"]["decode_tokens_per_s"]
        / max(results["static"]["decode_tokens_per_s"], 1e-9), 3,
    )
    results["num_slots"] = num_slots
    results["requests"] = len(request_specs)
    if quant_arms:
        quant = {}
        for label, qkw in QUANT_BENCH_ARMS.items():
            if not qkw:
                # the fp row IS the continuous arm measured above —
                # same engine kwargs, same workload; don't re-jit and
                # re-serve the whole thing a third time
                quant[label] = _quant_arm_row(*fp_arm)
                continue
            engine = ServingEngine(
                params, config, num_slots=num_slots, num_pages=num_pages,
                page_size=page_size, max_context=max_context, mesh=mesh,
                param_specs=param_specs, tp_axis=tp_axis, continuous=True,
                **engine_kwargs, **qkw,
            )
            engine.run(make_requests())
            outs, metrics = engine.run(make_requests())
            quant[label] = _quant_arm_row(engine, outs, metrics)
        fp = quant["fp"]
        quant["summary"] = {
            "tokens_per_s_vs_fp": {
                k: round(v["decode_tokens_per_s"]
                         / max(fp["decode_tokens_per_s"], 1e-9), 3)
                for k, v in quant.items() if k != "fp"
            },
            "kv_capacity_ratio_int8": (
                quant["int8kv"]["page_capacity_ratio"]
            ),
            "weight_bytes_ratio_int8": round(
                fp["weights_bytes"]
                / max(quant["int8w"]["weights_bytes"], 1), 3,
            ),
        }
        results["quant"] = quant
    if paged_kernel:
        paged: dict = {}
        pk_kwargs = dict(engine_kwargs)
        # the kernel's headline case is wire-precision int8 pages; an
        # explicit kv_dtype in engine_kwargs still wins
        pk_kv = pk_kwargs.pop("kv_dtype", "int8")
        arm_outs = {}
        for label in ("gather", "paged"):
            engine = ServingEngine(
                params, config, num_slots=num_slots, num_pages=num_pages,
                page_size=page_size, max_context=max_context, mesh=mesh,
                param_specs=param_specs, tp_axis=tp_axis, continuous=True,
                kv_dtype=pk_kv, attn_kernel=label, **pk_kwargs,
            )
            engine.run(make_requests())          # warmup: compile
            outs, metrics = engine.run(make_requests())
            arm_outs[label] = outs
            prof = engine.profile(steps=3, warmup=1)
            row = {
                "decode_tokens_per_s": metrics["decode_tokens_per_s"],
                "decode_step_time_s": metrics["decode_step_time_s"],
                "wall_time_s": metrics["wall_time_s"],
                # measured decode-step attribution (telemetry/xprof.py):
                # the component fractions PerfSentinel tracks as the
                # kernel's regression surface
                "step_wall_s": round(prof.wall_step_s, 6),
                "compute_fraction": round(prof.compute_fraction, 4),
                "comm_fraction": round(prof.comm_fraction, 4),
                "idle_fraction": round(prof.idle_fraction, 4),
            }
            if "max_decode_gap_s" in metrics:
                row["max_decode_gap_s"] = metrics["max_decode_gap_s"]
            if label == "paged":
                row["tile"] = engine._paged_tile(n_queries=1)
            paged[label] = row
        identical = all(
            np.array_equal(a.generated, b.generated)
            for a, b in zip(arm_outs["gather"], arm_outs["paged"])
        )
        paged["summary"] = {
            "kv_dtype": pk_kv or "fp",
            "outputs_token_identical": bool(identical),
            "tokens_per_s_vs_gather": round(
                paged["paged"]["decode_tokens_per_s"]
                / max(paged["gather"]["decode_tokens_per_s"], 1e-9), 3,
            ),
            "step_wall_vs_gather": round(
                paged["paged"]["step_wall_s"]
                / max(paged["gather"]["step_wall_s"], 1e-9), 3,
            ),
        }
        results["paged_kernel"] = paged
    return results


def make_skewed_replay(*, n_requests: int, n_prefixes: int, prefix_len: int,
                       suffix_lens: Sequence[int], max_new: int,
                       vocab: int, seed: int = 0, zipf_a: float = 1.2,
                       n_tenants: Optional[int] = None,
                       tenant_zipf_a: float = 1.2,
                       working_set_factor: Optional[float] = None,
                       num_pages: Optional[int] = None,
                       page_size: Optional[int] = None):
    """Synthetic heavy-traffic replay with SKEWED prompt reuse: each
    request's prompt is one of ``n_prefixes`` shared prefixes (drawn
    Zipf-style — rank r with weight 1/r^a, the few-hot-system-prompts
    shape production traffic has) followed by a private random suffix.
    Returns a list of (prompt ndarray, max_new) pairs; every call with
    the same seed replays the identical trace, so cache-on and
    cache-off arms measure the same workload.

    ``n_tenants``: multi-tenant flavor — each request additionally
    draws a tenant name ("t0".."tN") from a SECOND independent Zipf
    (``tenant_zipf_a``), the one-hot-customer shape the control plane's
    fairness ledger exists for, and the rows become (prompt, max_new,
    tenant) TRIPLES. Default None keeps the legacy pair shape, so
    every existing caller unpacks unchanged.

    ``working_set_factor``: size the distinct-prefix corpus RELATIVE to
    a pool's HBM capacity instead of passing ``n_prefixes`` absolutely
    — factor 2.0 against (``num_pages``, ``page_size``) makes the
    prefix working set twice what the pool can hold, the guaranteed-
    overflow replay the KV-tier bench needs (every factor > 1 forces
    LRU eviction; the tier turns those evictions into restores instead
    of recomputes). Requires ``num_pages`` and ``page_size``;
    overrides ``n_prefixes``."""
    if working_set_factor is not None:
        if num_pages is None or page_size is None:
            raise ValueError(
                "working_set_factor needs num_pages and page_size — it "
                "sizes the prefix corpus against the pool's capacity")
        if working_set_factor <= 0:
            raise ValueError(
                f"working_set_factor must be > 0, got {working_set_factor}")
        # pool capacity is num_pages - 1 (the scheduler's slack page)
        cap_tokens = (num_pages - 1) * page_size
        n_prefixes = max(1, -(-int(working_set_factor * cap_tokens)
                              // max(prefix_len, 1)))
    rng = np.random.RandomState(seed)
    prefixes = [rng.randint(1, vocab, (prefix_len,)) for _ in range(n_prefixes)]
    weights = np.array([1.0 / (r + 1) ** zipf_a for r in range(n_prefixes)])
    weights /= weights.sum()
    t_weights = None
    if n_tenants is not None:
        if n_tenants < 1:
            raise ValueError(f"n_tenants must be >= 1, got {n_tenants}")
        t_weights = np.array(
            [1.0 / (r + 1) ** tenant_zipf_a for r in range(n_tenants)]
        )
        t_weights /= t_weights.sum()
    specs = []
    for _ in range(n_requests):
        pfx = prefixes[rng.choice(n_prefixes, p=weights)]
        sfx = rng.randint(1, vocab, (int(rng.choice(suffix_lens)),))
        prompt = np.concatenate([pfx, sfx])
        if t_weights is None:
            specs.append((prompt, max_new))
        else:
            tenant = f"t{int(rng.choice(n_tenants, p=t_weights))}"
            specs.append((prompt, max_new, tenant))
    return specs


def prefix_replay_benchmark(params, config, *, n_requests=12, n_prefixes=3,
                            prefix_len=16, suffix_lens=(2, 4, 6), max_new=6,
                            seed=0, zipf_a=1.2, num_slots=4, num_pages=64,
                            page_size=8, max_context=64, prefill_chunk=None,
                            mesh=None, param_specs=None, tp_axis="tensor",
                            include_speculative=False, speculative=(1, 3),
                            trace=False, include_quant=False,
                            include_tiered=False, tiered_working_set=2.0,
                            tiered_budget_bytes=1 << 30):
    """Measure the tentpole: the same skewed-prompt-reuse replay through
    (a) the PR 1 baseline engine (monolithic prefill, no sharing),
    (b) chunked prefill alone, (c) the prefix cache alone, (d) both, and
    optionally (e) both + self-speculative decode. Per arm: tokens/s,
    TTFT p50/p99, prefill tokens actually forwarded (the FLOP meter —
    the cache arms' drop is proportional to the hit rate), and the max
    decode-step gap (chunking bounds it by one chunk's compute).
    JSON-able. The ``summary`` block compares the pure-cache arm to the
    baseline: on prefill-compute-bound workloads (long shared prefixes
    — the production shape) the TTFT win tracks the hit rate; the
    chunked arms trade a little TTFT for never stalling neighbors.

    ``trace=True`` additionally replays each arm ONCE MORE with a
    ``RequestTracer`` attached — OUTSIDE the measured run, so the
    measurement stays tracer-free — and returns a ``request_trace``
    block: per-arm latency attribution (every request's additive
    queue/prefill/decode/stall components, which sum to its measured
    e2e) plus a cross-arm summary showing how much of the cached arm's
    TTFT win the cache-savings share accounts for. This is what
    bench.py writes to ``bench_request_trace.json``.

    ``include_quant=True`` adds ``int8w`` / ``int8kv`` /
    ``int8w+int8kv`` arms — the cached+chunked engine with ROADMAP
    item 4's quantization knobs — each carrying its HBM bytes and
    page-capacity ratio next to the usual tokens/s and TTFT columns,
    and a ``summary.quant`` block pinning them against the fp
    cached+chunked arm of the same run.

    ``include_tiered=True`` adds the KV-memory-hierarchy block: a
    SECOND replay whose prefix working set is ``tiered_working_set``
    times the pool's HBM capacity (guaranteed eviction pressure) run
    through (a) ``lru`` — the plain cached+chunked engine, every
    eviction recomputes; (b) ``host_tier`` — the same engine with a
    host-DRAM tier, evictions spill and later misses restore; and
    (c) ``fleet_pull`` — a COLD replica pulling the prefixes a warm
    peer already holds through the cross-replica transfer path. Each
    arm reports tokens/s, TTFT p50/p99, hit rate, and the
    restored-vs-recomputed token split; ``tiered.summary`` pins the
    tier's hit-rate and TTFT-p99 wins over the LRU arm (the
    acceptance meters)."""
    vocab = getattr(config, "valid_vocab_size", None) or config.vocab_size
    replay = make_skewed_replay(
        n_requests=n_requests, n_prefixes=n_prefixes, prefix_len=prefix_len,
        suffix_lens=suffix_lens, max_new=max_new, vocab=vocab, seed=seed,
        zipf_a=zipf_a,
    )

    def requests():
        return [Request(prompt=p, max_new_tokens=n) for p, n in replay]

    chunk = prefill_chunk or page_size
    arms = {
        "baseline": {},
        "chunked": {"prefill_chunk": chunk},
        "cached": {"prefix_cache": True},
        "cached+chunked": {"prefill_chunk": chunk, "prefix_cache": True},
    }
    if include_speculative:
        arms["cached+spec"] = {
            "prefill_chunk": chunk, "prefix_cache": True,
            "speculative": tuple(speculative),
        }
    quant_labels = set()
    if include_quant:
        # quant arms ride the full cached+chunked configuration — the
        # production shape — so the int8 rows answer "what does
        # quantization cost/buy ON TOP of the PR 6 engine", and the
        # shared-page/COW paths run quantized in the same breath
        for qlabel, qkw in (("int8w", {"weight_dtype": "int8"}),
                            ("int8kv", {"kv_dtype": "int8"}),
                            ("int8w+int8kv", {"weight_dtype": "int8",
                                              "kv_dtype": "int8"})):
            arms[qlabel] = {"prefill_chunk": chunk, "prefix_cache": True,
                            **qkw}
            quant_labels.add(qlabel)
    results = {}
    arm_traces = {}
    for label, kw in arms.items():
        engine = ServingEngine(
            params, config, num_slots=num_slots, num_pages=num_pages,
            page_size=page_size, max_context=max_context, mesh=mesh,
            param_specs=param_specs, tp_axis=tp_axis, **kw,
        )
        # two warmups: the first is COLD (compiles the miss paths and
        # seeds the cache), the second exercises the WARM hit paths
        # (short-tail chunk buckets, COW) so nothing compiles inside
        # the measured replay
        engine.run(requests())
        engine.run(requests())
        outs, metrics = engine.run(requests())
        # TTFT quantiles through the shared telemetry Histogram (the
        # registry's single source of truth for percentile math — same
        # sorted-reservoir index rule the exporters report)
        h_ttft = Histogram(f"replay.{label}.ttft_seconds")  # standalone
        for o in outs:
            if o.ttft_s is not None:  # shed rows carry no TTFT
                h_ttft.observe(o.ttft_s)
        row = {
            "decode_tokens_per_s": metrics["decode_tokens_per_s"],
            "ttft_p50_s": round(h_ttft.quantile(0.5), 6),
            "ttft_p99_s": round(h_ttft.quantile(0.99), 6),
            "decode_steps": metrics["decode_steps"],
            "wall_time_s": metrics["wall_time_s"],
        }
        if trace:
            # one EXTRA traced replay on the warm engine — attribution
            # without perturbing the measured run above
            from pipegoose_tpu.telemetry.reqtrace import RequestTracer

            tracer = RequestTracer(registry=engine.registry,
                                   keep_completed=max(n_requests, 1))
            engine.attach_tracer(tracer)
            engine.run(requests())
            arm_traces[label] = tracer.attribution_summary()
            engine.attach_tracer(None)
        # one basis for every arm: prompt tokens the engine actually
        # forwarded (metrics["prefill_tokens"]), so the cached arms'
        # reduction divides like-for-like against the baseline
        row["prefill_tokens"] = metrics["prefill_tokens"]
        if label in quant_labels:
            mem = engine.memory_report()
            row["weights_bytes"] = mem["weights"]["total_bytes"]
            row["kv_bytes"] = mem["kv"]["total_bytes"]
            row["page_capacity_ratio"] = mem["kv"]["page_capacity_ratio"]
        if "max_decode_gap_s" in metrics:
            row["max_decode_gap_s"] = metrics["max_decode_gap_s"]
        if "prefix_cache" in metrics:
            row["hit_rate"] = metrics["prefix_cache"]["hit_rate"]
        if "speculative" in metrics:
            row["spec_acceptance_rate"] = (
                metrics["speculative"]["acceptance_rate"])
        results[label] = row
    base = results["baseline"]
    cached = results["cached"]
    results["summary"] = {
        "requests": n_requests,
        "shared_prefix_len": prefix_len,
        "hit_rate": cached.get("hit_rate", 0.0),
        "prefill_token_reduction": round(
            1.0 - cached["prefill_tokens"] / max(base["prefill_tokens"], 1),
            4,
        ),
        "ttft_p99_speedup": round(
            base["ttft_p99_s"] / max(cached["ttft_p99_s"], 1e-9), 3
        ),
        "tokens_per_s_speedup": round(
            cached["decode_tokens_per_s"]
            / max(base["decode_tokens_per_s"], 1e-9), 3,
        ),
    }
    if include_quant:
        both = results["int8w+int8kv"]
        cc = results["cached+chunked"]
        results["summary"]["quant"] = {
            # the acceptance meters: HBM multiplier of the int8 pool and
            # the throughput ratio vs the same engine at fp — both from
            # THIS run's rows, not a spec sheet
            "kv_page_capacity_ratio": both["page_capacity_ratio"],
            "tokens_per_s_vs_fp_cached": round(
                both["decode_tokens_per_s"]
                / max(cc["decode_tokens_per_s"], 1e-9), 3,
            ),
            "ttft_p99_vs_fp_cached": round(
                both["ttft_p99_s"] / max(cc["ttft_p99_s"], 1e-9), 3,
            ),
        }
    if trace:
        bt, ct = arm_traces["baseline"], arm_traces["cached"]
        b_ttft = bt["mean_ttft_s"] or 0.0
        c_ttft = ct["mean_ttft_s"] or 0.0
        b_pre = bt["mean_ttft_components"]["prefill_s"]
        c_pre = ct["mean_ttft_components"]["prefill_s"]
        results["request_trace"] = {
            "arms": arm_traces,
            # where did the cached arm's TTFT win come from? The queue
            # and prefill components decompose it, and the cache-savings
            # share (hit tokens / prompt tokens) must account for the
            # prefill-side reduction — ≈ prefill_token_reduction by
            # construction (both count the same hits)
            "summary": {
                "baseline_mean_ttft_s": b_ttft,
                "cached_mean_ttft_s": c_ttft,
                "ttft_improvement_s": b_ttft - c_ttft,
                "baseline_prefill_component_s": b_pre,
                "cached_prefill_component_s": c_pre,
                "prefill_component_reduction_s": b_pre - c_pre,
                "cache_hit_share": ct["cache_hit_share"],
                "prefill_token_reduction": (
                    results["summary"]["prefill_token_reduction"]
                ),
                "cached_mean_cache_saved_est_s": (
                    ct["mean_cache_saved_est_s"]
                ),
            },
        }
    if include_tiered:
        from pipegoose_tpu.serving.kv_tier import HostTier

        overflow = make_skewed_replay(
            n_requests=n_requests, n_prefixes=n_prefixes,
            prefix_len=prefix_len, suffix_lens=suffix_lens,
            max_new=max_new, vocab=vocab, seed=seed + 1, zipf_a=zipf_a,
            working_set_factor=tiered_working_set, num_pages=num_pages,
            page_size=page_size,
        )

        def overflow_requests():
            return [Request(prompt=p, max_new_tokens=n) for p, n in overflow]

        def tier_engine(**kw):
            return ServingEngine(
                params, config, num_slots=num_slots, num_pages=num_pages,
                page_size=page_size, max_context=max_context, mesh=mesh,
                param_specs=param_specs, tp_axis=tp_axis,
                prefill_chunk=chunk, prefix_cache=True, **kw,
            )

        def tier_row(engine, warmups=2):
            for _ in range(warmups):
                engine.run(overflow_requests())
            outs, m = engine.run(overflow_requests())
            h = Histogram("replay.tiered.ttft_seconds")  # standalone
            for o in outs:
                if o.ttft_s is not None:
                    h.observe(o.ttft_s)
            row = {
                "decode_tokens_per_s": m["decode_tokens_per_s"],
                "ttft_p50_s": round(h.quantile(0.5), 6),
                "ttft_p99_s": round(h.quantile(0.99), 6),
                "wall_time_s": m["wall_time_s"],
                "hit_rate": m["prefix_cache"]["hit_rate"],
                # the restore-vs-recompute split: prefill_tokens is the
                # FLOP meter (what WAS recomputed), restored/pulled are
                # tier/wire tokens that were not
                "recomputed_tokens": m["prefill_tokens"],
                "restored_tokens": m.get("kv_tier", {}).get(
                    "restored_tokens", 0),
                "pulled_tokens": m.get("kv_tier", {}).get(
                    "pulled_tokens", 0),
            }
            return row, engine

        tiered = {}
        tiered["lru"], _ = tier_row(tier_engine())
        tiered["host_tier"], warm_engine = tier_row(
            tier_engine(host_tier=HostTier(tiered_budget_bytes)))
        # fleet arm: a COLD replica (fresh cache, no tier) pulls from
        # the warm host_tier engine above — one warm run compiles the
        # puller; pulls keep happening in the measured run because the
        # overflow working set evicts between runs
        puller = tier_engine()
        puller.set_peer_source(warm_engine)
        tiered["fleet_pull"], _ = tier_row(puller, warmups=1)
        lru, ht = tiered["lru"], tiered["host_tier"]
        tiered["summary"] = {
            "working_set_factor": tiered_working_set,
            "hit_rate_lru": lru["hit_rate"],
            "hit_rate_tiered": ht["hit_rate"],
            "ttft_p99_speedup_vs_lru": round(
                lru["ttft_p99_s"] / max(ht["ttft_p99_s"], 1e-9), 3),
            "recompute_token_reduction": round(
                1.0 - ht["recomputed_tokens"]
                / max(lru["recomputed_tokens"], 1), 4),
        }
        results["tiered"] = tiered
    return results
