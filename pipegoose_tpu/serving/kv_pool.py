"""Paged KV-cache pool: fixed-size pages + a page-table attention path.

vLLM-style paging rebuilt for the jit/shard_map stack. The per-call
contiguous cache (models/generate.py:init_cache) allocates
``batch * max_len`` key/value slots whether or not a row ever fills
them; a serving engine multiplexing many requests instead draws from ONE
preallocated pool

    (n_layer, num_pages, page_size, n_head_local, head_dim)

per k and v, where a sequence owns ``ceil(len / page_size)`` pages wired
up by an integer page table. Three pieces live here:

- :class:`PagePool` — the HOST-side free-list allocator. Allocation is a
  LIFO stack pop, so placement is deterministic given the request/evict
  order (testable invariant); page 0 is reserved as the NULL page that
  absorbs writes from padded slots and pad positions.
- :func:`paged_decode_step` — one decode step over the ragged active
  batch: each slot's pending token is scatter-written through its page
  table, attention reads the gathered page view, and invalid key
  columns (beyond ``seq_lens``, stale page tails, null-page garbage)
  are masked to exactly zero softmax weight. Reuses the SAME qkv
  projection and attention core as the contiguous path
  (models/generate.py:_qkv_proj/_attn_core) so numerics cannot drift.
- :func:`write_prompt_pages` — scatter a prefill's contiguous cache
  into the pool, repacking a LEFT-padded prompt to logical positions
  0..len-1 (the unpadded layout the decode bias assumes).

Under TP every function sees the LOCAL head subset (call inside
shard_map with the pool's head dim sharded over the tensor axis), and
the engine pairs the local logits with ``global_greedy_pick`` exactly
like models/_decode.py's sharded driver.
"""
from __future__ import annotations

from collections import deque
from typing import Deque, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from pipegoose_tpu.models.bloom import NEG_INF, alibi_slopes, bloom_gelu, layer_norm, logits_fn
from pipegoose_tpu.models.generate import _attn_core, _qkv_proj
from pipegoose_tpu.nn.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)

NULL_PAGE = 0


class PagePool:
    """Free-list allocator over ``num_pages`` fixed-size KV pages.

    Page 0 is the NULL page — never handed out; padded slots and the pad
    positions of a bucketed prefill scatter their garbage there. The
    free list is a LIFO stack, so the physical placement of any workload
    is a pure function of the submit/evict order (the determinism
    invariant tests/serving/test_kv_pool.py pins down). ``history``
    keeps the most recent (event, pages) pairs for those tests and for
    debugging fragmentation — bounded so a long-lived engine never
    accumulates host memory per request."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is the null page)")
        if page_size < 1:
            raise ValueError(f"page_size must be positive, got {page_size}")
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._owned: set = set()
        self.history: Deque[Tuple[str, Tuple[int, ...]]] = deque(maxlen=1024)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_pages - 1 - len(self._free)

    @property
    def capacity(self) -> int:
        """Allocatable pages (the null page is not allocatable)."""
        return self.num_pages - 1

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"page pool exhausted: requested {n}, free {len(self._free)} "
                f"of {self.capacity} (admission control should prevent this)"
            )
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            if p == NULL_PAGE or p in self._owned:
                raise RuntimeError(f"allocator invariant broken: page {p} "
                                   f"double-allocated or null")
            self._owned.add(p)
        self.history.append(("alloc", tuple(pages)))
        return pages

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p not in self._owned:
                raise RuntimeError(f"freeing page {p} that is not allocated")
            self._owned.discard(p)
            self._free.append(p)
        self.history.append(("free", tuple(pages)))


def init_pages(config, num_pages: int, page_size: int, tp: int = 1):
    """The pool's device buffers; under TP each shard holds nh/tp heads
    (create the GLOBAL array and shard dim 3 over the tensor axis)."""
    L, nh, hd = config.n_layer, config.n_head, config.head_dim
    shape = (L, num_pages, page_size, nh // tp, hd)
    return jnp.zeros(shape, config.dtype), jnp.zeros(shape, config.dtype)


def write_prompt_pages(k_pages, v_pages, cache, phys_pages, pad, page_size):
    """Scatter a prefill's contiguous cache into the pool.

    ``cache`` is forward_cached's (L, 1, S_pad, nh, hd) pair holding a
    LEFT-padded prompt (``pad`` pad slots, then the prompt); logical
    prompt position p lands in page ``phys_pages[p // page_size]`` at
    offset ``p % page_size`` — the repack drops the padding, so decode
    sees the unpadded 0..len-1 layout. Pad positions route to the NULL
    page. ``phys_pages`` is the slot's full page-table row (fixed width,
    unused tail entries 0) so every bucket shares one compiled program.
    """
    k_seq, v_seq = cache["k"][:, 0], cache["v"][:, 0]  # (L, S_pad, nh, hd)
    s_pad = k_seq.shape[1]
    pos = jnp.arange(s_pad)
    logical = pos - pad
    valid = logical >= 0
    lclip = jnp.where(valid, logical, 0)
    dest_page = jnp.where(valid, phys_pages[lclip // page_size], NULL_PAGE)
    dest_off = jnp.where(valid, lclip % page_size, 0)
    k_pages = k_pages.at[:, dest_page, dest_off].set(k_seq.astype(k_pages.dtype))
    v_pages = v_pages.at[:, dest_page, dest_off].set(v_seq.astype(v_pages.dtype))
    return k_pages, v_pages


def gather_pages(pages, page_table):
    """Read the pool through a page table: (B, W) int32 -> the per-slot
    contiguous view (B, W * page_size, nh, hd). The read path of the
    paged attention; exposed for the reconstruction tests."""
    b, w = page_table.shape
    ps = pages.shape[-3]
    view = jnp.take(pages, page_table, axis=-4)
    # (.., B, W, ps, nh, hd) -> (.., B, W * ps, nh, hd)
    return view.reshape(view.shape[:-4] + (w * ps,) + view.shape[-2:])


def _paged_bias(config, seq_lens, n_keys, tp_axis):
    """Additive attention bias for one paged decode step: ALiBi over the
    GLOBAL key position + a per-ROW keep mask ``key_pos <= seq_len``
    (causal-by-slot: masks not-yet-written offsets, stale page tails
    from a previous owner, and null-page garbage alike). Serving slots
    hold UNPADDED sequences, so plain global positions apply — the same
    bias _decode_bias builds for extras=None, generalized to a per-row
    ``start``. Returns (B, nh_local, 1, n_keys)."""
    tp = jax.lax.axis_size(tp_axis) if tp_axis else 1
    nh = config.n_head // tp
    slopes = jnp.asarray(alibi_slopes(config.n_head))
    if tp_axis:
        slopes = lax.dynamic_slice_in_dim(
            slopes, jax.lax.axis_index(tp_axis) * nh, nh, 0
        )
    key_pos = jnp.arange(n_keys)
    keep = key_pos[None, :] <= seq_lens[:, None]  # (B, n_keys)
    bias = slopes[None, :, None, None] * key_pos[None, None, None, :].astype(jnp.float32)
    return bias + jnp.where(keep[:, None, None, :], 0.0, NEG_INF)


def paged_decode_step(params, tokens, k_pages, v_pages, page_table, seq_lens,
                      config, tp_axis=None):
    """One decode step for every slot of the ragged active batch.

    ``tokens`` (B,) are the pending tokens (each slot's last emitted
    token), ``seq_lens`` (B,) the number of tokens already cached per
    slot — the pending token's position. Each slot's k/v is written
    through its ``page_table`` (B, W) row at page ``seq_len // ps``,
    offset ``seq_len % ps``; attention reads the gathered page view.
    Padded slots must point every table entry at the NULL page (their
    writes and reads are garbage-in/garbage-out, masked by the bias and
    discarded by the scheduler).

    Returns (logits (B, V_local), k_pages, v_pages). Under ``tp_axis``
    the logits are the LOCAL vocab shard — pair with
    ``_decode.global_greedy_pick`` like the sharded generate driver.
    """
    b = tokens.shape[0]
    ps = k_pages.shape[2]
    n_keys = page_table.shape[1] * ps

    x = vocab_parallel_embedding(params["embed"], tokens[:, None], tp_axis)
    x = x.astype(config.dtype)
    x = layer_norm(params["embed_ln"], x, config.layer_norm_epsilon)
    bias = _paged_bias(config, seq_lens, n_keys, tp_axis)

    page_idx = seq_lens // ps
    off = seq_lens % ps
    phys = jnp.take_along_axis(page_table, page_idx[:, None], axis=1)[:, 0]

    def scan_fn(carry, blk_and_pages):
        h = carry
        blk, kp, vp = blk_and_pages
        ln1 = layer_norm(blk["ln_1"], h, config.layer_norm_epsilon)
        q, k, v = _qkv_proj({"qkv": blk["attn"]["qkv"]}, ln1, config, tp_axis)
        kp = kp.at[phys, off].set(k[:, 0].astype(kp.dtype))
        vp = vp.at[phys, off].set(v[:, 0].astype(vp.dtype))
        keys = gather_pages(kp, page_table)
        vals = gather_pages(vp, page_table)
        ctx = _attn_core(q, keys, vals, bias, None, h.dtype)
        h = h + row_parallel_linear(blk["attn"]["out"], ctx, tp_axis)
        ln2 = layer_norm(blk["ln_2"], h, config.layer_norm_epsilon)
        up = column_parallel_linear(blk["mlp"]["up"], ln2, tp_axis)
        h = h + row_parallel_linear(blk["mlp"]["down"], bloom_gelu(up), tp_axis)
        return h, (kp, vp)

    x, (k_pages, v_pages) = lax.scan(
        scan_fn, x, (params["blocks"], k_pages, v_pages)
    )
    x = layer_norm(params["ln_f"], x, config.layer_norm_epsilon)
    logits = logits_fn(params, x, tp_axis)[:, 0]  # (B, V_local)
    return logits, k_pages, v_pages
