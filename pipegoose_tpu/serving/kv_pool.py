"""Paged KV-cache pool: fixed-size pages + a page-table attention path.

vLLM-style paging rebuilt for the jit/shard_map stack. The per-call
contiguous cache (models/generate.py:init_cache) allocates
``batch * max_len`` key/value slots whether or not a row ever fills
them; a serving engine multiplexing many requests instead draws from ONE
preallocated pool

    (n_layer, num_pages, page_size, n_head_local, head_dim)

per k and v, where a sequence owns ``ceil(len / page_size)`` pages wired
up by an integer page table. Three pieces live here:

- :class:`PagePool` — the HOST-side free-list allocator. Allocation is a
  LIFO stack pop, so placement is deterministic given the request/evict
  order (testable invariant); page 0 is reserved as the NULL page that
  absorbs writes from padded slots and pad positions. Pages are
  REFCOUNTED (alloc/share/release) so the prefix cache
  (serving/prefix_cache.py) can point many requests at one physical
  page; :func:`copy_page` is the copy-on-write escape hatch when a
  shared page's tail must be written.
- :func:`paged_prefill_chunk` — forward a C-token chunk per row through
  the page tables (chunked prefill and self-speculative verification
  share this one program shape).
- :func:`paged_decode_step` — one decode step over the ragged active
  batch: each slot's pending token is scatter-written through its page
  table, attention reads the gathered page view, and invalid key
  columns (beyond ``seq_lens``, stale page tails, null-page garbage)
  are masked to exactly zero softmax weight. Reuses the SAME qkv
  projection and attention core as the contiguous path
  (models/generate.py:_qkv_proj/_attn_core) so numerics cannot drift.
- :func:`write_prompt_pages` — scatter a prefill's contiguous cache
  into the pool, repacking a LEFT-padded prompt to logical positions
  0..len-1 (the unpadded layout the decode bias assumes).

Under TP every function sees the LOCAL head subset (call inside
shard_map with the pool's head dim sharded over the tensor axis), and
the engine pairs the local logits with ``global_greedy_pick`` exactly
like models/_decode.py's sharded driver.

``init_pages(kv_dtype="int8")`` swaps each bank for an int8 pytree with
a per-page scale plane (one fp32 per layer/page-slot/head): writes
quantize (:func:`quantize_kv`), the attention gather dequantizes
(:func:`gather_pages`), ``copy_page`` COW-copies values and scales
together, and every signature stays identical — the quantized pool is
a drop-in for the fp one at ~``hd/(hd+4)``x fewer KV bytes per page.
"""
from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from pipegoose_tpu.models.bloom import NEG_INF, alibi_slopes, bloom_gelu, layer_norm, logits_fn
from pipegoose_tpu.models.generate import _attn_core, _qkv_proj
from pipegoose_tpu.ops.paged_attention import paged_attention
from pipegoose_tpu.nn.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)

NULL_PAGE = 0

KV_DTYPES = (None, "fp", "int8")

ATTN_IMPLS = ("gather", "paged")


def check_attn_impl(attn_impl: str) -> str:
    if attn_impl not in ATTN_IMPLS:
        raise ValueError(f"attn_impl must be one of {ATTN_IMPLS}, got "
                         f"{attn_impl!r}")
    return attn_impl

_KV_INT8_MAX = 127.0


def check_kv_dtype(kv_dtype: Optional[str]) -> Optional[str]:
    if kv_dtype not in KV_DTYPES:
        raise ValueError(f"kv_dtype must be one of {KV_DTYPES}, got "
                         f"{kv_dtype!r}")
    return None if kv_dtype == "fp" else kv_dtype


def quantize_kv(x):
    """fp (..., hd) -> (int8 (..., hd), f32 scale (...,)): symmetric
    max-abs per POSITION per HEAD over the head dim — the quantize-on-
    write half of the int8 pool. Per-(position, head) granularity keeps
    the write shard-local under TP head sharding and makes every write
    deterministic in the token values alone, which is what lets prefix
    sharing, COW, and evict->re-admit stay token-exact under int8."""
    x32 = x.astype(jnp.float32)
    scale = jnp.maximum(
        jnp.max(jnp.abs(x32), axis=-1) / _KV_INT8_MAX,
        jnp.finfo(jnp.float32).tiny,
    )
    q = jnp.clip(
        jnp.round(x32 / scale[..., None]), -_KV_INT8_MAX, _KV_INT8_MAX
    ).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale):
    """The dequantize-on-read half (inside the attention gather)."""
    return q.astype(jnp.float32) * scale[..., None]


def _is_quantized(pages) -> bool:
    return isinstance(pages, dict)


class PagePool:
    """Refcounted free-list allocator over ``num_pages`` fixed-size KV pages.

    Page 0 is the NULL page — never handed out; padded slots and the pad
    positions of a bucketed prefill scatter their garbage there. The
    free list is a LIFO stack, so the physical placement of any workload
    is a pure function of the submit/evict order (the determinism
    invariant tests/serving/test_kv_pool.py pins down).

    Pages carry a **refcount** so the prefix cache (serving/
    prefix_cache.py) can share one physical page between many readers:
    ``alloc`` hands out pages at refcount 1, ``share`` adds a reader,
    ``release`` drops one — a page returns to the free list only when
    its last reference is released. ``free`` is an alias for ``release``
    (the pre-sharing API). A shared page is READ-ONLY for everyone but
    its writer-by-construction: the scheduler guarantees write positions
    never land in a page with refcount > 1 (copy-on-write duplicates the
    page first).

    ``history`` keeps the most recent (event, pages, refcount-delta)
    triples for the determinism tests and for debugging fragmentation —
    the delta makes sharing visible (a ``release`` that does NOT free is
    a refcount decrement on a still-shared page). Bounded so a
    long-lived engine never accumulates host memory per request."""

    def __init__(self, num_pages: int, page_size: int,
                 history_limit: int = 1024):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is the null page)")
        if page_size < 1:
            raise ValueError(f"page_size must be positive, got {page_size}")
        if history_limit < 1:
            raise ValueError(
                f"history_limit must be >= 1, got {history_limit}")
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._ref: Dict[int, int] = {}   # page -> refcount (allocated only)
        self.history: Deque[Tuple[str, Tuple[int, ...], int]] = deque(
            maxlen=history_limit
        )
        # events the bounded ring has silently evicted — the ring
        # itself must not look lossless once it wraps
        self.history_dropped = 0
        # optional synchronous observer (telemetry/memledger.py): gets
        # every (event, pages) pair history records plus the owner tag
        # the call site declared through ``tag``. None (the default)
        # costs one attribute read + branch per pool event.
        self.ledger = None
        self.tag = None                  # owner tag for the NEXT event

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return self.num_pages - 1 - len(self._free)

    @property
    def capacity(self) -> int:
        """Allocatable pages (the null page is not allocatable)."""
        return self.num_pages - 1

    @property
    def shared_count(self) -> int:
        """Pages currently referenced more than once."""
        return sum(1 for c in self._ref.values() if c > 1)

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def pages_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def fragmentation(self) -> float:
        """1 - (largest contiguous free run / free pages): 0.0 when the
        free space is one run (or empty). Page-table indirection makes
        fragmentation harmless for correctness; the gauge exists because
        a rising value under sharing means the LIFO stack is being
        diced by mid-stream releases — a debugging signal, not a cost."""
        if not self._free:
            return 0.0
        runs, best = 1, 1
        ordered = sorted(self._free)
        for a, b in zip(ordered, ordered[1:]):
            runs = runs + 1 if b == a + 1 else 1
            best = max(best, runs)
        return 1.0 - best / len(self._free)

    def _record(self, event: str, pages: Tuple[int, ...],
                delta: int) -> None:
        """Ring the event (counting what the bounded ring drops) and
        feed the attached ledger, consuming the one-shot owner tag."""
        h = self.history
        if len(h) == h.maxlen:
            self.history_dropped += 1
        h.append((event, pages, delta))
        led = self.ledger
        if led is not None:
            led.on_pool_event(event, pages, self.tag)
            self.tag = None

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"page pool exhausted: requested {n}, free {len(self._free)} "
                f"of {self.capacity} (admission control should prevent this)"
            )
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            if p == NULL_PAGE or p in self._ref:
                raise RuntimeError(f"allocator invariant broken: page {p} "
                                   f"double-allocated or null")
            self._ref[p] = 1
        self._record("alloc", tuple(pages), +1)
        return pages

    def share(self, pages: List[int]) -> None:
        """Add one reference to each (already allocated) page — the
        prefix-cache hit path: a new reader of an existing page."""
        for p in pages:
            if p not in self._ref:
                raise RuntimeError(f"sharing page {p} that is not allocated")
        for p in pages:
            self._ref[p] += 1
        self._record("share", tuple(pages), +1)

    def release(self, pages: List[int]) -> None:
        """Drop one reference per page; pages reaching refcount 0 return
        to the free list (LIFO — placement stays a pure function of the
        event order even under sharing)."""
        for p in pages:
            if p not in self._ref:
                raise RuntimeError(f"freeing page {p} that is not allocated")
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] == 0:
                del self._ref[p]
                self._free.append(p)
        self._record("release", tuple(pages), -1)

    # pre-sharing name: release IS free when nothing is shared
    free = release


def init_pages(config, num_pages: int, page_size: int, tp: int = 1,
               kv_dtype: Optional[str] = None):
    """The pool's device buffers; under TP each shard holds nh/tp heads
    (create the GLOBAL array and shard dim 3 over the tensor axis).

    ``kv_dtype=None`` (or "fp") keeps the fp pool: a bare array pair in
    ``config.dtype``. ``"int8"`` stores each bank as a PYTREE
    ``{"q": int8 (L, P, ps, nh, hd), "scale": f32 (L, P, ps, nh)}`` —
    the per-page scale plane rides one fp32 scalar per (layer, page
    slot, head), ~hd x 4 bytes lighter than the values it scales. Every
    pool function below dispatches on the structure, so the engine's
    jitted programs, donation, and shard_map specs carry the pair as
    one value either way."""
    L, nh, hd = config.n_layer, config.n_head, config.head_dim
    kv_dtype = check_kv_dtype(kv_dtype)
    shape = (L, num_pages, page_size, nh // tp, hd)
    if kv_dtype is None:
        return jnp.zeros(shape, config.dtype), jnp.zeros(shape, config.dtype)

    def bank():
        return {"q": jnp.zeros(shape, jnp.int8),
                "scale": jnp.zeros(shape[:-1], jnp.float32)}

    return bank(), bank()


def write_prompt_pages(k_pages, v_pages, cache, phys_pages, pad, page_size):
    """Scatter a prefill's contiguous cache into the pool.

    ``cache`` is forward_cached's (L, 1, S_pad, nh, hd) pair holding a
    LEFT-padded prompt (``pad`` pad slots, then the prompt); logical
    prompt position p lands in page ``phys_pages[p // page_size]`` at
    offset ``p % page_size`` — the repack drops the padding, so decode
    sees the unpadded 0..len-1 layout. Pad positions route to the NULL
    page. ``phys_pages`` is the slot's full page-table row (fixed width,
    unused tail entries 0) so every bucket shares one compiled program.
    """
    k_seq, v_seq = cache["k"][:, 0], cache["v"][:, 0]  # (L, S_pad, nh, hd)
    s_pad = k_seq.shape[1]
    pos = jnp.arange(s_pad)
    logical = pos - pad
    valid = logical >= 0
    lclip = jnp.where(valid, logical, 0)
    dest_page = jnp.where(valid, phys_pages[lclip // page_size], NULL_PAGE)
    dest_off = jnp.where(valid, lclip % page_size, 0)

    def scatter(pages, seq):
        if _is_quantized(pages):
            q, s = quantize_kv(seq)
            return {"q": pages["q"].at[:, dest_page, dest_off].set(q),
                    "scale": pages["scale"].at[:, dest_page, dest_off].set(s)}
        return pages.at[:, dest_page, dest_off].set(seq.astype(pages.dtype))

    return scatter(k_pages, k_seq), scatter(v_pages, v_seq)


def _gather(arr, page_table, trailing: int):
    """Page-table gather over an array whose page dim sits ``trailing``
    dims from the end-plus-one: take inserts the (B, W) table dims,
    then W and the page_size dim merge into the contiguous view."""
    b, w = page_table.shape
    ps = arr.shape[-trailing]
    view = jnp.take(arr, page_table, axis=-(trailing + 1))
    return view.reshape(
        view.shape[:-(trailing + 1)] + (w * ps,) + view.shape[-(trailing - 1):]
    )


def gather_pages(pages, page_table):
    """Read the pool through a page table: (B, W) int32 -> the per-slot
    contiguous view (B, W * page_size, nh, hd). The read path of the
    paged attention; exposed for the reconstruction tests. An int8 bank
    dequantizes HERE — inside the gather, per (position, head) — so the
    attention core sees fp values and the pool keeps 1-byte pages."""
    if _is_quantized(pages):
        q = _gather(pages["q"], page_table, trailing=3)
        s = _gather(pages["scale"], page_table, trailing=2)
        return dequantize_kv(q, s)
    return _gather(pages, page_table, trailing=3)


def page_size_of(pages) -> int:
    """Static page_size of a bank, fp or int8 (dim 2 past the layer and
    page dims; the scale plane shares it)."""
    leaf = pages["q"] if _is_quantized(pages) else pages
    return leaf.shape[-3]


def _write_kv(pages, page_idx, off_idx, val):
    """Scatter fp values ``val`` at (page_idx, off_idx) of one LAYER's
    bank (leading layer dim already scanned away) — quantizing on write
    when the bank is int8, value and scale plane in lockstep."""
    if _is_quantized(pages):
        q, s = quantize_kv(val)
        return {"q": pages["q"].at[page_idx, off_idx].set(q),
                "scale": pages["scale"].at[page_idx, off_idx].set(s)}
    return pages.at[page_idx, off_idx].set(val.astype(pages.dtype))


def _local_slopes(config, tp_axis):
    """This shard's ALiBi slope subset (all heads when unsharded)."""
    tp = jax.lax.axis_size(tp_axis) if tp_axis else 1
    nh = config.n_head // tp
    slopes = jnp.asarray(alibi_slopes(config.n_head))
    if tp_axis:
        slopes = lax.dynamic_slice_in_dim(
            slopes, jax.lax.axis_index(tp_axis) * nh, nh, 0
        )
    return slopes


def _paged_bias(config, seq_lens, n_keys, tp_axis):
    """Additive attention bias for one paged decode step: ALiBi over the
    GLOBAL key position + a per-ROW keep mask ``key_pos <= seq_len``
    (causal-by-slot: masks not-yet-written offsets, stale page tails
    from a previous owner, and null-page garbage alike). Serving slots
    hold UNPADDED sequences, so plain global positions apply — the same
    bias _decode_bias builds for extras=None, generalized to a per-row
    ``start``. Returns (B, nh_local, 1, n_keys)."""
    slopes = _local_slopes(config, tp_axis)
    key_pos = jnp.arange(n_keys)
    keep = key_pos[None, :] <= seq_lens[:, None]  # (B, n_keys)
    bias = slopes[None, :, None, None] * key_pos[None, None, None, :].astype(jnp.float32)
    return bias + jnp.where(keep[:, None, None, :], 0.0, NEG_INF)


def paged_decode_step(params, tokens, k_pages, v_pages, page_table, seq_lens,
                      config, tp_axis=None, write_ok=None,
                      draft_layers: Optional[int] = None,
                      attn_impl: str = "gather"):
    """One decode step for every slot of the ragged active batch.

    ``tokens`` (B,) are the pending tokens (each slot's last emitted
    token), ``seq_lens`` (B,) the number of tokens already cached per
    slot — the pending token's position. Each slot's k/v is written
    through its ``page_table`` (B, W) row at page ``seq_len // ps``,
    offset ``seq_len % ps``; attention reads the gathered page view.
    Padded slots must point every table entry at the NULL page (their
    writes and reads are garbage-in/garbage-out, masked by the bias and
    discarded by the scheduler).

    ``write_ok`` (B,) bool routes a row's k/v write to the NULL page
    when False — the self-speculative draft loop uses it to cap
    per-slot draft depth inside one compiled program. ``draft_layers``
    (static) runs only the FIRST k transformer blocks before the final
    LN and lm head — the shallow-exit draft model that shares every
    weight with the verifier; its k/v writes land in the pool's first k
    layer planes (the verification pass later overwrites them with
    byte-identical values, since layer i's k/v depend only on the token
    sequence and layers < i).

    ``attn_impl`` selects the attention read: ``"gather"`` (default)
    materializes the page view (gather_pages + _attn_core, the parity
    reference), ``"paged"`` walks the page table in one fused Pallas
    pass (ops/paged_attention.py) — same mask/bias semantics, no
    contiguous KV buffer, int8 pages dequantized in-register.

    Returns (logits (B, V_local), k_pages, v_pages). Under ``tp_axis``
    the logits are the LOCAL vocab shard — pair with
    ``_decode.global_greedy_pick`` like the sharded generate driver.
    """
    check_attn_impl(attn_impl)
    b = tokens.shape[0]
    ps = page_size_of(k_pages)
    n_keys = page_table.shape[1] * ps

    x = vocab_parallel_embedding(params["embed"], tokens[:, None], tp_axis)
    x = x.astype(config.dtype)
    x = layer_norm(params["embed_ln"], x, config.layer_norm_epsilon)
    if attn_impl == "paged":
        slopes = _local_slopes(config, tp_axis)
        bias = None
    else:
        bias = _paged_bias(config, seq_lens, n_keys, tp_axis)

    page_idx = seq_lens // ps
    off = seq_lens % ps
    phys = jnp.take_along_axis(page_table, page_idx[:, None], axis=1)[:, 0]
    if write_ok is not None:
        phys = jnp.where(write_ok, phys, NULL_PAGE)
        off = jnp.where(write_ok, off, 0)

    blocks = params["blocks"]
    k_all, v_all = k_pages, v_pages
    if draft_layers is not None:
        blocks = jax.tree_util.tree_map(lambda a: a[:draft_layers], blocks)
        k_pages = jax.tree_util.tree_map(lambda a: a[:draft_layers], k_pages)
        v_pages = jax.tree_util.tree_map(lambda a: a[:draft_layers], v_pages)

    def scan_fn(carry, blk_and_pages):
        h = carry
        blk, kp, vp = blk_and_pages
        ln1 = layer_norm(blk["ln_1"], h, config.layer_norm_epsilon)
        q, k, v = _qkv_proj({"qkv": blk["attn"]["qkv"]}, ln1, config, tp_axis)
        kp = _write_kv(kp, phys, off, k[:, 0])
        vp = _write_kv(vp, phys, off, v[:, 0])
        if attn_impl == "paged":
            ctx = paged_attention(q, kp, vp, page_table, seq_lens,
                                  slopes=slopes)
            ctx = ctx.astype(h.dtype).reshape(b, 1, -1)
        else:
            keys = gather_pages(kp, page_table)
            vals = gather_pages(vp, page_table)
            ctx = _attn_core(q, keys, vals, bias, None, h.dtype)
        h = h + row_parallel_linear(blk["attn"]["out"], ctx, tp_axis)
        ln2 = layer_norm(blk["ln_2"], h, config.layer_norm_epsilon)
        up = column_parallel_linear(blk["mlp"]["up"], ln2, tp_axis)
        h = h + row_parallel_linear(blk["mlp"]["down"], bloom_gelu(up), tp_axis)
        return h, (kp, vp)

    x, (k_pages, v_pages) = lax.scan(scan_fn, x, (blocks, k_pages, v_pages))
    if draft_layers is not None:
        merge = lambda full, part: full.at[:draft_layers].set(part)  # noqa: E731
        k_pages = jax.tree_util.tree_map(merge, k_all, k_pages)
        v_pages = jax.tree_util.tree_map(merge, v_all, v_pages)
    x = layer_norm(params["ln_f"], x, config.layer_norm_epsilon)
    logits = logits_fn(params, x, tp_axis)[:, 0]  # (B, V_local)
    return logits, k_pages, v_pages


def export_page_slab(pages, page_ids, wire_dtype=None):
    """Page EXPORT view for cross-pool KV streaming (serving/disagg/):
    gather ``page_ids`` (W,) int32 out of one bank into a contiguous
    slab ``(L, W, ps, nh, hd)`` at WIRE precision. An int8 bank ships
    its ``{"q", "scale"}`` planes verbatim — quantized pages are NEVER
    dequantized in flight (the whole point of the int8 wire format);
    an fp bank optionally down-casts to ``wire_dtype="bf16"`` (the
    distributed/compressed.py convention — exact when the pool dtype
    is already bf16, lossy for an fp32 pool). Pure jax: jit it on the
    source pool's mesh and the gather resolves this shard's heads; the
    host fetch of the result is the resharding point."""
    if _is_quantized(pages):
        if wire_dtype is not None:
            raise ValueError(
                "int8 pools define their own wire format (q + scale); "
                f"wire_dtype={wire_dtype!r} does not apply"
            )
        return {"q": jnp.take(pages["q"], page_ids, axis=1),
                "scale": jnp.take(pages["scale"], page_ids, axis=1)}
    slab = jnp.take(pages, page_ids, axis=1)
    if wire_dtype == "bf16":
        return slab.astype(jnp.bfloat16)
    if wire_dtype is not None:
        raise ValueError(f"unknown wire_dtype {wire_dtype!r} "
                         f"(fp pools support None or 'bf16')")
    return slab


def import_page_slab(pages, slab, dst_ids):
    """Page IMPORT view: scatter a wire slab into ``dst_ids`` (W,) of
    one bank. The quantized layout lands q and scale planes together
    (still never dequantized — the decode pool's gather does that, per
    read, like for locally written pages); a bf16 wire slab up-casts to
    the pool dtype here. Padding entries route to the NULL page, the
    same sink every other pad write uses."""
    if _is_quantized(pages):
        return {"q": pages["q"].at[:, dst_ids].set(slab["q"]),
                "scale": pages["scale"].at[:, dst_ids].set(slab["scale"])}
    return pages.at[:, dst_ids].set(slab.astype(pages.dtype))


def copy_page(k_pages, v_pages, src, dst):
    """Copy-on-write duplication: device-copy one physical page (every
    layer's k and v planes) from ``src`` to ``dst``. The prefix cache
    uses it when a request's unique tail begins MID-page of a shared
    page — the new owner gets a private copy of the shared tokens' KV
    and writes its tail there, while readers of ``src`` are untouched.
    ``src``/``dst`` are runtime scalars: one compiled program covers
    every copy. An int8 bank copies its scale plane WITH the page —
    COW'd quantized values stay exactly the values the readers of
    ``src`` dequantize."""

    def cp(plane):
        return plane.at[:, dst].set(jnp.take(plane, src, axis=1))

    return (
        jax.tree_util.tree_map(cp, k_pages),
        jax.tree_util.tree_map(cp, v_pages),
    )


def paged_prefill_chunk(params, tokens, k_pages, v_pages, page_table, start,
                        n_valid, config, tp_axis=None, all_logits=False,
                        attn_impl: str = "gather"):
    """Forward one CHUNK of C tokens per row straight through the pool.

    The prefill half of a chunked-prefill mixed step: ``tokens`` (B, C)
    are each row's next prompt tokens, ``start`` (B,) the logical
    position of the row's first chunk token (= tokens already cached,
    whether written by earlier chunks or SHARED from the prefix cache),
    ``n_valid`` (B,) how many of the C are real. Each valid token's k/v
    is written through the row's page table; pad tails route writes to
    the NULL page and get zero context. Attention is causal over the
    global position — every cached position plus the chunk's own
    earlier tokens — with the same ALiBi-over-global-position bias as
    the decode step, so chunk boundaries are invisible in the math.

    Returns (logits, k_pages, v_pages): logits at each row's LAST VALID
    position, (B, V_local) — the next-token distribution chunked
    prefill needs — or at EVERY chunk position, (B, C, V_local), with
    ``all_logits=True`` (self-speculative verification scores the whole
    draft bundle in one pass through this same paged path).

    ``attn_impl="paged"`` routes the attention read through the fused
    Pallas page-table walk (ops/paged_attention.py) in its ragged
    multi-token mode — the same kernel the decode step uses, with
    ``start`` as the per-row global query origin; pad queries beyond
    ``n_valid`` are zeroed by the same qmask multiply as the gather
    path.
    """
    check_attn_impl(attn_impl)
    b, c = tokens.shape
    ps = page_size_of(k_pages)
    n_keys = page_table.shape[1] * ps

    x = vocab_parallel_embedding(params["embed"], tokens, tp_axis)
    x = x.astype(config.dtype)
    x = layer_norm(params["embed_ln"], x, config.layer_norm_epsilon)

    pos = start[:, None] + jnp.arange(c)[None, :]             # (B, C)
    valid = jnp.arange(c)[None, :] < n_valid[:, None]         # (B, C)
    dest_page = jnp.where(
        valid, jnp.take_along_axis(page_table, pos // ps, axis=1), NULL_PAGE
    )
    dest_off = jnp.where(valid, pos % ps, 0)

    slopes = _local_slopes(config, tp_axis)
    if attn_impl == "paged":
        bias = None
    else:
        key_pos = jnp.arange(n_keys)
        keep = key_pos[None, None, :] <= pos[:, :, None]      # (B, C, K)
        bias = slopes[None, :, None, None] * key_pos[
            None, None, None, :
        ].astype(jnp.float32)
        bias = bias + jnp.where(keep[:, None, :, :], 0.0, NEG_INF)
    qmask = valid

    def scan_fn(carry, blk_and_pages):
        h = carry
        blk, kp, vp = blk_and_pages
        ln1 = layer_norm(blk["ln_1"], h, config.layer_norm_epsilon)
        q, k, v = _qkv_proj({"qkv": blk["attn"]["qkv"]}, ln1, config, tp_axis)
        kp = _write_kv(kp, dest_page, dest_off, k)
        vp = _write_kv(vp, dest_page, dest_off, v)
        if attn_impl == "paged":
            ctx = paged_attention(q, kp, vp, page_table, start,
                                  slopes=slopes)
            ctx = ctx * qmask[:, :, None, None].astype(ctx.dtype)
            ctx = ctx.astype(h.dtype).reshape(b, c, -1)
        else:
            keys = gather_pages(kp, page_table)
            vals = gather_pages(vp, page_table)
            ctx = _attn_core(q, keys, vals, bias, qmask, h.dtype)
        h = h + row_parallel_linear(blk["attn"]["out"], ctx, tp_axis)
        ln2 = layer_norm(blk["ln_2"], h, config.layer_norm_epsilon)
        up = column_parallel_linear(blk["mlp"]["up"], ln2, tp_axis)
        h = h + row_parallel_linear(blk["mlp"]["down"], bloom_gelu(up), tp_axis)
        return h, (kp, vp)

    x, (k_pages, v_pages) = lax.scan(
        scan_fn, x, (params["blocks"], k_pages, v_pages)
    )
    x = layer_norm(params["ln_f"], x, config.layer_norm_epsilon)
    if all_logits:
        return logits_fn(params, x, tp_axis), k_pages, v_pages  # (B, C, V)
    last = jnp.take_along_axis(x, (n_valid - 1)[:, None, None], axis=1)
    logits = logits_fn(params, last, tp_axis)[:, 0]             # (B, V_local)
    return logits, k_pages, v_pages
