"""Continuous-batching inference serving over a paged KV-cache pool.

The layer above the model stack that the per-call ``generate()`` /
``generate_tp()`` paths cannot provide: request multiplexing. See
docs/serving.md for the request lifecycle and page-table layout.
"""
from pipegoose_tpu.serving.engine import (
    RequestOutput,
    ServingEngine,
    serving_ab_benchmark,
)
from pipegoose_tpu.serving.kv_pool import (
    NULL_PAGE,
    PagePool,
    gather_pages,
    init_pages,
    paged_decode_step,
    write_prompt_pages,
)
from pipegoose_tpu.serving.scheduler import Request, Scheduler, Status

__all__ = [
    "NULL_PAGE",
    "PagePool",
    "Request",
    "RequestOutput",
    "Scheduler",
    "ServingEngine",
    "Status",
    "gather_pages",
    "init_pages",
    "paged_decode_step",
    "serving_ab_benchmark",
    "write_prompt_pages",
]
