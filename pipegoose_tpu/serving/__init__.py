"""Continuous-batching inference serving over a paged KV-cache pool.

The layer above the model stack that the per-call ``generate()`` /
``generate_tp()`` paths cannot provide: request multiplexing, plus the
opt-in serving-perf modes — content-addressed copy-on-write prefix
caching, chunked prefill, self-speculative decoding, and quantized
inference (``weight_dtype``/``kv_dtype``: int8/int4 weights through the
dequant-fused matmul, int8 KV pages with per-page scale planes). See
docs/serving.md for the request lifecycle, page-table layout, the
prefix-cache / COW / eviction semantics, and the quantization accuracy
contract.
"""
from pipegoose_tpu.serving.disagg import (
    DisaggEngine,
    disagg_serving_benchmark,
)
from pipegoose_tpu.serving.engine import (
    ReplicaFault,
    RequestOutput,
    ServingEngine,
    make_skewed_replay,
    prefix_replay_benchmark,
    serving_ab_benchmark,
)
from pipegoose_tpu.serving.kv_pool import (
    NULL_PAGE,
    PagePool,
    copy_page,
    dequantize_kv,
    gather_pages,
    init_pages,
    paged_decode_step,
    paged_prefill_chunk,
    quantize_kv,
    write_prompt_pages,
)
from pipegoose_tpu.serving.prefix_cache import PrefixCache, PrefixHit
from pipegoose_tpu.serving.scheduler import Request, Scheduler, Status

__all__ = [
    "DisaggEngine",
    "NULL_PAGE",
    "PagePool",
    "PrefixCache",
    "PrefixHit",
    "ReplicaFault",
    "Request",
    "RequestOutput",
    "Scheduler",
    "ServingEngine",
    "Status",
    "copy_page",
    "dequantize_kv",
    "disagg_serving_benchmark",
    "gather_pages",
    "init_pages",
    "make_skewed_replay",
    "quantize_kv",
    "paged_decode_step",
    "paged_prefill_chunk",
    "prefix_replay_benchmark",
    "serving_ab_benchmark",
    "write_prompt_pages",
]
