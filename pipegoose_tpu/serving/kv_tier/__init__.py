"""Fleet-wide KV memory hierarchy (ROADMAP open item 5).

Two layers below the radix prefix cache's HBM pages:

- :mod:`host_tier` — a byte-budgeted host-DRAM LRU of page slabs at
  WIRE precision. Eviction from the HBM prefix cache SPILLS the cold
  page here instead of discarding its KV; a later lookup miss that
  hits the tier RESTORES the page (one jitted scatter) instead of
  re-prefilling the prefix.
- :mod:`directory` — the fleet-wide prefix directory: which replica
  holds which prefix, in HBM or host tier. A replica routed a request
  whose prefix a peer already computed PULLS the pages cross-replica
  through the ``PoolTransfer`` export/import path instead of
  re-prefilling.

:mod:`restore` holds the decision logic (calibrated restore-vs-
recompute cost) and the engine-side orchestration of both paths.
"""
from pipegoose_tpu.serving.kv_tier.directory import PrefixDirectory
from pipegoose_tpu.serving.kv_tier.host_tier import (
    HostTier,
    HostTierError,
    set_host_tier_fault,
)
from pipegoose_tpu.serving.kv_tier.restore import RestoreManager, RestorePlanner

__all__ = [
    "HostTier",
    "HostTierError",
    "PrefixDirectory",
    "RestoreManager",
    "RestorePlanner",
    "set_host_tier_fault",
]
