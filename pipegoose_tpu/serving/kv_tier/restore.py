"""Restore-vs-recompute decisions and the engine-side tier orchestration.

Two paths bring previously computed prefix pages back into a pool's
HBM without re-running prefill, both staged BEFORE normal admission so
the untouched admission/COW/chunking machinery serves the request
exactly as if the pages had never left:

- **Local host-tier restore** (``maybe_restore``): the queue head's
  prompt is probed against the radix cache, then the host tier is
  walked for the contiguous block run extending the HBM hit. Found
  slabs are scattered into freshly allocated pool pages through the
  same jitted import the disagg transfer uses, the chain is inserted
  into the prefix cache, and the pages are released to cache
  ownership — the very next ``Scheduler.admit`` sees a plain cache
  hit. Token-identical by construction: the slabs are the wire-exact
  bytes the eviction spilled.
- **Cross-replica pull** (``maybe_pull``): when the fleet directory
  (or an explicit peer hint) says another replica holds the prefix,
  the pages ship through a ``PoolTransfer`` between the two engines —
  peer HBM pages via the jitted gather, peer tier entries as-is (they
  are already host wire slabs) — staged through the scheduler's
  ``begin_transfer``/``transfer_pages``/``admit_with_pages`` ledger
  path, then the request RESUMES chunked prefill at the pulled
  length. Resharding happens at the host hop (tp=2 -> tp=1 works);
  int8 pages are never dequantized in flight.

:class:`RestorePlanner` decides restore-vs-recompute per prefix length
from the calibrated :class:`~pipegoose_tpu.planner.cost.CostModel`
(PR 13's fitted launch/bandwidth/overhead constants): a restore pays
per-shipment launches plus wire bytes over the link; a recompute pays
``2 * n_params`` FLOPs per token. No model (the default) means always
restore — on the CPU test rig there is nothing calibrated to consult.

Failure contract (exercised by testing/chaos.py's
``host_tier_io_error``): any :class:`HostTierError` /
:class:`TransferError` mid-restore degrades to recompute — partial
progress is kept when it is coherent (a front-to-back partial restore
is a valid shorter hit; a failed pull aborts its staging entirely and
re-queues), one ``kv_tier_fallback`` black box names the prefix, and
the trigger is consumed immediately (recovered-by-construction: the
recompute serves the request), so ``/healthz`` never flips. Never a
stall, never a lost request.

Host-side by design (jit-safety allowlisted): the only device programs
are the shared jitted export/import pair.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pipegoose_tpu.serving.disagg.transfer import (
    PageHandoff,
    PoolTransfer,
    TransferError,
)
from pipegoose_tpu.serving.kv_tier.host_tier import HostTierError
from pipegoose_tpu.serving.scheduler import Status


def wire_page_bytes(engine) -> int:
    """Per-page wire bytes for planner estimates: int8 ships q+scale
    (``hd + 4`` bytes per position-head), fp ships the pool dtype."""
    cfg = engine.config
    ps = engine.page_size
    per_pos_head = (
        cfg.head_dim + 4 if engine.kv_dtype == "int8"
        else cfg.head_dim * int(np.dtype(cfg.dtype).itemsize)
    )
    return 2 * cfg.n_layer * ps * cfg.n_head * per_pos_head


class RestorePlanner:
    """Calibrated restore-vs-recompute decision.

    ``cost_model`` is a :class:`~pipegoose_tpu.planner.cost.CostModel`
    (ideally post-``calibrate``); ``n_params`` sizes the recompute side
    (``2 * n_params`` FLOPs/token, the standard forward estimate).
    Without a model (or with ``n_params=0``) the planner always says
    restore — the conservative default for the uncalibrated test rig,
    where wire bytes are tiny and prefill is the only real cost.
    ``min_tokens`` floors the decision (restoring one page may not be
    worth the launch even when the model is missing)."""

    def __init__(self, cost_model=None, *, n_params: int = 0,
                 min_tokens: int = 0):
        self.cost_model = cost_model
        self.n_params = int(n_params)
        self.min_tokens = int(min_tokens)

    def restore_cost_s(self, n_bytes: int, *, n_ops: int = 1,
                       cross_replica: bool = False) -> float:
        """Wire cost of moving ``n_bytes`` in ``n_ops`` shipments:
        host<->HBM staging rides the ICI constant, a cross-replica pull
        the DCI one (the calibrated fabrics the fleet actually has)."""
        cm = self.cost_model
        if cm is None:
            return 0.0
        bw = cm.dci_bytes_per_s if cross_replica else cm.ici_bytes_per_s
        return (n_ops * cm.collective_launch_s + n_bytes / max(bw, 1.0)
                + cm.step_overhead_s)

    def recompute_cost_s(self, n_tokens: int) -> float:
        cm = self.cost_model
        if cm is None:
            return float("inf")
        return (cm.step_overhead_s
                + 2.0 * self.n_params * n_tokens / max(cm.peak_flops, 1.0))

    def should_restore(self, n_tokens: int, n_bytes: int, *,
                       n_ops: int = 1, cross_replica: bool = False) -> bool:
        if n_tokens < self.min_tokens or n_tokens <= 0:
            return False
        if self.cost_model is None or self.n_params <= 0:
            return True
        return (
            self.restore_cost_s(n_bytes, n_ops=n_ops,
                                cross_replica=cross_replica)
            < self.recompute_cost_s(n_tokens)
        )


class RestoreManager:
    """Engine-side orchestrator of both tier paths.

    Owns the lazily compiled transfer programs (one self-transfer for
    spill/restore, one :class:`PoolTransfer` per peer engine for
    pulls), the per-run restored/pulled token accounting the bench
    reads, and the one-probe-per-request bookkeeping that keeps the
    hit/miss counters request-scoped rather than tick-scoped. Created
    by every paged-prefill engine (cheap — nothing compiles until the
    first spill or pull), so any engine with a prefix cache can serve
    as a pull PEER even without a host tier of its own."""

    def __init__(self, engine):
        self.engine = engine
        self.planner = RestorePlanner()
        self._self_xfer: Optional[PoolTransfer] = None
        self._peer_xfers: Dict[int, PoolTransfer] = {}
        # uid -> peer engine: the control plane's (or bench's) routing
        # hint that a specific peer holds this request's prefix
        self.pull_hints: Dict[int, Any] = {}
        self.default_peer = None
        # run-scoped accounting (reset by on_run_start)
        self.restored_tokens = 0
        self.pulled_tokens = 0
        self.pulls = 0
        self.fallbacks = 0
        self._handled: set = set()

    # -- wiring ------------------------------------------------------------

    def set_peer_source(self, peer) -> None:
        """Default pull source for every request (bench/tests; the
        control plane hints per request instead)."""
        self.default_peer = peer

    def hint_pull(self, req, peer) -> None:
        """Route hint: ``peer`` (a ServingEngine) likely holds ``req``'s
        prefix. Advisory — a stale hint costs one inventory walk."""
        self.pull_hints[req.uid] = peer

    def on_run_start(self) -> None:
        self.restored_tokens = 0
        self.pulled_tokens = 0
        self.pulls = 0
        self.fallbacks = 0
        self._handled.clear()

    def run_stats(self) -> dict:
        return {
            "restored_tokens": self.restored_tokens,
            "pulled_tokens": self.pulled_tokens,
            "pulls": self.pulls,
            "fallbacks": self.fallbacks,
        }

    def _self_transfer(self) -> PoolTransfer:
        """Engine->itself transfer: the spill export and restore import
        pair. Width 1 — tier entries are page-granular by contract."""
        if self._self_xfer is None:
            eng = self.engine
            self._self_xfer = PoolTransfer(
                eng, eng, wire_dtype=eng.host_tier_wire, width=1,
            )
        return self._self_xfer

    def _peer_transfer(self, peer) -> PoolTransfer:
        """Peer->engine transfer for pulls (compiled once per peer).
        Raises ValueError on geometry mismatch — the caller treats
        that peer as unpullable."""
        xfer = self._peer_xfers.get(id(peer))
        if xfer is None:
            width = max(
                1, (peer.prefill_chunk or peer.page_size) // peer.page_size
            )
            xfer = PoolTransfer(peer, self.engine, width=width)
            self._peer_xfers[id(peer)] = xfer
        return xfer

    # -- spill (prefix_cache.spill_hook) -----------------------------------

    def spill(self, chain: Tuple[int, ...], page: int) -> None:
        """Eviction intercept: capture the victim page's KV into the
        host tier at wire precision. Best-effort by the cache's
        contract — a failure loses the tier copy, never the eviction."""
        tier = self.engine.host_tier
        if tier is None:
            return
        ks, vs, _ = self._self_transfer().export([page])
        try:
            stored = tier.put(chain, ks, vs)
        except HostTierError:
            tier.spill_drops += 1
            return
        if stored:
            self._publish(chain, "host")

    def _publish(self, tokens, location: str) -> None:
        hook = self.engine.on_prefix_publish
        if hook is not None:
            hook(tokens, location)

    # -- the pre-admission intercept (engine.tick_once) --------------------

    def tick_intercept(self, now) -> None:
        """Runs right before ``Scheduler.admit`` each tick: give the
        queue head its one shot at a pull (peer hint) and/or a local
        tier restore, so the admission that follows sees the pages as
        ordinary cache hits. One probe per request uid — the counters
        stay request-scoped and a nothing-to-restore head is not
        re-walked every tick."""
        eng = self.engine
        sched = eng.sched
        if not sched.continuous:
            return
        while sched.queue and any(s is None for s in sched.slots):
            req = sched.queue[0]
            if req.uid in self._handled:
                return
            outcome = "no"
            if req.uid in self.pull_hints or self.default_peer is not None:
                outcome = self.maybe_pull(req, now)
                if outcome == "retry":
                    return  # ledger blocked: keep the hint, next tick
            self._handled.add(req.uid)
            if outcome == "admitted":
                continue   # head left the queue: probe the new head too
            if eng.host_tier is not None:
                self.maybe_restore(req, now)
            return  # head stays queued; the admission below takes it

    # -- local host-tier restore -------------------------------------------

    def maybe_restore(self, req, now) -> bool:
        """Restore the contiguous host-tier run extending ``req``'s HBM
        cache hit back into pool pages and insert the chain into the
        cache (pages end up cache-owned and evictable — admission then
        pins what it needs). Returns True when >= 1 page was restored."""
        eng = self.engine
        tier = eng.host_tier
        cache = eng.prefix_cache
        ps = eng.page_size
        cap = req.target_len - 1   # admission forwards >= 1 token
        toks = [int(t) for t in np.asarray(req.tokens)[:req.target_len]]
        hit = cache.lookup(toks, max_tokens=cap)
        h = hit.tokens // ps
        keys: List[Tuple[int, ...]] = []
        i = h
        while (i + 1) * ps <= cap and tier.contains(
                tuple(toks[:(i + 1) * ps])):
            keys.append(tuple(toks[:(i + 1) * ps]))
            i += 1
        tier.note_probe(len(keys))
        if not keys:
            return False
        n_bytes = sum(tier.entry_bytes(k) for k in keys)
        if not self.planner.should_restore(len(keys) * ps, n_bytes,
                                           n_ops=len(keys)):
            return False
        # Pin the matched chain before allocating: the allocation may
        # evict, and an evicted ancestor would orphan the insert below.
        cache.acquire(hit)
        try:
            pages = eng.sched.alloc_for_restore(len(keys))
            keys = keys[:len(pages)]
            if not keys:
                return False
            tr = eng.tracer
            t0 = now()
            if tr is not None:
                tr.on_restore_start(req, t0)
            xfer = self._self_transfer()
            done: List[int] = []
            try:
                for key, page in zip(keys, pages):
                    t_a = now()
                    ks, vs, nb = tier.get(key)
                    rec = PageHandoff(
                        req=req, page_index=len(key) // ps - 1, n_pages=1,
                        tokens_end=len(key), k=ks, v=vs, wire_bytes=nb,
                        final=False, first_token=None, t_created=t_a,
                    )
                    xfer.import_(rec, [page])
                    done.append(page)
                    if tr is not None:
                        t_b = now()
                        tr.on_restore_chunk(req, t_b, dur_s=t_b - t_a,
                                            tokens=ps, pages=1, nbytes=nb)
            except (HostTierError, TransferError, KeyError) as exc:
                if pages[len(done):]:
                    if eng.pool.ledger is not None:
                        eng.pool.tag = ("restore",)
                    eng.pool.release(pages[len(done):])
                self._fallback_box("host tier restore", req,
                                   keys[0], exc)
            if done:
                m = h + len(done)
                cache.insert(toks[:m * ps], list(hit.pages) + done)
                if eng.pool.ledger is not None:
                    eng.pool.tag = ("restore",)
                eng.pool.release(done)   # cache's share now owns them
                tier.note_restored(len(done))
                self.restored_tokens += len(done) * ps
                self._publish(toks[:m * ps], "hbm")
            if tr is not None:
                tr.on_restore_done(req, now())
            return bool(done)
        finally:
            # drop the probe pins acquire() took (anonymous owner=None
            # pins — the ledger tags must match acquire's)
            if hit.pages:
                if eng.pool.ledger is not None:
                    eng.pool.tag = ("req", None)
                eng.pool.release(hit.pages)
            if hit.cow_page is not None:
                if eng.pool.ledger is not None:
                    eng.pool.tag = ("cow", None)
                eng.pool.release([hit.cow_page])

    # -- cross-replica pull -------------------------------------------------

    def prefix_inventory(self, tokens, max_blocks: int
                         ) -> Tuple[List[int], List[Tuple[int, ...]]]:
        """PEER-side truth at export time: the HBM page ids of this
        engine's cached chain for ``tokens`` plus the tier keys of the
        contiguous run extending it (first gap stops — a pull lands
        front-to-back). The directory may claim more; this is what the
        peer still actually holds."""
        eng = self.engine
        cache = eng.prefix_cache
        tier = eng.host_tier
        ps = eng.page_size
        toks = [int(t) for t in np.asarray(tokens)][:max_blocks * ps]
        hit = cache.lookup(toks)
        pages = list(hit.pages)
        keys: List[Tuple[int, ...]] = []
        i = len(pages)
        while (i + 1) * ps <= len(toks) and tier is not None \
                and tier.contains(tuple(toks[:(i + 1) * ps])):
            keys.append(tuple(toks[:(i + 1) * ps]))
            i += 1
        return pages, keys

    def maybe_pull(self, req, now) -> str:
        """Pull ``req``'s prefix pages from a peer engine and admit it
        with them, resuming chunked prefill at the pulled length.
        Returns ``"admitted"`` / ``"retry"`` (ledger blocked — keep the
        hint) / ``"no"`` (peer adds nothing, or the pull failed and the
        request re-queued for recompute)."""
        eng = self.engine
        peer = self.pull_hints.get(req.uid) or self.default_peer
        if peer is None or peer is eng:
            self.pull_hints.pop(req.uid, None)
            return "no"
        mgr = getattr(peer, "kv_tier", None)
        cache = eng.prefix_cache
        ps = eng.page_size
        max_blocks = (req.target_len - 1) // ps
        if mgr is None or cache is None or max_blocks <= 0:
            self.pull_hints.pop(req.uid, None)
            return "no"
        toks = [int(t) for t in np.asarray(req.tokens)[:req.target_len]]
        local = cache.restorable_len(toks, eng.host_tier,
                                     max_tokens=req.target_len - 1)
        try:
            xfer = self._peer_transfer(peer)
        except ValueError:
            self.pull_hints.pop(req.uid, None)
            return "no"   # geometry-incompatible peer
        peer_pages, peer_keys = mgr.prefix_inventory(toks, max_blocks)
        n_avail = len(peer_pages) + len(peer_keys)
        pulled_tokens = n_avail * ps
        if pulled_tokens <= local:
            self.pull_hints.pop(req.uid, None)
            return "no"   # local cache + tier already cover as much
        n_bytes = (len(peer_pages) * wire_page_bytes(peer)
                   + sum(peer.host_tier.entry_bytes(k) for k in peer_keys))
        n_ops = -(-len(peer_pages) // xfer.width) + len(peer_keys)
        if not self.planner.should_restore(pulled_tokens - local, n_bytes,
                                           n_ops=n_ops, cross_replica=True):
            self.pull_hints.pop(req.uid, None)
            return "no"
        t0 = now()
        if not eng.sched.begin_transfer(req, t0):
            return "retry"
        self.pull_hints.pop(req.uid, None)
        eng.sched.withdraw(req)
        req.status = Status.TRANSFER
        tr = eng.tracer
        if tr is not None:
            tr.on_transfer_start(req, t0)
        try:
            idx = 0
            while idx < len(peer_pages):     # peer HBM pages, batched
                chunk = peer_pages[idx:idx + xfer.width]
                t_a = now()
                ks, vs, nb = xfer.export(chunk)
                end = (idx + len(chunk)) * ps
                dst = eng.sched.transfer_pages(req, end)
                rec = PageHandoff(
                    req=req, page_index=idx, n_pages=len(chunk),
                    tokens_end=end, k=ks, v=vs, wire_bytes=nb,
                    final=False, first_token=None, t_created=t_a,
                )
                xfer.import_(rec, dst[idx:idx + len(chunk)])
                if tr is not None:
                    t_b = now()
                    tr.on_transfer_chunk(req, t_b, dur_s=t_b - t_a,
                                         tokens=len(chunk) * ps,
                                         pages=len(chunk), nbytes=nb)
                idx += len(chunk)
            for j, key in enumerate(peer_keys):  # peer tier entries
                t_a = now()
                ks, vs, nb = peer.host_tier.get(key)
                blk = len(peer_pages) + j
                end = (blk + 1) * ps
                dst = eng.sched.transfer_pages(req, end)
                rec = PageHandoff(
                    req=req, page_index=blk, n_pages=1, tokens_end=end,
                    k=ks, v=vs, wire_bytes=nb, final=False,
                    first_token=None, t_created=t_a,
                )
                xfer.import_(rec, dst[blk:blk + 1])
                if tr is not None:
                    t_b = now()
                    tr.on_restore_chunk(req, t_b, dur_s=t_b - t_a,
                                        tokens=ps, pages=1, nbytes=nb)
        except (HostTierError, TransferError, KeyError) as exc:
            eng.sched.abort_transfer(req)
            req.clear_residency()
            eng.sched.submit(req, now(), reuse_uid=True)
            if tr is not None:
                # stitched fleet traces surface the degraded pull: the
                # leg recomputed instead of importing the peer's pages
                tr.annotate(req, "tier_fallback", path="cross-replica pull")
            self._fallback_box("cross-replica pull", req,
                               tuple(toks[:ps]), exc)
            return "no"
        if not eng.sched.admit_with_pages(req, None, now(),
                                          prefilled_len=pulled_tokens):
            # no free slot (cannot happen from tick_intercept, which
            # checks first — defensive for direct callers)
            eng.sched.abort_transfer(req)
            req.clear_residency()
            eng.sched.submit(req, now(), reuse_uid=True)
            return "no"
        self.pulls += 1
        self.pulled_tokens += pulled_tokens
        self.restored_tokens += len(peer_keys) * ps
        return "admitted"

    # -- failure fallback ---------------------------------------------------

    def _fallback_box(self, path: str, req, key, exc: Exception) -> None:
        """One black box per degradation, naming the prefix — then the
        trigger is consumed immediately (the recompute that follows
        serves the request, so this is recovered-by-construction and
        must not flip /healthz). A pre-existing pending trigger
        survives (the plane's recovered-consume pattern)."""
        self.fallbacks += 1
        rec = self.engine.recorder
        if rec is None:
            return
        run = self.engine._run
        pending = rec.last_trigger
        chain = tuple(int(t) for t in key)
        trig = rec.fire_trigger(
            "kv_tier_fallback",
            f"{path} failed for uid={req.uid} "
            f"prefix={chain[:8]}{'...' if len(chain) > 8 else ''} "
            f"({len(chain)} tokens): {exc} — degrading to recompute",
            getattr(run, "tick", 0) if run is not None else 0,
            details={
                "path": path,
                "uid": req.uid,
                "prefix_head": list(chain[:16]),
                "prefix_len": len(chain),
                "error": str(exc),
            },
        )
        if rec.last_trigger is trig:
            rec.take_trigger()
            if pending is not None:
                rec.last_trigger = pending
