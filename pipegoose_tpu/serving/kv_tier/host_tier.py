"""Host-DRAM KV tier: byte-budgeted LRU of spilled prefix pages.

The tier sits UNDER the radix prefix cache (serving/prefix_cache.py):
when the cache's LRU eviction reclaims a cold refcount-1 leaf, the
engine's spill hook exports that page's KV through the same jitted
gather the disagg transfer uses (kv_pool.export_page_slab) and parks
the host slab here; a later lookup miss whose prefix the tier still
holds restores the page with one jitted scatter instead of re-running
the prefill that computed it.

Storage is at WIRE precision — the slab format of serving/disagg/
transfer.py IS the storage format:

- an int8 pool's ``{"q", "scale"}`` planes are stored verbatim
  (~``hd/(hd+4)``x denser than fp — the quantized pool's density
  carries straight into host DRAM, and pages are never dequantized in
  the hierarchy, so spill -> restore is byte-identical);
- an fp pool stores its pool dtype by default (exact round-trip), or
  bf16 when the engine opts into ``host_tier_wire="bf16"`` (the
  distributed/compressed.py convention — exact for bf16 pools, lossy
  for fp32 ones, so the token-identity pins run on the default).

Keys are the page's full token chain — ``tuple(tokens[: (i+1) * ps])``
for block ``i`` — exactly the radix-trie path that produced the page,
so a tier entry is valid for ANY request sharing that prefix (KV pages
are deterministic in the token values alone; see kv_pool.quantize_kv).
One entry per page keeps spill/restore page-granular: a chain restores
front-to-back and the first gap stops the walk.

``set_host_tier_fault`` is the failure seam (the ``set_transfer_fault``
convention): a hook raising :class:`HostTierError` fails that spill or
restore, and the engine's contract is to DEGRADE — a failed spill just
loses the tier copy, a failed restore falls back to recompute — never
to stall or lose the request (testing/chaos.py's ``host_tier_io_error``
exercises exactly this).

Host-side by design (jit-safety allowlisted): slabs are numpy, the
LRU is an OrderedDict; the only device programs are the engine's
jitted export/import pair.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Optional, Tuple

from pipegoose_tpu.serving.disagg.transfer import slab_nbytes


class HostTierError(RuntimeError):
    """A host-tier spill or restore failed (allocation failure, copy
    fault, test injection). The engine's contract: degrade — drop the
    spill, or recompute instead of restoring — never stall."""


_fault_hook: Optional[Callable[..., None]] = None


def set_host_tier_fault(hook: Optional[Callable[..., None]]):
    """Install a fault-injection hook ``hook(op, key, n_pages)`` called
    before every spill (``op="spill"``) and restore (``op="restore"``);
    raise :class:`HostTierError` from it to fail that operation.
    Returns the previous hook (restore it — the chaos-harness
    convention shared with ``set_transfer_fault``)."""
    global _fault_hook
    prev = _fault_hook
    _fault_hook = hook
    return prev


class HostTier:
    """Byte-budgeted LRU over host-resident page slabs.

    ``byte_budget`` bounds ``resident_bytes`` (exact ``slab_nbytes``
    census — values + scale planes at their wire dtypes, the int8
    density claim as arithmetic, not a comment); inserting past the
    budget evicts least-recently-used entries first. An entry larger
    than the whole budget is refused rather than thrashing the tier
    empty. ``get`` refreshes recency; ``contains`` does not (admission
    probes and directory audits must not perturb the LRU order).

    Counters follow the registry convention when one is bound
    (``serving.kv_tier.{hit,miss,restore,spill}_total`` +
    ``serving.kv_tier.bytes`` gauge); plain-int ``stats()`` works
    registry-free."""

    def __init__(self, byte_budget: int, *, registry=None):
        if byte_budget < 1:
            raise ValueError(
                f"byte_budget must be positive, got {byte_budget}"
            )
        self.byte_budget = int(byte_budget)
        # key (token-chain tuple) -> (k_slab, v_slab, nbytes)
        self._entries: "OrderedDict[Tuple[int, ...], Tuple[Any, Any, int]]" \
            = OrderedDict()
        self.resident_bytes = 0
        self.hits = 0          # probe found >= 1 restorable block
        self.misses = 0        # probe found none
        self.spills = 0        # pages captured
        self.restores = 0      # pages restored back to HBM
        self.spill_drops = 0   # spills refused (over-budget entry / fault)
        self._m_hit = self._m_miss = None
        self._m_restore = self._m_spill = self._m_bytes = None
        if registry is not None:
            self.bind_registry(registry)

    def bind_registry(self, registry) -> None:
        """Resolve the metric handles once (the engine-init convention)."""
        self._m_hit = registry.counter(
            "serving.kv_tier.hit_total",
            "restore probes that found >= 1 tiered block")
        self._m_miss = registry.counter(
            "serving.kv_tier.miss_total",
            "restore probes that found nothing tiered")
        self._m_restore = registry.counter(
            "serving.kv_tier.restore_total",
            "pages restored from the host tier to HBM")
        self._m_spill = registry.counter(
            "serving.kv_tier.spill_total",
            "pages spilled from HBM eviction into the host tier")
        self._m_bytes = registry.gauge(
            "serving.kv_tier.bytes",
            "host-resident tier bytes at wire precision")
        self._m_bytes.set(self.resident_bytes)

    # -- census ------------------------------------------------------------

    @property
    def resident_pages(self) -> int:
        return len(self._entries)

    def contains(self, key: Tuple[int, ...]) -> bool:
        """Probe without touching LRU order or counters."""
        return key in self._entries

    def entry_bytes(self, key: Tuple[int, ...]) -> int:
        ent = self._entries.get(key)
        return ent[2] if ent is not None else 0

    # -- spill / restore ---------------------------------------------------

    def put(self, key: Tuple[int, ...], k_slab, v_slab) -> bool:
        """Capture one spilled page (host wire slabs). Returns True when
        stored; an entry alone exceeding the budget is refused (stored
        False, counted in ``spill_drops``). Replacing an existing key
        re-censuses exactly."""
        if _fault_hook is not None:
            _fault_hook("spill", key, 1)
        nbytes = slab_nbytes(k_slab) + slab_nbytes(v_slab)
        if nbytes > self.byte_budget:
            self.spill_drops += 1
            return False
        old = self._entries.pop(key, None)
        if old is not None:
            self.resident_bytes -= old[2]
        while self._entries and self.resident_bytes + nbytes > self.byte_budget:
            _, (_, _, dropped) = self._entries.popitem(last=False)
            self.resident_bytes -= dropped
        self._entries[key] = (k_slab, v_slab, nbytes)
        self.resident_bytes += nbytes
        self.spills += 1
        if self._m_spill is not None:
            self._m_spill.inc()
            self._m_bytes.set(self.resident_bytes)
        return True

    def get(self, key: Tuple[int, ...]) -> Tuple[Any, Any, int]:
        """Fetch one page's slabs for restore (refreshes recency; the
        entry STAYS resident — a restored page may be evicted and
        re-spilled later, and until then the tier copy still serves
        peer pulls). Raises KeyError on a vanished entry,
        :class:`HostTierError` from the fault seam."""
        if _fault_hook is not None:
            _fault_hook("restore", key, 1)
        k_slab, v_slab, nbytes = self._entries[key]
        self._entries.move_to_end(key)
        return k_slab, v_slab, nbytes

    # -- probe accounting (engine-driven: one probe per restore attempt) ---

    def note_probe(self, found_blocks: int) -> None:
        if found_blocks > 0:
            self.hits += 1
            if self._m_hit is not None:
                self._m_hit.inc()
        else:
            self.misses += 1
            if self._m_miss is not None:
                self._m_miss.inc()

    def note_restored(self, n_pages: int) -> None:
        self.restores += n_pages
        if self._m_restore is not None:
            self._m_restore.inc(n_pages)

    # -- admin -------------------------------------------------------------

    def clear(self) -> None:
        self._entries.clear()
        self.resident_bytes = 0
        if self._m_bytes is not None:
            self._m_bytes.set(0)

    def stats(self) -> dict:
        return {
            "budget_bytes": self.byte_budget,
            "resident_bytes": self.resident_bytes,
            "resident_pages": self.resident_pages,
            "hits": self.hits,
            "misses": self.misses,
            "spills": self.spills,
            "restores": self.restores,
            "spill_drops": self.spill_drops,
        }
