"""Fleet-wide prefix directory: which replica holds which prefix.

The router's ``ShadowIndex`` (control_plane/router.py) answers "where
would this prefix be WARM?" from placement history alone — it never
knows whether the pages still exist. The directory is the promoted
form: replicas PUBLISH page-aligned prefixes as they materialize them
(prefill completion and tier restores publish ``"hbm"``, host-tier
spills re-publish as ``"host"``), so the control plane can route a
request to a replica that can PULL the prefix pages cross-replica
through the ``PoolTransfer`` export/import path instead of
re-prefilling.

Consistency model — DELIBERATELY weak, and documented as such
(docs/serving.md): publications are advisory hints, never leases.

- **Staleness**: an eviction that does not spill leaves a dangling
  ``"hbm"`` claim; a tier LRU drop leaves a dangling ``"host"`` one.
  Retraction happens only at replica granularity (drain / failure —
  the same moments the router drops its shadow). The PULL is therefore
  fallible by design: the peer re-walks its own cache + tier at
  export time and ships only what it still holds; a shortfall
  restores less (or nothing) and the puller recomputes the rest —
  correctness never depends on the directory being right.
- **Bounded**: like the ShadowIndex, the trie resets wholesale at
  ``max_blocks`` (graceful degradation to "no hints", counted in
  ``resets_total`` — never an error).

Block-granular radix trie over page-aligned token blocks; each node
carries ``{replica: location}`` holders. Host-side orchestration state
only — no device arrays live here.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

LOCATIONS = ("hbm", "host")


class _Node:
    __slots__ = ("children", "holders")

    def __init__(self):
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.holders: Dict[str, str] = {}


class PrefixDirectory:
    """Prefix -> holding replicas, at page granularity."""

    __slots__ = ("page_size", "max_blocks", "_root", "_blocks",
                 "resets_total", "publishes_total")

    def __init__(self, page_size: int, max_blocks: int = 100_000):
        if page_size < 1:
            raise ValueError(f"page_size must be positive, got {page_size}")
        if max_blocks < 1:
            raise ValueError(f"max_blocks must be positive, got {max_blocks}")
        self.page_size = page_size
        self.max_blocks = max_blocks
        self._root = _Node()
        self._blocks = 0
        self.resets_total = 0
        self.publishes_total = 0

    def clear(self) -> None:
        self._root = _Node()
        self._blocks = 0

    def _reset_on_cap(self) -> bool:
        if self._blocks >= self.max_blocks:
            self.clear()
            self.resets_total += 1
            return True
        return False

    def publish(self, replica: str, tokens, location: str) -> int:
        """Record that ``replica`` holds the page-aligned prefix of
        ``tokens`` at ``location`` ("hbm" or "host"). A deeper claim
        refreshes every ancestor block too (holding block i implies
        holding 0..i — that is what a chain is). Returns the number of
        blocks recorded (0 when under one page, or right after a cap
        reset)."""
        if location not in LOCATIONS:
            raise ValueError(
                f"location must be one of {LOCATIONS}, got {location!r}"
            )
        toks = np.asarray(tokens).reshape(-1)
        n_blocks = len(toks) // self.page_size
        if n_blocks == 0:
            return 0
        if self._reset_on_cap():
            return 0
        self.publishes_total += 1
        node = self._root
        for i in range(n_blocks):
            block = tuple(
                int(t) for t in
                toks[i * self.page_size:(i + 1) * self.page_size]
            )
            child = node.children.get(block)
            if child is None:
                child = _Node()
                node.children[block] = child
                self._blocks += 1
            child.holders[replica] = location
            node = child
        return n_blocks

    def retract_replica(self, name: str) -> None:
        """Drop every claim ``name`` holds (drain / failure — mirrors
        ``Router.drop_replica``). Empty nodes stay until the cap reset
        reclaims them (bounded by ``max_blocks`` either way)."""
        stack = [self._root]
        while stack:
            node = stack.pop()
            node.holders.pop(name, None)
            stack.extend(node.children.values())

    def longest_holder(self, tokens, exclude: Optional[str] = None
                       ) -> Tuple[int, Optional[str], Optional[str]]:
        """Deepest page-aligned prefix of ``tokens`` some replica other
        than ``exclude`` claims to hold. Returns ``(match_tokens,
        replica, location)`` — ``(0, None, None)`` on no claim.
        Deterministic tie-break at the deepest node: "hbm" claims beat
        "host" (an HBM export skips the tier fetch), then replica name
        order."""
        toks = np.asarray(tokens).reshape(-1)
        node = self._root
        best: Tuple[int, Optional[str], Optional[str]] = (0, None, None)
        depth = 0
        for i in range(len(toks) // self.page_size):
            block = tuple(
                int(t) for t in
                toks[i * self.page_size:(i + 1) * self.page_size]
            )
            node = node.children.get(block)
            if node is None:
                break
            depth += 1
            cands = sorted(
                ((loc != "hbm", name) for name, loc in node.holders.items()
                 if name != exclude),
            )
            if cands:
                host_pref, name = cands[0]
                best = (depth * self.page_size, name,
                        "host" if host_pref else "hbm")
        return best

    def stats(self) -> dict:
        return {
            "blocks": self._blocks,
            "max_blocks": self.max_blocks,
            "resets_total": self.resets_total,
            "publishes_total": self.publishes_total,
        }
