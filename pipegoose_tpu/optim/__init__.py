from pipegoose_tpu.optim.zero import DistributedOptimizer, ZeroState

__all__ = ["DistributedOptimizer", "ZeroState"]
