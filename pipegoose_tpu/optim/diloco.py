"""DiLoCo: distributed low-communication training (outer/inner loop).

The reference only aspires to DiLoCo (README.md:9-10 cites the paper; no
code — SURVEY.md §2.2). Implemented here because it shapes multi-slice
TPU training: inner workers (pod slices connected over DCN) each run H
local AdamW-style steps with NO cross-worker communication; every H
steps an OUTER optimizer (SGD + Nesterov momentum, per the paper)
updates the shared anchor from the averaged worker delta:

    outer_grad = anchor - mean_w(worker_params)
    anchor     = outer_opt(anchor, outer_grad)
    workers    = anchor                      (re-broadcast)

Workers map onto a mesh axis (default ``data``): worker-divergent params
carry a leading worker dim sharded over that axis, so "no communication
during inner steps" is literal — the compiled inner step contains zero
cross-worker collectives; only the sync step touches the axis (one
pmean riding DCN).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from pipegoose_tpu.distributed.parallel_context import ParallelContext

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map


def outer_optimizer(lr: float = 0.7, momentum: float = 0.9) -> optax.GradientTransformation:
    """The DiLoCo paper's outer optimizer: SGD with Nesterov momentum."""
    return optax.sgd(lr, momentum=momentum, nesterov=True)


class DiLoCo:
    def __init__(
        self,
        loss_fn: Callable[[Any, Any], jax.Array],
        inner_opt: optax.GradientTransformation,
        outer_opt: Optional[optax.GradientTransformation] = None,
        sync_every: int = 8,
        worker_axis: str = "data",
        parallel_context: Optional[ParallelContext] = None,
    ):
        self.loss_fn = loss_fn
        self.inner_opt = inner_opt
        self.outer_opt = outer_opt or outer_optimizer()
        self.sync_every = sync_every
        self.axis = worker_axis
        self.ctx = parallel_context or ParallelContext.get_context()
        self.W = self.ctx.mesh.shape[worker_axis]

    # -- state layout -------------------------------------------------------

    def _wspec(self, base: P = P()) -> P:
        return P(self.axis, *base)

    def init(self, params: Any):
        """(worker_params, inner_states, outer_state): workers start as W
        copies of the anchor (leading worker dim); divergence happens in
        the inner steps."""
        W = self.W
        worker_params = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (W,) + x.shape), params
        )
        inner = jax.vmap(self.inner_opt.init)(worker_params)
        outer = self.outer_opt.init(params)
        return worker_params, inner, outer

    # -- compiled steps -----------------------------------------------------

    def make_inner_step(self, worker_params: Any):
        """jit(step)(worker_params, inner_state, batch) — per-worker local
        update, zero cross-worker collectives."""
        mesh = self.ctx.mesh
        wspecs = jax.tree_util.tree_map(lambda _: self._wspec(), worker_params)
        inner_state_shape = jax.eval_shape(
            lambda wp: jax.vmap(self.inner_opt.init)(wp), worker_params
        )
        sspecs = jax.tree_util.tree_map(lambda _: self._wspec(), inner_state_shape)

        def local(wp, state, batch):
            p = jax.tree_util.tree_map(lambda x: x[0], wp)
            s = jax.tree_util.tree_map(lambda x: x[0], state)
            loss, grads = jax.value_and_grad(self.loss_fn)(p, batch)
            updates, s2 = self.inner_opt.update(grads, s, p)
            p2 = optax.apply_updates(p, updates)
            expand = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
            # global metric: without the pmean the P() out-spec would
            # surface one arbitrary worker's loss
            return expand(p2), expand(s2), lax.pmean(loss, self.axis)

        f = shard_map(
            local,
            mesh=mesh,
            in_specs=(wspecs, sspecs, P(self.axis)),
            out_specs=(wspecs, sspecs, P()),
            check_vma=False,
        )
        return jax.jit(f, donate_argnums=(0, 1))

    def make_sync_step(self, params_template: Any):
        """jit(sync)(anchor, worker_params, outer_state) -> new anchor,
        reset worker params, new outer state. One pmean over the worker
        axis — the only DCN traffic DiLoCo pays."""
        mesh = self.ctx.mesh
        wspecs = jax.tree_util.tree_map(lambda _: self._wspec(), params_template)

        def local(anchor, wp, outer_state):
            p = jax.tree_util.tree_map(lambda x: x[0], wp)
            avg = jax.tree_util.tree_map(lambda x: lax.pmean(x, self.axis), p)
            outer_grad = jax.tree_util.tree_map(lambda a, m: a - m, anchor, avg)
            updates, outer2 = self.outer_opt.update(outer_grad, outer_state, anchor)
            new_anchor = optax.apply_updates(anchor, updates)
            new_wp = jax.tree_util.tree_map(lambda x: x[None], new_anchor)
            return new_anchor, new_wp, outer2

        f = shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), wspecs, P()),
            out_specs=(P(), wspecs, P()),
            check_vma=False,
        )
        return jax.jit(f)
