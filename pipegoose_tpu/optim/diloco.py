"""DiLoCo: distributed low-communication training (outer/inner loop).

The reference only aspires to DiLoCo (README.md:9-10 cites the paper; no
code — SURVEY.md §2.2). Implemented here because it shapes multi-slice
TPU training: inner workers (pod slices connected over DCN) each run H
local AdamW-style steps with NO cross-worker communication; every H
steps an OUTER optimizer (SGD + Nesterov momentum, per the paper)
updates the shared anchor from the averaged worker delta:

    outer_grad = anchor - mean_w(worker_params)
    anchor     = outer_opt(anchor, outer_grad)
    workers    = anchor                      (re-broadcast)

Workers map onto a mesh axis (default ``data``): worker-divergent params
carry a leading worker dim sharded over that axis, so "no communication
during inner steps" is literal — the compiled inner step contains zero
cross-worker collectives; only the sync step touches the axis (one
pmean riding DCN).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from pipegoose_tpu.distributed.compat import shard_map
from pipegoose_tpu.distributed.parallel_context import ParallelContext


def outer_optimizer(lr: float = 0.7, momentum: float = 0.9) -> optax.GradientTransformation:
    """The DiLoCo paper's outer optimizer: SGD with Nesterov momentum."""
    return optax.sgd(lr, momentum=momentum, nesterov=True)


class DiLoCoHybrid:
    """DiLoCo outer loop around the FULL hybrid train step — the
    BASELINE config-5 composition ("Mixtral-8x7B 4D + DiLoCo") the
    reference only aspires to (reference README.md:9-10).

    Workers live on the dedicated OUTERMOST ``diloco`` mesh axis
    (ParallelContext(diloco_parallel_size=W)); inside each worker the
    loss runs with any tp/pp/ep axis names and the inner optimizer is
    the ZeRO-1 ``DistributedOptimizer`` sharding state over ``data`` —
    the two axes coexist because DiLoCo's worker dim is leading on every
    worker array while ZeRO chunks param dim 0 within the worker block.

    Communication contract (verified by tests/optim/test_diloco_4d.py):
    params/grads/optimizer state never cross workers until the sync
    step's pmean every ``sync_every`` steps (the one DCN transfer DiLoCo
    pays). With ``metric_pmean=True`` (default) the inner step ALSO
    pmeans the scalar loss over workers for a global metric — one
    scalar allreduce that still couples worker pacing over DCN; set it
    False on real multi-slice deployments to make inner steps literally
    collective-free over the worker axis (each worker then reports its
    local loss).
    """

    def __init__(
        self,
        loss_fn: Callable[..., jax.Array],
        param_specs: Any,
        inner_opt,  # DistributedOptimizer (ZeRO-1) or any object with .init/.step
        outer_opt: Optional[optax.GradientTransformation] = None,
        sync_every: int = 8,
        worker_axis: str = "diloco",
        parallel_context: Optional[ParallelContext] = None,
        batch_spec: Optional[P] = None,
        loss_axis=("data",),
        grad_sync_axes: tuple = (),
        with_rng: bool = False,
        metric_pmean: bool = True,
    ):
        self.loss_fn = loss_fn
        self.param_specs = param_specs
        self.inner_opt = inner_opt
        self.outer_opt = outer_opt or outer_optimizer()
        self.sync_every = sync_every
        self.axis = worker_axis
        self.ctx = parallel_context or ParallelContext.get_context()
        self.batch_spec = (
            batch_spec if batch_spec is not None else P((worker_axis, "data"))
        )
        self.loss_axis = loss_axis if isinstance(loss_axis, tuple) else (loss_axis,)
        self.grad_sync_axes = grad_sync_axes
        self.with_rng = with_rng
        self.metric_pmean = metric_pmean

    # -- spec plumbing -------------------------------------------------------

    def _prepend_worker(self, spec_tree):
        return jax.tree_util.tree_map(
            lambda s: P(self.axis, *s), spec_tree,
            is_leaf=lambda x: isinstance(x, P),
        )

    def _inner_state_spec(self, params):
        from pipegoose_tpu.parallel.hybrid import zero_state_spec

        return zero_state_spec(
            self.inner_opt, params, self.param_specs, self.ctx.mesh
        )

    def _outer_state_spec(self, params):
        from pipegoose_tpu.optim.zero import plain_state_specs

        shapes = jax.eval_shape(self.outer_opt.init, params)
        return plain_state_specs(shapes, params, self.param_specs)

    # -- lifecycle -----------------------------------------------------------

    def init(self, params):
        """(worker_params, inner_states, outer_state): every worker starts
        at the anchor (= ``params``); pass ``params`` on as the anchor."""
        mesh = self.ctx.mesh
        wspecs = self._prepend_worker(self.param_specs)
        isspec = self._prepend_worker(self._inner_state_spec(params))

        def _init(p):
            wp = jax.tree_util.tree_map(lambda x: x[None], p)
            st = self.inner_opt.init(p)
            return wp, jax.tree_util.tree_map(lambda x: x[None], st)

        f = shard_map(
            _init, mesh=mesh,
            in_specs=(self.param_specs,), out_specs=(wspecs, isspec),
            check_vma=False,
        )
        wp, inner = jax.jit(f)(params)
        outer = jax.jit(
            shard_map(
                self.outer_opt.init, mesh=mesh,
                in_specs=(self.param_specs,),
                out_specs=self._outer_state_spec(params),
                check_vma=False,
            )
        )(params)
        return wp, inner, outer

    # -- compiled steps ------------------------------------------------------

    def make_inner_step(self, params):
        """jit(step)(worker_params, inner_states, batch[, rng]) ->
        (worker_params, inner_states, loss). The full hybrid step per
        worker. ``loss`` is a global scalar with ``metric_pmean=True``,
        or a (W,) per-worker vector with ``metric_pmean=False`` (no
        collective over the worker axis at all)."""
        from pipegoose_tpu.parallel.hybrid import sync_replicated_grads

        mesh = self.ctx.mesh
        wspecs = self._prepend_worker(self.param_specs)
        isspec = self._prepend_worker(self._inner_state_spec(params))

        def _step(wp, st, batch, *rng):
            p = jax.tree_util.tree_map(lambda x: x[0], wp)
            s = jax.tree_util.tree_map(lambda x: x[0], st)
            loss, grads = jax.value_and_grad(self.loss_fn)(p, batch, *rng)
            if self.grad_sync_axes:
                grads = sync_replicated_grads(
                    grads, self.param_specs, self.grad_sync_axes
                )
            new_p, new_s = self.inner_opt.step(grads, s, p)
            for ax in self.loss_axis:
                loss = lax.pmean(loss, ax)
            if self.metric_pmean:
                # global metric — one scalar crossing the worker axis;
                # metric_pmean=False keeps inner steps collective-free
                # over DCN (see class docstring)
                loss = lax.pmean(loss, self.axis)
            else:
                loss = loss[None]  # (1,) local -> (W,) over the axis
            expand = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)  # noqa: E731
            return expand(new_p), expand(new_s), loss

        loss_spec = P() if self.metric_pmean else P(self.axis)
        in_specs = (wspecs, isspec, self.batch_spec) + (
            (P(),) if self.with_rng else ()
        )
        f = shard_map(
            _step, mesh=mesh,
            in_specs=in_specs, out_specs=(wspecs, isspec, loss_spec),
            check_vma=False,
        )
        return jax.jit(f, donate_argnums=(0, 1))

    def make_sync_step(self, params):
        """jit(sync)(anchor, worker_params, outer_state) -> (anchor,
        worker_params, outer_state). One pmean over the worker axis —
        the only DCN traffic DiLoCo pays. Inner optimizer state persists
        across rounds (per the paper)."""
        mesh = self.ctx.mesh
        wspecs = self._prepend_worker(self.param_specs)
        ospec = self._outer_state_spec(params)

        def _sync(anchor, wp, outer_state):
            p = jax.tree_util.tree_map(lambda x: x[0], wp)
            avg = jax.tree_util.tree_map(lambda x: lax.pmean(x, self.axis), p)
            outer_grad = jax.tree_util.tree_map(
                lambda a, m: (a - m).astype(a.dtype), anchor, avg
            )
            updates, outer2 = self.outer_opt.update(
                outer_grad, outer_state, anchor
            )
            new_anchor = optax.apply_updates(anchor, updates)
            new_wp = jax.tree_util.tree_map(lambda x: x[None], new_anchor)
            return new_anchor, new_wp, outer2

        f = shard_map(
            _sync, mesh=mesh,
            in_specs=(self.param_specs, wspecs, ospec),
            out_specs=(self.param_specs, wspecs, ospec),
            check_vma=False,
        )
        return jax.jit(f, donate_argnums=(1,))


class DiLoCo:
    def __init__(
        self,
        loss_fn: Callable[[Any, Any], jax.Array],
        inner_opt: optax.GradientTransformation,
        outer_opt: Optional[optax.GradientTransformation] = None,
        sync_every: int = 8,
        worker_axis: str = "data",
        parallel_context: Optional[ParallelContext] = None,
    ):
        self.loss_fn = loss_fn
        self.inner_opt = inner_opt
        self.outer_opt = outer_opt or outer_optimizer()
        self.sync_every = sync_every
        self.axis = worker_axis
        self.ctx = parallel_context or ParallelContext.get_context()
        self.W = self.ctx.mesh.shape[worker_axis]

    # -- state layout -------------------------------------------------------

    def _wspec(self, base: P = P()) -> P:
        return P(self.axis, *base)

    def init(self, params: Any):
        """(worker_params, inner_states, outer_state): workers start as W
        copies of the anchor (leading worker dim); divergence happens in
        the inner steps."""
        W = self.W
        worker_params = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (W,) + x.shape), params
        )
        inner = jax.vmap(self.inner_opt.init)(worker_params)
        outer = self.outer_opt.init(params)
        return worker_params, inner, outer

    # -- compiled steps -----------------------------------------------------

    def make_inner_step(self, worker_params: Any):
        """jit(step)(worker_params, inner_state, batch) — per-worker local
        update, zero cross-worker collectives."""
        mesh = self.ctx.mesh
        wspecs = jax.tree_util.tree_map(lambda _: self._wspec(), worker_params)
        inner_state_shape = jax.eval_shape(
            lambda wp: jax.vmap(self.inner_opt.init)(wp), worker_params
        )
        sspecs = jax.tree_util.tree_map(lambda _: self._wspec(), inner_state_shape)

        def local(wp, state, batch):
            p = jax.tree_util.tree_map(lambda x: x[0], wp)
            s = jax.tree_util.tree_map(lambda x: x[0], state)
            loss, grads = jax.value_and_grad(self.loss_fn)(p, batch)
            updates, s2 = self.inner_opt.update(grads, s, p)
            p2 = optax.apply_updates(p, updates)
            expand = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
            # global metric: without the pmean the P() out-spec would
            # surface one arbitrary worker's loss
            return expand(p2), expand(s2), lax.pmean(loss, self.axis)

        f = shard_map(
            local,
            mesh=mesh,
            in_specs=(wspecs, sspecs, P(self.axis)),
            out_specs=(wspecs, sspecs, P()),
            check_vma=False,
        )
        return jax.jit(f, donate_argnums=(0, 1))

    def make_sync_step(self, params_template: Any):
        """jit(sync)(anchor, worker_params, outer_state) -> new anchor,
        reset worker params, new outer state. One pmean over the worker
        axis — the only DCN traffic DiLoCo pays."""
        mesh = self.ctx.mesh
        wspecs = jax.tree_util.tree_map(lambda _: self._wspec(), params_template)

        def local(anchor, wp, outer_state):
            p = jax.tree_util.tree_map(lambda x: x[0], wp)
            avg = jax.tree_util.tree_map(lambda x: lax.pmean(x, self.axis), p)
            outer_grad = jax.tree_util.tree_map(lambda a, m: a - m, anchor, avg)
            updates, outer2 = self.outer_opt.update(outer_grad, outer_state, anchor)
            new_anchor = optax.apply_updates(anchor, updates)
            new_wp = jax.tree_util.tree_map(lambda x: x[None], new_anchor)
            return new_anchor, new_wp, outer2

        f = shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), wspecs, P()),
            out_specs=(P(), wspecs, P()),
            check_vma=False,
        )
        return jax.jit(f)
