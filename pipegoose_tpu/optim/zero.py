"""ZeRO-1: optimizer state sharded over the data axis.

TPU-native analog of the reference's ``DistributedOptimizer``
(pipegoose/optim/zero/optim.py:14-75) + ``OptimizerStateSharding``
(sharding.py:24-46). The reference greedily bin-packs whole params onto
DP ranks and, lacking a working reduce_scatter (functional.py:155-156),
broadcasts each rank's updated shard in a Python loop. Here every param
leaf is evenly chunked on its leading dim (padded to divisibility), and
one step is:

    grad shard   = reduce_scatter(local grads) / dp      (fused avg+shard)
    state/update = inner optax transform on the shard only
    new params   = all_gather(updated shards)

— the textbook ZeRO-1 dataflow, compiled into the train step. Works with
any ``optax.GradientTransformation``.

Run inside ``shard_map`` over a mesh with the given axis. With
``axis_name=None`` it degrades to a plain (unsharded) optax step, which
is the world-size-1 short-circuit of the reference.
"""
from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

from pipegoose_tpu.distributed.functional import all_gather, reduce_scatter


def _pad_to(x: jax.Array, mult: int) -> jax.Array:
    """Pad dim 0 to a multiple of ``mult`` (scalars are reshaped to (1,)
    first so every leaf has a leading dim to chunk)."""
    if x.ndim == 0:
        x = x[None]
    rem = (-x.shape[0]) % mult
    if rem:
        x = jnp.pad(x, ((0, rem),) + ((0, 0),) * (x.ndim - 1))
    return x


def _local_shard(x: jax.Array, axis_name: str) -> jax.Array:
    n = lax.axis_size(axis_name)
    xp = _pad_to(x, n)
    chunk = xp.shape[0] // n
    return lax.dynamic_slice_in_dim(xp, lax.axis_index(axis_name) * chunk, chunk, 0)


def _unshard(shard: jax.Array, orig_shape, axis_name: str) -> jax.Array:
    full = all_gather(shard, axis_name, dim=0)
    if len(orig_shape) == 0:
        return full[0]
    return full[: orig_shape[0]]


class ZeroState(NamedTuple):
    inner: Any      # inner optax state over param SHARDS
    # error-feedback residuals for the compressed gradient reduction
    # (distributed/compressed.py): one fp32 leaf of shape
    # (1, *padded_local_grad_shape) per param — the leading length-1 dim
    # carries the data-axis PartitionSpec (each rank's residual is its
    # OWN, the global array stacks them). None unless the optimizer was
    # built with grad_comm != "fp32" and error_feedback=True.
    ef: Any = None


class DistributedOptimizer:
    """ZeRO-1 wrapper over an optax transform (reference optim.py:14-75
    wraps a torch optimizer class the same way).

    ``grad_comm``: wire precision of the gradient reduce-scatter —
    ``"fp32"`` (default, the plain ``psum_scatter``), ``"bf16"``, or
    ``"int8"`` (EQuARX-style per-chunk-scaled quantization,
    distributed/compressed.py). ``error_feedback=True`` carries the
    local quantization residual across steps in ``ZeroState.ef`` and
    adds it back before the next quantize.
    """

    def __init__(
        self,
        inner: optax.GradientTransformation,
        axis_name: Optional[str] = "data",
        grad_comm: str = "fp32",
        error_feedback: bool = False,
    ):
        from pipegoose_tpu.distributed.compressed import check_grad_comm

        self.inner = inner
        self.axis_name = axis_name
        self.grad_comm = check_grad_comm(grad_comm)
        if error_feedback and self.grad_comm == "fp32":
            raise ValueError("error_feedback requires grad_comm bf16/int8")
        if error_feedback and axis_name is None:
            # the residual lives in ZeroState.ef, which only exists on
            # the sharded path — silently running compressed comm
            # WITHOUT the requested feedback would be worse than failing
            raise ValueError(
                "error_feedback requires a ZeRO axis_name (the plain-DP "
                "grad_comm path is stateless)"
            )
        self.error_feedback = bool(error_feedback)

    def replace(self, **kw) -> "DistributedOptimizer":
        """Copy with fields overridden (make_hybrid_train_step threads
        its ``grad_comm=`` through here without mutating the caller's
        optimizer)."""
        cfg = dict(
            inner=self.inner, axis_name=self.axis_name,
            grad_comm=self.grad_comm, error_feedback=self.error_feedback,
        )
        cfg.update(kw)
        return DistributedOptimizer(**cfg)

    # -- lifecycle ---------------------------------------------------------

    def _ef_zero(self, p: jax.Array, n: int) -> jax.Array:
        shape = tuple(p.shape) if p.ndim else (1,)
        d0 = -(-shape[0] // n) * n
        return jnp.zeros((1, d0) + shape[1:], jnp.float32)

    def init(self, params: Any) -> ZeroState:
        """Optimizer state exists only for this rank's shard — the memory
        saving that defines ZeRO-1 (reference sharding.py:24-46 achieves
        it by param-group bin-packing; even chunking balances exactly)."""
        if self.axis_name is None:
            return ZeroState(self.inner.init(params))
        shards = jax.tree_util.tree_map(
            partial(_local_shard, axis_name=self.axis_name), params
        )
        ef = None
        if self.error_feedback:
            n = lax.axis_size(self.axis_name)
            ef = jax.tree_util.tree_map(lambda p: self._ef_zero(p, n), params)
        return ZeroState(self.inner.init(shards), ef)

    def step(self, grads: Any, state: ZeroState, params: Any):
        """One ZeRO-1 step. ``grads`` are this device's LOCAL (unreduced)
        grads from its batch shard; the reduce_scatter both averages over
        the data axis and hands each rank its shard in one collective
        (the upgrade SURVEY.md §2.2 calls out over the reference's
        broadcast loop, optim.py:57-66) — at ``grad_comm`` wire
        precision when compressed."""
        ax = self.axis_name
        if ax is None:
            updates, inner = self.inner.update(grads, state.inner, params)
            return optax.apply_updates(params, updates), ZeroState(inner)

        n = lax.axis_size(ax)
        ef = getattr(state, "ef", None)
        if self.grad_comm == "fp32" and ef is None:
            g_shards = jax.tree_util.tree_map(
                lambda g: reduce_scatter(_pad_to(g, n), ax, dim=0) / n, grads
            )
            new_ef = None
        else:
            from pipegoose_tpu.distributed.compressed import (
                compressed_reduce_scatter_mean,
            )

            def shard_one(g, e):
                out, new_e = compressed_reduce_scatter_mean(
                    _pad_to(g, n), ax, self.grad_comm,
                    residual=None if e is None else e[0],
                )
                # keep the inner transform's grad dtype identical to the
                # fp32 wire path (state dtypes must not drift per step)
                return out.astype(g.dtype), (
                    None if new_e is None else new_e[None]
                )

            # flatten explicitly: shard_one returns 2-tuples, and a
            # tree_map + is_leaf=tuple would misfire on grads pytrees
            # that themselves contain tuples/NamedTuples
            g_leaves, treedef = jax.tree_util.tree_flatten(grads)
            e_leaves = (
                jax.tree_util.tree_leaves(ef)
                if ef is not None else [None] * len(g_leaves)
            )
            outs = [shard_one(g, e) for g, e in zip(g_leaves, e_leaves)]
            g_shards = jax.tree_util.tree_unflatten(
                treedef, [o[0] for o in outs]
            )
            new_ef = (
                jax.tree_util.tree_unflatten(
                    treedef, [o[1] for o in outs]
                )
                if ef is not None
                else None
            )
        p_shards = jax.tree_util.tree_map(partial(_local_shard, axis_name=ax), params)
        updates, inner = self.inner.update(g_shards, state.inner, p_shards)
        new_p_shards = optax.apply_updates(p_shards, updates)
        new_params = jax.tree_util.tree_map(
            lambda s, p: _unshard(s, p.shape, ax).astype(p.dtype), new_p_shards, params
        )
        return new_params, ZeroState(inner, new_ef)

    # reference API parity: state_dict passthrough (optim.py:48-55).
    # With error feedback the residuals are part of the training state
    # (dropping them would both lose the accumulated error AND hand the
    # jitted step a pytree that no longer matches its in_specs) — they
    # ride along under an explicit envelope; plain states keep the
    # legacy bare-inner form so old checkpoints restore unchanged.
    def state_dict(self, state: ZeroState) -> Any:
        ef = getattr(state, "ef", None)
        if ef is None:
            return state.inner
        return {"inner": state.inner, "ef": ef}

    def load_state_dict(self, inner_state: Any) -> ZeroState:
        if isinstance(inner_state, dict) and set(inner_state) == {"inner", "ef"}:
            return ZeroState(inner_state["inner"], inner_state["ef"])
        return ZeroState(inner_state)


# --------------------------------------------------------------------------
# PartitionSpec derivation for the sharded state
# --------------------------------------------------------------------------

def zero_param_spec(param_spec, param_ndim: int, axis_name: str = "data"):
    """Spec of a ZeRO shard leaf's GLOBAL layout: the data axis subdivides
    dim 0 *inside* any existing dim-0 sharding (all_gather over data is the
    innermost/contiguous factor). Scalars become shape-(1,) shards."""
    from jax.sharding import PartitionSpec as P

    if param_ndim == 0:
        return P(axis_name)
    dim0 = param_spec[0] if len(param_spec) > 0 else None
    if dim0 is None:
        new0 = axis_name
    elif isinstance(dim0, (tuple, list)):
        new0 = (*dim0, axis_name)
    else:
        new0 = (dim0, axis_name)
    rest = tuple(param_spec[1:]) if len(param_spec) > 1 else ()
    rest = rest + (None,) * (param_ndim - 1 - len(rest))
    return P(new0, *rest)


def ef_param_spec(param_spec, param_ndim: int, axis_name: str = "data"):
    """Spec of an error-feedback residual leaf: local shape is
    ``(1, *padded_local_grad_shape)`` and every data rank holds its OWN
    residual, so the leading dim is sharded over the data axis and the
    remaining dims follow the param's spec (the padding never changes a
    dim's sharding)."""
    from jax.sharding import PartitionSpec as P

    if param_ndim == 0:
        return P(axis_name, None)
    rest = tuple(param_spec[:param_ndim])
    rest = rest + (None,) * (param_ndim - len(rest))
    for entry in rest:
        entries = entry if isinstance(entry, (tuple, list)) else (entry,)
        if axis_name in entries:
            raise ValueError(
                f"error feedback needs params unsharded over the "
                f"{axis_name!r} axis, got spec {param_spec}"
            )
    return P(axis_name, *rest)


def ef_state_specs(params, param_specs, axis_name: str = "data"):
    """PartitionSpec pytree for ``ZeroState.ef`` (None-free params
    tree -> per-leaf ``ef_param_spec``)."""
    from jax.sharding import PartitionSpec as P

    spec_leaves = jax.tree_util.tree_leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P)
    )
    leaves, treedef = jax.tree_util.tree_flatten(params)
    mapped = [
        ef_param_spec(s, getattr(p, "ndim", 0), axis_name)
        for s, p in zip(spec_leaves, leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, mapped)


def state_specs(state_tree, params, param_specs, axis_name: str = "data",
                leaf_spec_fn=None):
    """PartitionSpec pytree for a ZeroState (or a shape-struct of one).

    optax states are nested (Named)tuples whose momentum-like members are
    whole pytrees with the SAME treedef as params (e.g. adam's mu/nu);
    those get per-param ZeRO specs, every other leaf (counts, scalars)
    replicates. Use with ``init_shapes``/``jax.eval_shape``.

    ``leaf_spec_fn(param_spec, param_ndim) -> spec`` overrides the
    per-param mapping (default: ZeRO dim-0 sharding over ``axis_name``).
    """
    from jax.sharding import PartitionSpec as P

    fn = leaf_spec_fn or (lambda s, nd: zero_param_spec(s, nd, axis_name))
    params_def = jax.tree_util.tree_structure(params)
    spec_leaves = jax.tree_util.tree_leaves(param_specs, is_leaf=lambda x: isinstance(x, P))
    ndim_leaves = [getattr(p, "ndim", 0) for p in jax.tree_util.tree_leaves(params)]

    def is_params_like(node):
        try:
            return jax.tree_util.tree_structure(node) == params_def
        except Exception:
            return False

    def rec(node):
        if node is None:  # empty subtree (e.g. ZeroState.ef off)
            return None
        if is_params_like(node):
            leaves, treedef = jax.tree_util.tree_flatten(node)
            mapped = [fn(s, nd) for s, nd in zip(spec_leaves, ndim_leaves)]
            return jax.tree_util.tree_unflatten(treedef, mapped)
        if isinstance(node, (tuple, list)) and not hasattr(node, "shape"):
            mapped = [rec(c) for c in node]
            if hasattr(node, "_fields"):  # NamedTuple
                return type(node)(*mapped)
            return type(node)(mapped)
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        # plain leaf (count scalars etc.): replicated
        return P()

    return rec(state_tree)


def plain_state_specs(state_tree, params, param_specs):
    """Specs for an UNSHARDED optax state: momentum-like members follow
    the param specs directly, scalars replicate (e.g. the DiLoCo outer
    optimizer's Nesterov momentum on the anchor)."""
    return state_specs(
        state_tree, params, param_specs, leaf_spec_fn=lambda s, nd: s
    )


def shard_shapes(params, dp_size: int):
    """ShapeDtypeStruct pytree of per-rank ZeRO shards (for eval_shape)."""

    def f(p):
        shape = p.shape if p.ndim > 0 else (1,)
        d0 = -(-shape[0] // dp_size)
        return jax.ShapeDtypeStruct((d0,) + tuple(shape[1:]), p.dtype)

    return jax.tree_util.tree_map(f, params)
