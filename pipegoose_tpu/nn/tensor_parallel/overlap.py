"""Ring collective-matmul: TP collectives hidden behind partial matmuls.

"On Optimizing the Communication of Model Parallelism"
(arxiv 2211.05322) observes that the collectives of Megatron-style
tensor parallelism need not run as monolithic ops serialized against
the matmuls they feed: an all-gather followed by a matmul can be
decomposed into ``tp`` partial matmuls interleaved with ``tp - 1``
``ppermute`` ring steps (and symmetrically a matmul followed by a
reduce becomes a ring matmul-reduce-scatter), so the per-hop transfer
overlaps the next partial matmul and the collective's latency hides
behind compute the program had to do anyway.

This module is that decomposition for the repo's TP layers
(nn/tensor_parallel/layers.py), under ``shard_map`` over a named mesh
axis, with hand-written VJPs so the BACKWARD pass rings too:

- :func:`ring_all_gather_matmul` — ``concat_c(x_c) @ w`` where rank r
  holds sequence chunk ``x_r``: the ColumnParallel input all-gather,
  decomposed. Its backward is a ring matmul-reduce-scatter for ``dx``
  plus a second ring accumulating ``dw``.
- :func:`ring_matmul_reduce_scatter` — ``sum_r(x^{(r)} @ w^{(r)})``
  scattered so rank r keeps sequence chunk r: the RowParallel output
  reduce, decomposed (all-reduce = reduce-scatter + all-gather; the
  reduce-scatter half — the half that must wait on the matmul — is
  what rings here). Its backward is one ring of ``dy`` chunks feeding
  both ``dx`` and ``dw`` partial matmuls.

The layer entry points :func:`column_parallel_linear_overlap` /
:func:`row_parallel_linear_overlap` compose to the Megatron
sequence-parallel dataflow: activations between layers live SHARDED on
the token dim over the tensor axis (1/tp the activation memory of the
replicated-stream path), the column layer gathers tokens while it
projects, the row layer reduces while it projects. Numerics match the
monolithic path to fp32 allclose (the only difference is fp32
summation order in the reduce); gradients are exact per rank — no
extra grad sync over the tensor axis is needed (replicated params used
on token shards are routed through :func:`replicated_for_overlap`'s
f-operator so their cotangents psum inside the backward).

Everything here requires a STATIC axis size (``lax.axis_size`` under
``shard_map``); the ring loops are Python-unrolled so XLA sees
``tp - 1`` independent collective-permutes it can schedule
asynchronously against the partial matmuls.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from pipegoose_tpu.distributed.functional import copy_to_tensor_group


def _ring_perm(n: int):
    """Send rank i -> i+1: after k hops rank r holds rank (r-k)'s value."""
    return [(i, (i + 1) % n) for i in range(n)]


def _chunk_dot(x, w):
    """Partial matmul in fp32 accumulation (the layers' convention)."""
    return jnp.dot(x, w, preferred_element_type=jnp.float32)


def ring_all_gather_matmul(x_local: jax.Array, w: jax.Array, axis_name: str):
    """``concat_over_ranks(x) @ w`` with the gather decomposed.

    ``x_local``: (..., m, K) — this rank's token chunk (chunk id = rank).
    Returns (..., n*m, N) fp32 — identical on every rank up to fp32
    rounding, chunk rows ordered by global chunk id. ``n - 1`` ppermute
    steps, each overlapping the next chunk's matmul.
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return _chunk_dot(x_local, w)
    r = lax.axis_index(axis_name)
    m = x_local.shape[-2]
    out = jnp.zeros(
        x_local.shape[:-2] + (n * m, w.shape[-1]), jnp.float32
    )
    perm = _ring_perm(n)
    cur = x_local
    for step in range(n):
        c = (r - step) % n  # chunk id currently held
        y_c = _chunk_dot(cur, w)
        out = lax.dynamic_update_slice_in_dim(out, y_c, c * m, axis=-2)
        if step < n - 1:
            cur = lax.ppermute(cur, axis_name, perm=perm)
    return out


def ring_matmul_reduce_scatter(x_full: jax.Array, w: jax.Array, axis_name: str):
    """``sum_over_ranks(x @ w)``, rank r keeping token chunk r.

    ``x_full``: (..., n*m, K) — full token dim, feature-sharded ``w``.
    Returns (..., m, N) fp32 — this rank's chunk of the summed output.
    The accumulator for chunk c starts at rank c+1 and rides the ring
    for ``n - 1`` hops, each hop's transfer overlapping the next
    chunk's partial matmul.
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return _chunk_dot(x_full, w)
    r = lax.axis_index(axis_name)
    m = x_full.shape[-2] // n
    perm = _ring_perm(n)
    acc = None
    for step in range(n):
        c = (r - 1 - step) % n  # chunk this rank contributes to now
        x_c = lax.dynamic_slice_in_dim(x_full, c * m, m, axis=-2)
        part = _chunk_dot(x_c, w)
        acc = part if acc is None else lax.ppermute(acc, axis_name, perm=perm) + part
    return acc  # after n steps: chunk (r - n) % n == r, fully summed


def _ring_accumulate_dw(x_local, dy_full, axis_name: str):
    """``dw = sum_c x_c^T @ dy[chunk c]`` with the x chunks ringed —
    the column backward's weight cotangent, comm overlapped exactly
    like the forward gather."""
    n = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name) if n > 1 else 0
    m = x_local.shape[-2]
    perm = _ring_perm(n)
    cur = x_local
    dw = jnp.zeros((x_local.shape[-1], dy_full.shape[-1]), jnp.float32)
    for step in range(n):
        c = (r - step) % n
        dy_c = lax.dynamic_slice_in_dim(dy_full, c * m, m, axis=-2)
        # sum all leading (batch) dims into the (K, N) cotangent
        dw = dw + jnp.einsum(
            "...mk,...mn->kn", cur, dy_c, preferred_element_type=jnp.float32
        )
        if step < n - 1:
            cur = lax.ppermute(cur, axis_name, perm=perm)
    return dw


# --------------------------------------------------------------------------
# Column parallel: gather tokens while projecting
# --------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _column_overlap(kernel, x_local, axis_name):
    return _column_overlap_fwd(kernel, x_local, axis_name)[0]


def _column_overlap_fwd(kernel, x_local, axis_name):
    y = ring_all_gather_matmul(x_local, kernel, axis_name)
    return y, (kernel, x_local)


def _column_overlap_bwd(axis_name, res, dy):
    kernel, x_local = res
    # dx_r = sum_q dy^{(q)}[chunk r] @ W_q^T — exactly a ring
    # matmul-reduce-scatter of the dy chunks over the OUT-sharded
    # kernels (one schedule, defined once above)
    dx = ring_matmul_reduce_scatter(dy, kernel.T, axis_name)
    dx = dx.astype(x_local.dtype)
    dw = _ring_accumulate_dw(x_local, dy, axis_name).astype(kernel.dtype)
    return dw, dx


_column_overlap.defvjp(_column_overlap_fwd, _column_overlap_bwd)


def column_parallel_linear_overlap(
    params: dict, x_local: jax.Array, axis_name: Optional[str]
) -> jax.Array:
    """ColumnParallel with the input token gather decomposed into the
    ring. ``x_local``: (..., m, K) token chunk; returns (..., n*m, O/n)
    full-token, OUT-sharded — exactly what the monolithic
    ``column_parallel_linear`` produces from the gathered input, to
    fp32 allclose. ``axis_name=None`` degrades to the plain matmul."""
    if not axis_name:
        y = _chunk_dot(x_local, params["kernel"]).astype(x_local.dtype)
    else:
        y = _column_overlap(params["kernel"], x_local, axis_name)
        y = y.astype(x_local.dtype)
    if "bias" in params and params["bias"] is not None:
        y = y + params["bias"]
    return y


# --------------------------------------------------------------------------
# Row parallel: reduce tokens while projecting
# --------------------------------------------------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _row_overlap(kernel, x_full, axis_name):
    return _row_overlap_fwd(kernel, x_full, axis_name)[0]


def _row_overlap_fwd(kernel, x_full, axis_name):
    y = ring_matmul_reduce_scatter(x_full, kernel, axis_name)
    return y, (kernel, x_full)


def _row_overlap_bwd(axis_name, res, dy_own):
    kernel, x_full = res
    n = lax.axis_size(axis_name)
    if n == 1:
        dx = jnp.einsum(
            "...mn,kn->...mk", dy_own, kernel, preferred_element_type=jnp.float32
        ).astype(x_full.dtype)
        dw = jnp.einsum(
            "...mk,...mn->kn", x_full, dy_own, preferred_element_type=jnp.float32
        ).astype(kernel.dtype)
        return dw, dx
    r = lax.axis_index(axis_name)
    m = dy_own.shape[-2]
    perm = _ring_perm(n)
    # ONE ring of the dy chunks feeds both cotangents: dx rows for chunk
    # c are dy_c @ W^T, dw accumulates x_c^T @ dy_c
    dx = jnp.zeros(x_full.shape, jnp.float32)
    dw = jnp.zeros(kernel.shape, jnp.float32)
    cur = dy_own
    for step in range(n):
        c = (r - step) % n  # dy chunk currently held
        dx_c = jnp.einsum(
            "...mn,kn->...mk", cur, kernel, preferred_element_type=jnp.float32
        )
        dx = lax.dynamic_update_slice_in_dim(dx, dx_c, c * m, axis=-2)
        x_c = lax.dynamic_slice_in_dim(x_full, c * m, m, axis=-2)
        dw = dw + jnp.einsum(
            "...mk,...mn->kn", x_c, cur, preferred_element_type=jnp.float32
        )
        if step < n - 1:
            cur = lax.ppermute(cur, axis_name, perm=perm)
    return dw.astype(kernel.dtype), dx.astype(x_full.dtype)


_row_overlap.defvjp(_row_overlap_fwd, _row_overlap_bwd)


def row_parallel_linear_overlap(
    params: dict, x_full: jax.Array, axis_name: Optional[str]
) -> jax.Array:
    """RowParallel with the output reduce decomposed into the ring.
    ``x_full``: (..., n*m, I/n) full-token, IN-sharded; returns
    (..., m, O) — this rank's token chunk of the fully reduced output
    (the reduce-scatter half of the monolithic all-reduce; the
    all-gather half belongs to whichever later op needs full tokens
    again). The replicated bias is added on the local chunk through the
    f-operator so its cotangent psums to the full-token sum."""
    if not axis_name:
        y = _chunk_dot(x_full, params["kernel"]).astype(x_full.dtype)
    else:
        y = _row_overlap(params["kernel"], x_full, axis_name)
        y = y.astype(x_full.dtype)
    if "bias" in params and params["bias"] is not None:
        bias = params["bias"]
        if axis_name:
            bias = copy_to_tensor_group(bias, axis_name)
        y = y + bias
    return y


# --------------------------------------------------------------------------
# Replicated-param use on token shards
# --------------------------------------------------------------------------

def replicated_for_overlap(params, axis_name: Optional[str]):
    """Route a replicated param (sub)tree through the f-operator before
    using it on a TOKEN SHARD of the sequence: forward identity,
    backward psums the cotangent over the tensor axis — so e.g. a
    LayerNorm applied to 1/tp of the tokens still produces the exact
    full-sequence parameter gradient on every rank, and the hybrid
    step's grad contract is unchanged between the overlap and
    monolithic paths."""
    if not axis_name:
        return params
    return jax.tree_util.tree_map(
        lambda p: copy_to_tensor_group(p, axis_name), params
    )
