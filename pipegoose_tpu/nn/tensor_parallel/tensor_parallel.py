"""TensorParallel: shard a params pytree along the tensor axis.

TPU-native analog of the reference's ``TensorParallel`` wrapper
(pipegoose/nn/tensor_parallel/tensor_parallel.py:18-82) and its
``ModuleParallelizer`` subclasses (parallelizer.py:61-229). The reference
walks leaf modules and re-classes them in place; here ``parallelize``
maps the params pytree through the policy table to PartitionSpecs and
device_puts the arrays. Vocab padding (EmbeddingParallelizer,
parallelizer.py:125-141) becomes an explicit ``pad_vocab`` helper.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pipegoose_tpu.distributed.parallel_context import ParallelContext
from pipegoose_tpu.nn.parallel import Parallel, shard_tree, spec_tree
from pipegoose_tpu.nn.parallel_mapping import ParallelMapping


class TensorParallel(Parallel):
    def __init__(
        self,
        mapping: ParallelMapping,
        parallel_context: Optional[ParallelContext] = None,
    ):
        super().__init__(parallel_context)
        self.mapping = mapping

    def specs(self, params: Any) -> Any:
        """PartitionSpec pytree for ``params`` (first policy match wins;
        unmatched params replicate — the reference simply skipped modules
        with no parallelizer, tensor_parallel.py:71-75).

        Bias handling mirrors the reference's slicing rules
        (parallelizer.py:105-112) via the rank-aware
        ``ParallelMapping.spec_for``."""
        return spec_tree(params, lambda path, x: self.mapping.spec_for(path, x.ndim))

    def parallelize(self, params: Any):
        specs = self.specs(params)
        return shard_tree(params, specs, self.parallel_context), specs


def pad_vocab(weight: jax.Array, multiple: int) -> jax.Array:
    """Pad embedding rows so vocab divides the tensor axis (reference
    EmbeddingParallelizer._resize_vocab_size, parallelizer.py:125-141).

    Padded rows are zeros, so with a tied LM head every padded slot gets
    logit exactly 0 — pass the true vocab size as ``valid_size`` to
    ``vocab_parallel_cross_entropy`` (or apply ``mask_padded_vocab`` before
    decoding) so padded slots can't shift the loss or win a greedy step."""
    vocab = weight.shape[0]
    rem = (-vocab) % multiple
    if rem == 0:
        return weight
    return jnp.pad(weight, ((0, rem),) + ((0, 0),) * (weight.ndim - 1))
