from pipegoose_tpu.nn.tensor_parallel.layers import (
    column_parallel_linear,
    layer_norm,
    row_parallel_linear,
    vocab_parallel_cross_entropy,
    vocab_parallel_embedding,
)
from pipegoose_tpu.nn.tensor_parallel.overlap import (
    column_parallel_linear_overlap,
    replicated_for_overlap,
    ring_all_gather_matmul,
    ring_matmul_reduce_scatter,
    row_parallel_linear_overlap,
)
from pipegoose_tpu.nn.tensor_parallel.tensor_parallel import TensorParallel, pad_vocab

__all__ = [
    "column_parallel_linear",
    "row_parallel_linear",
    "column_parallel_linear_overlap",
    "row_parallel_linear_overlap",
    "ring_all_gather_matmul",
    "ring_matmul_reduce_scatter",
    "replicated_for_overlap",
    "layer_norm",
    "vocab_parallel_embedding",
    "vocab_parallel_cross_entropy",
    "TensorParallel",
    "pad_vocab",
]
