"""Megatron-style 1D tensor-parallel layers as pure functions.

TPU-native analog of the reference's module surgery
(pipegoose/nn/tensor_parallel/linear.py:17-82, embedding.py:11-42,
layer_norm.py:8-25). Instead of re-classing ``nn.Linear`` in place, a
layer here is a pure function over a params dict, designed to run inside
``shard_map`` with the weight already sharded along the ``tensor`` mesh
axis. Passing ``axis_name=None`` gives the single-device path (the
reference's world-size-1 short-circuit).

Shape conventions (JAX style): kernels are (in_features, out_features) —
transposed from torch. Column parallelism shards the OUT dim, row
parallelism the IN dim, exactly mirroring the reference's dim-0/dim-1
weight slicing (parallelizer.py:105-112).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from pipegoose_tpu.distributed.functional import (
    all_reduce,
    copy_to_tensor_group,
    gather_from_tensor_group,
    reduce_from_tensor_group,
    scatter_to_tensor_group,
)


def _kernel_matmul(params: dict, x: jax.Array) -> jax.Array:
    """The local matmul both parallel linears share, dispatching on the
    leaf layout: ``{"kernel": fp}`` runs the plain dot; a quantized
    leaf ``{"q", "scale"}`` (quant/weights.py) runs the dequant-fused
    matmul so the fp kernel never materializes in HBM. Bias and the
    surrounding collectives are identical either way, which is what
    lets ``quantize_params`` drop into every serving forward —
    prefill, paged decode, and generate() references alike — without
    touching a call site."""
    if "q" in params:
        from pipegoose_tpu.quant.matmul import quantized_matmul

        y = quantized_matmul(x, params["q"], params["scale"])
    else:
        y = jnp.dot(x, params["kernel"], preferred_element_type=jnp.float32)
    return y.astype(x.dtype)


def column_parallel_linear(
    params: dict,
    x: jax.Array,
    axis_name: Optional[str],
    gather_output: bool = False,
    overlap: bool = False,
) -> jax.Array:
    """Y = X @ W[:, shard] (+ b[shard]).

    Reference ColumnParallelLinear.forward (linear.py:40-50): broadcast
    input (f-operator) -> local matmul -> optional all-gather of the
    output's last dim.

    ``overlap=True``: ``x`` is this rank's TOKEN CHUNK of the sequence
    (dim -2 sharded over ``axis_name``) and the gather back to full
    tokens is decomposed into ring ppermute steps interleaved with
    partial matmuls (nn/tensor_parallel/overlap.py) — comm hides behind
    compute, forward and backward. Output is full-token, OUT-sharded,
    numerically equal (fp32 allclose) to the monolithic path on the
    gathered input.
    """
    if overlap:
        if gather_output:
            raise ValueError(
                "column_parallel_linear(overlap=True) keeps the output "
                "OUT-sharded; gather_output is not supported"
            )
        if "q" in params:
            raise ValueError(
                "overlap=True is a training-path option; quantized "
                "(serving) kernels use the monolithic dequant matmul"
            )
        from pipegoose_tpu.nn.tensor_parallel.overlap import (
            column_parallel_linear_overlap,
        )

        return column_parallel_linear_overlap(params, x, axis_name)
    x = copy_to_tensor_group(x, axis_name) if axis_name else x
    y = _kernel_matmul(params, x)
    if "bias" in params and params["bias"] is not None:
        y = y + params["bias"]
    if gather_output and axis_name:
        y = gather_from_tensor_group(y, axis_name, dim=-1)
    return y


def row_parallel_linear(
    params: dict,
    x: jax.Array,
    axis_name: Optional[str],
    input_is_parallel: bool = True,
    overlap: bool = False,
) -> jax.Array:
    """Y = psum_over_shards(X[shard] @ W[shard, :]) + b.

    Reference RowParallelLinear.forward (linear.py:74-82): scatter input
    last dim -> local matmul -> all-reduce (g-operator) -> add full bias.

    ``overlap=True``: the output reduce is decomposed into a ring
    matmul-reduce-scatter (nn/tensor_parallel/overlap.py) — each rank
    returns its TOKEN CHUNK (dim -2) of the fully reduced output, the
    reduce's transfers hidden behind the partial matmuls, forward and
    backward. Equal (fp32 allclose) to the monolithic psum path's rows
    for this chunk.
    """
    if overlap:
        if not input_is_parallel:
            raise ValueError(
                "row_parallel_linear(overlap=True) requires the input "
                "already feature-sharded (input_is_parallel=True)"
            )
        if "q" in params:
            raise ValueError(
                "overlap=True is a training-path option; quantized "
                "(serving) kernels use the monolithic dequant matmul"
            )
        from pipegoose_tpu.nn.tensor_parallel.overlap import (
            row_parallel_linear_overlap,
        )

        return row_parallel_linear_overlap(params, x, axis_name)
    if axis_name and not input_is_parallel:
        x = scatter_to_tensor_group(x, axis_name, dim=-1)
    y = _kernel_matmul(params, x)
    if axis_name:
        y = reduce_from_tensor_group(y, axis_name)
    if "bias" in params and params["bias"] is not None:
        y = y + params["bias"]
    return y


def vocab_parallel_embedding(
    params: dict,
    ids: jax.Array,
    axis_name: Optional[str],
) -> jax.Array:
    """Vocab-sharded embedding lookup.

    Reference ParallelEmbedding.forward (embedding.py:26-42): mask ids
    outside this shard's [start, end) range, look up locally, zero the
    masked rows, all-reduce to combine. Shard range math mirrors
    VocabUtility (_utils.py:4-14).
    """
    weight = params["weight"]
    if not axis_name:
        return jnp.take(weight, ids, axis=0)
    per_shard = weight.shape[0]
    rank = jax.lax.axis_index(axis_name)
    start = rank * per_shard
    in_range = (ids >= start) & (ids < start + per_shard)
    local_ids = jnp.where(in_range, ids - start, 0)
    out = jnp.take(weight, local_ids, axis=0)
    out = jnp.where(in_range[..., None], out, 0)
    # reduce_from (identity backward): with the loss replicated across the
    # tensor axis, a plain psum would transpose to psum and scale weight
    # grads by the TP degree — same hazard the CE below avoids.
    return reduce_from_tensor_group(out, axis_name)


def layer_norm(params: dict, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    """Replicated LayerNorm (reference layer_norm.py:8-25). Stats in f32
    regardless of activation dtype — MXU-friendly bf16 activations keep
    full-precision normalization."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = xf.var(axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * params["scale"] + params["bias"]
    return y.astype(dtype)


def vocab_parallel_cross_entropy(
    logits: jax.Array,
    targets: jax.Array,
    axis_name: Optional[str],
    valid_size: Optional[int] = None,
) -> jax.Array:
    """Cross-entropy over vocab-sharded logits, per token.

    Reference VocabParallelCrossEntropy (loss.py:14-89): all-reduce(MAX)
    normalization, masked predicted-logit all-reduce(SUM), log-sum-exp
    all-reduce(SUM). Like the reference (and Megatron-LM, credited at
    loss.py:71-73) the backward is analytic — softmax minus one-hot on
    the local shard — via ``custom_vjp``. This both avoids any backward
    collective and sidesteps psum's psum-transpose, which would scale
    grads by the TP degree when the (replicated) loss is differentiated
    on every rank.

    Returns per-token losses; callers take the mean (the reference's
    module wrapper divides by len(targets), loss.py:92-103).

    ``valid_size``: when the vocab was padded for divisibility
    (``pad_vocab``), the true vocab size — padded slots are excluded from
    the log-sum-exp so the loss matches the unpadded model.
    """
    if valid_size is not None:
        logits = mask_padded_vocab(logits, axis_name, valid_size)
    if not axis_name:
        logits = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        pred = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        return lse - pred
    return _vp_ce(logits, targets, axis_name)


def chunked_ce_sums(
    hidden: jax.Array,   # (B, T, H) — already shifted to align with labels
    labels: jax.Array,   # (B, T)
    weights: jax.Array,  # (B, T) float mask
    logits_fn,           # (B, C, H) -> (B, C, V/tp) local-shard logits
    axis_name: Optional[str],
    valid_size: Optional[int],
    n_chunks: int,
):
    """(weighted loss sum, weight sum) without ever materializing the
    (B, T, V) logits: scan over T/n_chunks sequence chunks, computing
    each chunk's logits + CE inside ``jax.checkpoint`` so backward
    rematerializes them chunk by chunk. Bounds the logits working set to
    1/n_chunks — at bloom-560m bench shapes the full fp32 buffer is
    ~8 GB (b8 x s1024 x v250880), the single largest HBM consumer of
    the train step (docs/perf_tpu_v5e.md).

    The reference computes full logits then its VocabParallelCrossEntropy
    (loss.py:14-89); chunking composes with the same vocab-parallel CE,
    so the TP semantics (incl. padded-vocab masking) are unchanged."""
    b, t, h = hidden.shape
    if t % n_chunks:
        pad = n_chunks - t % n_chunks
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)))
        weights = jnp.pad(weights, ((0, 0), (0, pad)))  # pad weight 0
        t += pad
    c = t // n_chunks
    hs = hidden.reshape(b, n_chunks, c, h).transpose(1, 0, 2, 3)
    ls = labels.reshape(b, n_chunks, c).transpose(1, 0, 2)
    ws = weights.reshape(b, n_chunks, c).transpose(1, 0, 2)

    def chunk(carry, xs):
        tot, cnt = carry
        h_c, l_c, w_c = xs
        logits = logits_fn(h_c)
        per_tok = vocab_parallel_cross_entropy(
            logits, l_c, axis_name, valid_size=valid_size
        )
        w_c = w_c.astype(per_tok.dtype)
        return (tot + (per_tok * w_c).sum(), cnt + w_c.sum()), None

    zero = jnp.zeros((), jnp.float32)
    (tot, cnt), _ = jax.lax.scan(
        jax.checkpoint(chunk), (zero, zero), (hs, ls, ws)
    )
    return tot, cnt


def mask_padded_vocab(
    logits: jax.Array, axis_name: Optional[str], valid_size: int
) -> jax.Array:
    """Set logits of vocab slots >= valid_size to a large negative, so
    padded slots (zero rows from ``pad_vocab``) can never win a softmax
    or shift the log-sum-exp."""
    shard_v = logits.shape[-1]
    start = jax.lax.axis_index(axis_name) * shard_v if axis_name else 0
    slot = start + jnp.arange(shard_v)
    return jnp.where(slot < valid_size, logits, -1e9)


from functools import partial  # noqa: E402
import numpy as np  # noqa: E402


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _vp_ce(logits, targets, axis_name):
    return _vp_ce_fwd(logits, targets, axis_name)[0]


def _vp_ce_fwd(logits, targets, axis_name):
    in_dtype = logits.dtype
    logits = logits.astype(jnp.float32)
    shard_v = logits.shape[-1]
    start = jax.lax.axis_index(axis_name) * shard_v

    # numeric stabilization: global max over the sharded vocab dim
    global_max = all_reduce(logits.max(axis=-1), axis_name, op="max")
    shifted = logits - global_max[..., None]

    # log-sum-exp across shards
    exp = jnp.exp(shifted)
    sumexp = all_reduce(exp.sum(axis=-1), axis_name)
    lse = jnp.log(sumexp)

    # predicted logit: only the owning shard contributes
    in_range = (targets >= start) & (targets < start + shard_v)
    local_t = jnp.where(in_range, targets - start, 0)
    pred_local = jnp.take_along_axis(shifted, local_t[..., None], axis=-1)[..., 0]
    pred = all_reduce(jnp.where(in_range, pred_local, 0.0), axis_name)

    softmax_local = exp / sumexp[..., None]
    # dtype carried as a 0-size array (residuals must be JAX types)
    dtype_token = jnp.zeros((0,), dtype=in_dtype)
    return lse - pred, (softmax_local, in_range, local_t, dtype_token)


def _vp_ce_bwd(axis_name, res, g):
    softmax_local, in_range, local_t, dtype_token = res
    shard_v = softmax_local.shape[-1]
    onehot = jax.nn.one_hot(local_t, shard_v, dtype=softmax_local.dtype)
    onehot = onehot * in_range[..., None]
    grad = g[..., None] * (softmax_local - onehot)
    # integer targets carry no tangent
    t_zero = np.zeros(local_t.shape, dtype=jax.dtypes.float0)
    return grad.astype(dtype_token.dtype), t_zero


_vp_ce.defvjp(_vp_ce_fwd, _vp_ce_bwd)
