"""Parallel base: pytree sharding utilities.

TPU-native analog of the reference's ``Parallel`` base class
(pipegoose/nn/parallel.py:19-93). The reference monkey-patches ``.to()``
onto the torch module and moves shards to the rank's GPU; here
"parallelize" means: compute a ``PartitionSpec`` pytree for the params
and ``jax.device_put`` the arrays onto the mesh — XLA then keeps every
downstream computation sharded. Nothing is mutated and no device
placement is implicit.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import tree_flatten_with_path, tree_map_with_path

from pipegoose_tpu.distributed.parallel_context import ParallelContext


def path_str(path) -> str:
    """'/'-joined readable param path for a tree_util key path."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def spec_tree(params: Any, spec_fn: Callable[[str, jax.Array], P]) -> Any:
    """Map every leaf to a PartitionSpec via its path."""
    return tree_map_with_path(lambda p, x: spec_fn(path_str(p), x), params)


def shard_tree(params: Any, specs: Any, ctx: Optional[ParallelContext] = None) -> Any:
    """Place a (host or replicated) params pytree onto the mesh according
    to ``specs``. The sharded result is what the reference achieved by
    slicing weights per rank (parallelizer.py:105-112) — here XLA slices."""
    ctx = ctx or ParallelContext.get_context()
    mesh = ctx.mesh
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def unshard_tree(params: Any, ctx: Optional[ParallelContext] = None) -> Any:
    """Gather every leaf back to a fully-replicated array — the analog of
    the reference's ``deparallelize`` (unimplemented there)."""
    ctx = ctx or ParallelContext.get_context()
    rep = NamedSharding(ctx.mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, rep), params)


class Parallel:
    """Base for the parallelization wrappers (TensorParallel,
    DataParallel, ...). Subclasses return (sharded_params, specs)."""

    def __init__(self, parallel_context: Optional[ParallelContext] = None):
        self.parallel_context = parallel_context or ParallelContext.get_context()
        if self.parallel_context is None:
            raise ValueError("no ParallelContext; construct one first")

    def parallelize(self, params: Any):
        raise NotImplementedError

    def deparallelize(self, params: Any):
        return unshard_tree(params, self.parallel_context)
