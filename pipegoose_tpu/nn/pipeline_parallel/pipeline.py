"""GPipe as one compiled SPMD program.

This replaces the reference's entire pipeline runtime — PipelineEngine
(pipeline_engine.py:36-157), the Job system (_job/, 742 LoC), the
daemon-thread worker pool (_worker.py), RPC package transport (_comm.py)
and the RPC clock-consensus handshake (sync/, 290 LoC) — with a single
``lax.scan`` over clock cycles inside ``shard_map`` over the ``pipe``
mesh axis:

- stage-to-stage transfer is ``lax.ppermute`` over ICI (no TensorPipe,
  no dtype/shape preambles: shapes are static in the compiled program);
- clock consensus is unnecessary: the schedule is data-independent, so
  every device advances in lockstep by construction;
- the backward pass is reverse-mode AD of the scan — ppermute transposes
  to the reverse permutation and the scan replays in reverse, which IS
  the reference's reversed-forward backward schedule (scheduler.py:82-94)
  with none of its machinery;
- the GPipe bubble (P-1 idle clocks) manifests as masked compute on
  garbage inputs rather than idle threads — same cost, zero control flow.

Stage assignment falls out of the stacked-params layout: block params
(n_layer leading dim) are sharded over ``pipe``, so "partitioning" is a
PartitionSpec, not torch.fx graph surgery (vs partitioner.py:29-219).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from pipegoose_tpu.distributed.functional import (
    reduce_from_tensor_group,
    shift_left,
    shift_right,
)
from pipegoose_tpu.nn.pipeline_parallel.scheduler import GPipeScheduler


def _tree_index(tree: Any, i) -> Any:
    return jax.tree_util.tree_map(lambda a: lax.dynamic_index_in_dim(a, i, 0, keepdims=False), tree)


def _tree_update(tree: Any, vals: Any, i, write_mask) -> Any:
    """tree[i] = where(write_mask, vals, tree[i]) with dynamic i."""

    def f(buf, v):
        cur = lax.dynamic_index_in_dim(buf, i, 0, keepdims=False)
        new = jnp.where(write_mask, v, cur)
        return lax.dynamic_update_index_in_dim(buf, new, i, 0)

    return jax.tree_util.tree_map(f, tree, vals)


def gpipe(
    stage_fn: Callable[..., Any],
    stage_params: Any,
    inputs: Any,
    side_inputs: Optional[Any] = None,
    axis_name: str = "pipe",
    remat: bool = True,
    with_aux: bool = False,
) -> Any:
    """Run ``inputs`` (a pytree with leading microbatch dim M, the
    pipeline-entry activations, replicated over the pipe axis but only
    read on stage 0) through P pipeline stages.

    ``stage_fn(stage_params, h[, side]) -> h`` must preserve the
    activation structure/shape (each stage applies its local slice of the
    layer stack). ``side_inputs`` (optional, M-leading, replicated over
    pipe) are per-microbatch values every stage needs — attention masks,
    position biases. Each stage indexes them by ITS OWN current
    microbatch (m = clock - stage) instead of shipping them around the
    ring — for seq-length masks this avoids O(S^2) ppermute traffic.

    With ``with_aux=True``, ``stage_fn`` returns ``(h, aux)`` where
    ``aux`` is a pytree of per-stage values (e.g. MoE router losses);
    aux is summed over this stage's VALID microbatches only (bubble
    clocks contribute zero) and returned per rank — combine over the
    pipe axis with an identity-backward psum.

    Returns the last stage's outputs, shape like ``inputs``, valid on
    the last pipe rank (garbage elsewhere — combine with
    ``last_stage_value`` or mask downstream); with aux, returns
    ``(outputs, aux_sums)``.

    Clock-cycle semantics match GPipeScheduler: task (m, p) runs at
    clock m + p; n_clock = M + P - 1 (reference scheduler.py:66-80).
    """
    P = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = jax.tree_util.tree_leaves(inputs)[0].shape[0]
    n_clock = GPipeScheduler(M, P).total_forward_clocks

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    template = _tree_index(inputs, 0)
    out_buf = jax.tree_util.tree_map(lambda a: jnp.zeros_like(a), inputs)
    is_first = stage == 0
    is_last = stage == P - 1

    if with_aux:
        args = (stage_params, template) + (
            (_tree_index(side_inputs, 0),) if side_inputs is not None else ()
        )
        _, aux_shape = jax.eval_shape(stage_fn, *args)
        aux_acc0 = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), aux_shape
        )
    else:
        aux_acc0 = ()

    def clock_step(carry, c):
        recv, out_buf, aux_acc = carry
        # stage 0 consumes microbatch c (clamped; garbage past M never
        # reaches a valid output slot within n_clock clocks)
        m_in = jnp.clip(c, 0, M - 1)
        x0 = _tree_index(inputs, m_in)
        h_in = jax.tree_util.tree_map(
            lambda a, b: jnp.where(is_first, a, b), x0, recv
        )
        if side_inputs is not None:
            m_mine = jnp.clip(c - stage, 0, M - 1)  # this stage's microbatch
            side = _tree_index(side_inputs, m_mine)
            res = fn(stage_params, h_in, side)
        else:
            res = fn(stage_params, h_in)
        if with_aux:
            h_out, aux = res
            # this stage computes microbatch c - stage; clocks outside
            # [0, M) are bubble garbage and must not pollute the sums
            valid = (c >= stage) & (c - stage <= M - 1)
            aux_acc = jax.tree_util.tree_map(
                lambda acc, a: acc + jnp.where(valid, a, jnp.zeros_like(a)),
                aux_acc, aux,
            )
        else:
            h_out = res
        # last stage completed microbatch m = c - (P - 1)
        m_out = jnp.clip(c - (P - 1), 0, M - 1)
        write = is_last & (c >= P - 1)
        out_buf = _tree_update(out_buf, h_out, m_out, write)
        # hand to the next stage (ring; last->first carries garbage)
        sent = jax.tree_util.tree_map(lambda a: shift_right(a, axis_name), h_out)
        return (sent, out_buf, aux_acc), None

    (_, out_buf, aux_acc), _ = lax.scan(
        clock_step, (template, out_buf, aux_acc0), jnp.arange(n_clock)
    )
    return (out_buf, aux_acc) if with_aux else out_buf


def one_f_one_b(
    stage_fn: Callable[..., Any],
    stage_params: Any,
    head_fn: Callable[..., jax.Array],
    head_params: Any,
    inputs: Any,
    side_inputs: Any,
    axis_name: str = "pipe",
    with_aux: bool = False,
):
    """1F1B (PipeDream-flush) pipeline as ONE compiled SPMD program with a
    MANUAL interleaved backward.

    GPipe + reverse-mode AD (``gpipe``) keeps every in-flight microbatch's
    stage input alive until the backward scan replays — O(M) live
    activations per stage. Here the backward of microbatch m starts as
    soon as its forward returns from the last stage, so saved stage
    inputs live in a ring of ``n_slots <= P`` slots — the 1F1B memory
    guarantee (live activations bounded by the stage count, not the
    microbatch count).

    Mechanics:
    - the per-stage instruction streams (``OneFOneBScheduler.timeline``)
      are list-scheduled into static (n_clock, P) fwd/bwd timetables
      (``one_f_one_b_tables``); one ``lax.scan`` runs the global clock;
    - each clock, every stage executes exactly ONE of {forward,
      backward, idle} via ``lax.switch`` on its timetable entry
      (device-varying predicate — uniform across non-pipe axes, so
      tensor-parallel collectives inside ``stage_fn`` stay collective-
      safe: all tensor peers of a stage take the same branch);
    - forward saves ONLY the stage input (ring slot ``m % n_slots``);
      backward re-runs the stage forward inside ``jax.vjp``
      (rematerialization) and accumulates parameter gradients;
    - the LAST stage seeds its own backward: ``head_fn(head_params, h,
      side) -> scalar loss contribution`` (already normalized by the
      caller) is differentiated together with the stage, so the loss
      gradient flows without a separate backward engine;
    - stage-to-stage transfers are the same ``ppermute`` rings as gpipe:
      activations down, cotangents up, one clock of latency each, with
      in-transit values parked in ``n_slots`` rings (the timetable
      builder PROVES slot-collision freedom).

    Contract: ``stage_fn(stage_params, h, side) -> h`` exactly as in
    ``gpipe``; ``side_inputs`` is required (M-leading pytree; carry the
    head's labels/mask in it). Returns ``(loss_sum, d_inputs,
    d_stage_params, d_head_params)`` where loss_sum/d_head_params are
    valid on the LAST pipe rank (zeros elsewhere), d_inputs (M-leading)
    on the FIRST — combine replicated-param grads with a psum over the
    pipe axis (grad_sync_axes=("pipe", "sum")).

    ``with_aux=True``: ``stage_fn`` returns ``(h, aux_scalar)`` where
    ``aux_scalar`` is this stage's PRE-WEIGHTED, PRE-NORMALIZED scalar
    loss contribution for the microbatch (e.g. MoE router aux/z terms
    already multiplied by their coefficients and divided by L*M). Each
    stage's backward seeds a unit cotangent on its own aux scalar — its
    router gradients flow during ITS backward, no cross-stage traffic —
    and the aux values accumulate into loss_sum on EVERY rank, so the
    caller combines loss with a plain psum over the pipe axis.

    This runtime is callable from a non-differentiable context only (it
    RETURNS gradients); wrap it in ``jax.custom_vjp`` for use under
    ``jax.grad`` (see ``models.bloom.loss_fn_1f1b``).
    """
    from pipegoose_tpu.nn.pipeline_parallel.scheduler import one_f_one_b_tables

    P = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    M = jax.tree_util.tree_leaves(inputs)[0].shape[0]
    fwd_np, bwd_np, n_slots, n_clock = one_f_one_b_tables(M, P)
    fwd_tab = jnp.asarray(fwd_np)  # (n_clock, P)
    bwd_tab = jnp.asarray(bwd_np)

    tree_zeros = partial(jax.tree_util.tree_map, jnp.zeros_like)

    def tree_add(a, b):
        return jax.tree_util.tree_map(jnp.add, a, b)

    def ring_like(t):
        return jax.tree_util.tree_map(
            lambda a: jnp.zeros((n_slots,) + a.shape, a.dtype), t
        )

    template = _tree_index(inputs, 0)
    is_first = stage == 0
    is_last = stage == P - 1

    def lookup(tab, c, s):
        ok = (c >= 0) & (c <= n_clock - 1) & (s >= 0) & (s <= P - 1)
        val = tab[jnp.clip(c, 0, n_clock - 1), jnp.clip(s, 0, P - 1)]
        return jnp.where(ok, val, -1)

    def cycle(carry, c):
        (send_h, send_g, recv_h, recv_g, acts, dh0, pgrads, hgrads, loss) = carry

        # 1) receive what the neighbors sent at clock c-1; the sender's
        # timetable entry tells us which microbatch it is
        h_arr = jax.tree_util.tree_map(lambda a: shift_right(a, axis_name), send_h)
        g_arr = jax.tree_util.tree_map(lambda a: shift_left(a, axis_name), send_g)
        m_h = lookup(fwd_tab, c - 1, stage - 1)
        recv_h = _tree_update(
            recv_h, h_arr, jnp.clip(m_h, 0, M - 1) % n_slots, (m_h >= 0) & ~is_first
        )
        m_g = lookup(bwd_tab, c - 1, stage + 1)
        recv_g = _tree_update(
            recv_g, g_arr, jnp.clip(m_g, 0, M - 1) % n_slots, (m_g >= 0) & ~is_last
        )

        f_m = lookup(fwd_tab, c, stage)
        b_m = lookup(bwd_tab, c, stage)
        branch = jnp.where(f_m >= 0, 0, jnp.where(b_m >= 0, 1, 2))

        def f_branch(op):
            (send_h, send_g, recv_h, recv_g, acts, dh0, pgrads, hgrads, loss) = op
            m = jnp.clip(f_m, 0, M - 1)
            slot = m % n_slots
            x0 = _tree_index(inputs, m)
            h_in = jax.tree_util.tree_map(
                lambda a, b: jnp.where(is_first, a, b),
                x0, _tree_index(recv_h, slot),
            )
            acts = _tree_update(acts, h_in, slot, True)
            out = stage_fn(stage_params, h_in, _tree_index(side_inputs, m))
            h_out = out[0] if with_aux else out
            return (h_out, send_g, recv_h, recv_g, acts, dh0, pgrads, hgrads, loss)

        def b_branch(op):
            (send_h, send_g, recv_h, recv_g, acts, dh0, pgrads, hgrads, loss) = op
            m = jnp.clip(b_m, 0, M - 1)
            slot = m % n_slots
            h_in = _tree_index(acts, slot)
            side = _tree_index(side_inputs, m)
            g_in = _tree_index(recv_g, slot)

            def last_fn(_):
                def full(p, hp, h):
                    if with_aux:
                        h_out, aux = stage_fn(p, h, side)
                        return head_fn(hp, h_out, side) + aux
                    return head_fn(hp, stage_fn(p, h, side), side)

                loss_m, vjp = jax.vjp(full, stage_params, head_params, h_in)
                dp, dhp, dh = vjp(jnp.ones_like(loss_m))
                return loss_m.astype(jnp.float32), dp, dhp, dh

            def mid_fn(_):
                if with_aux:
                    (_, aux), vjp = jax.vjp(
                        lambda p, h: stage_fn(p, h, side), stage_params, h_in
                    )
                    # unit cotangent on this stage's own aux scalar
                    dp, dh = vjp((g_in, jnp.ones_like(aux)))
                    return aux.astype(jnp.float32), dp, tree_zeros(head_params), dh
                _, vjp = jax.vjp(
                    lambda p, h: stage_fn(p, h, side), stage_params, h_in
                )
                dp, dh = vjp(g_in)
                return jnp.zeros((), jnp.float32), dp, tree_zeros(head_params), dh

            loss_m, dp, dhp, dh = lax.cond(is_last, last_fn, mid_fn, None)
            pgrads = tree_add(pgrads, dp)
            hgrads = tree_add(hgrads, dhp)
            dh0 = _tree_update(dh0, dh, m, is_first)
            return (send_h, dh, recv_h, recv_g, acts, dh0, pgrads, hgrads, loss + loss_m)

        def idle(op):
            return op

        carry = lax.switch(
            branch, [f_branch, b_branch, idle],
            (send_h, send_g, recv_h, recv_g, acts, dh0, pgrads, hgrads, loss),
        )
        return carry, None

    carry0 = (
        tree_zeros(template),  # send_h
        tree_zeros(template),  # send_g
        ring_like(template),   # recv_h
        ring_like(template),   # recv_g
        ring_like(template),   # acts
        tree_zeros(inputs),    # dh0
        tree_zeros(stage_params),
        tree_zeros(head_params),
        jnp.zeros((), jnp.float32),
    )
    carry, _ = lax.scan(cycle, carry0, jnp.arange(n_clock))
    (_, _, _, _, _, dh0, pgrads, hgrads, loss) = carry
    return loss, dh0, pgrads, hgrads


def manual_grads_loss(run: Callable[[Any], tuple], params: Any) -> jax.Array:
    """Make a manual-backward pipeline differentiable: ``run(params) ->
    (loss, grads)`` computes gradients itself (the 1F1B fused
    forward+backward); this wraps it in a ``custom_vjp`` whose forward
    stashes the gradients as residuals and whose backward just scales
    them by the cotangent — so ``jax.value_and_grad(loss_fn)`` works
    unchanged. Shared by the bloom and mixtral ``loss_fn_1f1b``."""

    @jax.custom_vjp
    def pipelined(params):
        return run(params)[0]

    def fwd(params):
        return run(params)

    def bwd(grads, ct):
        return (jax.tree_util.tree_map(lambda g: (g * ct).astype(g.dtype), grads),)

    pipelined.defvjp(fwd, bwd)
    return pipelined(params)


def last_stage_value(x: jax.Array, axis_name: str = "pipe") -> jax.Array:
    """Combine a value computed validly on the LAST pipe rank (zeros/garbage
    elsewhere) into a replicated value, with identity backward so each
    rank's gradient contribution stays local (the psum-transpose hazard —
    see vocab_parallel_cross_entropy)."""
    P = lax.axis_size(axis_name)
    masked = jnp.where(lax.axis_index(axis_name) == P - 1, x, jnp.zeros_like(x))
    return reduce_from_tensor_group(masked, axis_name)


def pipe_stage_specs(n_layer_spec_tree: Any, axis_name: str = "pipe") -> Any:
    """Shift a stacked-blocks spec tree to shard the leading n_layer dim
    over the pipe axis (stage assignment as a PartitionSpec)."""
    from jax.sharding import PartitionSpec as P

    def f(spec):
        dim0 = spec[0] if len(spec) else None
        if dim0 is None:
            new0 = axis_name
        elif isinstance(dim0, (tuple, list)):
            new0 = (axis_name, *dim0)
        else:
            new0 = (axis_name, dim0)
        return P(new0, *spec[1:])

    return jax.tree_util.tree_map(
        f, n_layer_spec_tree, is_leaf=lambda x: isinstance(x, P)
    )
