"""Pipeline stage partitioning.

TPU-native analog of the reference's ``UniformPartitioner``
(pipegoose/nn/pipeline_parallel/partitioner.py:29-219), which
symbolically traces the HF model with torch.fx, counts params per graph
node, and rebuilds per-shard GraphModules. With stacked-layer params
(models/bloom.py) no graph surgery is needed: a partition is a
contiguous LAYER RANGE, and for the common equal-layers case simply a
PartitionSpec over the ``pipe`` axis (pipeline.py:pipe_stage_specs).

This module covers the general, non-uniform case: given per-layer costs
(param counts — the reference's metric, partitioner.py:73-99 — or FLOPs
from the profiler), compute the contiguous assignment minimizing the
bottleneck stage cost (exact interval-partition DP, not the reference's
greedy running-total heuristic, partitioner.py:101-144).

How UNEVEN stages run under SPMD (repartition_blocks + masked_stage_scan):
one compiled program requires identically-shaped param shards per pipe
rank, so stage p's ``n_p`` layers are padded to ``L_max = max_p n_p``
slots — but the pad slots are NOT computed-and-masked: ``lax.cond`` on
the runtime predicate ``slot < counts[stage]`` genuinely skips the block
at run time (the same device-varying-branch mechanism the 1F1B runtime
uses for its fwd/bwd/idle ``lax.switch``). Per-clock wall time on a
stage is therefore proportional to its OWN layer cost, and the DP split
minimizes the bottleneck stage — the balancing win is real, at the price
of ``P * L_max - L`` zero-weight pad slots in HBM. For transformer
stacks with identical per-layer cost the equal split IS the DP optimum
and the plain evenly-sharded path stays the default.
"""
from __future__ import annotations

from typing import Any, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def layer_param_counts(stacked_params: Any) -> np.ndarray:
    """Per-layer parameter counts from a stacked-blocks pytree (leading
    dim = n_layer on every leaf) — the reference's per-node param
    counting (partitioner.py:73-99) without tracing."""
    leaves = jax.tree_util.tree_leaves(stacked_params)
    n_layer = leaves[0].shape[0]
    per_layer = sum(int(np.prod(x.shape[1:])) for x in leaves)
    return np.full(n_layer, per_layer, dtype=np.int64)


def partition_costs(costs: Sequence[float], n_partitions: int) -> List[range]:
    """Contiguous ranges minimizing the max per-partition cost (exact DP).

    The reference assigns shards greedily when the running total passes
    total/n (partitioner.py:101-144), which can overload the last stage;
    the DP is optimal for the same contiguity constraint.
    """
    costs = list(costs)
    L, P = len(costs), n_partitions
    if P < 1 or P > L:
        raise ValueError(f"need 1 <= n_partitions <= n_layers, got {P} of {L}")
    prefix = np.concatenate([[0.0], np.cumsum(costs)])

    # dp[p][i] = minimal bottleneck for first i layers in p partitions
    dp = np.full((P + 1, L + 1), np.inf)
    cut = np.zeros((P + 1, L + 1), dtype=int)
    dp[0][0] = 0.0
    for p in range(1, P + 1):
        for i in range(p, L + 1):
            for j in range(p - 1, i):
                cand = max(dp[p - 1][j], prefix[i] - prefix[j])
                if cand < dp[p][i]:
                    dp[p][i] = cand
                    cut[p][i] = j
    bounds = [L]
    for p in range(P, 0, -1):
        bounds.append(cut[p][bounds[-1]])
    bounds.reverse()
    return [range(bounds[i], bounds[i + 1]) for i in range(P)]


def repartition_blocks(blocks: Any, ranges: Sequence[range]):
    """Stacked ``(L, ...)`` block params -> padded ``(P * L_max, ...)``
    layout for UNEVEN pipeline stages: stage p's local slice (after
    pipe-sharding the leading dim) holds its ``len(ranges[p])`` layers in
    slots ``[0, n_p)``; pad slots are zeros and are SKIPPED at runtime by
    :func:`masked_stage_scan`. Returns ``(padded_blocks, counts)`` where
    ``counts[p]`` is stage p's live-layer count (pass it as the
    ``stage_layer_counts`` of the model's pipeline loss).

    The layer ORDER is preserved across stages (ranges must be the
    contiguous, sorted output of :func:`partition_costs`)."""
    P = len(ranges)
    lens = [len(r) for r in ranges]
    L_max = max(lens)
    counts = np.asarray(lens, dtype=np.int32)

    def f(x):
        x = np.asarray(x)
        out = np.zeros((P, L_max) + x.shape[1:], dtype=x.dtype)
        for p, r in enumerate(ranges):
            out[p, : len(r)] = x[list(r)]
        return jnp.asarray(out.reshape((P * L_max,) + x.shape[1:]))

    return jax.tree_util.tree_map(f, blocks), counts


def stage_n_valid(stage_layer_counts, n_layer: int, axis_name: str = "pipe"):
    """Validate ``stage_layer_counts`` against the pipe axis and return
    THIS stage's live-layer count (traced scalar). Validation matters:
    jnp's clamped gather would turn a wrong-length tuple into silently
    wrong layer counts on the trailing stages."""
    P = lax.axis_size(axis_name)
    counts = np.asarray(stage_layer_counts, np.int64)
    if len(counts) != P or counts.sum() != n_layer:
        raise ValueError(
            f"stage_layer_counts {tuple(int(c) for c in counts)} must have "
            f"{P} entries (pipe axis size) summing to n_layer={n_layer}"
        )
    return jnp.asarray(counts, jnp.int32)[lax.axis_index(axis_name)]


def masked_stage_scan(block_fn, blocks_local: Any, h: Any, n_valid: jax.Array):
    """Scan this stage's ``L_max`` padded layer slots, applying
    ``block_fn(blk, h) -> h`` only to the first ``n_valid`` — the
    ``lax.cond`` predicate is a runtime value (``counts[axis_index]``),
    so pad slots genuinely skip the block's FLOPs instead of computing
    and masking them."""
    L_max = jax.tree_util.tree_leaves(blocks_local)[0].shape[0]

    def scan_fn(carry, xs):
        blk, i = xs
        out = lax.cond(
            i < n_valid, lambda hh: block_fn(blk, hh), lambda hh: hh, carry
        )
        return out, None

    h, _ = lax.scan(scan_fn, h, (blocks_local, jnp.arange(L_max)))
    return h


class UniformPartitioner:
    """API-parity wrapper (reference partitioner.py:29-57): split a model
    of ``n_layer`` layers into ``n_partitions`` contiguous stages by cost."""

    def __init__(self, n_partitions: int):
        self.n_partitions = n_partitions

    def split(self, costs: Sequence[float]) -> List[range]:
        return partition_costs(costs, self.n_partitions)

    def split_even(self, n_layer: int) -> List[range]:
        if n_layer % self.n_partitions != 0:
            return self.split([1.0] * n_layer)
        k = n_layer // self.n_partitions
        return [range(i * k, (i + 1) * k) for i in range(self.n_partitions)]
