"""Pipeline stage partitioning.

TPU-native analog of the reference's ``UniformPartitioner``
(pipegoose/nn/pipeline_parallel/partitioner.py:29-219), which
symbolically traces the HF model with torch.fx, counts params per graph
node, and rebuilds per-shard GraphModules. With stacked-layer params
(models/bloom.py) no graph surgery is needed: a partition is a
contiguous LAYER RANGE, and for the common equal-layers case simply a
PartitionSpec over the ``pipe`` axis (pipeline.py:pipe_stage_specs).

This module covers the general, non-uniform case: given per-layer costs
(param counts — the reference's metric, partitioner.py:73-99 — or FLOPs
from the profiler), compute the contiguous assignment minimizing the
bottleneck stage cost (exact interval-partition DP, not the reference's
greedy running-total heuristic, partitioner.py:101-144).

Why the RUNTIME uses equal stages only (gpipe/one_f_one_b consume an
evenly pipe-sharded stacked dim): the pipeline is ONE compiled SPMD
program — every pipe rank runs the same executable over identically-
shaped param shards, which is exactly what makes the thread/RPC engine
of the reference unnecessary. Genuinely uneven stages need per-rank
DIFFERENT param shapes (an MPMD runtime) or padding every stage to the
longest (which costs the padded compute on every stage and erases the
balancing win). For transformer stacks — identical per-layer cost by
construction — equal split IS the DP optimum; this partitioner is for
cost analysis and for heterogeneous-cost stacks feeding a future
per-stage-compiled (MPMD) runtime.
"""
from __future__ import annotations

from typing import Any, List, Sequence

import jax
import numpy as np


def layer_param_counts(stacked_params: Any) -> np.ndarray:
    """Per-layer parameter counts from a stacked-blocks pytree (leading
    dim = n_layer on every leaf) — the reference's per-node param
    counting (partitioner.py:73-99) without tracing."""
    leaves = jax.tree_util.tree_leaves(stacked_params)
    n_layer = leaves[0].shape[0]
    per_layer = sum(int(np.prod(x.shape[1:])) for x in leaves)
    return np.full(n_layer, per_layer, dtype=np.int64)


def partition_costs(costs: Sequence[float], n_partitions: int) -> List[range]:
    """Contiguous ranges minimizing the max per-partition cost (exact DP).

    The reference assigns shards greedily when the running total passes
    total/n (partitioner.py:101-144), which can overload the last stage;
    the DP is optimal for the same contiguity constraint.
    """
    costs = list(costs)
    L, P = len(costs), n_partitions
    if P < 1 or P > L:
        raise ValueError(f"need 1 <= n_partitions <= n_layers, got {P} of {L}")
    prefix = np.concatenate([[0.0], np.cumsum(costs)])

    # dp[p][i] = minimal bottleneck for first i layers in p partitions
    dp = np.full((P + 1, L + 1), np.inf)
    cut = np.zeros((P + 1, L + 1), dtype=int)
    dp[0][0] = 0.0
    for p in range(1, P + 1):
        for i in range(p, L + 1):
            for j in range(p - 1, i):
                cand = max(dp[p - 1][j], prefix[i] - prefix[j])
                if cand < dp[p][i]:
                    dp[p][i] = cand
                    cut[p][i] = j
    bounds = [L]
    for p in range(P, 0, -1):
        bounds.append(cut[p][bounds[-1]])
    bounds.reverse()
    return [range(bounds[i], bounds[i + 1]) for i in range(P)]


class UniformPartitioner:
    """API-parity wrapper (reference partitioner.py:29-57): split a model
    of ``n_layer`` layers into ``n_partitions`` contiguous stages by cost."""

    def __init__(self, n_partitions: int):
        self.n_partitions = n_partitions

    def split(self, costs: Sequence[float]) -> List[range]:
        return partition_costs(costs, self.n_partitions)

    def split_even(self, n_layer: int) -> List[range]:
        if n_layer % self.n_partitions != 0:
            return self.split([1.0] * n_layer)
        k = n_layer // self.n_partitions
        return [range(i * k, (i + 1) * k) for i in range(self.n_partitions)]
