"""Pipeline schedules as pure timeline data.

TPU-native analog of the reference's ``GPipeScheduler``
(pipegoose/nn/pipeline_parallel/scheduler.py:35-115). There, the schedule
drives a thread/RPC engine at run time; here the schedule is *compiled
into* the program (pipeline.py runs one ``lax.scan`` step per clock), so
this module's timeline exists for: sizing the scan (n_clock), tests that
pin the clock-cycle semantics to the torchgpipe timeline the reference
used, utilization analysis, and the 1F1B variant's ordering.

A task is (microbatch_idx, partition_idx); clock c runs every task with
``microbatch_idx + partition_idx == c`` (torchgpipe §3.2.1).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import List


class JobType(str, enum.Enum):
    FORWARD = "forward"
    BACKWARD = "backward"


@dataclasses.dataclass(frozen=True)
class Task:
    job_type: JobType
    microbatch_idx: int
    partition_idx: int


class GPipeScheduler:
    """Deterministic clock-cycle timeline (reference scheduler.py:66-94).

    Unlike the reference, the backward timeline here is a *description*
    of what autodiff already does: reverse-mode differentiation of the
    forward scan replays the clocks in reverse with flipped job types —
    there is no separate backward engine to drive.
    """

    def __init__(self, n_microbatches: int, n_partitions: int):
        assert n_microbatches >= 1 and n_partitions >= 1
        self.n_microbatches = n_microbatches
        self.n_partitions = n_partitions

    @property
    def total_forward_clocks(self) -> int:
        return self.n_microbatches + self.n_partitions - 1

    @property
    def total_backward_clocks(self) -> int:
        return self.total_forward_clocks

    def get_forward_schedules(self) -> List[List[Task]]:
        """clock -> tasks, forward: task (m, p) runs at clock m + p."""
        out: List[List[Task]] = []
        for c in range(self.total_forward_clocks):
            tasks = [
                Task(JobType.FORWARD, m, c - m)
                for m in range(self.n_microbatches)
                if 0 <= c - m < self.n_partitions
            ]
            out.append(tasks)
        return out

    def get_backward_schedules(self) -> List[List[Task]]:
        """Reverse of forward with flipped job type — matching the
        reference's deepcopy+reverse construction (scheduler.py:82-94),
        and exactly the order reverse-mode AD visits the forward scan."""
        fwd = self.get_forward_schedules()
        return [
            [Task(JobType.BACKWARD, t.microbatch_idx, t.partition_idx) for t in tasks]
            for tasks in reversed(fwd)
        ]


class OneFOneBScheduler(GPipeScheduler):
    """1F1B (PipeDream-flush) ordering: same total clocks, but each
    stage starts its backward as soon as its first microbatch returns,
    bounding live activations at ``n_partitions`` instead of
    ``n_microbatches``. The reference's backward schedule is a naive
    reversed-forward (SURVEY.md §7 quirks). Currently timeline-only:
    it documents/tests the ordering an interleaved pipeline runtime
    would follow; pipeline.py's gpipe keeps the plain GPipe schedule
    (remat bounds its activation memory instead)."""

    def timeline(self, partition_idx: int) -> List[Task]:
        """Per-stage instruction stream: warmup forwards, steady 1F1B
        pairs, cooldown backwards."""
        M, P = self.n_microbatches, self.n_partitions
        warmup = min(P - partition_idx - 1, M)
        steps: List[Task] = []
        fwd_m = bwd_m = 0
        for _ in range(warmup):
            steps.append(Task(JobType.FORWARD, fwd_m, partition_idx))
            fwd_m += 1
        while fwd_m < M:
            steps.append(Task(JobType.FORWARD, fwd_m, partition_idx))
            fwd_m += 1
            steps.append(Task(JobType.BACKWARD, bwd_m, partition_idx))
            bwd_m += 1
        while bwd_m < M:
            steps.append(Task(JobType.BACKWARD, bwd_m, partition_idx))
            bwd_m += 1
        return steps
