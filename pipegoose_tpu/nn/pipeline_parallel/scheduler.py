"""Pipeline schedules as pure timeline data.

TPU-native analog of the reference's ``GPipeScheduler``
(pipegoose/nn/pipeline_parallel/scheduler.py:35-115). There, the schedule
drives a thread/RPC engine at run time; here the schedule is *compiled
into* the program (pipeline.py runs one ``lax.scan`` step per clock), so
this module's timeline exists for: sizing the scan (n_clock), tests that
pin the clock-cycle semantics to the torchgpipe timeline the reference
used, utilization analysis, and the 1F1B variant's ordering.

A task is (microbatch_idx, partition_idx); clock c runs every task with
``microbatch_idx + partition_idx == c`` (torchgpipe §3.2.1).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import List


class JobType(str, enum.Enum):
    FORWARD = "forward"
    BACKWARD = "backward"


@dataclasses.dataclass(frozen=True)
class Task:
    job_type: JobType
    microbatch_idx: int
    partition_idx: int


class GPipeScheduler:
    """Deterministic clock-cycle timeline (reference scheduler.py:66-94).

    Unlike the reference, the backward timeline here is a *description*
    of what autodiff already does: reverse-mode differentiation of the
    forward scan replays the clocks in reverse with flipped job types —
    there is no separate backward engine to drive.
    """

    def __init__(self, n_microbatches: int, n_partitions: int):
        assert n_microbatches >= 1 and n_partitions >= 1
        self.n_microbatches = n_microbatches
        self.n_partitions = n_partitions

    @property
    def total_forward_clocks(self) -> int:
        return self.n_microbatches + self.n_partitions - 1

    @property
    def total_backward_clocks(self) -> int:
        return self.total_forward_clocks

    @property
    def bubble_fraction(self) -> float:
        """Idle share of the stage-clock grid: P stages over M + P - 1
        clocks hold M tasks each, so (P-1)/(M+P-1) of every stage's
        timeline is bubble (torchgpipe §3.3; identical for the forward
        and backward halves, and for the 1F1B reordering — it moves the
        idle clocks, it doesn't remove them). The theoretical ceiling
        the ``pipeline.bubble_fraction`` gauge reports
        (telemetry/chrometrace.py)."""
        return (self.n_partitions - 1) / self.total_forward_clocks

    def get_forward_schedules(self) -> List[List[Task]]:
        """clock -> tasks, forward: task (m, p) runs at clock m + p."""
        out: List[List[Task]] = []
        for c in range(self.total_forward_clocks):
            tasks = [
                Task(JobType.FORWARD, m, c - m)
                for m in range(self.n_microbatches)
                if 0 <= c - m < self.n_partitions
            ]
            out.append(tasks)
        return out

    def get_backward_schedules(self) -> List[List[Task]]:
        """Reverse of forward with flipped job type — matching the
        reference's deepcopy+reverse construction (scheduler.py:82-94),
        and exactly the order reverse-mode AD visits the forward scan."""
        fwd = self.get_forward_schedules()
        return [
            [Task(JobType.BACKWARD, t.microbatch_idx, t.partition_idx) for t in tasks]
            for tasks in reversed(fwd)
        ]


def one_f_one_b_tables(n_microbatches: int, n_partitions: int):
    """Compile the 1F1B per-stage instruction streams into a global
    clock timetable for the SPMD runtime (pipeline.py:one_f_one_b).

    Greedy list-scheduling of each stage's ``timeline`` under the data
    dependencies of a compiled pipeline with one-clock transfers:
    F(m, p) needs F(m, p-1) at an earlier clock (activation arrives the
    clock after it was produced); B(m, p) needs B(m, p+1) earlier (for
    the cotangent) — B(m, P-1) only needs its own F, which stream order
    guarantees. Each stage executes at most ONE instruction per clock.

    Returns ``(fwd, bwd, n_slots, n_clock)`` where ``fwd``/``bwd`` are
    (n_clock, P) int arrays holding the microbatch index executed by
    stage p at clock c (or -1), and ``n_slots`` is the verified ring
    size bounding simultaneously-live saved activations / in-transit
    values per stage (<= P + 1, the 1F1B memory guarantee).
    """
    import numpy as np

    M, P = n_microbatches, n_partitions
    streams = [OneFOneBScheduler(M, P).timeline(p) for p in range(P)]
    ptrs = [0] * P
    f_done: dict = {}
    b_done: dict = {}
    fwd_rows, bwd_rows = [], []
    c = 0
    while any(ptrs[p] < len(streams[p]) for p in range(P)):
        fwd_row = [-1] * P
        bwd_row = [-1] * P
        progressed = False
        for p in range(P):
            if ptrs[p] >= len(streams[p]):
                continue
            t = streams[p][ptrs[p]]
            m = t.microbatch_idx
            if t.job_type == JobType.FORWARD:
                ready = p == 0 or f_done.get((m, p - 1), c) < c
                if ready:
                    fwd_row[p] = m
                    f_done[(m, p)] = c
                    ptrs[p] += 1
                    progressed = True
            else:
                ready = (p == P - 1) or b_done.get((m, p + 1), c) < c
                if ready:
                    bwd_row[p] = m
                    b_done[(m, p)] = c
                    ptrs[p] += 1
                    progressed = True
        assert progressed, f"1F1B schedule deadlocked at clock {c} (M={M}, P={P})"
        fwd_rows.append(fwd_row)
        bwd_rows.append(bwd_row)
        c += 1

    # verify the ring bound: three per-stage buffer families, each keyed
    # by microbatch and indexed m % n_slots —
    #   act:    saved stage input, live [F(m,p), B(m,p)]
    #   recv_h: in-transit activation, live [F(m,p-1)+1, F(m,p)]
    #   recv_g: in-transit cotangent, live [B(m,p+1)+1, B(m,p)]
    span_families = []
    for p in range(P):
        span_families.append([(f_done[(m, p)], b_done[(m, p)]) for m in range(M)])
        if p > 0:
            span_families.append(
                [(f_done[(m, p - 1)] + 1, f_done[(m, p)]) for m in range(M)]
            )
        if p < P - 1:
            span_families.append(
                [(b_done[(m, p + 1)] + 1, b_done[(m, p)]) for m in range(M)]
            )

    def max_overlap(spans):
        return max(
            sum(1 for s2, e2 in spans if s2 <= s <= e2) for s, e in spans
        )

    n_slots = min(M, max(max_overlap(sp) for sp in span_families))
    for spans in span_families:
        for m1 in range(M):
            for m2 in range(m1 + 1, M):
                if m1 % n_slots == m2 % n_slots:
                    s1, e1 = spans[m1]
                    s2, e2 = spans[m2]
                    assert e1 < s2 or e2 < s1, (
                        f"ring collision: microbatches {m1},{m2} share a slot "
                        f"(n_slots={n_slots}, spans {spans[m1]} vs {spans[m2]})"
                    )
    return (
        np.asarray(fwd_rows, np.int32),
        np.asarray(bwd_rows, np.int32),
        n_slots,
        c,
    )


class OneFOneBScheduler(GPipeScheduler):
    """1F1B (PipeDream-flush) ordering: same total clocks, but each
    stage starts its backward as soon as its first microbatch returns,
    bounding live activations at ``n_partitions`` instead of
    ``n_microbatches``. The reference's backward schedule is a naive
    reversed-forward (SURVEY.md §7 quirks). Currently timeline-only:
    it documents/tests the ordering an interleaved pipeline runtime
    would follow; pipeline.py's gpipe keeps the plain GPipe schedule
    (remat bounds its activation memory instead)."""

    def tables(self):
        """Cached ``one_f_one_b_tables`` result — the (fwd, bwd,
        n_slots, n_clock) global clock timetable the compiled runtime
        executes."""
        if getattr(self, "_tables", None) is None:
            self._tables = one_f_one_b_tables(
                self.n_microbatches, self.n_partitions
            )
        return self._tables

    @property
    def n_clock(self) -> int:
        return int(self.tables()[3])

    @property
    def bubble_fraction(self) -> float:
        """Idle share of the ACTUAL compiled 1F1B timetable (not the
        inherited GPipe formula): each stage executes 2M instructions
        (one F and one B per microbatch) over ``n_clock`` clocks, so
        the per-stage-averaged idle share is ``1 - 2M/n_clock``. Equals
        GPipe's (P-1)/(M+P-1) whenever the greedy timetable achieves
        the PipeDream-flush bound of 2(M+P-1) clocks, and reports the
        TRUE number when list-scheduling needs extra clocks — so the
        ``pipeline.bubble_*`` gauges and the Perfetto timeline
        (telemetry/chrometrace.py) are no longer GPipe-only."""
        return 1.0 - (2.0 * self.n_microbatches) / self.n_clock

    def timeline(self, partition_idx: int) -> List[Task]:
        """Per-stage instruction stream: warmup forwards, steady 1F1B
        pairs, cooldown backwards."""
        M, P = self.n_microbatches, self.n_partitions
        warmup = min(P - partition_idx - 1, M)
        steps: List[Task] = []
        fwd_m = bwd_m = 0
        for _ in range(warmup):
            steps.append(Task(JobType.FORWARD, fwd_m, partition_idx))
            fwd_m += 1
        while fwd_m < M:
            steps.append(Task(JobType.FORWARD, fwd_m, partition_idx))
            fwd_m += 1
            steps.append(Task(JobType.BACKWARD, bwd_m, partition_idx))
            bwd_m += 1
        while bwd_m < M:
            steps.append(Task(JobType.BACKWARD, bwd_m, partition_idx))
            bwd_m += 1
        return steps
