"""Microbatch splitting.

Analog of the reference's ``microbatch.split``
(pipegoose/nn/pipeline_parallel/microbatch.py:11-26) — which passed the
microbatch COUNT to ``torch.split`` (a chunk-SIZE argument), yielding
size-n chunks instead of n chunks (SURVEY.md §7 quirks). Here splitting
is an explicit reshape to a leading microbatch dim: (B, ...) ->
(n, B/n, ...), which is also exactly the layout ``lax.scan`` wants.
"""
from __future__ import annotations

from typing import Any

import jax


def split(batch: Any, n_microbatches: int) -> Any:
    """Reshape every leaf (B, ...) -> (n_microbatches, B/n, ...)."""
    if n_microbatches < 1:
        raise ValueError(f"n_microbatches must be >= 1, got {n_microbatches}")

    def f(x: jax.Array) -> jax.Array:
        if x.shape[0] % n_microbatches != 0:
            raise ValueError(
                f"batch dim {x.shape[0]} not divisible by "
                f"n_microbatches={n_microbatches}"
            )
        return x.reshape((n_microbatches, x.shape[0] // n_microbatches) + x.shape[1:])

    return jax.tree_util.tree_map(f, batch)


def merge(microbatches: Any) -> Any:
    """Inverse of split: (n, b, ...) -> (n*b, ...)."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:]), microbatches
    )
