from pipegoose_tpu.nn.pipeline_parallel.microbatch import merge, split
from pipegoose_tpu.nn.pipeline_parallel.pipeline import (
    gpipe,
    last_stage_value,
    pipe_stage_specs,
)
from pipegoose_tpu.nn.pipeline_parallel.scheduler import (
    GPipeScheduler,
    JobType,
    OneFOneBScheduler,
    Task,
)

__all__ = [
    "gpipe",
    "last_stage_value",
    "pipe_stage_specs",
    "GPipeScheduler",
    "OneFOneBScheduler",
    "JobType",
    "Task",
    "split",
    "merge",
]
