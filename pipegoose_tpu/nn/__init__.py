from pipegoose_tpu.nn.parallel import Parallel, shard_tree, spec_tree, unshard_tree
from pipegoose_tpu.nn.parallel_mapping import (
    Column,
    Expert,
    ParallelInfo,
    ParallelMapping,
    Replicate,
    Row,
    Vocab,
)

__all__ = [
    "Parallel",
    "shard_tree",
    "spec_tree",
    "unshard_tree",
    "ParallelMapping",
    "ParallelInfo",
    "Column",
    "Row",
    "Vocab",
    "Expert",
    "Replicate",
]
