"""Ulysses-style (DeepSpeed) sequence parallelism: all_to_all
head/sequence exchange.

NEW CAPABILITY (absent from the reference — SURVEY.md §5). Where ring
attention keeps heads whole and rotates K/V blocks, Ulysses transposes
the sharding: activations enter sharded on SEQUENCE, two ``all_to_all``
ops re-shard them on HEADS for the attention proper (each device sees
the full sequence for nh/sp heads), and a final all_to_all restores
sequence sharding. Exact attention, 4 collectives per layer, best when
nh >= sp and sequence lengths make ring accumulation latency-bound.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from pipegoose_tpu.distributed.functional import all_to_all


def ulysses_attention(
    q: jax.Array,  # (B, S_local, nh, hd)
    k: jax.Array,
    v: jax.Array,
    axis_name: Optional[str],
    attn_fn: Callable[[jax.Array, jax.Array, jax.Array], jax.Array],
    # attn_fn(q, k, v) -> (B, S_full, nh_local, hd): full-sequence
    # attention on the local head subset (masks/bias applied inside)
) -> jax.Array:
    """seq-sharded -> head-sharded -> attn -> seq-sharded."""
    if axis_name is None:
        return attn_fn(q, k, v)

    def seq_to_heads(x):
        # (B, S/sp, nh, hd) -> (B, S, nh/sp, hd)
        return all_to_all(x, axis_name, split_dim=2, concat_dim=1)

    def heads_to_seq(x):
        return all_to_all(x, axis_name, split_dim=1, concat_dim=2)

    out = attn_fn(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v))
    return heads_to_seq(out)
