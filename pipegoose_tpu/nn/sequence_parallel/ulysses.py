"""Ulysses-style (DeepSpeed) sequence parallelism: all_to_all
head/sequence exchange.

NEW CAPABILITY (absent from the reference — SURVEY.md §5). Where ring
attention keeps heads whole and rotates K/V blocks, Ulysses transposes
the sharding: activations enter sharded on SEQUENCE, two ``all_to_all``
ops re-shard them on HEADS for the attention proper (each device sees
the full sequence for nh/sp heads), and a final all_to_all restores
sequence sharding. Exact attention, 4 collectives per layer, best when
nh >= sp and sequence lengths make ring accumulation latency-bound.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from pipegoose_tpu.distributed.functional import all_to_all


def ulysses_attention(
    q: jax.Array,  # (B, S_local, nh, hd)
    k: jax.Array,
    v: jax.Array,
    axis_name: Optional[str],
    attn_fn: Callable[[jax.Array, jax.Array, jax.Array], jax.Array],
    # attn_fn(q, k, v) -> (B, S_full, nh_local, hd): full-sequence
    # attention on the local head subset (masks/bias applied inside)
) -> jax.Array:
    """seq-sharded -> head-sharded -> attn -> seq-sharded."""
    if axis_name is None:
        return attn_fn(q, k, v)

    def seq_to_heads(x):
        # (B, S/sp, nh, hd) -> (B, S, nh/sp, hd)
        return all_to_all(x, axis_name, split_dim=2, concat_dim=1)

    def heads_to_seq(x):
        return all_to_all(x, axis_name, split_dim=1, concat_dim=2)

    out = attn_fn(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v))
    return heads_to_seq(out)


def ulysses_causal_attention(
    q: jax.Array,  # (B, S_local, nh, hd) — position encoding ALREADY applied
    k: jax.Array,  # (B, S_local, nh | nkv, hd)
    v: jax.Array,
    axis_name: str,
    pad_mask_local: Optional[jax.Array] = None,  # (B, S_local)
    alibi_slopes: Optional[jax.Array] = None,  # (nh,) LOCAL head slopes
    window: Optional[int] = None,
    use_flash: bool = False,
    alibi_pos_local: Optional[jax.Array] = None,  # (B, S_local) mask-aware pos
) -> jax.Array:
    """Causal Ulysses attention shared by the model families (bloom:
    ALiBi slopes; mixtral/llama: RoPE pre-applied, optional sliding
    window). Handles GQA (nkv < nh): both head counts split across the
    sp axis — the grouped-head mapping stays consistent because
    ``nh = g * nkv`` splits uniformly. Per-head state (the ALiBi slopes)
    follows the heads through the exchange: device r serves the r-th
    head subset."""
    from pipegoose_tpu.distributed.functional import all_gather
    from pipegoose_tpu.nn.sequence_parallel.ring_attention import (
        make_causal_alibi_bias_fn,
        ring_attention,
    )

    sp = jax.lax.axis_size(axis_name)
    nh, nkv = q.shape[2], k.shape[2]
    if nh % sp or nkv % sp:
        raise ValueError(
            f"ulysses needs local q heads {nh} AND kv heads {nkv} divisible "
            f"by the sequence axis size {sp}; use the ring variant (no "
            "head-count constraint)"
        )
    full_mask = (
        all_gather(pad_mask_local, axis_name, dim=1)
        if pad_mask_local is not None else None
    )
    # mask-aware global ALiBi positions (HF semantics for left-padded
    # batches — see models/bloom._sp_alibi_pos); full sequence per device
    # after the exchange, so they gather like the mask
    full_apos = (
        all_gather(alibi_pos_local, axis_name, dim=1)
        if alibi_pos_local is not None else None
    )
    sub_slopes = None
    if alibi_slopes is not None:
        nh_sub = nh // sp
        sub_slopes = jax.lax.dynamic_slice_in_dim(
            alibi_slopes, jax.lax.axis_index(axis_name) * nh_sub, nh_sub, 0
        )

    def attn_fn(qh, kh, vh):  # full-seq, nh/sp q heads, nkv/sp kv heads
        b, s_full = qh.shape[:2]
        if use_flash:
            from pipegoose_tpu.ops.flash_attention import (
                flash_attention,
                mask_to_kv_bias,
            )

            if full_apos is not None:
                kv_pos = full_apos  # mask-aware (kv_pos is ALiBi-only here;
                # causal comes from block indices inside the kernel)
            else:
                kv_pos = jnp.broadcast_to(
                    jnp.arange(s_full, dtype=jnp.float32)[None], (b, s_full)
                )  # plain global positions — same ALiBi semantics as ring
            kv_neg = (
                mask_to_kv_bias(full_mask)[1] if full_mask is not None else None
            )
            return flash_attention(
                qh, kh, vh, alibi_slopes=sub_slopes,
                kv_pos=kv_pos, kv_neg=kv_neg, causal=True, window=window,
            )
        bias_fn = make_causal_alibi_bias_fn(
            s_full, None, alibi_slopes=sub_slopes, window=window
        )
        side = (full_mask, full_apos) if full_apos is not None else full_mask
        # single-step ring == plain attention, with native GQA
        return ring_attention(qh, kh, vh, None, bias_fn, kv_side=side)

    return ulysses_attention(q, k, v, axis_name, attn_fn)


def ulysses_bidirectional_attention(
    q: jax.Array,  # (B, S_local, nh, hd)
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    pad_mask_local: Optional[jax.Array] = None,  # (B, S_local)
    use_flash: bool = False,
) -> jax.Array:
    """Encoder (bidirectional) Ulysses attention: same all_to_all
    head/sequence exchange, no causal mask, key-padding only. Position
    information is additive at embedding time for encoders, so no
    global-position plumbing is needed. With ``use_flash`` the
    full-sequence attention on the local head subset runs the fused
    kernel (causal=False) — the encoder's flash-under-SP path (the
    bidirectional RING still uses dense block math)."""
    from pipegoose_tpu.distributed.functional import all_gather
    from pipegoose_tpu.nn.sequence_parallel.ring_attention import (
        make_bidirectional_bias_fn,
        ring_attention,
    )

    sp = jax.lax.axis_size(axis_name)
    nh = q.shape[2]
    if nh % sp:
        raise ValueError(
            f"ulysses needs local heads {nh} divisible by the sequence "
            f"axis size {sp}; use the ring variant (no head constraint)"
        )
    full_mask = (
        all_gather(pad_mask_local, axis_name, dim=1)
        if pad_mask_local is not None else None
    )

    def attn_fn(qh, kh, vh):
        if use_flash:
            from pipegoose_tpu.ops.flash_attention import (
                flash_attention,
                mask_to_kv_bias,
            )

            kv_neg = (
                mask_to_kv_bias(full_mask)[1]
                if full_mask is not None else None
            )
            return flash_attention(qh, kh, vh, causal=False, kv_neg=kv_neg)
        # single-step ring == plain bidirectional attention
        return ring_attention(
            qh, kh, vh, None, make_bidirectional_bias_fn(),
            kv_side=full_mask,
        )

    return ulysses_attention(q, k, v, axis_name, attn_fn)
