"""Ring attention: exact attention over a sequence-sharded axis.

NEW CAPABILITY: the reference advertises sequence parallelism
(README.md:96) but implements none — no ring/Ulysses/context-parallel
code exists in its tree, and the reduce_scatter it would need is an
empty stub (SURVEY.md §5). This module provides the real thing, designed
for ICI:

- the sequence is sharded over the ``seq`` mesh axis: each device holds
  a (B, S/sp, H) chunk of Q, K, V;
- sp ring steps: attend local Q against the resident K/V block with a
  flash-attention-style online softmax (running max / denominator /
  accumulator — numerically exact, O(S_local^2) memory), then rotate
  K/V one hop with ``lax.ppermute``;
- communication is overlappable K/V block transfers around the ring —
  total bytes = K+V once around, independent of the attention matrix;
- backward is reverse-mode AD through the scan (the reverse ring).

Bias (causal mask, padding, ALiBi) is supplied per block via
``bias_fn(kv_rank, kv_pad_mask)`` so any additive attention bias works;
block global positions are reconstructed from the rank indices.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from pipegoose_tpu.distributed.functional import shift_right

NEG_INF = -1e9


def ring_attention(
    q: jax.Array,  # (B, Sq_local, nh, hd)
    k: jax.Array,  # (B, Skv_local, nh, hd)
    v: jax.Array,  # (B, Skv_local, nh, hd)
    axis_name: Optional[str],
    bias_fn: Callable[[jax.Array], jax.Array],
    kv_side: Optional[jax.Array] = None,  # e.g. (B, Skv_local) pad mask, rides the ring
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact softmax(QK^T * scale + bias) V with K/V ring rotation.

    ``bias_fn(kv_rank[, kv_side_block]) -> (B|1, nh|1, Sq, Skv)`` additive
    bias for the block where the resident K/V originated at ``kv_rank``.
    With ``axis_name=None`` this is single-device flash-style attention
    (one step, kv_rank = 0).
    """
    b, sq, nh, hd = q.shape
    if scale is None:
        scale = hd**-0.5
    sp = lax.axis_size(axis_name) if axis_name else 1
    rank = lax.axis_index(axis_name) if axis_name else 0

    qf = q.astype(jnp.float32) * scale

    def block(m, l, o, k_t, v_t, kv_rank, side_t):
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_t.astype(jnp.float32))
        bias = bias_fn(kv_rank, side_t) if side_t is not None else bias_fn(kv_rank)
        s = s + bias
        m_new = jnp.maximum(m, s.max(axis=-1))
        # fully-masked rows keep m = NEG_INF; avoid inf-inf -> nan
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, v_t.astype(jnp.float32))
        o_new = o * alpha[..., None] + pv
        return m_new, l_new, o_new

    m0 = jnp.full((b, nh, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, nh, sq), jnp.float32)
    o0 = jnp.zeros((b, nh, sq, hd), jnp.float32)

    if sp == 1:
        m, l, o = block(m0, l0, o0, k, v, jnp.asarray(0), kv_side)
    else:
        # sp-1 (block + rotate) steps, then a final block with NO rotation
        # — a rotation after the last block would be a dead K+V transfer
        # every layer (XLA can't DCE a collective feeding the loop carry)

        def scan_fn(carry, t):
            m, l, o, k_t, v_t, side_t = carry
            kv_rank = (rank - t) % sp
            m, l, o = block(m, l, o, k_t, v_t, kv_rank, side_t)
            # rotate K/V (and side data) to the next rank
            k_t = shift_right(k_t, axis_name)
            v_t = shift_right(v_t, axis_name)
            if side_t is not None:
                side_t = shift_right(side_t, axis_name)
            return (m, l, o, k_t, v_t, side_t), None

        (m, l, o, k_t, v_t, side_t), _ = lax.scan(
            scan_fn, (m0, l0, o0, k, v, kv_side), jnp.arange(sp - 1)
        )
        m, l, o = block(m, l, o, k_t, v_t, (rank - (sp - 1)) % sp, side_t)

    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def make_causal_alibi_bias_fn(
    seq_local: int,
    axis_name: Optional[str],
    alibi_slopes: Optional[jax.Array] = None,  # (nh,)
    q_rank: Optional[jax.Array] = None,
):
    """Block bias for BLOOM-style attention under sequence sharding:
    causal mask on GLOBAL positions + ALiBi (slope * global key position)
    + padding mask from the K/V chunk's attention mask (rides the ring
    as ``kv_side``)."""
    rank = (
        q_rank
        if q_rank is not None
        else (lax.axis_index(axis_name) if axis_name else 0)
    )
    q_pos = rank * seq_local + jnp.arange(seq_local)  # (Sq,)

    def bias_fn(kv_rank, kv_pad_mask=None):
        kv_pos = kv_rank * seq_local + jnp.arange(seq_local)  # (Skv,)
        causal = q_pos[:, None] >= kv_pos[None, :]  # (Sq, Skv)
        bias = jnp.where(causal, 0.0, NEG_INF)[None, None]  # (1,1,Sq,Skv)
        if alibi_slopes is not None:
            # NOTE: mask-aware position (cumsum) needs global context; for
            # right-padded batches plain positions match HF's alibi
            bias = bias + alibi_slopes[None, :, None, None] * kv_pos[None, None, None, :].astype(jnp.float32)
        if kv_pad_mask is not None:
            keep = kv_pad_mask[:, None, None, :] > 0  # (B,1,1,Skv)
            bias = bias + jnp.where(keep, 0.0, NEG_INF)
        return bias

    return bias_fn
