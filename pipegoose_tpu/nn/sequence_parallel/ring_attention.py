"""Ring attention: exact attention over a sequence-sharded axis.

NEW CAPABILITY: the reference advertises sequence parallelism
(README.md:96) but implements none — no ring/Ulysses/context-parallel
code exists in its tree, and the reduce_scatter it would need is an
empty stub (SURVEY.md §5). This module provides the real thing, designed
for ICI:

- the sequence is sharded over the ``seq`` mesh axis: each device holds
  a (B, S/sp, H) chunk of Q, K, V;
- sp ring steps: attend local Q against the resident K/V block with a
  flash-attention-style online softmax (running max / denominator /
  accumulator — numerically exact, O(S_local^2) memory), then rotate
  K/V one hop with ``lax.ppermute``;
- communication is overlappable K/V block transfers around the ring —
  total bytes = K+V once around, independent of the attention matrix;
- backward is reverse-mode AD through the scan (the reverse ring).

Bias (causal mask, padding, ALiBi) is supplied per block via
``bias_fn(kv_rank, kv_pad_mask)`` so any additive attention bias works;
block global positions are reconstructed from the rank indices.
"""
from __future__ import annotations

import functools
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from pipegoose_tpu.distributed.functional import shift_right

NEG_INF = -1e9


def _ring_scan(chunk_fn, state, k, v, kv_side, axis_name):
    """Shared ring driver: apply ``chunk_fn(state, k_t, v_t, kv_rank,
    side_t) -> state`` to the resident K/V chunk, rotate K/V (and the
    optional side data) one hop, repeat sp times. The LAST chunk skips
    the rotation — a rotation after the final block would be a dead
    K+V transfer every layer (XLA can't DCE a collective feeding the
    loop carry). Used by both the dense-math and flash ring paths so
    the rotation/indexing subtleties live in exactly one place."""
    sp = lax.axis_size(axis_name) if axis_name else 1
    rank = lax.axis_index(axis_name) if axis_name else 0

    if sp == 1:
        return chunk_fn(state, k, v, jnp.asarray(0), kv_side)

    def scan_fn(carry, t):
        state, k_t, v_t, side_t = carry
        kv_rank = (rank - t) % sp
        state = chunk_fn(state, k_t, v_t, kv_rank, side_t)
        k_t = shift_right(k_t, axis_name)
        v_t = shift_right(v_t, axis_name)
        if side_t is not None:
            side_t = shift_right(side_t, axis_name)
        return (state, k_t, v_t, side_t), None

    (state, k_t, v_t, side_t), _ = lax.scan(
        scan_fn, (state, k, v, kv_side), jnp.arange(sp - 1)
    )
    return chunk_fn(state, k_t, v_t, (rank - (sp - 1)) % sp, side_t)


def ring_attention(
    q: jax.Array,  # (B, Sq_local, nh, hd)
    k: jax.Array,  # (B, Skv_local, nh | nkv, hd) — fewer kv heads = native GQA
    v: jax.Array,
    axis_name: Optional[str],
    bias_fn: Callable[[jax.Array], jax.Array],
    kv_side: Optional[jax.Array] = None,  # e.g. (B, Skv_local) pad mask, rides the ring
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact softmax(QK^T * scale + bias) V with K/V ring rotation.

    ``bias_fn(kv_rank[, kv_side_block]) -> (B|1, nh|1, Sq, Skv)`` additive
    bias for the block where the resident K/V originated at ``kv_rank``.
    With ``axis_name=None`` this is single-device flash-style attention
    (one step, kv_rank = 0).

    GQA: when ``k``/``v`` carry ``nkv < nh`` heads (``nh = g * nkv``,
    query head h reads kv head h // g — the same grouping as
    :func:`ring_flash_attention`), the grouped einsum reads the shared
    K/V directly and only the nkv-headed K/V rides the ring — hop bytes
    shrink by g, with no materialized head repetition.
    """
    b, sq, nh, hd = q.shape
    nkv = k.shape[2]
    if nh % nkv:
        raise ValueError(f"n_head={nh} must be a multiple of n_kv_head={nkv}")
    g = nh // nkv
    if scale is None:
        scale = hd**-0.5

    qf = q.astype(jnp.float32) * scale

    def block(state, k_t, v_t, kv_rank, side_t):
        m, l, o = state
        skv = k_t.shape[1]
        if g == 1:
            s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_t.astype(jnp.float32))
        else:
            qg = qf.reshape(b, sq, nkv, g, hd)
            s = jnp.einsum(
                "bqhgd,bkhd->bhgqk", qg, k_t.astype(jnp.float32)
            ).reshape(b, nh, sq, skv)
        bias = bias_fn(kv_rank, side_t) if side_t is not None else bias_fn(kv_rank)
        s = s + bias
        m_new = jnp.maximum(m, s.max(axis=-1))
        # fully-masked rows keep m = NEG_INF; avoid inf-inf -> nan
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        if g == 1:
            pv = jnp.einsum("bhqk,bkhd->bhqd", p, v_t.astype(jnp.float32))
        else:
            pg = p.reshape(b, nkv, g, sq, skv)
            pv = jnp.einsum(
                "bhgqk,bkhd->bhgqd", pg, v_t.astype(jnp.float32)
            ).reshape(b, nh, sq, hd)
        o_new = o * alpha[..., None] + pv
        return m_new, l_new, o_new

    state0 = (
        jnp.full((b, nh, sq), NEG_INF, jnp.float32),
        jnp.zeros((b, nh, sq), jnp.float32),
        jnp.zeros((b, nh, sq, hd), jnp.float32),
    )
    m, l, o = _ring_scan(block, state0, k, v, kv_side, axis_name)

    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_flash_attention(
    q: jax.Array,  # (B, S_local, nh, hd)
    k: jax.Array,  # (B, S_local, nh | nkv, hd) — fewer kv heads = native GQA
    v: jax.Array,
    axis_name: Optional[str],
    alibi_slopes: Optional[jax.Array] = None,  # (nh,)
    kv_side: Optional[jax.Array] = None,  # (B, S_local) pad mask, rides the ring
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    alibi_pos: Optional[jax.Array] = None,  # (B, S_local) mask-aware GLOBAL pos
) -> jax.Array:
    """Ring attention with fused flash chunks, forward AND backward.

    Forward: per ring step the resident K/V chunk updates the
    online-softmax state inside a Pallas kernel — the (S_local, S_local)
    score block is never materialized in HBM (the plain
    :func:`ring_attention` materializes it per step), and NO per-step
    residuals are stacked (the plain ring's reverse-mode AD saves every
    rotated K/V copy — sp x the local K/V — plus per-step state).

    Backward: a SECOND gradient ring. With the final logsumexp, the
    flash backward identity p = exp(s - lse) holds globally, so each
    chunk's dQ adds locally while dK/dV contribution accumulators ride
    the ring alongside K/V and arrive home after a full rotation.
    Residual memory is O(S_local) per layer: q, k, v, out, lse.

    Semantics match ``ring_attention(..., make_causal_alibi_bias_fn)``
    exactly: causal on GLOBAL positions, ALiBi slope * global key
    position, padding from the chunk's mask.

    GQA: when ``k``/``v`` carry fewer heads than ``q`` (``nh = g *
    nkv``), the chunk kernels read the shared K/V via grouped index
    maps AND the ring rotates only the nkv-headed K/V — hop bytes
    shrink by g, exactly the traffic long-context GQA models care
    about. dK/dV contributions are computed per query head and
    group-summed into nkv-headed carriers riding the ring.

    ``alibi_pos``: mask-aware GLOBAL key positions for ALiBi — BLOOM's
    ``(cumsum(mask)-1)*mask`` computed over the full sequence (the
    caller supplies the global prefix, see models/bloom._sp_alibi_pos).
    Needed for LEFT-padded batches, where plain ``rank*S_local +
    arange`` positions diverge from HF. The chunk kernels keep using
    plain positions for the causal mask; the per-key ALiBi correction
    ``slope * (alibi_pos - plain_pos)`` folds into the additive key
    bias outside the kernel (exact — ALiBi is constant per key).
    """
    b, s_local, nh, hd = q.shape
    nkv = k.shape[2]
    if nh % nkv:
        raise ValueError(f"n_head={nh} must be a multiple of n_kv_head={nkv}")
    g = nh // nkv
    if alibi_pos is not None and g != 1:
        # the fold needs per-head key bias rows; under GQA the kernels
        # share one kneg row across g query heads (and no ALiBi model
        # uses GQA — ALiBi is the BLOOM family, g == 1)
        raise ValueError("alibi_pos requires n_head == n_kv_head (g == 1)")
    if scale is None:
        scale = hd**-0.5
    if alibi_slopes is None:
        alibi_slopes = jnp.zeros((nh,), jnp.float32)

    def flat(x):
        h = x.shape[2]
        return x.transpose(0, 2, 1, 3).reshape(b * h, s_local, hd)

    slopes = jnp.broadcast_to(
        alibi_slopes.astype(jnp.float32)[None], (b, nh)
    ).reshape(b * nh)
    # the pad bias rides the ring PER BATCH (B, S_local) — broadcasting
    # to (B*nkv, S_local) happens per chunk call, not per hop
    if kv_side is not None:
        kneg = (1.0 - kv_side.astype(jnp.float32)) * NEG_INF
    else:
        kneg = jnp.zeros((b, s_local), jnp.float32)

    out = _ring_flash(
        flat(q), flat(k), flat(v), slopes, kneg, alibi_pos,
        axis_name, float(scale), interpret, g,
    )
    return out.reshape(b, nh, s_local, hd).transpose(0, 2, 1, 3).astype(q.dtype)


def _ring_positions(axis_name, bh, s_local):
    rank = lax.axis_index(axis_name) if axis_name else 0
    qpos = jnp.broadcast_to(
        (rank * s_local + jnp.arange(s_local, dtype=jnp.float32))[None],
        (bh, s_local),
    )
    return rank, qpos


def _kpos_for(kv_rank, bh, s_local):
    return jnp.broadcast_to(
        (kv_rank * s_local + jnp.arange(s_local)).astype(jnp.float32)[None],
        (bh, s_local),
    )


def _expand_heads(x_b, bh):
    """(B, S) per-batch array -> (B*nh, S) for the flat kernel layout."""
    b, s = x_b.shape
    nh = bh // b
    return jnp.broadcast_to(x_b[:, None, :], (b, nh, s)).reshape(bh, s)


def _key_bias(kneg_t, apos_t, slopes, kv_rank, bkv, s_local):
    """Per-head additive key bias for one chunk: padding NEG_INF plus —
    when mask-aware ALiBi positions ride the ring — the correction
    ``slope * (alibi_pos - plain_pos)`` (the kernel itself adds
    ``slope * plain_pos``, so the sum is ``slope * alibi_pos``; plain
    positions stay in the kernel for the causal mask)."""
    kb = _expand_heads(kneg_t, bkv)
    if apos_t is not None:
        kpos = _kpos_for(kv_rank, bkv, s_local)
        kb = kb + slopes[:, None] * (_expand_heads(apos_t, bkv) - kpos)
    return kb


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9))
def _ring_flash(q, k, v, slopes, kneg, apos, axis_name, scale, interpret, g=1):
    out, _ = _ring_flash_fwd_pass(
        q, k, v, slopes, kneg, apos, axis_name, scale, interpret, g
    )
    return out


def _ring_flash_fwd_pass(q, k, v, slopes, kneg, apos, axis_name, scale,
                         interpret, g=1):
    from pipegoose_tpu.ops.flash_attention import flash_ring_chunk

    bh, s_local, hd = q.shape
    bkv = k.shape[0]  # b * nkv rows under GQA
    _, qpos = _ring_positions(axis_name, bh, s_local)
    state0 = (
        jnp.full((bh, s_local), NEG_INF, jnp.float32),
        jnp.zeros((bh, s_local), jnp.float32),
        jnp.zeros((bh, s_local, hd), jnp.float32),
    )

    def chunk(state, k_t, v_t, kv_rank, side_t):
        kneg_t, apos_t = side_t
        m, l, acc = state
        return flash_ring_chunk(
            q, k_t, v_t, slopes, qpos, _kpos_for(kv_rank, bkv, s_local),
            _key_bias(kneg_t, apos_t, slopes, kv_rank, bkv, s_local),
            m, l, acc, scale, interpret, g,
        )

    # the (kneg, apos) pair rides the ring together (ppermute on the
    # pytree; apos=None is an empty subtree and costs nothing)
    m, l, acc = _ring_scan(chunk, state0, k, v, (kneg, apos), axis_name)
    l = jnp.maximum(l, 1e-30)
    out = (acc / l[..., None]).astype(q.dtype)
    lse = m + jnp.log(l)
    return out, lse


def _ring_flash_vjp_fwd(q, k, v, slopes, kneg, apos, axis_name, scale,
                        interpret, g=1):
    out, lse = _ring_flash_fwd_pass(
        q, k, v, slopes, kneg, apos, axis_name, scale, interpret, g
    )
    # O(S_local) residuals only — no per-ring-step stacking
    return out, (q, k, v, slopes, kneg, apos, out, lse)


def _ring_flash_vjp_bwd(axis_name, scale, interpret, g, res, dout):
    from pipegoose_tpu.ops.flash_attention import flash_chunk_dq, flash_chunk_dkv

    q, k, v, slopes, kneg, apos, out, lse = res
    bh, s_local, hd = q.shape
    bkv = k.shape[0]
    rank, qpos = _ring_positions(axis_name, bh, s_local)
    sp = lax.axis_size(axis_name) if axis_name else 1
    delta = (dout.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)

    def contributions(dq, dk, dv, k_t, v_t, side_t, t):
        kneg_t, apos_t = side_t
        kv_rank = (rank - t) % sp
        kpos = _kpos_for(kv_rank, bkv, s_local)
        kneg_h = _key_bias(kneg_t, apos_t, slopes, kv_rank, bkv, s_local)
        dq = dq + flash_chunk_dq(
            q, k_t, v_t, dout, lse, delta, slopes, qpos, kpos, kneg_h,
            scale, interpret, g,
        )
        dkc, dvc = flash_chunk_dkv(
            q, k_t, v_t, dout, lse, delta, slopes, qpos, kpos, kneg_h,
            scale, interpret, g,
        )
        if g > 1:
            # per-query-head contributions -> shared kv-head carriers
            # (rows ordered so g consecutive query heads share one kv row)
            dkc = dkc.reshape(-1, g, s_local, hd).sum(1)
            dvc = dvc.reshape(-1, g, s_local, hd).sum(1)
        return dq, dk + dkc, dv + dvc

    def step(carry, t):
        k_t, v_t, side_t, dk, dv, dq = carry
        dq, dk, dv = contributions(dq, dk, dv, k_t, v_t, side_t, t)
        # the dK/dV accumulators ride with their chunk toward home
        k_t = shift_right(k_t, axis_name) if axis_name else k_t
        v_t = shift_right(v_t, axis_name) if axis_name else v_t
        side_t = shift_right(side_t, axis_name) if axis_name else side_t
        dk = shift_right(dk, axis_name) if axis_name else dk
        dv = shift_right(dv, axis_name) if axis_name else dv
        return (k_t, v_t, side_t, dk, dv, dq), None

    zeros_kv = jnp.zeros((bkv, s_local, hd), jnp.float32)
    dq0 = jnp.zeros((bh, s_local, hd), jnp.float32)
    side = (kneg, apos)
    if sp == 1:
        dq, dk, dv = contributions(dq0, zeros_kv, zeros_kv, k, v, side, 0)
    else:
        # sp-1 full steps, then a final step that ships ONLY the dK/dV
        # accumulators home — rotating k/v/kneg on the last step would be
        # a dead collective per layer (same rationale as the forward
        # _ring_scan's skipped last rotation)
        (k_t, v_t, side_t, dk, dv, dq), _ = lax.scan(
            step, (k, v, side, zeros_kv, zeros_kv, dq0), jnp.arange(sp - 1)
        )
        dq, dk, dv = contributions(dq, dk, dv, k_t, v_t, side_t, sp - 1)
        dk = shift_right(dk, axis_name)
        dv = shift_right(dv, axis_name)
    d_apos = None if apos is None else jnp.zeros_like(apos)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            jnp.zeros_like(slopes), jnp.zeros_like(kneg), d_apos)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def make_causal_alibi_bias_fn(
    seq_local: int,
    axis_name: Optional[str],
    alibi_slopes: Optional[jax.Array] = None,  # (nh,)
    q_rank: Optional[jax.Array] = None,
    window: Optional[int] = None,  # sliding window (Mistral semantics)
):
    """Block bias for attention under sequence sharding: causal mask on
    GLOBAL positions (+ optional sliding window) + ALiBi (omit slopes for
    RoPE families) + padding mask from the K/V chunk's attention mask
    (rides the ring as ``kv_side``).

    ALiBi positions: with a plain ``(B, Skv)`` mask as ``kv_side``, the
    slope multiplies the plain global key position — identical to HF's
    mask-aware ``(cumsum(mask)-1)*mask`` for unpadded/right-padded
    batches. For LEFT-padded batches pass ``kv_side`` as the pair
    ``(mask, alibi_pos)`` where ``alibi_pos`` holds the global
    mask-aware positions (models/bloom._sp_alibi_pos) — the pair rides
    the ring together and the slope multiplies ``alibi_pos`` instead."""
    rank = (
        q_rank
        if q_rank is not None
        else (lax.axis_index(axis_name) if axis_name else 0)
    )
    q_pos = rank * seq_local + jnp.arange(seq_local)  # (Sq,)

    def bias_fn(kv_rank, kv_side=None):
        if isinstance(kv_side, tuple):
            kv_pad_mask, apos = kv_side
        else:
            kv_pad_mask, apos = kv_side, None
        kv_pos = kv_rank * seq_local + jnp.arange(seq_local)  # (Skv,)
        keep = q_pos[:, None] >= kv_pos[None, :]  # (Sq, Skv)
        if window is not None:
            keep = keep & (q_pos[:, None] - kv_pos[None, :] < window)
        bias = jnp.where(keep, 0.0, NEG_INF)[None, None]  # (1,1,Sq,Skv)
        if alibi_slopes is not None:
            akp = (
                apos[:, None, None, :]  # (B,1,1,Skv) mask-aware
                if apos is not None
                else kv_pos[None, None, None, :]  # plain global
            ).astype(jnp.float32)
            bias = bias + alibi_slopes[None, :, None, None] * akp
        if kv_pad_mask is not None:
            keep_pad = kv_pad_mask[:, None, None, :] > 0  # (B,1,1,Skv)
            bias = bias + jnp.where(keep_pad, 0.0, NEG_INF)
        return bias

    return bias_fn


def make_bidirectional_bias_fn():
    """Block bias for ENCODER attention under sequence sharding: no
    causal mask — every query attends every valid key — only the
    key-padding bias from the K/V chunk's attention mask riding the
    ring as ``kv_side``. This is what lets bidirectional families
    (albert) compose with the ``seq`` axis; before it, the only ring
    bias was causal (:func:`make_causal_alibi_bias_fn`), so encoders
    could not ride the ring at all (VERDICT r4 weak #5).

    Position information for encoders is additive at embedding time
    (absolute position embeddings), so unlike the causal/ALiBi bias no
    global-position reconstruction is needed here — ``kv_rank`` is
    accepted for driver compatibility and unused.
    """

    def bias_fn(kv_rank, kv_side=None):
        del kv_rank
        if kv_side is None:
            return jnp.zeros((1, 1, 1, 1), jnp.float32)
        keep = kv_side[:, None, None, :] > 0  # (B,1,1,Skv)
        return jnp.where(keep, 0.0, NEG_INF)

    return bias_fn
