"""Ring attention: exact attention over a sequence-sharded axis.

NEW CAPABILITY: the reference advertises sequence parallelism
(README.md:96) but implements none — no ring/Ulysses/context-parallel
code exists in its tree, and the reduce_scatter it would need is an
empty stub (SURVEY.md §5). This module provides the real thing, designed
for ICI:

- the sequence is sharded over the ``seq`` mesh axis: each device holds
  a (B, S/sp, H) chunk of Q, K, V;
- sp ring steps: attend local Q against the resident K/V block with a
  flash-attention-style online softmax (running max / denominator /
  accumulator — numerically exact, O(S_local^2) memory), then rotate
  K/V one hop with ``lax.ppermute``;
- communication is overlappable K/V block transfers around the ring —
  total bytes = K+V once around, independent of the attention matrix;
- backward is reverse-mode AD through the scan (the reverse ring).

Bias (causal mask, padding, ALiBi) is supplied per block via
``bias_fn(kv_rank, kv_pad_mask)`` so any additive attention bias works;
block global positions are reconstructed from the rank indices.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from pipegoose_tpu.distributed.functional import shift_right

NEG_INF = -1e9


def _ring_scan(chunk_fn, state, k, v, kv_side, axis_name):
    """Shared ring driver: apply ``chunk_fn(state, k_t, v_t, kv_rank,
    side_t) -> state`` to the resident K/V chunk, rotate K/V (and the
    optional side data) one hop, repeat sp times. The LAST chunk skips
    the rotation — a rotation after the final block would be a dead
    K+V transfer every layer (XLA can't DCE a collective feeding the
    loop carry). Used by both the dense-math and flash ring paths so
    the rotation/indexing subtleties live in exactly one place."""
    sp = lax.axis_size(axis_name) if axis_name else 1
    rank = lax.axis_index(axis_name) if axis_name else 0

    if sp == 1:
        return chunk_fn(state, k, v, jnp.asarray(0), kv_side)

    def scan_fn(carry, t):
        state, k_t, v_t, side_t = carry
        kv_rank = (rank - t) % sp
        state = chunk_fn(state, k_t, v_t, kv_rank, side_t)
        k_t = shift_right(k_t, axis_name)
        v_t = shift_right(v_t, axis_name)
        if side_t is not None:
            side_t = shift_right(side_t, axis_name)
        return (state, k_t, v_t, side_t), None

    (state, k_t, v_t, side_t), _ = lax.scan(
        scan_fn, (state, k, v, kv_side), jnp.arange(sp - 1)
    )
    return chunk_fn(state, k_t, v_t, (rank - (sp - 1)) % sp, side_t)


def ring_attention(
    q: jax.Array,  # (B, Sq_local, nh, hd)
    k: jax.Array,  # (B, Skv_local, nh, hd)
    v: jax.Array,  # (B, Skv_local, nh, hd)
    axis_name: Optional[str],
    bias_fn: Callable[[jax.Array], jax.Array],
    kv_side: Optional[jax.Array] = None,  # e.g. (B, Skv_local) pad mask, rides the ring
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact softmax(QK^T * scale + bias) V with K/V ring rotation.

    ``bias_fn(kv_rank[, kv_side_block]) -> (B|1, nh|1, Sq, Skv)`` additive
    bias for the block where the resident K/V originated at ``kv_rank``.
    With ``axis_name=None`` this is single-device flash-style attention
    (one step, kv_rank = 0).
    """
    b, sq, nh, hd = q.shape
    if scale is None:
        scale = hd**-0.5

    qf = q.astype(jnp.float32) * scale

    def block(state, k_t, v_t, kv_rank, side_t):
        m, l, o = state
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_t.astype(jnp.float32))
        bias = bias_fn(kv_rank, side_t) if side_t is not None else bias_fn(kv_rank)
        s = s + bias
        m_new = jnp.maximum(m, s.max(axis=-1))
        # fully-masked rows keep m = NEG_INF; avoid inf-inf -> nan
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bhqd", p, v_t.astype(jnp.float32))
        o_new = o * alpha[..., None] + pv
        return m_new, l_new, o_new

    state0 = (
        jnp.full((b, nh, sq), NEG_INF, jnp.float32),
        jnp.zeros((b, nh, sq), jnp.float32),
        jnp.zeros((b, nh, sq, hd), jnp.float32),
    )
    m, l, o = _ring_scan(block, state0, k, v, kv_side, axis_name)

    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_flash_attention(
    q: jax.Array,  # (B, S_local, nh, hd)
    k: jax.Array,
    v: jax.Array,
    axis_name: Optional[str],
    alibi_slopes: Optional[jax.Array] = None,  # (nh,)
    kv_side: Optional[jax.Array] = None,  # (B, S_local) pad mask, rides the ring
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Ring attention with the fused flash chunk kernel: per ring step
    the resident K/V chunk is consumed by a Pallas kernel that updates
    the online-softmax state in VMEM — the (S_local, S_local) score
    block is never materialized in HBM (the plain :func:`ring_attention`
    materializes it per step). Semantics match
    ``ring_attention(..., make_causal_alibi_bias_fn(...))`` exactly:
    causal on GLOBAL positions, ALiBi slope * global key position,
    padding from the K/V chunk's mask. Backward rematerializes one dense
    chunk at a time inside the reverse ring
    (ops/flash_attention.py:flash_ring_chunk)."""
    from pipegoose_tpu.ops.flash_attention import NEG_INF as _NEG_INF
    from pipegoose_tpu.ops.flash_attention import flash_ring_chunk

    b, s_local, nh, hd = q.shape
    if scale is None:
        scale = hd**-0.5
    rank = lax.axis_index(axis_name) if axis_name else 0  # for global q positions
    if alibi_slopes is None:
        alibi_slopes = jnp.zeros((nh,), jnp.float32)

    def flat(x):
        return x.transpose(0, 2, 1, 3).reshape(b * nh, s_local, hd)

    def flat_bs(x):  # (B, S) -> (B*nh, S)
        return jnp.broadcast_to(
            x.astype(jnp.float32)[:, None, :], (b, nh, s_local)
        ).reshape(b * nh, s_local)

    qf, kf, vf = flat(q), flat(k), flat(v)
    slopes = jnp.broadcast_to(
        alibi_slopes.astype(jnp.float32)[None], (b, nh)
    ).reshape(b * nh)
    qpos = jnp.broadcast_to(
        (rank * s_local + jnp.arange(s_local, dtype=jnp.float32))[None],
        (b * nh, s_local),
    )
    bh = b * nh
    m0 = jnp.full((bh, s_local), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bh, s_local), jnp.float32)
    acc0 = jnp.zeros((bh, s_local, hd), jnp.float32)

    def chunk(state, k_t, v_t, kv_rank, side_t):
        m, l, acc = state
        kpos = jnp.broadcast_to(
            (kv_rank * s_local + jnp.arange(s_local)).astype(jnp.float32)[None],
            (bh, s_local),
        )
        if side_t is not None:
            kneg = (1.0 - flat_bs(side_t)) * _NEG_INF
        else:
            kneg = jnp.zeros((bh, s_local), jnp.float32)
        return flash_ring_chunk(
            qf, k_t, v_t, slopes, qpos, kpos, kneg, m, l, acc,
            float(scale), interpret,
        )

    m, l, acc = _ring_scan(chunk, (m0, l0, acc0), kf, vf, kv_side, axis_name)

    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.reshape(b, nh, s_local, hd).transpose(0, 2, 1, 3).astype(q.dtype)


def make_causal_alibi_bias_fn(
    seq_local: int,
    axis_name: Optional[str],
    alibi_slopes: Optional[jax.Array] = None,  # (nh,)
    q_rank: Optional[jax.Array] = None,
):
    """Block bias for BLOOM-style attention under sequence sharding:
    causal mask on GLOBAL positions + ALiBi (slope * global key position)
    + padding mask from the K/V chunk's attention mask (rides the ring
    as ``kv_side``)."""
    rank = (
        q_rank
        if q_rank is not None
        else (lax.axis_index(axis_name) if axis_name else 0)
    )
    q_pos = rank * seq_local + jnp.arange(seq_local)  # (Sq,)

    def bias_fn(kv_rank, kv_pad_mask=None):
        kv_pos = kv_rank * seq_local + jnp.arange(seq_local)  # (Skv,)
        causal = q_pos[:, None] >= kv_pos[None, :]  # (Sq, Skv)
        bias = jnp.where(causal, 0.0, NEG_INF)[None, None]  # (1,1,Sq,Skv)
        if alibi_slopes is not None:
            # NOTE: mask-aware position (cumsum) needs global context; for
            # right-padded batches plain positions match HF's alibi
            bias = bias + alibi_slopes[None, :, None, None] * kv_pos[None, None, None, :].astype(jnp.float32)
        if kv_pad_mask is not None:
            keep = kv_pad_mask[:, None, None, :] > 0  # (B,1,1,Skv)
            bias = bias + jnp.where(keep, 0.0, NEG_INF)
        return bias

    return bias_fn
