"""Next-token target alignment on a sequence-sharded axis.

The global shift-by-one of causal-LM labels crosses chunk boundaries
under sequence parallelism: each rank's last target is the FIRST label
of the next rank's chunk. One ``ppermute`` of the leading label/mask
column delivers it; the final rank's trailing target is weight-masked.

Shared by every model family's ``loss_fn_sp`` (the shift is family-
independent). The reference has no SP at all (SURVEY.md §5), so this
logic has no analog there.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from pipegoose_tpu.distributed.functional import shift_left


def sp_shifted_targets(labels: jax.Array, attention_mask: jax.Array,
                       sp_axis: str):
    """(labels, mask) of shape (B, S_local) -> (shifted_labels,
    shifted_weights) aligned to next-token prediction across the
    sequence shards."""
    sp = jax.lax.axis_size(sp_axis)
    rank = jax.lax.axis_index(sp_axis)
    next_first_label = shift_left(labels[:, :1], sp_axis)  # (B, 1)
    next_first_w = shift_left(attention_mask[:, :1], sp_axis)
    shifted_labels = jnp.concatenate([labels[:, 1:], next_first_label], axis=1)
    shifted_w = jnp.concatenate([attention_mask[:, 1:], next_first_w], axis=1)
    is_last = rank == sp - 1
    shifted_w = shifted_w.at[:, -1].multiply(jnp.where(is_last, 0, 1))
    return shifted_labels, shifted_w
