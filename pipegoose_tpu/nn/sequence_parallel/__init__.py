from pipegoose_tpu.nn.sequence_parallel.ring_attention import (
    make_causal_alibi_bias_fn,
    ring_flash_attention,
    ring_attention,
)
from pipegoose_tpu.nn.sequence_parallel.ulysses import ulysses_attention

__all__ = ["ring_attention", "make_causal_alibi_bias_fn", "ulysses_attention"]
