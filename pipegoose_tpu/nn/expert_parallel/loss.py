"""MoE auxiliary-loss combination.

TPU-native analog of the reference's ``ExpertLoss`` + ``ExpertContext``
(pipegoose/nn/expert_parallel/loss.py:8-29, expert_context.py:7-32). The
reference accumulates aux/z losses in a process-global singleton pushed
during forward and popped by the loss wrapper — incompatible with pure
functions. Here model forwards RETURN their router losses (pytree of
RouterOutput or scalars) and ``ExpertLoss`` just folds them in.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ExpertLoss:
    """loss = task_loss + aux_weight * sum(aux) + z_weight * sum(z)
    (reference loss.py:25-29 semantics, functional plumbing)."""

    aux_loss_weight: float = 0.01
    z_loss_weight: float = 0.001

    def __call__(self, task_loss: jax.Array, aux_losses: Any, z_losses: Any) -> jax.Array:
        # SUM over layers/leaves, matching the reference's accumulate-
        # then-sum (expert_context pushes per layer, loss.py:25-29) and
        # Switch-Transformer hyperparameter conventions.
        aux = sum(jnp.asarray(a).sum() for a in jax.tree_util.tree_leaves(aux_losses))
        z = sum(jnp.asarray(a).sum() for a in jax.tree_util.tree_leaves(z_losses))
        return task_loss + self.aux_loss_weight * aux + self.z_loss_weight * z
