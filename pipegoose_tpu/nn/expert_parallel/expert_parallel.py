"""ExpertParallel: turn a dense model's MLPs into expert-parallel MoE.

TPU-native analog of the reference's ``ExpertParallel`` wrapper
(pipegoose/nn/expert_parallel/expert_parallel.py:13-83), which regex-
matches ``transformer.h.{i}.mlp`` modules and swaps them for an
ExpertLayer reusing the dense MLP as the expert template (:53-80). Here
the transform is on the params pytree: each (stacked) dense MLP kernel
is tiled into ``num_experts`` expert copies (optionally perturbed so
experts diverge), and a router gate is added — returning a new params
tree for the MoE model plus its PartitionSpecs.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pipegoose_tpu.distributed.parallel_context import ParallelContext
from pipegoose_tpu.nn.parallel import Parallel


class ExpertParallel(Parallel):
    """Expand BLOOM-style stacked dense MLP params into MoE params
    (mirrors the reference's template-copy semantics; ``jitter`` adds
    per-expert noise so tiled experts don't stay identical forever)."""

    def __init__(
        self,
        num_experts: int,
        expert_axis: str = "expert",
        tensor_axis: Optional[str] = "tensor",
        jitter: float = 0.0,
        parallel_context: Optional[ParallelContext] = None,
    ):
        super().__init__(parallel_context)
        self.num_experts = num_experts
        self.expert_axis = expert_axis
        self.tensor_axis = tensor_axis
        self.jitter = jitter
        ep_size = self.parallel_context.mesh.shape.get(expert_axis, 1)
        if num_experts % ep_size != 0:
            raise ValueError(
                f"num_experts={num_experts} must divide over expert axis "
                f"size {ep_size} (reference asserts num_experts % tp == 0, "
                "expert_parallel.py:34)"
            )

    def expand_mlp(self, mlp_params: dict, key: Optional[jax.Array] = None) -> dict:
        """(L, H, F) dense kernels -> (L, E, H, F) expert kernels."""
        E = self.num_experts

        def tile(x):
            out = jnp.broadcast_to(x[:, None], (x.shape[0], E) + x.shape[1:])
            return out

        experts = jax.tree_util.tree_map(tile, mlp_params)
        if self.jitter and key is not None:
            leaves, treedef = jax.tree_util.tree_flatten(experts)
            keys = jax.random.split(key, len(leaves))
            leaves = [
                x * (1 + self.jitter * jax.random.normal(k, x.shape, x.dtype))
                for x, k in zip(leaves, keys)
            ]
            experts = jax.tree_util.tree_unflatten(treedef, leaves)
        return experts

    def init_router(self, key: jax.Array, n_layer: int, hidden: int, dtype=jnp.float32) -> dict:
        return {
            "gate": {
                "kernel": (
                    jax.random.normal(key, (n_layer, hidden, self.num_experts)) * 0.02
                ).astype(dtype)
            }
        }

    def expert_specs(self) -> dict:
        from pipegoose_tpu.nn.expert_parallel.experts import expert_mlp_specs

        return expert_mlp_specs(self.expert_axis, self.tensor_axis)

    def from_dense(
        self, params: dict, key: jax.Array, hidden: Optional[int] = None
    ) -> dict:
        """Upcycle a dense BLOOM params tree into BLOOM-MoE params: the
        stacked dense MLP becomes the template for every expert
        (reference semantics: the ExpertLayer reuses the wrapped dense
        MLP, expert_parallel.py:53-80) and a fresh router gate is added."""
        kj, kr = jax.random.split(key)
        out = dict(params)
        blocks = dict(params["blocks"])
        mlp = blocks.pop("mlp")
        blocks["moe"] = self.expand_mlp(mlp, kj if self.jitter else None)
        n_layer = jax.tree_util.tree_leaves(mlp)[0].shape[0]
        if hidden is None:
            hidden = params["embed"]["weight"].shape[-1]
        dtype = jax.tree_util.tree_leaves(mlp)[0].dtype
        blocks["router"] = self.init_router(kr, n_layer, hidden, dtype)
        out["blocks"] = blocks
        return out

    def parallelize(self, params: Any):
        """Shard BLOOM-MoE params onto the mesh (reference API parity:
        TensorParallel-style wrapper entry, expert_parallel.py:13-83)."""
        from pipegoose_tpu.models.bloom_moe import moe_specs
        from pipegoose_tpu.nn.parallel import shard_tree

        specs = moe_specs(
            params, tp_axis=self.tensor_axis or "tensor", ep_axis=self.expert_axis
        )
        return shard_tree(params, specs, self.parallel_context), specs
