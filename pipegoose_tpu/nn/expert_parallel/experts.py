"""Expert computation with all_to_all dispatch.

TPU-native analog of the reference's ``Experts``/``ExpertLayer``
(pipegoose/nn/expert_parallel/experts.py:15-102, layers.py:26-48). The
reference holds num_experts/tp experts per rank and dispatches by
boolean ``nonzero`` index-selects followed by an all_reduce combine
(experts.py:41-80) — dynamic shapes, and every rank ships every token.
Here dispatch is the GShard dataflow with static shapes:

    local tokens --einsum dispatch--> (E, C, H)
    all_to_all over the expert axis  -> (E_local, ep*C, H)
    per-expert MLP (one batched einsum on the MXU)
    all_to_all back                  -> (E, C, H)
    --einsum combine--> local tokens

Only capacity-bounded expert inputs cross the network, and expert grads
stay local to the owning rank (the reference's ``is_expert``/EXPERT_DATA
bookkeeping, experts.py:35-39 + data_parallel.py:35-43, falls out of the
sharding specs instead).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from pipegoose_tpu.distributed.functional import all_to_all
from pipegoose_tpu.nn.expert_parallel.routers import RouterOutput, TopKRouter


def init_experts(
    key: jax.Array,
    num_local_experts: int,
    hidden: int,
    ffn: int,
    dtype=jnp.float32,
    std: float = 0.02,
) -> dict:
    """Expert-stacked MLP params: leading dim = local experts."""
    k1, k2 = jax.random.split(key)
    return {
        "up": {
            "kernel": (jax.random.normal(k1, (num_local_experts, hidden, ffn)) * std).astype(dtype),
            "bias": jnp.zeros((num_local_experts, ffn), dtype),
        },
        "down": {
            "kernel": (jax.random.normal(k2, (num_local_experts, ffn, hidden)) * std).astype(dtype),
            "bias": jnp.zeros((num_local_experts, hidden), dtype),
        },
    }


def expert_mlp_specs(expert_axis: str = "expert", tensor_axis: Optional[str] = "tensor"):
    """PartitionSpecs for stacked expert MLP params (L, E, in, out):
    experts over the expert axis, FFN dim Megatron-sharded over tensor.
    Single source consumed by bloom_moe.moe_specs and ExpertParallel."""
    from jax.sharding import PartitionSpec as P

    t = tensor_axis
    e = expert_axis
    return {
        "up": {"kernel": P(None, e, None, t), "bias": P(None, e, t)},
        "down": {"kernel": P(None, e, t, None), "bias": P(None, e, None)},
    }


def expert_mlp(
    params: dict,
    x: jax.Array,
    act: Callable = jax.nn.gelu,
    tp_axis: Optional[str] = None,
) -> jax.Array:
    """(E_local, S, H) -> (E_local, S, H), one batched einsum per matmul.

    With ``tp_axis``, each expert's FFN dim is additionally Megatron-
    sharded over the tensor axis (up column / down row + reduce) — the
    4D interaction the reference only gestures at via its
    num_experts % tp == 0 assert (expert_parallel.py:34)."""
    from pipegoose_tpu.distributed.functional import (
        copy_to_tensor_group,
        reduce_from_tensor_group,
    )

    if tp_axis is not None:
        # f-operator: identity fwd, psum bwd — without it each tensor
        # rank's input cotangent is only its local FFN-shard partial and
        # every grad upstream of the MoE layer de-syncs across ranks
        x = copy_to_tensor_group(x, tp_axis)
    h = jnp.einsum("esh,ehf->esf", x, params["up"]["kernel"],
                   preferred_element_type=jnp.float32).astype(x.dtype)
    h = act(h + params["up"]["bias"][:, None, :])
    out = jnp.einsum("esf,efh->esh", h, params["down"]["kernel"],
                     preferred_element_type=jnp.float32).astype(x.dtype)
    if tp_axis is not None:
        out = reduce_from_tensor_group(out, tp_axis)
    return out + params["down"]["bias"][:, None, :]


def moe_layer(
    expert_params: dict,
    x: jax.Array,  # (..., H) local tokens
    routing: RouterOutput,
    axis_name: Optional[str],
    act: Optional[Callable] = jax.nn.gelu,
    tp_axis: Optional[str] = None,
    mlp_fn: Optional[Callable] = None,
) -> jax.Array:
    """Dispatch -> expert MLP -> combine. ``expert_params`` hold this
    rank's E_local experts (stacked leading dim); ``routing`` covers the
    E = E_local * ep global experts."""
    orig_shape = x.shape
    h = x.reshape(-1, orig_shape[-1])  # (T, H)
    dispatch, combine = routing.dispatch, routing.combine
    E = dispatch.shape[1]
    e_local = jax.tree_util.tree_leaves(expert_params)[0].shape[0]
    ep = 1 if axis_name is None else jax.lax.axis_size(axis_name)
    if e_local * ep != E:
        raise ValueError(
            f"router has {E} experts but params hold {e_local} x ep={ep}"
        )

    # (T,H) -> (E, C, H): capacity-bucketed expert inputs
    buckets = jnp.einsum("tec,th->ech", dispatch.astype(h.dtype), h)
    if axis_name is not None and ep > 1:
        # each rank keeps its E_local experts, gains every rank's C slots
        buckets = all_to_all(buckets, axis_name, split_dim=0, concat_dim=1)
    if mlp_fn is not None:
        # custom per-expert computation, e.g. Mixtral's SwiGLU
        # (models/mixtral.py:_swiglu_experts)
        out = mlp_fn(expert_params, buckets, tp_axis)
    else:
        out = expert_mlp(expert_params, buckets, act, tp_axis=tp_axis)
    if axis_name is not None and ep > 1:
        out = all_to_all(out, axis_name, split_dim=1, concat_dim=0)
    # (E, C, H) -> (T, H), gate-weighted
    y = jnp.einsum("tec,ech->th", combine.astype(out.dtype), out)
    return y.reshape(orig_shape)
