"""MoE token routers.

TPU-native analog of the reference's router stack
(pipegoose/nn/expert_parallel/routers.py:18-189): gate projection,
Switch-style multiplicative training noise (SwitchNoisePolicy,
routers.py:18-34), softmax, top-k selection, Switch aux load-balancing
loss (:73-89), ST-MoE router z-loss (:91-97), and expert-capacity
truncation (:133-143).

The decisive difference is the OUTPUT: the reference returns a dynamic
dispatching order consumed by index_select loops (experts.py:99-102),
which cannot be jit-compiled. Here the router emits dense one-hot
dispatch/combine tensors with STATIC (tokens, experts, capacity) shapes
— the Mesh-TensorFlow/GShard formulation — so the whole MoE layer
compiles onto the MXU and the dispatch becomes two einsums around an
``all_to_all``.

Losses are returned functionally in ``RouterOutput`` (no process-global
ExpertContext singleton, expert_context.py:7-32).
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class RouterOutput(NamedTuple):
    dispatch: jax.Array  # (T, E, C) one-hot: token t -> slot c of expert e
    combine: jax.Array  # (T, E, C) gate-weighted dispatch
    aux_loss: jax.Array  # scalar, Switch load-balancing loss
    z_loss: jax.Array  # scalar, ST-MoE router z-loss


@dataclasses.dataclass(frozen=True)
class SwitchNoisePolicy:
    """Multiplicative jitter on router logits during training (reference
    routers.py:18-34): logits *= U[1-eps, 1+eps]."""

    eps: float = 0.1

    def apply(self, key: jax.Array, logits: jax.Array) -> jax.Array:
        noise = jax.random.uniform(
            key, logits.shape, logits.dtype, 1.0 - self.eps, 1.0 + self.eps
        )
        return logits * noise


@dataclasses.dataclass(frozen=True)
class TopKRouter:
    """k-choice router with capacity (reference _TopKRouter,
    routers.py:49-147). Call with the gate params and flat tokens."""

    num_experts: int
    top_k: int = 1
    capacity_factor: float = 1.25
    noise: Optional[SwitchNoisePolicy] = SwitchNoisePolicy()
    normalize_gates: bool = True  # for k > 1, renormalize kept gates

    def capacity(self, n_tokens: int) -> int:
        # ceil, per the GShard/Switch convention — floor would drop tokens
        # under perfectly balanced routing despite the headroom factor
        import math

        return max(1, math.ceil(n_tokens * self.top_k * self.capacity_factor / self.num_experts))

    def __call__(
        self,
        params: dict,
        x: jax.Array,  # (T, H) flat tokens
        key: Optional[jax.Array] = None,
        train: bool = False,
        capacity: Optional[int] = None,
    ) -> RouterOutput:
        T = x.shape[0]
        E, k = self.num_experts, self.top_k
        C = capacity if capacity is not None else self.capacity(T)

        logits = jnp.dot(
            x, params["gate"]["kernel"], preferred_element_type=jnp.float32
        )
        if "bias" in params["gate"]:
            logits = logits + params["gate"]["bias"]
        if train and self.noise is not None:
            if key is None:
                raise ValueError("train-time routing needs a PRNG key for noise")
            logits = self.noise.apply(key, logits)

        probs = jax.nn.softmax(logits, axis=-1)  # (T, E)

        # z-loss on the pre-softmax logits (reference routers.py:91-97)
        z = jax.nn.logsumexp(logits, axis=-1)
        z_loss = jnp.mean(z**2)

        # top-k expert choices per token, by decreasing priority
        gates, idx = jax.lax.top_k(probs, k)  # (T, k)
        masks = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # (T, k, E)

        # Switch aux loss: E * sum_e f_e * P_e, f_e = fraction of tokens
        # whose (any-priority) choice is e, P_e = mean router prob
        # (reference routers.py:73-89)
        f = masks.sum(axis=1).mean(axis=0) / k  # (E,)
        p = probs.mean(axis=0)  # (E,)
        aux_loss = E * jnp.sum(f * p)

        # capacity assignment: priority j slots come after all j' < j
        # (reference's cumsum-position truncation, routers.py:133-143)
        dispatch = jnp.zeros((T, E, C), dtype=jnp.float32)
        combine = jnp.zeros((T, E, C), dtype=jnp.float32)
        offset = jnp.zeros((E,), dtype=jnp.float32)
        kept_gates = []
        kept_slots = []
        for j in range(k):
            m = masks[:, j]  # (T, E)
            pos = jnp.cumsum(m, axis=0) - m + offset[None, :]  # (T, E)
            keep = (pos < C) * m  # (T, E)
            slot = jax.nn.one_hot(
                jnp.sum(pos * m, axis=-1).astype(jnp.int32), C, dtype=jnp.float32
            )  # (T, C) slot index of this token's choice
            d_j = keep[:, :, None] * slot[:, None, :]  # (T, E, C)
            dispatch = dispatch + d_j
            kept_gates.append(gates[:, j] * keep.sum(axis=-1))
            kept_slots.append(d_j)
            offset = offset + m.sum(axis=0)

        g = jnp.stack(kept_gates, axis=1)  # (T, k), zeros where dropped
        if self.normalize_gates and k > 1:
            g = g / jnp.maximum(g.sum(axis=1, keepdims=True), 1e-9)
        for j in range(k):
            combine = combine + g[:, j][:, None, None] * kept_slots[j]

        return RouterOutput(dispatch, combine, aux_loss, z_loss)


def Top1Router(num_experts: int, **kw) -> TopKRouter:
    """Switch-Transformer router (reference Top1Router, routers.py:150-168)."""
    return TopKRouter(num_experts=num_experts, top_k=1, **kw)


def Top2Router(num_experts: int, **kw) -> TopKRouter:
    """GShard-style 2-choice router (reference Top2Router, routers.py:171-189)."""
    return TopKRouter(num_experts=num_experts, top_k=2, **kw)
