from pipegoose_tpu.nn.expert_parallel.expert_parallel import ExpertParallel
from pipegoose_tpu.nn.expert_parallel.experts import expert_mlp, init_experts, moe_layer
from pipegoose_tpu.nn.expert_parallel.loss import ExpertLoss
from pipegoose_tpu.nn.expert_parallel.routers import (
    RouterOutput,
    SwitchNoisePolicy,
    Top1Router,
    Top2Router,
    TopKRouter,
)

__all__ = [
    "ExpertParallel",
    "expert_mlp",
    "init_experts",
    "moe_layer",
    "ExpertLoss",
    "RouterOutput",
    "SwitchNoisePolicy",
    "Top1Router",
    "Top2Router",
    "TopKRouter",
]
