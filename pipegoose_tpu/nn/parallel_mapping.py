"""Parallelization policy registry.

TPU-native analog of the reference's ``ParallelMapping`` / ``ParallelInfo``
(pipegoose/nn/parallel_mapping.py:10-37 and
nn/tensor_parallel/parallel_mapping.py:16-52). The reference substring-
matches module-name suffixes and mutates matching modules' classes in
place; here a policy maps *param-path* regexes to declarative roles, and
the roles translate to ``PartitionSpec`` entries — the params pytree is
never mutated, and the same table drives GSPMD auto-sharding, shard_map
manual sharding, and checkpoint resharding.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Optional, Sequence

from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ParallelInfo:
    """Role of one param tensor. ``spec`` gives the mesh-axis name (or
    None) for each array dimension."""

    role: str  # "column" | "row" | "vocab" | "replicate" | "expert" | custom
    spec: P


# roles for a kernel stored (in_features, out_features), JAX convention
def Column(axis: str = "tensor") -> ParallelInfo:
    """Shard OUT dim (reference ColumnParallelLinear weight dim-0 slice in
    torch's (out, in) layout, parallelizer.py:105-108)."""
    return ParallelInfo("column", P(None, axis))


def Row(axis: str = "tensor") -> ParallelInfo:
    """Shard IN dim (reference RowParallelLinear weight dim-1 slice,
    parallelizer.py:109-112)."""
    return ParallelInfo("row", P(axis, None))


def Vocab(axis: str = "tensor") -> ParallelInfo:
    """Shard vocab (dim 0) of an embedding table (reference
    EmbeddingParallelizer, parallelizer.py:114-170)."""
    return ParallelInfo("vocab", P(axis, None))


def Replicate() -> ParallelInfo:
    return ParallelInfo("replicate", P())


def Expert(axis: str = "expert") -> ParallelInfo:
    """Shard the leading num_experts dim over the expert axis."""
    return ParallelInfo("expert", P(axis, None, None))


class ParallelMapping:
    """Ordered (pattern -> ParallelInfo) table; first match wins,
    unmatched params replicate. Patterns are regexes over the
    '/'-joined param path (reference _search, parallel_mapping.py:12-37,
    which substring-matched the last two dotted name segments)."""

    def __init__(self, rules: Sequence[tuple[str, ParallelInfo]]):
        self.rules = [(re.compile(pat), info) for pat, info in rules]

    def search(self, path: str) -> Optional[ParallelInfo]:
        for pat, info in self.rules:
            if pat.search(path):
                return info
        return None

    def spec_for(self, path: str, ndim: Optional[int] = None) -> P:
        """PartitionSpec for a param. Pass ``ndim`` to get the rank-aware
        spec: a column layer shards its 1-d bias (it lives on the OUT
        dim) while a row layer replicates its bias, added after the
        all-reduce — the reference's slicing rules
        (parallelizer.py:105-112, linear.py:74-82)."""
        info = self.search(path)
        if info is None:
            return P()
        if ndim is None:
            return info.spec
        is_1d = ndim == 1
        if info.role == "column":
            return P(info.spec[1]) if is_1d else info.spec
        if info.role == "row":
            return P() if is_1d else info.spec
        if is_1d and len(info.spec) > 1:
            return P(*info.spec[:1])
        return info.spec

    # convenience predicates, mirroring the reference API
    # (parallel_mapping.py:40-74: is_column_parallel/is_row_parallel/...)
    def _role(self, path: str) -> Optional[str]:
        info = self.search(path)
        return info.role if info else None

    def is_column_parallel(self, path: str) -> bool:
        return self._role(path) == "column"

    def is_row_parallel(self, path: str) -> bool:
        return self._role(path) == "row"

    def is_vocab_parallel(self, path: str) -> bool:
        return self._role(path) == "vocab"

    def is_expert(self, path: str) -> bool:
        return self._role(path) == "expert"
