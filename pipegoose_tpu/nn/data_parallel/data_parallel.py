"""Data parallelism.

TPU-native analog of the reference's ``DataParallel``
(pipegoose/nn/data_parallel/data_parallel.py:13-43), which registers a
per-parameter grad hook doing ``grad.div_(dp); all_reduce(grad)`` — one
unbucketed collective per parameter. Here the whole gradient pytree is
averaged with ONE logical ``pmean`` per step inside the compiled program
(XLA fuses and schedules the underlying all-reduces), and the batch is
sharded over the ``data`` mesh axis so each device computes grads on its
own shard.

Expert parameters (flagged via the policy table, reference
data_parallel.py:35-43) are reduced over a different axis — see
``average_gradients``'s ``expert_mapping``/``expert_axis`` arguments.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
from jax import lax
from jax.tree_util import tree_map_with_path

from pipegoose_tpu.distributed.parallel_context import ParallelContext
from pipegoose_tpu.nn.parallel import Parallel, path_str
from pipegoose_tpu.nn.parallel_mapping import ParallelMapping


def average_gradients(
    grads: Any,
    axis_name: Optional[str] = "data",
    expert_mapping: Optional[ParallelMapping] = None,
    expert_axis: Optional[str] = None,
) -> Any:
    """pmean the grad pytree over the data axis. Params matched as
    ``expert`` by ``expert_mapping`` are averaged over ``expert_axis``
    instead (the reference's is_expert -> EXPERT_DATA routing,
    data_parallel.py:35-43); ``expert_axis=None`` leaves them local."""
    if axis_name is None:
        return grads

    def avg(path, g):
        if expert_mapping is not None and expert_mapping.is_expert(path_str(path)):
            if expert_axis is None:
                return g
            return lax.pmean(g, expert_axis)
        return lax.pmean(g, axis_name)

    return tree_map_with_path(avg, grads)


class DataParallel(Parallel):
    """Wrapper with the reference's API shape. ``parallelize`` is a no-op
    on params (replicas are identical by construction under jit);
    the real work is ``average_gradients`` inside the train step plus
    batch sharding via ``batch_spec``."""

    def __init__(
        self,
        parallel_context: Optional[ParallelContext] = None,
        axis_name: str = "data",
    ):
        super().__init__(parallel_context)
        self.axis_name = axis_name

    def parallelize(self, params: Any):
        from jax.sharding import PartitionSpec as P

        from pipegoose_tpu.nn.parallel import shard_tree, spec_tree

        specs = spec_tree(params, lambda _p, _x: P())
        return shard_tree(params, specs, self.parallel_context), specs

    def batch_spec(self):
        from jax.sharding import PartitionSpec as P

        return P(self.axis_name)

    def average_gradients(self, grads: Any, **kw) -> Any:
        return average_gradients(grads, self.axis_name, **kw)
