"""Data parallelism.

TPU-native analog of the reference's ``DataParallel``
(pipegoose/nn/data_parallel/data_parallel.py:13-43), which registers a
per-parameter grad hook doing ``grad.div_(dp); all_reduce(grad)`` — one
unbucketed collective per parameter. Here the whole gradient pytree is
averaged with ONE logical ``pmean`` per step inside the compiled program
(XLA fuses and schedules the underlying all-reduces), and the batch is
sharded over the ``data`` mesh axis so each device computes grads on its
own shard.

Expert parameters (flagged via the policy table, reference
data_parallel.py:35-43) are reduced over a different axis — see
``average_gradients``'s ``expert_mapping``/``expert_axis`` arguments.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
from jax import lax
from jax.tree_util import tree_map_with_path

from pipegoose_tpu.distributed.parallel_context import ParallelContext
from pipegoose_tpu.nn.parallel import Parallel, path_str
from pipegoose_tpu.nn.parallel_mapping import ParallelMapping


def average_gradients(
    grads: Any,
    axis_name: Optional[str] = "data",
    expert_mapping: Optional[ParallelMapping] = None,
    expert_axis: Optional[str] = None,
    grad_comm: str = "fp32",
) -> Any:
    """pmean the grad pytree over the data axis. Params matched as
    ``expert`` by ``expert_mapping`` are averaged over ``expert_axis``
    instead (the reference's is_expert -> EXPERT_DATA routing,
    data_parallel.py:35-43); ``expert_axis=None`` leaves them local.

    ``grad_comm``: wire precision of the data-axis mean — "fp32" (the
    plain pmean), "bf16", or "int8" (EQuARX-style compressed all-reduce,
    distributed/compressed.py; docs/comm.md). Expert grads always sync
    in fp32 (they are few and routing-sensitive)."""
    if axis_name is None:
        return grads

    from pipegoose_tpu.distributed.compressed import (
        check_grad_comm,
        compressed_all_reduce_mean,
    )

    mode = check_grad_comm(grad_comm)

    def avg(path, g):
        if expert_mapping is not None and expert_mapping.is_expert(path_str(path)):
            if expert_axis is None:
                return g
            return lax.pmean(g, expert_axis)
        if mode == "fp32":
            return lax.pmean(g, axis_name)
        return compressed_all_reduce_mean(g, axis_name, mode)[0]

    return tree_map_with_path(avg, grads)


class DataParallel(Parallel):
    """Wrapper with the reference's API shape. ``parallelize`` is a no-op
    on params (replicas are identical by construction under jit);
    the real work is ``average_gradients`` inside the train step plus
    batch sharding via ``batch_spec``."""

    def __init__(
        self,
        parallel_context: Optional[ParallelContext] = None,
        axis_name: str = "data",
    ):
        super().__init__(parallel_context)
        self.axis_name = axis_name

    def parallelize(self, params: Any):
        from jax.sharding import PartitionSpec as P

        from pipegoose_tpu.nn.parallel import shard_tree, spec_tree

        specs = spec_tree(params, lambda _p, _x: P())
        return shard_tree(params, specs, self.parallel_context), specs

    def batch_spec(self):
        from jax.sharding import PartitionSpec as P

        return P(self.axis_name)

    def average_gradients(self, grads: Any, **kw) -> Any:
        """Supports the same ``grad_comm=`` wire-precision selection as
        the module-level function (docs/comm.md)."""
        return average_gradients(grads, self.axis_name, **kw)
