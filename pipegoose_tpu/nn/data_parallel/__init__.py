from pipegoose_tpu.nn.data_parallel.data_parallel import DataParallel, average_gradients

__all__ = ["DataParallel", "average_gradients"]
