"""Public testing utilities.

Analog of the reference's ``pipegoose/testing/utils.py`` (spawn /
init_parallel_context / calculate_parameter_similarity, testing/
utils.py:32-117). The reference simulates a cluster by spawning N OS
processes over gloo/TCP; on TPU the same coverage comes from XLA's
fake-device flag — one process, N CPU devices, exercising the real
jit/shard_map code paths (SURVEY.md §4). These helpers are what the
repo's own test suite builds on (tests/conftest.py).
"""
from __future__ import annotations

from typing import Any

from pipegoose_tpu.testing.chaos import (  # noqa: F401
    ChaosMonkey,
    ChaosSchedule,
    Injection,
    TransientIOFault,
    TransientTransferFault,
    schedule_fingerprint,
    tear_checkpoint,
)
from pipegoose_tpu.testing.fake_cluster import (  # noqa: F401
    fake_cluster,
    set_fake_device_flags,
)

__all__ = [
    "ChaosMonkey",
    "ChaosSchedule",
    "Injection",
    "TransientIOFault",
    "TransientTransferFault",
    "schedule_fingerprint",
    "tear_checkpoint",
    "fake_cluster",
    "set_fake_device_flags",
    "force_cpu_devices",
    "old_jax_cpu_reason",
    "parameter_similarity",
    "assert_trees_allclose",
    "random_input_ids",
]


def old_jax_cpu_reason(feature: str = "this check") -> Any:
    """Non-None (a human-readable reason) when the running environment
    is jax < 0.5 on the CPU backend — the combination several tests can
    NEVER pass under (multiprocess collectives unimplemented, Pallas
    interpret-mode f32 reduction-order drift). The single shared
    predicate the test suite's environment-detection skips use."""
    import jax

    version = tuple(int(x) for x in jax.__version__.split(".")[:2])
    if version < (0, 5) and jax.default_backend() == "cpu":
        return (
            f"jax {jax.__version__} on the CPU backend cannot run "
            f"{feature} (needs jax >= 0.5 or a real TPU/GPU backend)"
        )
    return None


def force_cpu_devices(n: int = 8) -> None:
    """Pin the jax backend to ``n`` fake CPU devices.

    Back-compat alias of :func:`fake_cluster` (the reference's
    ``spawn``, testing/utils.py:32-41, plays this role with OS
    processes); new code should call ``fake_cluster`` directly for the
    returned device list and the ``require`` guard.
    """
    fake_cluster(n, require=False)


def parameter_similarity(tree_a: Any, tree_b: Any, rtol: float = 1e-3) -> float:
    """Fraction of leaves that are element-wise close — the reference's
    anti-false-positive guard (``calculate_parameter_similarity``,
    testing/utils.py:103-117): before asserting a parallelized run
    matches a reference run, assert the reference actually MOVED
    (similarity to its initial params < 1)."""
    import jax
    import numpy as np

    la = jax.tree_util.tree_leaves(tree_a)
    lb = jax.tree_util.tree_leaves(tree_b)
    if len(la) != len(lb):
        raise ValueError(f"tree sizes differ: {len(la)} vs {len(lb)}")
    close = sum(
        bool(np.allclose(np.asarray(a), np.asarray(b), rtol=rtol))
        for a, b in zip(la, lb)
    )
    return close / max(len(la), 1)


def assert_trees_allclose(
    got: Any, want: Any, rtol: float = 1e-5, atol: float = 1e-6, prefix: str = ""
) -> None:
    """np.testing.assert_allclose over two pytrees, leaf by leaf, with
    the tree path in the failure message. Tree structures must match —
    a silent zip over mismatched trees would truncate to the shorter."""
    import jax
    import numpy as np

    ts_got = jax.tree_util.tree_structure(got)
    ts_want = jax.tree_util.tree_structure(want)
    if ts_got != ts_want:
        raise AssertionError(
            f"{prefix}tree structures differ: {ts_got} vs {ts_want}"
        )
    for (path, w), g in zip(
        jax.tree_util.tree_leaves_with_path(want), jax.tree_util.tree_leaves(got)
    ):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=rtol, atol=atol,
            err_msg=f"{prefix}{jax.tree_util.keystr(path)}",
        )


def random_input_ids(vocab_size: int, shape: tuple, seed: int = 0):
    """Deterministic token batch (reference ``get_microbatch``,
    testing/utils.py:123-133, without the datasets dependency)."""
    import jax.numpy as jnp
    import numpy as np

    return jnp.asarray(np.random.RandomState(seed).randint(0, vocab_size, shape))
