"""Fake-cluster bootstrap: one process, N CPU devices, real SPMD paths.

The ONE way this repo simulates a TPU slice on a host: XLA's
``--xla_force_host_platform_device_count`` flag plus a ``jax_platforms``
pin, so jit/shard_map programs compile and run against a real N-device
mesh without hardware (the reference spawned N OS processes over
gloo/TCP instead — testing/utils.py:32-41; SURVEY.md §4). Previously
copy-pasted between bench.py, tests/conftest.py, the mesh-doctor CLI,
and every example; now bench, the parallelism planner
(pipegoose_tpu/planner/), the CLIs, and the test suite all call here.

Two entry points, split by WHEN they may run:

- :func:`set_fake_device_flags` — pure ``XLA_FLAGS`` env mutation,
  never imports jax. The only piece that must run before the backend
  initializes; safe (and required) in a conftest/module prologue.
- :func:`fake_cluster` — flags + ``jax_platforms="cpu"`` config pin
  (env vars alone are not enough once an accelerator plugin's
  sitecustomize registered itself) and returns the device list. The
  one-call form for scripts, benches, and examples.
"""
from __future__ import annotations

import os
import re
from typing import List

_COUNT_FLAG = "xla_force_host_platform_device_count"


def set_fake_device_flags(n: int = 8, override: bool = True) -> None:
    """Put ``--xla_force_host_platform_device_count=n`` into XLA_FLAGS.

    Env mutation only — jax is not imported, so this is safe at any
    point before the first backend touch. ``override=False`` keeps an
    existing count (the test-suite convention: an operator-set
    XLA_FLAGS wins over the conftest default).
    """
    flag = f"--{_COUNT_FLAG}={n}"
    flags = os.environ.get("XLA_FLAGS", "")
    if _COUNT_FLAG in flags:
        if override:
            flags = re.sub(rf"--{_COUNT_FLAG}=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags


def fake_cluster(n: int = 8, require: bool = False,
                 override: bool = True) -> List:
    """Pin the jax backend to ``n`` fake CPU devices and return them.

    Must run before the first backend touch. Handles the environments
    where a sitecustomize pins ``jax_platforms`` to an accelerator
    plugin (the config update works where env vars alone do not).
    ``require=True`` raises if the backend came up with fewer than
    ``n`` devices — i.e. it was already initialized with other flags —
    instead of silently planning/benching on the wrong mesh.
    ``override=False`` keeps an operator-set device count in XLA_FLAGS
    (see :func:`set_fake_device_flags`); ``n`` is then only the
    default.
    """
    kept_existing = not override and _COUNT_FLAG in os.environ.get(
        "XLA_FLAGS", "")
    set_fake_device_flags(n, override=override)
    import jax

    jax.config.update("jax_platforms", "cpu")
    if not kept_existing:  # don't fight an operator-set count
        try:
            jax.config.update("jax_num_cpu_devices", n)
        except Exception:  # noqa: BLE001 - backend already up / older jax
            pass
    devices = jax.devices()
    if require and len(devices) < n:
        raise RuntimeError(
            f"fake_cluster({n}) got {len(devices)} device(s) — the jax "
            f"backend was initialized before the fake-device flags were "
            f"set (call fake_cluster/set_fake_device_flags earlier)"
        )
    return devices
