"""Deterministic chaos harness: inject the failures that end real runs.

Every large training/serving deployment eventually meets the same five
killers: a slice loses devices (preemption, hardware fault), gradients
go non-finite, a host stalls, a checkpoint write is torn by a kill, and
storage throws transient I/O errors. Production code paths for
surviving them exist in this repo (``AutoRecovery``/``ElasticRecovery``,
the flight recorder, crash-atomic checkpoints, the serving stall
watchdog) — but a recovery path that is never EXERCISED is a recovery
path that is broken. This module is the exerciser:

- :class:`ChaosSchedule` — a SEEDED, byte-reproducible injection plan:
  the same seed always yields the identical list of
  :class:`Injection` (step, kind, args), pinned by
  ``to_json()`` equality in tests. Determinism is the whole point —
  a chaos failure that cannot be replayed cannot be debugged.
- :class:`ChaosMonkey` — the executor. As a trainer ``Callback`` it
  applies training injections at their scheduled step; as a serving
  ``tick_hook`` (:meth:`ChaosMonkey.tick_hook`) it applies serving
  injections at their scheduled engine tick. Every application is
  logged to the attached ``FlightRecorder`` ring (kind
  ``chaos.injection``), so a post-mortem black box records what was
  INJECTED next to what was DETECTED.

Injection kinds (``KINDS``):

``device_loss``      simulate losing ``n_lose`` devices of the current
                     mesh (the fake-cluster analog of a slice
                     preemption): fires a structured ``device_loss``
                     flight-recorder trigger whose details name the
                     lost and surviving device ids —
                     ``ElasticRecovery`` (trainer/elastic.py) consumes
                     it and reshards onto the survivors. Requires a
                     recorder (the trigger IS the signal path).
``nonfinite_grads``  overwrite one leaf of a named module group's
                     params with ``inf`` before the step runs — the
                     loss and gradients that step go non-finite, the
                     health reduction/loss canary trips, and recovery
                     rolls back (the checkpointed state is clean; the
                     corruption never survives the restore).
``host_stall``       ``time.sleep(stall_s)`` — a GC pause, a noisy
                     neighbor, an NFS hiccup. Shows up in the fenced
                     step time (flight recorder) and the serving
                     ``decode_gap_seconds`` histogram the SLO monitor
                     watches.
``torn_checkpoint``  tear the NEWEST complete checkpoint under
                     ``checkpoint_dir`` the way a kill mid-save would
                     have before the atomic-rename contract: its
                     contents are replaced by a partial stub, so
                     ``latest_step`` still lists it but restore fails
                     — exercising ``AutoRecovery``'s older-checkpoint
                     fallback.
``ckpt_io_error``    arm ``utils/checkpoint.py``'s save-attempt fault
                     hook with ``fail_times`` transient ``OSError``s —
                     the bounded-retry+backoff path must absorb them.
``replica_crash``    arm the serving-engine fault seam on one fleet
                     replica (``ServingEngine.inject_fault("crash")``)
                     — its next ``tick_once`` raises ``ReplicaFault``;
                     the control plane's health state machine must
                     quarantine it and SALVAGE its admitted requests
                     (serving/control_plane/plane.py).
``replica_wedge``    same seam, ``"wedge"``: the replica's ticks return
                     without doing work — alive on the wire, dead in
                     fact — exercising the heartbeat's
                     SUSPECT -> FAILED ladder instead of the crash
                     shortcut.
``transfer_flap``    arm the disagg transfer fault seam
                     (serving/disagg/transfer.py ``set_transfer_fault``)
                     with ``fail_times`` transient ``TransferError``s —
                     each failed shipment must abort its staging and
                     fall back to a local re-prefill on the decode
                     pool, token-identically.
``host_tier_io_error``  arm the host KV-tier fault seam
                     (serving/kv_tier/host_tier.py
                     ``set_host_tier_fault``) with ``fail_times``
                     transient ``HostTierError``s on RESTORE — each
                     failed restore must degrade to recompute
                     (token-identically, one consumed
                     ``kv_tier_fallback`` black box), never stall or
                     lose the request.
``page_leak``        take one EXTRA pool reference on a live KV page,
                     owner-tagged ``("chaos", "page_leak")`` — the
                     classic lost-owner leak: the page survives its
                     real owner's release forever. Nothing crashes and
                     conservation stays exact (the reference is real);
                     only the memory ledger's ``audit()``
                     (telemetry/memledger.py) can catch it — one
                     ``memory_leak`` black box naming the page, the
                     chaos owner tag, and the ownership trail.
``stranded_reservation``  silently inflate the scheduler's admission
                     ledger (``_outstanding_total``) by ``pages`` —
                     phantom reserved pages no request backs, shrinking
                     every future admission's headroom. Detected by
                     ``audit()``'s reservation cross-check
                     (``stranded_reservation`` black box), not by any
                     crash.

Host-side by design (and jit-safety-allowlisted): injections run in
callback/tick context, never inside compiled code.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

KINDS: Tuple[str, ...] = (
    "device_loss",
    "nonfinite_grads",
    "host_stall",
    "torn_checkpoint",
    "ckpt_io_error",
    # fleet-serving kinds (appended, never inserted: the seeded draw
    # order follows this tuple, so adding a kind must not perturb the
    # steps of kinds drawn before it — byte-determinism pin)
    "replica_crash",
    "replica_wedge",
    "transfer_flap",
    "host_tier_io_error",
    "page_leak",
    "stranded_reservation",
)

#: kinds applied by the serving tick hook (matched on engine tick
#: number); the rest are trainer-callback injections (matched on step)
SERVING_KINDS: Tuple[str, ...] = ("host_stall", "transfer_flap",
                                  "host_tier_io_error", "page_leak",
                                  "stranded_reservation")

#: kinds applied by the FLEET hook (``ControlPlane.run(tick_hook=
#: monkey.fleet_hook)``), matched on the control-plane tick number
FLEET_KINDS: Tuple[str, ...] = (
    "replica_crash",
    "replica_wedge",
    "transfer_flap",
    "host_stall",
    "host_tier_io_error",
)


@dataclasses.dataclass(frozen=True)
class Injection:
    """One scheduled fault: fires when the run reaches ``step`` (train
    step for callback injections, engine tick for serving ones)."""

    step: int
    kind: str
    args: Tuple[Tuple[str, Any], ...] = ()

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown chaos kind {self.kind!r} "
                             f"(expected one of {KINDS})")
        if self.step < 1:
            raise ValueError(f"injection step must be >= 1, got {self.step}")

    @property
    def kwargs(self) -> Dict[str, Any]:
        return dict(self.args)

    def to_json(self) -> dict:
        return {"step": self.step, "kind": self.kind, "args": self.kwargs}


def _args(**kw: Any) -> Tuple[Tuple[str, Any], ...]:
    return tuple(sorted(kw.items()))


class ChaosSchedule:
    """An ordered, deterministic injection plan.

    Build explicitly (``ChaosSchedule([Injection(...), ...])``) for
    surgical tests, or via :meth:`seeded` for randomized-but-replayable
    chaos: the same ``(seed, max_step, counts, params)`` always
    produces the byte-identical plan (``to_json`` equality is the
    test-pinned contract — NOT "similar", identical). Injections are
    sorted by (step, kind) so even hand-built schedules iterate
    deterministically.
    """

    def __init__(self, injections: Sequence[Injection], seed: Optional[int] = None,
                 max_step: Optional[int] = None):
        self.injections: List[Injection] = sorted(
            injections, key=lambda i: (i.step, i.kind)
        )
        self.seed = seed
        self.max_step = max_step
        self._by_step: Dict[int, List[Injection]] = {}
        for inj in self.injections:
            self._by_step.setdefault(inj.step, []).append(inj)

    @classmethod
    def seeded(
        cls,
        seed: int,
        max_step: int,
        *,
        device_loss: int = 0,
        nonfinite_grads: int = 0,
        host_stall: int = 0,
        torn_checkpoint: int = 0,
        ckpt_io_error: int = 0,
        replica_crash: int = 0,
        replica_wedge: int = 0,
        transfer_flap: int = 0,
        host_tier_io_error: int = 0,
        page_leak: int = 0,
        stranded_reservation: int = 0,
        n_lose: int = 1,
        module_groups: Sequence[str] = ("embed",),
        stall_s: float = 0.05,
        fail_times: int = 1,
        n_replicas: int = 2,
        flap_times: int = 1,
        strand_pages: int = 1,
        min_step: int = 1,
    ) -> "ChaosSchedule":
        """Draw ``<kind>=count`` injections at distinct steps in
        ``[min_step, max_step]`` from a seeded RNG. Draw ORDER is fixed
        (the ``KINDS`` tuple order), so adding a kind to a schedule
        never perturbs the steps of kinds drawn before it."""
        if max_step < min_step:
            raise ValueError(f"max_step {max_step} < min_step {min_step}")
        rng = np.random.RandomState(seed)
        counts = {
            "device_loss": device_loss,
            "nonfinite_grads": nonfinite_grads,
            "host_stall": host_stall,
            "torn_checkpoint": torn_checkpoint,
            "ckpt_io_error": ckpt_io_error,
            "replica_crash": replica_crash,
            "replica_wedge": replica_wedge,
            "transfer_flap": transfer_flap,
            "host_tier_io_error": host_tier_io_error,
            "page_leak": page_leak,
            "stranded_reservation": stranded_reservation,
        }
        span = max_step - min_step + 1
        total = sum(counts.values())
        if total > span:
            raise ValueError(
                f"{total} injections do not fit in steps "
                f"[{min_step}, {max_step}] (one per step)"
            )
        # distinct steps across ALL kinds: two injections on one step
        # would make the application order (and thus the failure mode)
        # depend on dict iteration instead of the schedule
        steps = min_step + rng.choice(span, size=total, replace=False)
        injections: List[Injection] = []
        i = 0
        for kind in KINDS:
            for _ in range(counts[kind]):
                step = int(steps[i])
                i += 1
                if kind == "device_loss":
                    args = _args(n_lose=int(n_lose))
                elif kind == "nonfinite_grads":
                    group = module_groups[int(rng.randint(len(module_groups)))]
                    args = _args(module_group=str(group))
                elif kind == "host_stall":
                    args = _args(stall_s=float(stall_s))
                elif kind == "torn_checkpoint":
                    args = _args()
                elif kind == "ckpt_io_error":
                    args = _args(fail_times=int(fail_times))
                elif kind in ("replica_crash", "replica_wedge"):
                    # victim drawn per injection: the index is resolved
                    # modulo the LIVE candidates at fire time, so the
                    # same schedule applies to any fleet size
                    args = _args(replica=int(rng.randint(n_replicas)))
                elif kind == "transfer_flap":
                    args = _args(fail_times=int(flap_times))
                elif kind == "host_tier_io_error":
                    # shares flap_times: both are transient wire
                    # faults with a retry budget
                    args = _args(fail_times=int(flap_times))
                elif kind == "page_leak":
                    # victim drawn per injection, resolved modulo the
                    # LIVE allocated pages at fire time (same contract
                    # as the replica-fault victim index)
                    args = _args(page_index=int(rng.randint(4096)))
                else:  # stranded_reservation
                    args = _args(pages=int(strand_pages))
                injections.append(Injection(step, kind, args))
        return cls(injections, seed=seed, max_step=max_step)

    def at(self, step: int) -> List[Injection]:
        return self._by_step.get(step, [])

    def to_json(self) -> dict:
        return {
            "seed": self.seed,
            "max_step": self.max_step,
            "injections": [i.to_json() for i in self.injections],
        }

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ChaosSchedule) and (
            self.to_json() == other.to_json()
        )

    def __len__(self) -> int:
        return len(self.injections)

    def __repr__(self) -> str:
        return (f"ChaosSchedule(seed={self.seed}, "
                f"{len(self.injections)} injection(s))")


class TransientIOFault:
    """Save-attempt fault: raises ``OSError`` for the first ``times``
    calls, then passes — what ``ckpt_io_error`` arms on
    ``utils/checkpoint.py``'s :func:`~pipegoose_tpu.utils.checkpoint.
    set_io_fault_hook` seam."""

    def __init__(self, times: int):
        self.remaining = int(times)
        self.fired = 0

    def __call__(self) -> None:
        if self.remaining > 0:
            self.remaining -= 1
            self.fired += 1
            raise OSError(
                f"chaos: injected transient checkpoint I/O error "
                f"({self.fired} so far)"
            )


class TransientTransferFault:
    """Shipment-import fault: raises ``TransferError`` for the first
    ``times`` imports, then passes — what ``transfer_flap`` arms on
    serving/disagg/transfer.py's :func:`set_transfer_fault` seam. The
    hook signature is the seam's ``(kind, uid, n_pages)``."""

    def __init__(self, times: int):
        self.remaining = int(times)
        self.fired = 0

    def __call__(self, kind: str, uid: int, n_pages: int) -> None:
        if self.remaining > 0:
            from pipegoose_tpu.serving.disagg.transfer import TransferError

            self.remaining -= 1
            self.fired += 1
            raise TransferError(
                f"chaos: injected transfer flap on {kind} of uid={uid} "
                f"({n_pages} pages, {self.fired} so far)"
            )


class TransientHostTierFault:
    """Host-tier restore fault: raises ``HostTierError`` for the first
    ``times`` RESTORE ops, then passes — what ``host_tier_io_error``
    arms on serving/kv_tier/host_tier.py's :func:`set_host_tier_fault`
    seam. Spills pass through untouched (a dropped spill would just
    shrink the tier — the interesting contract is the restore-side
    degrade-to-recompute). Hook signature is the seam's
    ``(op, key, n_pages)``."""

    def __init__(self, times: int):
        self.remaining = int(times)
        self.fired = 0

    def __call__(self, op: str, key: Any, n_pages: int) -> None:
        if op != "restore":
            return
        if self.remaining > 0:
            from pipegoose_tpu.serving.kv_tier.host_tier import HostTierError

            self.remaining -= 1
            self.fired += 1
            raise HostTierError(
                f"chaos: injected host-tier I/O error on restore of "
                f"{len(key)}-token prefix ({self.fired} so far)"
            )


def tear_checkpoint(directory: str) -> Optional[str]:
    """Replace the newest COMPLETE checkpoint's contents with a partial
    stub — the on-disk state a kill mid-save used to leave before the
    atomic-rename contract. ``latest_step`` (which trusts the rename
    commit point) still lists it, restore fails, and recovery must fall
    back to the next-older checkpoint. Returns the torn path (None when
    there is nothing to tear)."""
    import shutil

    from pipegoose_tpu.utils.checkpoint import available_steps

    steps = available_steps(directory)
    if not steps:
        return None
    path = os.path.join(os.path.abspath(directory), f"step_{steps[0]}")
    shutil.rmtree(path)
    os.makedirs(path)
    with open(os.path.join(path, "TORN"), "w") as f:
        f.write("chaos: simulated torn checkpoint write\n")
    return path


class ChaosMonkey:
    """Apply a :class:`ChaosSchedule` to a live run.

    Duck-typed trainer callback (the full ``trainer.Callback`` hook
    surface, without inheriting it): this module must stay importable
    through ``pipegoose_tpu.testing`` BEFORE the jax backend
    initializes — the conftest imports the fake-cluster flags through
    the same package — and the trainer package pulls in jax at import.

    Trainer wiring: add to ``callbacks`` next to the ``FlightRecorder``
    and the recovery callback — order -30 runs it before the recorder
    (-20) records the step and before the detector (-10) reacts, so an
    injection and its detection land in the same step's callback round.
    Training injections match ``Injection.step`` against the step
    number ``on_step_start`` receives (the step about to run).

    Serving wiring: pass ``monkey.tick_hook`` as
    ``ServingEngine.run(tick_hook=...)`` — serving-capable kinds
    (``SERVING_KINDS``) match against the engine tick number instead.

    ``recorder``: the ``FlightRecorder`` every application is logged to
    (ring kind ``chaos.injection``) and through which ``device_loss``
    fires its structured trigger. ``checkpoint_dir``: where
    ``torn_checkpoint`` looks for its victim (defaults to nothing —
    the injection is skipped with a logged record naming why).
    """

    order = -30  # before FlightRecorder (-20) and FailureDetector (-10)

    def __init__(
        self,
        schedule: ChaosSchedule,
        recorder: Optional[Any] = None,
        checkpoint_dir: Optional[str] = None,
    ):
        self.schedule = schedule
        self.recorder = recorder
        self.checkpoint_dir = checkpoint_dir
        self.applied: List[Injection] = []
        self.io_faults: List[TransientIOFault] = []
        self.transfer_faults: List[TransientTransferFault] = []
        self.tier_faults: List[TransientHostTierFault] = []
        # hooks installed before our first arm — disarm restores them,
        # so the monkey never clobbers an externally installed fault
        # seam (one flag per seam: ckpt I/O and disagg transfer)
        self._prev_hook: Optional[Any] = None
        self._armed = False
        self._prev_xfer_hook: Optional[Any] = None
        self._xfer_armed = False
        self._prev_tier_hook: Optional[Any] = None
        self._tier_armed = False
        # fire-once bookkeeping: recovery REWINDS the step counter, so
        # the steps after a rollback replay through the schedule again —
        # re-injecting would make every recovery replay its own cause
        # (and a device_loss would compound: 8→4→0). An injection is an
        # EVENT, not a property of a step number.
        self._done: set = set()

    # -- logging -----------------------------------------------------------

    def _log(self, inj: Injection, **extra: Any) -> None:
        self.applied.append(inj)
        if self.recorder is not None:
            # the injection's kind rides as `injection` — `kind` is the
            # ring record's own discriminator ("chaos.injection")
            self.recorder.record(
                "chaos.injection", step=inj.step, injection=inj.kind,
                **inj.kwargs, **extra,
            )

    # -- trainer-side applications -----------------------------------------

    def _apply_nonfinite(self, trainer: Any, inj: Injection) -> None:
        import jax
        import jax.numpy as jnp

        group = inj.kwargs["module_group"]
        params = trainer.params
        if group not in params:
            raise KeyError(
                f"chaos nonfinite_grads: no module group {group!r} in "
                f"params (have {sorted(params)})"
            )
        sub = params[group]
        leaves, treedef = jax.tree_util.tree_flatten(sub)
        # one leaf is enough: the inf propagates to the loss and the
        # whole grad tree within the step
        leaves[0] = jnp.full_like(leaves[0], jnp.inf)
        new_params = dict(params)
        new_params[group] = jax.tree_util.tree_unflatten(treedef, leaves)
        trainer.params = new_params
        self._log(inj)

    def _apply_device_loss(self, trainer: Any, inj: Injection) -> None:
        if self.recorder is None:
            raise RuntimeError(
                "chaos device_loss needs a FlightRecorder: the "
                "structured trigger it fires is how ElasticRecovery "
                "learns WHICH devices died"
            )
        n_lose = int(inj.kwargs.get("n_lose", 1))
        devices = list(trainer.parallel_context.mesh.devices.reshape(-1))
        if n_lose >= len(devices):
            raise ValueError(
                f"chaos device_loss: n_lose={n_lose} would leave no "
                f"survivors out of {len(devices)} devices"
            )
        # deterministic victim choice: the TRAILING devices — on the
        # (pipe, data, ..., tensor) mesh order that is a whole trailing
        # slab of the data axis, i.e. "a slice went away"
        lost, surviving = devices[-n_lose:], devices[:-n_lose]
        details = {
            "lost_device_ids": [int(d.id) for d in lost],
            "surviving_device_ids": [int(d.id) for d in surviving],
            "n_lost": n_lose,
            "n_surviving": len(surviving),
        }
        self._log(inj, **details)
        self.recorder.fire_trigger(
            "device_loss",
            f"lost {n_lose} of {len(devices)} devices "
            f"(ids {details['lost_device_ids']}); "
            f"{len(surviving)} surviving",
            inj.step,
            details=details,
        )

    def _apply_torn_checkpoint(self, inj: Injection) -> None:
        if self.checkpoint_dir is None:
            self._log(inj, skipped="no checkpoint_dir configured")
            return
        torn = tear_checkpoint(self.checkpoint_dir)
        if torn is None:
            self._log(inj, skipped="no complete checkpoint to tear")
            return
        self._log(inj, torn_path=torn)

    def _apply_ckpt_io_error(self, inj: Injection) -> None:
        from pipegoose_tpu.utils.checkpoint import set_io_fault_hook

        fault = TransientIOFault(int(inj.kwargs.get("fail_times", 1)))
        self.io_faults.append(fault)
        prev = set_io_fault_hook(fault)
        if not self._armed:  # remember only the EXTERNAL hook
            self._prev_hook = prev
            self._armed = True
        self._log(inj)

    def _apply_host_stall(self, inj: Injection) -> None:
        time.sleep(float(inj.kwargs.get("stall_s", 0.05)))
        self._log(inj)

    # -- fleet-serving applications ----------------------------------------

    def _apply_transfer_flap(self, inj: Injection) -> None:
        from pipegoose_tpu.serving.disagg.transfer import set_transfer_fault

        fault = TransientTransferFault(int(inj.kwargs.get("fail_times", 1)))
        self.transfer_faults.append(fault)
        prev = set_transfer_fault(fault)
        if not self._xfer_armed:  # remember only the EXTERNAL hook
            self._prev_xfer_hook = prev
            self._xfer_armed = True
        self._log(inj)

    def _apply_host_tier_io_error(self, inj: Injection) -> None:
        from pipegoose_tpu.serving.kv_tier.host_tier import (
            set_host_tier_fault,
        )

        fault = TransientHostTierFault(int(inj.kwargs.get("fail_times", 1)))
        self.tier_faults.append(fault)
        prev = set_host_tier_fault(fault)
        if not self._tier_armed:  # remember only the EXTERNAL hook
            self._prev_tier_hook = prev
            self._tier_armed = True
        self._log(inj)

    def _apply_page_leak(self, engine: Any, inj: Injection) -> None:
        pool = engine.pool
        allocated = sorted(pool._ref)
        if not allocated:
            self._log(inj, skipped="no allocated page to leak")
            return
        page = allocated[int(inj.kwargs.get("page_index", 0))
                         % len(allocated)]
        # a REAL extra reference through the pool's own API (the ledger
        # mirrors it under the chaos owner tag), with no owner that
        # will ever release it — conservation stays exact; only the
        # ledger's audit() refcount-vs-holders cross-check can tell
        if pool.ledger is not None:
            pool.tag = ("chaos", "page_leak")
        pool.share([page])
        self._log(inj, page=int(page))

    def _apply_stranded_reservation(self, engine: Any,
                                    inj: Injection) -> None:
        n = int(inj.kwargs.get("pages", 1))
        # silent admission-ledger inflation: no pool traffic, no
        # crash — n phantom pages every future admission pays for,
        # visible only to audit()'s reservation cross-check
        engine.sched._outstanding_total += n
        self._log(inj)   # `pages` already rides in inj.kwargs

    def _apply_replica_fault(self, plane: Any, inj: Injection,
                             kind: str) -> None:
        from pipegoose_tpu.serving.control_plane.replica import ReplicaState

        victims = [r for r in plane.replicas
                   if r.state in (ReplicaState.SERVING,
                                  ReplicaState.SUSPECT,
                                  ReplicaState.DRAINING)]
        if not victims:
            self._log(inj, skipped="no live replica to fault")
            return
        victim = victims[int(inj.kwargs.get("replica", 0)) % len(victims)]
        victim.engine.inject_fault(kind)
        # `victim`, not `replica`: the injection's own arg (the drawn
        # index) already rides the record as `replica`
        self._log(inj, victim=victim.name, fault=kind)

    # -- trainer callback interface (duck-typed, see class docstring) ------

    def on_fit_start(self, trainer: Any) -> None:
        pass

    def on_checkpoint(self, trainer: Any, step: int, path: str) -> None:
        pass

    def _take(self, step: int, kinds: Tuple[str, ...]) -> List[Injection]:
        """Injections of ``kinds`` due at ``step`` that have not fired
        yet, marked fired (fire-once: steps replayed after a recovery
        rewind must not re-inject). ``kinds`` scopes the claim to what
        the calling hook actually applies — claiming a kind another
        hook owns would silently swallow it."""
        due = [i for i in self.schedule.at(step)
               if i.kind in kinds and id(i) not in self._done]
        self._done.update(id(i) for i in due)
        return due

    def on_step_start(self, trainer: Any, step: int) -> None:
        # step numbering: on_step_start receives trainer.state.step (the
        # 0-based count of COMPLETED steps); Injection.step is 1-based
        # "the N-th step about to run", matching the step number
        # on_step_end and the flight recorder see for the same step
        for inj in self._take(step + 1, ("nonfinite_grads", "host_stall",
                                         "torn_checkpoint", "ckpt_io_error")):
            if inj.kind == "nonfinite_grads":
                self._apply_nonfinite(trainer, inj)
            elif inj.kind == "host_stall":
                self._apply_host_stall(inj)
            elif inj.kind == "torn_checkpoint":
                self._apply_torn_checkpoint(inj)
            else:  # ckpt_io_error
                self._apply_ckpt_io_error(inj)
            # device_loss fires at step END (below): the step in flight
            # when the slice dies still runs — and is then rolled back,
            # exactly like the real event

    def on_step_end(self, trainer: Any, step: int, loss: Any) -> None:
        for inj in self._take(step, ("device_loss",)):
            self._apply_device_loss(trainer, inj)

    def on_fit_end(self, trainer: Any) -> None:
        self.disarm()

    def on_fit_abort(self, trainer: Any, exc: BaseException) -> None:
        # fit raising (budget exhaustion, a non-recoverable injection)
        # must not leak an armed fault into the NEXT run in the process
        self.disarm()

    def disarm(self) -> None:
        """Restore the pre-arm checkpoint-I/O and transfer fault hooks
        (idempotent) — a schedule's faults cannot outlive the run that
        armed them, and an externally installed hook is put back, not
        clobbered."""
        from pipegoose_tpu.utils.checkpoint import set_io_fault_hook

        if self._armed:
            set_io_fault_hook(self._prev_hook)
            self._prev_hook = None
            self._armed = False
        if self._xfer_armed:
            from pipegoose_tpu.serving.disagg.transfer import (
                set_transfer_fault,
            )

            set_transfer_fault(self._prev_xfer_hook)
            self._prev_xfer_hook = None
            self._xfer_armed = False
        if self._tier_armed:
            from pipegoose_tpu.serving.kv_tier.host_tier import (
                set_host_tier_fault,
            )

            set_host_tier_fault(self._prev_tier_hook)
            self._prev_tier_hook = None
            self._tier_armed = False

    # -- serving tick hooks ------------------------------------------------

    def tick_hook(self, engine: Any, tick: int) -> None:
        """``ServingEngine.run(tick_hook=...)`` /
        ``DisaggEngine.run(tick_hook=...)`` seam: apply serving-capable
        injections whose ``step`` matches the engine tick. One method
        instead of a lambda so tests can pass the monkey around
        whole."""
        for inj in self._take(tick, SERVING_KINDS):
            if inj.kind == "host_stall":
                self._apply_host_stall(inj)
            elif inj.kind == "host_tier_io_error":
                self._apply_host_tier_io_error(inj)
            elif inj.kind == "page_leak":
                self._apply_page_leak(engine, inj)
            elif inj.kind == "stranded_reservation":
                self._apply_stranded_reservation(engine, inj)
            else:  # transfer_flap
                self._apply_transfer_flap(inj)

    def fleet_hook(self, plane: Any, tick: int) -> None:
        """``ControlPlane.run(tick_hook=...)`` seam: apply fleet-level
        injections whose ``step`` matches the control-plane tick —
        ``replica_crash``/``replica_wedge`` arm the named (modulo live
        fleet size) replica's engine fault seam; ``transfer_flap`` and
        ``host_stall`` behave as in :meth:`tick_hook`. The failure this
        causes is DETECTED by the plane's health state machine next
        tick; the ring then shows the ``chaos.injection`` record right
        next to the ``replica_failure`` black box it provoked."""
        for inj in self._take(tick, FLEET_KINDS):
            if inj.kind in ("replica_crash", "replica_wedge"):
                self._apply_replica_fault(
                    plane, inj,
                    "crash" if inj.kind == "replica_crash" else "wedge",
                )
            elif inj.kind == "transfer_flap":
                self._apply_transfer_flap(inj)
            elif inj.kind == "host_tier_io_error":
                self._apply_host_tier_io_error(inj)
            else:  # host_stall
                self._apply_host_stall(inj)

    # -- forensics ---------------------------------------------------------

    def applied_json(self) -> List[dict]:
        """The applications so far, JSON-able — what trajectory-
        determinism tests compare across replayed runs."""
        return [i.to_json() for i in self.applied]

    def __repr__(self) -> str:
        return (f"ChaosMonkey({self.schedule!r}, "
                f"{len(self.applied)} applied)")


def schedule_fingerprint(schedule: ChaosSchedule) -> str:
    """Canonical JSON string of a schedule — the byte-reproducibility
    pin: ``schedule_fingerprint(a) == schedule_fingerprint(b)`` iff the
    two schedules inject identically."""
    return json.dumps(schedule.to_json(), sort_keys=True)
