"""Quantized gradient collectives (EQuARX-style, arxiv 2506.17615).

The DP/ZeRO gradient reduction moves full-precision bytes over the
wire every step; EQuARX shows a block-scaled int8 all-reduce cuts that
~4x with negligible loss impact. This module is that trade for the
repo's named-axis collectives, in three wire precisions selected by
``grad_comm``:

- ``"fp32"`` — the existing path (no-op here, kept for symmetry);
- ``"bf16"`` — cast, ``psum_scatter``, upcast: 2x fewer bytes, no
  scales (the jax-native decomposition the ISSUE names);
- ``"int8"`` — per-destination-chunk-scaled symmetric int8. An int8
  ``psum_scatter`` would WRAP (XLA reduces in the element type), so
  the reduce-scatter phase is the byte-equivalent quantize ->
  ``all_to_all`` -> local dequantize+sum: the wire moves 1-byte
  payloads of exactly the reduce-scatter's shape, the math happens in
  fp32 on arrival. 4x fewer gradient bytes (+ one fp32 scale per chunk).

ZeRO-1 stops after the reduce-scatter phase (each rank only needs its
shard — optim/zero.py); the plain-DP all-reduce adds a requantize +
``all_gather`` second stage.

Error feedback (optional): the local quantization residual
``g - dequant(quant(g))`` is carried across steps and added back
before the next quantize, so the quantization error ACCUMULATES into
later updates instead of being lost — the standard EF trick that
closes most of the quantized-vs-fp32 loss gap. The residual lives in
the optimizer state (``ZeroState.ef``).

All functions run inside ``shard_map`` over a named mesh axis and
assume a static axis size.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

GRAD_COMM_MODES = ("fp32", "bf16", "int8")

_INT8_MAX = 127.0


def check_grad_comm(mode: Optional[str]) -> str:
    mode = mode or "fp32"
    if mode not in GRAD_COMM_MODES:
        raise ValueError(
            f"grad_comm must be one of {GRAD_COMM_MODES}, got {mode!r}"
        )
    return mode


def _quantize_chunks(flat: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(n_chunks, m) fp32 -> (int8 values, per-chunk fp32 scales).
    Symmetric per-chunk max-abs scaling; an all-zero chunk gets a tiny
    positive scale so dequantization stays exact zeros."""
    scale = jnp.max(jnp.abs(flat), axis=1) / _INT8_MAX
    scale = jnp.maximum(scale, jnp.finfo(jnp.float32).tiny)
    q = jnp.clip(
        jnp.round(flat / scale[:, None]), -_INT8_MAX, _INT8_MAX
    ).astype(jnp.int8)
    return q, scale


def _dequantize(q: jax.Array, scale: jax.Array) -> jax.Array:
    return q.astype(jnp.float32) * scale[:, None]


def compressed_reduce_scatter_mean(
    g_padded: jax.Array,
    axis_name: str,
    mode: str,
    residual: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Mean over ``axis_name`` of the gradients, scattered so this rank
    keeps chunk ``rank`` of dim 0 — the ZeRO-1 gradient phase, at wire
    precision ``mode``.

    ``g_padded``: dim 0 already a multiple of the axis size (the
    caller's ``_pad_to``). ``residual``: previous step's error-feedback
    residual of the same shape (or None). Returns
    ``(mean_shard fp32, new_residual or None)``.
    """
    n = lax.axis_size(axis_name)
    mode = check_grad_comm(mode)
    g32 = g_padded.astype(jnp.float32)
    if residual is not None:
        g32 = g32 + residual
    if mode == "fp32":
        out = lax.psum_scatter(g32, axis_name, scatter_dimension=0, tiled=True)
        return out / n, (jnp.zeros_like(g32) if residual is not None else None)
    if mode == "bf16":
        gq = g32.astype(jnp.bfloat16)
        new_res = (
            g32 - gq.astype(jnp.float32) if residual is not None else None
        )
        out = lax.psum_scatter(gq, axis_name, scatter_dimension=0, tiled=True)
        return out.astype(jnp.float32) / n, new_res
    # int8: quantize per destination chunk, move 1-byte payloads with
    # all_to_all (psum_scatter would wrap in int8), reduce in fp32
    shape = g32.shape
    flat = g32.reshape(n, -1)  # chunk row i is bound for rank i
    q, scale = _quantize_chunks(flat)
    new_res = (
        (flat - _dequantize(q, scale)).reshape(shape)
        if residual is not None
        else None
    )
    q_recv = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=True)
    s_recv = lax.all_to_all(
        scale, axis_name, split_axis=0, concat_axis=0, tiled=True
    )
    mean = _dequantize(q_recv, s_recv).sum(axis=0) / n  # (m,)
    return mean.reshape((shape[0] // n,) + shape[1:]), new_res


def compressed_all_reduce_mean(
    g: jax.Array,
    axis_name: str,
    mode: str,
    residual: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Full mean-all-reduce at wire precision ``mode`` — the plain-DP
    gradient sync: the compressed reduce-scatter phase above, then the
    reduced chunk is requantized and ``all_gather``-ed (all-reduce =
    reduce-scatter + all-gather; both phases move compressed bytes).
    Any-shape ``g`` (dim 0 padded internally); returns
    ``(mean grad, new_residual or None)`` with ``g``'s shape/dtype."""
    n = lax.axis_size(axis_name)
    mode = check_grad_comm(mode)
    orig_shape, orig_dtype = g.shape, g.dtype
    gp = g[None] if g.ndim == 0 else g
    pad = (-gp.shape[0]) % n
    if pad:
        gp = jnp.pad(gp, ((0, pad),) + ((0, 0),) * (gp.ndim - 1))
    own, new_res = compressed_reduce_scatter_mean(gp, axis_name, mode, residual)
    if mode == "fp32":
        full = lax.all_gather(own, axis_name, axis=0, tiled=True)
    elif mode == "bf16":
        full = lax.all_gather(
            own.astype(jnp.bfloat16), axis_name, axis=0, tiled=True
        ).astype(jnp.float32)
    else:
        flat = own.reshape(1, -1)
        q, scale = _quantize_chunks(flat)
        q_full = lax.all_gather(q, axis_name, axis=0, tiled=True)  # (n, m)
        s_full = lax.all_gather(scale, axis_name, axis=0, tiled=True)  # (n,)
        full = _dequantize(q_full, s_full).reshape((-1,) + own.shape[1:])
    full = full[: orig_shape[0]] if len(orig_shape) else full[0]
    return full.reshape(orig_shape).astype(orig_dtype), new_res


def wire_itemsize(mode: str) -> int:
    """Bytes per gradient element on the wire for a grad_comm mode."""
    return {"fp32": 4, "bf16": 2, "int8": 1}[check_grad_comm(mode)]


def grad_comm_bytes_saved(params: Any, n_ranks: int, mode: str) -> int:
    """Analytic per-step wire-byte saving of the gradient
    reduce-scatter phase vs fp32, for the ``comm.bytes_saved`` gauge:
    every leaf moves ``padded_size x itemsize`` payload bytes through
    the reduce phase; int8 adds one fp32 scale per destination chunk.
    (The doctor's compiled-HLO payload accounting is the ground truth —
    this gauge is the cheap always-available estimate.)"""
    mode = check_grad_comm(mode)
    isize = wire_itemsize(mode)
    saved = 0
    for p in jax.tree_util.tree_leaves(params):
        d0 = p.shape[0] if getattr(p, "ndim", 0) else 1
        rest = int(getattr(p, "size", 1)) // max(d0, 1)
        padded = (-(-d0 // n_ranks) * n_ranks) * rest
        saved += padded * (4 - isize)
        if mode == "int8":
            saved -= n_ranks * 4  # per-chunk fp32 scales ride along
    return max(saved, 0)
