from pipegoose_tpu.distributed.parallel_context import ParallelContext
from pipegoose_tpu.distributed.parallel_mode import MESH_AXIS_ORDER, ParallelMode
from pipegoose_tpu.distributed import compressed, functional

__all__ = [
    "ParallelContext", "ParallelMode", "MESH_AXIS_ORDER", "functional",
    "compressed",
]
