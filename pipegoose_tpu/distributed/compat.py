"""JAX version compatibility shims (imported by ``pipegoose_tpu``'s
package init, so they are installed before any framework code runs).

APIs this codebase targets that moved under us on older jax:

- ``shard_map``: ``jax.experimental.shard_map`` (< 0.6) takes
  ``check_rep``; the promoted ``jax.shard_map`` renamed it
  ``check_vma``. Every sharded entry point here disables that check
  (pytree-of-arrays params defeat the replication inference), so the
  kwarg mismatch was a runtime ``TypeError`` on every shard_map call
  under jax 0.4.x. Import :func:`shard_map` from here — it speaks
  ``check_vma`` and translates — instead of repeating the try/except
  import dance at each call site.
- ``jax.lax.axis_size`` (missing < 0.6): installed via the
  ``psum(1, axis)`` const-fold.
- ``pallas.tpu.CompilerParams`` (named ``TPUCompilerParams`` < 0.6):
  aliased.
- ``jax.distributed.is_initialized`` (missing < 0.6): read from the
  coordination-service client handle.
"""
from __future__ import annotations

import inspect

import jax

try:
    from jax import shard_map as _shard_map  # jax >= 0.6
except ImportError:  # jax < 0.6
    from jax.experimental.shard_map import shard_map as _shard_map

_HAS_VMA = "check_vma" in inspect.signature(_shard_map).parameters


def shard_map(f, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions; ``check_vma`` maps to the
    old ``check_rep`` when running under jax < 0.6."""
    if _HAS_VMA:
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


try:
    import jax.experimental.pallas.tpu as _pltpu

    if not hasattr(_pltpu, "CompilerParams"):
        # jax < 0.6 calls it TPUCompilerParams; the Pallas kernels
        # (ops/flash_attention.py, ops/fused_ce.py) use the current name
        _pltpu.CompilerParams = _pltpu.TPUCompilerParams
except ImportError:  # pallas not available on this build
    pass

if not hasattr(jax.distributed, "is_initialized"):
    # jax < 0.6: the coordination client handle is the initialized flag
    # (parallel_context.init_multihost's idempotency check)
    def _is_initialized():
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None

    jax.distributed.is_initialized = _is_initialized

if not hasattr(jax.lax, "axis_size"):
    # jax < 0.6 has no ``lax.axis_size``; ``psum`` of a literal int
    # const-folds to a STATIC python int at trace time (no collective
    # emitted), which is exactly the newer API's contract — call sites
    # here use it for static shape math (``n_head // tp``). Installed on
    # jax.lax (not re-exported) so the ~40 existing call sites across
    # the model/nn stack keep reading as the current-jax idiom.
    def _axis_size(axis_name):
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = _axis_size
