"""Parallel axis names.

TPU-native analog of the reference's ``ParallelMode`` enum
(pipegoose/distributed/parallel_mode.py:4-12). Instead of naming process
groups, each mode names an axis of a single ``jax.sharding.Mesh``. The
GLOBAL mode corresponds to the whole mesh (all axes at once).

The reference's EXPERT_DATA group shares its layout with the TENSOR group
(pipegoose/distributed/_initializers/initialize_expert.py:10-44); here the
expert axis is a first-class mesh axis instead, with size 1 unless expert
parallelism is enabled.
"""
from __future__ import annotations

import enum


class ParallelMode(str, enum.Enum):
    GLOBAL = "global"
    TENSOR = "tensor"
    PIPELINE = "pipe"
    DATA = "data"
    EXPERT = "expert"
    # DiLoCo worker axis — outermost, spans pod slices over DCN; inner
    # steps never emit a collective over it (optim/diloco.py). The
    # reference only aspires to DiLoCo (its README cites the paper).
    DILOCO = "diloco"
    # Long-context/sequence axis — new capability, absent from the reference
    # (SURVEY.md §5: sequence parallelism advertised but unimplemented).
    SEQUENCE = "seq"

    @property
    def axis_name(self) -> str:
        return self.value


# Canonical mesh axis order, outermost first. ``pipe`` is outermost (stage
# boundaries cross the slowest links), ``tensor`` is innermost so tensor
# collectives ride the fastest ICI hops — mirroring the reference's layout
# where TENSOR groups are contiguous rank blocks
# (initialize_tensor.py:27-56) and PIPELINE groups are strided by
# world//pp (initialize_pipeline.py:27-56).
# ``diloco`` sits OUTSIDE even pipe: worker replicas are whole pod
# slices connected over DCN, and the only traffic crossing it is the
# sync step's pmean every H steps.
MESH_AXIS_ORDER = ("diloco", "pipe", "data", "seq", "expert", "tensor")
