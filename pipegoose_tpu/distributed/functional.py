"""Named-axis collective layer.

TPU-native analog of the reference's collective wrappers
(pipegoose/distributed/functional.py:30-183) and of the Megatron-style
autograd Functions (pipegoose/nn/tensor_parallel/_functional.py:15-95).

Differences by design:
- These run *inside* ``shard_map``/``jit`` over named mesh axes; XLA lowers
  them to ICI collectives. There is no process-group argument and no typed
  P2P preamble (_p2p.py:38-81) — shapes are static in the compiled program.
- ``reduce_scatter`` is actually implemented (the reference left it as an
  empty stub, functional.py:155-156).
- The world-size-1 short-circuit (functional.py:33-35 etc.) becomes
  ``axis_name=None`` or an axis of size 1 — both are handled.

The custom-vjp pairs at the bottom mirror the reference's ``_Broadcast`` /
``_Gather`` / ``_Scatter`` / ``_Reduce`` (tensor_parallel/_functional.py)
— the f/g conjugate operators of Megatron-LM tensor parallelism.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _noop(axis_name: Optional[str]) -> bool:
    return axis_name is None


# --------------------------------------------------------------------------
# Plain collectives (usable inside shard_map)
# --------------------------------------------------------------------------

def all_reduce(x, axis_name: Optional[str], op: str = "sum"):
    """Reference all_reduce (functional.py:133-152)."""
    if _noop(axis_name):
        return x
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    raise ValueError(f"unsupported reduce op: {op}")


def all_gather(x, axis_name: Optional[str], dim: int = -1):
    """Gather shards along ``dim`` (reference functional.py:94-130, which
    gathers a list then ``torch.cat`` on dim — here one fused HLO)."""
    if _noop(axis_name):
        return x
    return lax.all_gather(x, axis_name, axis=dim % x.ndim, tiled=True)


def scatter(x, axis_name: Optional[str], dim: int = -1):
    """Keep this rank's chunk of ``dim`` (reference functional.py:30-46)."""
    if _noop(axis_name):
        return x
    size = lax.axis_size(axis_name)
    if size == 1:
        return x
    dim = dim % x.ndim
    chunk = x.shape[dim] // size
    if chunk * size != x.shape[dim]:
        raise ValueError(f"dim {dim} of shape {x.shape} not divisible by {size}")
    idx = lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=dim)


def reduce_scatter(x, axis_name: Optional[str], dim: int = -1):
    """Sum across the axis, keep this rank's chunk of ``dim``. The
    reference stubbed this out (functional.py:155-156); Megatron-style
    sequence parallelism and ZeRO both need it."""
    if _noop(axis_name):
        return x
    return lax.psum_scatter(x, axis_name, scatter_dimension=dim % x.ndim, tiled=True)


def broadcast(x, axis_name: Optional[str], src: int = 0):
    """Every rank gets rank ``src``'s value (reference functional.py:72-91).
    Implemented as select-then-psum: non-src ranks contribute exact zeros
    (a multiply would leak NaN/Inf from non-src ranks into every rank)."""
    if _noop(axis_name):
        return x
    is_bool = x.dtype == jnp.bool_
    v = x.astype(jnp.int32) if is_bool else x
    v = jnp.where(lax.axis_index(axis_name) == src, v, jnp.zeros_like(v))
    out = lax.psum(v, axis_name)
    return out.astype(jnp.bool_) if is_bool else out


def reduce(x, axis_name: Optional[str], dst: int = 0, op: str = "sum"):
    """Reduce onto ``dst``; other ranks get zeros (reference
    functional.py:49-69 leaves other ranks' buffers unspecified)."""
    if _noop(axis_name):
        return x
    out = all_reduce(x, axis_name, op=op)
    keep = (lax.axis_index(axis_name) == dst).astype(x.dtype)
    return out * keep


def all_to_all(x, axis_name: Optional[str], split_dim: int, concat_dim: int):
    """MoE dispatch/combine primitive (absent from the reference, which
    used local indexing + all_reduce instead, experts.py:41-80)."""
    if _noop(axis_name):
        return x
    return lax.all_to_all(x, axis_name, split_axis=split_dim % x.ndim,
                          concat_axis=concat_dim % x.ndim, tiled=True)


def ppermute(x, axis_name: str, perm):
    """Point-to-point ring transfer; the analog of the reference's
    P2P send/recv (functional.py:159-176) and of the pipeline RPC
    transport (_comm.py:10-41) — but compiled, typed, and deadlock-free."""
    return lax.ppermute(x, axis_name, perm=perm)


def shift_right(x, axis_name: str):
    """Send to the next rank on the axis ring (pipeline stage handoff)."""
    n = lax.axis_size(axis_name)
    return lax.ppermute(x, axis_name, perm=[(i, (i + 1) % n) for i in range(n)])


def shift_left(x, axis_name: str):
    n = lax.axis_size(axis_name)
    return lax.ppermute(x, axis_name, perm=[(i, (i - 1) % n) for i in range(n)])


def barrier(axis_name: Optional[str] = None):
    """Reference barrier (functional.py:179-183). Inside one compiled XLA
    program execution is already bulk-synchronous; this is a no-op kept
    for API parity."""
    return None


# --------------------------------------------------------------------------
# Megatron f/g conjugate pairs (custom VJP)
# Reference: nn/tensor_parallel/_functional.py:15-95
# --------------------------------------------------------------------------

from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_group(x, axis_name: str):
    """f-operator: identity forward, all-reduce backward
    (reference _Broadcast, _functional.py:15-28)."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, g):
    return (all_reduce(g, axis_name),)


copy_to_tensor_group.defvjp(_copy_fwd, _copy_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_group(x, axis_name: str):
    """g-operator: all-reduce forward, identity backward
    (reference _Reduce, _functional.py:72-79)."""
    return all_reduce(x, axis_name)


def _reduce_fwd(x, axis_name):
    return all_reduce(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    return (g,)


reduce_from_tensor_group.defvjp(_reduce_fwd, _reduce_bwd)


def gather_from_tensor_group(x, axis_name: str, dim: int = -1):
    """all-gather forward / scatter backward (reference _Gather,
    _functional.py:31-48)."""
    return _gather_impl(x, axis_name, dim % x.ndim)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _gather_impl(x, axis_name, dim):
    return all_gather(x, axis_name, dim=dim)


def _gather_fwd(x, axis_name, dim):
    return all_gather(x, axis_name, dim=dim), None


def _gather_bwd(axis_name, dim, _, g):
    return (scatter(g, axis_name, dim=dim),)


_gather_impl.defvjp(_gather_fwd, _gather_bwd)


def scatter_to_tensor_group(x, axis_name: str, dim: int = -1):
    """scatter forward / all-gather backward (reference _Scatter,
    _functional.py:51-69)."""
    return _scatter_impl(x, axis_name, dim % x.ndim)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _scatter_impl(x, axis_name, dim):
    return scatter(x, axis_name, dim=dim)


def _scatter_fwd(x, axis_name, dim):
    return scatter(x, axis_name, dim=dim), None


def _scatter_bwd(axis_name, dim, _, g):
    return (all_gather(g, axis_name, dim=dim),)


_scatter_impl.defvjp(_scatter_fwd, _scatter_bwd)
