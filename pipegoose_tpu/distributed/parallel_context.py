"""ParallelContext: device-mesh construction and axis queries.

TPU-native analog of the reference's ``ParallelContext``
(pipegoose/distributed/parallel_context.py:49-407). The reference builds
torch.distributed process groups for a TP x PP x DP (x expert) cartesian
decomposition of the world, plus RPC workers for pipeline transport. On
TPU all of that collapses into ONE ``jax.sharding.Mesh`` with named axes:
collectives become XLA HLO ops emitted under ``shard_map``/``jit``, and
pipeline transport becomes ``lax.ppermute`` inside a compiled program —
no process groups, no RPC, no per-rank bookkeeping.

Rank-layout parity with the reference (so tests and checkpoints line up):
within one DiLoCo worker block, a global rank r decomposes exactly as in
the reference:

    r = pipe_rank * (dp*sp*ep*tp) + data_rank * (sp*ep*tp)
        + seq_rank * (ep*tp) + expert_rank * tp + tensor_rank

realized as ``devices.reshape(w, pp, dp, sp, ep, tp)`` with axis names
``(diloco, pipe, data, seq, expert, tensor)`` — the leading ``diloco``
axis (worker replicas over DCN, size 1 unless DiLoCo is on) multiplies
the whole layout and preserves the reference's intra-worker order:

- TENSOR groups = contiguous blocks of size tp (initialize_tensor.py:27-56)
- PIPELINE groups = strided by world//pp (initialize_pipeline.py:27-56)
- DATA groups = strided by tp within a pipe block (initialize_data.py:27-62)

The ``seq`` axis (sequence/context parallelism) is new capability the
reference only advertised (SURVEY.md §5).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pipegoose_tpu.distributed.parallel_mode import MESH_AXIS_ORDER, ParallelMode

_GLOBAL_CONTEXT: Optional["ParallelContext"] = None


@dataclasses.dataclass
class ParallelContext:
    """Holds the device mesh and answers axis-topology queries.

    Replaces the reference's god-object (parallel_context.py:86-137): no
    ``init_process_group``, no ``new_group`` storms, no RPC bring-up —
    constructing a Mesh is a purely local, instant operation.
    """

    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    data_parallel_size: int = 1
    expert_parallel_size: int = 1
    sequence_parallel_size: int = 1
    # DiLoCo worker replicas (outermost axis; only the sync step
    # communicates over it — optim/diloco.py)
    diloco_parallel_size: int = 1
    devices: Optional[Sequence[jax.Device]] = None
    mesh: Mesh = dataclasses.field(init=False)

    def __post_init__(self) -> None:
        tp = self.tensor_parallel_size
        pp = self.pipeline_parallel_size
        dp = self.data_parallel_size
        ep = self.expert_parallel_size
        sp = self.sequence_parallel_size
        w = self.diloco_parallel_size
        for name, size in [("tensor", tp), ("pipeline", pp), ("data", dp),
                           ("expert", ep), ("sequence", sp), ("diloco", w)]:
            if size < 1:
                raise ValueError(f"{name}_parallel_size must be >= 1, got {size}")

        devices = list(self.devices) if self.devices is not None else jax.devices()
        world = w * tp * pp * dp * ep * sp
        if len(devices) < world:
            raise ValueError(
                f"need diloco*tp*pp*dp*ep*sp = {w}*{tp}*{pp}*{dp}*{ep}*{sp} = "
                f"{world} devices, have {len(devices)}"
                # mirrors the reference's world-size assert (parallel_context.py:101-113)
            )
        dev_array = np.asarray(devices[:world], dtype=object).reshape(
            w, pp, dp, sp, ep, tp
        )
        self.mesh = Mesh(dev_array, MESH_AXIS_ORDER)
        _set_context(self)

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_mesh(cls, mesh: Mesh) -> "ParallelContext":
        """Wrap an existing mesh (axis names must be a subset of ours)."""
        unknown = set(mesh.axis_names) - set(MESH_AXIS_ORDER)
        if unknown:
            raise ValueError(
                f"mesh axis names {sorted(unknown)} are not parallel axes; "
                f"expected a subset of {MESH_AXIS_ORDER}"
            )
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        ctx = cls.__new__(cls)
        ctx.tensor_parallel_size = sizes.get("tensor", 1)
        ctx.pipeline_parallel_size = sizes.get("pipe", 1)
        ctx.data_parallel_size = sizes.get("data", 1)
        ctx.expert_parallel_size = sizes.get("expert", 1)
        ctx.sequence_parallel_size = sizes.get("seq", 1)
        ctx.diloco_parallel_size = sizes.get("diloco", 1)
        ctx.devices = list(mesh.devices.flat)
        ctx.mesh = mesh
        _set_context(ctx)
        return ctx

    @classmethod
    def init_multihost(
        cls,
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
        **kwargs,
    ) -> "ParallelContext":
        """Multi-host bring-up: the analog of the reference's torchrun env-var
        path (from_torch, parallel_context.py:55-84). With no explicit
        coordinator args, ``jax.distributed`` uses its own discovery (TPU
        metadata / cluster env vars); pass them explicitly for generic
        clusters (tested by tests/distributed/test_multihost.py's
        two-process localhost smoke)."""
        import warnings

        import jax.distributed

        if not jax.distributed.is_initialized():
            init_kw = {
                k: v
                for k, v in dict(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id,
                ).items()
                if v is not None
            }
            try:
                jax.distributed.initialize(**init_kw)
            except (RuntimeError, ValueError) as e:
                if init_kw:
                    raise  # explicit coordinator config failing is an error
                # jax raises ValueError('coordinator_address should be
                # defined.') when no coordinator is configured
                # no coordinator configured — single-process dev run
                warnings.warn(
                    f"jax.distributed.initialize failed ({e}); continuing "
                    "single-process. Multi-host runs need coordinator env "
                    "vars or TPU metadata."
                )
        return cls(**kwargs)

    @classmethod
    def get_context(cls) -> Optional["ParallelContext"]:
        """Singleton accessor (reference parallel_context.py:143-146)."""
        return _GLOBAL_CONTEXT

    # -- axis queries -------------------------------------------------------

    def get_world_size(self, mode: ParallelMode = ParallelMode.GLOBAL) -> int:
        """Axis size (reference get_world_size, parallel_context.py:324-330)."""
        if mode == ParallelMode.GLOBAL:
            return int(np.prod(self.mesh.devices.shape))
        return self.mesh.shape[mode.axis_name]

    def axis_size(self, axis: str) -> int:
        return self.mesh.shape[axis]

    def get_local_rank(self, device: jax.Device, mode: ParallelMode) -> int:
        """Coordinate of ``device`` along the mode's axis. Inside a
        shard_map'd function use ``jax.lax.axis_index(mode.axis_name)``
        instead — this host-side query is for placement/checkpoint logic
        (reference get_local_rank, parallel_context.py:313-317)."""
        coords = self._device_coords(device)
        if mode == ParallelMode.GLOBAL:
            return self.get_global_rank(device)
        return coords[MESH_AXIS_ORDER.index(mode.axis_name)]

    def get_global_rank(self, device: jax.Device) -> int:
        idx = np.flatnonzero(self.mesh.devices.flat == device)
        if idx.size == 0:
            raise ValueError(f"{device} not in mesh")
        return int(idx[0])

    def _device_coords(self, device: jax.Device):
        pos = np.argwhere(self.mesh.devices == device)
        if pos.size == 0:
            raise ValueError(f"{device} not in mesh")
        return tuple(int(c) for c in pos[0])

    def get_ranks_in_group(self, device: jax.Device, mode: ParallelMode):
        """Global ranks sharing every coordinate with ``device`` except the
        mode's axis (reference get_ranks_in_group, parallel_context.py:341-353)."""
        if mode == ParallelMode.GLOBAL:
            return list(range(self.get_world_size()))
        coords = list(self._device_coords(device))
        ax = MESH_AXIS_ORDER.index(mode.axis_name)
        ranks = []
        for i in range(self.mesh.devices.shape[ax]):
            coords[ax] = i
            ranks.append(self.get_global_rank(self.mesh.devices[tuple(coords)]))
        return ranks

    def is_first_rank(self, device: jax.Device, mode: ParallelMode) -> bool:
        return self.get_local_rank(device, mode) == 0

    def is_last_rank(self, device: jax.Device, mode: ParallelMode) -> bool:
        return self.get_local_rank(device, mode) == self.get_world_size(mode) - 1

    # -- sharding helpers ---------------------------------------------------

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # -- lifecycle ----------------------------------------------------------

    def destroy(self) -> None:
        """Reference destroy() tears down process groups + RPC
        (parallel_context.py:390-407); here only the singleton needs
        clearing — the mesh owns no OS resources."""
        global _GLOBAL_CONTEXT
        if _GLOBAL_CONTEXT is self:
            _GLOBAL_CONTEXT = None


def _set_context(ctx: ParallelContext) -> None:
    global _GLOBAL_CONTEXT
    _GLOBAL_CONTEXT = ctx
