"""Profiling & cost analysis.

TPU-native analog (and superset) of the reference's ``ProfileByMemory``
(pipegoose/partitioning/profile.py:19-49), which ran layers sequentially
on CUDA measuring ``memory_allocated`` deltas to feed non-uniform PP
partitioning. Here:

- ``estimate_block_costs``: analytic FLOPs/bytes per transformer block
  from shapes (what actually drives partitioning on TPU — deterministic,
  no warm-up runs);
- ``compiled_cost``: XLA's own cost analysis of any jitted function
  (flops, bytes accessed) — the compiler's ground truth;
- ``device_memory_stats``: live HBM usage per device;
- ``trace``: context manager around ``jax.profiler`` for timeline traces
  viewable in TensorBoard/Perfetto (the reference has no timeline
  tracing at all, SURVEY.md §5).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np


def estimate_block_costs(
    hidden: int, seq: int, batch: int, ffn_mult: int = 4, causal: bool = True
):
    """FLOPs and activation bytes for ONE transformer block at the given
    shapes (per microbatch). Attention term is 2*(2*B*S^2*H) matmul FLOPs
    (halved if causal), dense term 2*B*S*(qkv + out + mlp) MACs."""
    dense_params = hidden * 3 * hidden + hidden * hidden + 2 * ffn_mult * hidden * hidden
    dense_flops = 2 * batch * seq * dense_params
    attn_flops = 2 * 2 * batch * seq * seq * hidden
    if causal:
        attn_flops //= 2
    act_bytes = 2 * batch * seq * hidden * (4 + 2 * ffn_mult)  # bf16, rough
    return {"flops": dense_flops + attn_flops, "bytes": act_bytes}


def compiled_cost(fn: Callable, *args, **kwargs) -> dict:
    """XLA cost analysis of ``jit(fn)`` at these arg shapes: returns at
    least ``flops`` and ``bytes accessed`` where the backend reports them."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def device_memory_stats(device: Optional[Any] = None) -> dict:
    """Live HBM statistics (reference measured CUDA memory_allocated,
    profile.py:30-42). Backends without ``memory_stats()`` (CPU, some
    plugin platforms) return ``{"unavailable": "<platform>"}`` instead
    of a silent empty dict, so a caller staring at a blank HBM gauge
    can tell "no memory pressure" from "this backend can't say"."""
    device = device or jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if not stats:
        return {"unavailable": getattr(device, "platform", "unknown")}
    return dict(stats)


def trace(logdir: str, perfetto: bool = False, **kwargs):
    """jax.profiler timeline trace (TensorBoard/Perfetto viewable) —
    thin re-export of jax.profiler.trace for API discoverability.

    ``perfetto=True`` additionally writes the Perfetto-compatible
    ``perfetto_trace.json.gz`` conversion next to the raw
    ``*.trace.json.gz`` (sugar for ``create_perfetto_trace=True``,
    which remains passable directly). The raw artifact is what
    ``telemetry/xprof.py`` parses for measured step attribution."""
    if perfetto:
        kwargs.setdefault("create_perfetto_trace", True)
    return jax.profiler.trace(logdir, **kwargs)


def tree_size_bytes(tree: Any) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree))


def count_params(tree: Any) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))
