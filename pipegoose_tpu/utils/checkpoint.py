"""Sharded, mesh-aware, crash-atomic checkpointing.

TPU-native analog of the reference's checkpoint utils
(pipegoose/nn/utils.py:11-50), which write one torch state_dict file per
(tp, pp) coordinate named ``pytorch_model_tp_{tp}_pp_{pp}.bin``
(constants.py:4-5) — no optimizer state, no resharding on load, no async
save (SURVEY.md §5 flags this as a capability gap). Here checkpoints are
orbax/tensorstore: every array is written once in a sharded,
layout-independent format, and restore RESHARDS onto whatever mesh the
current run uses (different tp/pp/dp than the run that saved — the thing
the reference's per-coordinate files cannot do). Optimizer state and
step counters ride along in the same tree.

Crash-atomicity contract (the elasticity stack depends on it):

- every save writes to a ``<final>.tmp`` SIBLING and ``os.rename``s to
  the final name only after orbax finishes — a kill at any point leaves
  either the previous state or a ``.tmp`` directory, never a torn
  directory under a valid ``step_N`` name;
- transient I/O errors (``OSError``) are retried with exponential
  backoff up to ``retries`` times before surfacing — a blip on a
  network filesystem must not lose a checkpoint cadence slot;
- :func:`latest_step` / :func:`available_steps` list only COMPLETE
  checkpoints: ``.tmp`` siblings and empty directories (a crashed
  rename-less writer) are skipped, so a resume or an
  ``AutoRecovery`` restore never points at a torn newest checkpoint.

Fault injection for tests and the chaos harness
(``pipegoose_tpu/testing/chaos.py``): :func:`set_io_fault_hook`
installs a callable invoked at the start of every save ATTEMPT; raising
``OSError`` from it simulates a transient storage failure and exercises
the retry path without monkeypatching orbax.
"""
from __future__ import annotations

import os
import shutil
import time
from typing import Any, Callable, List, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from pipegoose_tpu.distributed.parallel_context import ParallelContext

#: suffix of the in-progress sibling a save writes before the atomic
#: rename; anything carrying it is by definition incomplete
TMP_SUFFIX = ".tmp"

# test/chaos seam: called at the start of every save attempt; raising
# OSError simulates a transient storage failure (the retry loop below
# absorbs up to `retries` of them)
_IO_FAULT_HOOK: Optional[Callable[[], None]] = None


def set_io_fault_hook(
    hook: Optional[Callable[[], None]]
) -> Optional[Callable[[], None]]:
    """Install (or clear, with None) the save-attempt fault hook;
    returns the previous hook so tests can restore it."""
    global _IO_FAULT_HOOK
    prev, _IO_FAULT_HOOK = _IO_FAULT_HOOK, hook
    return prev


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_pretrained(
    params: Any,
    path: str,
    step: Optional[int] = None,
    retries: int = 3,
    backoff_s: float = 0.05,
) -> str:
    """Write a sharded checkpoint (reference save_pretrained,
    nn/utils.py:11-28). Directory layout is orbax-standard; ``step``
    creates a numbered subdirectory for resumable training runs.

    Crash-atomic: the tree lands in ``<final>.tmp`` first and is
    renamed into place only after orbax reports the write finished, so
    a kill mid-save never leaves a torn directory under the final
    name. Transient ``OSError``s retry with exponential backoff
    (``retries`` attempts beyond the first); persistent ones surface.
    """
    path = os.path.abspath(path)
    if step is not None:
        path = os.path.join(path, f"step_{step}")
    if os.path.exists(path):
        # mirrors orbax's own exists check, but BEFORE the tmp write so
        # a doomed save doesn't burn I/O (and the rename can't clobber)
        raise ValueError(f"checkpoint already exists: {path}")
    tmp = path + TMP_SUFFIX
    last_err: Optional[BaseException] = None
    for attempt in range(retries + 1):
        try:
            if _IO_FAULT_HOOK is not None:
                _IO_FAULT_HOOK()
            if os.path.isdir(tmp):
                # stale sibling from a crashed/failed earlier attempt
                shutil.rmtree(tmp)
            ckpt = _checkpointer()
            ckpt.save(tmp, params)
            ckpt.wait_until_finished()
            os.rename(tmp, path)  # the commit point: atomic on one fs
            return path
        except OSError as e:  # transient I/O: retry with backoff
            last_err = e
            if attempt >= retries:
                raise
            time.sleep(backoff_s * (2 ** attempt))
    raise RuntimeError(  # pragma: no cover - loop always returns/raises
        f"checkpoint save failed after {retries + 1} attempts: {last_err}"
    )


def from_pretrained(
    path: str,
    like: Any,
    specs: Any = None,
    parallel_context: Optional[ParallelContext] = None,
) -> Any:
    """Restore onto the CURRENT mesh, resharding as needed (reference
    from_pretrained, nn/utils.py:31-50, could only reload the exact
    (tp, pp) layout that saved). ``like`` is a pytree of arrays or
    ShapeDtypeStructs giving structure/shape/dtype; ``specs`` (optional)
    a matching PartitionSpec tree for the target sharding."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ctx = parallel_context or ParallelContext.get_context()

    def to_struct(x, spec):
        shape = x.shape
        dtype = x.dtype
        if ctx is not None and spec is not None:
            sharding = NamedSharding(ctx.mesh, spec)
        elif ctx is not None:
            sharding = NamedSharding(ctx.mesh, P())
        else:
            sharding = None
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    if specs is None:
        specs = jax.tree_util.tree_map(lambda _: None, like)
    target = jax.tree_util.tree_map(
        to_struct, like, specs, is_leaf=lambda x: hasattr(x, "shape")
    )
    return _checkpointer().restore(path, target)


def _complete_step(path: str, name: str) -> Optional[int]:
    """``step_N`` -> N for a COMPLETE checkpoint directory, else None.

    Complete means: the canonical name (no ``.tmp`` sibling suffix — a
    writer that died before its atomic rename), parseable step number,
    a real directory, and non-empty (an empty dir is a writer that died
    between mkdir and content)."""
    if not name.startswith("step_") or name.endswith(TMP_SUFFIX):
        return None
    try:
        n = int(name.split("_", 1)[1])
    except ValueError:
        return None
    full = os.path.join(path, name)
    if not os.path.isdir(full):
        return None
    try:
        if not os.listdir(full):
            return None
    except OSError:
        return None
    return n


def available_steps(path: str) -> List[int]:
    """Steps of every COMPLETE ``step_N`` checkpoint under ``path``,
    newest first — the fallback order ``AutoRecovery`` walks when the
    newest checkpoint fails to restore."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return []
    steps = []
    for name in os.listdir(path):
        n = _complete_step(path, name)
        if n is not None:
            steps.append(n)
    return sorted(steps, reverse=True)


def latest_step(path: str) -> Optional[int]:
    """Largest COMPLETE ``step_N`` subdirectory, for resume. ``.tmp``
    siblings and empty directories (torn writes) are skipped — a kill
    mid-save must not leave a newest checkpoint that resume or
    recovery would then fail (or worse, half-succeed) to restore."""
    steps = available_steps(path)
    return steps[0] if steps else None


def save_train_state(
    path: str, step: int, params: Any, opt_state: Any = None, extra: Any = None
) -> str:
    """Checkpoint the full training state (params + optimizer shards +
    counters) — absent from the reference entirely (SURVEY.md §5).
    Inherits :func:`save_pretrained`'s crash-atomic tmp+rename and
    transient-retry behavior."""
    tree = {"params": params}
    if opt_state is not None:
        tree["opt_state"] = opt_state
    if extra is not None:
        tree["extra"] = extra
    return save_pretrained(tree, path, step=step)


def restore_train_state(
    path: str,
    step: Optional[int],
    like: Any,
    specs: Any = None,
    parallel_context: Optional[ParallelContext] = None,
) -> Any:
    if step is None:
        step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f"no step_N checkpoints under {path}")
    return from_pretrained(
        os.path.join(path, f"step_{step}"), like, specs, parallel_context
    )
