"""Sharded, mesh-aware checkpointing.

TPU-native analog of the reference's checkpoint utils
(pipegoose/nn/utils.py:11-50), which write one torch state_dict file per
(tp, pp) coordinate named ``pytorch_model_tp_{tp}_pp_{pp}.bin``
(constants.py:4-5) — no optimizer state, no resharding on load, no async
save (SURVEY.md §5 flags this as a capability gap). Here checkpoints are
orbax/tensorstore: every array is written once in a sharded,
layout-independent format, and restore RESHARDS onto whatever mesh the
current run uses (different tp/pp/dp than the run that saved — the thing
the reference's per-coordinate files cannot do). Optimizer state and
step counters ride along in the same tree.
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from pipegoose_tpu.distributed.parallel_context import ParallelContext


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_pretrained(params: Any, path: str, step: Optional[int] = None) -> str:
    """Write a sharded checkpoint (reference save_pretrained,
    nn/utils.py:11-28). Directory layout is orbax-standard; ``step``
    creates a numbered subdirectory for resumable training runs."""
    path = os.path.abspath(path)
    if step is not None:
        path = os.path.join(path, f"step_{step}")
    ckpt = _checkpointer()
    ckpt.save(path, params)
    ckpt.wait_until_finished()
    return path


def from_pretrained(
    path: str,
    like: Any,
    specs: Any = None,
    parallel_context: Optional[ParallelContext] = None,
) -> Any:
    """Restore onto the CURRENT mesh, resharding as needed (reference
    from_pretrained, nn/utils.py:31-50, could only reload the exact
    (tp, pp) layout that saved). ``like`` is a pytree of arrays or
    ShapeDtypeStructs giving structure/shape/dtype; ``specs`` (optional)
    a matching PartitionSpec tree for the target sharding."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ctx = parallel_context or ParallelContext.get_context()

    def to_struct(x, spec):
        shape = x.shape
        dtype = x.dtype
        if ctx is not None and spec is not None:
            sharding = NamedSharding(ctx.mesh, spec)
        elif ctx is not None:
            sharding = NamedSharding(ctx.mesh, P())
        else:
            sharding = None
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    if specs is None:
        specs = jax.tree_util.tree_map(lambda _: None, like)
    target = jax.tree_util.tree_map(
        to_struct, like, specs, is_leaf=lambda x: hasattr(x, "shape")
    )
    return _checkpointer().restore(path, target)


def latest_step(path: str) -> Optional[int]:
    """Largest ``step_N`` subdirectory, for resume."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return None
    steps = []
    for name in os.listdir(path):
        if name.startswith("step_"):
            try:
                steps.append(int(name.split("_", 1)[1]))
            except ValueError:
                continue
    return max(steps) if steps else None


def save_train_state(
    path: str, step: int, params: Any, opt_state: Any = None, extra: Any = None
) -> str:
    """Checkpoint the full training state (params + optimizer shards +
    counters) — absent from the reference entirely (SURVEY.md §5)."""
    tree = {"params": params}
    if opt_state is not None:
        tree["opt_state"] = opt_state
    if extra is not None:
        tree["extra"] = extra
    return save_pretrained(tree, path, step=step)


def restore_train_state(
    path: str,
    step: Optional[int],
    like: Any,
    specs: Any = None,
    parallel_context: Optional[ParallelContext] = None,
) -> Any:
    if step is None:
        step = latest_step(path)
        if step is None:
            raise FileNotFoundError(f"no step_N checkpoints under {path}")
    return from_pretrained(
        os.path.join(path, f"step_{step}"), like, specs, parallel_context
    )
