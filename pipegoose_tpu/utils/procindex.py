"""Shared lazily-cached 'is this the emitting process?' check.

Used by ``trainer.logger.DistributedLogger`` (rank-filtered logging)
and the telemetry exporters (rank-filtered file writes) so the caching
subtlety lives in exactly one place.

Caching after the first successful lookup is safe: ``process_index()``
forces backend initialization, and ``jax.distributed.initialize()``
RAISES once any backend exists, so the process topology (and this
index) cannot change after a successful lookup. The jax import stays
lazy — constructing a filter must not force backend init.
"""
from __future__ import annotations

from typing import Optional


class RankFilter:
    __slots__ = ("rank", "_idx")

    def __init__(self, rank: Optional[int]):
        """``rank``: only this process index passes; None = all do."""
        self.rank = rank
        self._idx: Optional[int] = None

    def __call__(self) -> bool:
        if self.rank is None:
            return True
        if self._idx is None:
            import jax

            self._idx = jax.process_index()
        return self._idx == self.rank
