"""pipegoose_tpu: a TPU-native 3D/4D-parallel training framework.

Built from scratch for JAX/XLA/Pallas with the capabilities of
xrsrke/pipegoose (reference surveyed in SURVEY.md): tensor, data,
pipeline, expert, and sequence parallelism plus a ZeRO-1 distributed
optimizer — expressed as one compiled SPMD program over a
``jax.sharding.Mesh`` instead of process groups, RPC, and threads.
"""
from pipegoose_tpu.distributed import compat as _compat  # noqa: F401 — installs jax<0.6 shims
from pipegoose_tpu.distributed import ParallelContext, ParallelMode

__version__ = "0.1.0"
__all__ = ["ParallelContext", "ParallelMode"]
