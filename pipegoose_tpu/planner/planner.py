"""The search driver: enumerate -> AOT-compile -> score -> rank.

One candidate evaluation is exactly one mesh-doctor inspection
(telemetry/doctor.py ``diagnose`` — a shape-only lower+compile on fake
host devices, nothing executes) scored through the static cost model
(planner/cost.py). The driver owns the bookkeeping the acceptance bar
demands: an infeasible candidate is PRUNED WITH A REASON and counted
(``planner.pruned_infeasible`` gauge + a log line), never silently
dropped; a candidate whose build/compile raises becomes a pruned row
carrying the exception, so one broken config cannot abort a 30-config
search.

The model side is a builder object (duck-typed; see
``planner/bloom_builder.py``):

- ``builder.describe() -> dict`` — model metadata for the artifact;
- ``builder.tokens_per_step -> int`` — the global batch every
  candidate is scored on;
- ``builder.validity(candidate) -> Optional[str]`` — cheap
  model-divisibility checks, a reason string prunes;
- ``builder.build(candidate)`` — context manager yielding the dict
  ``diagnose`` needs (step, args, intended, labels, mesh,
  bubble_fraction), releasing its mesh/context on exit.
"""
from __future__ import annotations

import logging
from typing import Any, Iterable, Optional

from pipegoose_tpu.planner.cost import CostModel, hbm_check, score_breakdown
from pipegoose_tpu.planner.report import CandidateResult, PlanReport
from pipegoose_tpu.planner.space import Candidate, enumerate_candidates
from pipegoose_tpu.telemetry import doctor

logger = logging.getLogger("pipegoose_tpu.planner")

# the most recent PlanReport produced by run_plan in this process —
# what the ops server's /debug/plan serves when wired to
# last_plan_report (bench.py, the CLI, and ElasticRecovery's
# planner-backed replan all route through run_plan, so one cache
# covers every producer)
_LAST_PLAN_REPORT: Optional[PlanReport] = None


def last_plan_report() -> Optional[PlanReport]:
    """The newest :class:`PlanReport` this process produced (None until
    the first ``run_plan``) — pass ``plan=last_plan_report`` to
    ``OpsServer`` for a live ``/debug/plan``."""
    return _LAST_PLAN_REPORT


def evaluate_candidate(
    builder: Any,
    candidate: Candidate,
    cost_model: CostModel,
    keep_doctor: bool = True,
) -> CandidateResult:
    """Score one candidate: validity -> shape-only compile -> doctor ->
    HBM feasibility -> cost breakdown. Never raises for a bad
    candidate — failures become pruned rows with the reason."""
    reason = builder.validity(candidate)
    if reason is not None:
        return CandidateResult(candidate=candidate, feasible=False,
                               prune_reason=reason)
    try:
        with builder.build(candidate) as built:
            report = doctor.diagnose(
                built["step"], *built["args"],
                intended=built.get("intended"),
                labels=built.get("labels"),
                mesh=built.get("mesh"),
            )
            bubble = float(built.get("bubble_fraction", 0.0))
    except Exception as e:  # noqa: BLE001 - one config must not kill the search
        return CandidateResult(
            candidate=candidate, feasible=False,
            prune_reason=f"build/compile failed: {type(e).__name__}: {e}"[:300],
        )
    hbm_reason = hbm_check(report, cost_model)
    if hbm_reason is not None:
        return CandidateResult(
            candidate=candidate, feasible=False, prune_reason=hbm_reason,
            doctor=report if keep_doctor else None,
        )
    breakdown = score_breakdown(
        candidate, report, cost_model,
        tokens_per_step=builder.tokens_per_step,
        bubble_fraction=bubble,
    )
    return CandidateResult(
        candidate=candidate, feasible=True,
        score=float(breakdown["score"]), breakdown=breakdown,
        doctor=report if keep_doctor else None,
    )


def set_planner_gauges(report: PlanReport, registry: Any = None) -> None:
    """``planner.candidates_evaluated`` / ``planner.pruned_infeasible``
    / ``planner.top1_score`` next to the doctor gauges
    (docs/observability.md). One branch when telemetry is disabled."""
    from pipegoose_tpu.telemetry.registry import get_registry

    reg = registry if registry is not None else get_registry()
    if not reg.enabled:
        return
    reg.gauge(
        "planner.candidates_evaluated",
        help="candidate layouts scored by the last planner run",
    ).set(float(len(report.candidates)))
    reg.gauge(
        "planner.pruned_infeasible",
        help="candidates pruned (HBM/validity/compile) in the last run",
    ).set(float(len(report.pruned)))
    top = report.top
    reg.gauge(
        "planner.top1_score",
        help="predicted tokens/s of the last planner run's best layout",
    ).set(float(top.score) if top else 0.0)


def run_plan(
    builder: Any,
    candidates: Iterable[Candidate],
    cost_model: Optional[CostModel] = None,
    keep_doctor: bool = True,
    registry: Any = None,
    progress: Any = None,
) -> PlanReport:
    """Evaluate every candidate and return the ranked
    :class:`PlanReport`. ``progress`` is an optional callable
    ``(index, total, result)`` the CLIs use for live output."""
    cost_model = cost_model or CostModel.for_device()
    cands = list(candidates)
    results = []
    for i, cand in enumerate(cands):
        res = evaluate_candidate(builder, cand, cost_model,
                                 keep_doctor=keep_doctor)
        results.append(res)
        if progress is not None:
            progress(i, len(cands), res)
    report = PlanReport(
        device_kind=cost_model.device_kind,
        n_devices=int(cands[0].n_devices) if cands else 1,
        model=builder.describe(),
        tokens_per_step=int(builder.tokens_per_step),
        cost_model=cost_model.to_json(),
        candidates=results,
    )
    report.sort()
    unmodeled = [r.name for r in results
                 if r.feasible and not r.breakdown.get("compute_modeled",
                                                       True)]
    if unmodeled:
        logger.warning(
            "planner: %d candidate(s) scored WITHOUT compute time (the "
            "backend reported no cost-analysis FLOPs) — ranking is "
            "comm-time only for: %s", len(unmodeled), unmodeled,
        )
    pruned = report.pruned
    logger.info(
        "planner: %d candidate(s) evaluated, %d pruned infeasible, top-1 %s",
        len(results), len(pruned),
        report.top.name if report.top else "<none>",
    )
    for p in pruned:
        logger.info("planner: pruned %s — %s", p.name, p.prune_reason)
    set_planner_gauges(report, registry=registry)
    global _LAST_PLAN_REPORT
    _LAST_PLAN_REPORT = report
    return report


def plan_layout_at(
    builder: Any,
    n_devices: int,
    *,
    pp_sizes: Any = (1,),
    ep_sizes: Any = (1,),
    grad_comms: Any = ("fp32",),
    overlap: Any = (False,),
    remat: Any = (True,),
    n_microbatches: int = 2,
    cost_model: Optional[CostModel] = None,
    keep_doctor: bool = False,
    registry: Any = None,
    progress: Any = None,
) -> PlanReport:
    """Rank the layout space at an ARBITRARY device count — the
    elasticity query: "a slice died, N devices survive; what is the
    best feasible (dp, tp, pp) now?". Same machinery as a full plan
    (every candidate is the real step, shape-only compiled and scored),
    restricted by default to the recovery-relevant axes: fp32 wire, no
    overlap/remat sweep — recovery wants ONE good layout fast, not an
    exhaustive study. ``ElasticRecovery`` (trainer/elastic.py) calls
    this through :func:`best_layout_at` with the run's own builder."""
    cands = enumerate_candidates(
        n_devices, pp_sizes=pp_sizes, ep_sizes=ep_sizes,
        grad_comms=grad_comms, overlap=overlap, remat=remat,
        n_microbatches=n_microbatches,
    )
    return run_plan(
        builder, cands, cost_model=cost_model, keep_doctor=keep_doctor,
        registry=registry, progress=progress,
    )


def best_layout_at(
    builder: Any, n_devices: int, **plan_kwargs: Any
) -> Optional[Candidate]:
    """The winning :class:`Candidate` of :func:`plan_layout_at` (None
    when NO layout at that device count is feasible — the caller must
    surface that, not guess)."""
    report = plan_layout_at(builder, n_devices, **plan_kwargs)
    top = report.top
    return top.candidate if top is not None else None
