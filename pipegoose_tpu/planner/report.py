"""PlanReport: the ranked, serializable output of one planner run.

Same artifact discipline as the mesh doctor's reports
(telemetry/doctor.py): dataclasses, ``to_json``/``from_json``
round-trip, ``format_table`` for humans, and FORWARD-COMPATIBLE
deserialization — every ``from_json`` picks known keys only, so a plan
artifact written by a newer version (extra fields at any level) still
loads in an older CLI's ``--check`` mode instead of crashing CI.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from pipegoose_tpu.planner.space import Candidate, candidate_key
from pipegoose_tpu.telemetry.doctor import DoctorReport, _fmt_bytes


@dataclasses.dataclass
class CandidateResult:
    """One scored (or pruned) candidate."""

    candidate: Candidate
    feasible: bool
    prune_reason: Optional[str] = None
    score: Optional[float] = None        # predicted global tokens/s
    breakdown: Dict[str, Any] = dataclasses.field(default_factory=dict)
    doctor: Optional[DoctorReport] = None
    measured: Optional[Dict[str, Any]] = None   # sweep/bench fill this in

    @property
    def name(self) -> str:
        return self.candidate.name

    def to_json(self) -> dict:
        return {
            "candidate": self.candidate.to_json(),
            "feasible": self.feasible,
            "prune_reason": self.prune_reason,
            "score": self.score,
            "breakdown": dict(self.breakdown),
            "doctor": self.doctor.to_json() if self.doctor else None,
            "measured": dict(self.measured) if self.measured else None,
        }

    @classmethod
    def from_json(cls, d: dict) -> "CandidateResult":
        return cls(
            candidate=Candidate.from_json(d["candidate"]),
            feasible=bool(d["feasible"]),
            prune_reason=d.get("prune_reason"),
            score=(None if d.get("score") is None else float(d["score"])),
            breakdown=dict(d.get("breakdown") or {}),
            doctor=(DoctorReport.from_json(d["doctor"])
                    if d.get("doctor") else None),
            measured=(dict(d["measured"]) if d.get("measured") else None),
        )


@dataclasses.dataclass
class PlanReport:
    """Ranked candidates (feasible best-first, then pruned) for one
    model/topology, plus the budgets they were scored against."""

    device_kind: str
    n_devices: int
    model: Dict[str, Any]
    tokens_per_step: int
    cost_model: Dict[str, Any]
    candidates: List[CandidateResult]

    # -- views -------------------------------------------------------------

    @property
    def ranked(self) -> List[CandidateResult]:
        return [c for c in self.candidates if c.feasible]

    @property
    def pruned(self) -> List[CandidateResult]:
        return [c for c in self.candidates if not c.feasible]

    @property
    def top(self) -> Optional[CandidateResult]:
        r = self.ranked
        return r[0] if r else None

    def find(self, want: Candidate) -> Optional[CandidateResult]:
        key = candidate_key(want)
        for c in self.candidates:
            if candidate_key(c.candidate) == key:
                return c
        return None

    def sort(self) -> None:
        """Feasible candidates by score descending, pruned last (stable
        within each group)."""
        self.candidates.sort(
            key=lambda c: (not c.feasible, -(c.score or 0.0))
        )

    # -- check gate --------------------------------------------------------

    def check(
        self, current: Candidate, tolerance: float = 0.25
    ) -> Tuple[bool, str]:
        """CI gate semantics: the currently-configured layout must be in
        the plan, feasible, and score at least ``(1 - tolerance)`` of
        the top-1. Returns (ok, human-readable message). The configured
        layout is canonicalized first (space.py) — a runtime-no-op flag
        like int8 wire on dp=1 matches its canonical twin instead of
        reading as 'not in the plan'."""
        from pipegoose_tpu.planner.space import canonicalize

        current = canonicalize(current)
        top = self.top
        if top is None:
            return False, "no feasible candidate in the plan"
        cur = self.find(current)
        if cur is None:
            return False, (
                f"configured layout {current.name} is not in the plan's "
                f"candidate space ({len(self.candidates)} candidates)"
            )
        if not cur.feasible:
            return False, (
                f"configured layout {cur.name} is infeasible: "
                f"{cur.prune_reason}"
            )
        floor = (1.0 - tolerance) * float(top.score or 0.0)
        if (cur.score or 0.0) < floor:
            return False, (
                f"configured layout {cur.name} predicts "
                f"{cur.score:,.0f} tokens/s < {1 - tolerance:.0%} of "
                f"top-1 {top.name} ({top.score:,.0f} tokens/s) — "
                f"re-plan or switch layouts"
            )
        return True, (
            f"configured layout {cur.name} scores {cur.score:,.0f} "
            f"tokens/s vs top-1 {top.name} {top.score:,.0f} "
            f"(within {tolerance:.0%})"
        )

    # -- predicted vs measured ---------------------------------------------

    def record_measurement(
        self, candidate: Candidate, measured: Dict[str, Any]
    ) -> Optional[CandidateResult]:
        """Attach a measured result (e.g. ``{"tokens_per_sec": x}``)
        to the matching candidate, recording the predicted-vs-measured
        delta in the artifact. Returns the updated result, or None if
        the candidate is not in the plan."""
        cur = self.find(candidate)
        if cur is None:
            return None
        m = dict(measured)
        if cur.score and m.get("tokens_per_sec"):
            m["predicted_tokens_per_sec"] = float(cur.score)
            m["measured_over_predicted"] = (
                float(m["tokens_per_sec"]) / float(cur.score)
            )
        cur.measured = m
        return cur

    def record_profile(
        self, candidate: Candidate, profile: Any
    ) -> Optional[CandidateResult]:
        """Attach a measured ``telemetry.xprof.StepProfile`` to the
        matching candidate — the component-level measurement
        (compute / per-axis collective / idle seconds) next to the
        aggregate tokens/s ``record_measurement`` tracks. Derives
        ``tokens_per_sec`` from the profile's fenced wall when the
        caller has not recorded one, so a profile alone closes the
        predicted-vs-measured loop. Returns the updated result, None if
        the candidate is not in the plan."""
        cur = self.find(candidate)
        if cur is None:
            return None
        pj = profile.to_json() if hasattr(profile, "to_json") \
            else dict(profile)
        m = dict(cur.measured or {})
        m["profile"] = pj
        wall = float(pj.get("wall_step_s") or 0.0)
        if not m.get("tokens_per_sec") and wall > 0:
            m["tokens_per_sec"] = self.tokens_per_step / wall
        return self.record_measurement(candidate, m)

    def calibration_observations(self) -> List[Dict[str, Any]]:
        """The ``CostModel.calibrate`` input: one observation per
        candidate carrying a recorded profile (static breakdown +
        measured components + the overlap flag)."""
        return [
            {
                "profile": c.measured["profile"],
                "breakdown": c.breakdown,
                "overlap_tp": bool(getattr(c.candidate, "overlap_tp",
                                           False)),
            }
            for c in self.candidates
            if c.measured and c.measured.get("profile")
        ]

    def calibrate_cost_model(self) -> Any:
        """Fit this plan's cost model to its recorded profiles
        (``CostModel.calibrate``) and return the calibrated model —
        feed it back through :meth:`rescore` to close the loop."""
        from pipegoose_tpu.planner.cost import CostModel

        return CostModel.from_json(self.cost_model).calibrate(
            self.calibration_observations()
        )

    def rescore(self, cost_model: Any) -> "PlanReport":
        """Re-score every feasible candidate (with an embedded doctor
        report) under a new cost model — the calibration loop's second
        half — refreshing scores, breakdowns, the stored model, and the
        measured-over-predicted ratios, then re-sorting. Candidates
        without a doctor report keep their old score (their compiled
        schedule is gone; logged via the returned report's
        ``cost_model["calibration"]`` provenance, never silently
        rescored from nothing)."""
        from pipegoose_tpu.planner.cost import score_breakdown

        for c in self.candidates:
            if not c.feasible or c.doctor is None:
                continue
            bubble = float(c.breakdown.get("bubble_fraction", 0.0))
            c.breakdown = score_breakdown(
                c.candidate, c.doctor, cost_model,
                tokens_per_step=self.tokens_per_step,
                bubble_fraction=bubble,
            )
            c.score = float(c.breakdown["score"])
            if c.measured and c.measured.get("tokens_per_sec") and c.score:
                c.measured["predicted_tokens_per_sec"] = c.score
                c.measured["measured_over_predicted"] = (
                    float(c.measured["tokens_per_sec"]) / c.score
                )
        self.cost_model = cost_model.to_json()
        self.sort()
        return self

    def predicted_vs_measured(self) -> Dict[str, Any]:
        """Summary of every measured candidate: per-candidate ratios
        plus whether the predicted-best and measured-best agree — the
        regression signal the sweep records next to the BENCH artifacts."""
        rows = [c for c in self.candidates if c.measured
                and c.measured.get("tokens_per_sec") is not None]
        if not rows:
            return {"measured": 0}
        best_measured = max(
            rows, key=lambda c: float(c.measured["tokens_per_sec"])
        )
        scored = [c for c in rows if c.score]
        best_predicted = (max(scored, key=lambda c: float(c.score))
                          if scored else None)
        return {
            "measured": len(rows),
            "predicted_best": best_predicted.name if best_predicted else None,
            "measured_best": best_measured.name,
            "rank_agreement": bool(
                best_predicted is not None
                and best_predicted.name == best_measured.name
            ),
            "per_candidate": {
                c.name: {
                    "predicted": c.score,
                    "measured": float(c.measured["tokens_per_sec"]),
                    "measured_over_predicted":
                        c.measured.get("measured_over_predicted"),
                }
                for c in rows
            },
        }

    # -- serialization -----------------------------------------------------

    def to_json(self) -> dict:
        return {
            "version": 1,
            "device_kind": self.device_kind,
            "n_devices": self.n_devices,
            "model": dict(self.model),
            "tokens_per_step": self.tokens_per_step,
            "cost_model": dict(self.cost_model),
            "candidates": [c.to_json() for c in self.candidates],
        }

    @classmethod
    def from_json(cls, d: dict) -> "PlanReport":
        return cls(
            device_kind=str(d["device_kind"]),
            n_devices=int(d["n_devices"]),
            model=dict(d.get("model") or {}),
            tokens_per_step=int(d.get("tokens_per_step", 0)),
            cost_model=dict(d.get("cost_model") or {}),
            candidates=[CandidateResult.from_json(c)
                        for c in d.get("candidates", [])],
        )

    # -- humans ------------------------------------------------------------

    def format_table(self, top_k: Optional[int] = None) -> str:
        from pipegoose_tpu.telemetry.doctor import _align

        lines = [
            f"plan: {self.n_devices} x {self.device_kind}  "
            f"model={self.model.get('name', '?')}  "
            f"tokens/step={self.tokens_per_step}",
            "",
        ]
        ranked = self.ranked
        shown = ranked if top_k is None else ranked[:top_k]
        if shown:
            rows = [("#", "candidate", "pred tok/s", "compute",
                     "comm", "bubble", "hbm peak")]
            for i, c in enumerate(shown):
                b = c.breakdown
                rows.append((
                    str(i + 1), c.name, f"{c.score:,.0f}",
                    f"{b.get('compute_seconds', 0) * 1e3:.2f}ms",
                    f"{b.get('comm_seconds', 0) * 1e3:.2f}ms",
                    f"{b.get('bubble_fraction', 0):.0%}",
                    _fmt_bytes(b.get("hbm_peak_bytes", 0)),
                ))
            lines += _align(rows)
            if top_k is not None and len(ranked) > top_k:
                lines.append(f"  ... {len(ranked) - top_k} more ranked "
                             f"candidate(s)")
        else:
            lines.append("  (no feasible candidate)")
        pruned = self.pruned
        if pruned:
            lines += ["", f"pruned ({len(pruned)}):"]
            lines += _align([("candidate", "reason")] + [
                (c.name, c.prune_reason or "?") for c in pruned
            ])
        return "\n".join(lines)
