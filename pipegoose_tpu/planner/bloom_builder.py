"""BLOOM builder for the planner: candidate -> compilable hybrid step.

Maps one :class:`~pipegoose_tpu.planner.space.Candidate` onto the SAME
production machinery the trainer uses — ``make_hybrid_train_step`` with
``bloom.loss_fn`` (dense) or ``bloom.loss_fn_pp`` (pipelined,
``grad_sync_axes=("pipe",)`` like tests/test_3d_parallel.py) — via the
enumeration hooks in ``parallel/hybrid.py``
(``parallel_context_sizes``/``hybrid_step_kwargs``), so the planner
scores the real compiled program, not a proxy.

Shape-only throughout: params come from ``jax.eval_shape`` over
``init_params`` + ``pad_for_tp`` (nothing materializes — a bloom-176b
plan needs no 350 GB of host RAM), and the step is never executed, only
lowered+compiled by the doctor.
"""
from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Dict, Optional

from pipegoose_tpu.planner.space import Candidate


class BloomPlanModel:
    """``builder`` protocol implementation (see planner/planner.py) for
    the BLOOM family at one (batch, seq) workload."""

    def __init__(self, config: Any, batch: int, seq: int,
                 lr: float = 1e-3):
        self.config = config
        self.batch = int(batch)
        self.seq = int(seq)
        self.lr = lr

    @property
    def tokens_per_step(self) -> int:
        return self.batch * self.seq

    def describe(self) -> Dict[str, Any]:
        cfg = self.config
        return {
            "name": f"bloom(v={cfg.vocab_size},h={cfg.hidden_size},"
                    f"L={cfg.n_layer},heads={cfg.n_head})",
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.hidden_size,
            "n_layer": cfg.n_layer,
            "n_head": cfg.n_head,
            "dtype": str(getattr(cfg, "dtype", "float32")),
            "batch": self.batch,
            "seq": self.seq,
        }

    # -- model-divisibility pruning ---------------------------------------

    def validity(self, c: Candidate) -> Optional[str]:
        cfg = self.config
        if c.ep > 1:
            return "dense BLOOM has no expert axis (ep > 1 needs a MoE model)"
        if cfg.n_head % c.tp:
            return f"n_head {cfg.n_head} not divisible by tp={c.tp}"
        if cfg.hidden_size % c.tp:
            return f"hidden {cfg.hidden_size} not divisible by tp={c.tp}"
        if self.batch % c.dp:
            return f"batch {self.batch} not divisible by dp={c.dp}"
        if c.overlap_tp and self.seq % c.tp:
            return (f"overlap_tp needs seq % tp == 0 "
                    f"(seq={self.seq}, tp={c.tp})")
        if c.pp > 1:
            if cfg.n_layer % c.pp:
                return f"n_layer {cfg.n_layer} not divisible by pp={c.pp}"
            local_batch = self.batch // c.dp
            if local_batch % c.n_microbatches:
                return (f"per-replica batch {local_batch} not divisible "
                        f"by {c.n_microbatches} microbatches")
        return None

    # -- step construction --------------------------------------------------

    @contextlib.contextmanager
    def build(self, c: Candidate):
        import jax
        import jax.numpy as jnp
        import optax

        from pipegoose_tpu.distributed import ParallelContext
        from pipegoose_tpu.models import bloom
        from pipegoose_tpu.optim.zero import DistributedOptimizer
        from pipegoose_tpu.parallel import (
            hybrid_step_kwargs,
            make_hybrid_train_step,
            parallel_context_sizes,
            train_step_intended_specs,
        )

        cfg = dataclasses.replace(
            self.config, overlap_tp=c.overlap_tp, remat=c.remat
        )

        # shape-only padded params; the post-padding config is derived
        # from the SDS embedding shape (pad_for_tp:525-533's math)
        def _padded(key):
            p = bloom.init_params(cfg, key)
            p, _ = bloom.pad_for_tp(p, cfg, c.tp)
            return p

        p_sds = jax.eval_shape(_padded, jax.random.PRNGKey(0))
        v_padded = p_sds["embed"]["weight"].shape[0]
        if v_padded != cfg.vocab_size:
            cfg = dataclasses.replace(
                cfg, vocab_size=v_padded,
                valid_vocab_size=cfg.valid_vocab_size or cfg.vocab_size,
            )

        ctx = ParallelContext(**parallel_context_sizes(c))
        try:
            if c.pp > 1:
                specs = bloom.pp_specs(p_sds)
                n_micro = c.n_microbatches

                def loss_fn(p, ids):
                    return bloom.loss_fn_pp(
                        p, ids, None, ids, cfg, n_micro,
                        tp_axis="tensor", pipe_axis="pipe",
                    )
            else:
                specs = bloom.tp_specs(p_sds)

                def loss_fn(p, ids):
                    return bloom.loss_fn(
                        p, ids, None, ids, cfg, tp_axis="tensor"
                    )

            opt = DistributedOptimizer(
                optax.adam(self.lr), axis_name="data",
                grad_comm=c.grad_comm,
            )
            init_fn, make_step = make_hybrid_train_step(
                loss_fn, specs, opt, ctx, **hybrid_step_kwargs(c)
            )
            opt_sds = jax.eval_shape(init_fn, p_sds)
            step = make_step(p_sds)
            batch_sds = jax.ShapeDtypeStruct(
                (self.batch, self.seq), jnp.int32
            )
            bubble = 0.0
            if c.pp > 1:
                from pipegoose_tpu.nn.pipeline_parallel.scheduler import (
                    GPipeScheduler,
                )

                bubble = GPipeScheduler(
                    c.n_microbatches, c.pp
                ).bubble_fraction
            yield {
                "step": step,
                "args": (p_sds, opt_sds, batch_sds),
                "intended": train_step_intended_specs(
                    opt, p_sds, specs, ctx.mesh
                ),
                "labels": ("params", "opt_state", "batch"),
                "mesh": ctx.mesh,
                "bubble_fraction": bubble,
            }
        finally:
            ctx.destroy()
