"""Static cost model: compiled program -> predicted step time.

Everything here is computed from artifacts a shape-only lower+compile
already produced (telemetry/doctor.py) plus the per-chip spec tables
next to ``PEAK_FLOPS`` (telemetry/derived.py) — no hardware, no
execution:

- compute seconds: XLA cost-analysis FLOPs of the per-device SPMD
  program over the chip's peak;
- comm seconds: the doctor's per-collective wire-byte estimates
  (``estimated_wire_bytes`` — payload conventions normalized per op)
  grouped by the mesh axes each collective spans, divided by the
  fabric bandwidth those axes ride (ICI inside a slice, DCI for
  cross-slice axes like the DiLoCo outer loop); the ring-overlap path
  hides a configured fraction of the tensor-axis traffic behind the
  partial matmuls;
- pipeline bubble: the analytic idle fraction from the schedulers
  (``GPipeScheduler``/``OneFOneBScheduler.bubble_fraction``) inflates
  the busy time;
- HBM feasibility: the doctor's per-device peak vs the chip budget —
  an infeasible candidate is pruned with the numbers in the reason.

The model ranks layouts; it does not promise wall-clock accuracy. The
``sweep_tpu_perf.py plan`` mode measures the top-K and records the
predicted-vs-measured delta next to the plan artifact (docs/planner.md).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from pipegoose_tpu.telemetry.derived import (
    dci_bytes_per_s_for,
    hbm_bytes_for,
    ici_bytes_per_s_for,
    peak_flops_for,
)
from pipegoose_tpu.telemetry.doctor import wire_bytes_by_axes


@dataclasses.dataclass
class CostModel:
    """Per-chip budgets + scoring knobs for one target device kind."""

    device_kind: str = "cpu"
    peak_flops: float = 1e12
    ici_bytes_per_s: float = 10e9
    dci_bytes_per_s: float = 1e9
    hbm_bytes: float = 16 * 1024**3
    # mesh axes that ride the data-center network instead of ICI
    dci_axes: Tuple[str, ...] = ("diloco",)
    # fraction of tensor-axis wire time the ring collective-matmul
    # overlap hides behind partial matmuls (docs/comm.md measured the
    # hops interleaving with tp-1 partial matmuls; 0.75 is the planner's
    # deliberately conservative default)
    overlap_hidden_fraction: float = 0.75

    @classmethod
    def for_device(
        cls,
        device_kind: Optional[str] = None,
        hbm_bytes: Optional[float] = None,
    ) -> "CostModel":
        """Budgets from the spec tables (telemetry/derived.py) for a
        device-kind string; defaults to the first visible device.
        ``hbm_bytes`` overrides the table (plan for a chip you don't
        have)."""
        if device_kind is None:
            import jax

            dev = jax.devices()[0]
            device_kind = getattr(dev, "device_kind", dev.platform)
        return cls(
            device_kind=device_kind,
            peak_flops=peak_flops_for(device_kind),
            ici_bytes_per_s=ici_bytes_per_s_for(device_kind),
            dci_bytes_per_s=dci_bytes_per_s_for(device_kind),
            hbm_bytes=(float(hbm_bytes) if hbm_bytes is not None
                       else hbm_bytes_for(device_kind)),
        )

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "CostModel":
        base = cls()
        return cls(
            device_kind=str(d.get("device_kind", base.device_kind)),
            peak_flops=float(d.get("peak_flops", base.peak_flops)),
            ici_bytes_per_s=float(d.get("ici_bytes_per_s",
                                        base.ici_bytes_per_s)),
            dci_bytes_per_s=float(d.get("dci_bytes_per_s",
                                        base.dci_bytes_per_s)),
            hbm_bytes=float(d.get("hbm_bytes", base.hbm_bytes)),
            dci_axes=tuple(d.get("dci_axes", base.dci_axes)),
            overlap_hidden_fraction=float(
                d.get("overlap_hidden_fraction",
                      base.overlap_hidden_fraction)),
        )

    def bandwidth_for_axes(self, axes: Tuple[str, ...]) -> float:
        if any(ax in self.dci_axes for ax in axes):
            return self.dci_bytes_per_s
        return self.ici_bytes_per_s


def hbm_check(report: Any, cost_model: CostModel) -> Optional[str]:
    """None when the compiled program fits the chip, else the prune
    reason with the numbers. The live backend ``bytes_limit`` wins
    where the doctor saw one (a real TPU); the spec-table budget covers
    fake-device planning."""
    from pipegoose_tpu.telemetry.doctor import _fmt_bytes

    budget = float(report.memory.hbm_limit or cost_model.hbm_bytes)
    peak = float(report.memory.peak_bytes)
    if peak > budget:
        return (f"HBM-infeasible: per-device peak {_fmt_bytes(int(peak))} "
                f"> budget {_fmt_bytes(int(budget))} "
                f"({cost_model.device_kind})")
    return None


def score_breakdown(
    candidate: Any,
    report: Any,
    cost_model: CostModel,
    tokens_per_step: int,
    bubble_fraction: float = 0.0,
) -> Dict[str, Any]:
    """The per-candidate score anatomy (docs/planner.md):

    {"score" (predicted global tokens/s — the ranking key),
     "step_seconds", "compute_seconds", "comm_seconds",
     "comm_seconds_by_axes", "wire_bytes_by_axes", "bubble_fraction",
     "flops_per_device", "hbm_peak_bytes", "hbm_budget_bytes",
     "tokens_per_step"}.

    All candidates score the SAME global batch, so the tokens/s ranking
    is exactly the inverse step-time ranking.
    """
    # a backend without AOT cost analysis yields cost_flops=None
    # (doctor.py treats it as advisory): the ranking then rests on comm
    # time alone — carried as an explicit compute_modeled=False marker
    # in the breakdown, and run_plan logs it, never a silent zero
    compute_modeled = report.cost_flops is not None
    flops = float(report.cost_flops or 0.0)
    compute_s = flops / cost_model.peak_flops
    wire = wire_bytes_by_axes(report)
    comm_by_axes: Dict[str, float] = {}
    wire_by_axes: Dict[str, int] = {}
    overlap_on = bool(getattr(candidate, "overlap_tp", False))
    for axes, nbytes in sorted(wire.items()):
        t = nbytes / cost_model.bandwidth_for_axes(axes)
        if overlap_on and axes == ("tensor",):
            t *= 1.0 - cost_model.overlap_hidden_fraction
        key = "+".join(axes) if axes else "?"
        comm_by_axes[key] = comm_by_axes.get(key, 0.0) + t
        wire_by_axes[key] = wire_by_axes.get(key, 0) + int(nbytes)
    comm_s = sum(comm_by_axes.values())
    busy_s = compute_s + comm_s
    bubble = min(max(float(bubble_fraction), 0.0), 0.99)
    step_s = busy_s / (1.0 - bubble) if busy_s > 0 else 0.0
    score = tokens_per_step / step_s if step_s > 0 else 0.0
    return {
        "score": score,
        "step_seconds": step_s,
        "compute_modeled": compute_modeled,
        "compute_seconds": compute_s,
        "comm_seconds": comm_s,
        "comm_seconds_by_axes": comm_by_axes,
        "wire_bytes_by_axes": wire_by_axes,
        "bubble_fraction": bubble,
        "flops_per_device": flops,
        "hbm_peak_bytes": int(report.memory.peak_bytes),
        "hbm_budget_bytes": int(report.memory.hbm_limit
                                or cost_model.hbm_bytes),
        "tokens_per_step": int(tokens_per_step),
    }
