"""Static cost model: compiled program -> predicted step time.

Everything here is computed from artifacts a shape-only lower+compile
already produced (telemetry/doctor.py) plus the per-chip spec tables
next to ``PEAK_FLOPS`` (telemetry/derived.py) — no hardware, no
execution:

- compute seconds: XLA cost-analysis FLOPs of the per-device SPMD
  program over the chip's peak;
- comm seconds: the doctor's per-collective wire-byte estimates
  (``estimated_wire_bytes`` — payload conventions normalized per op)
  grouped by the mesh axes each collective spans, divided by the
  fabric bandwidth those axes ride (ICI inside a slice, DCI for
  cross-slice axes like the DiLoCo outer loop); the ring-overlap path
  hides a configured fraction of the tensor-axis traffic behind the
  partial matmuls;
- pipeline bubble: the analytic idle fraction from the schedulers
  (``GPipeScheduler``/``OneFOneBScheduler.bubble_fraction``) inflates
  the busy time;
- HBM feasibility: the doctor's per-device peak vs the chip budget —
  an infeasible candidate is pruned with the numbers in the reason.

The model ranks layouts; it does not promise wall-clock accuracy. The
``sweep_tpu_perf.py plan`` mode measures the top-K and records the
predicted-vs-measured delta next to the plan artifact (docs/planner.md).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from pipegoose_tpu.telemetry.derived import (
    DCI_AXES,
    dci_bytes_per_s_for,
    hbm_bytes_for,
    ici_bytes_per_s_for,
    peak_flops_for,
)
from pipegoose_tpu.telemetry.doctor import wire_bytes_by_axes


@dataclasses.dataclass
class CostModel:
    """Per-chip budgets + scoring knobs for one target device kind."""

    device_kind: str = "cpu"
    peak_flops: float = 1e12
    ici_bytes_per_s: float = 10e9
    dci_bytes_per_s: float = 1e9
    hbm_bytes: float = 16 * 1024**3
    # mesh axes that ride the data-center network instead of ICI (the
    # shared definition lives in telemetry/derived.py next to the
    # bandwidth tables; override per model for custom topologies)
    dci_axes: Tuple[str, ...] = DCI_AXES
    # fraction of tensor-axis wire time the ring collective-matmul
    # overlap hides behind partial matmuls (docs/comm.md measured the
    # hops interleaving with tp-1 partial matmuls; 0.75 is the planner's
    # deliberately conservative default — calibrate() replaces it with
    # the MEASURED value)
    overlap_hidden_fraction: float = 0.75
    # fixed cost per collective INSTRUCTION (launch/dispatch latency) —
    # 0.0 in the uncalibrated spec-table model (bandwidth-only), fit by
    # calibrate() from measured profiles: small collectives are
    # launch-bound, and a model that prices them at bytes/bandwidth
    # alone calls a 40-instruction schedule free
    collective_launch_s: float = 0.0
    # fixed per-step time outside compute+comm (host dispatch, gaps) —
    # 0.0 uncalibrated, fit from the measured idle component
    step_overhead_s: float = 0.0
    # per-HLO-instruction dispatch/thunk cost — 0.0 uncalibrated, fit
    # jointly with step_overhead_s from (instruction count, idle)
    # samples: on a dispatch-bound backend (the CPU smoke) the step
    # wall ranks by instruction count, and a model blind to it cannot
    # reproduce the measured ranking
    dispatch_s_per_instruction: float = 0.0
    # provenance of a calibrated model: the fitted efficiencies + the
    # sample counts they rest on (None = uncalibrated spec tables)
    calibration: Optional[Dict[str, Any]] = None

    @classmethod
    def for_device(
        cls,
        device_kind: Optional[str] = None,
        hbm_bytes: Optional[float] = None,
    ) -> "CostModel":
        """Budgets from the spec tables (telemetry/derived.py) for a
        device-kind string; defaults to the first visible device.
        ``hbm_bytes`` overrides the table (plan for a chip you don't
        have)."""
        if device_kind is None:
            import jax

            dev = jax.devices()[0]
            device_kind = getattr(dev, "device_kind", dev.platform)
        return cls(
            device_kind=device_kind,
            peak_flops=peak_flops_for(device_kind),
            ici_bytes_per_s=ici_bytes_per_s_for(device_kind),
            dci_bytes_per_s=dci_bytes_per_s_for(device_kind),
            hbm_bytes=(float(hbm_bytes) if hbm_bytes is not None
                       else hbm_bytes_for(device_kind)),
        )

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "CostModel":
        base = cls()
        return cls(
            device_kind=str(d.get("device_kind", base.device_kind)),
            peak_flops=float(d.get("peak_flops", base.peak_flops)),
            ici_bytes_per_s=float(d.get("ici_bytes_per_s",
                                        base.ici_bytes_per_s)),
            dci_bytes_per_s=float(d.get("dci_bytes_per_s",
                                        base.dci_bytes_per_s)),
            hbm_bytes=float(d.get("hbm_bytes", base.hbm_bytes)),
            dci_axes=tuple(d.get("dci_axes", base.dci_axes)),
            overlap_hidden_fraction=float(
                d.get("overlap_hidden_fraction",
                      base.overlap_hidden_fraction)),
            collective_launch_s=float(
                d.get("collective_launch_s", base.collective_launch_s)),
            step_overhead_s=float(
                d.get("step_overhead_s", base.step_overhead_s)),
            dispatch_s_per_instruction=float(
                d.get("dispatch_s_per_instruction",
                      base.dispatch_s_per_instruction)),
            calibration=(dict(d["calibration"])
                         if d.get("calibration") else None),
        )

    def bandwidth_for_axes(self, axes: Tuple[str, ...]) -> float:
        if any(ax in self.dci_axes for ax in axes):
            return self.dci_bytes_per_s
        return self.ici_bytes_per_s

    def fabric_for_axes(self, axes: Tuple[str, ...]) -> str:
        return "dci" if any(ax in self.dci_axes for ax in axes) else "ici"

    # -- measured-delta calibration ----------------------------------------

    def calibrate(self, observations: Any) -> "CostModel":
        """Fit the model's constants to MEASURED step profiles and
        return the calibrated copy (self untouched).

        ``observations``: iterable of dicts, one per profiled
        candidate —

        - ``"profile"``: a ``telemetry.xprof.StepProfile`` (or its
          ``to_json()`` dict) of the candidate's real compiled step;
        - ``"breakdown"``: that candidate's STATIC score anatomy
          (``score_breakdown`` output: ``wire_bytes_by_axes``,
          ``collective_counts_by_axes``, ``flops_per_device``);
        - ``"overlap_tp"``: optional bool (default False) — overlap
          candidates' tensor-axis buckets feed the hidden-fraction fit,
          not the bandwidth fit (their measured time is post-overlap).

        Fits, in order (each falls back to the current constant when no
        sample supports it, recorded in ``calibration``):

        1. **flops efficiency** — median of achieved FLOP/s
           (``flops_per_device / compute_s``) over the spec-table peak;
           scales ``peak_flops``.
        2. **per-fabric bandwidth + launch cost** — least squares of
           measured bucket seconds against ``n_instructions * launch +
           bytes / bandwidth`` over every non-overlapped axes bucket;
           scales ``ici_bytes_per_s`` / ``dci_bytes_per_s`` and sets
           ``collective_launch_s`` (small collectives are launch-bound;
           a bytes-only model cannot rank schedules that differ mostly
           in instruction count).
        3. **measured overlap_hidden_fraction** — 1 - measured/expected
           un-overlapped tensor-axis time on overlap candidates,
           medianed and clamped to [0, 0.95].
        4. **step overhead** — median measured idle component (host
           dispatch + gaps the busy-time model never sees).
        """
        import statistics

        obs = []
        for o in observations:
            prof = o.get("profile")
            if prof is not None and hasattr(prof, "to_json"):
                prof = prof.to_json()
            if not prof:
                continue
            obs.append({
                "profile": prof,
                "breakdown": dict(o.get("breakdown") or {}),
                "overlap_tp": bool(o.get("overlap_tp", False)),
            })
        cal: Dict[str, Any] = {"observations": len(obs)}
        if not obs:
            return dataclasses.replace(self, calibration=cal)

        # 1) flops efficiency
        eff_samples = []
        for o in obs:
            flops = o["breakdown"].get("flops_per_device") \
                or o["profile"].get("flops_per_device")
            comp = float(o["profile"].get("compute_s") or 0.0)
            if flops and comp > 0:
                eff_samples.append(float(flops) / comp / self.peak_flops)
        flops_eff = (statistics.median(eff_samples)
                     if eff_samples else 1.0)
        cal["flops_efficiency"] = flops_eff
        cal["flops_samples"] = len(eff_samples)

        # 2) per-fabric bandwidth + launch: samples are (n, bytes, secs)
        per_fabric: Dict[str, list] = {"ici": [], "dci": []}
        overlap_samples = []  # (n, bytes, secs) of overlap tensor buckets
        for o in obs:
            wire = o["breakdown"].get("wire_bytes_by_axes") or {}
            counts = o["breakdown"].get("collective_counts_by_axes") or {}
            measured = o["profile"].get("comm_by_axes") or {}
            for key, secs in measured.items():
                nbytes = float(wire.get(key, 0.0))
                n = float(counts.get(key, 0.0))
                if secs <= 0 or (nbytes <= 0 and n <= 0):
                    continue
                axes = tuple(key.split("+")) if key != "?" else ()
                if o["overlap_tp"] and axes == ("tensor",):
                    overlap_samples.append((n, nbytes, float(secs)))
                    continue
                per_fabric[self.fabric_for_axes(axes)].append(
                    (n, nbytes, float(secs))
                )
        bw = {"ici": self.ici_bytes_per_s, "dci": self.dci_bytes_per_s}
        launch_samples = []
        for fabric, samples in per_fabric.items():
            if not samples:
                continue
            import numpy as np

            a = np.array([[n, b] for n, b, _ in samples], dtype=float)
            y = np.array([s for _, _, s in samples], dtype=float)
            launch = inv_bw = None
            if len(samples) >= 2 and np.linalg.matrix_rank(a) == 2:
                sol, *_ = np.linalg.lstsq(a, y, rcond=None)
                launch, inv_bw = float(sol[0]), float(sol[1])
            if launch is None or launch < 0 or inv_bw is None or inv_bw <= 0:
                # degenerate fit (few buckets, uniform bytes, or a
                # negative coefficient): split the aggregate measured
                # time evenly between the two terms — but only when
                # BOTH exist (counts absent in a pre-calibration
                # artifact must not halve the fitted bandwidth; bytes
                # absent must not zero the launch cost)
                tot_n = sum(n for n, _, _ in samples)
                tot_b = sum(b for _, b, _ in samples)
                tot_s = sum(s for _, _, s in samples)
                if tot_b > 0 and tot_n > 0:
                    inv_bw = tot_s / tot_b / 2.0
                    launch = tot_s / 2.0 / tot_n
                elif tot_b > 0:
                    inv_bw = tot_s / tot_b
                    launch = 0.0
                else:
                    inv_bw = 1.0 / bw[fabric]
                    launch = (tot_s / tot_n) if tot_n else 0.0
            bw[fabric] = 1.0 / inv_bw
            launch_samples.append(launch)
            cal[f"{fabric}_bandwidth_efficiency"] = (
                bw[fabric] / (self.ici_bytes_per_s if fabric == "ici"
                              else self.dci_bytes_per_s)
            )
            cal[f"{fabric}_samples"] = len(samples)
        launch_s = (statistics.median(launch_samples)
                    if launch_samples else self.collective_launch_s)
        launch_s = max(float(launch_s), 0.0)
        cal["collective_launch_s"] = launch_s

        # 3) measured overlap hidden fraction
        hidden = self.overlap_hidden_fraction
        if overlap_samples:
            hs = []
            for n, nbytes, secs in overlap_samples:
                expected = n * launch_s + nbytes / bw["ici"]
                if expected > 0:
                    hs.append(1.0 - secs / expected)
            if hs:
                hidden = min(max(statistics.median(hs), 0.0), 0.95)
        cal["overlap_hidden_fraction"] = hidden
        cal["overlap_samples"] = len(overlap_samples)

        # 4) per-step overhead from the measured idle component: joint
        # (base, per-instruction) fit over (n_instr, idle) samples —
        # idle on a dispatch-bound backend scales with the instruction
        # count (static, per candidate), so a flat median would erase
        # exactly the differences the re-scored ranking needs
        import numpy as np

        idle_samples = []
        for o in obs:
            idle = float(o["profile"].get("idle_s") or 0.0)
            n = (o["breakdown"].get("hlo_instructions")
                 or o["profile"].get("hlo_instructions"))
            idle_samples.append((float(n) if n else 0.0, idle))
        overhead = dispatch = 0.0
        if idle_samples:
            ns = {n for n, _ in idle_samples}
            if len(ns) >= 2:
                a = np.array([[1.0, n] for n, _ in idle_samples])
                y = np.array([i for _, i in idle_samples])
                sol, *_ = np.linalg.lstsq(a, y, rcond=None)
                # a base within float noise of zero is zero, not a
                # reason to throw the fit away
                overhead = max(float(sol[0]), 0.0)
                dispatch = float(sol[1])
            if dispatch <= 0:
                overhead = statistics.median([i for _, i in idle_samples])
                dispatch = 0.0
        cal["step_overhead_s"] = overhead
        cal["dispatch_s_per_instruction"] = dispatch

        return dataclasses.replace(
            self,
            peak_flops=self.peak_flops * flops_eff,
            ici_bytes_per_s=bw["ici"],
            dci_bytes_per_s=bw["dci"],
            overlap_hidden_fraction=hidden,
            collective_launch_s=launch_s,
            step_overhead_s=overhead,
            dispatch_s_per_instruction=dispatch,
            calibration=cal,
        )


def hbm_check(report: Any, cost_model: CostModel) -> Optional[str]:
    """None when the compiled program fits the chip, else the prune
    reason with the numbers. The live backend ``bytes_limit`` wins
    where the doctor saw one (a real TPU); the spec-table budget covers
    fake-device planning."""
    from pipegoose_tpu.telemetry.doctor import _fmt_bytes

    budget = float(report.memory.hbm_limit or cost_model.hbm_bytes)
    peak = float(report.memory.peak_bytes)
    if peak > budget:
        return (f"HBM-infeasible: per-device peak {_fmt_bytes(int(peak))} "
                f"> budget {_fmt_bytes(int(budget))} "
                f"({cost_model.device_kind})")
    return None


def score_breakdown(
    candidate: Any,
    report: Any,
    cost_model: CostModel,
    tokens_per_step: int,
    bubble_fraction: float = 0.0,
) -> Dict[str, Any]:
    """The per-candidate score anatomy (docs/planner.md):

    {"score" (predicted global tokens/s — the ranking key),
     "step_seconds", "compute_seconds", "comm_seconds",
     "comm_seconds_by_axes", "wire_bytes_by_axes", "bubble_fraction",
     "flops_per_device", "hbm_peak_bytes", "hbm_budget_bytes",
     "tokens_per_step"}.

    All candidates score the SAME global batch, so the tokens/s ranking
    is exactly the inverse step-time ranking.
    """
    # a backend without AOT cost analysis yields cost_flops=None
    # (doctor.py treats it as advisory): the ranking then rests on comm
    # time alone — carried as an explicit compute_modeled=False marker
    # in the breakdown, and run_plan logs it, never a silent zero
    compute_modeled = report.cost_flops is not None
    flops = float(report.cost_flops or 0.0)
    compute_s = flops / cost_model.peak_flops
    wire = wire_bytes_by_axes(report)
    # instruction counts per axes bucket: the launch-cost numerator (a
    # calibrated model prices dispatch-bound small collectives by
    # count, not bytes) and the calibration fit's sample shape
    sharding = getattr(report, "sharding", report)
    counts: Dict[str, int] = {}
    for c in sharding.collectives:
        key = "+".join(c.mesh_axes) if c.mesh_axes else "?"
        counts[key] = counts.get(key, 0) + 1
    comm_by_axes: Dict[str, float] = {}
    wire_by_axes: Dict[str, int] = {}
    overlap_on = bool(getattr(candidate, "overlap_tp", False))
    for axes, nbytes in sorted(wire.items()):
        key = "+".join(axes) if axes else "?"
        t = (nbytes / cost_model.bandwidth_for_axes(axes)
             + counts.get(key, 0) * cost_model.collective_launch_s)
        if overlap_on and axes == ("tensor",):
            t *= 1.0 - cost_model.overlap_hidden_fraction
        comm_by_axes[key] = comm_by_axes.get(key, 0.0) + t
        wire_by_axes[key] = wire_by_axes.get(key, 0) + int(nbytes)
    comm_s = sum(comm_by_axes.values())
    busy_s = compute_s + comm_s
    bubble = min(max(float(bubble_fraction), 0.0), 0.99)
    step_s = busy_s / (1.0 - bubble) if busy_s > 0 else 0.0
    n_instr = int(getattr(report, "hlo_instructions", None) or 0)
    overhead_s = (cost_model.step_overhead_s
                  + cost_model.dispatch_s_per_instruction * n_instr)
    step_s += overhead_s
    score = tokens_per_step / step_s if step_s > 0 else 0.0
    return {
        "score": score,
        "step_seconds": step_s,
        "compute_modeled": compute_modeled,
        "compute_seconds": compute_s,
        "comm_seconds": comm_s,
        "comm_seconds_by_axes": comm_by_axes,
        "wire_bytes_by_axes": wire_by_axes,
        "collective_counts_by_axes": counts,
        "hlo_instructions": n_instr or None,
        "overhead_seconds": overhead_s,
        "bubble_fraction": bubble,
        "flops_per_device": flops,
        "hbm_peak_bytes": int(report.memory.peak_bytes),
        "hbm_budget_bytes": int(report.memory.hbm_limit
                                or cost_model.hbm_bytes),
        "tokens_per_step": int(tokens_per_step),
    }
