"""Candidate layout space: every way to run a model on N chips.

GSPMD (arxiv 2105.04663) and Mesh-TensorFlow (arxiv 1811.02084) frame
layout choice as *the* scaling decision; this module makes the choice
set explicit and finite. A :class:`Candidate` is one point in the
(dp, tp, pp, ep) x overlap x grad_comm x remat space;
:func:`enumerate_candidates` walks the device count's factorizations
crossed with the engine options, applying only LAYOUT-level dedup rules
(an overlap flag on tp=1 or a wire format on dp=1 changes nothing, so
those duplicates are skipped, not pruned). Model-specific feasibility
(head divisibility, sequence divisibility for the overlap path, HBM)
belongs to the builder/planner, which prunes WITH A REASON — the
enumeration itself never silently drops a distinct config.
"""
from __future__ import annotations

import dataclasses
from typing import Iterable, List, Optional, Sequence, Tuple

GRAD_COMMS: Tuple[str, ...] = ("fp32", "bf16", "int8")


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One parallelism layout + engine-option choice."""

    dp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1
    overlap_tp: bool = False
    grad_comm: str = "fp32"
    remat: bool = True
    n_microbatches: int = 1   # pipeline microbatches; meaningful when pp > 1

    def __post_init__(self):
        for ax in ("dp", "tp", "pp", "ep"):
            if getattr(self, ax) < 1:
                raise ValueError(f"{ax} must be >= 1, got {getattr(self, ax)}")
        if self.grad_comm not in GRAD_COMMS:
            raise ValueError(
                f"grad_comm must be one of {GRAD_COMMS}, got {self.grad_comm!r}"
            )

    @property
    def n_devices(self) -> int:
        return self.dp * self.tp * self.pp * self.ep

    @property
    def name(self) -> str:
        parts = [f"dp{self.dp}", f"tp{self.tp}"]
        if self.pp > 1:
            parts.append(f"pp{self.pp}")
        if self.ep > 1:
            parts.append(f"ep{self.ep}")
        s = "x".join(parts)
        if self.pp > 1:
            s += f"+m{self.n_microbatches}"
        if self.overlap_tp:
            s += "+overlap"
        if self.grad_comm != "fp32":
            s += f"+{self.grad_comm}"
        if not self.remat:
            s += "+noremat"
        return s

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: dict) -> "Candidate":
        # known keys only, and VALUES survive too: a wire format this
        # version doesn't know (a newer artifact's grad_comm) loads
        # losslessly instead of tripping __post_init__ — deserialization
        # must not enforce the constructor's enum (forward compat)
        gc = str(d.get("grad_comm", "fp32"))
        c = cls(
            dp=int(d.get("dp", 1)), tp=int(d.get("tp", 1)),
            pp=int(d.get("pp", 1)), ep=int(d.get("ep", 1)),
            overlap_tp=bool(d.get("overlap_tp", False)),
            grad_comm=gc if gc in GRAD_COMMS else "fp32",
            remat=bool(d.get("remat", True)),
            n_microbatches=int(d.get("n_microbatches", 1)),
        )
        if gc not in GRAD_COMMS:
            object.__setattr__(c, "grad_comm", gc)
        return c


def canonicalize(c: Candidate) -> Candidate:
    """The canonical twin of a candidate: options that are layout
    no-ops dropped — overlap needs a tensor axis and the dense path,
    a non-fp32 wire format needs a data axis, microbatches need a
    pipeline. Enumeration emits only canonical forms; a configured
    layout must be canonicalized the same way before matching against
    a plan (``PlanReport.check`` does this), or a runtime-no-op flag
    would read as 'not in the plan'."""
    return Candidate(
        dp=c.dp, tp=c.tp, pp=c.pp, ep=c.ep,
        overlap_tp=c.overlap_tp and c.tp > 1 and c.pp == 1,
        grad_comm=c.grad_comm if c.dp > 1 else "fp32",
        remat=c.remat,
        n_microbatches=c.n_microbatches if c.pp > 1 else 1,
    )


def divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def mesh_factorizations(
    n_devices: int,
    pp_sizes: Sequence[int] = (1,),
    ep_sizes: Sequence[int] = (1,),
) -> List[Tuple[int, int, int, int]]:
    """All (dp, tp, pp, ep) splits of ``n_devices``: for every requested
    pp/ep size that divides the device count, every (dp, tp) split of
    the remainder. Deterministic order — dp descending (the pure-DP
    layout first, matching how operators usually escalate)."""
    out: List[Tuple[int, int, int, int]] = []
    for pp in pp_sizes:
        for ep in ep_sizes:
            if pp < 1 or ep < 1 or n_devices % (pp * ep):
                continue
            rem = n_devices // (pp * ep)
            for tp in divisors(rem):
                out.append((rem // tp, tp, pp, ep))
    return out


def enumerate_candidates(
    n_devices: int,
    pp_sizes: Sequence[int] = (1,),
    ep_sizes: Sequence[int] = (1,),
    grad_comms: Sequence[str] = GRAD_COMMS,
    overlap: Sequence[bool] = (False, True),
    remat: Sequence[bool] = (True, False),
    n_microbatches: int = 2,
) -> List[Candidate]:
    """The candidate list the planner scores. Layout-level dedup only:

    - ``overlap_tp`` needs a tensor axis (> 1) and the dense path
      (pp == 1 — the PP composition ignores the flag), so those combos
      collapse onto their overlap-off twin;
    - a non-fp32 ``grad_comm`` with dp == 1 reduces over a size-1 axis
      (no wire), so it collapses onto fp32.

    Everything else — including configs a given model cannot run — is
    emitted, for the planner to prune with a stated reason.
    """
    seen = set()
    out: List[Candidate] = []
    for dp, tp, pp, ep in mesh_factorizations(n_devices, pp_sizes, ep_sizes):
        for ovl in overlap:
            for gc in grad_comms:
                for rm in remat:
                    # canonicalize instead of skipping: a no-op option
                    # collapses onto its canonical twin even when a
                    # restricted sweep (e.g. overlap=(True,)) would not
                    # enumerate that twin itself — every (dp,tp,pp,ep)
                    # split always appears
                    cand = canonicalize(Candidate(
                        dp=dp, tp=tp, pp=pp, ep=ep, overlap_tp=ovl,
                        grad_comm=gc, remat=rm,
                        n_microbatches=n_microbatches,
                    ))
                    if cand.name not in seen:
                        seen.add(cand.name)
                        out.append(cand)
    return out


def candidate_key(c: Candidate) -> tuple:
    """Identity tuple for matching a configured layout against a plan's
    results (dataclass equality would also compare ``n_microbatches``
    on non-pipelined candidates, where it is meaningless)."""
    return (c.dp, c.tp, c.pp, c.ep, c.overlap_tp, c.grad_comm, c.remat,
            c.n_microbatches if c.pp > 1 else 1)


def find_candidate(
    candidates: Iterable[Candidate], want: Candidate
) -> Optional[Candidate]:
    key = candidate_key(want)
    for c in candidates:
        if candidate_key(c) == key:
            return c
    return None
