"""Serving decode-layout planning: (tp, weight_dtype, kv_dtype) x HBM.

ROADMAP item 3's stated headroom ("plan serving decode layouts next to
train steps") meets item 4's quantization axis: for a decode engine the
layout question is not FLOPs — a one-token-per-slot step is HBM-BOUND —
but *what fits* and *how many bytes the step must stream*. So the
serving planner is ANALYTIC: per-device resident bytes (weights at
their wire precision + the KV pool at its page dtype + the fp
embedding) against the chip budget for feasibility, and
(weights + KV-read) / HBM bandwidth for the step-time score. No
compile: every number comes from shapes and the spec tables
(telemetry/derived.py), so a capacity question ("does bloom-560m at
fp32 KV fit a v5e slice with 4096 pages?") answers in microseconds.

Candidates carry ``weight_dtype``/``kv_dtype`` as first-class pruning/
cost axes. EVERY row keeps both sides of the HBM comparison in its
``reason`` string — an fp layout that is infeasible shows
"HBM-infeasible: peak X > budget Y" while its int8 twin shows a
feasible "HBM ok: peak X' <= budget Y" — so the ~2x quantization
headroom is visible as rows flipping from pruned to feasible with the
numbers that flipped them, not as silently disappearing configs.

Byte model (per device; mirrors quant/weights.py + serving/kv_pool.py
exactly — the engine's ``memory_report()`` is the measured twin):

- block kernels: 12 L h^2 elements, sharded 1/tp; fp at config dtype,
  int8 at 1 byte + out-channel scales, int4 at 1/2 byte + grouped
  scales; biases/lns fp.
- embedding: v h fp (never quantized — it is also the lm head),
  vocab-sharded 1/tp.
- KV pool: 2 banks x L x pages x page_size x (nh/tp) x hd at the page
  dtype; int8 adds the fp32 per-(slot, head) scale plane.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from pipegoose_tpu.planner.cost import CostModel
from pipegoose_tpu.planner.space import divisors
from pipegoose_tpu.telemetry.derived import hbm_bw_bytes_per_s_for
from pipegoose_tpu.telemetry.doctor import _fmt_bytes

SERVING_WEIGHT_DTYPES = ("fp", "int8", "int4")
SERVING_KV_DTYPES = ("fp", "int8")


@dataclasses.dataclass(frozen=True)
class ServingCandidate:
    """One decode layout: tensor-parallel degree + wire precisions."""

    tp: int = 1
    weight_dtype: str = "fp"
    kv_dtype: str = "fp"

    def __post_init__(self):
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {self.tp}")
        if self.weight_dtype not in SERVING_WEIGHT_DTYPES:
            raise ValueError(
                f"weight_dtype must be one of {SERVING_WEIGHT_DTYPES}, "
                f"got {self.weight_dtype!r}"
            )
        if self.kv_dtype not in SERVING_KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {SERVING_KV_DTYPES}, got "
                f"{self.kv_dtype!r}"
            )

    @property
    def name(self) -> str:
        return f"tp{self.tp}+w:{self.weight_dtype}+kv:{self.kv_dtype}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def serving_weight_bytes(config: Any, cand: ServingCandidate,
                         group_size: int = 32) -> int:
    """Per-device resident weight bytes at the candidate's precision."""
    h, v, L = config.hidden_size, config.vocab_size, config.n_layer
    itemsize = int(np.dtype(config.dtype).itemsize)
    kernel_elems = 12 * L * h * h // cand.tp
    # per-kernel out dims (column shards out/tp, row keeps out whole):
    # qkv 3h/tp + up 4h/tp (column) + out h + down h (row), per layer
    scale_out = L * (3 * h // cand.tp + 4 * h // cand.tp + 2 * h)
    if cand.weight_dtype == "fp":
        kernels = kernel_elems * itemsize
    elif cand.weight_dtype == "int8":
        kernels = kernel_elems + 4 * scale_out
    else:  # int4: half a byte per element + grouped scales
        kernels = kernel_elems // 2 + 4 * (kernel_elems // group_size)
    embed = v * h * itemsize // cand.tp          # vocab-sharded, fp
    biases = L * (3 * h // cand.tp + 4 * h // cand.tp + 2 * h) * itemsize
    # 2 per block (ln_1, ln_2) + embed_ln + ln_f, scale+bias each
    lns = (2 * L + 2) * 2 * h * itemsize
    return int(kernels + embed + biases + lns)


def serving_kv_bytes(config: Any, cand: ServingCandidate, num_pages: int,
                     page_size: int) -> int:
    """Per-device KV pool bytes at the candidate's page dtype."""
    L, nh, hd = config.n_layer, config.n_head, config.head_dim
    slots = 2 * L * num_pages * page_size * (nh // cand.tp)
    if cand.kv_dtype == "fp":
        return int(slots * hd * np.dtype(config.dtype).itemsize)
    return int(slots * (hd + 4))   # int8 values + fp32 scale plane


def evaluate_serving_candidate(
    config: Any,
    cand: ServingCandidate,
    cost_model: CostModel,
    *,
    num_pages: int,
    page_size: int,
    num_slots: int,
    group_size: int = 32,
) -> Dict[str, Any]:
    """One row: resident-byte breakdown, feasibility WITH the numbers
    in the reason either way, the page headroom the budget leaves, and
    the bandwidth-bound tokens/s score."""
    if config.n_head % cand.tp:
        return {
            "candidate": cand.to_json(), "name": cand.name,
            "feasible": False,
            "reason": (f"n_head={config.n_head} not divisible by "
                       f"tp={cand.tp}"),
        }
    weights = serving_weight_bytes(config, cand, group_size)
    kv = serving_kv_bytes(config, cand, num_pages, page_size)
    peak = weights + kv
    budget = float(cost_model.hbm_bytes)
    feasible = peak <= budget
    cmp = "<=" if feasible else ">"
    reason = (
        f"{'HBM ok' if feasible else 'HBM-infeasible'}: peak "
        f"{_fmt_bytes(int(peak))} (weights {_fmt_bytes(weights)} + kv "
        f"{_fmt_bytes(kv)}) {cmp} budget {_fmt_bytes(int(budget))} "
        f"({cost_model.device_kind})"
    )
    # pages the leftover budget could hold at this kv dtype: the
    # concurrent-capacity axis the bench's capacity ratio measures
    per_page = max(serving_kv_bytes(config, cand, 1, page_size), 1)
    capacity_pages = int(max(budget - weights, 0.0) // per_page)
    row: Dict[str, Any] = {
        "candidate": cand.to_json(), "name": cand.name,
        "feasible": feasible, "reason": reason,
        "weights_bytes": weights, "kv_bytes": kv, "hbm_peak_bytes": peak,
        "hbm_budget_bytes": int(budget), "capacity_pages": capacity_pages,
    }
    if feasible:
        # memory-bound decode floor: every step streams the resident
        # weights once plus the active KV once (upper bound: full pool)
        bw = hbm_bw_bytes_per_s_for(cost_model.device_kind)
        step_s = (weights + kv) / bw
        row["step_seconds_floor"] = step_s
        row["score"] = num_slots / step_s if step_s > 0 else 0.0
    return row


def plan_serving_decode(
    config: Any,
    n_devices: int,
    *,
    num_pages: int = 1024,
    page_size: int = 16,
    num_slots: int = 8,
    cost_model: Optional[CostModel] = None,
    weight_dtypes: Sequence[str] = SERVING_WEIGHT_DTYPES,
    kv_dtypes: Sequence[str] = SERVING_KV_DTYPES,
    group_size: int = 32,
) -> Dict[str, Any]:
    """Rank every (tp | n_devices) x weight_dtype x kv_dtype decode
    layout. Returns a JSON-able artifact: feasible rows sorted by score
    (bandwidth-bound tokens/s, descending), pruned rows kept WITH their
    reasons — the planner's never-silently-drop contract."""
    cost_model = cost_model or CostModel.for_device()
    rows = [
        evaluate_serving_candidate(
            config, ServingCandidate(tp=tp, weight_dtype=w, kv_dtype=kv),
            cost_model, num_pages=num_pages, page_size=page_size,
            num_slots=num_slots, group_size=group_size,
        )
        for tp in divisors(n_devices)
        for w in weight_dtypes
        for kv in kv_dtypes
    ]
    feasible = sorted((r for r in rows if r["feasible"]),
                      key=lambda r: -r["score"])
    pruned = [r for r in rows if not r["feasible"]]
    return {
        "device_kind": cost_model.device_kind,
        "n_devices": int(n_devices),
        "num_pages": int(num_pages), "page_size": int(page_size),
        "num_slots": int(num_slots),
        "model": {
            "hidden_size": config.hidden_size, "n_layer": config.n_layer,
            "n_head": config.n_head, "vocab_size": config.vocab_size,
            "dtype": str(np.dtype(config.dtype)),
        },
        "rows": feasible + pruned,
        "n_feasible": len(feasible),
        "n_pruned": len(pruned),
        "top": feasible[0]["name"] if feasible else None,
    }


def format_serving_plan(plan: Dict[str, Any], max_rows: int = 24) -> str:
    """Human table of a :func:`plan_serving_decode` artifact."""
    lines = [
        f"serving decode layouts on {plan['n_devices']} x "
        f"{plan['device_kind']} (pool {plan['num_pages']} pages x "
        f"{plan['page_size']} tokens): {plan['n_feasible']} feasible, "
        f"{plan['n_pruned']} pruned"
    ]
    for r in plan["rows"][:max_rows]:
        mark = "ok  " if r["feasible"] else "PRUNE"
        cap = r.get("capacity_pages")
        extra = f"  capacity={cap}p" if cap is not None else ""
        lines.append(f"  [{mark}] {r['name']:<24} {r['reason']}{extra}")
    if len(plan["rows"]) > max_rows:
        lines.append(f"  ... {len(plan['rows']) - max_rows} more rows")
    return "\n".join(lines)
