"""Compile-time parallelism planner: static search of the mesh/layout
space, no hardware needed.

The mesh doctor (telemetry/doctor.py) extracts per-collective wire
bytes, partitioner-inserted resharding, compiled FLOPs, and the HBM
peak from ONE shape-only lower+compile on fake host devices. This
package turns that single-config inspector into a search: enumerate
every (dp, tp, pp, ep) x overlap_tp x grad_comm x remat candidate for a
device count (planner/space.py), AOT-compile each through the real
``make_hybrid_train_step`` (planner/bloom_builder.py), score with a
static cost model — wire bytes over the ICI/DCI peer bandwidths, FLOPs
over ``PEAK_FLOPS``, analytic pipeline bubble, HBM vs the chip budget
(planner/cost.py) — and emit a ranked, JSON-round-tripping
:class:`PlanReport` (planner/report.py).

Entry points: :func:`run_plan` (library),
``scripts/plan_parallelism.py`` (CLI + ``--check`` CI gate),
``scripts/sweep_tpu_perf.py plan`` (measure the top-K, record
predicted-vs-measured), ``examples/plan_parallelism_demo.py``.
Docs: docs/planner.md.
"""
from pipegoose_tpu.planner.bloom_builder import BloomPlanModel
from pipegoose_tpu.planner.cost import CostModel, hbm_check, score_breakdown
from pipegoose_tpu.planner.planner import (
    best_layout_at,
    evaluate_candidate,
    last_plan_report,
    plan_layout_at,
    run_plan,
    set_planner_gauges,
)
from pipegoose_tpu.planner.report import CandidateResult, PlanReport
from pipegoose_tpu.planner.serving import (
    ServingCandidate,
    evaluate_serving_candidate,
    format_serving_plan,
    plan_serving_decode,
)
from pipegoose_tpu.planner.space import (
    Candidate,
    candidate_key,
    enumerate_candidates,
    find_candidate,
    mesh_factorizations,
)

__all__ = [
    "BloomPlanModel",
    "Candidate",
    "CandidateResult",
    "CostModel",
    "PlanReport",
    "ServingCandidate",
    "best_layout_at",
    "evaluate_serving_candidate",
    "format_serving_plan",
    "plan_serving_decode",
    "candidate_key",
    "enumerate_candidates",
    "evaluate_candidate",
    "plan_layout_at",
    "find_candidate",
    "hbm_check",
    "last_plan_report",
    "mesh_factorizations",
    "run_plan",
    "score_breakdown",
    "set_planner_gauges",
]
