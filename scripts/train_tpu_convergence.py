"""Real-hardware convergence run: bloom-560m byte-level LM on local text.

The reference's public evidence of correctness is convergence curves
(wandb links, reference README.md:87-92) from training bloom-560m on
imdb. This environment has no dataset egress, so the corpus is the
repository's own text (source + docs, ~1 MB) tokenized at the BYTE
level — real, structured natural-ish data with a well-defined held-out
split — trained on the REAL flagship config (bloom-560m, bf16, flash
kernels, remat, Adam) on the attached TPU.

What this demonstrates (and the CPU equivalence records cannot):
- the full single-chip train step LEARNS on hardware: train loss falls
  from ~ln(vocab) toward byte-entropy levels, val loss tracks it;
- sustained multi-step optimization with the bench configuration (the
  bench itself runs 10 steps from init).

Timing per docs/perf_tpu_v5e.md: steps live inside lax.scan (the
tunnel's per-dispatch RTT is ~67ms), value fetches force completion.

    PYTHONPATH=.:/root/.axon_site python scripts/train_tpu_convergence.py \
        [out.json] [--steps 300]
"""
from __future__ import annotations

import functools
import json
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax

REPO = Path(__file__).resolve().parent.parent


def build_corpus() -> bytes:
    """Deterministic corpus: all tracked text files of the repo."""
    parts = []
    for pat in ("pipegoose_tpu/**/*.py", "tests/**/*.py", "docs/**/*.md",
                "*.md", "examples/*.py", "native/*.cpp"):
        for f in sorted(REPO.glob(pat)):
            parts.append(f.read_bytes())
    return b"\n\n".join(parts)


def batches(data: np.ndarray, rng: np.random.RandomState, n: int, b: int, s: int):
    """(n, b, s+0) random contiguous byte windows."""
    starts = rng.randint(0, len(data) - s - 1, size=(n, b))
    return np.stack(
        [[data[st:st + s] for st in row] for row in starts]
    ).astype(np.int32)


def main() -> None:
    steps = 300
    if "--steps" in sys.argv:
        steps = int(sys.argv[sys.argv.index("--steps") + 1])
    model = "bloom"
    if "--model" in sys.argv:
        model = sys.argv[sys.argv.index("--model") + 1]
    # per-model default paths so `--model mixtral` can never silently
    # overwrite the bloom acceptance record; names match the committed
    # records STATUS.md/PARITY.md cite
    default_out = {
        "bloom": "docs/acceptance/TRAIN_TPU_r03.json",
        "mixtral": "docs/acceptance/TRAIN_TPU_MOE_r03.json",
    }.get(model, f"docs/acceptance/TRAIN_TPU_{model.upper()}_r03.json")
    out_path = (
        sys.argv[1]
        if len(sys.argv) > 1 and not sys.argv[1].startswith("--")
        else default_out
    )
    if "--cpu" in sys.argv:
        # the sitecustomize pins jax_platforms to the axon plugin and
        # IGNORES the JAX_PLATFORMS env var; only this works
        jax.config.update("jax_platforms", "cpu")

    from pipegoose_tpu.models import bloom, mixtral

    dev = jax.devices()[0]
    on_tpu = dev.platform.lower() != "cpu"
    b, s, inner = (8, 1024, 10) if on_tpu else (2, 128, 2)

    corpus = np.frombuffer(build_corpus(), dtype=np.uint8)
    split = int(len(corpus) * 0.9)
    train_data, val_data = corpus[:split], corpus[split:]
    print(f"corpus {len(corpus)} bytes, train {split}, val {len(val_data)}",
          file=sys.stderr)

    if model == "mixtral":
        # ~450M-param sparse-MoE sibling: GQA + SwiGLU experts + top-2
        # routing + the GQA flash kernels — the BASELINE config-5 family
        # exercised end-to-end on hardware (single-chip, EP dense here)
        cfg = (
            mixtral.MixtralConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=1792,
                n_layer=8, n_head=16, n_kv_head=4, num_experts=8, top_k=2,
                capacity_factor=1.25, dtype=jnp.bfloat16, remat=True,
                use_flash=True,
            )
            if on_tpu
            else mixtral.MixtralConfig(
                vocab_size=512, hidden_size=64, intermediate_size=96,
                n_layer=2, n_head=4, n_kv_head=2, num_experts=2, top_k=1,
            )
        )
        mod = mixtral
        model_name = "mixtral-moe-450m (8 experts, top-2, GQA, byte-level ids)"
    else:
        cfg = (
            bloom.BloomConfig.bloom_560m(dtype=jnp.bfloat16, remat=True,
                                         use_flash=True)
            if on_tpu
            else bloom.BloomConfig(vocab_size=512, hidden_size=128, n_layer=2,
                                   n_head=4)
        )
        mod = bloom
        model_name = "bloom-560m (byte-level ids over local text corpus)"
    # byte ids 0..255 live inside the real vocab; the model simply never
    # sees the other ids (their embeddings stay at init)
    params = mod.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(optax.linear_schedule(0.0, 2e-4, 20), weight_decay=0.01),
    )
    opt_state = opt.init(params)

    rng = np.random.RandomState(0)
    val_ids = jnp.asarray(batches(val_data, np.random.RandomState(1), 4, b, s))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run_chunk(params, opt_state, ids_chunk):
        def body(carry, ids):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(mod.loss_fn)(
                params, ids, None, ids, cfg
            )
            updates, opt_state = opt.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), ids_chunk
        )
        return params, opt_state, losses

    @jax.jit
    def val_loss(params, val_ids):
        def one(ids):
            return mod.loss_fn(params, ids, None, ids, cfg)
        # sequential over val batches: one (B,S,V) fp32 logits buffer at
        # a time (a vmap would materialize all of them at once — 32 GB)
        return jax.lax.map(one, val_ids).mean()

    n_chunks = steps // inner
    if n_chunks < 1:
        raise SystemExit(f"--steps {steps} < chunk size {inner}: nothing to run")
    steps = n_chunks * inner  # record what actually runs

    curve = []
    v0 = float(val_loss(params, val_ids))
    t0 = time.perf_counter()
    tokens = 0
    for chunk in range(n_chunks):
        ids = jnp.asarray(batches(train_data, rng, inner, b, s))
        params, opt_state, losses = run_chunk(params, opt_state, ids)
        losses = np.asarray(losses, np.float64)  # fetch forces completion
        tokens += inner * b * s
        curve.append(
            {"step": (chunk + 1) * inner, "train_loss": round(float(losses[-1]), 4)}
        )
        print(curve[-1], file=sys.stderr)
    dt = time.perf_counter() - t0
    v1 = float(val_loss(params, val_ids))

    record = {
        "record": "real-hardware-training-convergence",
        "family": model,
        "device": getattr(dev, "device_kind", dev.platform),
        "model": model_name if on_tpu else f"{model}-tiny smoke",
        "batch": b, "seq": s, "steps": steps,
        "corpus_bytes": int(len(corpus)),
        "val_loss_init": round(v0, 4),
        "val_loss_final": round(v1, 4),
        "train_curve": curve,
        "tokens_per_sec": round(tokens / dt, 1),
        "note": "loss starts near ln(vocab_size) (uniform) and must "
                "fall toward byte-level text entropy; val on a held-out "
                "10% split of the corpus",
    }
    Path(out_path).write_text(json.dumps(record, indent=1))
    print(json.dumps({"val_loss_init": v0, "val_loss_final": v1,
                      "final_train": curve[-1]["train_loss"]}))


if __name__ == "__main__":
    main()
