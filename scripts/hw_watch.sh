#!/bin/bash
# Tunnel watcher: retry the round-5 hardware agenda until the single-
# client axon tunnel opens (rc=3 = never attached, retryable), then run
# it once and stop. SIGTERM-only termination throughout — a SIGKILLed
# attached client wedges the tunnel for the whole session.
#
#   bash scripts/hw_watch.sh [attempts] [sleep_s]
cd "$(dirname "$0")/.."
ATTEMPTS=${1:-30}
SLEEP_S=${2:-420}
for i in $(seq 1 "$ATTEMPTS"); do
  echo "hw_watch: attempt $i/$ATTEMPTS $(date -u +%H:%M:%S)"
  timeout --signal=TERM 4200 python scripts/hw_agenda_r05.py
  rc=$?
  if [ "$rc" -eq 0 ]; then
    echo "hw_watch: AGENDA COMPLETE"
    exit 0
  fi
  # retry only the retryable outcomes: 3 = backend never attached,
  # 124/143 = watchdog timeout (tunnel stalled mid-attach). Anything
  # else is a deterministic agenda failure — stop, don't burn the round.
  case "$rc" in
    3|124|143) ;;
    *) echo "hw_watch: non-retryable rc=$rc; stopping"; exit "$rc" ;;
  esac
  echo "hw_watch: rc=$rc; sleeping ${SLEEP_S}s"
  sleep "$SLEEP_S"
done
echo "hw_watch: exhausted $ATTEMPTS attempts without completing"
exit 1
