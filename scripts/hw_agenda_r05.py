"""Round-5 single-attach hardware agenda.

The axon tunnel is single-client and intermittently held (rounds 3-4:
round-end bench fell back to CPU four times). This script therefore
packs EVERY round-5 hardware capture into ONE attached process, run
opportunistically (scripts/hw_watch.sh retries until the tunnel opens):

  1. BENCH   -> docs/acceptance/BENCH_TPU_r05.json
     bloom-560m train throughput/MFU, champion flash config first, the
     no-remat variants retried (the r3 compile-helper HTTP 500 may have
     healed), cumulative write after every variant.
  2. TRAIN   -> docs/acceptance/TRAIN_TPU_r05.json
     full-vocab convergence: bloom-560m over the REAL 250,880-token
     vocab with word-level Zipfian ids (reference acceptance protocol,
     /root/reference/tests/convergence/run_hybrid_parallel.py:83-177;
     no HF tokenizer is reachable offline, so the corpus is word-
     tokenized locally and ranks are permuted across the full id
     range — same embedding-table + vocab-CE distribution shape).
  3. DECODE  -> docs/acceptance/DECODE_TPU_r05.json
     KV-cache decode throughput for bloom-560m AND a GQA family
     (mixtral-450m) — the r3 record covered bloom only.

Parent/child split mirrors bench.py: the parent never touches the
backend; the child prints ``AGENDA_READY`` right after attach.
Parent rc: 0 = child ran the agenda (individual stage errors are
recorded in the JSONs), 3 = backend never attached (retryable).

Timing recipe per docs/perf_tpu_v5e.md: step loops live inside jit
(lax.scan), value fetches force completion, dispatch RTT subtracted.

    PYTHONPATH=.:/root/.axon_site python scripts/hw_agenda_r05.py
"""
from __future__ import annotations

import functools
import json
import os
import re
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
ACC = REPO / "docs" / "acceptance"

ATTACH_DEADLINE_S = int(os.environ.get("AGENDA_ATTACH_DEADLINE_S", "300"))
RUN_DEADLINE_S = int(os.environ.get("AGENDA_RUN_DEADLINE_S", "3600"))
# AGENDA_SMOKE=1: run the full flow with tiny shapes on CPU into /tmp —
# validates the script end-to-end without holding the tunnel
SMOKE = bool(os.environ.get("AGENDA_SMOKE"))

PEAK_FLOPS = {
    "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12,
    "v6 lite": 918e12, "v6e": 918e12, "v4": 275e12,
}


def _peak_flops(device_kind: str) -> float:
    kind = device_kind.lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return 1e12


def _rtt() -> float:
    import jax
    import jax.numpy as jnp

    tiny = jax.jit(lambda x: x + 1.0)
    z = jnp.zeros(())
    float(tiny(z))
    t0 = time.perf_counter()
    for _ in range(3):
        float(tiny(z))
    return (time.perf_counter() - t0) / 3


# ---------------------------------------------------------------- stage 1


def stage_bench(device_kind: str) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pipegoose_tpu.models import bloom

    steps = 10
    variants = {
        # champion first: the most important number lands even if a
        # later variant wedges the tunnel
        "flash": (dict(remat=True, use_flash=True), 8, 1024),
        # fused Pallas CE (ops/fused_ce.py, new this round): the 8 GB
        # logits buffer never exists, so no-remat finally has the HBM to
        # run at full batch — the primary MFU>=0.40 candidates
        "noremat+flash+fusedce": (
            dict(remat=False, use_flash=True, fused_ce=True), 8, 1024),
        "flash+fusedce": (
            dict(remat=True, use_flash=True, fused_ce=True), 8, 1024),
        "noremat+flash+fusedce_b16": (
            dict(remat=False, use_flash=True, fused_ce=True), 16, 1024),
        # the r3 sweep's candidates, blocked then by the remote-compile-
        # helper HTTP 500 — retry (VERDICT r4 next #2)
        "noremat+flash+ce8": (
            dict(remat=False, use_flash=True, ce_chunks=8), 8, 1024),
        "flash+ce8": (dict(remat=True, use_flash=True, ce_chunks=8), 8, 1024),
        "flash_s2048": (dict(remat=True, use_flash=True), 4, 2048),
        "xla": (dict(remat=True), 8, 1024),
    }
    make_cfg = functools.partial(bloom.BloomConfig.bloom_560m, dtype=jnp.bfloat16)
    if SMOKE:
        steps = 2
        variants = {
            "flash": (dict(remat=True, use_flash=True), 2, 128),
            "xla": (dict(remat=True), 2, 128),
        }

        variants["fusedce"] = (dict(remat=True, fused_ce=True), 2, 128)

        def make_cfg(**kw):
            kw.pop("ce_chunks", None)
            return bloom.BloomConfig(
                vocab_size=512, hidden_size=64, n_layer=2, n_head=4, **kw
            )

    def measure(cfg, batch, seq):
        params = bloom.init_params(cfg, jax.random.PRNGKey(0))
        opt = optax.adam(1e-4)
        opt_state = opt.init(params)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (batch, seq))
        )

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def run(params, opt_state, ids):
            def body(carry, _):
                p, o = carry
                loss, grads = jax.value_and_grad(bloom.loss_fn)(
                    p, ids, None, ids, cfg
                )
                updates, o = opt.update(grads, o, p)
                return (optax.apply_updates(p, updates), o), loss
            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), None, length=steps
            )
            return params, opt_state, losses[-1]

        params, opt_state, loss = run(params, opt_state, ids)
        loss = float(loss)  # compile+warm; fetch forces completion
        rtt = _rtt()
        t0 = time.perf_counter()
        params, opt_state, loss = run(params, opt_state, ids)
        loss = float(loss)
        dt = max(time.perf_counter() - t0 - rtt, 1e-9)
        tokens_per_sec = batch * seq * steps / dt
        n_params = sum(
            int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params)
        )
        flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.hidden_size * seq
        mfu = tokens_per_sec * flops_per_token / _peak_flops(device_kind)
        return {
            "tokens_per_sec": round(tokens_per_sec, 1),
            "mfu": round(mfu, 4), "loss": loss,
        }

    results: dict = {}
    out = ACC / "BENCH_TPU_r05.json"
    for name, (kw, batch, seq) in variants.items():
        b = batch
        while True:
            try:
                cfg = make_cfg(**kw)
                results[name] = measure(cfg, b, seq)
                results[name].update(batch=b, seq=seq)
                break
            except Exception as e:  # noqa: BLE001
                if "RESOURCE_EXHAUSTED" in str(e) and b > 1:
                    b //= 2
                    continue
                results[name] = {"error": f"{type(e).__name__}: {e}"[:400]}
                break
        ok = {k: v for k, v in results.items() if "error" not in v}
        if ok:
            best = max(ok, key=lambda k: ok[k]["tokens_per_sec"])
            record = {
                "metric": "bloom-560m train tokens/sec/chip",
                "value": ok[best]["tokens_per_sec"],
                "unit": "tokens/sec/chip",
                "vs_baseline": round(ok[best]["mfu"] / 0.40, 4),
                "mfu": ok[best]["mfu"],
                "device": device_kind,
                "best_variant": best,
                "variants": results,
                "loss": ok[best]["loss"],
                "captured": "round 5 in-round (scripts/hw_agenda_r05.py)",
            }
            out.write_text(json.dumps(record, indent=1))
        print("BENCH", name, json.dumps(results[name])[:200], flush=True)
    return results


# ---------------------------------------------------------------- stage 2


def build_word_stream(full_vocab: int = 250_880):
    """Word-level Zipfian ids over the FULL vocab range.

    The repo's text corpus is tokenized into words/punctuation; word
    frequency ranks (naturally Zipf-distributed for text) are mapped
    through a fixed permutation of ``range(full_vocab)`` so the ids the
    model sees span the whole 250,880-row embedding table and every
    vocab-parallel CE shard — the distribution shape of the reference's
    real-tokenizer protocol, reproducible with zero egress.
    """
    import numpy as np

    parts = []
    for pat in ("pipegoose_tpu/**/*.py", "tests/**/*.py", "docs/**/*.md",
                "*.md", "examples/*.py", "native/*.cpp"):
        for f in sorted(REPO.glob(pat)):
            parts.append(f.read_text(errors="replace"))
    text = "\n\n".join(parts)
    words = re.findall(r"[A-Za-z_]+|[0-9]+|[^\sA-Za-z_0-9]", text)
    from collections import Counter

    by_freq = [w for w, _ in Counter(words).most_common()]
    perm = np.random.RandomState(7).permutation(full_vocab)
    word_to_id = {w: int(perm[r]) for r, w in enumerate(by_freq)}
    stream = np.asarray([word_to_id[w] for w in words], dtype=np.int32)
    return stream, len(by_freq)


def stage_fullvocab_train(device_kind: str, steps: int = 300) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pipegoose_tpu.models import bloom

    b, s, inner = 8, 1024, 10
    if SMOKE:
        b, s, inner, steps = 2, 64, 2, 4
    stream, n_words = build_word_stream()
    split = int(len(stream) * 0.9)
    train_data, val_data = stream[:split], stream[split:]

    cfg = (
        bloom.BloomConfig.bloom_560m(
            dtype=jnp.bfloat16, remat=True, use_flash=True
        )
        if not SMOKE
        # smoke keeps the FULL 250,880 vocab (the point of the record)
        # on a tiny trunk
        else bloom.BloomConfig(
            vocab_size=250_880, hidden_size=64, n_layer=2, n_head=4
        )
    )
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(optax.linear_schedule(0.0, 2e-4, 20), weight_decay=0.01),
    )
    opt_state = opt.init(params)

    def batches(data, rng, n):
        starts = rng.randint(0, len(data) - s - 1, size=(n, b))
        return np.stack(
            [[data[st:st + s] for st in row] for row in starts]
        ).astype(np.int32)

    rng = np.random.RandomState(0)
    val_ids = jnp.asarray(batches(val_data, np.random.RandomState(1), 4))

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run_chunk(params, opt_state, ids_chunk):
        def body(carry, ids):
            p, o = carry
            loss, grads = jax.value_and_grad(bloom.loss_fn)(
                p, ids, None, ids, cfg
            )
            updates, o = opt.update(grads, o, p)
            return (optax.apply_updates(p, updates), o), loss
        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), ids_chunk
        )
        return params, opt_state, losses

    @jax.jit
    def val_loss(params, val_ids):
        # lax.map: ONE (B,S,V) fp32 logits buffer at a time (vmap would
        # materialize all four at once — tens of GB at V=250,880)
        return jax.lax.map(
            lambda ids: bloom.loss_fn(params, ids, None, ids, cfg), val_ids
        ).mean()

    n_chunks = steps // inner
    curve = []
    v0 = float(val_loss(params, val_ids))
    t0 = time.perf_counter()
    for chunk in range(n_chunks):
        ids = jnp.asarray(batches(train_data, rng, inner))
        params, opt_state, losses = run_chunk(params, opt_state, ids)
        losses = np.asarray(losses, np.float64)
        curve.append({
            "step": (chunk + 1) * inner,
            "train_loss": round(float(losses[-1]), 4),
        })
        print("TRAIN", curve[-1], flush=True)
    dt = time.perf_counter() - t0
    v1 = float(val_loss(params, val_ids))

    record = {
        "record": "real-hardware-full-vocab-convergence",
        "family": "bloom",
        "device": device_kind,
        "model": "bloom-560m bf16+flash+remat, FULL 250,880-token vocab",
        "tokenization": (
            f"word-level over the repo corpus: {n_words} distinct words, "
            "frequency ranks (Zipfian) permuted across the full "
            "0..250,879 id range (reference protocol uses the real HF "
            "bloom tokenizer, run_hybrid_parallel.py:83-177; no HF hub "
            "egress here, so token STATISTICS are reproduced instead)"
        ),
        "distinct_ids": int(n_words),
        "max_id_seen": int(stream.max()),
        "batch": b, "seq": s, "steps": n_chunks * inner,
        "val_loss_init": round(v0, 4),
        "val_loss_final": round(v1, 4),
        "train_curve": curve,
        "tokens_per_sec": round(n_chunks * inner * b * s / dt, 1),
        "note": (
            "init loss must start near ln(250880)=12.43 (uniform over the "
            "FULL vocab — proves the whole embedding/CE participates) and "
            "fall toward word-level corpus entropy"
        ),
    }
    (ACC / "TRAIN_TPU_r05.json").write_text(json.dumps(record, indent=1))
    return record


# ---------------------------------------------------------------- stage 3


def stage_decode(device_kind: str) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pipegoose_tpu.models import bloom, generate as gen, mixtral

    results = {}

    def time_decode(run, batch, new):
        out = run()  # compile + warm
        rtt = _rtt()
        t0 = time.perf_counter()
        run()
        dt = max(time.perf_counter() - t0 - 2 * rtt, 1e-9)
        return {
            "decode_tokens_per_sec": round(batch * new / dt, 1),
            "per_sequence_tokens_per_sec": round(new / dt, 1),
            "wall_s": round(dt, 3),
        }

    # bloom-560m (MHA + ALiBi)
    try:
        cfg = (
            bloom.BloomConfig.bloom_560m(dtype=jnp.bfloat16)
            if not SMOKE
            else bloom.BloomConfig(
                vocab_size=512, hidden_size=64, n_layer=2, n_head=4
            )
        )
        params = bloom.init_params(cfg, jax.random.PRNGKey(0))
        batch, prompt, new = (8, 128, 256) if not SMOKE else (2, 8, 8)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (batch, prompt))
        )

        def run_bloom():
            out = gen.generate(params, ids, cfg, max_new_tokens=new)
            np.asarray(out)
            return out

        results["bloom-560m"] = dict(
            time_decode(run_bloom, batch, new),
            batch=batch, prompt_len=prompt, new_tokens=new,
            attention="MHA+ALiBi",
        )
        del params
    except Exception as e:  # noqa: BLE001
        results["bloom-560m"] = {"error": f"{type(e).__name__}: {e}"[:400]}
    print("DECODE bloom", json.dumps(results["bloom-560m"])[:200], flush=True)

    # mixtral-450m: the GQA + sliding-window + MoE cache path
    # (VERDICT r4 next #8 — no GQA-family decode record existed)
    try:
        cfg = (
            mixtral.MixtralConfig(
                vocab_size=32000, hidden_size=1024, intermediate_size=1792,
                n_layer=8, n_head=16, n_kv_head=4, num_experts=8, top_k=2,
                capacity_factor=1.25, dtype=jnp.bfloat16,
            )
            if not SMOKE
            else mixtral.MixtralConfig(
                vocab_size=512, hidden_size=64, intermediate_size=96,
                n_layer=2, n_head=4, n_kv_head=2, num_experts=2, top_k=1,
            )
        )
        params = mixtral.init_params(cfg, jax.random.PRNGKey(0))
        batch, prompt, new = (8, 128, 256) if not SMOKE else (2, 8, 8)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (batch, prompt))
        )

        def run_mixtral():
            out = mixtral.generate(params, ids, cfg, max_new_tokens=new)
            np.asarray(out)
            return out

        results["mixtral-450m-gqa"] = dict(
            time_decode(run_mixtral, batch, new),
            batch=batch, prompt_len=prompt, new_tokens=new,
            attention="GQA 16q/4kv, 8 experts top-2",
        )
    except Exception as e:  # noqa: BLE001
        results["mixtral-450m-gqa"] = {"error": f"{type(e).__name__}: {e}"[:400]}
    print("DECODE mixtral", json.dumps(results["mixtral-450m-gqa"])[:200],
          flush=True)

    record = {
        "record": "kv-cache-decode-throughput",
        "device": device_kind,
        "families": results,
        "note": "greedy decode, whole generation = 1 prefill + 1 scanned "
                "decode dispatch; tokens counted = batch * new_tokens",
    }
    (ACC / "DECODE_TPU_r05.json").write_text(json.dumps(record, indent=1))
    return record


# ----------------------------------------------------------------- driver


def child() -> None:
    global ACC
    import jax

    if SMOKE:
        jax.config.update("jax_platforms", "cpu")
        ACC = Path("/tmp/agenda_smoke")
    dev = jax.devices()[0]
    print("AGENDA_READY", dev.platform, flush=True)
    if dev.platform.lower() == "cpu" and not SMOKE:
        print("AGENDA_ABORT cpu-only", flush=True)
        sys.exit(4)
    device_kind = getattr(dev, "device_kind", dev.platform)
    ACC.mkdir(parents=True, exist_ok=True)

    summary = {}
    for name, fn in (
        ("bench", stage_bench),
        ("fullvocab_train", stage_fullvocab_train),
        ("decode", stage_decode),
    ):
        t0 = time.perf_counter()
        try:
            fn(device_kind)
            summary[name] = f"ok ({time.perf_counter() - t0:.0f}s)"
        except Exception as e:  # noqa: BLE001
            summary[name] = f"FAILED {type(e).__name__}: {e}"[:300]
        print("STAGE", name, summary[name], flush=True)
    print("AGENDA_DONE", json.dumps(summary), flush=True)
    if not any(v.startswith("ok") for v in summary.values()):
        sys.exit(5)  # nothing captured — let the watcher retry


def parent() -> int:
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env={**os.environ, "AGENDA_CHILD": "1"},
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    ready = threading.Event()
    done = threading.Event()

    def reader():
        for line in proc.stdout:
            print(line.rstrip("\n"), flush=True)
            if line.startswith("AGENDA_READY"):
                ready.set()
        done.set()

    err_tail: list[str] = []

    def err_reader():
        for line in proc.stderr:
            err_tail.append(line)
            if len(err_tail) > 100:
                del err_tail[:-100]

    threading.Thread(target=reader, daemon=True).start()
    threading.Thread(target=err_reader, daemon=True).start()

    def wait_for(ev, timeout_s):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if ev.wait(min(2.0, max(0.0, deadline - time.monotonic()))):
                return True
            if proc.poll() is not None:
                return ev.wait(2.0)
        return False

    attached = wait_for(ready, ATTACH_DEADLINE_S)
    if attached:
        wait_for(done, RUN_DEADLINE_S)
    if proc.poll() is None:
        proc.terminate()  # SIGTERM only — a SIGKILLed client wedges the tunnel
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
    rc = proc.wait()
    if err_tail:
        sys.stderr.write("".join(err_tail)[-3000:])
    if not attached:
        print("AGENDA: backend never attached", flush=True)
        return 3
    return 0 if rc == 0 else rc


if __name__ == "__main__":
    if os.environ.get("AGENDA_CHILD"):
        child()
    else:
        sys.exit(parent())
