#!/usr/bin/env python
"""Mesh doctor CLI: compile a hybrid train step (and optionally the
serving decode step) on a host-device mesh and print/guard its
partitioning plan (pipegoose_tpu/telemetry/doctor.py).

Standalone CI gate: with ``--check`` the process exits non-zero when
the compiled program contains partitioner-inserted resharding
collectives, intended-vs-actual sharding mismatches, or large fully
replicated buffers — so a PartitionSpec regression fails a pipeline at
compile time on fake CPU devices, long before a TPU bench notices.

    # inspect a tp=2 x dp=4 BLOOM-ish step on 8 fake devices
    python scripts/mesh_doctor.py --fake-devices 8 --tp 2 --dp 4

    # CI gate: guards on, JSON artifact out, serving decode step too
    python scripts/mesh_doctor.py --fake-devices 8 --tp 2 --dp 4 \
        --check --serving --json mesh_doctor.json

Exit codes: 0 ok, 2 guard violation.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

# runnable from anywhere: the repo root is the import root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_train_report(args, ctx, cfg, params, bloom):
    import jax
    import jax.numpy as jnp
    import optax

    from pipegoose_tpu.optim.zero import DistributedOptimizer
    from pipegoose_tpu.parallel import (
        make_hybrid_train_step,
        train_step_intended_specs,
    )
    from pipegoose_tpu.telemetry import doctor

    specs = bloom.tp_specs(params)
    opt = DistributedOptimizer(
        optax.adam(1e-3), axis_name="data", grad_comm=args.grad_comm
    )

    def loss_fn(p, ids):
        return bloom.loss_fn(p, ids, None, ids, cfg, tp_axis="tensor")

    init_fn, make_step = make_hybrid_train_step(
        loss_fn, specs, opt, ctx, overlap_tp=args.overlap
    )
    opt_sds = jax.eval_shape(init_fn, params)  # shapes only, no init run
    step = make_step(params)
    batch = jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int32)
    return doctor.diagnose(
        step, params, opt_sds, batch,
        intended=train_step_intended_specs(opt, params, specs, ctx.mesh),
        labels=("params", "opt_state", "batch"),
        mesh=ctx.mesh, large_bytes=args.large_bytes,
    )


def build_serving_reports(args, ctx, cfg, params, bloom):
    """Decode step AND the chunked-prefill program of the mixed step
    (prefix cache + chunking on): ISSUE 6 pins BOTH at zero
    partitioner-inserted resharding, so a PartitionSpec regression in
    either half of the serving tick dies here at compile time. The
    fused paged-attention variants (ISSUE 20, int8 pool — the kernel's
    headline case) are pinned the same way: the Pallas call must lower
    under the head-sharded mesh without the partitioner moving a page,
    and their reports log the tile geometry the VMEM guard approved."""
    from pipegoose_tpu.serving import ServingEngine

    engine = ServingEngine(
        params, cfg, num_slots=2, num_pages=16, page_size=8,
        max_context=32, mesh=ctx.mesh, param_specs=bloom.tp_specs(params),
        prefix_cache=True, prefill_chunk=16,
    )
    paged = ServingEngine(
        params, cfg, num_slots=2, num_pages=16, page_size=8,
        max_context=32, mesh=ctx.mesh, param_specs=bloom.tp_specs(params),
        prefix_cache=True, prefill_chunk=16, kv_dtype="int8",
        attn_kernel="paged",
    )
    return {
        "decode_step": engine.doctor(large_bytes=args.large_bytes),
        "prefill_chunk": engine.doctor_chunk(large_bytes=args.large_bytes),
        "decode_step_paged": paged.doctor(large_bytes=args.large_bytes),
        "prefill_chunk_paged": paged.doctor_chunk(
            large_bytes=args.large_bytes),
    }


def run_guards(name, report, args) -> int:
    from pipegoose_tpu.telemetry import doctor

    rc = 0
    for guard, kwargs in (
        (doctor.assert_no_resharding, {"allow": args.allow}),
        (doctor.assert_matches_intended, {"allow": args.allow_paths}),
        (doctor.assert_fully_sharded,
         {"min_bytes": args.min_shard_bytes, "allow": args.allow_paths}),
    ):
        try:
            guard(report, **kwargs)
        except doctor.ShardingRegressionError as e:
            print(f"\n[{name}] GUARD VIOLATION ({guard.__name__}):\n{e}",
                  file=sys.stderr)
            rc = 2
    return rc


def main() -> int:
    ap = argparse.ArgumentParser(
        description="compiled-program sharding & memory inspector")
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--dp", type=int, default=4)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (XLA_FLAGS host "
                         "platform count; works under a sitecustomize "
                         "that pins an accelerator platform)")
    ap.add_argument("--serving", action="store_true",
                    help="also doctor the paged decode step and the "
                         "chunked-prefill mixed-step program")
    ap.add_argument("--overlap", action="store_true",
                    help="build the ring collective-matmul train step "
                         "(config.overlap_tp — docs/comm.md)")
    ap.add_argument("--grad-comm", default="fp32",
                    choices=("fp32", "bf16", "int8"),
                    help="gradient-reduction wire precision for the "
                         "train step (distributed/compressed.py)")
    ap.add_argument("--expect-ppermute", action="store_true",
                    help="guard: fail (exit 2) unless the train step's "
                         "compiled schedule contains ppermute ring "
                         "collectives (the overlap gate in ci_fast.sh)")
    ap.add_argument("--check", action="store_true",
                    help="run the regression guards; exit 2 on violation")
    ap.add_argument("--allow", action="append", default=[],
                    help="fnmatch pattern of tolerated resharding "
                         "collectives (op, source, or op:source)")
    ap.add_argument("--allow-paths", action="append", default=[],
                    help="fnmatch pattern of buffer paths exempt from "
                         "the mismatch/fully-sharded guards")
    ap.add_argument("--min-shard-bytes", type=int, default=1 << 16,
                    help="fully-sharded guard threshold (default 64KiB "
                         "— sized for the CLI's tiny demo model)")
    ap.add_argument("--large-bytes", type=int, default=1 << 16,
                    help="report-flag threshold for replicated buffers")
    ap.add_argument("--json", default=None,
                    help="write the report(s) as JSON to this path")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the tables (guards/JSON only)")
    args = ap.parse_args()

    if args.fake_devices:
        from pipegoose_tpu.testing import force_cpu_devices

        force_cpu_devices(args.fake_devices)

    import jax

    from pipegoose_tpu.distributed import ParallelContext
    from pipegoose_tpu.models import bloom

    cfg = bloom.BloomConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        n_layer=args.layers, n_head=args.heads,
        overlap_tp=args.overlap,
    )
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    ctx = ParallelContext(tensor_parallel_size=args.tp,
                          data_parallel_size=args.dp)
    rc = 0
    blobs = {}
    try:
        reports = {"train_step": build_train_report(args, ctx, cfg, params,
                                                    bloom)}
        if args.serving:
            reports.update(build_serving_reports(args, ctx, cfg, params,
                                                 bloom))
        for name, report in reports.items():
            if not args.quiet:
                print(f"== {name} ==")
                print(report.format_table())
                print()
            blobs[name] = report.to_json()
            if args.check:
                rc = max(rc, run_guards(name, report, args))
            if args.expect_ppermute and name == "train_step":
                perms = [
                    c for c in report.sharding.collectives
                    if c.op == "collective-permute"
                    and c.source == "ppermute"
                ]
                if not perms:
                    print(
                        f"\n[{name}] GUARD VIOLATION (expect-ppermute): "
                        "no ppermute ring collectives in the compiled "
                        "schedule — the overlap path did not engage",
                        file=sys.stderr,
                    )
                    rc = 2
    finally:
        ctx.destroy()
    if args.json:
        with open(args.json, "w") as f:
            json.dump(blobs, f, indent=1)
        print(f"report written: {args.json}")
    print("mesh doctor:", "FAILED (sharding regression)" if rc else "OK")
    return rc


if __name__ == "__main__":
    sys.exit(main())
