"""Compile-and-verify every Pallas kernel on the real TPU chip.

All flash tests in tests/ run with ``interpret=True`` on the CPU mesh;
block shapes, VMEM budgets, and scalar-prefetch layouts routinely pass
interpret mode and fail (or crawl) on hardware. This script runs each
kernel COMPILED (``interpret=False``) on the attached TPU, checks
numerics against the dense XLA reference, times the flash-vs-XLA A/B,
and writes a JSON acceptance record.

Usage (the axon tunnel is single-client — run only when no other
process holds the TPU):

    python scripts/verify_kernels_tpu.py [out.json]

Covers:
- flash fwd+bwd: causal+ALiBi (BLOOM), padded mask, GQA (nkv<nh),
  sliding window (Mixtral), non-causal  (ops/flash_attention.py)
- ring-flash chunk kernels via ring_flash_attention's sp=1 path, which
  invokes flash_ring_chunk / flash_chunk_dq / flash_chunk_dkv compiled
  (nn/sequence_parallel/ring_attention.py)
- timing: fwd and fwd+bwd wall-clock vs the XLA (S,S) path at a
  realistic shape.
"""
from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from pipegoose_tpu.ops import flash_attention as fa


def dense_reference(q, k, v, slopes, scale, causal, attention_mask=None,
                    window=None):
    """(B, S, nh, hd) dense attention with ALiBi/padding/window — the
    ground truth every kernel variant is checked against (f32 math)."""
    b, s, nh, hd = q.shape
    nkv = k.shape[2]
    if nkv != nh:  # GQA: expand shared kv heads for the dense path
        g = nh // nkv
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if attention_mask is not None:
        kv_pos, kv_neg = fa.mask_to_kv_bias(attention_mask)
    else:
        kv_pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.float32)[None], (b, s))
        kv_neg = jnp.zeros((b, s), jnp.float32)
    scores = scores + slopes[None, :, None, None] * kv_pos[:, None, None, :]
    scores = scores + kv_neg[:, None, None, :]
    qi = jnp.arange(s)[:, None]
    ki = jnp.arange(s)[None, :]
    keep = jnp.ones((s, s), bool)
    if causal:
        keep = keep & (ki <= qi)
    if window is not None:
        keep = keep & (qi - ki < window)
    scores = jnp.where(keep[None, None], scores, fa.NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def rel_err(a, b):
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    denom = max(float(np.abs(b).max()), 1e-6)
    return float(np.abs(a - b).max() / denom)


def check_variant(name, *, b=2, s=512, nh=8, nkv=None, hd=64, causal=True,
                  alibi=True, padded=False, window=None, dtype=jnp.bfloat16):
    nkv = nkv or nh
    key = jax.random.PRNGKey(0)
    kq, kk, kv_, kg = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, s, nh, hd), dtype)
    k = jax.random.normal(kk, (b, s, nkv, hd), dtype)
    v = jax.random.normal(kv_, (b, s, nkv, hd), dtype)
    slopes = (
        jnp.asarray([2.0 ** (-(i + 1)) for i in range(nh)], jnp.float32)
        if alibi else jnp.zeros((nh,), jnp.float32)
    )
    mask = None
    if padded:
        lens = np.full((b,), s)
        lens[0] = s - 3 * (s // 8)  # ragged right padding
        mask = jnp.asarray(np.arange(s)[None, :] < lens[:, None]).astype(jnp.int32)
    scale = hd ** -0.5

    def flash_loss(q, k, v):
        out = fa.flash_attention(
            q, k, v, alibi_slopes=slopes, attention_mask=mask,
            causal=causal, interpret=False, window=window,
        )
        return (out.astype(jnp.float32) ** 2).sum(), out

    def ref_loss(q, k, v):
        out = dense_reference(q, k, v, slopes, scale, causal,
                              attention_mask=mask, window=window)
        return (out.astype(jnp.float32) ** 2).sum(), out

    (_, out_f), grads_f = jax.jit(
        jax.value_and_grad(flash_loss, argnums=(0, 1, 2), has_aux=True)
    )(q, k, v)
    (_, out_r), grads_r = jax.jit(
        jax.value_and_grad(ref_loss, argnums=(0, 1, 2), has_aux=True)
    )(q, k, v)
    jax.block_until_ready((out_f, grads_f, out_r, grads_r))

    if padded:  # padded rows hold uniform garbage by design — compare valid only
        m = np.asarray(mask)[:, :, None, None].astype(bool)
        sel = lambda x: np.asarray(x, np.float32) * m  # noqa: E731
    else:
        sel = lambda x: np.asarray(x, np.float32)  # noqa: E731

    errs = {
        "out": rel_err(sel(out_f), sel(out_r)),
        "dq": rel_err(sel(grads_f[0]), sel(grads_r[0])),
        "dk": rel_err(np.asarray(grads_f[1], np.float32),
                      np.asarray(grads_r[1], np.float32)),
        "dv": rel_err(np.asarray(grads_f[2], np.float32),
                      np.asarray(grads_r[2], np.float32)),
    }
    ok = all(e < 2.5e-2 for e in errs.values())  # bf16 in, f32 accum
    return {"variant": name, "ok": ok, "max_rel_err": errs}


def check_ring_chunks(b=2, s=512, nh=8, hd=64, dtype=jnp.bfloat16):
    """ring_flash_attention with axis_name=None compiles and runs
    flash_ring_chunk + flash_chunk_dq/dkv on the chip (sp=1 path)."""
    from pipegoose_tpu.nn.sequence_parallel.ring_attention import (
        ring_flash_attention,
    )

    key = jax.random.PRNGKey(1)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, nh, hd), dtype)
    k = jax.random.normal(kk, (b, s, nh, hd), dtype)
    v = jax.random.normal(kv_, (b, s, nh, hd), dtype)
    slopes = jnp.asarray([2.0 ** (-(i + 1)) for i in range(nh)], jnp.float32)
    lens = np.full((b,), s)
    lens[0] = s - s // 4
    mask = jnp.asarray(np.arange(s)[None, :] < lens[:, None]).astype(jnp.float32)
    scale = hd ** -0.5

    def ring_loss(q, k, v):
        out = ring_flash_attention(
            q, k, v, axis_name=None, alibi_slopes=slopes, kv_side=mask,
            interpret=False,
        )
        return (out.astype(jnp.float32) ** 2).sum(), out

    def ref_loss(q, k, v):
        # the ring path uses plain (non-cumsum) key positions for ALiBi —
        # matches HF for right padding; mirror that here
        b_, s_ = mask.shape
        kv_pos = jnp.broadcast_to(
            jnp.arange(s_, dtype=jnp.float32)[None], (b_, s_)
        )
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) * scale
        scores = scores + slopes[None, :, None, None] * kv_pos[:, None, None, :]
        scores = scores + jnp.where(mask[:, None, None, :] > 0, 0.0, fa.NEG_INF)
        keep = jnp.arange(s_)[None, :] <= jnp.arange(s_)[:, None]
        scores = jnp.where(keep[None, None], scores, fa.NEG_INF)
        p = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
        return (out.astype(jnp.float32) ** 2).sum(), out

    (_, out_f), grads_f = jax.jit(
        jax.value_and_grad(ring_loss, argnums=(0, 1, 2), has_aux=True)
    )(q, k, v)
    (_, out_r), grads_r = jax.jit(
        jax.value_and_grad(ref_loss, argnums=(0, 1, 2), has_aux=True)
    )(q, k, v)
    jax.block_until_ready((out_f, grads_f, out_r, grads_r))

    m = np.asarray(mask)[:, :, None, None].astype(bool)
    errs = {
        "out": rel_err(np.asarray(out_f, np.float32) * m,
                       np.asarray(out_r, np.float32) * m),
        "dq": rel_err(np.asarray(grads_f[0], np.float32) * m,
                      np.asarray(grads_r[0], np.float32) * m),
        "dk": rel_err(np.asarray(grads_f[1], np.float32),
                      np.asarray(grads_r[1], np.float32)),
        "dv": rel_err(np.asarray(grads_f[2], np.float32),
                      np.asarray(grads_r[2], np.float32)),
    }
    ok = all(e < 2.5e-2 for e in errs.values())
    return {"variant": "ring-flash-chunks(sp=1,causal,alibi,padded)",
            "ok": ok, "max_rel_err": errs}


def _measure_rtt():
    """Dispatch+fetch round trip of the tunnelled backend (subtracted
    from measurements; jax.block_until_ready does NOT wait on axon)."""
    tiny = jax.jit(lambda x: x + 1.0)
    z = jnp.zeros(())
    float(tiny(z))
    t0 = time.perf_counter()
    for _ in range(3):
        float(tiny(z))
    return (time.perf_counter() - t0) / 3


def time_ab(b=8, s=2048, nh=16, hd=64, dtype=jnp.bfloat16, iters=20):
    """Flash-vs-XLA wall clock. The iteration loop lives INSIDE jit
    (lax.scan, output chained into the next input so steps serialize)
    and completion is forced by fetching a scalar — the only honest
    timing recipe on this backend (see bench.py)."""
    from jax import lax

    key = jax.random.PRNGKey(2)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, nh, hd), dtype)
    k = jax.random.normal(kk, (b, s, nh, hd), dtype)
    v = jax.random.normal(kv_, (b, s, nh, hd), dtype)
    slopes = jnp.asarray([2.0 ** (-(i + 1)) for i in range(nh)], jnp.float32)
    scale = hd ** -0.5

    def flash_out(q):
        return fa.flash_attention(
            q, k, v, alibi_slopes=slopes, causal=True, interpret=False
        )

    def xla_out(q):
        return dense_reference(q, k, v, slopes, scale, True)

    def bench(out_fn, grad):
        if grad:
            step = jax.grad(lambda x: (out_fn(x).astype(jnp.float32) ** 2).sum())
        else:
            step = out_fn

        @jax.jit
        def chain(q):
            def body(c, _):
                return step(c).astype(dtype), ()
            o, _ = lax.scan(body, q, None, length=iters)
            return o.astype(jnp.float32).sum()

        float(chain(q))  # compile + warm
        rtt = _measure_rtt()
        t0 = time.perf_counter()
        float(chain(q))
        return max(time.perf_counter() - t0 - rtt, 1e-9) / iters * 1e3  # ms

    res = {
        "shape": [b, s, nh, hd],
        "fwd_ms": {"flash": bench(flash_out, False), "xla": bench(xla_out, False)},
        "fwd_bwd_ms": {"flash": bench(flash_out, True), "xla": bench(xla_out, True)},
    }
    res["fwd_speedup"] = round(res["fwd_ms"]["xla"] / res["fwd_ms"]["flash"], 3)
    res["fwd_bwd_speedup"] = round(
        res["fwd_bwd_ms"]["xla"] / res["fwd_bwd_ms"]["flash"], 3
    )
    return res


def check_fused_ce(layout="vh", t=1024, h=1024, v=250_880,
                   dtype=jnp.bfloat16):
    """Fused vocab CE (ops/fused_ce.py) COMPILED at the real bench
    vocab: loss + both grads vs the materialized-logits reference.
    ``layout``: vh = tied (V,H) embedding, hv = untied (H,V) head."""
    from pipegoose_tpu.ops.fused_ce import fused_ce_sums

    key = jax.random.PRNGKey(2)
    kh, kw = jax.random.split(key)
    hid = jax.random.normal(kh, (t, h), dtype) * 0.3
    w = jax.random.normal(
        kw, (v, h) if layout == "vh" else (h, v), dtype
    ) * 0.02
    targets = jnp.asarray(np.random.RandomState(0).randint(0, v, (t,)))
    token_w = jnp.asarray(
        (np.random.RandomState(1).rand(t) < 0.9).astype(np.float32)
    )

    def fused_loss(hid, w):
        tot, cnt = fused_ce_sums(
            hid, w, targets, token_w, interpret=False, weight_layout=layout
        )
        return tot / cnt

    def ref_loss(hid, w):
        hid32 = hid.astype(jnp.float32)
        w32 = w.astype(jnp.float32)
        eq = "th,vh->tv" if layout == "vh" else "th,hv->tv"
        logits = jnp.einsum(eq, hid32, w32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        pred = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
        per = lse - pred
        return (per * token_w).sum() / token_w.sum()

    fl, (fdh, fdw) = jax.jit(
        jax.value_and_grad(fused_loss, argnums=(0, 1))
    )(hid, w)
    rl, (rdh, rdw) = jax.jit(
        jax.value_and_grad(ref_loss, argnums=(0, 1))
    )(hid, w)
    jax.block_until_ready((fl, fdh, fdw, rl, rdh, rdw))
    errs = {
        "loss": abs(float(fl) - float(rl)) / max(abs(float(rl)), 1e-6),
        "dh": rel_err(fdh, rdh),
        "dw": rel_err(fdw, rdw),
    }
    ok = all(e < 2.5e-2 for e in errs.values())
    return {"variant": f"fused-ce-{layout}", "ok": ok, "max_rel_err": errs,
            "shape": {"t": t, "h": h, "v": v}}


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "docs/acceptance/KERNELS_TPU_r03.json"
    dev = jax.devices()[0]
    record = {
        "record": "pallas-kernels-compiled-on-hardware",
        "device": getattr(dev, "device_kind", str(dev)),
        "platform": dev.platform,
        "interpret": False,
        "variants": [],
    }
    variants = [
        ("causal+alibi (BLOOM)", dict(alibi=True)),
        ("causal no-bias", dict(alibi=False)),
        ("non-causal", dict(alibi=False, causal=False)),
        ("padded mask", dict(alibi=True, padded=True)),
        ("GQA g=4", dict(alibi=False, nh=8, nkv=2)),
        ("sliding window=128 (Mixtral)", dict(alibi=False, window=128)),
        ("GQA g=4 + window=128", dict(alibi=False, nh=8, nkv=2, window=128)),
        ("long seq 4096", dict(alibi=True, s=4096, b=1)),
    ]
    for name, kw in variants:
        t0 = time.perf_counter()
        try:
            r = check_variant(name, **kw)
        except Exception as e:  # noqa: BLE001
            r = {"variant": name, "ok": False,
                 "error": f"{type(e).__name__}: {e}"[:400]}
        r["wall_s"] = round(time.perf_counter() - t0, 1)
        record["variants"].append(r)
        print(json.dumps(r), flush=True)

    t0 = time.perf_counter()
    try:
        r = check_ring_chunks()
    except Exception as e:  # noqa: BLE001
        r = {"variant": "ring-flash-chunks", "ok": False,
             "error": f"{type(e).__name__}: {e}"[:400]}
    r["wall_s"] = round(time.perf_counter() - t0, 1)
    record["variants"].append(r)
    print(json.dumps(r), flush=True)

    for layout in ("vh", "hv"):
        t0 = time.perf_counter()
        try:
            r = check_fused_ce(layout)
        except Exception as e:  # noqa: BLE001
            r = {"variant": f"fused-ce-{layout}", "ok": False,
                 "error": f"{type(e).__name__}: {e}"[:400]}
        r["wall_s"] = round(time.perf_counter() - t0, 1)
        record["variants"].append(r)
        print(json.dumps(r), flush=True)

    try:
        record["timing"] = time_ab()
        print(json.dumps(record["timing"]), flush=True)
    except Exception as e:  # noqa: BLE001
        record["timing"] = {"error": f"{type(e).__name__}: {e}"[:400]}

    record["all_ok"] = all(v.get("ok") for v in record["variants"])
    with open(out_path, "w") as f:
        json.dump(record, f, indent=1)
    print(f"wrote {out_path} all_ok={record['all_ok']}")


if __name__ == "__main__":
    main()
