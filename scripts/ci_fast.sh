#!/usr/bin/env bash
# Fast-tier CI: the curated `pytest -m fast` smoke (one representative
# slice per subsystem, < 5 min on one core — see tests/conftest.py's
# FAST_FILES/FAST_TESTS tables) on fake CPU devices.
#
# The telemetry disabled-cost guards run FIRST and separately, so a
# perf regression in the always-on instrumentation (the < 5 µs
# counter/span contract, the health-off byte-identical-program
# contract) fails loudly up front instead of drowning in the tier's
# output:
#
#   ./scripts/ci_fast.sh            # guards + full fast tier
#   ./scripts/ci_fast.sh -x -q      # extra pytest args pass through
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

# Persistent XLA compilation cache for the SERVING smokes (same dir as
# tests/conftest.py — see there for why it is serving-only: this
# jaxlib segfaults deserializing hybrid train-step executables, while
# jit-pure serving programs round-trip cleanly). Prefix a smoke's
# python invocation with $JAX_SERVING_CACHE_ENV to opt it in.
JAX_SERVING_CACHE_ENV="JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/pipegoose_jax_cache} JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=0 JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES=0"

# Static jit-safety lint FIRST (scripts/lint_jit_safety.py): pure AST,
# no jax import — host-sync calls (.item(), np.asarray, time.*,
# jax.device_get) or bare excepts landing in a jit-path module fail in
# about a second, before anything compiles. Known host-side modules
# live in scripts/jit_safety_allowlist.txt.
echo "== jit-safety lint =="
python scripts/lint_jit_safety.py

echo "== telemetry disabled-cost guards =="
python -m pytest -q -p no:cacheprovider \
    "tests/telemetry/test_registry.py::test_disabled_overhead_under_5us" \
    "tests/telemetry/test_health.py::test_health_off_lowers_to_the_unchanged_program" \
    "$@"

# The sharding-regression gate (mesh doctor, telemetry/doctor.py):
# compile the hybrid train step AND the serving decode step AND the
# chunked-prefill mixed-step program (prefix cache + chunking on,
# ISSUE 6) on an 8-fake-device mesh and fail (exit 2) on
# partitioner-inserted resharding collectives, intended-vs-actual spec
# mismatches, or large replicated buffers — a broken PartitionSpec
# dies here at compile time, not in a TPU bench.
echo "== sharding-regression guard (mesh doctor) =="
python scripts/mesh_doctor.py --fake-devices 8 --tp 2 --dp 4 \
    --check --serving --quiet

# The comm-engine variant of the same gate: the ring-overlap train step
# must compile with ppermute collectives in place of the monolithic
# layer gather AND still zero partitioner-inserted resharding
# (docs/comm.md) — a regression that silently falls back to the
# monolithic path fails here, not in a TPU bench.
echo "== sharding-regression guard (mesh doctor, overlap variant) =="
python scripts/mesh_doctor.py --fake-devices 8 --tp 2 --dp 4 \
    --overlap --grad-comm int8 --check --expect-ppermute --quiet

# The parallelism-planner gate (pipegoose_tpu/planner/, ISSUE 7): rank
# the layout space for the smoke model on 8 fake devices and verify the
# expected-best config — the ring-overlap + int8-wire layout the comm
# engine exists to make fastest — still scores within tolerance of the
# planner's top-1. A regression that silently drops the ppermute
# overlap or the compressed gradient wire format collapses that
# config's relative score and exits 2 here, at compile time.
echo "== parallelism-planner gate =="
python scripts/plan_parallelism.py --fake-devices 8 \
    --grad-comms fp32,int8 --remat-sweep on \
    --check --tp 4 --dp 2 --overlap --grad-comm int8 \
    --tolerance 0.3 --quiet

# Ops-endpoint smoke (telemetry/opsserver.py, ISSUE 8): start the live
# endpoint on an ephemeral port, scrape /metrics and /healthz, and
# assert the exposition parses — the stdlib-only serving observability
# surface must come up before any engine does.
echo "== ops endpoint smoke =="
python - <<'PY'
import json
from urllib.request import urlopen

from pipegoose_tpu.telemetry.opsserver import OpsServer, parse_prometheus_text
from pipegoose_tpu.telemetry.registry import MetricsRegistry

reg = MetricsRegistry(enabled=True)
reg.counter("smoke.requests_total").inc(3)
reg.histogram("smoke.latency_seconds").observe(0.01)
with OpsServer(registry=reg, port=0) as srv:
    assert srv.url, "ops server refused to start"
    body = urlopen(srv.url + "/metrics", timeout=5).read().decode()
    parsed = parse_prometheus_text(body)
    assert parsed["smoke_requests_total"] == 3.0, body
    assert parsed["smoke_latency_seconds_count"] == 1.0, body
    hz = urlopen(srv.url + "/healthz", timeout=5)
    assert hz.status == 200 and json.loads(hz.read())["ok"] is True
print("ops endpoint smoke OK")
PY

# Chaos smoke (testing/chaos.py + trainer recovery, ISSUE 9): a SEEDED
# nonfinite-gradient bomb mid-run must be detected, black-boxed, and
# rolled back to the last checkpoint, and the run must finish with
# finite losses — the recovery path stays exercised on every CI run,
# not just when the robustness suites rotate through the fast tier.
echo "== chaos smoke (seeded nonfinite bomb -> recovery) =="
python - <<'PY'
import shutil
import tempfile

from pipegoose_tpu.testing import ChaosMonkey, ChaosSchedule, force_cpu_devices

force_cpu_devices(1)

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pipegoose_tpu.distributed import ParallelContext
from pipegoose_tpu.models import bloom
from pipegoose_tpu.optim.zero import DistributedOptimizer
from pipegoose_tpu.telemetry import FlightRecorder
from pipegoose_tpu.trainer import AutoRecovery, CheckpointCallback, Trainer

cfg = bloom.BloomConfig(vocab_size=64, hidden_size=32, n_layer=2, n_head=2)
params = bloom.init_params(cfg, jax.random.PRNGKey(0))
out = tempfile.mkdtemp(prefix="chaos_smoke_")
try:
    schedule = ChaosSchedule.seeded(1234, max_step=4, min_step=2,
                                    nonfinite_grads=1)
    recorder = FlightRecorder(out + "/bb", capacity=16)
    monkey = ChaosMonkey(schedule, recorder=recorder,
                         checkpoint_dir=out + "/ckpt")
    recovery = AutoRecovery(out + "/ckpt", max_restores=2,
                            recorder=recorder)
    ctx = ParallelContext()
    trainer = Trainer(
        lambda p, ids: bloom.loss_fn(p, ids, None, ids, cfg,
                                     tp_axis="tensor"),
        params, bloom.tp_specs(params),
        DistributedOptimizer(optax.adam(1e-3), axis_name="data"), ctx,
        callbacks=[monkey, CheckpointCallback(out + "/ckpt", every=1),
                   recorder, recovery],
    )
    rng = np.random.RandomState(0)
    state = trainer.fit(
        jnp.asarray(rng.randint(1, cfg.vocab_size, (4, 8)))
        for _ in range(6)
    )
    assert len(monkey.applied) == 1, monkey.applied_json()
    assert recovery.restores == 1, recovery.restores
    assert state.losses and all(
        np.isfinite(float(l)) for l in state.losses
    ), state.losses
finally:
    shutil.rmtree(out, ignore_errors=True)
print("chaos smoke OK: injected nonfinite bomb recovered, losses finite")
PY

# Quant greedy-parity smoke (pipegoose_tpu/quant/ + serving, ISSUE 10):
# an int8-weight + int8-KV engine must serve the exact token streams of
# the fp engine on a shared-prefix workload, at >= 1.8x measured page
# capacity — the quantization accuracy contract stays exercised on
# every CI run before the tier proper.
echo "== quant greedy-parity smoke (int8 weights + int8 KV) =="
env $JAX_SERVING_CACHE_ENV python - <<'PY'
from pipegoose_tpu.testing import force_cpu_devices

force_cpu_devices(1)

import jax
import numpy as np

from pipegoose_tpu.models import bloom
from pipegoose_tpu.serving import Request, ServingEngine

cfg = bloom.BloomConfig(vocab_size=64, hidden_size=64, n_layer=2, n_head=4)
params = bloom.init_params(cfg, jax.random.PRNGKey(0))
rng = np.random.RandomState(7)
shared = rng.randint(1, 64, (9,))
reqs = [(np.concatenate([shared, rng.randint(1, 64, (k,))]), n)
        for k, n in [(2, 4), (4, 3)]]

def serve(**quant):
    eng = ServingEngine(params, cfg, num_slots=2, num_pages=16,
                        page_size=4, max_context=32, prefix_cache=True,
                        **quant)
    outs, _ = eng.run([Request(prompt=p, max_new_tokens=n)
                       for p, n in reqs])
    return eng, [np.asarray(o.generated) for o in outs]

_, fp = serve()
eng, q = serve(weight_dtype="int8", kv_dtype="int8")
for a, b in zip(fp, q):
    np.testing.assert_array_equal(a, b, err_msg="int8 engine diverged")
ratio = eng.memory_report()["kv"]["page_capacity_ratio"]
assert ratio >= 1.8, f"page capacity {ratio} < 1.8x"
print(f"quant smoke OK: greedy token-identical, {ratio}x page capacity")
PY

# Control-plane router smoke (serving/control_plane/, ISSUE 12): two
# replicas serving the same multi-tenant Zipf-skewed replay — the
# cache-aware arm must forward strictly fewer prefill tokens than
# round-robin (placement turns hit rate from luck into a decision),
# and a forced scale-down drain must migrate in-flight work and finish
# every request with token streams identical to the no-drain run.
echo "== control-plane router smoke (2 replicas, cache-aware vs RR) =="
env $JAX_SERVING_CACHE_ENV python - <<'PY'
from pipegoose_tpu.testing import force_cpu_devices

force_cpu_devices(1)

import jax

from pipegoose_tpu.models import bloom
from pipegoose_tpu.serving.control_plane import (
    control_plane_replay_benchmark,
)

cfg = bloom.BloomConfig(vocab_size=64, hidden_size=32, n_layer=2, n_head=2)
params = bloom.init_params(cfg, jax.random.PRNGKey(0))
# one implementation of the warmup/clear/measure/drain choreography —
# the packaged benchmark bench.py and the TPU sweep also run
res = control_plane_replay_benchmark(
    params, cfg, n_requests=12, n_prefixes=3, prefix_len=48,
    suffix_lens=(2, 4), max_new=2, n_tenants=3, n_replicas=2,
    num_slots=1, num_pages=33, page_size=8, max_context=96,
)
rr, ca = res["round_robin"], res["cache_aware"]
assert ca["prefill_tokens"] < rr["prefill_tokens"], (ca, rr)
assert res["summary"]["prefill_token_reduction"] > 0, res["summary"]
assert ca["shed_requests"] == 0 and rr["shed_requests"] == 0
drain = res["drain"]
assert drain["performed"] and drain["dropped"] == 0, drain
assert drain["outputs_token_identical"] is True, drain
print(f"router smoke OK: cache-aware forwarded {ca['prefill_tokens']} vs "
      f"round-robin {rr['prefill_tokens']} prefill tokens "
      f"({res['summary']['prefill_token_reduction']:.0%} reduction); "
      f"drain dropped {drain['dropped']} of {drain['finished']} "
      f"(token-identical)")
PY

# Disagg smoke (serving/disagg/, ISSUE 13): a 2-pool CPU run — prefill
# pool streaming int8 KV pages into a decode pool — must emit token
# streams identical to one monolithic engine, with the tracer's new
# `transfer` phase keeping queue+prefill+transfer+decode+stall == e2e
# exactly. The cross-mesh handoff contract stays exercised on every CI
# run before the tier proper.
echo "== disagg smoke (2-pool token identity + exact attribution) =="
env $JAX_SERVING_CACHE_ENV python - <<'PY'
from pipegoose_tpu.testing import force_cpu_devices

force_cpu_devices(1)

import jax
import numpy as np

from pipegoose_tpu.models import bloom
from pipegoose_tpu.serving import DisaggEngine, Request, ServingEngine
from pipegoose_tpu.telemetry import MetricsRegistry
from pipegoose_tpu.telemetry.reqtrace import RequestTracer

cfg = bloom.BloomConfig(vocab_size=64, hidden_size=64, n_layer=2, n_head=4)
params = bloom.init_params(cfg, jax.random.PRNGKey(0))
rng = np.random.RandomState(7)
shared = rng.randint(1, 64, (9,))
reqs = [(np.concatenate([shared, rng.randint(1, 64, (k,))]), n)
        for k, n in [(2, 4), (4, 3)]]

def requests():
    return [Request(prompt=p, max_new_tokens=n) for p, n in reqs]

single = ServingEngine(params, cfg, num_slots=2, num_pages=16, page_size=4,
                       max_context=32, prefix_cache=True, prefill_chunk=8,
                       kv_dtype="int8", registry=MetricsRegistry())
ref, _ = single.run(requests())

reg = MetricsRegistry(enabled=True)
tracer = RequestTracer(registry=reg, keep_completed=8)
pe = ServingEngine(params, cfg, num_slots=2, num_pages=16, page_size=4,
                   max_context=32, prefix_cache=True, prefill_chunk=8,
                   prefill_only=True, kv_dtype="int8",
                   registry=MetricsRegistry())
de = ServingEngine(params, cfg, num_slots=2, num_pages=16, page_size=4,
                   max_context=32, prefix_cache=True, prefill_chunk=8,
                   kv_dtype="int8", registry=MetricsRegistry(),
                   stall_patience=10_000)
disagg = DisaggEngine(pe, de, max_inflight=4, registry=reg, tracer=tracer)
outs, metrics = disagg.run(requests())
for a, b in zip(ref, outs):
    np.testing.assert_array_equal(a.generated, b.generated,
                                  err_msg="disagg diverged")
for tl in tracer.completed:
    total = sum(tl.components.values())
    assert abs(total - tl.e2e_s) < 1e-6, (tl.uid, total, tl.e2e_s)
    assert tl.components["transfer_s"] > 0, "transfer phase missing"
xfer = metrics["transfer"]
assert xfer["wire_bytes"] < xfer["fp_equiv_bytes"], xfer
print(f"disagg smoke OK: token-identical across pools, attribution exact, "
      f"{xfer['pages']} pages at {xfer['wire_bytes']} wire bytes "
      f"({xfer['wire_savings_ratio']:.0%} under fp)")
PY

# Crash-recovery smoke (serving/control_plane/ + testing/chaos.py,
# ISSUE 15): a SEEDED replica_crash mid-run on a 2-replica fleet must
# be detected by the health state machine, the dead replica
# quarantined, and every admitted request SALVAGED onto the survivor —
# outputs token-identical to the no-crash fleet, zero requests lost.
echo "== crash-recovery smoke (2 replicas, seeded replica_crash) =="
env $JAX_SERVING_CACHE_ENV python - <<'PY'
import tempfile

from pipegoose_tpu.testing import (
    ChaosMonkey,
    ChaosSchedule,
    force_cpu_devices,
    schedule_fingerprint,
)

force_cpu_devices(1)

import jax
import numpy as np

from pipegoose_tpu.models import bloom
from pipegoose_tpu.serving import Request, ServingEngine, make_skewed_replay
from pipegoose_tpu.serving.control_plane import ControlPlane
from pipegoose_tpu.telemetry import FlightRecorder

cfg = bloom.BloomConfig(vocab_size=64, hidden_size=32, n_layer=2, n_head=2)
params = bloom.init_params(cfg, jax.random.PRNGKey(0))
replay = make_skewed_replay(n_requests=10, n_prefixes=3, prefix_len=32,
                            suffix_lens=(2, 4), max_new=2, vocab=64,
                            seed=0, n_tenants=2)
reqs = lambda: [Request(prompt=p, max_new_tokens=m, tenant=t)
                for p, m, t in replay]

def factory(name, registry):
    return ServingEngine(params, cfg, num_slots=1, num_pages=33,
                         page_size=8, max_context=96, prefix_cache=True,
                         registry=registry)

out = tempfile.mkdtemp(prefix="crash_smoke_")
recorder = FlightRecorder(out, capacity=64)
plane = ControlPlane(factory, n_replicas=2, recorder=recorder)
clean, _ = plane.run(reqs())
schedule = ChaosSchedule.seeded(99, max_step=6, min_step=4,
                                replica_crash=1, n_replicas=2)
assert schedule_fingerprint(schedule) == schedule_fingerprint(
    ChaosSchedule.seeded(99, max_step=6, min_step=4, replica_crash=1,
                         n_replicas=2)), "seeded schedule not reproducible"
monkey = ChaosMonkey(schedule, recorder=recorder)
crashed, metrics = plane.run(reqs(), tick_hook=monkey.fleet_hook)
assert len(monkey.applied) == 1, monkey.applied_json()
assert len(crashed) == len(clean) == 10, (len(clean), len(crashed))
for a, b in zip(clean, crashed):
    np.testing.assert_array_equal(a.generated, b.generated,
                                  err_msg="crash recovery diverged")
assert plane._m_failures.value == 1.0, "crash was not detected"
assert plane._m_lost.value == 0.0, "admitted requests were lost"
assert plane.fleet_status()["failed"] == 1
assert recorder.last_trigger is None, "recovered failure left /healthz red"
print(f"crash-recovery smoke OK: replica failed + quarantined, "
      f"{int(plane._m_salvaged.value + plane._m_resubmitted.value)} "
      f"request(s) salvaged, outputs token-identical, 0 lost")
PY

# Profile smoke (telemetry/xprof.py, ISSUE 14): measured step
# attribution of a tiny hybrid step on fake CPU devices — the
# compute + per-axis-collective + idle components must sum to the
# fenced step wall time within 5%, the profiled collective set must
# agree op-for-op with the mesh doctor's compiled schedule, and the
# StepProfile JSON must round-trip. The measured mirror of the doctor
# gates above stays exercised on every CI run.
echo "== profile smoke (measured step attribution) =="
python - <<'PY'
import json

from pipegoose_tpu.testing import force_cpu_devices

force_cpu_devices(8)

import jax
import jax.numpy as jnp
import numpy as np
import optax

from pipegoose_tpu.distributed import ParallelContext
from pipegoose_tpu.models import bloom
from pipegoose_tpu.optim.zero import DistributedOptimizer
from pipegoose_tpu.parallel import make_hybrid_train_step
from pipegoose_tpu.telemetry import diagnose
from pipegoose_tpu.telemetry.xprof import StepProfile, profile_step

cfg = bloom.BloomConfig(vocab_size=64, hidden_size=32, n_layer=2, n_head=2)
params = bloom.init_params(cfg, jax.random.PRNGKey(0))
ctx = ParallelContext(tensor_parallel_size=2, data_parallel_size=4)
try:
    specs = bloom.tp_specs(params)
    opt = DistributedOptimizer(optax.adam(1e-3), axis_name="data")
    init_fn, make_step = make_hybrid_train_step(
        lambda p, ids: bloom.loss_fn(p, ids, None, ids, cfg,
                                     tp_axis="tensor"),
        specs, opt, ctx,
    )
    opt_state = init_fn(params)
    step = make_step(params)
    ids = jnp.asarray(np.random.RandomState(0).randint(1, 64, (8, 8)))
    prof = profile_step(
        step, params, opt_state, ids, steps=3,
        update_args=lambda out, a: (out[0], out[1], a[2]),
        mesh=ctx.mesh,
    )
    assert prof.source == "device_trace", prof.source
    total = prof.compute_s + prof.comm_s + prof.idle_s
    assert abs(total - prof.wall_step_s) <= 0.05 * prof.wall_step_s, (
        total, prof.wall_step_s, prof.residual_s)
    # op-for-op agreement with the doctor's compiled schedule
    rep = diagnose(step, params, opt_state, ids, mesh=ctx.mesh)
    sched = {c.name for c in rep.sharding.collectives}
    measured = {c["name"] for c in prof.collectives}
    assert measured == sched, (sorted(measured ^ sched))
    rt = StepProfile.from_json(json.loads(json.dumps(prof.to_json())))
    assert rt.comm_by_axes == prof.comm_by_axes
    assert abs(rt.wall_step_s - prof.wall_step_s) < 1e-12
finally:
    ctx.destroy()
print(f"profile smoke OK: {len(prof.collectives)} collectives matched "
      f"op-for-op, compute/comm/idle = "
      f"{prof.compute_fraction:.0%}/{prof.comm_fraction:.0%}/"
      f"{prof.idle_fraction:.0%} of {prof.wall_step_s*1e3:.1f}ms")
PY

# KV-tier smoke (serving/kv_tier/, ISSUE 16): an int8 pool whose
# working set overflows HBM spills evicted prefix pages into the
# host-DRAM tier and restores them on replay — outputs token-identical
# to an all-HBM reference, the restore-aware latency attribution sums
# to e2e exactly, and the tier's resident bytes equal the int8 wire
# census (q+scale planes, never fp).
echo "== kv-tier smoke (host-DRAM spill/restore) =="
env $JAX_SERVING_CACHE_ENV python - <<'PY'
from pipegoose_tpu.testing import force_cpu_devices

force_cpu_devices(1)

import jax
import numpy as np

from pipegoose_tpu.models import bloom
from pipegoose_tpu.serving import Request, ServingEngine
from pipegoose_tpu.serving.kv_tier import HostTier
from pipegoose_tpu.serving.kv_tier.restore import wire_page_bytes
from pipegoose_tpu.telemetry.reqtrace import RequestTracer

cfg = bloom.BloomConfig(vocab_size=64, hidden_size=64, n_layer=2, n_head=4)
params = bloom.init_params(cfg, jax.random.PRNGKey(0))
rng = np.random.RandomState(7)
prefixes = [rng.randint(1, 64, (12,)) for _ in range(2)]
suffixes = [rng.randint(1, 64, (2,)) for _ in range(2)]


def phase(prefix):
    return [Request(prompt=np.concatenate([prefix, s]).astype(np.int32),
                    max_new_tokens=4) for s in suffixes]


kw = dict(num_slots=2, page_size=4, max_context=32, prefill_chunk=4,
          prefix_cache=True, kv_dtype="int8")
tier = HostTier(1 << 20)
eng = ServingEngine(params, cfg, num_pages=9, host_tier=tier, **kw)
tracer = RequestTracer()
eng.attach_tracer(tracer)
ref = ServingEngine(params, cfg, num_pages=33, **kw)

outs, routs, restored = [], [], 0
for pfx in (prefixes[0], prefixes[1], prefixes[0]):
    done, m = eng.run(phase(pfx))
    outs += [o.generated for o in done]
    restored += m.get("kv_tier", {}).get("restored_tokens", 0)
    rdone, _ = ref.run(phase(pfx))
    routs += [o.generated for o in rdone]
assert tier.spills > 0, "overflow never spilled into the tier"
assert restored > 0 and tier.restores > 0, "replay never restored"
for a, b in zip(outs, routs):
    np.testing.assert_array_equal(
        a, b, err_msg="spill->restore round trip diverged from all-HBM")
tls = list(tracer.completed)
assert tls, "tracer recorded nothing"
for tl in tls:
    total = sum(tl.components.values())
    assert abs(total - tl.e2e_s) <= 1e-6 * max(tl.e2e_s, 1.0), (
        tl.uid, total, tl.e2e_s, tl.components)
assert any(tl.components["restore_s"] > 0 for tl in tls), (
    "no request attributed restore time")
wire = wire_page_bytes(eng)
assert tier.resident_bytes == tier.resident_pages * wire, (
    tier.resident_bytes, tier.resident_pages, wire)
rep = eng.memory_report()["host_tier"]
assert rep["resident_bytes"] == tier.resident_bytes
print(f"kv-tier smoke OK: {tier.spills} page(s) spilled, "
      f"{tier.restores} restored ({restored} tokens), outputs "
      f"token-identical to all-HBM, attribution sums to e2e, "
      f"{tier.resident_pages} x {wire} B int8 wire slabs resident")
PY

# Fleet-trace smoke (telemetry/fleettrace.py, ISSUE 17): a 2-replica
# plane with a seeded replica_crash mid-run — every stitched
# cross-replica trace (plane hops + per-replica phases, INCLUDING the
# salvaged request's victim + survivor legs) must sum to its fleet e2e
# at 1e-6, and the replica_failure black box must embed a tail
# exemplar naming the dominant hop. The distributed-tracing exactness
# contract stays exercised on every CI run before the tier proper.
echo "== fleet-trace smoke (2 replicas, stitched crash-salvage trace) =="
env $JAX_SERVING_CACHE_ENV python - <<'PY'
import json
import tempfile

from pipegoose_tpu.testing import ChaosMonkey, ChaosSchedule, force_cpu_devices
from pipegoose_tpu.testing.chaos import Injection

force_cpu_devices(1)

import jax

from pipegoose_tpu.models import bloom
from pipegoose_tpu.serving import Request, ServingEngine, make_skewed_replay
from pipegoose_tpu.serving.control_plane import ControlPlane
from pipegoose_tpu.telemetry import FleetTracer, FlightRecorder
from pipegoose_tpu.telemetry.registry import MetricsRegistry

cfg = bloom.BloomConfig(vocab_size=64, hidden_size=32, n_layer=2, n_head=2)
params = bloom.init_params(cfg, jax.random.PRNGKey(0))
replay = make_skewed_replay(n_requests=8, n_prefixes=3, prefix_len=32,
                            suffix_lens=(2, 4), max_new=2, vocab=64,
                            seed=0, n_tenants=2)

def factory(name, registry):
    return ServingEngine(params, cfg, num_slots=1, num_pages=33,
                         page_size=8, max_context=96, prefix_cache=True,
                         registry=registry)

out = tempfile.mkdtemp(prefix="fleettrace_smoke_")
reg = MetricsRegistry(enabled=True)
ft = FleetTracer(registry=reg)
recorder = FlightRecorder(out, capacity=64)
plane = ControlPlane(factory, n_replicas=2, registry=reg,
                     recorder=recorder, fleet_tracer=ft)
monkey = ChaosMonkey(
    ChaosSchedule([Injection(4, "replica_crash", (("replica", 1),))]),
    recorder=recorder,
)
outs, _ = plane.run(
    [Request(prompt=p, max_new_tokens=m, tenant=t) for p, m, t in replay],
    tick_hook=monkey.fleet_hook,
)
assert len(outs) == 8 and len(monkey.applied) == 1, len(outs)
done = [t for t in ft.completed if not t.lost]
assert len(done) == 8, len(done)
salvaged = [t for t in done if len(t.legs) > 1]
assert salvaged, "crash produced no multi-leg stitched trace"
for t in done:
    row = t.attribution()
    assert abs(row["stitched_total_s"] - t.e2e_s) < 1e-6, (
        t.trace_id, row["stitched_total_s"], t.e2e_s)
    for leg in t.legs:
        assert leg["timeline"].trace_id == t.trace_id
box_path = [p for p in recorder.dumps if "replica_failure" in p][0]
with open(box_path) as f:
    box = json.load(f)
ex = box["trigger"]["details"]["exemplar"]
assert ex and ex["dominant_hop"], "black box lost its exemplar"
assert "fleet_traces" in box, "flight recorder dropped the trace embed"
print(f"fleet-trace smoke OK: {len(done)} stitched traces exact at 1e-6 "
      f"({len(salvaged)} salvaged across replicas, "
      f"{max(len(t.legs) for t in done)} legs max); replica_failure "
      f"exemplar names {ex['dominant_hop']}")
PY

# Memory-audit smoke (telemetry/memledger.py, ISSUE 18): a skewed
# overflow replay with the live memory ledger attached and the leak
# audit running EVERY tick — per-owner-class page accounting must sum
# to pool capacity exactly on every tick, the audit must find zero
# leaks/double-owners/strands, and /debug/memory must serve a parsing
# JSON report over real HTTP. The byte-exact conservation contract
# stays exercised on every CI run before the tier proper.
echo "== memory-audit smoke (ledger conservation + /debug/memory) =="
env $JAX_SERVING_CACHE_ENV python - <<'PY'
import json
from urllib.request import urlopen

from pipegoose_tpu.testing import force_cpu_devices

force_cpu_devices(1)

import jax

from pipegoose_tpu.models import bloom
from pipegoose_tpu.serving import Request, ServingEngine, make_skewed_replay
from pipegoose_tpu.telemetry import MemoryLedger
from pipegoose_tpu.telemetry.opsserver import OpsServer
from pipegoose_tpu.telemetry.registry import MetricsRegistry

cfg = bloom.BloomConfig(vocab_size=64, hidden_size=32, n_layer=2, n_head=2)
params = bloom.init_params(cfg, jax.random.PRNGKey(0))
replay = make_skewed_replay(n_requests=8, n_prefixes=2, prefix_len=8,
                            suffix_lens=(2, 4), max_new=4, vocab=64,
                            seed=0, working_set_factor=1.5,
                            num_pages=17, page_size=4)
eng = ServingEngine(params, cfg, num_slots=2, num_pages=17, page_size=4,
                    max_context=32, prefix_cache=True, prefill_chunk=4,
                    memledger=MemoryLedger(audit_every=1),
                    registry=MetricsRegistry(enabled=True))
breaks = []

def hook(engine, tick):
    cons = engine.memledger.conservation()
    if not cons["ok"]:
        breaks.append((tick, cons))

outs, metrics = eng.run(
    [Request(prompt=p, max_new_tokens=m) for p, m in replay],
    tick_hook=hook)
assert len(outs) == 8, len(outs)
ml = eng.memledger
assert breaks == [], f"conservation broke: {breaks[:3]}"
mem = metrics["memory"]
assert mem["conservation_failures"] == 0, mem
assert mem["leaks"] == 0 and ml.audits_run > 0, mem
assert ml.last_audit["ok"], ml.last_audit
with OpsServer(registry=eng.registry, port=0, memory=ml.report) as srv:
    body = urlopen(srv.url + "/debug/memory", timeout=5).read().decode()
rep = json.loads(body)
assert rep["conservation"]["ok"] is True, rep["conservation"]
total = sum(c["pages"] for c in rep["classes"].values())
assert total == rep["capacity_pages"], (total, rep["capacity_pages"])
print(f"memory-audit smoke OK: {ml.ticks} ticks conserved exactly, "
      f"{ml.audits_run} audits clean (0 leaks), /debug/memory parses "
      f"({rep['capacity_bytes']} B capacity, "
      f"peak request {mem['peak_pages'].get('request', 0)} page(s))")
PY

# Goodput smoke (telemetry/goodput.py, ISSUE 19): a 2-replica plane
# with the goodput ledger attached and a SEEDED replica_crash mid-run
# — per-replica class-seconds must sum to alive wall EXACTLY (the
# conservation contract at 1e-6), and the crash must mint exactly ONE
# incident that closes at rejoin with MTTR > 0 and a positive
# capacity-gap integral. The wall-attribution contract stays exercised
# on every CI run before the tier proper.
echo "== goodput smoke (conservation + seeded crash incident) =="
env $JAX_SERVING_CACHE_ENV python - <<'PY'
import tempfile

from pipegoose_tpu.testing import force_cpu_devices

force_cpu_devices(1)

import jax

from pipegoose_tpu.models import bloom
from pipegoose_tpu.serving import Request, ServingEngine, make_skewed_replay
from pipegoose_tpu.serving.control_plane import ControlPlane
from pipegoose_tpu.telemetry import FlightRecorder
from pipegoose_tpu.testing.chaos import ChaosMonkey, ChaosSchedule, Injection

cfg = bloom.BloomConfig(vocab_size=64, hidden_size=32, n_layer=2, n_head=2)
params = bloom.init_params(cfg, jax.random.PRNGKey(0))
replay = make_skewed_replay(n_requests=8, n_prefixes=3, prefix_len=32,
                            suffix_lens=(2, 4), max_new=3, vocab=64,
                            seed=0, n_tenants=2)

def factory(name, registry):
    return ServingEngine(params, cfg, num_slots=1, num_pages=33,
                         page_size=8, max_context=96, prefix_cache=True,
                         registry=registry)

recorder = FlightRecorder(tempfile.mkdtemp(), capacity=128)
plane = ControlPlane(factory, n_replicas=2, policy="cache_aware",
                     recorder=recorder, goodput=True)
monkey = ChaosMonkey(
    ChaosSchedule([Injection(4, "replica_crash", (("replica", 1),))]),
    recorder=recorder)
outs, metrics = plane.run(
    [Request(prompt=p, max_new_tokens=m, tenant=t) for p, m, t in replay],
    tick_hook=monkey.fleet_hook)
assert len(outs) == 8, len(outs)
plane.rejoin("replica1")
cons = plane.goodput.conservation()
assert cons["ok"] and cons["max_error_s"] <= 1e-6, cons
incidents = plane.goodput.report()["incident_log"]
assert len(incidents) == 1, incidents
inc = incidents[0]
assert inc["kind"] == "crash" and not inc["open"], inc
assert inc["resolved_by"] == "rejoin" and inc["mttr_s"] > 0, inc
assert inc["capacity_gap_integral_s"] > 0, inc
assert inc["detection_latency_ticks"] == 0, inc
gs = metrics["goodput"]
assert gs["conservation_ok"] and 0 < gs["goodput_fraction"] <= 1, gs
print(f"goodput smoke OK: {len(cons['replicas'])} replicas conserved "
      f"exactly (max err {cons['max_error_s']:.1e}s), 1 crash incident "
      f"MTTR {inc['mttr_s']*1e3:.1f}ms, gap integral "
      f"{inc['capacity_gap_integral_s']*1e3:.1f} replica-ms, goodput "
      f"{gs['goodput_fraction']:.0%}")
PY

# Paged-attention kernel smoke (ops/paged_attention.py, ISSUE 20):
# one int8 decode step through the paged one-pass attention (off-TPU
# auto mode: the compiled XLA lane of the kernel's algorithm) must
# match the XLA gather reference's logits (allclose) and greedy token
# exactly, and the VMEM feasibility guard must REFUSE an oversized
# tile for compiled runs instead of silently falling back to gather. (The tp=2 zero-resharding pin on the kernel step rides the
# mesh-doctor --serving gate above — its serving reports now include
# the paged decode/chunk programs.)
echo "== paged-attention kernel smoke (int8 parity + VMEM guard) =="
env $JAX_SERVING_CACHE_ENV python - <<'PY'
from pipegoose_tpu.testing import force_cpu_devices

force_cpu_devices(1)

import jax
import jax.numpy as jnp
import numpy as np

from pipegoose_tpu.models import bloom
from pipegoose_tpu.ops import check_paged_tile
from pipegoose_tpu.serving.kv_pool import (
    init_pages,
    paged_decode_step,
    paged_prefill_chunk,
)

cfg = bloom.BloomConfig(vocab_size=64, hidden_size=64, n_layer=2, n_head=4)
params = bloom.init_params(cfg, jax.random.PRNGKey(0))
rng = np.random.RandomState(3)
kp, vp = init_pages(cfg, 16, 4, kv_dtype="int8")
pt = jnp.asarray(rng.permutation(np.arange(1, 16))[:8][None], jnp.int32)
ids = jnp.asarray(rng.randint(1, 64, (1, 7)), jnp.int32)
n_valid = jnp.asarray([7], jnp.int32)
_, kp, vp = paged_prefill_chunk(params, ids, kp, vp, pt,
                                jnp.zeros((1,), jnp.int32), n_valid, cfg)
tok = jnp.asarray(rng.randint(1, 64, (1,)), jnp.int32)
ref, _, _ = paged_decode_step(params, tok, kp, vp, pt, n_valid, cfg)
out, _, _ = paged_decode_step(params, tok, kp, vp, pt, n_valid, cfg,
                              attn_impl="paged")
err = float(jnp.max(jnp.abs(ref - out)))
assert err < 1e-4, f"kernel diverged from gather: max |dlogits| = {err}"
assert int(jnp.argmax(ref, -1)[0]) == int(jnp.argmax(out, -1)[0])
# the guard refuses an infeasible tile loudly for compiled runs and
# stays exempt in interpret mode (the interpreter has no VMEM limit)
try:
    check_paged_tile(4096, 4096, 1, quantized=True, interpret=False)
    raise SystemExit("VMEM guard accepted an impossible tile")
except ValueError as e:
    assert "VMEM" in str(e), e
check_paged_tile(4096, 4096, 1, quantized=True, interpret=True)
print(f"paged kernel smoke OK: int8 decode step token-identical "
      f"(max |dlogits| {err:.1e}), VMEM guard raises on oversized tile")
PY

echo "== fast tier =="
python -m pytest tests/ -q -m fast -p no:cacheprovider \
    --continue-on-collection-errors "$@"
