#!/usr/bin/env bash
# Fast-tier CI: the curated `pytest -m fast` smoke (one representative
# slice per subsystem, < 5 min on one core — see tests/conftest.py's
# FAST_FILES/FAST_TESTS tables) on fake CPU devices.
#
# The telemetry disabled-cost guards run FIRST and separately, so a
# perf regression in the always-on instrumentation (the < 5 µs
# counter/span contract, the health-off byte-identical-program
# contract) fails loudly up front instead of drowning in the tier's
# output:
#
#   ./scripts/ci_fast.sh            # guards + full fast tier
#   ./scripts/ci_fast.sh -x -q      # extra pytest args pass through
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== telemetry disabled-cost guards =="
python -m pytest -q -p no:cacheprovider \
    "tests/telemetry/test_registry.py::test_disabled_overhead_under_5us" \
    "tests/telemetry/test_health.py::test_health_off_lowers_to_the_unchanged_program" \
    "$@"

echo "== fast tier =="
python -m pytest tests/ -q -m fast -p no:cacheprovider \
    --continue-on-collection-errors "$@"
