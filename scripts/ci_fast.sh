#!/usr/bin/env bash
# Fast-tier CI: the curated `pytest -m fast` smoke (one representative
# slice per subsystem, < 5 min on one core — see tests/conftest.py's
# FAST_FILES/FAST_TESTS tables) on fake CPU devices.
#
# The telemetry disabled-cost guards run FIRST and separately, so a
# perf regression in the always-on instrumentation (the < 5 µs
# counter/span contract, the health-off byte-identical-program
# contract) fails loudly up front instead of drowning in the tier's
# output:
#
#   ./scripts/ci_fast.sh            # guards + full fast tier
#   ./scripts/ci_fast.sh -x -q      # extra pytest args pass through
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS=cpu

echo "== telemetry disabled-cost guards =="
python -m pytest -q -p no:cacheprovider \
    "tests/telemetry/test_registry.py::test_disabled_overhead_under_5us" \
    "tests/telemetry/test_health.py::test_health_off_lowers_to_the_unchanged_program" \
    "$@"

# The sharding-regression gate (mesh doctor, telemetry/doctor.py):
# compile the hybrid train step AND the serving decode step AND the
# chunked-prefill mixed-step program (prefix cache + chunking on,
# ISSUE 6) on an 8-fake-device mesh and fail (exit 2) on
# partitioner-inserted resharding collectives, intended-vs-actual spec
# mismatches, or large replicated buffers — a broken PartitionSpec
# dies here at compile time, not in a TPU bench.
echo "== sharding-regression guard (mesh doctor) =="
python scripts/mesh_doctor.py --fake-devices 8 --tp 2 --dp 4 \
    --check --serving --quiet

# The comm-engine variant of the same gate: the ring-overlap train step
# must compile with ppermute collectives in place of the monolithic
# layer gather AND still zero partitioner-inserted resharding
# (docs/comm.md) — a regression that silently falls back to the
# monolithic path fails here, not in a TPU bench.
echo "== sharding-regression guard (mesh doctor, overlap variant) =="
python scripts/mesh_doctor.py --fake-devices 8 --tp 2 --dp 4 \
    --overlap --grad-comm int8 --check --expect-ppermute --quiet

echo "== fast tier =="
python -m pytest tests/ -q -m fast -p no:cacheprovider \
    --continue-on-collection-errors "$@"
