"""KV-cache decode throughput on the real TPU.

The reference has NO generation/inference path at all (its wrapped HF
model's .generate breaks once modules are re-classed); this framework's
KV-cache decode (models/_decode.py: compiled prefill + one lax.scan
over decode steps) is a beyond-reference capability — this script puts
a hardware number on it.

Timing per docs/perf_tpu_v5e.md: the whole decode loop is ONE dispatch
(lax.scan inside jit), value fetch forces completion, RTT subtracted.

    PYTHONPATH=.:/root/.axon_site python scripts/bench_decode_tpu.py [out.json]
"""
from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np


def main() -> None:
    out_path = (
        sys.argv[1]
        if len(sys.argv) > 1 and not sys.argv[1].startswith("--")
        else "docs/acceptance/DECODE_TPU_r03.json"
    )
    if "--cpu" in sys.argv:
        jax.config.update("jax_platforms", "cpu")

    from pipegoose_tpu.models import bloom, generate as gen

    dev = jax.devices()[0]
    on_tpu = dev.platform.lower() != "cpu"
    if on_tpu:
        cfg = bloom.BloomConfig.bloom_560m(dtype=jnp.bfloat16)
        batch, prompt, new = 8, 128, 256
    else:
        cfg = bloom.BloomConfig(vocab_size=256, hidden_size=64, n_layer=2, n_head=4)
        batch, prompt, new = 2, 8, 8

    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (batch, prompt))
    )

    def run():
        out = gen.generate(params, ids, cfg, max_new_tokens=new)
        np.asarray(out)  # fetch forces completion on the tunnel
        return out

    out = run()  # compile + warm
    assert out.shape == (batch, prompt + new)

    tiny = jax.jit(lambda x: x + 1.0)
    z = jnp.zeros(())
    float(tiny(z))
    t0 = time.perf_counter()
    for _ in range(3):
        float(tiny(z))
    rtt = (time.perf_counter() - t0) / 3

    t0 = time.perf_counter()
    out = run()
    dt = max(time.perf_counter() - t0 - 2 * rtt, 1e-9)  # prefill + decode dispatches

    toks = batch * new
    record = {
        "record": "kv-cache-decode-throughput",
        "device": getattr(dev, "device_kind", dev.platform),
        "model": "bloom-560m bf16" if on_tpu else "bloom-tiny smoke",
        "batch": batch, "prompt_len": prompt, "new_tokens": new,
        "decode_tokens_per_sec": round(toks / dt, 1),
        "per_sequence_tokens_per_sec": round(new / dt, 1),
        "wall_s": round(dt, 3),
        "note": "greedy decode, whole generation = 1 prefill + 1 scanned "
                "decode dispatch; tokens counted = batch * new_tokens",
    }
    Path(out_path).write_text(json.dumps(record, indent=1))
    print(json.dumps(record))


if __name__ == "__main__":
    main()
