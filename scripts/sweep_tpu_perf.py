"""Single-chip perf sweep on the real TPU: flash block sizes + model
config levers (remat, flash on/off) for the bloom-560m bench shape.

Timing recipe per bench.py: loop inside jit (lax.scan), scalar fetch,
RTT subtracted. One attach per run (tunnel is single-client).

    python scripts/sweep_tpu_perf.py [kernel|model]
"""
from __future__ import annotations

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def measure_rtt():
    tiny = jax.jit(lambda x: x + 1.0)
    z = jnp.zeros(())
    float(tiny(z))
    t0 = time.perf_counter()
    for _ in range(3):
        float(tiny(z))
    return (time.perf_counter() - t0) / 3


def timed_chain(step_fn, x0, iters):
    """step_fn: x -> x (same shape/dtype). Returns ms/iter."""

    @jax.jit
    def chain(x):
        def body(c, _):
            return step_fn(c), ()
        o, _ = lax.scan(body, x, None, length=iters)
        return jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32).sum(), o
        )

    r = chain(x0)
    jax.tree_util.tree_map(lambda a: float(a), r)  # compile+warm
    rtt = measure_rtt()
    t0 = time.perf_counter()
    r = chain(x0)
    jax.tree_util.tree_map(lambda a: float(a), r)
    return max(time.perf_counter() - t0 - rtt, 1e-9) / iters * 1e3


def kernel_sweep():
    from pipegoose_tpu.ops import flash_attention as fa

    b, s, nh, hd = 8, 2048, 16, 64
    key = jax.random.PRNGKey(0)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, nh, hd), jnp.bfloat16)
    k = jax.random.normal(kk, (b, s, nh, hd), jnp.bfloat16)
    v = jax.random.normal(kv_, (b, s, nh, hd), jnp.bfloat16)
    slopes = jnp.asarray([2.0 ** (-(i + 1)) for i in range(nh)], jnp.float32)

    results = {}
    orig = fa._pick_block
    for bq in (128, 256, 512):
        for bk in (128, 256, 512, 1024):
            if bq > s or bk > s:
                continue

            # the production call sites pass target=128 for q blocks and
            # target=512 for kv blocks — dispatch the override on that
            def pick(n, target=128, _bq=bq, _bk=bk):
                return _bq if target == 128 else _bk

            fa._pick_block = pick

            def fwd(x):
                return fa.flash_attention(
                    x, k, v, alibi_slopes=slopes, causal=True, interpret=False
                ).astype(jnp.bfloat16)

            def fwdbwd(x):
                return jax.grad(
                    lambda y: (fwd(y).astype(jnp.float32) ** 2).sum()
                )(x).astype(jnp.bfloat16)

            try:
                ms_f = timed_chain(fwd, q, 20)
                ms_fb = timed_chain(fwdbwd, q, 10)
                results[f"bq{bq}_bk{bk}"] = {
                    "fwd_ms": round(ms_f, 3), "fwd_bwd_ms": round(ms_fb, 3)
                }
            except Exception as e:  # noqa: BLE001
                results[f"bq{bq}_bk{bk}"] = {
                    "error": f"{type(e).__name__}: {e}"[:200]
                }
            print(f"bq{bq}_bk{bk}", json.dumps(results[f"bq{bq}_bk{bk}"]),
                  flush=True)
    fa._pick_block = orig
    print(json.dumps(results))


def model_sweep():
    import optax

    from pipegoose_tpu.models import bloom

    batch, seq, steps = 8, 1024, 8
    variants = {
        "remat+flash": dict(remat=True, use_flash=True),
        "remat+xla": dict(remat=True, use_flash=False),
        "attn+flash": dict(remat=True, remat_policy="attn", use_flash=True),
        "dots+flash+ce8": dict(
            remat=True, remat_policy="dots", use_flash=True, ce_chunks=8
        ),
        # b8 no-remat reproducibly kills the remote compile helper
        # (HTTP 500); b4 is the largest batch that compiles no-remat
        "noremat+flash+ce8_b4": dict(
            remat=False, use_flash=True, ce_chunks=8, _batch=4
        ),
    }
    results = {}
    for name, kw in variants.items():
        kw = dict(kw)
        b = kw.pop("_batch", batch)
        cfg = bloom.BloomConfig.bloom_560m(dtype=jnp.bfloat16, **kw)
        while True:
            try:
                params = bloom.init_params(cfg, jax.random.PRNGKey(0))
                opt = optax.adam(1e-4)
                opt_state = opt.init(params)
                ids = jnp.asarray(
                    np.random.RandomState(0).randint(0, cfg.vocab_size, (b, seq))
                )

                @functools.partial(jax.jit, donate_argnums=(0, 1))
                def run(params, opt_state, ids, cfg=cfg):
                    def body(carry, _):
                        p, o = carry
                        loss, g = jax.value_and_grad(bloom.loss_fn)(
                            p, ids, None, ids, cfg
                        )
                        u, o = opt.update(g, o, p)
                        return (optax.apply_updates(p, u), o), loss
                    (p, o), losses = lax.scan(
                        body, (params, opt_state), None, length=steps
                    )
                    return p, o, losses[-1]

                params, opt_state, loss = run(params, opt_state, ids)
                float(loss)
                rtt = measure_rtt()
                t0 = time.perf_counter()
                params, opt_state, loss = run(params, opt_state, ids)
                float(loss)
                dt = max(time.perf_counter() - t0 - rtt, 1e-9)
                tps = b * seq * steps / dt
                results[name] = {"tokens_per_sec": round(tps, 1), "batch": b}
                break
            except Exception as e:  # noqa: BLE001
                if "RESOURCE_EXHAUSTED" in str(e) and b > 1:
                    b //= 2
                    continue
                results[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
                break
        print(name, json.dumps(results[name]), flush=True)
    print(json.dumps(results))


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "kernel"
    (kernel_sweep if mode == "kernel" else model_sweep)()
