"""Single-chip perf sweep on the real TPU: flash block sizes + model
config levers (remat, flash on/off) for the bloom-560m bench shape.

Timing recipe per bench.py: loop inside jit (lax.scan), scalar fetch,
RTT subtracted. One attach per run (tunnel is single-client).

    python scripts/sweep_tpu_perf.py \
        [kernel|model|fusedce|serving|comm|plan|control-plane|disagg]
    python scripts/sweep_tpu_perf.py serving --prefix-replay   # ISSUE 6:
        # Zipf shared-prefix replay arms (baseline / chunked / cached /
        # cached+spec) per slot count instead of the continuous-vs-
        # static A/B
    python scripts/sweep_tpu_perf.py serving --quant   # ISSUE 10: add
        # int8w / int8kv / int8w+int8kv arms (tokens/s, TTFT, HBM,
        # page-capacity ratio vs the fp rows); composes with
        # --prefix-replay
    python scripts/sweep_tpu_perf.py serving --paged   # ISSUE 20: add
        # the fused Pallas paged-attention arm (gather vs kernel
        # tokens/s + profiled decode-step component split at the
        # bloom-560m geometry)
    python scripts/sweep_tpu_perf.py plan   # ISSUE 7: static layout
        # ranking (pipegoose_tpu/planner/), then measure ONLY the
        # top-K (PLAN_TOP_K) and record predicted-vs-measured deltas
        # in the PLAN_JSON artifact
    python scripts/sweep_tpu_perf.py control-plane   # ISSUE 12: the
        # multi-tenant replay through round-robin vs cache-aware
        # routing at 2 and 4 replicas — forwarded prefill tokens,
        # TTFT, tenant shares, drain zero-drop verdict
    python scripts/sweep_tpu_perf.py disagg   # ISSUE 13: prefill pool
        # streaming KV pages into a decode pool vs one monolithic
        # engine — token identity, decode-pool tokens/s vs the
        # decode-only rate, wire-vs-fp byte savings, fp + int8 KV
"""
from __future__ import annotations

import functools
import json
import os as _os
import sys
import time

# runnable from anywhere: the repo root is the import root
sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def measure_rtt():
    tiny = jax.jit(lambda x: x + 1.0)
    z = jnp.zeros(())
    float(tiny(z))
    t0 = time.perf_counter()
    for _ in range(3):
        float(tiny(z))
    return (time.perf_counter() - t0) / 3


def timed_chain(step_fn, x0, iters):
    """step_fn: x -> x (same shape/dtype). Returns ms/iter."""

    @jax.jit
    def chain(x):
        def body(c, _):
            return step_fn(c), ()
        o, _ = lax.scan(body, x, None, length=iters)
        return jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32).sum(), o
        )

    r = chain(x0)
    jax.tree_util.tree_map(lambda a: float(a), r)  # compile+warm
    rtt = measure_rtt()
    t0 = time.perf_counter()
    r = chain(x0)
    jax.tree_util.tree_map(lambda a: float(a), r)
    return max(time.perf_counter() - t0 - rtt, 1e-9) / iters * 1e3


def kernel_sweep():
    from pipegoose_tpu.ops import flash_attention as fa

    b, s, nh, hd = 8, 2048, 16, 64
    key = jax.random.PRNGKey(0)
    kq, kk, kv_ = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, nh, hd), jnp.bfloat16)
    k = jax.random.normal(kk, (b, s, nh, hd), jnp.bfloat16)
    v = jax.random.normal(kv_, (b, s, nh, hd), jnp.bfloat16)
    slopes = jnp.asarray([2.0 ** (-(i + 1)) for i in range(nh)], jnp.float32)

    results = {}
    orig = fa._pick_block
    for bq in (128, 256, 512):
        for bk in (128, 256, 512, 1024):
            if bq > s or bk > s:
                continue

            # the production call sites pass target=128 for q blocks and
            # target=512 for kv blocks — dispatch the override on that
            def pick(n, target=128, _bq=bq, _bk=bk):
                return _bq if target == 128 else _bk

            fa._pick_block = pick

            def fwd(x):
                return fa.flash_attention(
                    x, k, v, alibi_slopes=slopes, causal=True, interpret=False
                ).astype(jnp.bfloat16)

            def fwdbwd(x):
                return jax.grad(
                    lambda y: (fwd(y).astype(jnp.float32) ** 2).sum()
                )(x).astype(jnp.bfloat16)

            try:
                ms_f = timed_chain(fwd, q, 20)
                ms_fb = timed_chain(fwdbwd, q, 10)
                results[f"bq{bq}_bk{bk}"] = {
                    "fwd_ms": round(ms_f, 3), "fwd_bwd_ms": round(ms_fb, 3)
                }
            except Exception as e:  # noqa: BLE001
                results[f"bq{bq}_bk{bk}"] = {
                    "error": f"{type(e).__name__}: {e}"[:200]
                }
            print(f"bq{bq}_bk{bk}", json.dumps(results[f"bq{bq}_bk{bk}"]),
                  flush=True)
    fa._pick_block = orig
    print(json.dumps(results))


def model_sweep():
    import optax

    from pipegoose_tpu.models import bloom

    batch, seq, steps = 8, 1024, 8
    variants = {
        "remat+flash": dict(remat=True, use_flash=True),
        "remat+xla": dict(remat=True, use_flash=False),
        "attn+flash": dict(remat=True, remat_policy="attn", use_flash=True),
        "dots+flash+ce8": dict(
            remat=True, remat_policy="dots", use_flash=True, ce_chunks=8
        ),
        # b8 no-remat reproducibly kills the remote compile helper
        # (HTTP 500); b4 is the largest batch that compiles no-remat
        "noremat+flash+ce8_b4": dict(
            remat=False, use_flash=True, ce_chunks=8, _batch=4
        ),
    }
    results = {}
    for name, kw in variants.items():
        kw = dict(kw)
        b = kw.pop("_batch", batch)
        cfg = bloom.BloomConfig.bloom_560m(dtype=jnp.bfloat16, **kw)
        while True:
            try:
                params = bloom.init_params(cfg, jax.random.PRNGKey(0))
                opt = optax.adam(1e-4)
                opt_state = opt.init(params)
                ids = jnp.asarray(
                    np.random.RandomState(0).randint(0, cfg.vocab_size, (b, seq))
                )

                @functools.partial(jax.jit, donate_argnums=(0, 1))
                def run(params, opt_state, ids, cfg=cfg):
                    def body(carry, _):
                        p, o = carry
                        loss, g = jax.value_and_grad(bloom.loss_fn)(
                            p, ids, None, ids, cfg
                        )
                        u, o = opt.update(g, o, p)
                        return (optax.apply_updates(p, u), o), loss
                    (p, o), losses = lax.scan(
                        body, (params, opt_state), None, length=steps
                    )
                    return p, o, losses[-1]

                params, opt_state, loss = run(params, opt_state, ids)
                float(loss)
                rtt = measure_rtt()
                t0 = time.perf_counter()
                params, opt_state, loss = run(params, opt_state, ids)
                float(loss)
                dt = max(time.perf_counter() - t0 - rtt, 1e-9)
                tps = b * seq * steps / dt
                results[name] = {"tokens_per_sec": round(tps, 1), "batch": b}
                break
            except Exception as e:  # noqa: BLE001
                if "RESOURCE_EXHAUSTED" in str(e) and b > 1:
                    b //= 2
                    continue
                results[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
                break
        print(name, json.dumps(results[name]), flush=True)
    print(json.dumps(results))


def fusedce_sweep():
    """Fused-CE block sizes + A/B vs the materialized-logits CE at the
    bench head shape (T = 8x1024 tokens, H=1024, V=250880)."""
    from pipegoose_tpu.ops import fused_ce as fc

    t, h, v = 8 * 1024, 1024, 250_880
    key = jax.random.PRNGKey(0)
    kh, kw = jax.random.split(key)
    hid = jax.random.normal(kh, (t, h), jnp.bfloat16) * 0.3
    w = jax.random.normal(kw, (v, h), jnp.bfloat16) * 0.02
    targets = jnp.asarray(np.random.RandomState(0).randint(0, v, (t,)))
    token_w = jnp.ones((t,), jnp.float32)

    results = {}

    def xla_ce(hid, w):
        logits = jnp.einsum(
            "th,vh->tv", hid.astype(jnp.float32), w.astype(jnp.float32)
        )
        lse = jax.nn.logsumexp(logits, axis=-1)
        pred = jnp.take_along_axis(logits, targets[:, None], -1)[:, 0]
        return ((lse - pred) * token_w).sum() / token_w.sum()

    def timed_grad(loss_fn, label):
        g = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1)))
        out = g(hid, w)
        float(out[0])  # compile+warm; fetch forces completion
        rtt = measure_rtt()
        t0 = time.perf_counter()
        out = g(hid, w)
        float(out[0])
        ms = max(time.perf_counter() - t0 - rtt, 1e-9) * 1e3
        results[label] = {"fwd_bwd_ms": round(ms, 2)}
        print(label, json.dumps(results[label]), flush=True)

    try:
        timed_grad(xla_ce, "xla_full_logits")
    except Exception as e:  # noqa: BLE001
        results["xla_full_logits"] = {"error": f"{type(e).__name__}: {e}"[:200]}
        print("xla_full_logits", json.dumps(results["xla_full_logits"]),
              flush=True)

    for bt in (128, 256, 512):
        for bv in (256, 512, 1024):
            label = f"fused_bt{bt}_bv{bv}"
            try:
                def fl(hid, w, _bt=bt, _bv=bv):
                    tot, cnt = fc.fused_ce_sums(
                        hid, w, targets, token_w, block_t=_bt, block_v=_bv,
                        interpret=False,
                    )
                    return tot / cnt
                timed_grad(fl, label)
            except Exception as e:  # noqa: BLE001
                results[label] = {"error": f"{type(e).__name__}: {e}"[:200]}
                print(label, json.dumps(results[label]), flush=True)
    print(json.dumps(results))


def comm_sweep():
    """Communication-engine A/B on the attached device mesh: the ring
    collective-matmul overlap vs the monolithic TP path, and the
    int8/bf16-quantized gradient reduction vs fp32, at the bloom-560m
    bench shape (docs/comm.md). Needs >= 2 devices — a single chip
    prints a skip record (the CPU smoke coverage lives in bench.py and
    tests/test_comm_hybrid.py)."""
    import optax

    from pipegoose_tpu.distributed import ParallelContext
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.optim.zero import DistributedOptimizer
    from pipegoose_tpu.parallel import make_hybrid_train_step

    ndev = len(jax.devices())
    if ndev < 2:
        print(json.dumps({"skipped": f"comm sweep needs >= 2 devices, "
                                     f"have {ndev}"}))
        return
    batch, seq, steps = 8, 1024, 8
    tp = 2 if ndev % 2 == 0 else 1
    variants = {
        "flash": dict(overlap=False, grad_comm="fp32"),
        "flash+overlap": dict(overlap=True, grad_comm="fp32"),
        "flash+int8ar": dict(overlap=False, grad_comm="int8"),
        "flash+bf16ar": dict(overlap=False, grad_comm="bf16"),
        "flash+overlap+int8ar": dict(overlap=True, grad_comm="int8"),
    }
    results = {}
    for name, kw in variants.items():
        b = batch
        while True:
            try:
                cfg = bloom.BloomConfig.bloom_560m(
                    dtype=jnp.bfloat16, remat=True, use_flash=True,
                    overlap_tp=kw["overlap"],
                )
                params = bloom.init_params(cfg, jax.random.PRNGKey(0))
                params, cfg = bloom.pad_for_tp(params, cfg, tp)
                ctx = ParallelContext(
                    tensor_parallel_size=tp, data_parallel_size=ndev // tp
                )
                try:
                    specs = bloom.tp_specs(params)
                    opt = DistributedOptimizer(
                        optax.adam(1e-4), axis_name="data",
                        grad_comm=kw["grad_comm"],
                    )

                    def loss_fn(p, ids, cfg=cfg):
                        return bloom.loss_fn(
                            p, ids, None, ids, cfg, tp_axis="tensor"
                        )

                    init_fn, make_step = make_hybrid_train_step(
                        loss_fn, specs, opt, ctx, overlap_tp=kw["overlap"]
                    )
                    opt_state = init_fn(params)
                    step = make_step(params)
                    ids = jnp.asarray(np.random.RandomState(0).randint(
                        0, cfg.valid_vocab_size or cfg.vocab_size, (b, seq)
                    ))
                    p = params
                    p, opt_state, loss = step(p, opt_state, ids)
                    float(loss)  # compile + warm
                    rtt = measure_rtt()
                    t0 = time.perf_counter()
                    for _ in range(steps):
                        p, opt_state, loss = step(p, opt_state, ids)
                    float(loss)
                    dt = max(time.perf_counter() - t0 - rtt, 1e-9)
                finally:
                    ctx.destroy()
                results[name] = {
                    "tokens_per_sec": round(b * seq * steps / dt, 1),
                    "batch": b, "mesh": f"tp{tp}xdp{ndev // tp}",
                }
                break
            except Exception as e:  # noqa: BLE001
                if "RESOURCE_EXHAUSTED" in str(e) and b > 1:
                    b //= 2
                    continue
                results[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
                break
        print(name, json.dumps(results[name]), flush=True)
    print(json.dumps(results))


def plan_sweep():
    """Planner-guided sweep (pipegoose_tpu/planner/, docs/planner.md):
    rank the whole (dp, tp) x overlap x grad_comm layout space from
    shape-only compiles, then MEASURE only the top-K candidates with
    the comm-sweep timing recipe and record the predicted-vs-measured
    delta per candidate in the plan artifact (``PLAN_JSON``, default
    ``plan_report.json``) — the regression signal CI diffs next to the
    BENCH artifacts. ``PLAN_TOP_K`` (default 3) bounds the measured
    set; the static ranking itself costs no device time."""
    import os

    import optax

    from pipegoose_tpu.distributed import ParallelContext
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.optim.zero import DistributedOptimizer
    from pipegoose_tpu.parallel import (
        hybrid_step_kwargs,
        make_hybrid_train_step,
        parallel_context_sizes,
    )
    from pipegoose_tpu.planner import (
        BloomPlanModel,
        CostModel,
        enumerate_candidates,
        run_plan,
    )
    from pipegoose_tpu.telemetry.doctor import report_json_dumps
    from pipegoose_tpu.telemetry.exporters import atomic_write_text

    ndev = len(jax.devices())
    if ndev < 2:
        print(json.dumps({"skipped": f"plan sweep needs >= 2 devices, "
                                     f"have {ndev}"}))
        return
    on_tpu = jax.devices()[0].platform.lower() != "cpu"
    if on_tpu:
        cfg = bloom.BloomConfig.bloom_560m(
            dtype=jnp.bfloat16, remat=True, use_flash=True
        )
        batch, seq, steps = 8, 1024, 8
    else:
        cfg = bloom.BloomConfig(
            vocab_size=512, hidden_size=64, n_layer=2, n_head=4
        )
        batch, seq, steps = 8, 64, 3
    top_k = int(os.environ.get("PLAN_TOP_K", "3"))

    model = BloomPlanModel(cfg, batch=batch, seq=seq)
    candidates = enumerate_candidates(
        ndev, grad_comms=("fp32", "int8"), remat=(True,)
    )
    report = run_plan(model, candidates, CostModel.for_device())
    print(report.format_table(top_k=10), flush=True)

    def measure(c):
        import dataclasses

        ccfg = dataclasses.replace(
            cfg, overlap_tp=c.overlap_tp, remat=c.remat
        )
        params = bloom.init_params(ccfg, jax.random.PRNGKey(0))
        params, ccfg = bloom.pad_for_tp(params, ccfg, c.tp)
        ctx = ParallelContext(**parallel_context_sizes(c))
        try:
            specs = bloom.tp_specs(params)
            opt = DistributedOptimizer(
                optax.adam(1e-4), axis_name="data", grad_comm=c.grad_comm
            )

            def loss_fn(p, ids, ccfg=ccfg):
                return bloom.loss_fn(p, ids, None, ids, ccfg,
                                     tp_axis="tensor")

            init_fn, make_step = make_hybrid_train_step(
                loss_fn, specs, opt, ctx, **hybrid_step_kwargs(c)
            )
            opt_state = init_fn(params)
            step = make_step(params)
            ids = jnp.asarray(np.random.RandomState(0).randint(
                0, ccfg.valid_vocab_size or ccfg.vocab_size, (batch, seq)
            ))
            p = params
            p, opt_state, loss = step(p, opt_state, ids)
            float(loss)  # compile + warm
            rtt = measure_rtt()
            t0 = time.perf_counter()
            for _ in range(steps):
                p, opt_state, loss = step(p, opt_state, ids)
            float(loss)
            dt = max(time.perf_counter() - t0 - rtt, 1e-9)
        finally:
            ctx.destroy()
        return {"tokens_per_sec": round(batch * seq * steps / dt, 1),
                "steps": steps}

    # measure the top-K only — the whole point: static search prunes the
    # space, hardware time goes to the few configs worth timing. NO
    # batch backoff on OOM (unlike comm_sweep): the planner scored THIS
    # workload, so a smaller batch would not be the predicted config —
    # an OOM is recorded as the finding it is.
    for res in report.ranked[:top_k]:
        if res.candidate.pp > 1:
            continue  # the timing loop above is the dense hybrid step
        try:
            measured = measure(res.candidate)
        except Exception as e:  # noqa: BLE001
            measured = {"error": f"{type(e).__name__}: {e}"[:300]}
        if "tokens_per_sec" in measured:
            report.record_measurement(res.candidate, measured)
        print(res.name, json.dumps(measured), flush=True)

    summary = report.predicted_vs_measured()
    print(json.dumps({"predicted_vs_measured": summary}))
    plan_path = os.environ.get("PLAN_JSON", "plan_report.json")
    if plan_path:
        atomic_write_text(plan_path, report_json_dumps(
            report.to_json(), indent=1
        ))
        print(f"plan artifact: {plan_path}")


def control_plane_sweep():
    """Multi-replica control plane (serving/control_plane/, ISSUE 12):
    the multi-tenant Zipf trace through round-robin vs cache-aware
    routing at 2 and 4 replicas on the real chip — forwarded prefill
    tokens, TTFT p50/p99, per-tenant dispatched shares, and the
    scale-down drain's zero-drop verdict per fleet size."""
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.serving.control_plane import (
        control_plane_replay_benchmark,
    )

    cfg = bloom.BloomConfig.bloom_560m(dtype=jnp.bfloat16)
    params = bloom.init_params(cfg, jax.random.PRNGKey(1))
    from pipegoose_tpu import telemetry

    reg = telemetry.get_registry()
    was_enabled = reg.enabled
    results = {}
    for replicas in (2, 4):
        label = f"replicas{replicas}"
        reg.disable()
        try:
            results[label] = control_plane_replay_benchmark(
                params, cfg, n_requests=8 * replicas, n_prefixes=6,
                prefix_len=96, suffix_lens=(8, 16), max_new=8,
                n_tenants=4, n_replicas=replicas, num_slots=1,
                num_pages=65, page_size=32, max_context=192,
            )
        except Exception as e:  # noqa: BLE001
            results[label] = {"error": f"{type(e).__name__}: {e}"[:300]}
        finally:
            if was_enabled:
                reg.enable()
        print(label, json.dumps(results[label]), flush=True)
    print(json.dumps(results))


def disagg_sweep():
    """Disaggregated prefill/decode (serving/disagg/, ISSUE 13): the
    skewed replay through a prefill pool streaming int8 KV pages into
    a decode pool vs one monolithic engine, on the real chip — token
    identity, decode-pool tokens/s vs the monolithic decode-only rate
    (the "prefill off the critical path" meter), TTFT p50/p99, and the
    wire-vs-fp byte savings, at fp and int8 KV."""
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.serving.disagg import disagg_serving_benchmark

    cfg = bloom.BloomConfig.bloom_560m(dtype=jnp.bfloat16)
    params = bloom.init_params(cfg, jax.random.PRNGKey(1))
    from pipegoose_tpu import telemetry

    reg = telemetry.get_registry()
    was_enabled = reg.enabled
    results = {}
    for label, kv in (("fp", None), ("int8kv", "int8")):
        reg.disable()
        try:
            results[label] = disagg_serving_benchmark(
                params, cfg, n_requests=12, n_prefixes=3, prefix_len=96,
                suffix_lens=(8, 16), max_new=16, num_slots=4,
                prefill_pages=65, decode_pages=65, page_size=32,
                max_context=256, prefill_chunk=64, kv_dtype=kv,
            )
        except Exception as e:  # noqa: BLE001
            results[label] = {"error": f"{type(e).__name__}: {e}"[:300]}
        finally:
            if was_enabled:
                reg.enable()
        print(label, json.dumps(results[label]), flush=True)
    print(json.dumps(results))


def serving_sweep(prefix_replay: bool = False, quant: bool = False,
                  tiered: bool = False, paged: bool = False):
    """Continuous-batching vs naive padded serving (serving/engine.py)
    across slot counts on the real chip: the decode-step savings grow
    with the slot count as long as the mixed-length workload keeps
    slots refillable. Prompt lengths stay inside one page bucket so
    each engine compiles a single prefill program (dispatch RTT, not
    compile count, should dominate).

    ``--prefix-replay`` swaps the workload for the ISSUE 6 Zipf-skewed
    shared-prefix replay and measures the four engine arms (monolithic
    baseline, chunked prefill, chunked + prefix cache, + speculative)
    per slot count — tokens/s, TTFT p50/p99, hit rate, prefill-token
    reduction, max decode gap.

    ``--quant`` (ROADMAP item 4) adds the int8w / int8kv / int8w+int8kv
    arms to whichever workload runs: tokens/s, TTFT, resident HBM, and
    the measured page-capacity ratio per slot count, pinned against the
    fp rows of the same run.

    ``--tiered`` (ISSUE 16) adds the KV-memory-hierarchy arms to the
    prefix replay: an overflow variant of the same workload (working
    set > HBM pages) through LRU-evict-and-recompute vs host-tier
    restore vs cross-replica pull — hit rate, TTFT p99, and the
    recompute-token reduction per slot count. Implies
    ``--prefix-replay``.

    ``--paged`` (ISSUE 20) adds the fused Pallas paged-attention arm
    to the A/B workload at the bloom-560m geometry: gather vs kernel
    decode tokens/s, token identity, and the profiled decode-step
    compute/comm/idle split per slot count — the on-hardware numbers
    the bench.py CPU smoke is a stand-in for."""
    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.serving import (
        prefix_replay_benchmark,
        serving_ab_benchmark,
    )

    cfg = bloom.BloomConfig.bloom_560m(dtype=jnp.bfloat16)
    params = bloom.init_params(cfg, jax.random.PRNGKey(1))
    specs = [(10, 50), (30, 15), (20, 35), (5, 60), (28, 25), (12, 8),
             (25, 45), (8, 22), (17, 40), (22, 12), (9, 55), (14, 30)]
    # timed A/B runs with telemetry DISABLED: the continuous arm would
    # otherwise pay per-step event I/O the padded arm doesn't (the
    # __main__ wiring re-enables for the end-of-run snapshot)
    from pipegoose_tpu import telemetry

    reg = telemetry.get_registry()
    was_enabled = reg.enabled
    prefix_replay = prefix_replay or tiered
    results = {}
    for slots in (2, 4, 8):
        label = f"slots{slots}"
        reg.disable()
        try:
            if prefix_replay:
                results[label] = prefix_replay_benchmark(
                    params, cfg, n_requests=4 * slots, n_prefixes=3,
                    prefix_len=64, suffix_lens=(8, 16, 24), max_new=24,
                    num_slots=slots, num_pages=1 + 16 * slots,
                    page_size=32, max_context=256, prefill_chunk=64,
                    include_speculative=True, speculative=(4, 3),
                    include_quant=quant, include_tiered=tiered,
                )
            else:
                results[label] = serving_ab_benchmark(
                    params, cfg, specs, num_slots=slots,
                    num_pages=1 + 3 * slots, page_size=32, max_context=128,
                    quant_arms=quant, paged_kernel=paged,
                )
        except Exception as e:  # noqa: BLE001
            results[label] = {"error": f"{type(e).__name__}: {e}"[:300]}
        finally:
            if was_enabled:
                reg.enable()
        reg.event("sweep.result", label=label, **{
            k: v for k, v in results[label].items()
            if not isinstance(v, dict)
        })
        print(label, json.dumps(results[label]), flush=True)
    print(json.dumps(results))


if __name__ == "__main__":
    import os

    mode = sys.argv[1] if len(sys.argv) > 1 else "kernel"
    modes = {"kernel": kernel_sweep, "model": model_sweep,
             "fusedce": fusedce_sweep, "serving": serving_sweep,
             "comm": comm_sweep, "plan": plan_sweep,
             "control-plane": control_plane_sweep,
             "disagg": disagg_sweep}
    if mode not in modes:
        raise SystemExit(f"unknown mode {mode!r}; pick one of {sorted(modes)}")
    if mode == "serving":
        modes["serving"] = functools.partial(
            serving_sweep,
            prefix_replay="--prefix-replay" in sys.argv[2:],
            quant="--quant" in sys.argv[2:],
            tiered="--tiered" in sys.argv[2:],
            paged="--paged" in sys.argv[2:],
        )
    # telemetry JSONL artifact (the serving sweep's engines emit their
    # per-step time series into it; every mode gets a final snapshot) —
    # set SWEEP_TELEMETRY_JSONL="" to disable
    from pipegoose_tpu import telemetry

    tel_path = os.environ.get(
        "SWEEP_TELEMETRY_JSONL", f"sweep_{mode}_telemetry.jsonl"
    )
    tel = None
    if tel_path:
        reg = telemetry.get_registry()
        reg.enable()
        tel = telemetry.JSONLExporter(tel_path, registry=reg, mode="w")
        reg.event("sweep.start", mode=mode)
    try:
        modes[mode]()
    finally:
        if tel is not None:
            tel.export_snapshot(telemetry.get_registry())
            tel.close()
