#!/usr/bin/env python
"""Parallelism-planner CLI: rank every layout for a model/topology from
shape-only compiles on fake devices (pipegoose_tpu/planner/,
docs/planner.md).

"How do I run this model on N chips" as one call — the planner
enumerates the (dp, tp, pp) x overlap x grad_comm x remat space for the
device count, AOT-compiles each candidate's hybrid train step (nothing
executes), scores wire bytes / FLOPs / HBM / pipeline bubble against
the chip's spec budgets, and prints the ranked table:

    # rank layouts for a bloom-ish model on 8 fake devices
    python scripts/plan_parallelism.py --fake-devices 8

    # plan for real v5e chips without hardware, JSON artifact out
    python scripts/plan_parallelism.py --fake-devices 8 \
        --device-kind v5e --json plan.json --top-k 5

    # CI gate: exit 2 when the configured layout scores below the
    # planner's top-1 by more than --tolerance (or went infeasible)
    python scripts/plan_parallelism.py --fake-devices 8 \
        --check --tp 4 --dp 2 --overlap --grad-comm int8

Exit codes: 0 ok, 2 check violation.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable from anywhere: the repo root is the import root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bool_set(s: str):
    return {"both": (False, True), "on": (True,), "off": (False,)}[s]


def main() -> int:
    ap = argparse.ArgumentParser(
        description="compile-time parallelism planner (static layout search)")
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--fake-devices", type=int, default=None,
                    help="force N fake CPU devices (works under a "
                         "sitecustomize that pins an accelerator platform)")
    ap.add_argument("--device-kind", default=None,
                    help="score against this chip's spec budgets (v5e, "
                         "v5p, v4, ...) instead of the attached device — "
                         "plan for hardware you don't have")
    ap.add_argument("--hbm-gib", type=float, default=None,
                    help="override the per-chip HBM budget (GiB)")
    ap.add_argument("--pp", default="1",
                    help="comma list of pipeline sizes to enumerate "
                         "(default '1'; e.g. '1,2,4')")
    ap.add_argument("--microbatches", type=int, default=2,
                    help="pipeline microbatches for pp>1 candidates")
    ap.add_argument("--grad-comms", default="fp32,bf16,int8",
                    help="comma list of gradient wire formats to enumerate")
    ap.add_argument("--overlap-sweep", default="both",
                    choices=("both", "on", "off"),
                    help="ring collective-matmul overlap options")
    ap.add_argument("--remat-sweep", default="both",
                    choices=("both", "on", "off"),
                    help="rematerialization options")
    ap.add_argument("--top-k", type=int, default=None,
                    help="table rows to print (all by default)")
    ap.add_argument("--json", default=None,
                    help="write the PlanReport as JSON to this path")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the table and per-candidate progress "
                         "(check/JSON only)")
    # --check: the currently-configured layout, compared against top-1
    ap.add_argument("--check", action="store_true",
                    help="gate mode: exit 2 when the --tp/--dp/... layout "
                         "scores below top-1 by more than --tolerance")
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--dp", type=int, default=None,
                    help="default: devices // (tp * pp)")
    ap.add_argument("--pp-current", type=int, default=1,
                    help="pipeline size of the configured layout")
    ap.add_argument("--overlap", action="store_true",
                    help="configured layout uses overlap_tp")
    ap.add_argument("--grad-comm", default="fp32",
                    choices=("fp32", "bf16", "int8"))
    ap.add_argument("--no-remat", action="store_true",
                    help="configured layout runs without remat")
    ap.add_argument("--tolerance", type=float, default=0.5,
                    help="allowed score gap to top-1 in check mode "
                         "(0.5 = configured must reach 50%% of top-1)")
    # serving decode-layout mode (planner/serving.py, ROADMAP items 3+4):
    # analytic (tp, weight_dtype, kv_dtype) x HBM ranking — no compiles
    ap.add_argument("--serving-decode", action="store_true",
                    help="rank serving DECODE layouts instead of train "
                         "steps: (tp, weight_dtype, kv_dtype) vs the "
                         "chip's HBM budget + bandwidth, analytically")
    ap.add_argument("--num-pages", type=int, default=1024,
                    help="serving-decode mode: KV pool pages")
    ap.add_argument("--page-size", type=int, default=16,
                    help="serving-decode mode: tokens per page")
    ap.add_argument("--num-slots", type=int, default=8,
                    help="serving-decode mode: decode slots")
    ap.add_argument("--weight-dtype", default="fp",
                    choices=("fp", "int8", "int4"),
                    help="serving-decode --check: configured weight wire "
                         "precision")
    ap.add_argument("--kv-dtype", default="fp", choices=("fp", "int8"),
                    help="serving-decode --check: configured KV page dtype")
    args = ap.parse_args()

    if args.fake_devices:
        from pipegoose_tpu.testing.fake_cluster import fake_cluster

        fake_cluster(args.fake_devices)

    import jax

    from pipegoose_tpu.models import bloom
    from pipegoose_tpu.planner import (
        BloomPlanModel,
        Candidate,
        CostModel,
        enumerate_candidates,
        run_plan,
    )

    n_devices = len(jax.devices())
    cfg = bloom.BloomConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        n_layer=args.layers, n_head=args.heads,
    )
    cost_model = CostModel.for_device(
        args.device_kind,
        hbm_bytes=(args.hbm_gib * 1024**3 if args.hbm_gib else None),
    )

    if args.serving_decode:
        from pipegoose_tpu.planner import (
            format_serving_plan,
            plan_serving_decode,
        )

        plan = plan_serving_decode(
            cfg, n_devices, num_pages=args.num_pages,
            page_size=args.page_size, num_slots=args.num_slots,
            cost_model=cost_model,
        )
        if not args.quiet:
            print(format_serving_plan(plan))
        if args.json:
            from pipegoose_tpu.telemetry.exporters import atomic_write_text

            atomic_write_text(args.json, json.dumps(plan, indent=1))
            print(f"serving plan written: {args.json}")
        if args.check:
            # gate semantics, serving flavor: the configured
            # (tp, weight_dtype, kv_dtype) row must be FEASIBLE and
            # within --tolerance of the top score — same exit contract
            # as the train-step gate (exit 2 + the row's reason)
            name = (f"tp{args.tp}+w:{args.weight_dtype}"
                    f"+kv:{args.kv_dtype}")
            row = next((r for r in plan["rows"] if r["name"] == name),
                       None)
            if row is None:
                print(f"serving check FAILED: {name} is not in the "
                      f"enumerated space (tp must divide "
                      f"{plan['n_devices']} devices)")
                return 2
            if not row["feasible"]:
                print(f"serving check FAILED: {name} — {row['reason']}")
                return 2
            top = plan["rows"][0]
            if row["score"] < (1.0 - args.tolerance) * top["score"]:
                print(f"serving check FAILED: {name} scores "
                      f"{row['score']:,.0f} tok/s vs top-1 {top['name']} "
                      f"{top['score']:,.0f} (below "
                      f"{1.0 - args.tolerance:.0%})")
                return 2
            print(f"serving check: OK — {name} feasible "
                  f"({row['reason']}), {row['score']:,.0f} tok/s vs "
                  f"top-1 {top['score']:,.0f}")
        return 0

    model = BloomPlanModel(cfg, batch=args.batch, seq=args.seq)
    candidates = enumerate_candidates(
        n_devices,
        pp_sizes=tuple(int(x) for x in args.pp.split(",") if x),
        grad_comms=tuple(x for x in args.grad_comms.split(",") if x),
        overlap=_bool_set(args.overlap_sweep),
        remat=_bool_set(args.remat_sweep),
        n_microbatches=args.microbatches,
    )

    t0 = time.perf_counter()

    def progress(i, n, res):
        if args.quiet:
            return
        tag = (f"{res.score:,.0f} tok/s" if res.feasible
               else f"pruned: {res.prune_reason}")
        print(f"  [{i + 1}/{n}] {res.name}: {tag}", flush=True)

    report = run_plan(model, candidates, cost_model, progress=progress)
    elapsed = time.perf_counter() - t0

    if not args.quiet:
        print()
        print(report.format_table(top_k=args.top_k))
        print(f"\n{len(report.ranked)} ranked, {len(report.pruned)} pruned "
              f"in {elapsed:.1f}s")
    if args.json:
        from pipegoose_tpu.telemetry.exporters import atomic_write_text

        atomic_write_text(args.json, json.dumps(report.to_json(), indent=1))
        print(f"plan written: {args.json}")

    rc = 0
    if args.check:
        dp = args.dp
        if dp is None:
            dp = max(1, n_devices // (args.tp * args.pp_current))
        current = Candidate(
            dp=dp, tp=args.tp, pp=args.pp_current,
            overlap_tp=args.overlap, grad_comm=args.grad_comm,
            remat=not args.no_remat,
            n_microbatches=args.microbatches if args.pp_current > 1 else 1,
        )
        ok, msg = report.check(current, tolerance=args.tolerance)
        print(("plan check: OK — " if ok else "plan check: FAILED — ") + msg,
              file=sys.stdout if ok else sys.stderr)
        rc = 0 if ok else 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
