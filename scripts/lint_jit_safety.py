#!/usr/bin/env python
"""Static jit-safety lint over ``pipegoose_tpu/`` (CI gate).

A host sync inside a jit-path module is the classic silent TPU
performance bug: ``.item()``, ``np.asarray``, ``jax.device_get`` or a
wall-clock read forces a device round-trip per call (or, under
``jit``, a tracer error at the worst possible time), and
nondeterministic host state (``datetime.now``, ``random.*``) bakes a
different program into every trace. This lint walks the library's AST
— no imports, no jax — and flags:

- ``host-sync``: ``.item()`` calls, ``np``/``numpy`` ``asarray``,
  ``jax.device_get``, and ``time.*`` calls, in modules NOT declared
  host-side;
- ``nondeterminism``: ``datetime.now/utcnow/today`` and ``random.*``
  module calls, in modules NOT declared host-side;
- ``bare-except``: ``except:`` with no exception class, in EVERY
  module (it swallows KeyboardInterrupt and tracer-leak errors alike).

The allowlist (``scripts/jit_safety_allowlist.txt``) names the KNOWN
host-side modules/functions — telemetry exporters, the serving host
scheduler, checkpoint I/O — one fnmatch pattern per line, either
``<path glob>`` (whole module) or ``<path glob>::<qualname glob>``
(one function/class). A line carrying a trailing ``# jit-host-ok``
comment in the source is also exempt (visible, reviewable waiver).

    python scripts/lint_jit_safety.py              # lint, exit 1 on findings
    python scripts/lint_jit_safety.py --verbose    # also list allowed hits

Wired into scripts/ci_fast.sh before the doctor gates.
"""
from __future__ import annotations

import argparse
import ast
import os
import sys
from fnmatch import fnmatch
from typing import List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_ROOT = "pipegoose_tpu"
DEFAULT_ALLOWLIST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "jit_safety_allowlist.txt"
)

WAIVER = "jit-host-ok"

# module aliases numpy is commonly imported under; any attribute call
# of `time` counts as a host-clock read
_NP_NAMES = {"np", "numpy", "onp"}
_DATETIME_NONDET = {"now", "utcnow", "today"}


class Finding:
    def __init__(self, path: str, line: int, rule: str, message: str,
                 qualname: str):
        self.path, self.line, self.rule = path, line, rule
        self.message, self.qualname = message, qualname

    def key(self) -> Tuple[str, str]:
        return (self.path, self.qualname)

    def __str__(self) -> str:
        where = f" (in {self.qualname})" if self.qualname else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{where}"


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for an Attribute/Name chain, None for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source_lines: List[str], host_side: bool):
        self.path = path
        self.lines = source_lines
        self.host_side = host_side
        self.stack: List[str] = []
        self.findings: List[Finding] = []

    # -- helpers -----------------------------------------------------------

    @property
    def qualname(self) -> str:
        return ".".join(self.stack)

    def _waived(self, node: ast.AST) -> bool:
        line = self.lines[node.lineno - 1] if node.lineno <= len(self.lines) \
            else ""
        return WAIVER in line

    def _add(self, node: ast.AST, rule: str, message: str) -> None:
        if not self._waived(node):
            self.findings.append(Finding(
                self.path, node.lineno, rule, message, self.qualname
            ))

    # -- scope tracking ----------------------------------------------------

    def visit_FunctionDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    # -- rules -------------------------------------------------------------

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self._add(node, "bare-except",
                      "bare `except:` swallows KeyboardInterrupt and "
                      "tracer errors — name the exception class")
        self.generic_visit(node)

    def visit_Call(self, node):
        if not self.host_side:
            self._check_host_sync(node)
        self.generic_visit(node)

    def _check_host_sync(self, node: ast.Call) -> None:
        fn = node.func
        # x.item()
        if isinstance(fn, ast.Attribute) and fn.attr == "item" \
                and not node.args and not node.keywords:
            self._add(node, "host-sync",
                      "`.item()` forces a device->host sync per call")
            return
        dotted = _dotted(fn)
        if dotted is None:
            return
        head, _, tail = dotted.partition(".")
        if head in _NP_NAMES and tail in ("asarray", "array"):
            self._add(node, "host-sync",
                      f"`{dotted}` materializes device values on host "
                      f"(use jnp, or mark the module host-side)")
        elif dotted == "jax.device_get":
            self._add(node, "host-sync",
                      "`jax.device_get` is an explicit device->host fetch")
        elif head == "time" and tail and "." not in tail:
            self._add(node, "host-sync",
                      f"`{dotted}()` reads the host clock on the jit path "
                      f"(fence + measure outside, or mark host-side)")
        elif head == "random" and tail and "." not in tail:
            self._add(node, "nondeterminism",
                      f"`{dotted}()` draws unseeded host randomness — "
                      f"thread a jax PRNG key instead")
        elif tail.split(".")[-1] in _DATETIME_NONDET and "datetime" in dotted:
            self._add(node, "nondeterminism",
                      f"`{dotted}()` bakes wall-clock state into the "
                      f"traced program")


def load_allowlist(path: str) -> List[str]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if line:
                out.append(line)
    return out


def _allowed(patterns: List[str], relpath: str, qualname: str) -> bool:
    for pat in patterns:
        if "::" in pat:
            ppat, qpat = pat.split("::", 1)
            if fnmatch(relpath, ppat) and (
                fnmatch(qualname, qpat)
                or fnmatch(qualname, qpat + ".*")
            ):
                return True
        elif fnmatch(relpath, pat):
            return True
    return False


def lint_source(
    source: str, relpath: str, patterns: List[str]
) -> Tuple[List[Finding], List[Finding]]:
    """(violations, allowed) for one module's source text."""
    # whole-module status comes from module-form entries only — a
    # "path::*" qualname glob must not silently promote itself
    host_side = _allowed([p for p in patterns if "::" not in p],
                         relpath, "")
    tree = ast.parse(source, filename=relpath)
    linter = _Linter(relpath, source.splitlines(), host_side)
    linter.visit(tree)
    violations, allowed = [], []
    # a qualname glob of bare "*" is a whole-module entry in disguise —
    # it may clear host-sync findings but, like a real whole-module
    # entry, never a bare except
    qual_patterns = [
        p for p in patterns
        if "::" in p and p.split("::", 1)[1].strip() != "*"
    ]
    for f in linter.findings:
        if f.rule == "bare-except":
            # no module-level exemption — only a NAMED qualname entry
            # or an inline waiver clears a bare except
            ok = _allowed(qual_patterns, relpath, f.qualname)
        else:
            ok = host_side or _allowed(patterns, relpath, f.qualname)
        (allowed if ok else violations).append(f)
    return violations, allowed


def lint_tree(
    root: str, patterns: List[str], repo: str = REPO
) -> Tuple[List[Finding], List[Finding]]:
    violations: List[Finding] = []
    allowed: List[Finding] = []
    top = os.path.join(repo, root)
    for dirpath, dirnames, filenames in os.walk(top):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, repo).replace(os.sep, "/")
            with open(full, encoding="utf-8") as f:
                src = f.read()
            v, a = lint_source(src, rel, patterns)
            violations += v
            allowed += a
    return violations, allowed


def main() -> int:
    ap = argparse.ArgumentParser(description="jit-safety static lint")
    ap.add_argument("--root", default=DEFAULT_ROOT,
                    help="directory to lint, relative to the repo root")
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                    help="host-side allowlist file")
    ap.add_argument("--verbose", action="store_true",
                    help="also print allowlisted hits")
    args = ap.parse_args()

    patterns = load_allowlist(args.allowlist)
    violations, allowed = lint_tree(args.root, patterns)
    for f in violations:
        print(str(f), file=sys.stderr)
    if args.verbose:
        for f in allowed:
            print(f"allowed: {f}")
    n_mod = len({f.path for f in violations})
    if violations:
        print(
            f"\njit-safety lint: {len(violations)} violation(s) in "
            f"{n_mod} module(s). Fix, or — for genuinely host-side code "
            f"— add a `path::qualname` line to "
            f"{os.path.relpath(args.allowlist, REPO)} or a trailing "
            f"`# {WAIVER}` comment.",
            file=sys.stderr,
        )
        return 1
    print(f"jit-safety lint: OK ({len(allowed)} allowlisted hit(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
