"""Throughput benchmark: BLOOM-560m train step on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Also writes a telemetry JSONL artifact (``BENCH_TELEMETRY_JSONL``,
default ``bench_telemetry.jsonl``; empty string disables): per-variant
events, the serving engine's per-step time series, and a final metrics
snapshot (pipegoose_tpu/telemetry/, docs/observability.md) — plus a
sibling Perfetto timeline (``BENCH_TRACE_JSON``, default
``bench_telemetry_trace.json``; open in ui.perfetto.dev) of the same
run's spans, and a request-trace artifact (``BENCH_REQTRACE_JSON``,
default ``bench_request_trace.json``) whose per-arm latency attribution
decomposes the prefix-replay TTFT deltas (telemetry/reqtrace.py).

The reference publishes no throughput numbers (BASELINE.md) — its
acceptance bar is convergence only. ``vs_baseline`` therefore reports
achieved MFU / 0.40, the north-star MFU threshold from BASELINE.json.

Robustness: the TPU backend in this environment can wedge (single-client
tunnel). The parent process therefore NEVER touches the accelerator
backend itself: the full bench runs in ONE child process that prints a
``BENCH_READY <platform>`` sentinel right after backend init. The parent
enforces a short deadline for the sentinel (wedged-backend bound) and a
longer one for the measurement, terminating gracefully (SIGTERM first —
a SIGKILLed attached client wedges the tunnel). Any child failure or
timeout falls back to a CPU smoke run reported with
``"device": "cpu-fallback"`` instead of rc=1.
"""
from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import threading
import time

PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
TPU_BENCH_TIMEOUT_S = int(os.environ.get("BENCH_TPU_TIMEOUT_S", "1200"))
# backend-attach retries: the axon tunnel is single-client, so a lingering
# attached process (the r03 round-end failure mode) makes the FIRST probe
# hang; once that holder exits/is killed the tunnel frees up, so retrying
# with a pause converts "wedged at snapshot time" into a captured result
PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3"))
PROBE_BACKOFF_S = int(os.environ.get("BENCH_PROBE_BACKOFF_S", "45"))


def _peak_flops(device_kind: str) -> float:
    # the peak table lives in telemetry.derived (single source of truth
    # for the MFU denominator); import lazily — the parent process must
    # not import jax-adjacent modules before spawning the child
    from pipegoose_tpu.telemetry.derived import peak_flops_for

    return peak_flops_for(device_kind)


def _run_bench_child():
    """Run the bench in ONE child process (single backend attach).

    The child prints ``BENCH_READY <platform>`` right after backend init
    and its JSON result line at the end. Deadlines: PROBE_TIMEOUT_S until
    the sentinel, TPU_BENCH_TIMEOUT_S after it. Termination is graceful
    (SIGTERM, then SIGKILL after 15s) — the axon tunnel is single-client
    and a SIGKILLed attached client wedges it for the session.

    Returns ``(json_line_or_None, backend_ready)`` — the ready flag lets
    the caller distinguish "tunnel held by another client" (retryable)
    from "measurement itself failed".
    """
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env={**os.environ, "BENCH_CHILD": "1"},
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    lines: list[str] = []
    err_tail: list[str] = []  # bounded — keep the last ~100 lines
    ready = threading.Event()
    done = threading.Event()

    def reader():
        for line in proc.stdout:
            lines.append(line.rstrip("\n"))
            if line.startswith("BENCH_READY"):
                ready.set()
        done.set()

    err_done = threading.Event()

    def err_reader():
        # drain continuously: a chatty child (TPU runtime logs) can fill
        # the 64KB pipe buffer and deadlock if stderr is read only at exit
        for line in proc.stderr:
            err_tail.append(line)
            if len(err_tail) > 100:
                del err_tail[:-100]
        err_done.set()

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    threading.Thread(target=err_reader, daemon=True).start()

    def wait_for(ev: threading.Event, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if ev.wait(min(2.0, max(0.0, deadline - time.monotonic()))):
                return True
            if proc.poll() is not None:  # child already exited
                return ev.wait(2.0)
        return False

    ok = wait_for(ready, PROBE_TIMEOUT_S) and wait_for(done, TPU_BENCH_TIMEOUT_S)
    if not ok:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
    rc = proc.wait()
    done.wait(5)  # let the readers drain
    err_done.wait(5)  # the traceback flushes last — wait for EOF
    err = "".join(err_tail)
    # walk back to the last line that PARSES: a child killed mid-print
    # (the SIGTERM path above) can leave a truncated final line
    json_lines = []
    for ln in lines:
        if ln.startswith("{"):
            try:
                json.loads(ln)
            except ValueError:
                continue
            json_lines.append(ln)
    if json_lines:
        if not (ok and rc == 0):
            # the child emits a cumulative result line after EVERY
            # variant, so a late-variant hang/crash (e.g. the no-remat
            # compile killing the helper) must not discard the
            # measurements already taken — but DO surface the traceback
            sys.stderr.write(
                f"bench child died rc={rc} after partial results; using "
                "last. child stderr tail:\n" + err[-2000:] + "\n"
            )
        return json_lines[-1], True
    sys.stderr.write(
        f"bench child failed rc={rc} ready={ready.is_set()}:\n"
        + err[-2000:] + "\n"
    )
    return None, ready.is_set()


def _cached_hardware_result():
    """Newest builder-recorded hardware bench (docs/acceptance/BENCH_TPU_*).

    Embedded in the CPU-fallback JSON under an explicit
    ``cached_hardware_result`` key so a wedged tunnel at snapshot time
    still carries secondary (clearly-labelled, self-reported) evidence.
    """
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    paths = sorted(
        glob.glob(os.path.join(here, "docs", "acceptance", "BENCH_TPU_*.json")),
        key=os.path.getmtime,
    )
    for p in reversed(paths):
        try:
            with open(p) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        return {
            "note": (
                "builder-recorded hardware result (NOT captured by this "
                "run — live capture fell back to CPU)"
            ),
            "source": os.path.relpath(p, here),
            "recorded_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(os.path.getmtime(p))
            ),
            "result": rec,
        }
    return None


def run_bench(force_cpu: bool) -> None:
    if force_cpu:
        # Force CPU BEFORE the first backend touch — the axon sitecustomize
        # ignores JAX_PLATFORMS, only the config update works. Fake 8
        # host devices so the hybrid comm variants (overlap / int8
        # all-reduce need a mesh) run in the CPU smoke too;
        # override=False keeps an operator-set device count (the
        # historical bench behavior, and the test-suite convention).
        from pipegoose_tpu.testing.fake_cluster import fake_cluster

        fake_cluster(8, override=False)
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pipegoose_tpu.models import bloom

    dev = jax.devices()[0]
    if os.environ.get("BENCH_CHILD"):
        print("BENCH_READY", dev.platform, flush=True)
    on_tpu = dev.platform.lower() != "cpu"
    device_kind = getattr(dev, "device_kind", "cpu") if on_tpu else (
        "cpu-fallback" if force_cpu else "cpu"
    )

    # telemetry JSONL artifact alongside the stdout JSON line: variant
    # events + the serving engine's step time series + final snapshot.
    # File I/O only — the one-JSON-line stdout contract is untouched.
    from pipegoose_tpu import telemetry

    reg = telemetry.get_registry()
    tel_path = os.environ.get("BENCH_TELEMETRY_JSONL", "bench_telemetry.jsonl")
    tel = trace = None
    if tel_path:
        # enable ONLY when an artifact is wanted: an empty path opts out
        # of the measurement overhead (fenced spans, histograms) too
        reg.enable()
        # mode="w": each run_bench invocation owns the artifact — a
        # retried child attempt or the CPU fallback must not interleave
        # with a previous attempt's stream
        tel = telemetry.JSONLExporter(tel_path, registry=reg, mode="w")
        # sibling Perfetto timeline of the same run (ui.perfetto.dev);
        # same opt-out, same per-run ownership (write() replaces)
        trace_path = os.environ.get(
            "BENCH_TRACE_JSON", os.path.splitext(tel_path)[0] + "_trace.json"
        )
        if trace_path:
            trace = telemetry.ChromeTraceExporter(trace_path, registry=reg)
        reg.event("bench.start", device=device_kind, on_tpu=on_tpu)

    if on_tpu:
        steps = 10
        # variant -> (config, batch, seq); CHAMPION FIRST — the child
        # emits a cumulative result line after every variant, so the
        # most important number lands even if a later variant wedges
        variants = {
            "flash": (
                bloom.BloomConfig.bloom_560m(
                    dtype=jnp.bfloat16, remat=True, use_flash=True
                ),
                8, 1024,
            ),
            # fused Pallas CE (ops/fused_ce.py): the 8 GB fp32 logits
            # buffer never exists, so no-remat has the HBM to run at
            # full batch — the primary MFU>=0.40 candidates (round 5)
            "noremat+flash+fusedce": (
                bloom.BloomConfig.bloom_560m(
                    dtype=jnp.bfloat16, remat=False, use_flash=True,
                    fused_ce=True,
                ),
                8, 1024,
            ),
            "flash+fusedce": (
                bloom.BloomConfig.bloom_560m(
                    dtype=jnp.bfloat16, remat=True, use_flash=True,
                    fused_ce=True,
                ),
                8, 1024,
            ),
            "xla": (
                bloom.BloomConfig.bloom_560m(dtype=jnp.bfloat16, remat=True),
                8, 1024,
            ),
            # chunked CE keeps the 8 GB fp32 logits buffer off HBM
            # (docs/perf_tpu_v5e.md) — enables the no-remat variant
            "flash+ce8": (
                bloom.BloomConfig.bloom_560m(
                    dtype=jnp.bfloat16, remat=True, use_flash=True, ce_chunks=8
                ),
                8, 1024,
            ),
            # longer sequence, same token count: the flash kernels' edge
            # over XLA attention grows with S (docs/perf_tpu_v5e.md)
            "flash_s2048": (
                bloom.BloomConfig.bloom_560m(
                    dtype=jnp.bfloat16, remat=True, use_flash=True
                ),
                4, 2048,
            ),
            # LAST: b8 no-remat with full logits reproducibly killed the
            # remote compile helper in r3 (docs/perf_tpu_v5e.md) — keep
            # probing in case the toolchain heals, but never at the
            # other variants' cost
            "noremat+flash+ce8": (
                bloom.BloomConfig.bloom_560m(
                    dtype=jnp.bfloat16, remat=False, use_flash=True, ce_chunks=8
                ),
                8, 1024,
            ),
        }
    else:  # CPU smoke fallback
        steps = 3
        variants = {
            "xla": (
                bloom.BloomConfig(
                    vocab_size=1024, hidden_size=256, n_layer=4, n_head=8,
                    dtype=jnp.float32,
                ),
                2, 128,
            )
        }

    def measure(cfg, batch, seq):
        params = bloom.init_params(cfg, jax.random.PRNGKey(0))
        opt = optax.adam(1e-4)
        opt_state = opt.init(params)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, cfg.vocab_size, (batch, seq))
        )

        # Timing on the tunnelled TPU backend needs care:
        # - jax.block_until_ready does NOT wait for remote execution on
        #   the axon platform (measured: "4400 TFLOP/s" on a 197-peak
        #   chip) — only a value fetch (float()) forces completion;
        # - per-dispatch round-trip is ~67ms, so the step loop must live
        #   INSIDE jit (lax.scan) and the residual RTT is subtracted.
        # Donation: without it XLA holds old AND new params+opt state
        # live across the step — 2x state memory OOMs 560m+Adam on 16GB.
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def run(params, opt_state, ids):
            def body(carry, _):
                params, opt_state = carry
                loss, grads = jax.value_and_grad(bloom.loss_fn)(
                    params, ids, None, ids, cfg
                )
                updates, opt_state = opt.update(grads, opt_state, params)
                return (optax.apply_updates(params, updates), opt_state), loss
            (params, opt_state), losses = jax.lax.scan(
                body, (params, opt_state), None, length=steps
            )
            return params, opt_state, losses[-1]

        # warmup/compile (fetch forces completion)
        params, opt_state, loss = run(params, opt_state, ids)
        loss = float(loss)

        # dispatch+fetch round-trip to subtract from the measurement
        tiny = jax.jit(lambda x: x + 1.0)
        z = jnp.zeros(())
        float(tiny(z))
        t0 = time.perf_counter()
        for _ in range(3):
            float(tiny(z))
        rtt = (time.perf_counter() - t0) / 3

        t0 = time.perf_counter()
        params, opt_state, loss = run(params, opt_state, ids)
        loss = float(loss)
        dt = max(time.perf_counter() - t0 - rtt, 1e-9)

        tokens_per_sec = batch * seq * steps / dt
        # model FLOPs per token: 6*N for dense matmuls + 12*L*H*seq attention
        n_params = sum(
            int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params)
        )
        flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.hidden_size * seq
        mfu = tokens_per_sec * flops_per_token / _peak_flops(device_kind)
        return {
            "tokens_per_sec": round(tokens_per_sec, 1),
            "mfu": round(mfu, 4),
            "loss": float(loss),
        }

    # communication-engine variants (docs/comm.md): the hybrid TP x DP
    # step with (a) the ring collective-matmul overlap path and (b) the
    # int8-quantized gradient reduction — variant -> (config, batch,
    # seq, tp, grad_comm). These need >= 2 devices (the CPU smoke fakes
    # 8); measured with the step's own jitted shard_map in a Python
    # loop (one warm-up, RTT-corrected) so the compiled program is the
    # production one, not a scan-wrapped cousin.
    if on_tpu:
        comm_base = dict(dtype=jnp.bfloat16, remat=True, use_flash=True)
        comm_shape = (8, 1024)
    else:
        # flash on CPU means interpreter-mode Pallas — keep the smoke's
        # variant LABELS (the TPU contract) but run XLA attention
        comm_base = dict(
            vocab_size=1024, hidden_size=256, n_layer=4, n_head=8,
            dtype=jnp.float32,
        )
        comm_shape = (8, 128)
    comm_variants = {
        "flash+overlap": (dict(comm_base, overlap_tp=True), 2, "fp32"),
        "flash+int8ar": (dict(comm_base), 1, "int8"),
        "flash+overlap+int8ar": (dict(comm_base, overlap_tp=True), 2, "int8"),
    }

    def measure_hybrid(cfg_kw, tp, grad_comm, batch, seq):
        import optax

        from pipegoose_tpu.distributed import ParallelContext
        from pipegoose_tpu.optim.zero import DistributedOptimizer
        from pipegoose_tpu.parallel import make_hybrid_train_step

        ndev = len(jax.devices())
        if ndev < 2 or ndev % max(tp, 1):
            raise RuntimeError(
                f"comm variant needs a mesh ({ndev} device(s), tp={tp})"
            )
        cfg = (
            bloom.BloomConfig.bloom_560m(**cfg_kw)
            if on_tpu else bloom.BloomConfig(**cfg_kw)
        )
        params = bloom.init_params(cfg, jax.random.PRNGKey(0))
        params, cfg = bloom.pad_for_tp(params, cfg, tp)
        ctx = ParallelContext(
            tensor_parallel_size=tp, data_parallel_size=ndev // tp
        )
        try:
            specs = bloom.tp_specs(params)
            opt = DistributedOptimizer(
                optax.adam(1e-4), axis_name="data", grad_comm=grad_comm
            )

            def hloss(p, ids):
                return bloom.loss_fn(p, ids, None, ids, cfg, tp_axis="tensor")

            init_fn, make_step = make_hybrid_train_step(
                loss_fn=hloss, param_specs=specs, optimizer=opt,
                parallel_context=ctx,
                overlap_tp=bool(cfg_kw.get("overlap_tp")),
            )
            opt_state = init_fn(params)
            step = make_step(params)
            ids = jnp.asarray(np.random.RandomState(0).randint(
                0, cfg.valid_vocab_size or cfg.vocab_size, (batch, seq)
            ))
            p = params
            p, opt_state, loss = step(p, opt_state, ids)  # compile+warm
            loss = float(loss)
            tiny = jax.jit(lambda x: x + 1.0)
            z = jnp.zeros(())
            float(tiny(z))
            t0 = time.perf_counter()
            for _ in range(3):
                float(tiny(z))
            rtt = (time.perf_counter() - t0) / 3
            t0 = time.perf_counter()
            for _ in range(steps):
                p, opt_state, loss = step(p, opt_state, ids)
            loss = float(loss)
            dt = max(time.perf_counter() - t0 - rtt, 1e-9)
        finally:
            ctx.destroy()
        tokens_per_sec = batch * seq * steps / dt
        n_params = sum(
            int(np.prod(q.shape)) for q in jax.tree_util.tree_leaves(params)
        )
        flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.hidden_size * seq
        mfu = tokens_per_sec * flops_per_token / (
            _peak_flops(device_kind) * ndev
        )
        return {
            "tokens_per_sec": round(tokens_per_sec, 1),
            "mfu": round(mfu, 4),
            "loss": loss,
            "mesh": f"tp{tp}xdp{ndev // tp}",
            "grad_comm": grad_comm,
        }

    def serving_block():
        """Continuous-batching vs naive padded batching at mixed
        sequence lengths (serving/engine.py A/B). Prompt lengths stay
        inside ONE page bucket so each arm compiles a single prefill
        program; the raggedness that padded batching pays for comes
        from the mixed max_new_tokens.

        Telemetry is DISABLED for the timed A/B — the continuous arm
        would otherwise pay a JSONL write+flush per decode step that
        the padded arm doesn't, skewing the reported speedup — and the
        per-step time series is captured by ONE extra instrumented run
        afterwards, outside the measurement.

        The block also replays a Zipf-skewed shared-prefix workload
        (ISSUE 6) through four engine arms — monolithic baseline,
        chunked prefill, chunked + prefix cache, + self-speculative —
        reporting tokens/s, TTFT p50/p99, the prefill-token (FLOP)
        reduction at the measured hit rate, and the max decode-step gap
        chunking bounds."""
        from pipegoose_tpu.serving import (
            Request,
            ServingEngine,
            prefix_replay_benchmark,
            serving_ab_benchmark,
        )

        if on_tpu:
            scfg = bloom.BloomConfig.bloom_560m(dtype=jnp.bfloat16)
            specs = [(10, 50), (30, 15), (20, 35), (5, 60),
                     (28, 25), (12, 8), (25, 45), (8, 22)]
            kw = dict(num_slots=4, num_pages=33, page_size=32,
                      max_context=128)
            replay_kw = dict(n_requests=16, n_prefixes=3, prefix_len=96,
                             suffix_lens=(8, 16, 24), max_new=16,
                             num_slots=4, num_pages=65, page_size=32,
                             max_context=256, prefill_chunk=64)
            cp_kw = dict(n_requests=16, n_prefixes=4, prefix_len=96,
                         suffix_lens=(8, 16), max_new=8, n_tenants=3,
                         n_replicas=2, num_slots=1, num_pages=65,
                         page_size=32, max_context=192)
            dg_kw = dict(n_requests=12, n_prefixes=3, prefix_len=96,
                         suffix_lens=(8, 16), max_new=16, num_slots=4,
                         prefill_pages=65, decode_pages=65, page_size=32,
                         max_context=256, prefill_chunk=64,
                         kv_dtype="int8")
        else:
            scfg = bloom.BloomConfig(
                vocab_size=512, hidden_size=128, n_layer=2, n_head=4,
                dtype=jnp.float32,
            )
            specs = [(6, 10), (3, 4), (7, 13), (2, 6)]
            kw = dict(num_slots=2, num_pages=13, page_size=8,
                      max_context=32)
            replay_kw = dict(n_requests=10, n_prefixes=3, prefix_len=48,
                             suffix_lens=(2, 4, 6), max_new=4,
                             num_slots=2, num_pages=33, page_size=8,
                             max_context=64, prefill_chunk=16)
            cp_kw = dict(n_requests=12, n_prefixes=4, prefix_len=48,
                         suffix_lens=(2, 4), max_new=2, n_tenants=3,
                         n_replicas=2, num_slots=1, num_pages=41,
                         page_size=8, max_context=64)
            dg_kw = dict(n_requests=8, n_prefixes=3, prefix_len=24,
                         suffix_lens=(2, 4), max_new=4, num_slots=2,
                         prefill_pages=33, decode_pages=33, page_size=8,
                         max_context=64, prefill_chunk=16,
                         kv_dtype="int8")
        sparams = bloom.init_params(scfg, jax.random.PRNGKey(1))
        # request-trace artifact (BENCH_REQTRACE_JSON, default
        # bench_request_trace.json; empty disables): one EXTRA traced
        # replay per arm AFTER the measurement, whose per-arm latency
        # attribution explains the cached-vs-baseline TTFT delta
        # (ISSUE 8) — queue/prefill/decode/stall components per request
        # plus the cache-savings share vs the prefill-token reduction.
        reqtrace_path = os.environ.get(
            "BENCH_REQTRACE_JSON", "bench_request_trace.json"
        )
        # fleet-trace artifact (BENCH_FLEETTRACE_JSON, default
        # bench_fleet_trace.json; empty disables): one EXTRA traced
        # control-plane replay AFTER the measurement whose stitched
        # cross-replica attribution (ISSUE 17) reports per-hop p50/p99
        # (ingress/ledger/route/dispatch/replica) plus the top-3
        # slowest tail exemplars, each naming its dominant hop
        fleettrace_path = os.environ.get(
            "BENCH_FLEETTRACE_JSON", "bench_fleet_trace.json"
        )
        was_enabled = reg.enabled
        reg.disable()
        try:
            # quant arms (ISSUE 10): fp/int8w/int8kv/int8w+int8kv rows —
            # tokens/s + TTFT + the measured HBM/page-capacity ratios —
            # land in the same serving artifact every bench run
            # paged-kernel arm (ISSUE 20): the fused Pallas
            # paged-attention kernel vs the XLA gather on the same
            # int8-pool workload — tokens/s, token identity, and the
            # profiled decode-step compute/comm/idle split
            res = serving_ab_benchmark(sparams, scfg, specs,
                                       quant_arms=True, paged_kernel=True,
                                       **kw)
            # KV memory hierarchy (ISSUE 16): an overflow replay whose
            # working set exceeds HBM pages, through LRU-recompute vs
            # host-tier restore vs cross-replica pull — hit rate, TTFT
            # p99, and the recompute-token reduction land in the same
            # artifact
            res["prefix_replay"] = prefix_replay_benchmark(
                sparams, scfg, seed=0, include_speculative=True,
                include_quant=True, include_tiered=True,
                trace=bool(reqtrace_path), **replay_kw,
            )
            # multi-replica control plane (ISSUE 12): the same
            # multi-tenant Zipf trace through 2 replicas at each
            # routing arm — cache-aware vs round-robin on forwarded
            # prefill tokens + TTFT, plus the scale-down drain's
            # zero-drop verdict
            from pipegoose_tpu.serving.control_plane import (
                control_plane_replay_benchmark,
            )

            res["control_plane"] = control_plane_replay_benchmark(
                sparams, scfg, seed=0,
                fleet_trace=bool(fleettrace_path), **cp_kw,
            )
            # disaggregated prefill/decode (ISSUE 13): the same skewed
            # replay through a prefill pool streaming int8 KV pages
            # into a decode pool vs one monolithic engine — token
            # identity, decode-pool rate vs the monolithic decode-only
            # rate, and the wire-vs-fp byte savings
            from pipegoose_tpu.serving.disagg import (
                disagg_serving_benchmark,
            )

            res["disagg"] = disagg_serving_benchmark(
                sparams, scfg, seed=0, **dg_kw,
            )
        finally:
            if was_enabled:
                reg.enable()
        if reqtrace_path and "request_trace" in res["prefix_replay"]:
            from pipegoose_tpu.telemetry.exporters import (
                atomic_write_text as _awt,
                safe_json_dumps as _sjd,
            )

            # the per-request rows live in the sibling artifact, the
            # stdout payload keeps only the cross-arm summary
            rt = res["prefix_replay"].pop("request_trace")
            _awt(reqtrace_path, _sjd({
                "device": device_kind,
                "replay": {k: v for k, v in replay_kw.items()},
                "ttft_per_arm": {
                    arm: {q: row[q] for q in ("ttft_p50_s", "ttft_p99_s")}
                    for arm, row in res["prefix_replay"].items()
                    if isinstance(row, dict) and "ttft_p50_s" in row
                },
                **rt,
            }, indent=1))
            res["prefix_replay"]["request_trace_summary"] = rt["summary"]
            res["prefix_replay"]["request_trace_json"] = reqtrace_path
        if fleettrace_path and "fleet_trace" in res["control_plane"]:
            from pipegoose_tpu.telemetry.exporters import (
                atomic_write_text as _awt,
                safe_json_dumps as _sjd,
            )

            # per-hop rows + exemplar traces live in the sibling
            # artifact; the stdout payload keeps only the pointer
            ftr = res["control_plane"].pop("fleet_trace")
            _awt(fleettrace_path, _sjd({
                "device": device_kind,
                "replay": {k: v for k, v in cp_kw.items()},
                **ftr,
            }, indent=1))
            res["control_plane"]["fleet_trace_json"] = fleettrace_path
        if tel is not None:
            srng = np.random.RandomState(0)
            vocab = getattr(scfg, "valid_vocab_size", None) or scfg.vocab_size
            # the instrumented replay also carries the live memory
            # ledger (ISSUE 18): peak per-owner-class occupancy +
            # fragmentation land in the serving payload and the
            # BENCH_HISTORY row, conservation-checked for free
            engine = ServingEngine(sparams, scfg, memledger=True, **kw)
            _, smetrics = engine.run([
                Request(prompt=srng.randint(1, vocab, (int(s),)),
                        max_new_tokens=int(n))
                for s, n in specs
            ])
            mem = smetrics.get("memory")
            if mem is not None:
                res["memory"] = mem
                reg.event("bench.serving_memory",
                          peak_pages=mem["peak_pages"],
                          peak_bytes=mem["peak_bytes"],
                          peak_fragmentation=mem["peak_fragmentation"],
                          conservation_failures=mem[
                              "conservation_failures"],
                          leaks=mem["leaks"])
            # the fleet goodput ledger's wall attribution (ISSUE 19):
            # availability lands in bench_telemetry.jsonl next to the
            # memory peaks, so an incident-burning bench run is visible
            # without opening the trace
            gp = res.get("control_plane", {}).get("goodput")
            if gp is not None:
                reg.event("bench.serving_goodput",
                          goodput_fraction=gp["goodput_fraction"],
                          badput_seconds=gp["badput_seconds"],
                          incidents=gp["incidents"],
                          conservation_ok=gp["conservation_ok"])
        return res

    def emit(results, serving=None) -> bool:
        ok = {k: v for k, v in results.items() if "error" not in v}
        if not ok:
            return False
        best = max(ok, key=lambda k: ok[k]["tokens_per_sec"])
        r = results[best]
        payload = {
            "metric": "bloom-560m train tokens/sec/chip"
            if on_tpu
            else "bloom-tiny train tokens/sec (cpu smoke)",
            "value": r["tokens_per_sec"],
            "unit": "tokens/sec/chip",
            # a CPU smoke number in the MFU schema would read as a
            # real (terrible) TPU result — report null off-hardware
            "vs_baseline": round(r["mfu"] / 0.40, 4) if on_tpu else None,
            "mfu": r["mfu"],
            "device": device_kind,
            "best_variant": best,
            "variants": results,
            "loss": r["loss"],
        }
        if serving is not None:
            payload["serving"] = serving
        if not on_tpu:
            cached = _cached_hardware_result()
            if cached is not None:
                payload["cached_hardware_result"] = cached
        print(json.dumps(payload), flush=True)
        return True

    results = {}
    for name, (cfg, batch, seq) in variants.items():
        # a failing variant (e.g. an experimental kernel) must not discard
        # the other variants' measurements; OOM backs off the batch size
        b = batch
        while True:
            try:
                results[name] = measure(cfg, b, seq)
                results[name]["batch"] = b
                results[name]["seq"] = seq
                reg.gauge(f"bench.{name}.tokens_per_s").set(
                    results[name]["tokens_per_sec"]
                )
                reg.gauge(f"bench.{name}.mfu").set(results[name]["mfu"])
                break
            except Exception as e:  # noqa: BLE001
                if "RESOURCE_EXHAUSTED" in str(e) and b > 1:
                    b //= 2
                    continue
                results[name] = {"error": f"{type(e).__name__}: {e}"[:500]}
                break
        reg.event("bench.variant", name=name, **results[name])
        # cumulative emission (CHILD mode only — the parent filters to
        # the last line; in direct/fallback mode it would break the
        # one-JSON-line stdout contract): a later variant hanging or
        # killing the backend costs nothing
        if os.environ.get("BENCH_CHILD"):
            emit(results)

    # comm-engine variants AFTER the champions (same crash-isolation
    # argument; they must never cost the primary numbers); OOM backs
    # off the batch like the main loop
    cb, cs = comm_shape
    for name, (cfg_kw, tp, grad_comm) in comm_variants.items():
        b = cb
        while True:
            try:
                results[name] = measure_hybrid(cfg_kw, tp, grad_comm, b, cs)
                results[name]["batch"] = b
                results[name]["seq"] = cs
                reg.gauge(f"bench.{name}.tokens_per_s").set(
                    results[name]["tokens_per_sec"]
                )
                reg.gauge(f"bench.{name}.mfu").set(results[name]["mfu"])
                break
            except Exception as e:  # noqa: BLE001
                if "RESOURCE_EXHAUSTED" in str(e) and b > 1:
                    b //= 2
                    continue
                results[name] = {"error": f"{type(e).__name__}: {e}"[:500]}
                break
        reg.event("bench.variant", name=name, **{
            k: v for k, v in results[name].items() if not isinstance(v, dict)
        })
        if os.environ.get("BENCH_CHILD"):
            emit(results)

    # the best PLAIN variant (comm variants carry their own mesh/step
    # shape) + its one-step train fn: shared by the mesh-doctor
    # artifact (shape-only compile) and the BENCH_HISTORY profile (real
    # execution) below — ONE definition of "the benched step"
    ok_variants = [
        k for k, v in results.items() if "error" not in v and k in variants
    ]
    best_variant = (
        max(ok_variants, key=lambda k: results[k]["tokens_per_sec"])
        if ok_variants else None
    )

    def bench_one_step(cfg, opt):
        def one_step(params, opt_state, ids):
            loss, grads = jax.value_and_grad(bloom.loss_fn)(
                params, ids, None, ids, cfg
            )
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss
        return one_step

    # mesh-doctor artifact (BENCH_DOCTOR_JSON, default bench_doctor.json;
    # empty disables): the benched step's ACTUAL shardings + per-device
    # HBM table (telemetry/doctor.py), recorded per bench run so a
    # partitioning regression is visible in the artifact diff, not just
    # as a slower number. Shape-only AOT compile — nothing executes, and
    # a doctor failure never discards the measurements above.
    doctor_path = os.environ.get("BENCH_DOCTOR_JSON", "bench_doctor.json")
    if doctor_path and best_variant is not None:
        try:
            from pipegoose_tpu.telemetry import doctor as _doctor
            from pipegoose_tpu.telemetry.exporters import atomic_write_text

            dcfg, _, dseq = variants[best_variant]
            dbatch = results[best_variant]["batch"]
            p_sds = jax.eval_shape(
                lambda k: bloom.init_params(dcfg, k), jax.random.PRNGKey(0)
            )
            dopt = optax.adam(1e-4)
            o_sds = jax.eval_shape(dopt.init, p_sds)
            ids_sds = jax.ShapeDtypeStruct((dbatch, dseq), jnp.int32)

            report = _doctor.diagnose(
                jax.jit(bench_one_step(dcfg, dopt), donate_argnums=(0, 1)),
                p_sds, o_sds, ids_sds,
                labels=("params", "opt_state", "batch"),
            )
            _doctor.set_doctor_gauges(report, registry=reg)
            atomic_write_text(doctor_path, json.dumps({
                "variant": best_variant, "device": device_kind,
                "batch": dbatch, "seq": dseq,
                "report": report.to_json(),
            }, indent=1))
            if tel is not None:
                reg.event(
                    "bench.doctor", variant=best_variant, path=doctor_path,
                    replicated_bytes=report.sharding.replicated_bytes,
                    resharding_bytes=report.sharding.resharding_bytes,
                    hbm_peak_bytes=report.memory.peak_bytes,
                )
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(f"bench doctor failed (non-fatal): {e}\n")

    # parallelism-planner artifact (BENCH_PLAN_JSON, default
    # bench_plan.json; empty disables): statically rank EXACTLY the
    # hybrid comm variants this run measured and record the
    # predicted-vs-measured delta per variant — the planner's
    # acceptance signal (ISSUE 7): top-1 agreement with the measured
    # best, or the divergence on the record. Shape-only compiles;
    # non-fatal like the doctor artifact.
    plan_path = os.environ.get("BENCH_PLAN_JSON", "bench_plan.json")
    comm_ok = [k for k, v in results.items()
               if "error" not in v and k in comm_variants]
    if plan_path and comm_ok:
        try:
            from pipegoose_tpu.planner import (
                BloomPlanModel,
                Candidate,
                CostModel,
                run_plan,
            )
            from pipegoose_tpu.telemetry.exporters import atomic_write_text

            ndev = len(jax.devices())
            base_kw = {k: v for k, v in comm_base.items() if k != "overlap_tp"}
            plan_cfg = (
                bloom.BloomConfig.bloom_560m(**base_kw)
                if on_tpu else bloom.BloomConfig(**base_kw)
            )
            cand_of = {
                name: Candidate(
                    dp=ndev // tp, tp=tp,
                    overlap_tp=bool(kw.get("overlap_tp")),
                    grad_comm=gc,
                    remat=bool(base_kw.get("remat", False)),
                )
                for name, (kw, tp, gc) in comm_variants.items()
            }
            # ONE workload per plan: variants whose OOM backoff shrank
            # the batch below the nominal comm batch were measured at a
            # DIFFERENT workload — planning them at cb would skew (or
            # validity-prune) the comparison, so they are listed as
            # skipped instead of silently mixed in
            plan_names = [n for n in comm_ok if results[n]["batch"] == cb]
            skipped = {n: f"measured at backed-off batch "
                          f"{results[n]['batch']} != {cb}"
                       for n in comm_ok if n not in plan_names}
            plan_model = BloomPlanModel(plan_cfg, batch=cb, seq=cs)
            plan_report = run_plan(
                plan_model, [cand_of[n] for n in plan_names],
                CostModel.for_device(device_kind), registry=reg,
            )
            for name in plan_names:
                plan_report.record_measurement(
                    cand_of[name],
                    {"tokens_per_sec": results[name]["tokens_per_sec"],
                     "bench_variant": name},
                )
            pvm = plan_report.predicted_vs_measured()
            atomic_write_text(plan_path, json.dumps({
                "device": device_kind,
                "variants": {n: cand_of[n].name for n in plan_names},
                "skipped_batch_mismatch": skipped,
                "predicted_vs_measured": pvm,
                "report": plan_report.to_json(),
            }, indent=1))
            if tel is not None:
                reg.event(
                    "bench.plan", path=plan_path,
                    rank_agreement=pvm.get("rank_agreement"),
                    predicted_best=pvm.get("predicted_best"),
                    measured_best=pvm.get("measured_best"),
                )
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(f"bench planner failed (non-fatal): {e}\n")

    # serving throughput A/B LAST: the train numbers are the primary
    # contract, a serving failure must not discard them
    try:
        serving = serving_block()
    except Exception as e:  # noqa: BLE001
        serving = {"error": f"{type(e).__name__}: {e}"[:300]}

    # perf-trajectory history (BENCH_HISTORY_JSONL, default
    # BENCH_HISTORY.jsonl; empty disables): ONE summary row per bench
    # run — run id, per-arm tokens/s, best-variant MFU, and the
    # MEASURED component fractions of one profiled train step
    # (telemetry/xprof.py) — appended so the repo's perf trajectory is
    # machine-readable. The perf sentinel (telemetry/sentinel.py) reads
    # the tail as its baseline window and stamps a regression verdict
    # on the row ("idle time 2.1x baseline") before it is written.
    # Non-fatal like the doctor/plan artifacts.
    history_path = os.environ.get("BENCH_HISTORY_JSONL",
                                  "BENCH_HISTORY.jsonl")
    if history_path:
        try:
            from pipegoose_tpu.telemetry.sentinel import PerfSentinel
            from pipegoose_tpu.telemetry.xprof import profile_step

            row = {
                "run_id": time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
                "device": device_kind,
                "arms": {
                    k: v["tokens_per_sec"] for k, v in results.items()
                    if "error" not in v
                },
            }
            if best_variant is not None:
                row["best_variant"] = best_variant
                row["tokens_per_s"] = results[best_variant]["tokens_per_sec"]
                row["mfu"] = results[best_variant]["mfu"]
                hcfg, _, hseq = variants[best_variant]
                hbatch = results[best_variant]["batch"]
                hparams = bloom.init_params(hcfg, jax.random.PRNGKey(0))
                hopt = optax.adam(1e-4)
                hopt_state = hopt.init(hparams)
                hids = jnp.asarray(np.random.RandomState(0).randint(
                    0, hcfg.vocab_size, (hbatch, hseq)))
                # the SAME step the doctor artifact above AOT-compiled,
                # this time executed for real under the profiler
                prof = profile_step(
                    jax.jit(bench_one_step(hcfg, hopt),
                            donate_argnums=(0, 1)),
                    hparams, hopt_state, hids, steps=2, warmup=2,
                    update_args=lambda out, a: (out[0], out[1], a[2]),
                )
                row["profile"] = {
                    "source": prof.source,
                    "wall_step_s": prof.wall_step_s,
                    "compute_s": prof.compute_s,
                    "comm_s": prof.comm_s,
                    "idle_s": prof.idle_s,
                    "comm_by_axes": prof.comm_by_axes,
                    "compute_fraction": round(prof.compute_fraction, 4),
                    "comm_fraction": round(prof.comm_fraction, 4),
                    "idle_fraction": round(prof.idle_fraction, 4),
                    "measured_mfu": prof.mfu,
                }
            # the instrumented serving replay's memory-ledger peaks
            # (ISSUE 18) ride the same trajectory row, so per-class KV
            # occupancy creep is as machine-readable as tokens/s
            if isinstance(serving, dict) and "memory" in serving:
                smem = serving["memory"]
                row["serving_memory"] = {
                    "peak_pages": smem["peak_pages"],
                    "peak_fragmentation": smem["peak_fragmentation"],
                    "conservation_failures":
                        smem["conservation_failures"],
                    "leaks": smem["leaks"],
                }
            # paged-attention kernel (ISSUE 20): both arms' profiled
            # decode-step component fractions ride the trajectory row,
            # so a kernel regression (compute share collapsing back
            # toward the gather path's idle-dominated split, or the
            # step wall ratio drifting) is machine-readable
            if (isinstance(serving, dict)
                    and isinstance(serving.get("paged_kernel"), dict)):
                spk = serving["paged_kernel"]
                row["serving_paged_kernel"] = {
                    arm: {
                        "step_wall_s": spk[arm]["step_wall_s"],
                        "compute_fraction": spk[arm]["compute_fraction"],
                        "comm_fraction": spk[arm]["comm_fraction"],
                        "idle_fraction": spk[arm]["idle_fraction"],
                    }
                    for arm in ("gather", "paged") if arm in spk
                } | {"summary": spk.get("summary")}
            # fleet goodput (ISSUE 19): availability fraction +
            # incident count per trajectory row — PerfSentinel can
            # watch goodput the same way it watches tokens/s
            if (isinstance(serving, dict)
                    and isinstance(serving.get("control_plane"), dict)
                    and serving["control_plane"].get("goodput")):
                sgp = serving["control_plane"]["goodput"]
                row["serving_goodput"] = {
                    "goodput_fraction": sgp["goodput_fraction"],
                    "incidents": sgp["incidents"],
                    "conservation_ok": sgp["conservation_ok"],
                }
            # baseline = same-device healthy rows only: a CPU-fallback
            # run judged against a TPU trajectory (or vice versa) would
            # stamp a bogus regression into the history forever
            sentinel = PerfSentinel.from_history(
                history_path, device=device_kind, window=8
            )
            verdict = sentinel.observe(row)
            if verdict is not None:
                reason = getattr(verdict, "reason",
                                 None) or verdict.get("reason")
                row["perf_regression"] = reason
                sys.stderr.write(f"bench perf sentinel: REGRESSION vs "
                                 f"history tail — {reason}\n")
            with open(history_path, "a") as hf:
                hf.write(json.dumps(row) + "\n")
            if tel is not None:
                reg.event("bench.history", path=history_path,
                          regression=row.get("perf_regression"))
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(f"bench history failed (non-fatal): {e}\n")
    if tel is not None:
        reg.event("bench.serving", **{
            k: v for k, v in serving.items() if not isinstance(v, dict)
        })
        tel.export_snapshot(reg)
        tel.close()
    if trace is not None:
        trace.write()
        trace.close()
    if os.environ.get("BENCH_CHILD"):
        emit(results, serving)  # final cumulative line carries serving
        ok_any = bool({k: v for k, v in results.items() if "error" not in v})
    else:
        ok_any = emit(results, serving)
    if not ok_any:
        raise RuntimeError(f"all bench variants failed: {results}")


def main() -> None:
    if os.environ.get("BENCH_CHILD"):
        run_bench(force_cpu=False)
        return
    if not os.environ.get("BENCH_FORCE_CPU"):
        for attempt in range(PROBE_ATTEMPTS):
            line, ready = _run_bench_child()
            if line is not None:
                print(line)
                return
            if ready:
                # backend attached but every variant failed — a structural
                # failure a fresh attach won't fix; fall back immediately
                break
            if attempt + 1 < PROBE_ATTEMPTS:
                sys.stderr.write(
                    f"bench: backend never attached (attempt {attempt + 1}/"
                    f"{PROBE_ATTEMPTS}) — tunnel likely held by another "
                    f"client; retrying in {PROBE_BACKOFF_S}s\n"
                )
                time.sleep(PROBE_BACKOFF_S)
    run_bench(force_cpu=True)


if __name__ == "__main__":
    main()
