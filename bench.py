"""Throughput benchmark: BLOOM-560m train step on the available device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no throughput numbers (BASELINE.md) — its
acceptance bar is convergence only. ``vs_baseline`` therefore reports
achieved MFU / 0.40, the north-star MFU threshold from BASELINE.json.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

# per-chip peak bf16 FLOP/s
PEAK_FLOPS = {
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "cpu": 1e12,  # nominal, CPU fallback only
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return 1e12


def main() -> None:
    from pipegoose_tpu.models import bloom

    dev = jax.devices()[0]
    on_tpu = "tpu" in getattr(dev, "platform", "").lower() or "lite" in getattr(
        dev, "device_kind", ""
    ).lower()

    if on_tpu:
        cfg = bloom.BloomConfig.bloom_560m(dtype=jnp.bfloat16, remat=True)
        batch, seq, steps = 8, 1024, 10
    else:  # CPU smoke fallback
        cfg = bloom.BloomConfig(
            vocab_size=1024, hidden_size=256, n_layer=4, n_head=8, dtype=jnp.float32
        )
        batch, seq, steps = 2, 128, 3

    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.adam(1e-4)
    opt_state = opt.init(params)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, cfg.vocab_size, (batch, seq)))

    @jax.jit
    def step(params, opt_state, ids):
        loss, grads = jax.value_and_grad(bloom.loss_fn)(params, ids, None, ids, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # warmup/compile
    params, opt_state, loss = step(params, opt_state, ids)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, ids)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * steps / dt

    # model FLOPs per token: 6*N for dense matmuls + 12*L*H*seq attention
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.hidden_size * seq
    mfu = tokens_per_sec * flops_per_token / _peak_flops(dev)

    print(
        json.dumps(
            {
                "metric": "bloom-560m train tokens/sec/chip"
                if on_tpu
                else "bloom-tiny train tokens/sec (cpu smoke)",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(mfu / 0.40, 4),
                "mfu": round(mfu, 4),
                "device": getattr(dev, "device_kind", str(dev)),
                "loss": float(loss),
            }
        )
    )


if __name__ == "__main__":
    main()
