// Native host-side token data loader.
//
// The TPU compute path is JAX/XLA; the runtime AROUND it is native where
// it matters. Feeding a pod slice is a host-side job — tokenized corpora
// are flat binary token files, and the loader must assemble (batch, seq)
// windows fast enough to stay ahead of the accelerator. The reference
// delegates this to torch's DataLoader + DistributedSampler
// (examples/hybrid_parallelism.py); this is the standalone equivalent:
//
// - mmap the token file (zero-copy reads, OS page cache does the IO);
// - a background thread assembles batches into a ring of pinned buffers
//   (double-buffering: the next batch is ready before the host asks);
// - deterministic sharded sampling: rank r of R takes window i where
//   hash(seed, epoch, i) % R == r is NOT used — instead windows are
//   strided (i*R + r), the same disjoint-coverage guarantee as
//   torch's DistributedSampler, cheap and exactly reproducible.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <random>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Loader {
  // mmap'd token file
  const uint32_t* tokens = nullptr;
  size_t n_tokens = 0;
  int fd = -1;
  size_t map_bytes = 0;

  // batch geometry + sharding
  size_t batch = 0, seq = 0;
  size_t rank = 0, world = 0;
  uint64_t seed = 0;
  std::atomic<uint64_t> epoch{0};

  // ring of prefetched batches
  static constexpr size_t RING = 4;
  std::vector<std::vector<uint32_t>> ring;
  std::atomic<uint64_t> produced{0}, consumed{0};
  std::mutex mu;
  std::condition_variable cv_prod, cv_cons;
  std::thread worker;
  std::atomic<bool> stop{false};

  size_t windows_per_epoch() const {
    size_t w = n_tokens / seq;            // non-overlapping seq windows
    return (w / world) / batch * batch;   // full batches per rank
  }

  void fill(uint64_t step, uint32_t* out) {
    // deterministic shuffle of window order per epoch
    const size_t per_rank = windows_per_epoch();
    const uint64_t ep = epoch.load();
    std::mt19937_64 rng(seed ^ (ep * 0x9e3779b97f4a7c15ULL));
    // sample `batch` window indices for this step without materializing
    // a permutation: splitmix-style hash of (step, slot)
    for (size_t b = 0; b < batch; ++b) {
      uint64_t h = (step * batch + b) * 0xbf58476d1ce4e5b9ULL + rng();
      h ^= h >> 31;
      size_t widx = (h % per_rank);                 // window for this rank
      size_t global_window = widx * world + rank;   // strided disjoint shard
      const uint32_t* src = tokens + global_window * seq;
      std::memcpy(out + b * seq, src, seq * sizeof(uint32_t));
    }
  }

  void run() {
    uint64_t step = 0;
    while (!stop.load()) {
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_prod.wait(lk, [&] {
          return stop.load() || produced.load() - consumed.load() < RING;
        });
      }
      if (stop.load()) break;
      fill(step, ring[produced.load() % RING].data());
      ++step;
      produced.fetch_add(1);
      cv_cons.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void* pgt_loader_open(const char* path, uint64_t batch, uint64_t seq,
                      uint64_t rank, uint64_t world, uint64_t seed) {
  auto* L = new Loader();
  L->fd = ::open(path, O_RDONLY);
  if (L->fd < 0) { delete L; return nullptr; }
  struct stat st;
  if (fstat(L->fd, &st) != 0) { ::close(L->fd); delete L; return nullptr; }
  L->map_bytes = static_cast<size_t>(st.st_size);
  void* p = mmap(nullptr, L->map_bytes, PROT_READ, MAP_PRIVATE, L->fd, 0);
  if (p == MAP_FAILED) { ::close(L->fd); delete L; return nullptr; }
  madvise(p, L->map_bytes, MADV_SEQUENTIAL);
  L->tokens = static_cast<const uint32_t*>(p);
  L->n_tokens = L->map_bytes / sizeof(uint32_t);
  L->batch = batch; L->seq = seq; L->rank = rank; L->world = world;
  L->seed = seed;
  if (L->windows_per_epoch() == 0) {
    munmap(p, L->map_bytes); ::close(L->fd); delete L; return nullptr;
  }
  L->ring.assign(Loader::RING, std::vector<uint32_t>(batch * seq));
  L->worker = std::thread([L] { L->run(); });
  return L;
}

uint64_t pgt_loader_windows(void* h) {
  return static_cast<Loader*>(h)->windows_per_epoch();
}

// blocks until the next prefetched batch is ready, copies it to `out`
// (batch*seq uint32)
void pgt_loader_next(void* h, uint32_t* out) {
  auto* L = static_cast<Loader*>(h);
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_cons.wait(lk, [&] { return L->produced.load() > L->consumed.load(); });
  }
  const auto& buf = L->ring[L->consumed.load() % Loader::RING];
  std::memcpy(out, buf.data(), buf.size() * sizeof(uint32_t));
  L->consumed.fetch_add(1);
  L->cv_prod.notify_one();
}

void pgt_loader_set_epoch(void* h, uint64_t epoch) {
  static_cast<Loader*>(h)->epoch.store(epoch);
}

void pgt_loader_close(void* h) {
  auto* L = static_cast<Loader*>(h);
  L->stop.store(true);
  L->cv_prod.notify_all();
  L->cv_cons.notify_all();
  if (L->worker.joinable()) L->worker.join();
  if (L->tokens) munmap(const_cast<uint32_t*>(L->tokens), L->map_bytes);
  if (L->fd >= 0) ::close(L->fd);
  delete L;
}

}  // extern "C"
