// Native host-side token data loader.
//
// The TPU compute path is JAX/XLA; the runtime AROUND it is native where
// it matters. Feeding a pod slice is a host-side job — tokenized corpora
// are flat binary token files, and the loader must assemble (batch, seq)
// windows fast enough to stay ahead of the accelerator. The reference
// delegates this to torch's DataLoader + DistributedSampler
// (examples/hybrid_parallelism.py); this is the standalone equivalent:
//
// - mmap the token file (zero-copy reads, OS page cache does the IO);
// - a background thread assembles batches into a ring of buffers
//   (double-buffering: the next batch is ready before the host asks);
// - sampling is a STATELESS PERMUTATION: window order per epoch is an
//   affine bijection (odd multiplier mod 2^k, cycle-walked onto
//   [0, per_rank)) keyed by splitmix64(seed, epoch) — every window
//   visited exactly once per epoch (DistributedSampler semantics), and
//   the arithmetic is integer-exact so the Python fallback
//   (pipegoose_tpu/data/dataloader.py) reproduces identical batches;
// - ranks shard windows disjointly by striding (global = local*W + r).
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

inline uint64_t pow2mask(uint64_t n) {
  uint64_t m = 1;
  while (m < n) m <<= 1;
  return m - 1;
}

// bijection on [0, n): affine map mod 2^k (odd multiplier => bijective),
// cycle-walked back into range. Identical in the Python fallback.
inline uint64_t permute(uint64_t idx, uint64_t n, uint64_t key) {
  const uint64_t mask = pow2mask(n);
  const uint64_t a = splitmix64(key) | 1ULL;
  const uint64_t b = splitmix64(key ^ 0xda3e39cb94b95bdbULL);
  uint64_t x = idx;
  do {
    x = (a * x + b) & mask;
  } while (x >= n);
  return x;
}

struct Loader {
  const uint32_t* tokens = nullptr;
  size_t n_tokens = 0;
  int fd = -1;
  size_t map_bytes = 0;

  size_t batch = 0, seq = 0;
  size_t rank = 0, world = 0;
  uint64_t seed = 0;
  uint64_t epoch = 0;

  static constexpr size_t RING = 4;
  std::vector<std::vector<uint32_t>> ring;
  std::atomic<uint64_t> produced{0}, consumed{0};
  uint64_t step = 0;  // worker-local, reset by set_epoch
  std::mutex mu;
  std::condition_variable cv_prod, cv_cons;
  std::thread worker;
  std::atomic<bool> stop{false};

  size_t windows_per_epoch() const {
    size_t w = n_tokens / seq;            // non-overlapping seq windows
    return (w / world) / batch * batch;   // full batches per rank
  }

  void fill(uint64_t step, uint32_t* out) {
    const uint64_t per_rank = windows_per_epoch();
    const uint64_t key = splitmix64(seed) ^ splitmix64(epoch + 1);
    for (size_t b = 0; b < batch; ++b) {
      uint64_t linear = (step * batch + b) % per_rank;
      uint64_t widx = permute(linear, per_rank, key);
      size_t global_window = widx * world + rank;  // strided disjoint shard
      const uint32_t* src = tokens + global_window * seq;
      std::memcpy(out + b * seq, src, seq * sizeof(uint32_t));
    }
  }

  void run() {
    while (!stop.load()) {
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_prod.wait(lk, [&] {
          return stop.load() || produced.load() - consumed.load() < RING;
        });
      }
      if (stop.load()) break;
      fill(step, ring[produced.load() % RING].data());
      ++step;
      produced.fetch_add(1);
      cv_cons.notify_one();
    }
  }

  void start_worker() {
    stop.store(false);
    worker = std::thread([this] { run(); });
  }

  void stop_worker() {
    stop.store(true);
    cv_prod.notify_all();
    cv_cons.notify_all();
    if (worker.joinable()) worker.join();
  }
};

}  // namespace

extern "C" {

void* pgt_loader_open(const char* path, uint64_t batch, uint64_t seq,
                      uint64_t rank, uint64_t world, uint64_t seed) {
  auto* L = new Loader();
  L->fd = ::open(path, O_RDONLY);
  if (L->fd < 0) { delete L; return nullptr; }
  struct stat st;
  if (fstat(L->fd, &st) != 0) { ::close(L->fd); delete L; return nullptr; }
  L->map_bytes = static_cast<size_t>(st.st_size);
  void* p = mmap(nullptr, L->map_bytes, PROT_READ, MAP_PRIVATE, L->fd, 0);
  if (p == MAP_FAILED) { ::close(L->fd); delete L; return nullptr; }
  madvise(p, L->map_bytes, MADV_WILLNEED);
  L->tokens = static_cast<const uint32_t*>(p);
  L->n_tokens = L->map_bytes / sizeof(uint32_t);
  L->batch = batch; L->seq = seq; L->rank = rank; L->world = world;
  L->seed = seed;
  if (L->windows_per_epoch() == 0) {
    munmap(p, L->map_bytes); ::close(L->fd); delete L; return nullptr;
  }
  L->ring.assign(Loader::RING, std::vector<uint32_t>(batch * seq));
  L->start_worker();
  return L;
}

uint64_t pgt_loader_windows(void* h) {
  return static_cast<Loader*>(h)->windows_per_epoch();
}

// blocks until the next prefetched batch is ready, copies it to `out`
// (batch*seq uint32)
void pgt_loader_next(void* h, uint32_t* out) {
  auto* L = static_cast<Loader*>(h);
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_cons.wait(lk, [&] { return L->produced.load() > L->consumed.load(); });
  }
  const auto& buf = L->ring[L->consumed.load() % Loader::RING];
  std::memcpy(out, buf.data(), buf.size() * sizeof(uint32_t));
  L->consumed.fetch_add(1);
  L->cv_prod.notify_one();
}

// quiesces the worker and DISCARDS any prefetched old-epoch batches —
// the next pgt_loader_next returns epoch `epoch`, step 0.
void pgt_loader_set_epoch(void* h, uint64_t epoch) {
  auto* L = static_cast<Loader*>(h);
  L->stop_worker();
  L->epoch = epoch;
  L->step = 0;
  L->produced.store(0);
  L->consumed.store(0);
  L->start_worker();
}

void pgt_loader_close(void* h) {
  auto* L = static_cast<Loader*>(h);
  L->stop_worker();
  if (L->tokens) munmap(const_cast<uint32_t*>(L->tokens), L->map_bytes);
  if (L->fd >= 0) ::close(L->fd);
  delete L;
}

}  // extern "C"
