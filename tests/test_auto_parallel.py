"""GSPMD auto path vs manual shard_map path: identical training
trajectories for TP x DP BLOOM (the pjit story of BASELINE.json)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pipegoose_tpu.distributed import ParallelContext
from pipegoose_tpu.models import bloom
from pipegoose_tpu.parallel import make_auto_train_step


def test_auto_matches_single_device(devices):
    cfg = bloom.BloomConfig(vocab_size=128, hidden_size=64, n_layer=2, n_head=4)
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 128, (8, 12)))

    # single-device reference
    opt = optax.adam(1e-3)
    st = opt.init(params)
    p_ref = params
    ref_losses = []

    @jax.jit
    def ref_step(p, s, ids):
        loss, grads = jax.value_and_grad(bloom.loss_fn)(p, ids, None, ids, cfg)
        u, s2 = opt.update(grads, s, p)
        return optax.apply_updates(p, u), s2, loss

    for _ in range(3):
        p_ref, st, loss = ref_step(p_ref, st, ids)
        ref_losses.append(float(loss))

    ctx = ParallelContext(tensor_parallel_size=2, data_parallel_size=4)
    try:
        init_fn, step = make_auto_train_step(
            lambda p, b: bloom.loss_fn(p, b, None, b, cfg),  # single-device code
            bloom.tp_specs(params),
            optax.adam(1e-3),
            ctx,
        )
        p, s = init_fn(params)
        # params really are sharded over tensor
        qkv = p["blocks"]["attn"]["qkv"]["kernel"]
        assert qkv.sharding.shard_shape(qkv.shape)[-1] == qkv.shape[-1] // 2
        losses = []
        for _ in range(3):
            p, s, loss = step(p, s, ids)
            losses.append(float(loss))
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-3, atol=2e-4)
        for (path, r), t in zip(
            jax.tree_util.tree_leaves_with_path(p_ref), jax.tree_util.tree_leaves(p)
        ):
            np.testing.assert_allclose(
                np.asarray(t), np.asarray(r), rtol=5e-3, atol=5e-4, err_msg=str(path)
            )
    finally:
        ctx.destroy()
