"""Perfetto/Chrome trace export (telemetry/chrometrace.py): trace_event
schema validity (the "loads in Perfetto" contract is: one JSON object
with µs complete events, int pids/tids, metadata names), the pipeline
clock timeline rows, and the bubble-fraction gauge."""
import json
import os
import threading

import pytest

from pipegoose_tpu.nn.pipeline_parallel.scheduler import (
    GPipeScheduler,
    OneFOneBScheduler,
)
from pipegoose_tpu.telemetry import MetricsRegistry
from pipegoose_tpu.telemetry.chrometrace import (
    ChromeTraceExporter,
    pipeline_trace_events,
    register_pipeline_gauges,
    span_events_to_trace,
    trace_from_jsonl,
)
from pipegoose_tpu.telemetry.spans import span


def _assert_valid_trace(payload):
    assert set(payload) >= {"traceEvents", "displayTimeUnit"}
    for ev in payload["traceEvents"]:
        assert isinstance(ev["name"], str)
        assert ev["ph"] in ("X", "M", "i")
        assert isinstance(ev["pid"], int)
        if ev["ph"] != "M" or "tid" in ev:
            assert isinstance(ev.get("tid", 0), int)
        if ev["ph"] == "X":
            assert ev["dur"] >= 0 and ev["ts"] >= 0
    json.dumps(payload)  # fully serializable


def test_span_events_to_trace_microsecond_math():
    events = [
        {"kind": "span", "span": "train.step", "ts": 10.0, "dur_s": 0.5},
        {"kind": "train.fit_start", "ts": 9.0},
        {"no_kind": True},  # ignored
    ]
    out = span_events_to_trace(events)
    assert len(out) == 2
    slice_, instant = out
    assert slice_["name"] == "train.step" and slice_["ph"] == "X"
    assert slice_["ts"] == pytest.approx(9.5e6)   # start = end - dur
    assert slice_["dur"] == pytest.approx(0.5e6)
    assert instant["ph"] == "i" and instant["name"] == "train.fit_start"


def test_pipeline_trace_events_rows_match_schedule():
    M, P = 4, 2
    sched = GPipeScheduler(M, P)
    events = pipeline_trace_events(sched, clock_s=1e-3)
    meta = [e for e in events if e["ph"] == "M"]
    slices = [e for e in events if e["ph"] == "X"]
    # one thread_name per stage + the process_name
    assert {m["args"]["name"] for m in meta} == {
        "pipeline (theoretical clock timeline)", "stage 0", "stage 1",
    }
    # every (microbatch, stage) task appears once per direction
    fwd = [e for e in slices if e["cat"] == "pipeline.forward"]
    bwd = [e for e in slices if e["cat"] == "pipeline.backward"]
    assert len(fwd) == M * P and len(bwd) == M * P
    # forward task (m, p) sits at clock m + p on stage p's row
    for e in fwd:
        m, p = e["args"]["microbatch"], e["args"]["stage"]
        assert e["tid"] == p
        assert e["args"]["clock"] == m + p
        assert e["ts"] == pytest.approx((m + p) * 1e-3 * 1e6)
    # backwards start after the forward clocks
    n_fwd = sched.total_forward_clocks
    assert min(e["args"]["clock"] for e in bwd) == n_fwd
    _assert_valid_trace({"traceEvents": events, "displayTimeUnit": "ms"})


def test_bubble_fraction_and_gauges():
    assert GPipeScheduler(4, 4).bubble_fraction == pytest.approx(3 / 7)
    assert GPipeScheduler(8, 1).bubble_fraction == 0.0
    assert GPipeScheduler(1, 4).bubble_fraction == pytest.approx(3 / 4)
    # the 1F1B reordering keeps the same bubble (it moves idle clocks)
    assert OneFOneBScheduler(4, 4).bubble_fraction == pytest.approx(3 / 7)

    reg = MetricsRegistry(enabled=True)
    frac = register_pipeline_gauges(
        GPipeScheduler(8, 4), registry=reg, step_seconds=0.2
    )
    assert frac == pytest.approx(3 / 11)
    assert reg.gauge("pipeline.bubble_fraction").value == pytest.approx(3 / 11)
    assert reg.gauge("pipeline.bubble_seconds").value == (
        pytest.approx(0.2 * 3 / 11)
    )
    assert reg.gauge("pipeline.n_microbatches").value == 8.0


def test_pipeline_trace_events_1f1b_interleaved_timetable():
    """A OneFOneBScheduler renders from its ACTUAL timetable: every
    (microbatch, stage) pair appears once per direction, at most one
    slice per (stage, clock), the span covers exactly n_clock clocks,
    and the steady state interleaves B between Fs (not the GPipe
    two-phase layout)."""
    M, P = 4, 2
    sched = OneFOneBScheduler(M, P)
    events = pipeline_trace_events(sched, clock_s=1e-3)
    slices = [e for e in events if e["ph"] == "X"]
    fwd = [e for e in slices if e["cat"] == "pipeline.forward"]
    bwd = [e for e in slices if e["cat"] == "pipeline.backward"]
    assert len(fwd) == M * P and len(bwd) == M * P
    seen = set()
    for e in slices:
        key = (e["tid"], e["args"]["clock"])
        assert key not in seen, f"two slices on one stage-clock: {key}"
        seen.add(key)
    assert max(e["args"]["clock"] for e in slices) == sched.n_clock - 1
    # steady state on the last stage: some BACKWARD lands BEFORE the
    # last forward clock — impossible in the GPipe two-phase rendering
    last_fwd_clock = max(e["args"]["clock"] for e in fwd)
    assert any(e["args"]["clock"] < last_fwd_clock for e in bwd)
    _assert_valid_trace({"traceEvents": events, "displayTimeUnit": "ms"})


def test_1f1b_bubble_fraction_from_timetable_and_gauges():
    """OneFOneBScheduler.bubble_fraction comes from its own timetable
    (1 - 2M/n_clock) and feeds register_pipeline_gauges like GPipe's."""
    s = OneFOneBScheduler(4, 2)
    assert s.bubble_fraction == pytest.approx(1.0 - 8.0 / s.n_clock)
    # flush bound achieved here: matches the GPipe closed form
    assert s.n_clock == 2 * (4 + 2 - 1)
    reg = MetricsRegistry(enabled=True)
    frac = register_pipeline_gauges(s, registry=reg, step_seconds=0.1)
    assert frac == pytest.approx(s.bubble_fraction)
    assert reg.gauge("pipeline.bubble_fraction").value == pytest.approx(frac)
    assert reg.gauge("pipeline.bubble_seconds").value == (
        pytest.approx(0.1 * frac)
    )


def test_exporter_collects_spans_and_writes_atomically(tmp_path):
    reg = MetricsRegistry(enabled=True)
    path = str(tmp_path / "trace.json")
    exp = ChromeTraceExporter(path, registry=reg)
    with span("train.step", registry=reg):
        with span("forward", registry=reg):
            pass
    reg.event("train.fit_end")
    exp.add_pipeline_timeline(GPipeScheduler(2, 2), clock_s=1e-3)
    out = exp.write()
    assert out == path
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]

    payload = json.load(open(path))
    _assert_valid_trace(payload)
    names = [e["name"] for e in payload["traceEvents"]]
    assert "train.step" in names
    assert "train.step.forward" in names          # nesting kept the path
    assert "train.fit_end" in names               # instant marker
    assert "F0" in names and "B1" in names        # pipeline rows
    # the nested span sits inside its parent's interval — with µs-scale
    # slack: a slice start is RECONSTRUCTED as exit-wall-clock minus a
    # perf_counter duration, and the two clocks are read a few µs apart
    # at each exit, so exact ordering at the boundary is not guaranteed
    slack_us = 1000.0
    by = {e["name"]: e for e in payload["traceEvents"] if e["ph"] == "X"}
    outer, inner = by["train.step"], by["train.step.forward"]
    assert outer["ts"] <= inner["ts"] + slack_us
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + slack_us
    exp.close()
    assert exp not in reg._sinks


def test_exporter_bounds_memory_keeping_newest(tmp_path):
    exp = ChromeTraceExporter(str(tmp_path / "t.json"), max_events=5)
    for i in range(12):
        exp({"kind": "span", "span": f"s{i}", "ts": float(i), "dur_s": 0.1})
    payload_path = exp.write()
    payload = json.load(open(payload_path))
    spans = [e for e in payload["traceEvents"] if e["ph"] == "X"]
    assert [e["name"] for e in spans] == ["s7", "s8", "s9", "s10", "s11"]
    assert payload["otherData"]["dropped_events"] == 7


def test_exporter_separates_threads(tmp_path):
    exp = ChromeTraceExporter(str(tmp_path / "t.json"))
    exp({"kind": "span", "span": "main", "ts": 1.0, "dur_s": 0.1})
    t = threading.Thread(
        target=exp, args=({"kind": "span", "span": "bg", "ts": 1.0,
                           "dur_s": 0.1},)
    )
    t.start()
    t.join()
    payload = json.load(open(exp.write()))
    by = {e["name"]: e for e in payload["traceEvents"] if e["ph"] == "X"}
    assert by["main"]["tid"] != by["bg"]["tid"]


def test_rank_filter_suppresses_write(tmp_path):
    exp = ChromeTraceExporter(str(tmp_path / "t.json"), rank=7)
    exp({"kind": "span", "span": "s", "ts": 1.0, "dur_s": 0.1})
    assert exp.write() is None
    assert not os.path.exists(tmp_path / "t.json")


def test_trace_from_jsonl_offline_conversion(tmp_path):
    jsonl = tmp_path / "run.jsonl"
    lines = [
        {"ts": 1.0, "kind": "span", "span": "serving.decode_step",
         "dur_s": 0.01},
        {"ts": 2.0, "kind": "snapshot", "counters": {}},  # skipped
        {"ts": 3.0, "kind": "serving.step", "step": 1},
    ]
    with open(jsonl, "w") as f:
        for l in lines:
            f.write(json.dumps(l) + "\n")
        f.write('{"truncated": \n')  # killed-run tail must not block
    out = trace_from_jsonl(str(jsonl), str(tmp_path / "trace.json"))
    payload = json.load(open(out))
    _assert_valid_trace(payload)
    names = [e["name"] for e in payload["traceEvents"]]
    assert "serving.decode_step" in names
    assert "serving.step" in names
    assert "snapshot" not in names
