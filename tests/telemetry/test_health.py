"""In-graph health stats (telemetry/health.py) + the
``make_hybrid_train_step(with_health=...)`` contract: sharded stats
match a single-device reference, nonfinite injection is localized to
the offending module group, and the OFF path lowers to a program with
no health ops in it (the zero-cost guard)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from pipegoose_tpu.distributed import ParallelContext
from pipegoose_tpu.models import bloom
from pipegoose_tpu.optim.zero import DistributedOptimizer
from pipegoose_tpu.parallel import make_hybrid_train_step
from pipegoose_tpu.telemetry.health import health_stats, host_health


# -- pure arithmetic (no mesh) ---------------------------------------------


def test_health_stats_math_single_device():
    params = {
        "embed": {"w": jnp.asarray([[3.0, 4.0]])},       # norm 5
        "head": {"b": jnp.asarray([0.0, 0.0])},
    }
    grads = {
        "embed": {"w": jnp.asarray([[0.6, 0.8]])},       # norm 1
        "head": {"b": jnp.asarray([2.0, 0.0])},          # norm 2
    }
    new_params = {
        "embed": {"w": jnp.asarray([[3.0, 4.0]])},       # update 0
        "head": {"b": jnp.asarray([0.5, 0.0])},          # update (.5, 0)
    }
    specs = jax.tree_util.tree_map(lambda _: P(), params)
    h = host_health(health_stats(grads, params, new_params, specs))
    assert h["grad_norm"] == pytest.approx(np.sqrt(5.0))
    assert h["grad_norm_per_module"]["embed"] == pytest.approx(1.0)
    assert h["grad_norm_per_module"]["head"] == pytest.approx(2.0)
    assert h["param_norm"] == pytest.approx(5.0)
    assert h["update_norm"] == pytest.approx(0.5)
    assert h["update_max_abs"] == pytest.approx(0.5)
    assert h["update_ratio"] == pytest.approx(0.1, rel=1e-5)
    assert h["nonfinite_grad_leaves"] == 0.0
    assert h["nonfinite_update_leaves"] == 0.0


def test_health_stats_counts_nonfinite_leaves():
    params = {"a": jnp.ones(3), "b": jnp.ones(3)}
    specs = {"a": P(), "b": P()}
    grads = {"a": jnp.asarray([1.0, jnp.nan, 1.0]), "b": jnp.ones(3)}
    new_params = {"a": jnp.ones(3), "b": jnp.asarray([jnp.inf, 1.0, 1.0])}
    h = host_health(health_stats(grads, params, new_params, specs))
    assert h["nonfinite_grad_leaves"] == 1.0
    assert h["nonfinite_update_leaves"] == 1.0
    assert np.isnan(h["grad_norm"])                      # NaN propagates
    assert np.isnan(h["grad_norm_per_module"]["a"])
    assert h["grad_norm_per_module"]["b"] == pytest.approx(np.sqrt(3.0))


def test_health_stats_tree_mismatch_raises():
    params = {"a": jnp.ones(2)}
    with pytest.raises(ValueError, match="tree mismatch"):
        health_stats(
            {"a": jnp.ones(2), "b": jnp.ones(2)}, params, params,
            {"a": P()},
        )


# -- sharded step equivalence ----------------------------------------------


@pytest.fixture()
def parts(devices):
    cfg = bloom.BloomConfig(vocab_size=64, hidden_size=32, n_layer=2, n_head=2)
    params = bloom.init_params(cfg, jax.random.PRNGKey(0))
    ctx = ParallelContext(tensor_parallel_size=2, data_parallel_size=4)
    yield cfg, params, ctx
    ctx.destroy()


def _hybrid_health_step(cfg, params, ctx, loss_fn, **kwargs):
    init_fn, make_step = make_hybrid_train_step(
        loss_fn, bloom.tp_specs(params),
        DistributedOptimizer(optax.adam(1e-3), axis_name="data"), ctx,
        with_health=True, **kwargs,
    )
    return init_fn(params), make_step(params)


def test_sharded_health_matches_single_device_reference(parts):
    cfg, params, ctx = parts

    def loss_fn(p, ids):
        return bloom.loss_fn(p, ids, None, ids, cfg, tp_axis="tensor")

    p0 = jax.tree_util.tree_map(jnp.copy, params)
    opt_state, step = _hybrid_health_step(cfg, params, ctx, loss_fn)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 8)))
    new_p, _, loss, health = step(params, opt_state, ids)
    h = host_health(health)

    # reference grad norm: plain single-device value_and_grad
    _, g = jax.value_and_grad(
        lambda p, i: bloom.loss_fn(p, i, None, i, cfg)
    )(p0, ids)
    ref_sq = sum(
        float(jnp.sum(jnp.square(x.astype(jnp.float32))))
        for x in jax.tree_util.tree_leaves(g)
    )
    assert h["grad_norm"] == pytest.approx(np.sqrt(ref_sq), rel=1e-4)
    # per-module norms recombine to the global norm
    assert sum(v ** 2 for v in h["grad_norm_per_module"].values()) == (
        pytest.approx(h["grad_norm"] ** 2, rel=1e-5)
    )
    assert set(h["grad_norm_per_module"]) == set(params.keys())

    # update stats against the actually-applied update
    upd = jax.tree_util.tree_map(
        lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32), new_p, p0
    )
    ref_u = np.sqrt(sum(
        float(jnp.sum(jnp.square(x))) for x in jax.tree_util.tree_leaves(upd)
    ))
    ref_umx = max(
        float(jnp.max(jnp.abs(x))) for x in jax.tree_util.tree_leaves(upd)
    )
    assert h["update_norm"] == pytest.approx(ref_u, rel=1e-4)
    assert h["update_max_abs"] == pytest.approx(ref_umx, rel=1e-4)
    assert 0 < h["update_ratio"] < 1
    assert h["nonfinite_grad_leaves"] == 0.0
    assert np.isfinite(float(loss))


def test_injected_overflow_localizes_to_module_group(parts):
    """A gradient bomb on the embedding shows up as nonfinite leaves and
    a nonfinite 'embed' per-module norm while other groups stay finite —
    the signal the flight-recorder dump names."""
    cfg, params, ctx = parts

    def loss_fn(p, ids):
        base = bloom.loss_fn(p, ids, None, ids, cfg, tp_axis="tensor")
        bomb = jnp.where(ids[0, 0] == 0, jnp.float32(jnp.inf), 0.0)
        return base + bomb * jnp.sum(
            jnp.square(p["embed"]["weight"].astype(jnp.float32))
        )

    opt_state, step = _hybrid_health_step(cfg, params, ctx, loss_fn)
    ids = np.random.RandomState(0).randint(1, 64, (8, 8))
    ids[0, 0] = 0  # arm the bomb
    _, _, loss, health = step(params, opt_state, jnp.asarray(ids))
    h = host_health(health)
    assert h["nonfinite_grad_leaves"] > 0
    assert not np.isfinite(h["grad_norm_per_module"]["embed"])
    assert np.isfinite(h["grad_norm_per_module"]["blocks"])
    assert np.isfinite(h["grad_norm_per_module"]["ln_f"])
    assert not np.isfinite(float(loss))


# -- the zero-cost OFF guard -----------------------------------------------


def test_health_off_lowers_to_the_unchanged_program(parts):
    """with_health=False must cost NOTHING: same output arity as the
    pre-feature step and a lowered program containing none of the
    health reductions (``is-finite`` ops), so the off path cannot
    regress step time. The ON program carries them and one extra
    (replicated-scalars) output tree."""
    cfg, params, ctx = parts

    def loss_fn(p, ids):
        return bloom.loss_fn(p, ids, None, ids, cfg, tp_axis="tensor")

    specs = bloom.tp_specs(params)
    opt = DistributedOptimizer(optax.adam(1e-3), axis_name="data")
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (8, 8)))

    lowered, arity = {}, {}
    for flag in (False, True):
        init_fn, make_step = make_hybrid_train_step(
            loss_fn, specs, opt, ctx, with_health=flag
        )
        opt_state = jax.eval_shape(init_fn, params)
        step = make_step(params)
        lowered[flag] = step.lower(params, opt_state, ids).as_text()
        arity[flag] = len(jax.eval_shape(step, params, opt_state, ids))

    off, on = lowered[False], lowered[True]
    assert "is_finite" not in off and "is-finite" not in off
    assert "is_finite" in on or "is-finite" in on
    # off output arity: (params, opt_state, loss) and nothing else
    assert arity[False] == 3 and arity[True] == 4
