"""Goodput-ledger unit contract (telemetry/goodput.py, ISSUE 19): the
telescoping wall account conserves exactly by construction (per-replica
class-seconds == alive wall), episodes merge into state bands, lifecycle
gaps book by state, the classify priority tree orders the taxonomy,
incidents join seeded chaos injections by ring distance and close with
MTTR + SLO burn, transfer-flap bursts merge, the availability ratio SLO
reads the monotone counters, the Perfetto renderer emits one band track
per replica, the trainer mirror conserves fit wall and prices recovery
rewinds, and the autoscaler's audit log is bounded with a drop count."""
from collections import deque
from types import SimpleNamespace

import pytest

from pipegoose_tpu.telemetry.chrometrace import (
    PID_GOODPUT,
    goodput_trace_events,
)
from pipegoose_tpu.telemetry.goodput import (
    CLASSES,
    GOOD_CLASSES,
    GoodputLedger,
    TrainerGoodput,
    availability_slo_target,
)
from pipegoose_tpu.telemetry.registry import MetricsRegistry
from pipegoose_tpu.telemetry.slo import SLOMonitor


def _rep(state="serving", probation=0, programs_run=0, deferrals=0):
    eng = SimpleNamespace(
        programs_run=programs_run,
        sched=SimpleNamespace(admission_deferrals=deferrals),
        kv_tier=None,
    )
    return SimpleNamespace(state=SimpleNamespace(value=state),
                           engine=eng, probation_ticks_left=probation)


# --- wall attribution: conservation, episodes, lifecycle gaps --------------


def test_telescoping_conservation_is_exact():
    led = GoodputLedger()
    led.touch("r0", 10.0, "serving", 0)
    t = 10.0
    for tick, klass in enumerate(
            ["compile_warmup", "productive", "productive", "idle",
             "stall", "idle"], start=1):
        t += 0.125  # binary fractions: even float addition is exact
        led.account("r0", t, klass, "serving", tick)
    cons = led.conservation()
    assert cons["ok"] and cons["max_error_s"] == 0.0
    acct = led.replicas["r0"]
    assert acct.alive_wall_s == pytest.approx(0.75)
    assert sum(acct.classes.values()) == acct.alive_wall_s
    tot = led.totals()
    assert tot["productive_seconds"] == pytest.approx(0.25)
    assert tot["badput_seconds"] == pytest.approx(0.5)
    assert tot["fraction"] == pytest.approx(1 / 3)


def test_episodes_merge_consecutive_same_class_and_state():
    led = GoodputLedger()
    led.touch("r0", 0.0, "serving", 0)
    for tick, (klass, state) in enumerate(
            [("productive", "serving"), ("productive", "serving"),
             ("stall", "serving"), ("stall", "suspect"),
             ("stall", "suspect")], start=1):
        led.account("r0", float(tick), klass, state, tick)
    eps = led.replicas["r0"].episodes
    # 2 productive ticks merge, stall splits on the state flip
    assert [(e["class"], e["state"], e["ticks"]) for e in eps] == [
        ("productive", "serving", 2),
        ("stall", "serving", 1),
        ("stall", "suspect", 2),
    ]
    assert eps[0]["t0"] == 0.0 and eps[0]["t1"] == 2.0
    # state dwell follows the state, not the class
    assert led.state_seconds("r0") == {"serving": 3.0, "suspect": 2.0}


def test_touch_books_lifecycle_gap_by_state():
    led = GoodputLedger()
    led.touch("r0", 0.0, "serving", 0)
    led.account("r0", 1.0, "productive", "serving", 1)
    # between-runs gap while FAILED: the gap is quarantine wall, and
    # conservation still telescopes to the new mark
    led.touch("r0", 3.0, "failed", 5)
    acct = led.replicas["r0"]
    assert acct.classes["failed_quarantine"] == pytest.approx(2.0)
    assert led.conservation()["ok"]
    # a touch that does not advance the clock books nothing
    led.touch("r0", 2.5, "failed", 6)
    assert acct.last_mark == 3.0


def test_classify_priority_tree():
    led = GoodputLedger()
    pre = (0, 0, 0)
    # terminal states outrank everything
    assert led.classify(_rep("failed"), pre, True, True, True) \
        == "failed_quarantine"
    assert led.classify(_rep("draining"), pre, True, True, False) \
        == "draining"
    assert led.classify(_rep("stopped"), pre, False, False, False) \
        == "draining"
    # progress: first-compile detection rides the programs_run delta
    assert led.classify(_rep(programs_run=1), pre, True, True, False) \
        == "compile_warmup"
    assert led.classify(_rep(), pre, True, True, False) == "productive"
    assert led.classify(_rep(), pre, True, False, True) == "productive"
    # no progress with work: suspect > admission_blocked > stall
    assert led.classify(_rep("suspect"), pre, True, False, False) \
        == "suspect_probing"
    assert led.classify(_rep(deferrals=2), pre, True, False, False) \
        == "admission_blocked"
    assert led.classify(_rep(), pre, True, False, False) == "stall"
    # no work: probation > suspect-idle > idle
    assert led.classify(_rep(probation=3), pre, False, False, False) \
        == "probation"
    assert led.classify(_rep("suspect"), pre, False, False, False) \
        == "suspect_probing"
    assert led.classify(_rep(), pre, False, False, False) == "idle"
    for klass in ("productive", "compile_warmup", "idle", "probation",
                  "admission_blocked", "stall", "suspect_probing",
                  "failed_quarantine", "draining"):
        assert klass in CLASSES
    assert GOOD_CLASSES == ("productive",)


# --- incidents: lifecycle, injection joins, flap merge, bounds -------------


def _ring(*records):
    return SimpleNamespace(records=deque(records))


def test_incident_mttr_gap_integral_and_slo_burn():
    led = GoodputLedger()
    led.touch("r0", 0.0, "serving", 0)
    led.touch("r1", 0.0, "serving", 0)
    led.account("r0", 1.0, "productive", "serving", 1)
    led.account("r1", 1.0, "productive", "serving", 1)
    led.on_tick(1, 1.0)
    inc = led.open_incident("crash", "r1", 2, 2.0, reason="boom",
                            capacity_gap=1)
    assert inc.open and led.open_incidents == [inc]
    # gap integral accrues tick wall while open — 2 ticks of 1s each
    led.account("r0", 2.0, "productive", "serving", 2)
    led.account("r1", 2.0, "failed_quarantine", "failed", 2)
    led.on_tick(2, 2.0)
    led.account("r0", 3.0, "productive", "serving", 3)
    led.account("r1", 3.0, "failed_quarantine", "failed", 3)
    led.on_tick(3, 3.0)
    assert inc.capacity_gap_integral_s == pytest.approx(2.0)
    closed = led.resolve_incident("r1", 12, 4.5, "rejoin")
    assert closed is inc and not inc.open
    assert inc.mttr_s == pytest.approx(2.5)
    assert inc.mttr_ticks == 10
    assert led.open_incidents == []
    # SLO burn over the window: r1's 2 quarantine seconds were the
    # only badput booked between open and close
    assert inc.slo_burn["badput_s"] == pytest.approx(2.0)
    assert inc.slo_burn["wall_s"] == pytest.approx(4.0)
    assert inc.slo_burn["availability"] == pytest.approx(0.5)
    d = inc.as_dict()
    assert d["resolved_by"] == "rejoin" and d["reason"] == "boom"


def test_injection_join_latency_is_ring_distance_and_claims_once():
    rec = _ring(
        {"ts": 0.0, "kind": "chaos.injection", "injection":
         "replica_crash", "step": 4, "victim": "r1"},
        {"ts": 0.0, "kind": "chaos.injection", "injection":
         "replica_wedge", "step": 6, "victim": "r0"},
        {"ts": 0.0, "kind": "other_noise"},
    )
    led = GoodputLedger()
    a = led.open_incident("crash", "r1", 7, 7.0, recorder=rec,
                          injection_kinds=("replica_crash",
                                           "replica_wedge"))
    assert a.detection_latency_ticks == 3 and a.injection_step == 4
    # victim filter: r0's wedge record, not r1's already-claimed crash
    b = led.open_incident("wedge", "r0", 9, 9.0, recorder=rec,
                          injection_kinds=("replica_crash",
                                           "replica_wedge"))
    assert b.detection_latency_ticks == 3 and b.injection_step == 6
    # ring exhausted: organic failure, no join
    c = led.open_incident("crash", "r1", 11, 11.0, recorder=rec,
                          injection_kinds=("replica_crash",
                                           "replica_wedge"))
    assert c.detection_latency_ticks is None and c.injection_step is None


def test_injection_join_matches_victimless_records():
    # transfer_flap injections carry no victim field — any replica's
    # flap may claim them
    rec = _ring({"ts": 0.0, "kind": "chaos.injection",
                 "injection": "transfer_flap", "step": 2})
    led = GoodputLedger()
    inc = led.note_transfer_flap("r0", 5, 5.0, 3, recorder=rec)
    assert inc.detection_latency_ticks == 3
    assert not inc.open and inc.resolved_by == "fallback"
    assert inc.mttr_s == 0.0 and inc.events == 3


def test_transfer_flap_bursts_merge_into_one_incident():
    led = GoodputLedger()
    first = led.note_transfer_flap("r0", 5, 5.0, 2)
    assert first is not None
    # consecutive ticks extend the SAME incident
    assert led.note_transfer_flap("r0", 6, 6.0, 1) is None
    assert led.note_transfer_flap("r0", 7, 7.0, 1) is None
    assert first.events == 4
    # a gap starts a new episode; another replica is independent
    second = led.note_transfer_flap("r0", 10, 10.0, 1)
    assert second is not None and second is not first
    assert led.note_transfer_flap("r1", 10, 10.0, 1) is not None
    assert len(led.incidents) == 3


def test_incident_log_bounded_with_drop_counter():
    led = GoodputLedger(max_incidents=3)
    for i in range(5):
        inc = led.open_incident("crash", f"r{i}", i, float(i))
        led.resolve_incident(f"r{i}", i, float(i), "rejoin")
        assert inc is not None
    assert len(led.incidents) == 3
    assert led.incidents_dropped == 2
    assert [i.replica for i in led.incidents] == ["r2", "r3", "r4"]


def test_resolve_without_replica_closes_oldest_open():
    led = GoodputLedger()
    a = led.open_incident("crash", "r0", 1, 1.0)
    b = led.open_incident("crash", "r1", 2, 2.0)
    closed = led.resolve_incident(None, 3, 3.0, "scale_up")
    assert closed is a and b.open
    assert led.resolve_incident(None, 4, 4.0, "scale_up") is b
    assert led.resolve_incident(None, 5, 5.0, "scale_up") is None


# --- registry surface: gauges, monotone counters, availability SLO ---------


def test_publish_gauges_and_monotone_counters():
    reg = MetricsRegistry(enabled=True)
    led = GoodputLedger(registry=reg)
    led.touch("r0", 0.0, "serving", 0)
    led.account("r0", 1.0, "productive", "serving", 1)
    led.account("r0", 2.0, "stall", "serving", 2)
    led.on_tick(2, 2.0)
    snap = reg.snapshot()
    assert snap["gauges"]["goodput.fraction"] == pytest.approx(0.5)
    assert snap["gauges"]["goodput.productive_seconds"] \
        == pytest.approx(1.0)
    assert snap["gauges"]["goodput.badput.stall_seconds"] \
        == pytest.approx(1.0)
    assert snap["counters"]["goodput.badput_seconds_total"] \
        == pytest.approx(1.0)
    assert snap["counters"]["goodput.wall_seconds_total"] \
        == pytest.approx(2.0)
    # counters are deltas off high-water marks: a second publish with
    # no new wall adds nothing
    led.publish()
    snap2 = reg.snapshot()
    assert snap2["counters"]["goodput.wall_seconds_total"] \
        == snap["counters"]["goodput.wall_seconds_total"]


def test_availability_slo_target_breaches_on_badput_burn():
    reg = MetricsRegistry(enabled=True)
    led = GoodputLedger(registry=reg)
    clock = [0.0]
    mon = SLOMonitor([availability_slo_target(target=0.95)],
                     registry=reg, clock=lambda: clock[0],
                     fast_window_s=10.0, slow_window_s=100.0,
                     burn_threshold=2.0)
    mon.evaluate()
    led.touch("r0", 0.0, "serving", 0)
    led.account("r0", 10.0, "failed_quarantine", "failed", 1)
    led.on_tick(1, 10.0)
    clock[0] = 5.0
    st = mon.evaluate()
    t = st["targets"]["fleet_availability"]
    assert t["bad_fraction_fast"] == pytest.approx(1.0)
    assert t["breaching"]


def test_availability_target_validates():
    t = availability_slo_target(0.99)
    assert t.kind == "ratio" and t.target == 0.99
    with pytest.raises(ValueError):
        availability_slo_target(1.0)


# --- Perfetto state bands --------------------------------------------------


def test_goodput_trace_events_render_bands_and_incident_markers():
    led = GoodputLedger()
    led.touch("r0", 0.0, "serving", 0)
    led.account("r0", 1.0, "productive", "serving", 1)
    led.account("r0", 2.0, "stall", "serving", 2)
    led.touch("r1", 0.0, "serving", 0)
    led.account("r1", 2.0, "idle", "serving", 2)
    inc = led.open_incident("crash", "r1", 2, 1.5)
    led.resolve_incident("r1", 4, 2.0, "rejoin")
    evs = goodput_trace_events(led)
    procs = [e for e in evs if e["name"] == "process_name"]
    assert procs and all(e["pid"] == PID_GOODPUT for e in evs)
    threads = {e["args"]["name"]: e["tid"] for e in evs
               if e["name"] == "thread_name"}
    assert set(threads) == {"r0", "r1"}
    bands = [e for e in evs if e.get("cat") == "goodput.state"]
    assert all(e["ph"] == "X" for e in bands)
    r0_bands = [e for e in bands if e["tid"] == threads["r0"]]
    assert [e["name"] for e in r0_bands] == ["productive", "stall"]
    assert r0_bands[0]["ts"] == 0.0 and r0_bands[0]["dur"] == 1e6
    marks = [e for e in evs if e.get("cat") == "goodput.incident"]
    assert len(marks) == 1 and marks[0]["ph"] == "i"
    assert marks[0]["name"] == "incident crash"
    assert marks[0]["tid"] == threads["r1"]
    assert marks[0]["args"]["mttr_s"] == inc.mttr_s


# --- trainer mirror --------------------------------------------------------


class _FakeTrainer:
    def __init__(self, step=0):
        self.state = SimpleNamespace(step=step)


def test_trainer_goodput_partitions_fit_wall_and_prices_rewind():
    clock = [0.0]
    gp = TrainerGoodput(clock=lambda: clock[0])
    tr = _FakeTrainer()
    gp.on_fit_start(tr)

    def run_step(step, dt, gap=0.25):
        clock[0] += gap
        gp.on_step_start(tr, step)
        clock[0] += dt
        gp.on_step_end(tr, step, 0.0)

    run_step(1, 2.0)          # first step: compile_warmup
    run_step(2, 0.5)          # steady state
    gp.on_checkpoint(tr, 2, "/tmp/ck")
    run_step(3, 0.5, gap=1.0)  # the 1.0s gap is checkpoint save wall
    assert gp.classes["checkpoint_save"] == pytest.approx(1.0)
    # recovery rewinds to step 2: the gap is restore, the re-run steps
    # are rewind_replay badput, and one incident prices the episode
    run_step(2, 0.5, gap=0.75)
    assert gp.classes["restore"] == pytest.approx(0.75)
    assert len(gp.incidents) == 1 and gp.incidents[0]["open"]
    assert gp.incidents[0]["rewound_to"] == 2
    assert gp.incidents[0]["step_detected"] == 3
    run_step(3, 0.5)          # re-reaches high-water: incident closes
    inc = gp.incidents[0]
    assert not inc["open"] and inc["replayed_steps"] == 2
    assert inc["mttr_s"] == pytest.approx(1.25)  # 0.5 + 0.25 + 0.5
    run_step(4, 0.5)          # back to goodput
    gp.on_fit_end(tr)
    rep = gp.report()
    assert rep["conservation_ok"], rep
    assert rep["replayed_steps"] == 2
    assert rep["classes"]["compile_warmup"] == pytest.approx(2.0)
    assert rep["classes"]["rewind_replay"] == pytest.approx(1.0)
    assert rep["classes"]["step_compute"] == pytest.approx(1.5)
    total = sum(rep["classes"].values())
    assert total == pytest.approx(rep["fit_wall_s"])
    assert 0 < rep["goodput_fraction"] < 1


def test_trainer_goodput_publishes_gauges_and_sorts_first():
    reg = MetricsRegistry(enabled=True)
    clock = [0.0]
    gp = TrainerGoodput(clock=lambda: clock[0], registry=reg)
    assert gp.order == -100  # books step wall before recovery/ckpt act
    tr = _FakeTrainer()
    gp.on_fit_start(tr)
    clock[0] = 1.0
    gp.on_step_start(tr, 1)
    clock[0] = 2.0
    gp.on_step_end(tr, 1, 0.0)
    gp.on_fit_abort(tr, RuntimeError("x"))
    snap = reg.snapshot()
    assert "train.goodput.fraction" in snap["gauges"]
    assert snap["gauges"]["train.goodput.compile_warmup_seconds"] \
        == pytest.approx(1.0)


# --- autoscaler audit-log bound (satellite) --------------------------------


def test_autoscaler_log_bounded_with_dropped_counter():
    from pipegoose_tpu.serving.control_plane import (
        Autoscaler,
        AutoscalerConfig,
    )

    mon = SimpleNamespace(evaluate=lambda now=None: {"targets": {}})
    asc = Autoscaler(mon, AutoscalerConfig(cooldown_ticks=0,
                                           max_replicas=100),
                     max_log=4)
    for tick in range(10):
        # an uncompensated failure forces an "up" decision every tick
        assert asc.decide(tick, n_serving=1, backlog=0,
                          n_failed=1) == "up"
    assert len(asc.log) == 4
    assert asc.log_dropped == 6
    assert [e["tick"] for e in asc.log] == [6, 7, 8, 9]  # newest kept
