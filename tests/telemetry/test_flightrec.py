"""Flight recorder (telemetry/flightrec.py): ring bounds, structured
triggers, atomic black-box dumps, and the recovery handshake — all
host-side (no device work), so the whole file rides the fast tier."""
import json
import os
import types

import pytest

from pipegoose_tpu.telemetry import MetricsRegistry
from pipegoose_tpu.telemetry.flightrec import FlightRecorder, TriggerEvent


def _trainer_stub(health=None, tokens=128):
    """Minimal duck-typed trainer for the callback interface."""
    state = types.SimpleNamespace(last_health=health, step=0)
    return types.SimpleNamespace(
        state=state, tokens_per_step=tokens, parallel_context=None,
        logger=None,
    )


def _healthy(gn=1.0):
    return {
        "grad_norm": gn,
        "grad_norm_per_module": {"embed": gn * 0.9, "blocks": gn * 0.1},
        "nonfinite_grad_leaves": 0.0,
        "nonfinite_update_leaves": 0.0,
        "update_max_abs": 1e-3,
        "update_norm": 0.1,
        "param_norm": 10.0,
        "update_ratio": 0.01,
    }


def _run_steps(rec, trainer, losses, healths=None):
    for i, loss in enumerate(losses, start=1):
        trainer.state.last_health = (
            healths[i - 1] if healths is not None else _healthy()
        )
        rec.on_step_start(trainer, i)
        rec.on_step_end(trainer, i, loss)


def test_ring_is_bounded(tmp_path):
    rec = FlightRecorder(str(tmp_path), capacity=4)
    for i in range(10):
        rec.record("x", step=i)
    assert len(rec.records) == 4
    assert [r["step"] for r in rec.records] == [6, 7, 8, 9]


def test_nonfinite_trigger_names_module_and_dumps(tmp_path):
    rec = FlightRecorder(str(tmp_path), capacity=8)
    trainer = _trainer_stub()
    bad = _healthy()
    bad["nonfinite_grad_leaves"] = 2.0
    bad["grad_norm"] = float("inf")
    bad["grad_norm_per_module"] = {"embed": float("inf"), "blocks": 0.1}
    _run_steps(rec, trainer, [4.0, 4.0, float("inf")],
               [_healthy(), _healthy(), bad])
    trig = rec.take_trigger()
    assert trig is not None and trig.name == "nonfinite"
    assert "'embed'" in trig.reason          # names the module group
    assert "non-finite loss" in trig.reason
    assert trig.dump_path and os.path.exists(trig.dump_path)
    # consuming clears it
    assert rec.take_trigger() is None

    # STRICT JSON: the nonfinite dump is exactly where inf/nan live;
    # bare Infinity/NaN tokens would make the black box unreadable by
    # jq/JS/log pipelines right when it matters (RFC 8259 has no such
    # literals — python's json.load merely tolerates them)
    text = open(trig.dump_path).read()
    assert "Infinity" not in text and "NaN" not in text
    data = json.loads(
        text, parse_constant=lambda c: pytest.fail(f"non-JSON token {c}")
    )
    assert data["records"][-1]["health"]["grad_norm"] == "inf"
    assert data["trigger"]["name"] == "nonfinite"
    assert data["trigger"]["step"] == 3
    assert data["trigger"]["details"]["bad_modules"] == ["embed"]
    kinds = [r["kind"] for r in data["records"]]
    assert kinds.count("train.step") == 3
    assert data["records"][-1]["health"]["nonfinite_grad_leaves"] == 2.0
    assert data["records"][-1]["step_time_s"] is not None
    assert "jax" in data["environment"]
    # atomic write: no temp litter
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]


def test_update_overflow_triggers_without_bad_loss(tmp_path):
    """Overflowed optimizer updates under a still-finite loss (the
    CheckpointCallback blind spot) must fire on their own."""
    rec = FlightRecorder(str(tmp_path))
    bad = _healthy()
    bad["nonfinite_update_leaves"] = 1.0
    _run_steps(rec, _trainer_stub(), [4.0], [bad])
    trig = rec.take_trigger()
    assert trig is not None and trig.name == "nonfinite"
    assert "optimizer updates" in trig.reason


def test_loss_spike_zscore_arms_after_warmup(tmp_path):
    # below the arming threshold a spike-looking value must not fire
    # (startup loss cliffs would trip a day-one z-score)
    rec0 = FlightRecorder(str(tmp_path / "a"), loss_spike_z=4.0, window=8,
                          grad_explosion_factor=None)
    _run_steps(rec0, _trainer_stub(), [4.0, 50.0])
    assert rec0.take_trigger() is None

    rec = FlightRecorder(str(tmp_path / "b"), loss_spike_z=4.0, window=8,
                         grad_explosion_factor=None)
    trainer = _trainer_stub()
    _run_steps(rec, trainer, [4.0, 4.1, 3.9, 4.0])   # >= window//2: armed
    assert rec.take_trigger() is None
    _run_steps(rec, trainer, [50.0])
    trig = rec.take_trigger()
    assert trig is not None and trig.name == "loss_spike"
    assert "sigma" in trig.reason
    assert trig.details["z"] > 4.0


def test_grad_explosion_trigger_names_largest_module(tmp_path):
    rec = FlightRecorder(str(tmp_path), grad_explosion_factor=10.0,
                         window=4, loss_spike_z=None)
    trainer = _trainer_stub()
    _run_steps(rec, trainer, [4.0, 4.0], [_healthy(1.0), _healthy(1.1)])
    assert rec.take_trigger() is None
    _run_steps(rec, trainer, [4.0], [_healthy(100.0)])
    trig = rec.take_trigger()
    assert trig is not None and trig.name == "grad_explosion"
    assert "'embed'" in trig.reason          # largest per-module norm
    assert trig.details["grad_norm"] == pytest.approx(100.0)


def test_spike_does_not_poison_its_own_baseline(tmp_path):
    """A triggering step's loss must NOT enter the trailing window —
    otherwise one spike shifts the mean and masks the next one."""
    rec = FlightRecorder(str(tmp_path), loss_spike_z=4.0, window=6,
                         grad_explosion_factor=None)
    trainer = _trainer_stub()
    _run_steps(rec, trainer, [4.0, 4.1, 3.9, 4.0])
    _run_steps(rec, trainer, [60.0])
    assert rec.take_trigger().name == "loss_spike"
    assert 60.0 not in rec._loss_hist
    _run_steps(rec, trainer, [55.0])         # second spike still fires
    assert rec.take_trigger().name == "loss_spike"


def test_check_every_skips_off_steps(tmp_path):
    rec = FlightRecorder(str(tmp_path), check_every=2)
    trainer = _trainer_stub()
    bad = _healthy()
    bad["nonfinite_grad_leaves"] = 1.0
    # step 1 is an off step (1 % 2 != 0): not recorded, no trigger
    trainer.state.last_health = bad
    rec.on_step_start(trainer, 1)
    rec.on_step_end(trainer, 1, float("nan"))
    assert len(rec.records) == 0 and rec.take_trigger() is None
    rec.on_step_start(trainer, 2)
    rec.on_step_end(trainer, 2, float("nan"))
    assert len(rec.records) == 1 and rec.take_trigger() is not None


def test_reset_after_restore_clears_baselines_and_marks_ring(tmp_path):
    rec = FlightRecorder(str(tmp_path))
    _run_steps(rec, _trainer_stub(), [4.0, 4.0, 4.0])
    assert len(rec._loss_hist) == 3
    rec.last_trigger = TriggerEvent("nonfinite", "x", 3)
    rec.reset_after_restore(2)
    assert not rec._loss_hist and not rec._grad_hist
    assert rec.take_trigger() is None
    assert rec.records[-1]["kind"] == "restore"
    assert rec.records[-1]["step"] == 2


def test_max_dumps_bounds_disk(tmp_path):
    rec = FlightRecorder(str(tmp_path), max_dumps=2)
    for i in range(4):
        path = rec.dump(TriggerEvent("nonfinite", "r", i))
        assert (path is not None) == (i < 2)
    assert len([f for f in os.listdir(tmp_path) if f.endswith(".json")]) == 2


def test_span_summaries_drain_from_enabled_registry(tmp_path):
    from pipegoose_tpu.telemetry.spans import span

    reg = MetricsRegistry(enabled=True)
    rec = FlightRecorder(str(tmp_path), registry=reg)
    trainer = _trainer_stub()
    rec.on_fit_start(trainer)
    with span("train.step", registry=reg):
        pass
    with span("train.step", registry=reg):
        pass
    rec.on_step_start(trainer, 1)
    rec.on_step_end(trainer, 1, 4.0)
    spans = rec.records[-1]["spans"]
    assert spans["train.step"]["n"] == 2
    assert spans["train.step"]["total_s"] >= 0
    rec.on_fit_end(trainer)
    assert rec._sink not in reg._sinks


def test_disabled_registry_is_never_implicitly_enabled(tmp_path):
    reg = MetricsRegistry(enabled=False)
    rec = FlightRecorder(str(tmp_path), registry=reg)
    rec.on_fit_start(_trainer_stub())
    assert not reg.enabled and not rec._attached


def test_serving_stall_trigger_dumps(tmp_path):
    rec = FlightRecorder(str(tmp_path))
    rec.observe_serving_step(1, active=2, queue_depth=3, dur_s=0.01, tokens=2)
    trig = rec.trigger_decode_stall(
        5, "no decode progress", context={"queued": 3}
    )
    assert trig.name == "decode_stall"
    data = json.load(open(trig.dump_path))
    assert data["context"]["queued"] == 3
    assert data["records"][0]["kind"] == "serving.step"


def test_validation():
    with pytest.raises(ValueError, match="capacity"):
        FlightRecorder("/tmp/x", capacity=0)
    with pytest.raises(ValueError, match="check_every"):
        FlightRecorder("/tmp/x", check_every=0)
    with pytest.raises(ValueError, match="window"):
        FlightRecorder("/tmp/x", window=1)
