"""Derived gauges: MFU arithmetic against the peak table, HLO
communication-bytes accounting (synthetic text + a real compiled
shard_map program), compiled step stats, HBM fallback."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pipegoose_tpu.distributed.compat import shard_map
from pipegoose_tpu.telemetry import derived


def test_peak_flops_table_substring_match():
    assert derived.peak_flops_for("TPU v5e") == 197e12
    assert derived.peak_flops_for("TPU v5 lite") == 197e12
    assert derived.peak_flops_for("v5p slice") == 459e12
    assert derived.peak_flops_for("cpu-fallback") == 1e12
    assert derived.peak_flops_for("martian accelerator") == 1e12  # default


def test_mfu_arithmetic():
    # 1e12 FLOPs in 10ms on a 197e12-peak chip -> 1e14/1.97e14
    assert derived.mfu(1e12, 0.01, peak=197e12) == pytest.approx(
        1e14 / 197e12
    )
    # n_devices divides the peak pool
    assert derived.mfu(1e12, 0.01, peak=197e12, n_devices=4) == pytest.approx(
        1e14 / (4 * 197e12)
    )
    assert derived.mfu(1e12, 0.0, peak=1e12) == 0.0
    assert derived.tokens_per_second(100, 2.0) == 50.0
    assert derived.tokens_per_second(100, 0.0) == 0.0


def test_collective_bytes_parses_hlo_text():
    hlo = "\n".join([
        "  %ar = f32[8,16]{1,0} all-reduce(f32[8,16] %x), replica_groups={}",
        "  %ag = bf16[4,256]{1,0} all-gather(bf16[2,256] %y), dimensions={0}",
        "  %rs = f32[2,8]{1,0} reduce-scatter(f32[8,8] %z), dimensions={0}",
        "  %cp = u8[128]{0} collective-permute(u8[128] %w)",
        "  %a2a = f32[16]{0} all-to-all(f32[16] %v)",
        "  %dead = f32[999] add(f32[999] %a, f32[999] %b)",
    ])
    out = derived.collective_bytes(hlo)
    assert out["all-reduce"] == 8 * 16 * 4
    assert out["all-gather"] == 4 * 256 * 2
    assert out["reduce-scatter"] == 2 * 8 * 4
    assert out["collective-permute"] == 128
    assert out["all-to-all"] == 16 * 4
    assert out["total"] == sum(
        v for k, v in out.items() if k != "total"
    )


def test_collective_bytes_counts_async_start_once():
    # real XLA async form: the -start result tuple carries BOTH the
    # operand and output buffers; only the output half is the payload,
    # and the -done half must not count at all
    hlo = "\n".join([
        "  %s = (f32[64]{0}, f32[64]{0}) all-reduce-start(f32[64] %x)",
        "  %d = f32[64]{0} all-reduce-done((f32[64], f32[64]) %s)",
    ])
    out = derived.collective_bytes(hlo)
    assert out["all-reduce"] == 64 * 4


def test_collective_bytes_async_asymmetric_and_contexts():
    # asymmetric async collectives: the output half differs from the
    # input half, so "half the tuple" would miscount — all-gather grows
    # (2,256)->(4,256), reduce-scatter shrinks (8,8)->(2,8); trailing
    # scalar u32 context slots (collective-permute-start) are ignored
    hlo = "\n".join([
        "  %ag = (bf16[2,256]{1,0}, bf16[4,256]{1,0}) all-gather-start(bf16[2,256] %x)",
        "  %rs = (f32[8,8]{1,0}, f32[2,8]{1,0}) reduce-scatter-start(f32[8,8] %y)",
        "  %cp = (u8[128]{0}, u8[128]{0}, u32[], u32[]) collective-permute-start(u8[128] %z)",
    ])
    out = derived.collective_bytes(hlo)
    assert out["all-gather"] == 4 * 256 * 2
    assert out["reduce-scatter"] == 2 * 8 * 4
    assert out["collective-permute"] == 128


def test_compiled_step_stats_reports_flops_and_comms(devices):
    """One lower+compile yields XLA flops AND the all-reduce bytes of a
    psum'd shard_map program — the compiler-ground-truth MFU/comms
    inputs (GSPMD lineage, ISSUE 2)."""
    mesh = jax.sharding.Mesh(np.array(devices).reshape(8), ("d",))

    def f(x):
        return jax.lax.psum((x * x).sum(), "d")

    g = shard_map(f, mesh=mesh, in_specs=(P("d"),), out_specs=P())
    stats = derived.compiled_step_stats(g, jnp.ones((8, 128)))
    assert stats["flops"] > 0
    assert stats["comm_bytes"] >= 4  # the f32 psum scalar, at least
    assert "all-reduce" in stats["comm_by_op"]

    # a collective-free program reports zero comm bytes
    stats0 = derived.compiled_step_stats(lambda x: x * 2, jnp.ones(16))
    assert stats0["comm_bytes"] == 0
    assert stats0["comm_by_op"] == {}


def test_step_flops_matmul_scales():
    a = jnp.ones((32, 32))
    b = jnp.ones((128, 128))
    f = lambda x: x @ x  # noqa: E731
    small, big = derived.step_flops(f, a), derived.step_flops(f, b)
    assert small > 0
    # 4x dim -> 64x matmul FLOPs
    assert big == pytest.approx(64 * small, rel=0.01)


def test_hbm_utilization_empty_on_cpu():
    # CPU devices report no memory stats: the gauge source degrades to
    # an empty dict, never an exception
    assert derived.hbm_utilization() == {}


def test_collective_bytes_tuple_shaped_sync_variadic():
    # variadic SYNC forms print a tuple result whose elements are ALL
    # outputs (the ISSUE-4 satellite fix: structural tuple parsing
    # instead of treating the tuple like an async operand/output pair)
    hlo = "\n".join([
        "  %rs = (f32[2,8]{1,0}, f32[4]{0}) reduce-scatter(f32[8,8] %a, "
        "f32[16] %b), dimensions={0}",
        "  %cp = (f32[128]{0}, f32[128]{0}) collective-permute("
        "(f32[128], f32[128]) %p), source_target_pairs={{0,1},{1,0}}",
    ])
    out = derived.collective_bytes(hlo)
    assert out["reduce-scatter"] == 2 * 8 * 4 + 4 * 4
    assert out["collective-permute"] == 2 * 128 * 4


def test_collective_bytes_sync_permute_strips_context_slots():
    # sync collective-permute keeping trailing u32[] context slots: the
    # scalars are bookkeeping, not payload
    hlo = ("  %cp = (u8[128]{0}, u32[], u32[]) collective-permute("
           "u8[128] %z), source_target_pairs={{0,1}}")
    assert derived.collective_bytes(hlo)["collective-permute"] == 128


def test_collective_bytes_nested_variadic_start():
    # async variadic start: ((operands...), (outputs...), contexts) —
    # only the LAST nested tuple (the outputs) is payload
    hlo = "\n".join([
        "  %rs = ((f32[8,8]{1,0}, f32[16]{0}), (f32[2,8]{1,0}, f32[4]{0}), "
        "u32[], u32[]) reduce-scatter-start(f32[8,8] %a, f32[16] %b)",
        "  %d = (f32[2,8]{1,0}, f32[4]{0}) reduce-scatter-done(%rs)",
        "  %cps = ((u8[128]{0}), (u8[128]{0}), u32[], u32[]) "
        "collective-permute-start(u8[128] %z)",
    ])
    out = derived.collective_bytes(hlo)
    assert out["reduce-scatter"] == 2 * 8 * 4 + 4 * 4  # done half skipped
    assert out["collective-permute"] == 128


def test_iter_collectives_line_level():
    hlo = "\n".join([
        "  %ar = f32[8]{0} all-reduce(f32[8] %x)",
        "  %ag = (bf16[2,4]{1,0}, bf16[4,4]{1,0}) all-gather-start(bf16[2,4] %y)",
        "  %agd = bf16[4,4]{1,0} all-gather-done(%ag)",
    ])
    items = list(derived.iter_collectives(hlo))
    assert [(c["op"], c["bytes"], c["start"]) for c in items] == [
        ("all-reduce", 32, False),
        ("all-gather", 32, True),
    ]


def test_unknown_device_kind_falls_back_loudly():
    """ISSUE 14 satellite: every `*_for` peer-table lookup must fall
    back to its DOCUMENTED default on an unknown device kind — and WARN
    naming the table, never return a silent zero (a typo'd
    --device-kind would otherwise score every layout against garbage).
    Pinned for PEAK_FLOPS / ICI / DCI / HBM (+ HBM bandwidth)."""
    cases = [
        (derived.peak_flops_for, derived.DEFAULT_PEAK_FLOPS, "PEAK_FLOPS"),
        (derived.ici_bytes_per_s_for, derived.DEFAULT_ICI_BYTES,
         "PEAK_ICI_BYTES"),
        (derived.dci_bytes_per_s_for, derived.DEFAULT_DCI_BYTES,
         "PEAK_DCI_BYTES"),
        (derived.hbm_bytes_for, derived.DEFAULT_HBM_BYTES, "HBM_BYTES"),
        (derived.hbm_bw_bytes_per_s_for, derived.DEFAULT_HBM_BW_BYTES,
         "HBM_BW_BYTES"),
    ]
    for fn, default, table in cases:
        with pytest.warns(UserWarning, match=table):
            got = fn("martian accelerator v9")
        assert got == default and got > 0


def test_known_device_kinds_never_warn():
    import warnings as _w

    for kind in ("TPU v5e", "TPU v5 lite", "v5p slice", "cpu-fallback",
                 "TPU v4"):
        with _w.catch_warnings():
            _w.simplefilter("error")
            assert derived.peak_flops_for(kind) > 0
            assert derived.ici_bytes_per_s_for(kind) > 0
            assert derived.hbm_bytes_for(kind) > 0
