"""Memory-ledger unit contract (telemetry/memledger.py, ISSUE 18):
the owner-tag multiset mirrors pool refcounts exactly, pages classify
by strongest owner, conservation is integer-exact every tick, the
audit cross-check catches leaks / double owners / stranded
reservations and fires each black box ONCE, the exhaustion forecast
walks monotonically to zero under steady consumption, and the
Perfetto counter-track renderer emits one "C" event set per sample."""
import math
from types import SimpleNamespace

import pytest

from pipegoose_tpu.serving.kv_pool import PagePool
from pipegoose_tpu.telemetry.chrometrace import (
    PID_MEMORY,
    memory_trace_events,
)
from pipegoose_tpu.telemetry.flightrec import FlightRecorder
from pipegoose_tpu.telemetry.memledger import MemoryLedger
from pipegoose_tpu.telemetry.registry import MetricsRegistry


def _pool(n=16, ps=4, **kw):
    return PagePool(n, ps, **kw)


def _bound(pool=None, **kw):
    pool = pool if pool is not None else _pool()
    led = MemoryLedger()
    led.bind(pool, **kw)
    return pool, led


def _alloc(pool, n, tag):
    pool.tag = tag
    return pool.alloc(n)


# --- observer feed: tags, classes, priority --------------------------------


def test_alloc_share_release_mirror_refcounts_and_classify():
    pool, led = _bound()
    pages = _alloc(pool, 2, ("req", 7))
    assert led.counts()["request"] == 2
    # a cache share on a request page: counted ONCE, strongest owner
    pool.tag = ("cache",)
    pool.share([pages[0]])
    c = led.counts()
    assert c["request"] == 2 and c["cached"] == 0
    # the request side releases: the page DEMOTES to cached, not freed
    pool.tag = ("req", 7)
    pool.release([pages[0]])
    c = led.counts()
    assert c["request"] == 1 and c["cached"] == 1
    assert pool.refcount(pages[0]) == 1
    assert led.conservation()["ok"]
    assert led.mismatched_releases == 0


def test_untagged_release_drops_weakest_tag():
    pool, led = _bound()
    (p,) = _alloc(pool, 1, ("req", 1))
    pool.tag = ("cache",)
    pool.share([p])
    # untagged release (legacy call site): the WEAKEST owner goes, the
    # page stays request-class — a ledger gap may misattribute, never
    # demote a live request's page
    pool.release([p])
    assert led.counts()["request"] == 1
    assert led.counts()["cached"] == 0


def test_mismatched_release_counted_not_raised():
    pool, led = _bound()
    (p,) = _alloc(pool, 1, ("req", 1))
    pool.tag = ("stage", 99)         # release a tag the page never had
    pool.release([p])
    assert led.mismatched_releases == 1
    assert led.counts()["request"] == 0   # refcount 0: fully freed
    assert led.conservation()["ok"]


def test_retag_moves_staged_to_request_without_refcount_change():
    pool, led = _bound()
    pages = _alloc(pool, 2, ("stage", 3))
    assert led.counts()["staged"] == 2
    led.retag(pages, ("stage", 3), ("req", 3))
    c = led.counts()
    assert c["staged"] == 0 and c["request"] == 2
    assert pool.used_count == 2 and led.conservation()["ok"]


def test_trail_records_transitions_and_survives_free():
    pool, led = _bound()
    (p,) = _alloc(pool, 1, ("req", 5))
    pool.tag = ("req", 5)
    pool.release([p])
    trail = led.trail(p)
    assert [e["event"] for e in trail] == ["alloc", "release"]
    assert trail[0]["owner"] == ["req", 5]
    assert p not in led._tags            # freed, but the trail remains


def test_resync_adopts_warm_pool_as_untracked():
    pool = _pool()
    pages = pool.alloc(3)                # allocated BEFORE any ledger
    led = MemoryLedger()
    led.bind(pool)
    assert led.counts()["request"] == 3  # untracked counts as request
    assert led.conservation()["ok"]
    # the adopted refs release cleanly (weakest-tag drop)
    pool.release(pages)
    assert led.counts()["request"] == 0


# --- conservation with reservations ----------------------------------------


def test_reserved_unmaterialized_completes_the_partition():
    pool = _pool(16)
    sched = SimpleNamespace(_outstanding_total=5, transfers={},
                            active=lambda: [])
    led = MemoryLedger()
    led.bind(pool, sched=sched)
    _alloc(pool, 4, ("req", 1))
    c = led.counts()
    assert c["reserved_unmaterialized"] == 5
    assert c["free"] == pool.free_count - 5
    cons = led.conservation()
    assert cons["ok"]
    assert cons["sum_pages"] == pool.capacity
    # reservations beyond the physically free pages report as
    # evictable-backed overlap, keeping the capacity sum a partition
    sched._outstanding_total = pool.free_count + 3
    cons = led.conservation()
    assert cons["ok"] and cons["reserved_evictable_backed"] == 3


def test_on_tick_conservation_break_fires_once_and_never_raises(tmp_path):
    pool = _pool()
    rec = FlightRecorder(str(tmp_path), capacity=8)
    led = MemoryLedger()
    led.bind(pool, recorder=rec)
    _alloc(pool, 2, ("req", 1))
    # corrupt the mirror behind the ledger's back: classified != used
    led._tags.clear()
    led._class.clear()
    led._counts = {k: 0 for k in led._counts}
    led.on_tick(1)
    led.on_tick(2)
    assert led.conservation_failures == 2
    trig = rec.take_trigger()
    assert trig is not None and trig.name == "ledger_conservation"
    assert rec.take_trigger() is None    # fired ONCE across both ticks


# --- audit: leaks, double owners, stranded reservations --------------------


def test_audit_detects_leak_with_owner_trail_and_fires_once(tmp_path):
    pool = _pool()
    rec = FlightRecorder(str(tmp_path), capacity=8)
    sched = SimpleNamespace(_outstanding_total=0, transfers={},
                            active=lambda: [])
    led = MemoryLedger()
    led.bind(pool, sched=sched, recorder=rec)
    (p,) = _alloc(pool, 1, ("req", 4))
    # the leak: an extra reference nobody reachable owns
    pool.tag = ("req", 4)
    pool.share([p])
    report = led.audit()
    assert not report["ok"]
    (leak,) = report["leaks"]
    assert leak["page"] == p and leak["refcount"] == 2
    assert leak["holders"] == 0          # the stub sched holds nothing
    assert leak["trail"], "leak box must carry the ownership trail"
    trig = rec.take_trigger()
    assert trig is not None and trig.name == "memory_leak"
    assert str(p) in trig.reason
    led.audit()                          # re-audit: counted, quiet
    assert led.audits_run == 2
    assert rec.take_trigger() is None


def test_audit_detects_double_owner(tmp_path):
    pool = _pool()
    rec = FlightRecorder(str(tmp_path), capacity=8)
    led = MemoryLedger()
    (p,) = pool.alloc(1)
    # two requests both claim the page; the pool granted ONE reference
    req_a = SimpleNamespace(uid=1, pages=[p], cow=None, outstanding=0)
    req_b = SimpleNamespace(uid=2, pages=[p], cow=None, outstanding=0)
    sched = SimpleNamespace(_outstanding_total=0, transfers={},
                            active=lambda: [req_a, req_b])
    led.bind(pool, sched=sched, recorder=rec)
    report = led.audit()
    (dbl,) = report["double_owners"]
    assert dbl["page"] == p and dbl["holders"] == 2 and dbl["refcount"] == 1
    trig = rec.take_trigger()
    assert trig is not None and trig.name == "double_owner"


def test_audit_detects_stranded_reservation(tmp_path):
    pool = _pool()
    rec = FlightRecorder(str(tmp_path), capacity=8)
    sched = SimpleNamespace(_outstanding_total=3, transfers={},
                            active=lambda: [])
    led = MemoryLedger()
    led.bind(pool, sched=sched, recorder=rec)
    report = led.audit()
    assert report["stranded_reserved_pages"] == 3
    trig = rec.take_trigger()
    assert trig is not None and trig.name == "stranded_reservation"
    assert "3" in trig.reason


def test_audit_clean_pool_is_ok():
    pool, led = _bound()
    req = SimpleNamespace(uid=1, pages=[], cow=None, outstanding=0)
    sched = SimpleNamespace(_outstanding_total=0, transfers={},
                            active=lambda: [req])
    led.sched = sched
    req.pages = _alloc(pool, 2, ("req", 1))
    assert led.audit()["ok"]


# --- exhaustion forecast ---------------------------------------------------


def test_forecast_monotone_to_zero_under_steady_consumption():
    pool = _pool(32)
    sched = SimpleNamespace(_outstanding_total=0, transfers={},
                            active=lambda: [])
    led = MemoryLedger()
    led.bind(pool, sched=sched)
    seen = []
    for t in range(1, 14):
        _alloc(pool, 2, ("req", t))
        led.note_admission(4, True)
        led.on_tick(t)
        seen.append(led.steps_to_exhaustion)
    finite = [s for s in seen if not math.isinf(s)]
    assert finite, "a steady drain must produce a finite forecast"
    assert finite == sorted(finite, reverse=True)   # monotone down
    assert finite[-1] == 0.0
    assert led.min_steps_to_exhaustion == 0.0


def test_forecast_infinite_without_consumption_trend():
    pool, led = _bound()
    for t in range(1, 4):
        led.on_tick(t)
    assert math.isinf(led.steps_to_exhaustion)


def test_note_admission_block_records_first_tick():
    pool, led = _bound()
    led.on_tick(1)
    led.on_tick(2)
    led.note_admission(4, False)
    led.note_admission(4, False)
    assert led.first_admission_block_tick == 2   # first block only


# --- reports, gauges, history ring, trace renderer -------------------------


def test_report_shapes_and_gauges(tmp_path):
    reg = MetricsRegistry(enabled=True)
    pool = _pool()
    led = MemoryLedger()
    led.bind(pool, registry=reg, bytes_per_page=128)
    _alloc(pool, 3, ("req", 1))
    led.on_tick(1, t=0.25)
    rep = led.report()
    assert rep["classes"]["request"] == {"pages": 3, "bytes": 384}
    assert rep["conservation"]["ok"]
    assert rep["capacity_bytes"] == pool.capacity * 128
    assert rep["forecast"]["steps_to_exhaustion"] is None   # inf -> None
    g = reg.gauge("serving.memledger.request_bytes")
    assert g.value == 384.0
    assert reg.gauge("serving.memledger.steps_to_exhaustion").value == -1.0
    summary = led.run_summary()
    assert summary["peak_pages"]["request"] == 3
    assert summary["peak_bytes"]["request"] == 384
    assert summary["conservation_failures"] == 0


def test_history_ring_bounded_with_dropped_counter():
    pool = _pool(64, 4, history_limit=4)
    for _ in range(6):
        pool.release(pool.alloc(1))
    assert len(pool.history) == 4
    assert pool.history_dropped == 8          # 12 events, 4 kept
    with pytest.raises(ValueError, match="history_limit"):
        _pool(history_limit=0)


def test_ledger_exact_after_history_ring_wraps():
    """The observer contract: accounting stays exact even after the
    (bounded) history ring has dropped events — the ledger is fed
    synchronously, not parsed from the ring."""
    pool = _pool(64, 4, history_limit=2)
    led = MemoryLedger()
    led.bind(pool)
    held = []
    for i in range(8):
        held += _alloc(pool, 1, ("req", i))
    assert pool.history_dropped > 0
    assert led.counts()["request"] == 8
    assert led.conservation()["ok"]
    for i, p in enumerate(held):
        pool.tag = ("req", i)
        pool.release([p])
    assert led.counts()["request"] == 0 and led.conservation()["ok"]


def test_memory_trace_events_render_counter_tracks():
    pool, led = _bound(host_tier=SimpleNamespace(
        resident_bytes=640, resident_pages=5, byte_budget=1 << 20))
    led.bytes_per_page = 64
    _alloc(pool, 2, ("req", 1))
    led.on_tick(1, t=1.0)
    led.on_tick(2, t=1.5)
    events = memory_trace_events(led)
    assert events[0]["ph"] == "M" and events[0]["pid"] == PID_MEMORY
    counters = [e for e in events if e["ph"] == "C"]
    kv = [e for e in counters if e["name"] == "kv bytes"]
    assert len(kv) == 2
    assert kv[0]["ts"] == 1.0 * 1e6
    assert kv[0]["args"]["request"] == 2 * 64
    assert {e["name"] for e in counters} >= {
        "kv bytes", "fragmentation", "host tier bytes"}


def test_unbind_detaches_observer():
    pool, led = _bound()
    led.unbind()
    assert pool.ledger is None
    pool.alloc(1)
    assert led.counts()["request"] == 0   # no longer fed
