"""Exporters: JSONL event stream round-trip, Prometheus textfile
atomicity/content, and the rank-0 DistributedLogger convention."""
import json
import os

from pipegoose_tpu.telemetry import (
    JSONLExporter,
    MetricsRegistry,
    PrometheusTextfileExporter,
)


def _reg():
    reg = MetricsRegistry(enabled=True)
    reg.counter("tok.total").inc(42)
    reg.gauge("tps").set(1234.5)
    reg.histogram("lat.seconds").observe(0.02)
    return reg


def test_jsonl_events_and_snapshot_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    reg = _reg()
    with JSONLExporter(path, registry=reg) as ex:
        reg.event("step", i=0, tokens_per_s=10.0)
        reg.event("step", i=1, tokens_per_s=12.0)
        ex.export_snapshot()
    lines = [json.loads(l) for l in open(path)]
    assert [l["kind"] for l in lines] == ["step", "step", "snapshot"]
    assert lines[1]["tokens_per_s"] == 12.0
    snap = lines[2]
    assert snap["counters"]["tok.total"] == 42.0
    assert snap["gauges"]["tps"] == 1234.5
    assert snap["histograms"]["lat.seconds"]["count"] == 1


def test_jsonl_close_detaches_sink(tmp_path):
    path = str(tmp_path / "e.jsonl")
    reg = _reg()
    ex = JSONLExporter(path, registry=reg)
    reg.event("a")
    ex.close()
    reg.event("b")  # after close: not written
    kinds = [json.loads(l)["kind"] for l in open(path)]
    assert kinds == ["a"]


def test_jsonl_serializes_numpy_scalars(tmp_path):
    import numpy as np

    path = str(tmp_path / "np.jsonl")
    reg = _reg()
    with JSONLExporter(path, registry=reg):
        reg.event("x", v=np.float32(1.5), n=np.int64(3))
    (line,) = [json.loads(l) for l in open(path)]
    assert line["v"] == 1.5 and line["n"] == 3


def test_prometheus_textfile_write(tmp_path):
    path = str(tmp_path / "metrics.prom")
    reg = _reg()
    out = PrometheusTextfileExporter(path).write(reg)
    assert out == path
    text = open(path).read()
    assert "tok_total 42.0" in text
    assert "tps 1234.5" in text
    assert 'lat_seconds_bucket{le="+Inf"} 1' in text
    # atomic write leaves no temp litter
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []


def test_rank_filter_suppresses_non_matching_rank(tmp_path):
    """Rank filtering reuses the DistributedLogger convention: only
    jax.process_index() == rank writes. This single-process test IS
    process 0, so rank=1 exporters must produce nothing."""
    jl = str(tmp_path / "r1.jsonl")
    reg = _reg()
    ex = JSONLExporter(jl, registry=reg, rank=1)
    reg.event("x")
    ex.export_snapshot()
    ex.close()
    assert not os.path.exists(jl)

    prom = str(tmp_path / "r1.prom")
    assert PrometheusTextfileExporter(prom, rank=1).write(reg) is None
    assert not os.path.exists(prom)

    # rank=None: every process writes
    all_path = str(tmp_path / "all.jsonl")
    with JSONLExporter(all_path, registry=reg):
        reg.event("y")
    assert os.path.exists(all_path)
