"""Metrics registry: counters/gauges/histograms, thread safety, the
disabled-overhead contract that lets instrumentation live in library
hot loops, and jit-trace safety (ISSUE 2 regression)."""
import json
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from pipegoose_tpu.telemetry import MetricsRegistry
from pipegoose_tpu.telemetry.registry import DEFAULT_TIME_BUCKETS


@pytest.fixture()
def reg():
    return MetricsRegistry(enabled=True)


def test_counter_gauge_basics(reg):
    c = reg.counter("req.total", help="requests")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("depth")
    g.set(7)
    g.set(3)
    assert g.value == 3.0


def test_metric_getters_idempotent_and_type_checked(reg):
    assert reg.counter("x") is reg.counter("x")
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("x")


def test_histogram_stats_and_quantiles(reg):
    h = reg.histogram("lat.seconds")
    for i in range(1, 101):
        h.observe(i / 1000)  # 1ms..100ms
    assert h.count == 100
    assert h.sum == pytest.approx(5.05)
    snap = h.snapshot()
    assert snap["min"] == pytest.approx(0.001)
    assert snap["max"] == pytest.approx(0.1)
    assert snap["p50"] == pytest.approx(0.05, rel=0.1)
    assert snap["p99"] == pytest.approx(0.1, rel=0.05)
    # bucket counts cover every observation exactly once
    assert sum(
        snap["buckets"][str(b)] for b in DEFAULT_TIME_BUCKETS
    ) + snap["buckets"]["+Inf"] == 100


def test_histogram_reservoir_bounded(reg):
    h = reg.histogram("r", reservoir=64)
    for i in range(10_000):
        h.observe(float(i))
    assert len(h._reservoir) == 64
    assert h.count == 10_000
    # reservoir quantiles stay in the observed range
    assert 0 <= h.quantile(0.5) < 10_000


def test_thread_safety_no_lost_increments(reg):
    c = reg.counter("t")
    h = reg.histogram("th")

    def work():
        for _ in range(10_000):
            c.inc()
            h.observe(0.001)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 40_000
    assert h.count == 40_000


def test_disabled_registry_records_nothing():
    reg = MetricsRegistry(enabled=False)
    c = reg.counter("c")
    g = reg.gauge("g")
    h = reg.histogram("h")
    events = []
    reg.attach(events.append)
    c.inc()
    g.set(1.0)
    h.observe(1.0)
    reg.event("e")
    assert c.value == 0.0
    assert g.value != g.value  # NaN: never set
    assert h.count == 0
    assert events == []
    reg.enable()
    c.inc()
    assert c.value == 1.0


def test_disabled_overhead_under_5us():
    """The CI overhead guard (ISSUE 2): instrumentation stays ON in
    library code because a disabled counter inc / span entry costs
    < 5 µs median — measured over batches to beat timer noise."""
    from pipegoose_tpu.telemetry import span

    reg = MetricsRegistry(enabled=False)
    c = reg.counter("c")
    n = 2000

    def med(fn):
        samples = []
        for _ in range(15):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            samples.append((time.perf_counter() - t0) / n)
        return sorted(samples)[len(samples) // 2]

    assert med(c.inc) < 5e-6

    def enter_span():
        with span("s", registry=reg):
            pass

    assert med(enter_span) < 5e-6


def test_tracer_and_trace_time_mutation_noop(reg):
    """Counters/gauges/histograms touched inside jit-traced code no-op
    cleanly: no crash, no per-compile phantom counts, correct result."""
    c = reg.counter("jit.c")
    g = reg.gauge("jit.g")
    h = reg.histogram("jit.h")

    @jax.jit
    def f(x):
        c.inc()            # trace-time host mutation
        g.set(x.sum())     # tracer value
        h.observe(x[0])    # tracer value
        return x * 2

    for _ in range(3):
        out = f(jnp.arange(4.0))
    assert list(out) == [0.0, 2.0, 4.0, 6.0]
    assert c.value == 0.0
    assert g.value != g.value  # still NaN
    assert h.count == 0


def test_snapshot_and_prometheus_render(reg):
    reg.counter("a.total", help="things").inc(3)
    reg.gauge("b.depth").set(2.0)
    reg.histogram("c.seconds").observe(0.02)
    snap = reg.snapshot()
    assert snap["counters"]["a.total"] == 3.0
    assert snap["gauges"]["b.depth"] == 2.0
    assert snap["histograms"]["c.seconds"]["count"] == 1
    json.dumps(snap)  # JSON-able contract (utils/profiler.py convention)

    text = reg.to_prometheus()
    assert "# TYPE a_total counter" in text
    assert "a_total 3.0" in text
    assert "b_depth 2.0" in text
    assert '# HELP a_total things' in text
    assert 'c_seconds_bucket{le="+Inf"} 1' in text
    assert "c_seconds_count 1" in text
    # cumulative buckets are monotone
    counts = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
              if line.startswith("c_seconds_bucket")]
    assert counts == sorted(counts)


def test_events_dispatch_to_sinks(reg):
    got = []
    reg.attach(got.append)
    reg.event("step", i=1)
    reg.detach(got.append)
    reg.event("step", i=2)
    assert len(got) == 1
    assert got[0]["kind"] == "step" and got[0]["i"] == 1
    assert "ts" in got[0]
