"""SLO monitor units: hand-computed burn rates over fast/slow windows,
conservative bucket accounting, breach-episode triggers through the
flight recorder, and the ratio (error-rate) target kind."""
import json

import pytest

from pipegoose_tpu.telemetry.flightrec import FlightRecorder
from pipegoose_tpu.telemetry.registry import MetricsRegistry
from pipegoose_tpu.telemetry.slo import (
    SLOMonitor,
    SLOTarget,
    default_serving_slos,
)


@pytest.fixture()
def reg():
    return MetricsRegistry(enabled=True)


def _monitor(reg, targets=None, **kw):
    clock = [0.0]
    kw.setdefault("fast_window_s", 10.0)
    kw.setdefault("slow_window_s", 100.0)
    kw.setdefault("burn_threshold", 2.0)
    mon = SLOMonitor(
        targets or [SLOTarget(name="ttft", metric="serving.ttft_seconds",
                              objective=0.1, target=0.9)],
        registry=reg, clock=lambda: clock[0], **kw,
    )
    return mon, clock


def test_target_validation():
    with pytest.raises(ValueError, match="target must be in"):
        SLOTarget(name="x", metric="m", target=1.0)
    with pytest.raises(ValueError, match="latency kind needs"):
        SLOTarget(name="x")
    with pytest.raises(ValueError, match="ratio kind needs"):
        SLOTarget(name="x", kind="ratio")
    with pytest.raises(ValueError, match="unknown kind"):
        SLOTarget(name="x", metric="m", kind="mean")


def test_monitor_validation(reg):
    t = SLOTarget(name="a", metric="m")
    with pytest.raises(ValueError, match="at least one"):
        SLOMonitor([], registry=reg)
    with pytest.raises(ValueError, match="windows"):
        SLOMonitor([t], registry=reg, fast_window_s=60, slow_window_s=60)
    with pytest.raises(ValueError, match="duplicate"):
        SLOMonitor([t, t], registry=reg)


def test_burn_rate_hand_computed(reg):
    """target=0.9 -> 10% budget. 20 good then 5 bad out of 25 new
    events in the window -> bad fraction 0.2 -> burn 2.0."""
    h = reg.histogram("serving.ttft_seconds")
    mon, clock = _monitor(reg)
    mon.evaluate()                       # baseline sample at t=0
    for _ in range(20):
        h.observe(0.01)                  # good: <= 0.1
    for _ in range(5):
        h.observe(1.0)                   # bad
    clock[0] = 5.0
    st = mon.evaluate()
    t = st["targets"]["ttft"]
    assert t["bad_fraction_fast"] == pytest.approx(5 / 25)
    assert t["burn_fast"] == pytest.approx((5 / 25) / 0.1)
    assert t["breaching"] and not st["ok"]
    # gauges exported next to the histograms they judge
    snap = reg.snapshot()
    assert snap["gauges"]["slo.ttft.burn_fast"] == pytest.approx(2.0)
    assert snap["gauges"]["slo.breaching"] == 1.0
    assert snap["counters"]["slo.alerts_total"] == 1.0


def test_no_data_means_no_burn(reg):
    mon, clock = _monitor(reg)
    st = mon.evaluate()
    assert st["ok"]
    clock[0] = 50.0
    st = mon.evaluate()                  # still no observations
    assert st["ok"]
    assert st["targets"]["ttft"]["events_fast"] == 0


def test_objective_between_buckets_counts_conservatively(reg):
    """An observation in the bucket straddling the objective counts as
    BAD (only buckets whose upper bound <= objective are good) — the
    monitor over-alerts rather than under-alerts."""
    h = reg.histogram("x.seconds", buckets=(0.1, 1.0))
    mon, clock = _monitor(
        reg, [SLOTarget(name="x", metric="x.seconds", objective=0.5,
                        target=0.5)],
    )
    mon.evaluate()
    h.observe(0.3)   # truly meets the 0.5 objective, but lands in the
    h.observe(0.05)  # (0.1, 1.0] bucket -> judged bad
    clock[0] = 5.0
    st = mon.evaluate()
    assert st["targets"]["x"]["bad_fraction_fast"] == pytest.approx(0.5)


def test_short_blip_does_not_page_when_slow_window_is_clean(reg):
    """Multi-window behavior: a burst that blows the fast window while
    the slow window still averages under threshold must NOT alert."""
    h = reg.histogram("serving.ttft_seconds")
    mon, clock = _monitor(reg)
    # 200s of good history, sampled every 5s (beyond the slow window)
    for i in range(41):
        clock[0] = i * 5.0
        for _ in range(10):
            h.observe(0.01)
        mon.evaluate()
    # now a short 100%-bad burst inside the fast window only
    clock[0] = 205.0
    for _ in range(10):
        h.observe(2.0)
    st = mon.evaluate()
    t = st["targets"]["ttft"]
    assert t["burn_fast"] >= 2.0          # fast window is on fire...
    assert t["burn_slow"] < 2.0           # ...slow window dilutes it
    assert st["ok"]                       # -> no page


def test_trigger_fires_once_per_breach_episode(reg, tmp_path):
    h = reg.histogram("serving.ttft_seconds")
    rec = FlightRecorder(str(tmp_path), registry=reg)
    mon, clock = _monitor(reg, recorder=rec)
    mon.evaluate()
    for _ in range(30):
        h.observe(5.0)
    clock[0] = 5.0
    st = mon.evaluate()
    assert not st["ok"]
    trig = rec.last_trigger
    assert trig is not None and trig.name == "slo_burn"
    assert "ttft" in trig.reason and "burning" in trig.reason
    assert trig.dump_path is not None
    blackbox = json.loads(open(trig.dump_path).read())
    assert blackbox["trigger"]["name"] == "slo_burn"
    assert blackbox["trigger"]["details"]["target"]["name"] == "ttft"
    # still breaching on the next evaluation: no second dump
    clock[0] = 8.0
    mon.evaluate()
    assert len(rec.dumps) == 1
    assert mon.breaching == ["ttft"]
    # recovery clears the breach state; a NEW episode re-fires
    clock[0] = 200.0
    for _ in range(500):
        h.observe(0.01)
    mon.evaluate()
    clock[0] = 205.0
    st = mon.evaluate()
    assert st["targets"]["ttft"]["breaching"] is False


def test_ratio_kind_uses_counters(reg):
    bad = reg.counter("serving.errors_total")
    tot = reg.counter("serving.requests_total")
    mon, clock = _monitor(
        reg,
        [SLOTarget(name="errors", kind="ratio",
                   bad_metric="serving.errors_total",
                   total_metric="serving.requests_total", target=0.99)],
    )
    mon.evaluate()
    tot.inc(100)
    bad.inc(4)
    clock[0] = 5.0
    st = mon.evaluate()
    t = st["targets"]["errors"]
    assert t["bad_fraction_fast"] == pytest.approx(0.04)
    assert t["burn_fast"] == pytest.approx(0.04 / 0.01)
    assert t["breaching"]


def test_status_is_evaluate(reg):
    h = reg.histogram("serving.ttft_seconds")
    mon, clock = _monitor(reg)
    mon.evaluate()
    for _ in range(10):
        h.observe(9.0)
    clock[0] = 5.0
    # /healthz's entry point: one status() call sees the blown budget
    assert mon.status()["ok"] is False


def test_default_serving_slos_cover_ttft_and_decode_gap():
    targets = default_serving_slos()
    assert [t.name for t in targets] == ["ttft", "decode_gap",
                                         "shed_fraction"]
    assert targets[0].metric == "serving.ttft_seconds"
    assert targets[1].metric == "serving.decode_gap_seconds"
    # graceful degradation: shed / submitted as a ratio-kind target —
    # /healthz stays 200 under shedding until the budget burns
    assert targets[2].kind == "ratio"
    assert targets[2].bad_metric == "serving.shed_total"
    assert targets[2].total_metric == "serving.requests_total"
