"""Request tracer units: phase attribution sums to e2e by construction,
bounded event rings, Perfetto rendering, and the disabled-path cost
guard (the engine's per-tick branch when tracing is off)."""
import threading
import time
from types import SimpleNamespace

import pytest

from pipegoose_tpu.telemetry.registry import MetricsRegistry
from pipegoose_tpu.telemetry.reqtrace import (
    COMPONENTS,
    NULL_TRACER,
    RequestTracer,
    request_trace_events,
)


def _req(uid, prompt_len=8, max_new=4):
    return SimpleNamespace(
        uid=uid, prompt_len=prompt_len, max_new_tokens=max_new, slot=None,
        hit_tokens=0, generated=[], finish_reason=None,
    )


@pytest.fixture()
def reg():
    return MetricsRegistry(enabled=True)


def _tracer(reg, **kw):
    t = [0.0]
    tr = RequestTracer(registry=reg, clock=lambda: t[0], **kw)
    return tr, t


def test_components_are_contiguous_segments_and_sum_to_e2e(reg):
    """queue/prefill/decode/stall are lifecycle segments — their sum IS
    submit→done, exactly, including across a preemption."""
    tr, t = _tracer(reg)
    r = _req(0)
    tr.on_submit(r, 0.0)
    r.slot, r.hit_tokens = 1, 4
    tr.on_admit(r, 1.0)                      # queue = 1.0
    tr.on_prefill_chunk(r, 1.5, dur_s=0.4, tokens=4)
    tr.on_first_token(r, 2.0)                # prefill = 1.0
    tr.on_decode_tick(r, 2.5, dur_s=0.5)
    t[0] = 3.0
    tr.on_preempt(r)                         # decode += 1.0
    tr.on_admit(r, 4.0)                      # stall = 1.0
    tr.on_prefill_chunk(r, 4.5, dur_s=0.4, tokens=8)
    tr.on_resume(r, 5.0)                     # prefill += 1.0 (re-prefill)
    r.finish_reason = "length"
    tr.on_done(r, 6.0)                       # decode += 1.0
    (row,) = tr.attribution_summary()["requests"]
    assert row["components"] == {
        "queue_s": 1.0, "prefill_s": 2.0, "restore_s": 0.0,
        "transfer_s": 0.0, "decode_s": 2.0, "stall_s": 1.0,
    }
    assert row["e2e_s"] == 6.0
    assert sum(row["components"].values()) == pytest.approx(row["e2e_s"])
    # TTFT decomposes from the accumulator snapshot at the first token
    assert row["ttft_s"] == 2.0
    assert row["ttft_components"] == {
        "queue_s": 1.0, "prefill_s": 1.0, "restore_s": 0.0,
        "transfer_s": 0.0, "decode_s": 0.0, "stall_s": 0.0,
    }
    assert row["preemptions"] == 1
    # cache-savings estimate: prefill paid 2.0s for 12 forwarded tokens,
    # 4 tokens hit -> 2.0 * 4/12
    assert row["cache_saved_est_s"] == pytest.approx(2.0 * 4 / 12)


def test_attrib_histograms_observed_on_done(reg):
    tr, _ = _tracer(reg)
    for uid in range(3):
        r = _req(uid)
        tr.on_submit(r, 0.0)
        r.slot = 0
        tr.on_admit(r, 1.0)
        tr.on_first_token(r, 2.0)
        r.finish_reason = "length"
        tr.on_done(r, 3.0)
    snap = reg.snapshot()
    assert snap["counters"]["serving.attrib.requests_total"] == 3
    for c in ("queue", "prefill", "decode", "stall"):
        assert snap["histograms"][f"serving.attrib.{c}_seconds"]["count"] == 3
    assert snap["histograms"]["serving.attrib.queue_seconds"]["max"] == 1.0


def test_event_ring_is_bounded_but_attribution_stays_exact(reg):
    tr, _ = _tracer(reg, max_events=8)
    r = _req(0)
    tr.on_submit(r, 0.0)
    r.slot = 0
    tr.on_admit(r, 1.0)
    tr.on_first_token(r, 2.0)
    for i in range(100):
        tr.on_decode_tick(r, 2.0 + i * 0.01, dur_s=0.01)
    r.finish_reason = "length"
    tr.on_done(r, 10.0)
    tl = tr.snapshot()["completed"][0]
    assert len(tl["events"]) == 8
    assert tl["events_dropped"] == 104 - 8  # submit+admit+first+100+done
    assert tl["decode_ticks"] == 100          # counters, not the ring
    # the dropped submit/admit events cannot corrupt the accounting
    assert tl["components"]["queue_s"] == 1.0
    assert sum(tl["components"].values()) == pytest.approx(tl["e2e_s"])


def test_readmit_keeps_first_admissions_hit_tokens(reg):
    tr, _ = _tracer(reg)
    r = _req(0)
    tr.on_submit(r, 0.0)
    r.slot, r.hit_tokens = 0, 6
    tr.on_admit(r, 1.0)
    tr.on_preempt(r, 2.0)
    r.hit_tokens = 8          # re-admission hits more (its own tokens)
    tr.on_admit(r, 3.0)
    r.finish_reason = "length"
    tr.on_done(r, 4.0)
    (row,) = tr.attribution_summary()["requests"]
    assert row["hit_tokens"] == 6  # user-visible cache benefit: first admit


def test_perfetto_rows_per_slot_with_markers(reg):
    tr, t = _tracer(reg)
    r = _req(0)
    tr.on_submit(r, 0.0)
    r.slot, r.hit_tokens = 2, 0
    tr.on_admit(r, 1.0)
    tr.on_cow(r, 1.2)
    tr.on_prefill_chunk(r, 1.5, dur_s=0.3, tokens=8)
    tr.on_first_token(r, 2.0)
    tr.on_spec(r, 2.5, dur_s=0.5, drafted=3, accepted=1)  # a reject
    t[0] = 3.0
    tr.on_preempt(r)
    tr.on_admit(r, 4.0)
    tr.on_resume(r, 5.0)
    r.finish_reason = "eos"
    tr.on_done(r, 6.0)
    events = request_trace_events(tr)
    names = [e["name"] for e in events]
    threads = {e["args"]["name"] for e in events if e["name"] == "thread_name"}
    assert "slot 2" in threads and "queue / preempted" in threads
    markers = {e["name"] for e in events if e["ph"] == "i"}
    assert {"req0 preempt", "req0 cow", "req0 spec_reject",
            "req0 first_token"} <= markers
    slices = {e["name"]: e for e in events if e["ph"] == "X"}
    assert {"req0 queue", "req0 prefill", "req0 decode", "req0 stall",
            "req0 chunk"} <= set(slices)
    # phase slices ride the slot track; waits ride the queue track
    assert slices["req0 prefill"]["tid"] == 2
    assert slices["req0 queue"]["tid"] == slices["req0 stall"]["tid"]
    assert slices["req0 queue"]["tid"] != 2
    assert "process_name" in names


def test_in_flight_timelines_visible_and_blackbox_names_them(reg):
    tr, _ = _tracer(reg)
    stuck = _req(7)
    tr.on_submit(stuck, 0.0)
    stuck.slot = 0
    tr.on_admit(stuck, 1.0)
    done = _req(8)
    tr.on_submit(done, 0.0)
    done.slot = 1
    tr.on_admit(done, 1.0)
    tr.on_first_token(done, 2.0)
    done.finish_reason = "length"
    tr.on_done(done, 3.0)
    payload = tr.blackbox_payload()
    assert [tl["uid"] for tl in payload["in_flight"]] == [7]
    assert [tl["uid"] for tl in payload["last_completed"]] == [8]
    snap = tr.snapshot()
    assert len(snap["in_flight"]) == 1 and len(snap["completed"]) == 1
    # open phase slices still render for in-flight requests
    ev = request_trace_events(tr)
    assert any(e["name"] == "req7 prefill" and e["args"].get("open")
               for e in ev if e["ph"] == "X")


def test_completed_ring_is_bounded(reg):
    tr, _ = _tracer(reg, keep_completed=4)
    for uid in range(10):
        r = _req(uid)
        tr.on_submit(r, 0.0)
        r.slot = 0
        tr.on_admit(r, 1.0)
        r.finish_reason = "length"
        tr.on_done(r, 2.0)
    assert [tl["uid"] for tl in tr.snapshot()["completed"]] == [6, 7, 8, 9]


def test_concurrent_snapshot_while_recording(reg):
    """The ops endpoint reads while the engine thread mutates — both
    under the tracer lock; this just has to not corrupt or raise."""
    tr, _ = _tracer(reg)
    stop = threading.Event()
    errs = []

    def reader():
        while not stop.is_set():
            try:
                tr.snapshot()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

    th = threading.Thread(target=reader)
    th.start()
    for uid in range(200):
        r = _req(uid)
        tr.on_submit(r, 0.0)
        r.slot = 0
        tr.on_admit(r, 1.0)
        r.finish_reason = "length"
        tr.on_done(r, 2.0)
    stop.set()
    th.join()
    assert not errs


def _median_call_seconds(fn, n=2000, rounds=15):
    samples = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        samples.append((time.perf_counter() - t0) / n)
    return sorted(samples)[len(samples) // 2]


def test_disabled_tracer_guard_under_5us():
    """The engine's hot-loop contract: with ``tracer=None`` (the
    default) the per-tick tracing hook — ``ServingEngine._trace_tick``
    — is one attribute read + branch, same budget as a disabled
    registry metric. Timed on the REAL method (unbound, against a
    tracer-less stand-in) so a regression in the guard itself fails
    here."""
    from pipegoose_tpu.serving.engine import ServingEngine

    fake_engine = SimpleNamespace(tracer=None)
    active = [_req(i) for i in range(4)]

    def tick():
        ServingEngine._trace_tick(fake_engine, active, 0.0, 0.0)

    assert _median_call_seconds(tick) < 5e-6
    # the NULL_TRACER fallback hooks are no-op methods with the same bound
    assert _median_call_seconds(
        lambda: NULL_TRACER.on_decode_tick(active[0], 0.0, 0.0)
    ) < 5e-6


def test_validation():
    with pytest.raises(ValueError, match="max_events"):
        RequestTracer(registry=MetricsRegistry(), max_events=2)
    with pytest.raises(ValueError, match="keep_completed"):
        RequestTracer(registry=MetricsRegistry(), keep_completed=0)


def test_set_clock_reanchors_wall_offset(reg):
    tr, _ = _tracer(reg)
    off0 = tr.wall_offset
    tr.set_clock(lambda: -1000.0)
    assert tr.wall_offset != off0
    tr.set_clock(tr.clock)  # same object: no-op


# -- disagg transfer phase (serving/disagg/, ISSUE 13) ----------------------


def test_transfer_phase_is_additive_and_exact(reg):
    """The disagg lifecycle: queue -> prefill -> (first token at
    handoff) -> transfer -> decode. TTFT excludes the transfer (the
    token exists at handoff); the five components still sum to e2e
    exactly."""
    tr, t = _tracer(reg)
    r = _req(0)
    tr.on_submit(r, 0.0)
    r.slot = 0
    tr.on_admit(r, 1.0)                      # queue = 1.0
    tr.on_prefill_chunk(r, 1.5, dur_s=0.4, tokens=8)
    # streamed chunk lands DURING prefill: counters only, no transition
    tr.on_transfer_chunk(r, 1.6, dur_s=0.05, tokens=8, pages=2,
                         nbytes=4096)
    tr.on_first_token(r, 2.0)                # prefill = 1.0
    tr.on_transfer_start(r, 2.0)             # decode += 0.0
    tr.on_transfer_chunk(r, 2.5, dur_s=0.1, tokens=4, pages=1,
                         nbytes=2048)
    tr.on_transfer_done(r, 3.0)              # transfer = 1.0
    r.finish_reason = "length"
    tr.on_done(r, 5.0)                       # decode += 2.0
    (row,) = tr.attribution_summary()["requests"]
    assert row["components"] == {
        "queue_s": 1.0, "prefill_s": 1.0, "restore_s": 0.0,
        "transfer_s": 1.0, "decode_s": 2.0, "stall_s": 0.0,
    }
    assert sum(row["components"].values()) == pytest.approx(row["e2e_s"])
    assert row["ttft_s"] == 2.0              # queue + prefill, no transfer
    tl = tr.completed[-1]
    assert tl.transfer_chunks == 2
    assert tl.transfer_pages == 3
    assert tl.transfer_bytes == 4096 + 2048
    assert tl.transfer_compute_s == pytest.approx(0.15)
    # the attribution histogram saw the new component
    snap = reg.snapshot()
    assert snap["histograms"]["serving.attrib.transfer_seconds"]["count"] == 1


def test_transfer_failure_books_requeue_as_queue_time(reg):
    """The fallback path: transfer fails, the request re-submits on the
    decode pool — the post-failure wait books as queue, the sum stays
    exact."""
    tr, t = _tracer(reg)
    r = _req(1)
    tr.on_submit(r, 0.0)
    r.slot = 0
    tr.on_admit(r, 1.0)
    tr.on_first_token(r, 2.0)
    tr.on_transfer_start(r, 2.0)
    tr.on_submit(r, 3.0)                     # fallback resubmit: transfer=1
    tr.on_admit(r, 4.0)                      # queue += 1
    tr.on_resume(r, 5.0)                     # (re-)prefill = 1
    r.finish_reason = "length"
    tr.on_done(r, 6.0)                       # decode += 1
    (row,) = tr.attribution_summary()["requests"]
    assert row["components"]["transfer_s"] == 1.0
    assert row["components"]["queue_s"] == 2.0
    assert sum(row["components"].values()) == pytest.approx(row["e2e_s"])


def test_perfetto_transfer_track(reg):
    """transfer_start/chunk/done render on a dedicated transfer track
    with a named thread row."""
    tr, t = _tracer(reg)
    r = _req(2)
    tr.on_submit(r, 0.0)
    r.slot = 1
    tr.on_admit(r, 1.0)
    tr.on_first_token(r, 2.0)
    tr.on_transfer_start(r, 2.0)
    tr.on_transfer_chunk(r, 2.5, dur_s=0.1, tokens=4, pages=1,
                         nbytes=2048)
    tr.on_transfer_done(r, 3.0)
    r.finish_reason = "length"
    tr.on_done(r, 4.0)
    evs = request_trace_events(tr)
    xfer = [e for e in evs if e.get("cat") == "request.transfer"]
    assert len(xfer) == 1 and xfer[0]["tid"] == 2_000
    assert xfer[0]["dur"] == pytest.approx(1e6)      # 1 s in µs
    chunks = [e for e in evs if e.get("cat") == "request.transfer_chunk"]
    assert len(chunks) == 1 and chunks[0]["tid"] == 2_000
    assert chunks[0]["args"]["nbytes"] == 2048
    rows = [e["args"]["name"] for e in evs
            if e.get("ph") == "M" and e["name"] == "thread_name"]
    assert any(name.startswith("transfer") for name in rows)
